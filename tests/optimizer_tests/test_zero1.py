"""ZeRO-1 sharded-optimizer-state tests (beyond-reference: the reference
replicated optimizer state on every rank; `zero1_optimizer` shards it over
the data axis via psum_scatter/all_gather — see
chainermn_tpu/training/optimizers.py).

Checks: (a) numerical equivalence with the replicated pmean+inner path for
elementwise optimizers, (b) odd leaf sizes exercise the padding lanes,
(c) optimizer state is genuinely 1/N-sized per replica, (d) params stay
replicated across steps, (e) bf16 wire mode, (f) double-buffering
composition through create_multi_node_optimizer."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from chainermn_tpu import create_communicator, create_multi_node_optimizer
from chainermn_tpu.training.optimizers import (
    cross_replica_mean,
    zero1_init,
    zero1_optimizer,
)

from chainermn_tpu.testing import requires_vma as _requires_vma

# Pre-vma shard_map (old check_rep) cannot express what these tests pin:
# grads of replicated outputs taken inside shard_map over-count by the
# axis size, replicated out_specs can't be inferred through gathers, and
# scan carries may not gain replication.  vma typing (jax >= 0.7) is the
# semantic fix; on older jax the cases below are undefined, not wrong.
requires_vma = _requires_vma("requires vma-typed shard_map AD semantics")

AX = "world"


@pytest.fixture()
def comm():
    return create_communicator("tpu_xla", axis_name=AX)


def _params():
    # odd sizes on purpose: 5*3=15 and 7 are not multiples of 8 devices
    r = np.random.RandomState(0)
    return {
        "w": jnp.asarray(r.randn(5, 3), jnp.float32),
        "b": jnp.asarray(r.randn(7), jnp.float32),
        "s": jnp.asarray(r.randn(), jnp.float32),
    }


def _grads_per_rank(n):
    r = np.random.RandomState(1)
    return {
        "w": jnp.asarray(r.randn(n, 5, 3), jnp.float32),
        "b": jnp.asarray(r.randn(n, 7), jnp.float32),
        "s": jnp.asarray(r.randn(n), jnp.float32),
    }


def _run_steps(comm, opt, params, grads_per_rank, n_steps=3):
    """Run ``n_steps`` updates inside shard_map (per-rank grads vary);
    return final params, world-stacked (so replication is observable)."""

    def body(params, grads):
        grads = jax.tree.map(lambda g: g[0], grads)  # drop shard dim
        state = opt.init(params)

        def one(carry, _):
            params, state = carry
            updates, state = opt.update(grads, state, params)
            return (optax.apply_updates(params, updates), state), None

        (params, _), _ = jax.lax.scan(one, (params, state), None, n_steps)
        return jax.tree.map(lambda p: p[None], params)

    f = jax.jit(jax.shard_map(
        body, mesh=comm.mesh, in_specs=(P(), P(AX)), out_specs=P(AX)))
    return f(params, grads_per_rank)


@pytest.mark.parametrize("inner", ["adam", "sgd_momentum", "adamw"])
@requires_vma
def test_matches_replicated_path(comm, inner):
    n = comm.size
    make = {
        "adam": lambda: optax.adam(1e-2),
        "sgd_momentum": lambda: optax.sgd(1e-2, momentum=0.9),
        # adamw exercises the params-dependent (weight decay) path
        "adamw": lambda: optax.adamw(1e-2, weight_decay=1e-2),
    }[inner]
    params, grads = _params(), _grads_per_rank(n)

    ref = _run_steps(
        comm, optax.chain(cross_replica_mean(AX), make()), params, grads)
    got = _run_steps(comm, zero1_optimizer(make(), AX), params, grads)

    for k in params:
        r, g = np.asarray(ref[k]), np.asarray(got[k])
        # params must remain replicated across ranks
        for i in range(1, n):
            np.testing.assert_array_equal(g[i], g[0])
        np.testing.assert_allclose(g[0], r[0], rtol=2e-5, atol=2e-6)


def test_state_is_sharded(comm):
    n = comm.size
    params = _params()

    def init_shapes(params):
        state = zero1_optimizer(optax.adam(1e-2), AX).init(params)
        # adam state: (ScaleByAdamState(count, mu, nu), EmptyState)
        mu = state[0].mu
        return jax.tree.map(lambda m: jnp.zeros(m.shape + (0,)), mu)

    f = jax.jit(jax.shard_map(
        init_shapes, mesh=comm.mesh, in_specs=P(), out_specs=P()))
    shapes = jax.tree.map(lambda z: z.shape[:-1], f(params))
    # each leaf's moment shard is ceil(size/n) elements, flat
    assert shapes["w"] == (-(-15 // n),)
    assert shapes["b"] == (-(-7 // n),)
    assert shapes["s"] == (-(-1 // n),)


@requires_vma
def test_bf16_wire(comm):
    n = comm.size
    params, grads = _params(), _grads_per_rank(n)
    ref = _run_steps(
        comm, optax.chain(cross_replica_mean(AX), optax.adam(1e-2)),
        params, grads)
    got = _run_steps(
        comm, zero1_optimizer(optax.adam(1e-2), AX,
                              wire_dtype=jnp.bfloat16),
        params, grads)
    for k in params:
        np.testing.assert_allclose(
            np.asarray(got[k])[0], np.asarray(ref[k])[0],
            rtol=2e-2, atol=2e-2)


def test_persistent_state_across_jit_boundaries(comm):
    """The real-training pattern: state initialised once with zero1_init,
    carried world-stacked through separate jitted step calls; a DP
    least-squares regression must converge and recover the true weights."""
    n = comm.size
    r = np.random.RandomState(0)
    w_true = r.randn(4, 3).astype(np.float32)
    x = r.randn(n, 16, 4).astype(np.float32)
    y = np.einsum("rbi,ij->rbj", x, w_true)

    params = {"w": jnp.zeros((4, 3))}
    opt = create_multi_node_optimizer(
        optax.adam(5e-2), comm, zero1=True)
    state = zero1_init(opt, params, comm.mesh, AX)
    # adam mu shard: ceil(12/n) per member, world-stacked with member axis
    assert state[0].mu["w"].shape == (n, -(-12 // n))
    assert state[0].count.shape == (n,)

    def step(params, state, x, y):
        x, y, state = x[0], y[0], jax.tree.map(lambda s: s[0], state)

        def loss_fn(p):
            return jnp.mean((x @ p["w"] - y) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, state = opt.update(grads, state, params)
        return (optax.apply_updates(params, updates),
                jax.tree.map(lambda s: s[None], state),
                jax.lax.pmean(loss, AX))

    f = jax.jit(jax.shard_map(
        step, mesh=comm.mesh,
        in_specs=(P(), P(AX), P(AX), P(AX)),
        out_specs=(P(), P(AX), P())))
    x, y = jnp.asarray(x), jnp.asarray(y)
    for _ in range(300):
        params, state, loss = f(params, state, x, y)
    assert float(loss) < 1e-3
    np.testing.assert_allclose(params["w"], w_true, atol=0.05)


@requires_vma
def test_create_multi_node_optimizer_zero1_double_buffering(comm):
    n = comm.size
    params, grads = _params(), _grads_per_rank(n)
    ref = _run_steps(
        comm,
        create_multi_node_optimizer(
            optax.sgd(1e-1), comm, double_buffering=True),
        params, grads)
    got = _run_steps(
        comm,
        create_multi_node_optimizer(
            optax.sgd(1e-1), comm, double_buffering=True, zero1=True),
        params, grads)
    for k in params:
        np.testing.assert_allclose(
            np.asarray(got[k])[0], np.asarray(ref[k])[0],
            rtol=2e-5, atol=2e-6)
