"""Resume-mid-training drill for accumulation windows, reusing
``chainermn_tpu.testing.FaultPlan``: SIGKILL a real accum_steps=4
training process mid-epoch, resume from the checkpoint, and require the
continuation to be BITWISE identical to an uninterrupted run — the
proof that window-fused accumulation keeps no hidden cross-window state
a checkpoint could miss (the accumulator lives inside the jitted step).
"""

import os
import signal
import subprocess
import sys

import numpy as np
import pytest

from chainermn_tpu.testing import FaultPlan
from chainermn_tpu.utils import load_state

_WORKER = os.path.join(os.path.dirname(__file__),
                       "_accum_fault_worker.py")
_REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", ".."))


def _run_phase(phase, workdir, plan=None, expect_kill=False, timeout=240):
    env = dict(os.environ)
    for k in list(env):
        if k.startswith(("TPU_", "LIBTPU", "PJRT_", "JAX_", "XLA_")):
            env.pop(k)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    plan_json = (plan or FaultPlan()).to_json()
    proc = subprocess.run(
        [sys.executable, _WORKER, phase, str(workdir), plan_json],
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd=_REPO_ROOT)
    if expect_kill:
        assert proc.returncode == -signal.SIGKILL, (
            f"expected SIGKILL death, got rc={proc.returncode}\n"
            f"{proc.stdout}\n{proc.stderr}")
    else:
        assert proc.returncode == 0, (
            f"phase {phase} failed rc={proc.returncode}\n"
            f"{proc.stdout}\n{proc.stderr}")
    return proc


@pytest.mark.slow
def test_kill_mid_epoch_resume_matches_uninterrupted(tmp_path):
    ref_dir, kill_dir = tmp_path / "ref", tmp_path / "kill"
    ref_dir.mkdir(), kill_dir.mkdir()
    _run_phase("ref", ref_dir)
    # 8 microbatches/epoch in 4-deep windows: iteration 20 is window 5 —
    # mid-epoch 3, mid-shuffle.  Checkpoints (sync, every window
    # boundary) leave a durable set at 20; the kill lands right after.
    proc = _run_phase("train", kill_dir,
                      FaultPlan(kill_at_iteration=20), expect_kill=True)
    assert "PHASE_OK" not in proc.stdout      # really died mid-run
    out = _run_phase("resume", kill_dir)
    assert "RESUMED_AT 20" in out.stdout
    ref = load_state(os.path.join(str(ref_dir), "ref.npz"))
    got = load_state(os.path.join(str(kill_dir), "resumed.npz"))
    assert int(got["iteration"]) == int(ref["iteration"]) == 48
    for k in ("w", "b"):
        np.testing.assert_array_equal(
            np.asarray(got["params"][k]), np.asarray(ref["params"][k]),
            err_msg=f"resumed {k} differs from uninterrupted accum run")
    np.testing.assert_array_equal(
        got["log_losses"], ref["log_losses"],
        err_msg="per-epoch loss log differs after resume")
