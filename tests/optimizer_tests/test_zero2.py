"""ZeRO-2 (``zero2_optimizer``): bucketed reduce-scatter gradient
exchange + 1/N optimizer-state shards.

The load-bearing claims, in order of strength:

- the member-major bucket layout makes every per-element cross-member
  sum happen in the SAME order as ``zero1_optimizer``'s per-leaf
  scatter, so ZeRO-2 training is bitwise-identical to ZeRO-1 in the
  parameters (the state may differ by an ULP where XLA picks a
  different reduce algorithm for the differently-shaped buffer);
- against the pure-DP oracle (``cross_replica_mean`` + inner) the
  match is within the established zero1 tolerance, with params exactly
  replicated across ranks;
- a single-device mesh and leaves smaller than the world (a scalar and
  a 7-element bias on 8 devices) are exact degenerate cases;
- bucket size is a pure performance knob: any ``bucket_bytes`` yields
  the same numbers.
"""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from chainermn_tpu import create_communicator, create_multi_node_optimizer
from chainermn_tpu.training.optimizers import (
    Zero2Transformation,
    _zero2_buckets,
    cross_replica_mean,
    zero1_init,
    zero1_optimizer,
    zero2_optimizer,
)

from chainermn_tpu.testing import requires_vma as _requires_vma

# Pre-vma shard_map (old check_rep) cannot express what these tests pin:
# scan carries may not gain replication and grads of replicated outputs
# over-count by the axis size.  vma typing (jax >= 0.7) is the semantic
# fix; on older jax the cases below are undefined, not wrong.  The
# external-loop tests below cover the same parity claims un-gated.
requires_vma = _requires_vma("requires vma-typed shard_map AD semantics")

AX = "world"


@pytest.fixture()
def comm():
    return create_communicator("tpu_xla", axis_name=AX)


def _params():
    # odd sizes on purpose: 5*3=15 and 7 are not multiples of 8 devices,
    # and the scalar leaf is SMALLER than the world (7 pad lanes)
    r = np.random.RandomState(0)
    return {
        "w": jnp.asarray(r.randn(5, 3), jnp.float32),
        "b": jnp.asarray(r.randn(7), jnp.float32),
        "s": jnp.asarray(r.randn(), jnp.float32),
    }


def _grads_per_rank(n):
    r = np.random.RandomState(1)
    return {
        "w": jnp.asarray(r.randn(n, 5, 3), jnp.float32),
        "b": jnp.asarray(r.randn(n, 7), jnp.float32),
        "s": jnp.asarray(r.randn(n), jnp.float32),
    }


def _run_steps(comm, opt, params, grads_per_rank, n_steps=3):
    def body(params, grads):
        grads = jax.tree.map(lambda g: g[0], grads)
        state = opt.init(params)

        def one(carry, _):
            params, state = carry
            updates, state = opt.update(grads, state, params)
            return (optax.apply_updates(params, updates), state), None

        (params, _), _ = jax.lax.scan(one, (params, state), None, n_steps)
        return jax.tree.map(lambda p: p[None], params)

    f = jax.jit(jax.shard_map(
        body, mesh=comm.mesh, in_specs=(P(), P(AX)), out_specs=P(AX)))
    return f(params, grads_per_rank)


@pytest.mark.parametrize("inner", ["adam", "sgd_momentum", "adamw"])
@requires_vma
def test_matches_replicated_path(comm, inner):
    n = comm.size
    make = {
        "adam": lambda: optax.adam(1e-2),
        "sgd_momentum": lambda: optax.sgd(1e-2, momentum=0.9),
        "adamw": lambda: optax.adamw(1e-2, weight_decay=1e-2),
    }[inner]
    params, grads = _params(), _grads_per_rank(n)

    ref = _run_steps(
        comm, optax.chain(cross_replica_mean(AX), make()), params, grads)
    got = _run_steps(comm, zero2_optimizer(make(), AX), params, grads)

    for k in params:
        r, g = np.asarray(ref[k]), np.asarray(got[k])
        for i in range(1, n):
            np.testing.assert_array_equal(g[i], g[0])
        np.testing.assert_allclose(g[0], r[0], rtol=2e-5, atol=2e-6)


# --------------------------------------------------------------------- #
# the un-gated parity drill: jitted step called in a Python loop with a
# world-stacked state carry (the real-training pattern, expressible on
# pre-vma shard_map)
# --------------------------------------------------------------------- #


def _train(comm, make_opt, sharded, n_steps=4):
    """An 8-rank DP least-squares regression; returns (params, state)
    after ``n_steps``.  ``sharded`` runs the world-stacked ZeRO carry,
    else the replicated-state oracle."""
    n = comm.size
    params = {"w": jnp.zeros((4, 3)), "b": jnp.zeros((7,)),
              "s": jnp.zeros(())}
    r = np.random.RandomState(0)
    w_true = jnp.asarray(r.randn(4, 3), jnp.float32)
    x = jnp.asarray(r.randn(n, 16, 4), jnp.float32)
    y = jnp.einsum("rbi,ij->rbj", x, w_true)
    opt = make_opt()
    if sharded:
        state = zero1_init(opt, params, comm.mesh, AX)
        st_spec = P(AX)
    else:
        state = opt.init(params)
        st_spec = P()

    def step(params, state, x, y):
        x, y = x[0], y[0]
        if sharded:
            state = jax.tree.map(lambda s: s[0], state)

        def loss_fn(p):
            pred = x @ p["w"] + p["b"][:3] + p["s"]
            return jnp.mean((pred - y) ** 2)

        grads = jax.grad(loss_fn)(params)
        updates, state = opt.update(grads, state, params)
        if sharded:
            state = jax.tree.map(lambda s: s[None], state)
        return optax.apply_updates(params, updates), state

    f = jax.jit(jax.shard_map(
        step, mesh=comm.mesh,
        in_specs=(P(), st_spec, P(AX), P(AX)), out_specs=(P(), st_spec)))
    for _ in range(n_steps):
        params, state = f(params, state, x, y)
    return params, state


def test_bitwise_matches_zero1(comm):
    """The central ZeRO-2 claim: the member-major bucket exchange
    computes the SAME per-element sums in the SAME order as the ZeRO-1
    per-leaf scatter, so training trajectories agree bitwise in the
    parameters."""
    z1_p, z1_s = _train(comm, lambda: zero1_optimizer(
        optax.adam(1e-2), AX), True)
    z2_p, z2_s = _train(comm, lambda: zero2_optimizer(
        optax.adam(1e-2), AX), True)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)), z1_p, z2_p)
    # the moments agree to the last ulp or one past it (XLA may lower
    # the differently-shaped scatter with a different reduce schedule)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-6, atol=0),
        z1_s, z2_s)


def test_matches_dp_oracle(comm):
    """ZeRO-2 vs the replicated-state pure-DP oracle, trained through
    jitted steps (un-gated: no scan carry, no replicated-loss grads)."""
    dp_p, _ = _train(comm, lambda: optax.chain(
        cross_replica_mean(AX), optax.adam(1e-2)), False)
    z2_p, _ = _train(comm, lambda: zero2_optimizer(
        optax.adam(1e-2), AX), True)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-6),
        dp_p, z2_p)


def test_bucket_bytes_is_pure_perf_knob(comm):
    """Any bucket split computes identical numbers: 64-byte buckets
    (every leaf its own bucket) vs the single default bucket."""
    ref_p, ref_s = _train(comm, lambda: zero2_optimizer(
        optax.adam(1e-2), AX), True)
    tiny_p, tiny_s = _train(comm, lambda: zero2_optimizer(
        optax.adam(1e-2), AX, bucket_bytes=64), True)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)), ref_p, tiny_p)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-6, atol=0),
        ref_s, tiny_s)


def test_state_is_sharded(comm):
    n = comm.size
    params = _params()

    def init_shapes(params):
        state = zero2_optimizer(optax.adam(1e-2), AX).init(params)
        mu = state[0].mu
        return jax.tree.map(lambda m: jnp.zeros(m.shape + (0,)), mu)

    f = jax.jit(jax.shard_map(
        init_shapes, mesh=comm.mesh, in_specs=P(), out_specs=P()))
    shapes = jax.tree.map(lambda z: z.shape[:-1], f(params))
    assert shapes["w"] == (-(-15 // n),)
    assert shapes["b"] == (-(-7 // n),)
    assert shapes["s"] == (-(-1 // n),)


def test_single_device_mesh():
    """World 1: the scatter/gather degenerate to identity.  ZeRO-2
    matches ZeRO-1 bitwise (identical exchange semantics) and the bare
    inner optimizer to the last ulp (XLA fuses the flat-shard program
    differently from the tree-shaped one, so exact bit equality with
    the inner is not a contract)."""
    mesh = Mesh(np.asarray(jax.devices()[:1]), (AX,))
    params = _params()
    grads = jax.tree.map(lambda p: jnp.ones_like(p) * 0.25, params)

    def run(opt):
        def body(params):
            state = opt.init(params)
            for _ in range(3):
                updates, state = opt.update(grads, state, params)
                params = optax.apply_updates(params, updates)
            return params

        return jax.jit(jax.shard_map(
            body, mesh=mesh, in_specs=(P(),), out_specs=P()))(params)

    ref = run(optax.adam(1e-2))
    z1 = run(zero1_optimizer(optax.adam(1e-2), AX))
    z2 = run(zero2_optimizer(optax.adam(1e-2), AX))
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)), z1, z2)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=3e-7, atol=0), ref, z2)


# --------------------------------------------------------------------- #
# bucket construction + factory wiring
# --------------------------------------------------------------------- #


def test_bucket_construction():
    leaves = [jnp.zeros((64,), jnp.float32),
              jnp.zeros((64,), jnp.float32),
              jnp.zeros((8,), jnp.bfloat16),
              jnp.zeros((64,), jnp.float32)]
    # dtype groups split buckets; fp32 leaves pack in first-occurrence
    # order until the PER-MEMBER shard byte budget runs out: each fp32
    # leaf is ceil(64/8)*4 = 32 shard bytes, so two fit per 64-byte
    # bucket
    buckets = _zero2_buckets(leaves, 8, bucket_bytes=64)
    assert [(str(dt), idxs) for dt, idxs in buckets] == [
        ("float32", [0, 1]), ("float32", [3]), ("bfloat16", [2])]
    one = _zero2_buckets(leaves, 8, bucket_bytes=None)
    assert [(str(dt), idxs) for dt, idxs in one] == [
        ("float32", [0, 1, 3]), ("bfloat16", [2])]


def test_factory_mutual_exclusion(comm):
    with pytest.raises(ValueError, match="mutually exclusive"):
        create_multi_node_optimizer(
            optax.adam(1e-2), comm, zero1=True, zero2=True)


def test_factory_returns_zero2_transformation(comm):
    opt = create_multi_node_optimizer(optax.adam(1e-2), comm, zero2=True)
    assert isinstance(opt, Zero2Transformation)
    assert not opt.overlap


def test_factory_plan_is_ignored_under_zero2(comm):
    class FakePlan:
        strategy = "fused/flat/native"
        program = None

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        create_multi_node_optimizer(
            optax.adam(1e-2), comm, zero2=True, plan=FakePlan())
    assert any("zero1/zero2" in str(x.message) for x in w)


def test_updater_detects_zero2(comm):
    import chainermn_tpu as cmn
    from chainermn_tpu.models import init_mlp, mlp_apply, \
        softmax_cross_entropy

    rng = np.random.RandomState(0)
    data = [(rng.randn(6).astype(np.float32), np.int32(i % 3))
            for i in range(64)]
    it = cmn.SerialIterator(data, 16, shuffle=True, seed=7)
    params = init_mlp(jax.random.PRNGKey(0), [6, 12, 3])
    opt = create_multi_node_optimizer(optax.adam(5e-2), comm, zero2=True)
    upd = cmn.StandardUpdater(it, opt, lambda p, x, y:
                              softmax_cross_entropy(mlp_apply(p, x), y),
                              params, comm)
    assert upd.sharding == "zero2"
    assert upd.zero1          # the world-stacked carry convention
    upd.update()
    assert upd.status()["sharding"] == "zero2"
    n = comm.size
    assert any(m.ndim >= 1 and m.shape[0] == n
               for m in jax.tree.leaves(upd.opt_state))
