"""Worker for the accumulation kill→resume drill: one deterministic
accum_steps=4 training job per invocation, driven by a FaultPlan JSON.

    python _accum_fault_worker.py <phase> <workdir> <plan_json>

Phases (mirrors tests/extension_tests/_fault_worker.py):
  ref    — run 6 epochs uninterrupted, write final params to ref.npz
  train  — run with the fault plan armed (a kill plan dies mid-run)
  resume — maybe_load from the checkpoint, finish, write resumed.npz

The accumulation-specific claim: the gradient accumulator lives INSIDE
the jitted window step (no cross-window carry), so a checkpoint taken
at any update boundary — which with accum_steps=4 is every 4th
iteration, mid-epoch and mid-shuffle for the kill below — resumes
BITWISE identical to the uninterrupted run, params and loss log both.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"

from chainermn_tpu.testing import ensure_virtual_pod  # noqa: E402

ensure_virtual_pod(8)

import numpy as np  # noqa: E402
import optax  # noqa: E402

import chainermn_tpu as cmn  # noqa: E402
from chainermn_tpu.extensions import (  # noqa: E402
    create_multi_node_checkpointer,
)
from chainermn_tpu.testing import FaultInjector, FaultPlan  # noqa: E402
from chainermn_tpu.training import LogReport  # noqa: E402
from chainermn_tpu.utils import save_state  # noqa: E402

ACCUM = 4


def _dataset(n=64):
    rng = np.random.RandomState(0)
    x = rng.randn(n, 4).astype(np.float32)
    w = rng.randn(4).astype(np.float32)
    y = (x @ w).astype(np.float32)
    return [(x[i], y[i]) for i in range(n)]


def _loss_fn(params, x, y):
    import jax.numpy as jnp

    pred = x @ params["w"] + params["b"]
    return jnp.mean((pred - y) ** 2)


def _build(comm, workdir):
    import jax.numpy as jnp

    # 64 examples / batch 8 = 8 microbatches per epoch = 2 accumulation
    # windows; iteration advances 4 per update
    it = cmn.SerialIterator(_dataset(), batch_size=8, shuffle=True,
                            seed=5)
    opt = cmn.create_multi_node_optimizer(optax.sgd(0.05), comm)
    params = {"w": jnp.zeros(4), "b": jnp.zeros(())}
    up = cmn.StandardUpdater(it, opt, _loss_fn, params, comm,
                             accum_steps=ACCUM)
    trainer = cmn.Trainer(up, stop_trigger=(6, "epoch"),
                          out=os.path.join(workdir, "out"))
    log = LogReport(trigger=(1, "epoch"))
    trainer.extend(log)
    # sync writes (a kill right after a save must find it durable);
    # trigger every 3 iterations — crossing semantics fire it at every
    # 4-iteration window boundary, i.e. mid-epoch, mid-shuffle points
    cp = create_multi_node_checkpointer(
        comm, os.path.join(workdir, "ckpt"), async_write=False,
        history=2)
    trainer.extend(cp, trigger=(3, "iteration"))
    return trainer, up, cp, log


def main():
    phase, workdir, plan_json = sys.argv[1], sys.argv[2], sys.argv[3]
    comm = cmn.create_communicator("tpu_xla")
    trainer, up, cp, log = _build(comm, workdir)
    if phase == "train":
        plan = FaultPlan.from_json(plan_json)
        trainer.extend(FaultInjector(plan, comm))
    elif phase == "resume":
        resumed = cp.maybe_load(up, trainer)
        print(f"RESUMED_AT {resumed}", flush=True)
    trainer.run()
    final = {"params": up.params, "iteration": up.iteration,
             "log_losses": np.asarray(
                 [e["main/loss"] for e in log.log], np.float64)}
    name = {"ref": "ref.npz", "resume": "resumed.npz",
            "train": "train.npz"}[phase]
    save_state(os.path.join(workdir, name), final)
    print(f"PHASE_OK {phase} iter={up.iteration}", flush=True)


if __name__ == "__main__":
    main()
