"""Backward-overlapped gradient exchange — the ``overlap`` plan family
through the full stack: the ``ops.fused.overlap_exchange`` lowering
(parity, schedules, the non-float wire exemption), the updater's
final-microbatch peel under accumulation, the compiled-HLO overlap
proof (``assert_overlap_collectives`` passes the overlap program and
rejects the window-end one), and composition with
prefetch/steps_per_execution (bitwise loss trajectories) and ZeRO-1.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import chainermn_tpu as cmn
from chainermn_tpu.models import init_mlp, mlp_apply, softmax_cross_entropy
from chainermn_tpu.ops import fused as F
from chainermn_tpu.parallel._compat import shard_map
from chainermn_tpu.utils import (
    assert_overlap_collectives,
    collective_stats,
)


@pytest.fixture()
def comm():
    return cmn.create_communicator("tpu_xla")


@pytest.fixture()
def mesh():
    return Mesh(np.array(jax.devices()), ("d",))


def _world_exchange(mesh, exchange):
    """Run ``exchange`` on each member's slice of a world-stacked tree."""
    def body(g):
        local = jax.tree.map(lambda a: a[0], g)
        red = exchange(local)
        return jax.tree.map(lambda a: a[None], red)

    return jax.jit(shard_map(body, mesh=mesh, in_specs=P("d"),
                             out_specs=P("d")))


def _stacked_tree(n=8, seed=0):
    """World-stacked mixed-dtype tree: rank-varying floats, a
    rank-identical int leaf (its mean is exact — the wire-exemption
    probe), and an empty leaf."""
    rng = np.random.RandomState(seed)
    ints = (rng.rand(1, 33) * 70000).astype(np.int32)
    return {
        "w1": rng.randn(n, 257, 3).astype(np.float32),
        "b1": rng.randn(n, 19).astype(np.float32),
        "idx": np.broadcast_to(ints, (n, 33)).copy(),
        "w2": rng.randn(n, 1500).astype(np.float32),
        "empty": np.zeros((n, 0), np.float32),
    }


def _assert_tree_close(got, want, rtol=1e-6, atol=1e-6):
    for g, w in zip(jax.tree.leaves(got), jax.tree.leaves(want)):
        np.testing.assert_allclose(
            np.asarray(g, np.float64), np.asarray(w, np.float64),
            rtol=rtol, atol=atol)


class TestOverlapExchangeOp:
    def _ref(self, mesh, tree):
        fn = _world_exchange(mesh, lambda g: jax.tree.map(
            lambda a: jax.lax.pmean(a, "d") if a.size else a, g))
        return fn(tree)

    @pytest.mark.parametrize("schedule", [
        None,                                               # derived
        ({"leaves": 4, "mode": "eager", "via": "ar"},),     # one bucket
        ({"leaves": 1, "mode": "eager", "via": "rs"},       # mixed modes
         {"leaves": 2, "mode": "deferred", "via": "ar"},
         {"leaves": 1, "mode": "eager", "via": "rs"}),
    ], ids=["derived", "single_bucket", "mixed_modes"])
    def test_parity_vs_per_leaf(self, mesh, schedule):
        tree = _stacked_tree()
        got = _world_exchange(mesh, lambda g: F.overlap_exchange(
            g, "d", schedule=schedule, bucket_bytes=2048))(tree)
        _assert_tree_close(got, self._ref(mesh, tree))

    def test_nonfloat_wire_exemption_is_exact(self, mesh):
        """int32 leaves must NOT be cast to the bf16 wire: a bf16
        round-trip of values past 2**8 silently drops low bits."""
        tree = _stacked_tree()
        got = _world_exchange(mesh, lambda g: F.overlap_exchange(
            g, "d", bucket_bytes=1024, wire_dtype=jnp.bfloat16))(tree)
        assert got["idx"].dtype == np.int32
        np.testing.assert_array_equal(np.asarray(got["idx"]),
                                      tree["idx"])
        # floats carry the documented wire tolerance
        _assert_tree_close(got, self._ref(mesh, tree), rtol=5e-2,
                           atol=5e-2)

    def test_single_leaf_pytree(self, mesh):
        """Single-bucket/single-leaf tree: no anchors, one exchange."""
        rng = np.random.RandomState(1)
        tree = rng.randn(8, 101).astype(np.float32)
        got = _world_exchange(mesh, lambda g: F.overlap_exchange(
            g, "d"))(tree)
        np.testing.assert_allclose(np.asarray(got),
                                   np.asarray(self._ref(mesh, tree)),
                                   rtol=1e-6, atol=1e-6)

    def test_schedule_mismatch_raises(self, mesh):
        tree = _stacked_tree()
        with pytest.raises(ValueError, match="payload signature"):
            _world_exchange(mesh, lambda g: F.overlap_exchange(
                g, "d",
                schedule=({"leaves": 2, "mode": "eager"},)))(tree)

    @pytest.mark.parametrize("entry,err", [
        ({"leaves": 0, "mode": "eager"}, "positive leaf count"),
        ({"leaves": 1, "mode": "lazy"}, "mode"),
        ({"leaves": 1, "mode": "eager", "via": "nccl"}, "via"),
    ])
    def test_bad_schedule_entries_raise(self, entry, err):
        with pytest.raises(ValueError, match=err):
            F._normalize_schedule((entry,))

    def test_build_schedule_covers_leaves_and_wire_itemsize(self):
        sds = [jax.ShapeDtypeStruct((4096,), jnp.float32),
               jax.ShapeDtypeStruct((10,), jnp.float32),
               jax.ShapeDtypeStruct((0,), jnp.float32),
               jax.ShapeDtypeStruct((4096,), jnp.float32)]
        native = F.build_overlap_schedule(sds, bucket_bytes=16384)
        assert sum(e["leaves"] for e in native) == 3    # empty skipped
        # bf16 wire halves the float bytes, so the same bucket size
        # packs MORE leaves per bucket (fewer buckets)
        bf16 = F.build_overlap_schedule(sds, 16384, "bfloat16")
        assert len(bf16) <= len(native)
        assert sum(e["leaves"] for e in bf16) == 3

    def test_plan_allreduce_dispatches_overlap(self, mesh):
        tree = _stacked_tree()
        plan = {"strategy": "overlap", "bucket_bytes": 2048,
                "wire_dtype": None,
                "schedule": [{"leaves": 4, "mode": "eager",
                              "via": "rs"}]}
        got = _world_exchange(mesh, lambda g: F.plan_allreduce(
            g, "d", plan))(tree)
        _assert_tree_close(got, self._ref(mesh, tree))


# ----------------------------------------------------------------- #
# training stack
# ----------------------------------------------------------------- #

_N, _DIM, _H, _C = 512, 24, 48, 5


def _dataset(seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(_N, _DIM).astype(np.float32)
    Y = (rng.rand(_N) * _C).astype(np.int32)
    return X, Y


def _loss_fn(p, x, y):
    return softmax_cross_entropy(mlp_apply(p, x), y)


def _params(depth=4):
    return init_mlp(jax.random.PRNGKey(0),
                    [_DIM] + [_H] * depth + [_C])


def _make(comm, overlap, accum=4, depth=4, batch=32, seed=3,
          bucket=2048, **kw):
    X, Y = _dataset()
    it = cmn.SerialIterator((X, Y), batch, shuffle=True, seed=seed)
    opt_kw = {k: kw.pop(k) for k in ("plan", "zero1",
                                     "allreduce_grad_dtype")
              if k in kw}
    opt = cmn.create_multi_node_optimizer(
        optax.sgd(0.05), comm, overlap=overlap, bucket_bytes=bucket,
        **opt_kw)
    return cmn.StandardUpdater(it, opt, _loss_fn, _params(depth), comm,
                               accum_steps=accum, **kw)


def _compile_window(upd, n_steps=1, accum=4):
    arrays, k, _tail = upd._assemble_host_window()
    fn = upd._get_step(len(arrays), n_steps, accum)
    carry = (upd.params, upd.state, upd.opt_state)
    return fn.lower(carry, *arrays).compile()


def _losses(upd, n):
    out = []
    for _ in range(n):
        upd.update()
        out.append(float(upd.observation["main/loss"]))
    return out


class TestOverlapTraining:
    def test_parity_vs_window_end(self, comm):
        a, b = _make(comm, True), _make(comm, False)
        la, lb = _losses(a, 5), _losses(b, 5)
        # same data, same accumulation order; only the exchange
        # lowering differs (rs→ag vs fused all-reduce) — fp32
        # collective-reduction-order tolerance, nothing more
        np.testing.assert_allclose(la, lb, rtol=1e-4, atol=1e-5)
        jax.tree.map(
            lambda x, y: np.testing.assert_allclose(
                np.asarray(x), np.asarray(y), rtol=2e-4, atol=1e-5),
            a.params, b.params)

    def test_accum_one_trains_and_proves(self, comm):
        u = _make(comm, True, accum=1)
        losses = _losses(u, 3)
        assert np.isfinite(losses).all()
        rep = assert_overlap_collectives(_compile_window(u, 1, 1))
        assert rep["total"] >= 4 and rep["frac"] >= 0.5

    def test_overlap_proof_accum_window(self, comm):
        rep = assert_overlap_collectives(
            _compile_window(_make(comm, True)))
        assert rep["frac"] >= 0.5

    def test_window_end_fails_the_proof(self, comm):
        """The PR 4 window-end exchange (default 4 MiB bucket: the
        whole grad tree rides one arena, whose concat joins every
        leaf) really does cluster after the backward — the proof must
        reject it, or it proves nothing."""
        with pytest.raises(AssertionError, match="cluster"):
            assert_overlap_collectives(
                _compile_window(_make(comm, False, bucket=None)))

    def test_no_inscan_exchange_with_peel(self, comm):
        """The peel must not leak collectives INTO the M-1 scan: the
        stream fires once per window, under the final backward only."""
        stats = collective_stats(_compile_window(_make(comm, True)))
        assert sum(s.looped for s in stats.values()) == 0

    def test_composition_bitwise_prefetch_spe(self, comm):
        """overlap × prefetch × steps_per_execution: identical data
        through identical programs — the loss trajectory per consumed
        microbatch must be BITWISE equal across pipeline knobs."""
        # max_inflight=1 keeps the observed loss CURRENT (the default
        # prefetch pipelining reports the retired window's loss, which
        # lags — a display offset, not a numeric difference)
        base = _make(comm, True, accum=2)
        pf = _make(comm, True, accum=2, prefetch=2, max_inflight=1)
        spe = _make(comm, True, accum=2, steps_per_execution=2,
                    prefetch=2, max_inflight=1)
        try:
            lb = _losses(base, 4)                    # 4 windows of M=2
            lp = _losses(pf, 4)
            ls = _losses(spe, 2)                     # 2 double-windows
        finally:
            pf.finalize()
            spe.finalize()
        assert lb == lp, (lb, lp)
        # spe=2 reports the mean of each 2-window dispatch
        want = [(lb[0] + lb[1]) / 2, (lb[2] + lb[3]) / 2]
        np.testing.assert_allclose(ls, want, rtol=0, atol=1e-7)

    def test_zero1_overlap_trains_at_parity(self, comm):
        a = _make(comm, True, zero1=True)
        b = _make(comm, False, zero1=True)
        la, lb = _losses(a, 4), _losses(b, 4)
        # ZeRO-1's exchange is identical in both arms (per-leaf
        # psum_scatter); the peel only reorders the schedule, not the
        # math — bitwise
        assert la == lb, (la, lb)
        rep = assert_overlap_collectives(_compile_window(a),
                                         min_bytes=64)
        assert rep["frac"] >= 0.5

    def test_overlap_true_with_window_end_plan_raises(self, comm):
        from chainermn_tpu.utils import autotune

        plan = autotune.Plan(strategy="fused_flat", bucket_bytes=4096)
        with pytest.raises(ValueError, match="overlap"):
            cmn.create_multi_node_optimizer(optax.sgd(0.1), comm,
                                            plan=plan, overlap=True)

    def test_static_overlap_plan_without_comm_probes(self, comm):
        """overlap=True with plan=None must not tune: the analytic
        schedule is derived at trace time, no probes, no cache."""
        u = _make(comm, True)
        cell = u.optimizer.plan_cell
        assert cell.plan.strategy == "overlap"
        assert cell.plan.n_probes == 0
        assert cell.plan.schedule is None       # derived at trace time
