"""StandardUpdater(zero1=True) — ZeRO-1 sharded optimizer state driven
by the stock trainer loop must be numerically identical to the
replicated-state path (sharding is an implementation detail) and must
compose with fused windows."""

import jax
import numpy as np
import optax
import pytest

import chainermn_tpu as cmn
from chainermn_tpu.models import init_mlp, mlp_apply, softmax_cross_entropy


@pytest.fixture()
def comm():
    return cmn.create_communicator("tpu_xla")


def _dataset(n=96, dim=6, classes=3, seed=0):
    rng = np.random.RandomState(seed)
    return [(rng.randn(dim).astype(np.float32), np.int32(i % classes))
            for i in range(n)]


def _make(comm, zero1, steps_per_execution=1):
    it = cmn.SerialIterator(_dataset(), 16, shuffle=True, seed=7)
    params = init_mlp(jax.random.PRNGKey(0), [6, 12, 3])
    opt = cmn.create_multi_node_optimizer(
        optax.adam(5e-2), comm, zero1=zero1)

    def loss_fn(p, x, y):
        return softmax_cross_entropy(mlp_apply(p, x), y)

    # no flag on the updater: ZeRO-1 is detected from the optimizer type
    return cmn.StandardUpdater(
        it, opt, loss_fn, params, comm,
        steps_per_execution=steps_per_execution)


def test_zero1_matches_replicated(comm):
    plain = _make(comm, zero1=False)
    z1 = _make(comm, zero1=True)
    for _ in range(8):
        plain.update()
        z1.update()
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5),
        plain.params, z1.params)
    # the state really is sharded: world-stacked leading member axis
    mu = jax.tree.leaves(z1.opt_state)
    n = comm.size
    assert any(m.ndim >= 1 and m.shape[0] == n for m in mu)


def test_zero1_with_fused_windows(comm):
    ref = _make(comm, zero1=True)
    fused = _make(comm, zero1=True, steps_per_execution=3)
    for _ in range(6):
        ref.update()
    for _ in range(2):
        fused.update()
    assert ref.iteration == fused.iteration == 6
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5),
        ref.params, fused.params)


def test_zero1_converges_in_trainer(comm):
    upd = _make(comm, zero1=True)
    trainer = cmn.Trainer(upd, (4, "epoch"))
    trainer.run()
    assert float(upd.observation["main/loss"]) < 1.0
