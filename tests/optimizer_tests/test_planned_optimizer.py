"""``create_multi_node_optimizer(plan=...)`` — the plan-driven exchange
through the full training stack: auto-tuning at init, training parity
with the default fused optimizer, the updater's ``main/exchange_time``
observation feeding the drift guard, and the plan riding the snapshot
so a resumed run compiles the identical exchange program.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import chainermn_tpu as cmn
from chainermn_tpu.models import init_mlp, mlp_apply, softmax_cross_entropy
from chainermn_tpu.training._resume import (
    collect_train_state,
    restore_train_state,
)
from chainermn_tpu.training.optimizers import PlannedOptimizer
from chainermn_tpu.utils import autotune


@pytest.fixture()
def comm():
    return cmn.create_communicator("tpu_xla")


def _dataset(n=128, dim=6, classes=3, seed=0):
    rng = np.random.RandomState(seed)
    return [(rng.randn(dim).astype(np.float32), np.int32(i % classes))
            for i in range(n)]


def _loss_fn(p, x, y):
    return softmax_cross_entropy(mlp_apply(p, x), y)


def _params():
    return init_mlp(jax.random.PRNGKey(0), [6, 12, 3])


@pytest.fixture()
def scratch_cache(tmp_path, monkeypatch):
    """Route the default plan cache (what plan='auto' consults) to a
    per-test scratch file — auto-tuning stays hermetic and fast."""
    path = str(tmp_path / "plans.json")
    monkeypatch.setenv(autotune.PLAN_CACHE_ENV, path)
    return path


def _make(comm, plan="auto", batch=16, **kw):
    it = cmn.SerialIterator(_dataset(), batch, repeat=True, shuffle=True,
                            seed=7)
    if plan is None:
        opt = cmn.create_multi_node_optimizer(optax.sgd(0.05), comm)
    else:
        opt = cmn.create_multi_node_optimizer(
            optax.sgd(0.05), comm, plan=plan)
    return cmn.StandardUpdater(it, opt, _loss_fn, _params(), comm, **kw)


def _assert_params_close(a, b, rtol=1e-5, atol=1e-6):
    jax.tree.map(
        lambda x, y: np.testing.assert_allclose(
            np.asarray(x), np.asarray(y), rtol=rtol, atol=atol), a, b)


class TestPlannedOptimizer:
    def test_auto_resolves_at_init_and_trains_at_parity(self, comm,
                                                        scratch_cache):
        planned = _make(comm)
        baseline = _make(comm, plan=None)
        cell = planned.optimizer.plan_cell
        assert isinstance(planned.optimizer, PlannedOptimizer)
        assert cell.plan is not None
        assert cell.plan.strategy in ("per_leaf", "fused_flat",
                                      "reduce_scatter")
        for _ in range(4):
            planned.update()
            baseline.update()
        # native-wire plans compute elementwise-identical reductions of
        # the same members (tight parity with the default fused path);
        # a tuned bf16-wire plan carries the documented wire tolerance
        if cell.plan.wire_dtype:
            _assert_params_close(planned.params, baseline.params,
                                 rtol=3e-2, atol=3e-2)
        else:
            _assert_params_close(planned.params, baseline.params)

    def test_explicit_plan_skips_tuning(self, comm):
        plan = autotune.Plan(strategy="reduce_scatter",
                             bucket_bytes=2048, wire_dtype=None,
                             measured_ms=1.0, key="pinned")
        upd = _make(comm, plan=plan)
        cell = upd.optimizer.plan_cell
        assert cell.plan.strategy == "reduce_scatter"
        assert cell.plan.n_probes == 0
        upd.update()
        assert upd.iteration > 0

    def test_plan_dict_accepted(self, comm):
        upd = _make(comm, plan={"strategy": "fused_flat",
                                      "bucket_bytes": 4096,
                                      "wire_dtype": None})
        upd.update()
        assert upd.optimizer.plan_cell.plan.bucket_bytes == 4096

    def test_auto_without_comm_raises(self):
        with pytest.raises(ValueError, match="comm"):
            cmn.create_multi_node_optimizer(
                optax.sgd(0.1), axis_name="world", plan="auto")

    def test_plan_with_zero1_falls_back_with_one_warning(self, comm,
                                                         monkeypatch):
        """plan='auto' must be safe to set globally: under zero1 the
        plan is ignored in favour of the analytic reduce-scatter path,
        with ONE RuntimeWarning per process (not an error, not a
        per-construction nag)."""
        import warnings as _warnings

        from chainermn_tpu.training import optimizers as _opt

        monkeypatch.setattr(_opt, "_ZERO1_PLAN_WARNED", False)
        with _warnings.catch_warnings(record=True) as rec:
            _warnings.simplefilter("always")
            opt = cmn.create_multi_node_optimizer(
                optax.sgd(0.1), comm, zero1=True, plan="auto")
            cmn.create_multi_node_optimizer(
                optax.sgd(0.1), comm, zero1=True, plan="auto")
        warned = [w for w in rec if issubclass(w.category,
                                               RuntimeWarning)]
        assert len(warned) == 1
        assert "zero1" in str(warned[0].message)
        # the fallback is the full ZeRO-1 transformation, and it trains
        from chainermn_tpu.training.optimizers import Zero1Transformation

        assert isinstance(opt, Zero1Transformation)
        it = cmn.SerialIterator(_dataset(), 16, repeat=True,
                                shuffle=True, seed=7)
        upd = cmn.StandardUpdater(it, opt, _loss_fn, _params(), comm)
        upd.update()
        assert np.isfinite(float(upd.observation["main/loss"]))

    def test_bad_plan_string_raises(self, comm):
        with pytest.raises(ValueError, match="auto"):
            cmn.create_multi_node_optimizer(
                optax.sgd(0.1), comm, plan="fastest")

    def test_unresolved_plan_fails_loudly_in_update(self, comm):
        opt = cmn.create_multi_node_optimizer(
            optax.sgd(0.1), comm, plan=autotune.PlanCell())
        from jax.sharding import PartitionSpec as P

        def step(g):
            # chain-shaped state: [planned mean, sgd] — the planned
            # reducer raises before the inner state is ever touched
            u, _ = opt.update(g, (optax.EmptyState(),
                                  optax.EmptyState()), None)
            return u

        with pytest.raises(RuntimeError, match="unresolved"):
            jax.jit(jax.shard_map(
                step, mesh=comm.mesh, in_specs=P("world"),
                out_specs=P("world")))(jnp.ones((comm.size, 4)))


class TestExchangeObservation:
    def test_exchange_time_observed_with_profiler_row(self, comm,
                                                      scratch_cache):
        from chainermn_tpu.utils.profiling import get_profiler

        upd = _make(comm, exchange_probe_every=2)
        upd.update()
        assert "main/exchange_time" not in upd.observation
        upd.update()      # 2nd window: probe fires
        assert upd.observation["main/exchange_time"] > 0
        assert "updater/exchange_time" in get_profiler().stats
        # the observation fed the drift guard
        cell = upd.optimizer.plan_cell
        assert cell.observed_s == \
            upd.observation["main/exchange_time"]

    def test_drift_guard_fires_and_retune_recovers(self, comm,
                                                   scratch_cache):
        upd = _make(comm, exchange_probe_every=1)
        cell = upd.optimizer.plan_cell
        # pretend the plan was tuned on a much faster machine: the
        # observed probe time will depart by far more than the factor
        cell.plan.measured_ms = 1e-6
        upd.update()
        assert cell.drifted
        # optional re-tune: adopts a freshly measured plan, after which
        # the observation slate is clean
        newplan = cell.retune(comm, upd.params,
                              cache_path=scratch_cache,
                              trials=1, warmup=1)
        assert cell.plan is newplan and not cell.drifted

    def test_retune_auto_invalidates_step_cache(self, comm,
                                                scratch_cache):
        """A plan change (retune / any resolve) bumps the cell's
        generation; the updater notices on its next update() and
        recompiles — no manual reach into the private step cache."""
        upd = _make(comm)
        upd.update()
        assert len(upd._step_cache) > 0
        upd.optimizer.plan_cell.resolve(autotune.Plan(
            strategy="per_leaf", bucket_bytes=1, measured_ms=1.0,
            key="swapped"))
        upd.update()      # clears + recompiles with the new plan
        assert upd._plan_generation == upd.optimizer.plan_cell.generation
        # the freshly compiled program is the only cached one
        assert len(upd._step_cache) == 1

    def test_probe_requires_planned_optimizer(self, comm):
        with pytest.raises(ValueError, match="planned optimizer"):
            _make(comm, plan=None, exchange_probe_every=1)

    def test_negative_probe_interval_rejected(self, comm,
                                               scratch_cache):
        with pytest.raises(ValueError, match=">= 0"):
            _make(comm, exchange_probe_every=-1)


class TestPlanRidesSnapshot:
    def test_collect_and_restore_roundtrip(self, comm, scratch_cache):
        writer = _make(comm)
        writer.update()
        state = collect_train_state(writer, None)
        assert state["exchange_plan"] == \
            writer.optimizer.plan_cell.plan.to_dict()

        # the reader tuned into a DIFFERENT plan (cache moved, machine
        # differs): restore must adopt the writer's and invalidate the
        # compiled steps so the resumed program is identical
        reader = _make(comm)
        reader.optimizer.plan_cell.resolve(autotune.Plan(
            strategy="per_leaf", bucket_bytes=1, measured_ms=9.9,
            key="different"))
        reader.update()
        assert len(reader._step_cache) > 0
        restore_train_state(state, reader, None)
        assert reader.optimizer.plan_cell.plan.to_dict() == \
            state["exchange_plan"]
        assert len(reader._step_cache) == 0
        reader.update()       # recompiles with the writer's plan

    def test_restore_same_plan_keeps_step_cache(self, comm,
                                                scratch_cache):
        upd = _make(comm)
        upd.update()
        state = collect_train_state(upd, None)
        n_cached = len(upd._step_cache)
        assert n_cached > 0
        restore_train_state(state, upd, None)
        # identical plan: nothing invalidated, no recompile storm
        assert len(upd._step_cache) == n_cached

    def test_restore_exec_identical_plan_keeps_step_cache(
            self, comm, scratch_cache):
        """Only the executable fields (strategy, bucket, wire) decide
        program identity: a snapshot plan differing solely in meta
        (timings, timestamps) must NOT force a recompile at resume."""
        upd = _make(comm)
        upd.update()
        state = collect_train_state(upd, None)
        n_cached = len(upd._step_cache)
        twin = dict(state["exchange_plan"])
        twin["measured_ms"] = 123.456
        twin["meta"] = {"created": "some-other-day"}
        restore_train_state(dict(state, exchange_plan=twin), upd, None)
        assert len(upd._step_cache) == n_cached

    def test_resume_is_bitwise_with_snapshot_plan(self, comm,
                                                  scratch_cache):
        """The acceptance property: resume never re-tunes into a
        different program.  Two fresh updaters restored from the same
        (params, plan) state must produce bit-identical params."""
        writer = _make(comm)
        for _ in range(2):
            writer.update()
        state = collect_train_state(writer, None)
        params = jax.tree.map(np.asarray, writer.params)

        def resume_and_step():
            upd = _make(comm)
            upd.params = upd.comm.bcast_data(
                jax.tree.map(jnp.asarray, params))
            # a resumed run may have tuned a different plan locally...
            upd.optimizer.plan_cell.resolve(autotune.Plan(
                strategy="per_leaf", bucket_bytes=1, key="local"))
            restore_train_state(state, upd, None)
            upd.update()
            return jax.tree.map(np.asarray, upd.params)

        a, b = resume_and_step(), resume_and_step()
        jax.tree.map(np.testing.assert_array_equal, a, b)

    def test_snapshot_without_plan_is_clean(self, comm):
        upd = _make(comm, plan=None)
        upd.update()
        state = collect_train_state(upd, None)
        assert "exchange_plan" not in state
        restore_train_state(state, upd, None)     # no-op, no crash
