"""fuse_steps / steps_per_execution — fused multi-step windows must be
numerically identical to the plain one-dispatch-per-step loop (the fusion
is a latency optimisation, never a semantics change)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import chainermn_tpu as cmn
from chainermn_tpu.models import init_mlp, mlp_apply, softmax_cross_entropy
from chainermn_tpu.training import fuse_steps


@pytest.fixture()
def comm():
    return cmn.create_communicator("tpu_xla")


def _toy_step():
    opt = optax.sgd(0.1, momentum=0.9)

    def step(carry, x, y):
        params, opt_state = carry

        def loss_fn(p):
            return jnp.mean((mlp_apply(p, x).squeeze(-1) - y) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return (optax.apply_updates(params, updates), opt_state), loss

    params = init_mlp(jax.random.PRNGKey(0), [4, 8, 1])
    return step, (params, opt.init(params))


class TestFuseSteps:
    def test_fixed_batch_matches_loop(self):
        step, carry = _toy_step()
        x = jnp.asarray(np.random.RandomState(0).randn(16, 4), jnp.float32)
        y = jnp.asarray(np.random.RandomState(1).randn(16), jnp.float32)

        loop_carry, losses = carry, []
        for _ in range(5):
            loop_carry, l = step(loop_carry, x, y)
            losses.append(l)

        fused = jax.jit(fuse_steps(step, 5))
        fused_carry, fused_losses = fused(carry, x, y)

        assert fused_losses.shape == (5,)
        np.testing.assert_allclose(
            np.asarray(fused_losses), np.asarray(jnp.stack(losses)),
            rtol=1e-5)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5),
            fused_carry, loop_carry)

    def test_scan_batches_matches_loop(self):
        step, carry = _toy_step()
        rng = np.random.RandomState(2)
        xs = jnp.asarray(rng.randn(4, 16, 4), jnp.float32)
        ys = jnp.asarray(rng.randn(4, 16), jnp.float32)

        loop_carry = carry
        for i in range(4):
            loop_carry, _ = step(loop_carry, xs[i], ys[i])

        fused = jax.jit(fuse_steps(step, 4, scan_batches=True))
        fused_carry, fused_losses = fused(carry, xs, ys)

        assert fused_losses.shape == (4,)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5),
            fused_carry, loop_carry)


def _dataset(n=96, dim=6, classes=3, seed=0):
    rng = np.random.RandomState(seed)
    return [(rng.randn(dim).astype(np.float32), np.int32(i % classes))
            for i in range(n)]


def _make_updater(comm, steps_per_execution, repeat=True, n=96,
                  batch_size=16):
    it = cmn.SerialIterator(_dataset(n=n), batch_size, repeat=repeat,
                            shuffle=True, seed=7)
    params = init_mlp(jax.random.PRNGKey(0), [6, 12, 3])
    opt = cmn.create_multi_node_optimizer(optax.sgd(0.05), comm)

    def loss_fn(p, x, y):
        return softmax_cross_entropy(mlp_apply(p, x), y)

    return cmn.StandardUpdater(
        it, opt, loss_fn, params, comm,
        steps_per_execution=steps_per_execution)


class TestStepsPerExecution:
    def test_identical_to_unfused(self, comm):
        plain = _make_updater(comm, 1)
        fused = _make_updater(comm, 3)

        for _ in range(6):
            plain.update()
        for _ in range(2):
            fused.update()

        assert plain.iteration == fused.iteration == 6
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
            plain.params, fused.params)

    def test_window_mean_loss_observed(self, comm):
        fused = _make_updater(comm, 3)
        fused.update()
        assert float(fused.observation["main/loss"]) > 0
        assert fused.iteration == 3

    def test_ragged_tail_batch(self, comm):
        # 40 examples / batch 16 -> batches of 16, 16, 8: the ragged tail
        # cannot stack into the window and must still be consumed.
        upd = _make_updater(comm, 4, repeat=False, n=40)
        upd.update()
        assert upd.iteration == 3
        with pytest.raises(StopIteration):
            upd.update()

    def test_iteration_trigger_crossing_inside_window(self, comm):
        # window of 3, trigger every 5 iterations: the trigger points 5,
        # 25, ... fall INSIDE fused windows (iteration jumps 3->6,
        # 24->27) and must still fire via crossing semantics.
        upd = _make_updater(comm, 3)
        trainer = cmn.Trainer(upd, (5, "epoch"))
        fired = []

        @cmn.training.make_extension(trigger=(5, "iteration"))
        def probe(tr):
            fired.append(tr.updater.iteration)

        trainer.extend(probe)
        trainer.run()
        # 30 iterations in windows of 3 -> crossings of 5 at 6,12,15,21,
        # 27,30 (one fire per crossed multiple of 5)
        assert fired == [6, 12, 15, 21, 27, 30]

    def test_stateful_model_identical_to_unfused(self, comm):
        # BN running stats thread through the fused scan exactly as
        # through per-step dispatches (the `state is not None` path)
        from chainermn_tpu.links import (init_batch_norm,
                                         multi_node_batch_normalization)

        bn_params, bn_state = init_batch_norm(6)
        w = jax.random.normal(jax.random.PRNGKey(1), (6, 3))

        def make(steps_per_execution):
            it = cmn.SerialIterator(_dataset(), 16, shuffle=True, seed=7)
            opt = cmn.create_multi_node_optimizer(optax.sgd(0.05), comm)

            def loss_fn(p, state, x, y):
                h, new_state = multi_node_batch_normalization(
                    p["bn"], state, x, axis_name=comm.axis_name)
                return softmax_cross_entropy(h @ p["w"], y), new_state

            return cmn.StandardUpdater(
                it, opt, loss_fn, {"bn": bn_params, "w": w}, comm,
                state=bn_state, steps_per_execution=steps_per_execution)

        plain, fused = make(1), make(3)
        for _ in range(6):
            plain.update()
        for _ in range(2):
            fused.update()
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
            (plain.params, plain.state), (fused.params, fused.state))
        assert int(plain.state.n) == int(fused.state.n) == 6

    def test_trainer_stop_trigger_with_fused_window(self, comm):
        # 96/16 = 6 batches/epoch; window 3 divides it: 2 updates/epoch.
        upd = _make_updater(comm, 3)
        trainer = cmn.Trainer(upd, (2, "epoch"))
        trainer.run()
        assert upd.iteration == 12
        assert upd.epoch == 2
