"""Ledger-backed training invariants (ISSUE 15): the accum training
loop compiles NOTHING after step 1 (the zero-steady-state-recompile
pin for the training half), epoch-tail shapes are ATTRIBUTED ledger
events rather than silent wall time, and GoodputReport's compile
badput decomposes a real updater window."""

import jax
import numpy as np
import optax
import pytest

import chainermn_tpu as cmn
from chainermn_tpu.models import init_mlp, mlp_apply, softmax_cross_entropy
from chainermn_tpu.utils.metrics import (
    GoodputReport,
    MetricsRegistry,
    set_registry,
)
from chainermn_tpu.utils.programs import ProgramLedger, set_ledger
from chainermn_tpu.utils.telemetry import TraceRecorder, set_recorder


@pytest.fixture()
def comm():
    return cmn.create_communicator("tpu_xla")


@pytest.fixture()
def ledger():
    led = ProgramLedger(enabled=True)
    prev = set_ledger(led)
    try:
        yield led
    finally:
        set_ledger(prev)


def _dataset(n=256, dim=6, classes=3, seed=0):
    rng = np.random.RandomState(seed)
    return [(rng.randn(dim).astype(np.float32), np.int32(i % classes))
            for i in range(n)]


def _loss_fn(p, x, y):
    return softmax_cross_entropy(mlp_apply(p, x), y)


def _make(comm, batch_size, **kw):
    it = cmn.SerialIterator(_dataset(n=kw.pop("n", 256)), batch_size,
                            repeat=kw.pop("repeat", True),
                            shuffle=True, seed=7)
    optimizer = cmn.create_multi_node_optimizer(
        optax.sgd(0.05), comm, zero1=kw.pop("zero1", False))
    params = init_mlp(jax.random.PRNGKey(0), [6, 12, 3])
    return cmn.StandardUpdater(it, optimizer, _loss_fn, params, comm,
                               **kw)


class TestZeroSteadyStateRecompile:
    def test_accum_loop_post_step_1(self, comm, ledger):
        """The acceptance invariant: step 1 compiles the one fused
        accumulating window program; every later steady window runs
        it signature-identically — zero compiles post-step-1, proven
        by the ledger."""
        upd = _make(comm, 16, accum_steps=4, steps_per_execution=2)
        upd.update()                     # step 1: the compile
        assert ledger.compiles("train/") >= 1
        stats = ledger.label_stats()["train/step"]
        assert stats["compiles"] == 1 and stats["programs"] == 1
        upd.mark_steady()
        for _ in range(6):
            upd.update()
        assert ledger.steady_retraces("train/") == 0, \
            ledger.entries(scope="train/")
        assert ledger.label_stats()["train/step"]["compiles"] == 1

    def test_rebind_world_re_records_the_recompile(self, comm,
                                                   ledger):
        """rebind_world drops the ledger's train/ signature memory:
        the rebuilt step program's compile is re-recorded even though
        the world (and so the signature) is unchanged — the
        post-resize recompile can never hide behind a seen
        signature."""
        upd = _make(comm, 16)
        upd.update()
        upd.mark_steady()
        assert ledger.compiles("train/") >= 1
        before = ledger.compiles("train/")
        for pending in list(upd._inflight):
            jax.block_until_ready(pending)
        upd.rebind_world(comm, upd.optimizer)
        assert not ledger.is_steady("train/step")
        upd.update()
        assert ledger.compiles("train/") > before
        assert ledger.steady_retraces("train/") == 0

    def test_epoch_tail_shapes_are_attributed(self, comm, ledger):
        """A non-dividing epoch tail flushes through the n_steps=1
        programs — EXTRA compiles under the same train/step label,
        each a ledger entry whose signature diff names the batch-shape
        change (the PR 4 epoch-tail story, now attributed)."""
        # 250 examples / batch 16 -> 15 full batches + a 10-row tail
        upd = _make(comm, 16, n=250, repeat=False,
                    steps_per_execution=2)
        with pytest.raises(StopIteration):
            for _ in range(100):
                upd.update()
        stats = ledger.label_stats()["train/step"]
        assert stats["compiles"] >= 2    # steady window + tail program
        entries = ledger.entries(scope="train/step")
        diffs = [e["diff"] for e in entries if e["diff"] is not None]
        assert diffs, entries
        assert any("shape" in d["kinds"] or "structure" in d["kinds"]
                   for d in diffs)


class TestGoodputDecomposition:
    def test_compile_badput_on_a_real_window(self, comm, ledger):
        """Window 1 (the step-1 compile) bills compile_s > 0 and the
        compile seconds leave productive; window 2 (steady) bills
        zero."""
        reg = MetricsRegistry(enabled=True)
        prev_reg = set_registry(reg)
        rec = TraceRecorder(enabled=True)
        prev_rec = set_recorder(rec)
        try:
            report = GoodputReport(recorder=rec, write=False,
                                   registry=reg)
            report.initialize()
            upd = _make(comm, 16, accum_steps=2)
            upd.update()
            jax.block_until_ready(upd.params)
            report()
            first = report.last_report
            assert first["badput"]["compile_s"] > 0.0
            assert first["badput"]["compile_s"] == pytest.approx(
                ledger.total_compile_s)
            for _ in range(3):
                upd.update()
            jax.block_until_ready(upd.params)
            report()
            second = report.last_report
            assert second["badput"]["compile_s"] == 0.0
            assert second["productive_s"] > 0.0
        finally:
            set_registry(prev_reg)
            set_recorder(prev_rec)
