"""StandardUpdater(accum_steps=M) — microbatched gradient accumulation
with a window-fused cross-replica exchange.

The contract under test: M microbatches accumulated locally and
exchanged ONCE per window are numerically equivalent to a single
M×-larger batch (equal-sized microbatches ⇒ mean of means), the
compiled steady-state step provably exchanges gradients once per window
(zero collectives inside the microbatch scan — assert_accum_collectives
on real HLO), the mode composes with steps_per_execution / ZeRO-1 / the
prefetched feed, and tail-of-epoch partial windows flush through
already-cached programs instead of compiling one-off shapes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import chainermn_tpu as cmn
from chainermn_tpu.models import init_mlp, mlp_apply, softmax_cross_entropy
from chainermn_tpu.utils import assert_accum_collectives, collective_stats


@pytest.fixture()
def comm():
    return cmn.create_communicator("tpu_xla")


def _dataset(n=256, dim=6, classes=3, seed=0):
    rng = np.random.RandomState(seed)
    return [(rng.randn(dim).astype(np.float32), np.int32(i % classes))
            for i in range(n)]


def _loss_fn(p, x, y):
    return softmax_cross_entropy(mlp_apply(p, x), y)


def _params():
    return init_mlp(jax.random.PRNGKey(0), [6, 12, 3])


def _make(comm, batch_size, accum_steps=1, steps_per_execution=1,
          zero1=False, opt=None, n=256, repeat=True, prefetch=0,
          accum_dtype=None, seed=7):
    it = cmn.SerialIterator(_dataset(n=n), batch_size, repeat=repeat,
                            shuffle=True, seed=seed)
    optimizer = cmn.create_multi_node_optimizer(
        opt or optax.sgd(0.05), comm, zero1=zero1)
    return cmn.StandardUpdater(
        it, optimizer, _loss_fn, _params(), comm,
        accum_steps=accum_steps, steps_per_execution=steps_per_execution,
        prefetch=prefetch, accum_dtype=accum_dtype)


def _assert_params_close(a, b, rtol=1e-5, atol=1e-6):
    jax.tree.map(
        lambda x, y: np.testing.assert_allclose(
            np.asarray(x), np.asarray(y), rtol=rtol, atol=atol), a, b)


class TestAccumParity:
    def test_matches_single_large_batch(self, comm):
        """accum_steps=4 over batch-16 microbatches == one batch-64 step
        (the correctness-equivalence the whole mode stands on)."""
        acc = _make(comm, 16, accum_steps=4)
        big = _make(comm, 64)
        for _ in range(4):
            acc.update()
            big.update()
        assert acc.iteration == 16 and big.iteration == 4
        _assert_params_close(acc.params, big.params)

    def test_matches_large_batch_with_adam(self, comm):
        """Stateful inner optimiser: moments must advance once per
        WINDOW (not per microbatch) to match the large-batch run."""
        acc = _make(comm, 16, accum_steps=4, opt=optax.adam(5e-2))
        big = _make(comm, 64, opt=optax.adam(5e-2))
        for _ in range(4):
            acc.update()
            big.update()
        _assert_params_close(acc.params, big.params, rtol=2e-4, atol=1e-5)

    def test_composes_with_steps_per_execution(self, comm):
        """steps_per_execution=2 × accum_steps=2: one dispatch carries 4
        microbatches and performs 2 optimiser updates — identical to 4
        unfused batch-32 updates over the same examples."""
        fused = _make(comm, 16, accum_steps=2, steps_per_execution=2)
        plain = _make(comm, 32)
        for _ in range(2):
            fused.update()
        for _ in range(4):
            plain.update()
        assert fused.iteration == 8 and plain.iteration == 4
        _assert_params_close(fused.params, plain.params)

    def test_zero1_composition(self, comm):
        """ZeRO-1 + accumulation: the sharded-state reduce-scatter fires
        once per window and still matches the large-batch ZeRO run."""
        acc = _make(comm, 16, accum_steps=4, zero1=True,
                    opt=optax.adam(5e-2))
        big = _make(comm, 64, zero1=True, opt=optax.adam(5e-2))
        for _ in range(4):
            acc.update()
            big.update()
        _assert_params_close(acc.params, big.params, rtol=2e-4, atol=1e-5)
        # the optimiser state really is world-stacked/sharded
        assert any(m.ndim >= 1 and m.shape[0] == comm.size
                   for m in jax.tree.leaves(acc.opt_state))

    def test_prefetched_feed_bitwise(self, comm):
        """accum + PrefetchIterator must be bitwise-identical to the
        serial accum feed (the shared window contract)."""
        serial = _make(comm, 16, accum_steps=4)
        pre = _make(comm, 16, accum_steps=4, prefetch=2)
        try:
            for _ in range(3):
                serial.update()
                pre.update()
            jax.block_until_ready(pre.params)
            jax.tree.map(
                lambda a, b: np.testing.assert_array_equal(
                    np.asarray(a), np.asarray(b)),
                serial.params, pre.params)
        finally:
            pre.finalize()

    def test_bf16_accum_dtype_runs(self, comm):
        """The accum_dtype knob: a narrow accumulator still trains
        (values drift within bf16 tolerance of the fp32 default)."""
        narrow = _make(comm, 16, accum_steps=4, accum_dtype=jnp.bfloat16)
        wide = _make(comm, 16, accum_steps=4)
        for _ in range(2):
            narrow.update()
            wide.update()
        assert narrow.accum_dtype == jnp.bfloat16
        _assert_params_close(narrow.params, wide.params, rtol=2e-2,
                             atol=2e-2)
        for leaf in jax.tree.leaves(narrow.params):
            assert np.isfinite(np.asarray(leaf)).all()


class TestAccumCollectives:
    def test_one_exchange_per_window(self, comm):
        """The M→1 proof on compiled HLO: the accum program's microbatch
        scan body contains ZERO reduction collectives and the top level
        stays within the fused budget (+1 scalar loss mean), while the
        per-microbatch program (plain fused window, same M microbatches
        per dispatch) carries its exchange INSIDE the scan body — M
        collective firings per window."""
        upd = _make(comm, 16, accum_steps=4)
        arrays, k, tail = upd._assemble_host_window()
        assert k == 4 and tail is None
        fn = upd._get_step(len(arrays), 1, 4)
        carry = (upd.params, upd.state, upd.opt_state)
        stats = collective_stats(fn.lower(carry, *arrays).compile())
        grad_bytes = sum(l.size * l.dtype.itemsize
                         for l in jax.tree.leaves(upd.params))
        n = assert_accum_collectives(stats, grad_bytes, 4 << 20)
        assert n >= 1  # the window-end exchange exists

        base = _make(comm, 16, steps_per_execution=4)
        arrays, k, tail = base._assemble_host_window()
        fnb = base._get_step(len(arrays), 4, 1)
        carry = (base.params, base.state, base.opt_state)
        per_micro = collective_stats(fnb.lower(carry, *arrays).compile())
        looped = sum(s.looped for s in per_micro.values())
        assert looped >= 1, per_micro
        with pytest.raises(AssertionError, match="inside a while body"):
            assert_accum_collectives(per_micro, grad_bytes, 4 << 20)


class TestPartialWindows:
    def test_accum_tail_flushes_through_cached_programs(self, comm):
        """80 examples / batch 8 = 10 microbatches per epoch against a
        4-deep window: 2 full windows + a 2-deep partial.  The partial
        must flush through the n_steps=1 singles program — never
        compiling a one-off (2, ...) window shape."""
        upd = _make(comm, 8, accum_steps=4, n=80, repeat=False)
        upd.update()
        upd.update()
        upd.update()
        assert upd.iteration == 10
        assert sorted(upd._step_cache) == [(2, 1, 1), (2, 1, 4)]
        with pytest.raises(StopIteration):
            upd.update()

    def test_fused_tail_flushes_through_singles(self, comm):
        """accum off, steps_per_execution=4 against a 2-full-batch
        epoch: the short window flushes as single steps via the ONE
        (n_args, 1, 1) executable instead of compiling a (2,)-window
        program (pre-change behaviour compiled a fresh shape per
        distinct tail length)."""
        upd = _make(comm, 16, steps_per_execution=4, n=40, repeat=False)
        upd.update()                       # 16, 16 flushed + ragged 8
        assert upd.iteration == 3
        assert sorted(upd._step_cache) == [(2, 1, 1)]
        with pytest.raises(StopIteration):
            upd.update()

    def test_partial_flush_deterministic(self, comm):
        """The flushed partial window is part of training semantics:
        two identically-seeded accum runs over the ragged epoch must
        land on bitwise-identical params."""
        a = _make(comm, 8, accum_steps=4, n=80, repeat=False, seed=3)
        c = _make(comm, 8, accum_steps=4, n=80, repeat=False, seed=3)
        for _ in range(3):
            a.update()
            c.update()
        jax.tree.map(
            lambda x, y: np.testing.assert_array_equal(
                np.asarray(x), np.asarray(y)), a.params, c.params)


class TestAccumBookkeeping:
    def test_observation_reports_accum_time(self, comm):
        upd = _make(comm, 16, accum_steps=4)
        upd.update()
        obs = upd.observation
        assert "main/accum_time" in obs
        # step_time is per microbatch, accum_time per optimiser update:
        # their ratio is exactly the window depth
        np.testing.assert_allclose(
            obs["main/accum_time"], obs["main/step_time"] * 4, rtol=1e-9)
        assert float(obs["main/loss"]) > 0

    def test_no_accum_time_when_disabled(self, comm):
        upd = _make(comm, 16)
        upd.update()
        assert "main/accum_time" not in upd.observation

    def test_mixed_window_loss_is_microbatch_weighted(self, comm):
        """A partial window that flushes as one M-group + a leftover
        single + a ragged tail mixes an M-microbatch mean with
        1-microbatch losses: main/loss must weight them M:1:1 (the
        per-microbatch mean an unfused updater would log), not average
        the three entries equally."""
        def mk(accum, spe=1):
            # 56 examples / batch 16 = 3 full batches + a ragged 8:
            # against a spe=2 × M=2 window the ragged pull interrupts
            # assembly at k=3 → one M-group (weight 2) + one single
            # (weight 1) + the tail (weight 1), all in ONE update
            it = cmn.SerialIterator(_dataset(n=56), 16, repeat=False,
                                    shuffle=True, seed=9)
            # lr=0: params never move, so every microbatch loss is
            # comparable across the two updaters
            opt = cmn.create_multi_node_optimizer(optax.sgd(0.0), comm)
            return cmn.StandardUpdater(it, opt, _loss_fn, _params(),
                                       comm, accum_steps=accum,
                                       steps_per_execution=spe)

        acc, plain = mk(2, spe=2), mk(1)
        acc.update()
        assert acc.iteration == 4
        per_micro = []
        for _ in range(4):
            plain.update()
            per_micro.append(float(plain.observation["main/loss"]))
        np.testing.assert_allclose(
            float(acc.observation["main/loss"]), np.mean(per_micro),
            rtol=1e-6)

    def test_trainer_triggers_count_microbatches(self, comm):
        """256/16 = 16 microbatches per epoch, window 4: iteration
        advances 4 per update and epoch triggers fire on data
        consumed."""
        upd = _make(comm, 16, accum_steps=4)
        trainer = cmn.Trainer(upd, (2, "epoch"))
        trainer.run()
        assert upd.iteration == 32
        assert upd.epoch == 2

    def test_resume_from_snapshot_bitwise(self, comm):
        """Mid-stream snapshot/restore: accumulation carries NO state
        across windows (the accumulator lives inside the step), so a
        checkpoint at a window boundary resumes bitwise."""
        a = _make(comm, 16, accum_steps=4)
        for _ in range(2):
            a.update()
        snap_it = a.iterator.state_dict()
        snap_params = jax.tree.map(np.asarray, a.params)
        snap_opt = jax.tree.map(np.asarray, a.opt_state)

        b = _make(comm, 16, accum_steps=4)
        b.iterator.load_state_dict(
            {k: (v.copy() if isinstance(v, np.ndarray) else v)
             for k, v in snap_it.items()})
        b.params = jax.tree.map(jnp.asarray, snap_params)
        b.opt_state = jax.tree.map(jnp.asarray, snap_opt)
        b.iteration = a.iteration
        for _ in range(3):
            a.update()
            b.update()
        jax.tree.map(
            lambda x, y: np.testing.assert_array_equal(
                np.asarray(x), np.asarray(y)), a.params, b.params)

    def test_invalid_args(self, comm):
        with pytest.raises(ValueError, match="accum_steps"):
            _make(comm, 16, accum_steps=0)
        it = cmn.SerialIterator(_dataset(), 16, shuffle=True, seed=7)
        pre = cmn.PrefetchIterator(it, comm, steps_per_execution=4,
                                   depth=2)
        try:
            with pytest.raises(ValueError, match="accum_steps"):
                # a prebuilt 4-deep prefetcher cannot serve a
                # steps_per_execution × accum_steps = 8 window
                cmn.StandardUpdater(
                    pre, cmn.create_multi_node_optimizer(
                        optax.sgd(0.05), comm),
                    _loss_fn, _params(), comm,
                    steps_per_execution=2, accum_steps=4)
        finally:
            pre.close()
