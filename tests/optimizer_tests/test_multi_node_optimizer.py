"""Multi-node optimizer — analogue of the reference's ``optimizer_tests``:
grad averaging correctness vs local NumPy mean, bf16 mode with loosened
tolerance, double-buffering staleness semantics."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from chainermn_tpu import create_communicator, create_multi_node_optimizer
from chainermn_tpu.training.optimizers import cross_replica_mean

AX = "world"


@pytest.fixture()
def comm():
    return create_communicator("tpu_xla", axis_name=AX)


def run_sharded_update(comm, opt, grads_per_rank, params):
    """Apply opt.update under shard_map with per-rank grads; return updates
    (world-stacked) and the new params from rank 0's perspective."""
    n = comm.size

    def step(params, grads):
        state = opt.init(params)
        updates, _ = opt.update(grads, state, params)
        return updates

    f = jax.jit(jax.shard_map(
        step, mesh=comm.mesh, in_specs=(P(), P(AX)), out_specs=P()))
    return f(params, grads_per_rank)


class TestCrossReplicaMean:
    def test_matches_numpy_mean(self, comm):
        n = comm.size
        params = {"w": jnp.zeros(3)}
        grads = np.random.RandomState(0).randn(n, 3).astype(np.float32)
        opt = cross_replica_mean(AX)

        def step(g):
            state = opt.init(params)
            u, _ = opt.update({"w": g}, state, params)
            return u["w"]

        f = jax.jit(jax.shard_map(
            step, mesh=comm.mesh, in_specs=P(AX), out_specs=P()))
        out = np.asarray(f(grads))  # per-shard (1, 3), replicated
        np.testing.assert_allclose(out[0], grads.mean(0), rtol=1e-5)

    def test_bf16_wire_dtype(self, comm):
        n = comm.size
        params = {"w": jnp.zeros(16)}
        grads = np.random.RandomState(1).randn(n, 16).astype(np.float32)
        opt = cross_replica_mean(AX, dtype=jnp.bfloat16)

        def step(g):
            state = opt.init(params)
            u, _ = opt.update({"w": g}, state, params)
            return u["w"]

        f = jax.jit(jax.shard_map(
            step, mesh=comm.mesh, in_specs=P(AX), out_specs=P()))
        out = np.asarray(f(grads))
        assert out.dtype == np.float32  # cast back after the wire
        np.testing.assert_allclose(out[0], grads.mean(0), rtol=3e-2, atol=3e-2)


class TestMultiNodeOptimizer:
    def test_sgd_equivalence_to_serial_large_batch(self, comm):
        """DP training on N shards == serial training on the full batch —
        THE correctness invariant of data parallelism."""
        n = comm.size
        rng = np.random.RandomState(2)
        X = rng.randn(n * 8, 4).astype(np.float32)
        y = rng.randn(n * 8, 1).astype(np.float32)
        w0 = np.zeros((4, 1), np.float32)

        def loss(w, xb, yb):
            return jnp.mean((xb @ w - yb) ** 2)

        # serial reference
        w_serial = jnp.asarray(w0)
        opt_serial = optax.sgd(0.1)
        st = opt_serial.init(w_serial)
        for _ in range(5):
            g = jax.grad(loss)(w_serial, X, y)
            u, st = opt_serial.update(g, st, w_serial)
            w_serial = optax.apply_updates(w_serial, u)

        # distributed — differentiate the pmean'd loss (StandardUpdater
        # pattern): grads come out as the global mean; the optimizer's
        # cross_replica_mean is then an idempotent no-op on top.
        opt = create_multi_node_optimizer(optax.sgd(0.1), comm)

        def dist_step(w, state, xb, yb):
            g = jax.grad(
                lambda p: jax.lax.pmean(loss(p, xb, yb), AX))(w)
            u, state = opt.update(g, state, w)
            return optax.apply_updates(w, u), state

        f = jax.jit(jax.shard_map(
            dist_step, mesh=comm.mesh,
            in_specs=(P(), P(), P(AX), P(AX)), out_specs=(P(), P())))
        w = jnp.asarray(w0)
        state = opt.init(w)
        for _ in range(5):
            w, state = f(w, state, X, y)
        np.testing.assert_allclose(np.asarray(w), np.asarray(w_serial),
                                   rtol=1e-5, atol=1e-6)

    def test_requires_axis(self):
        with pytest.raises(ValueError, match="comm or axis_name"):
            create_multi_node_optimizer(optax.sgd(0.1))

    def test_double_buffering_is_one_step_stale(self, comm):
        """Step t applies step t-1's mean grads; first step applies zeros —
        the reference's pipelined-SGD contract."""
        opt = create_multi_node_optimizer(
            optax.sgd(1.0), comm, double_buffering=True)
        w0 = jnp.zeros(2)

        def step(w, state, g):
            u, state = opt.update(g, state, w)
            return optax.apply_updates(w, u), state

        f = jax.jit(jax.shard_map(
            step, mesh=comm.mesh, in_specs=(P(), P(), P(AX)),
            out_specs=(P(), P())))
        state = opt.init(w0)
        g1 = np.tile(np.array([[1.0, 2.0]], np.float32), (comm.size, 1))
        g2 = np.tile(np.array([[10.0, 20.0]], np.float32), (comm.size, 1))
        w1, state = f(w0, state, g1)
        np.testing.assert_allclose(np.asarray(w1), 0.0)  # first: zeros
        w2, state = f(w1, state, g2)
        np.testing.assert_allclose(np.asarray(w2)[0], [-1.0, -2.0])  # g1

    def test_large_batch_recipe_composition(self, comm):
        """BASELINE config 5 composition: warmup→decay LR schedule ×
        double buffering × bf16 wire dtype.  Step t must apply
        lr(t) × mean(grads at t−1) — the schedule advances with the
        step counter while the gradient is one step stale."""
        import sys, os
        sys.path.insert(0, os.path.join(
            os.path.dirname(__file__), "..", "..", "examples", "imagenet"))
        from train_imagenet_large_batch import make_lr_schedule

        sched = make_lr_schedule(base_lr=0.1, global_batch=1024,
                                 warmup_epochs=1, total_epochs=3,
                                 steps_per_epoch=4)
        # linear scaling: peak lr = 0.1 * 1024/256 = 0.4, reached at step 4
        np.testing.assert_allclose(float(sched(0)), 0.1, rtol=1e-6)
        np.testing.assert_allclose(float(sched(4)), 0.4, rtol=1e-6)
        assert float(sched(8)) < 0.4  # cosine decay after warmup

        opt = create_multi_node_optimizer(
            optax.sgd(sched), comm, double_buffering=True,
            allreduce_grad_dtype=jnp.bfloat16)

        def step(w, state, g):
            u, state = opt.update(g, state, w)
            return optax.apply_updates(w, u), state

        f = jax.jit(jax.shard_map(
            step, mesh=comm.mesh, in_specs=(P(), P(), P(AX)),
            out_specs=(P(), P())))
        w = jnp.zeros(2)
        state = opt.init(w)
        # per-rank grads whose mean is [1, 2] (exercises the pmean too)
        base = np.tile(np.array([[1.0, 2.0]], np.float32), (comm.size, 1))
        scale = (np.arange(comm.size, dtype=np.float32)[:, None] + 0.5) * 2 \
            / comm.size
        g = base * scale  # mean over ranks == base[0]
        w, state = f(w, state, g)
        np.testing.assert_allclose(np.asarray(w), 0.0, atol=1e-7)  # stale 0
        w, state = f(w, state, g)
        # step 1 applies lr(1) × mean grad from step 0 (bf16 wire: ~1e-2)
        lr1 = float(sched(1))
        np.testing.assert_allclose(
            np.asarray(w)[0], [-lr1 * 1.0, -lr1 * 2.0], rtol=2e-2)


class TestGradientAccumulation:
    def _step_fn(self, comm, opt, zero1):
        """zero1: world-stacked state carry (zero1_init contract);
        plain: replicated state exactly like StandardUpdater passes it."""
        if zero1:
            def body(params, state, grads):
                g = jax.tree.map(lambda a: a[0], grads)
                state = jax.tree.map(lambda a: a[0], state)
                updates, state = opt.update(g, state, params)
                state = jax.tree.map(lambda a: a[None], state)
                return optax.apply_updates(params, updates), state

            return jax.jit(jax.shard_map(
                body, mesh=comm.mesh,
                in_specs=(P(), P(AX), P(AX)), out_specs=(P(), P(AX))))

        def body(params, state, grads):
            g = jax.tree.map(lambda a: a[0], grads)
            updates, state = opt.update(g, state, params)
            return optax.apply_updates(params, updates), state

        return jax.jit(jax.shard_map(
            body, mesh=comm.mesh,
            in_specs=(P(), P(), P(AX)), out_specs=(P(), P())))

    def _init(self, comm, opt, params, zero1):
        from chainermn_tpu.training.optimizers import zero1_init

        if zero1:
            return zero1_init(opt, params, comm.mesh, AX)
        return jax.jit(opt.init)(params)

    @pytest.mark.parametrize("zero1", [False, True])
    @pytest.mark.parametrize("inner", ["sgd", "adam"])
    def test_two_micro_steps_equal_one_big(self, comm, zero1, inner):
        from chainermn_tpu.parallel._compat import HAS_VMA

        if not zero1 and not HAS_VMA:
            # the pmean path's accumulation scan carry gains replication
            # the first time the mean fires; old check_rep forbids a
            # rep-gaining carry (the zero1 arm's reduce-scatter typing
            # stays varying, so it runs everywhere)
            pytest.skip("pmean accumulation scan requires vma typing")
        make = {"sgd": lambda: optax.sgd(0.5),
                "adam": lambda: optax.adam(1e-2)}[inner]
        n = comm.size
        params = {"w": jnp.ones(6)}
        rng = np.random.RandomState(0)
        g1 = {"w": jnp.asarray(rng.randn(n, 6), jnp.float32)}
        g2 = {"w": jnp.asarray(rng.randn(n, 6), jnp.float32)}

        opt = create_multi_node_optimizer(
            make(), comm, accum_steps=2, zero1=zero1)
        state = self._init(comm, opt, params, zero1)
        step = self._step_fn(comm, opt, zero1)
        p_mid, state = step(params, state, g1)
        # non-final micro-step: parameters must NOT move
        np.testing.assert_array_equal(np.asarray(p_mid["w"]),
                                      np.asarray(params["w"]))
        p_acc, _ = step(p_mid, state, g2)

        ref_opt = create_multi_node_optimizer(make(), comm, zero1=zero1)
        ref_state = self._init(comm, ref_opt, params, zero1)
        g_big = {"w": (g1["w"] + g2["w"]) / 2.0}
        p_ref, _ = self._step_fn(comm, ref_opt, zero1)(params, ref_state, g_big)
        np.testing.assert_allclose(
            np.asarray(p_acc["w"]), np.asarray(p_ref["w"]),
            rtol=1e-5, atol=1e-6)

    def test_invalid_accum_steps(self, comm):
        with pytest.raises(ValueError, match="accum_steps"):
            create_multi_node_optimizer(optax.sgd(0.1), comm,
                                        accum_steps=0)


class TestMuDtypeBf16:
    """optax ``mu_dtype="bfloat16"`` through the multi-node wrapper:
    the first-moment traffic lever the r4 roofline itemised (9.2
    GB/step of Adam state on the 300M config).  The second moment
    stays fp32, so the update direction survives the cast — pinned
    here by a short training trajectory staying close to the fp32-mu
    run while the stored mu really is bf16."""

    def test_trajectory_close_and_state_is_bf16(self, comm):
        def train(mu_dtype):
            opt = create_multi_node_optimizer(
                optax.adam(1e-2, mu_dtype=mu_dtype), comm)
            params = {"w": jnp.ones((4, 4)) * 0.5}
            state = jax.jit(opt.init)(params)
            x = jnp.asarray(
                np.random.RandomState(0).randn(comm.size, 4, 4),
                jnp.float32)

            def loss_fn(p):
                return jnp.mean((p["w"] - x[0]) ** 2)

            grad = jax.jit(jax.grad(loss_fn))
            update = jax.jit(jax.shard_map(
                lambda gg, ss, pp: opt.update(gg, ss, pp),
                mesh=comm.mesh, in_specs=(P(), P(), P()),
                out_specs=P()))
            losses = []
            for _ in range(20):
                losses.append(float(loss_fn(params)))
                u, state = update(grad(params), state, params)
                params = optax.apply_updates(params, u)
            return losses, state

        fp_losses, _ = train(None)
        bf_losses, bf_state = train(jnp.bfloat16)
        # the stored first moment really is bf16
        mus = [l for l in jax.tree.leaves(bf_state)
               if hasattr(l, "dtype") and l.dtype == jnp.bfloat16]
        assert mus, "no bf16 moment found in the optimizer state"
        # and the trajectory stays close to the fp32-mu run
        np.testing.assert_allclose(bf_losses, fp_losses,
                                   rtol=2e-2, atol=1e-4)
