"""Admission control: predictor math, quota/queue-bound/deadline
shedding, cancellation, deadline scheduling, and the deterministic
tie-breaks the seeded overload bench depends on.

The host-side policy pieces (predictor, controller verdicts) are pure
and tested without a mesh; the engine-integration pieces reuse the
conftest MiniLM fixtures.  Token identity for everything ADMITTED
stays pinned by the oracle, sheds and all — admission control must
change WHO is served, never WHAT they are served."""

import time

import numpy as np
import pytest

from chainermn_tpu.serving import (
    SHED_REASONS,
    AdmissionController,
    ServiceTimePredictor,
    ServingEngine,
    ShedCompletion,
)
from chainermn_tpu.serving.engine import Request
from chainermn_tpu.utils.metrics import MetricsRegistry, set_registry


def _req(rid, max_new=8, priority=0, tenant=None, deadline=None,
         t_submit=0.0, plen=4):
    return Request(rid, np.zeros(plen, np.int32), max_new,
                   t_submit=t_submit, priority=priority, tenant=tenant,
                   deadline=deadline)


class TestPredictor:
    def test_cold_predicts_nothing(self):
        p = ServiceTimePredictor()
        assert p.ttft() is None and p.tpot() is None
        assert p.predict_e2e(10) is None
        assert p.predict_remaining(10) is None

    def test_defaults_until_min_count(self):
        p = ServiceTimePredictor(default_ttft=0.5, default_tpot=0.01,
                                 min_count=4)
        assert p.predict_e2e(11) == pytest.approx(0.5 + 0.01 * 10)
        for _ in range(4):
            p.observe_ttft(0.1)
            p.observe_tpot(0.002)
        # live percentiles replace the defaults once fed
        assert p.ttft() == pytest.approx(0.1)
        assert p.predict_e2e(11) == pytest.approx(0.1 + 0.002 * 10)

    def test_quantile_is_the_tail(self):
        p = ServiceTimePredictor(quantile=90.0, min_count=1)
        for v in (0.01, 0.01, 0.01, 0.01, 0.01, 0.01, 0.01, 0.01, 0.01,
                  1.0):
            p.observe_tpot(v)
        assert p.tpot() > 10 * 0.01       # the tail, not the median
        assert p.tpot() == pytest.approx(
            float(np.percentile([0.01] * 9 + [1.0], 90)))

    def test_validation(self):
        with pytest.raises(ValueError, match="quantile"):
            ServiceTimePredictor(quantile=0)
        with pytest.raises(ValueError, match="min_count"):
            ServiceTimePredictor(min_count=0)

    def test_snapshot(self):
        p = ServiceTimePredictor(default_tpot=0.1)
        snap = p.snapshot()
        assert snap["tpot"] == 0.1 and snap["ttft_count"] == 0

    def test_predict_queue_drain(self):
        """The retry-after estimate (ROADMAP admission open end #3):
        backlog tokens over the aggregate decode rate ``n_slots /
        TPOT`` — TTFT deliberately amortised away, cold stays None."""
        p = ServiceTimePredictor()
        assert p.predict_queue_drain(100, 8) is None   # no evidence
        p = ServiceTimePredictor(default_ttft=9.9, default_tpot=0.01)
        assert p.predict_queue_drain(800, 8) == pytest.approx(1.0)
        assert p.predict_queue_drain(0, 8) == 0.0
        assert p.predict_queue_drain(-5, 8) == 0.0     # clamped
        # degenerate slot counts never divide by zero
        assert p.predict_queue_drain(80, 0) == pytest.approx(0.8)
        # the controller surface is a pass-through of the same estimate
        c = AdmissionController(predictor=p)
        assert c.retry_after(800, 8) == pytest.approx(1.0)
        assert AdmissionController().retry_after(800, 8) is None


class TestControllerVerdicts:
    def test_unbounded_admits_everything(self):
        c = AdmissionController()
        admit, reason, victim = c.check_submit(_req("a"), [], {})
        assert (admit, reason, victim) == (True, None, None)

    def test_quota_shed(self):
        c = AdmissionController(quotas={"t": 20})
        admit, reason, _ = c.check_submit(
            _req("a", max_new=8, tenant="t"), [], {"t": 16})
        assert not admit and reason == "over_quota"
        # exactly-at-quota admits
        admit, _, _ = c.check_submit(
            _req("a", max_new=4, tenant="t"), [], {"t": 16})
        assert admit
        # other tenants unaffected (no default quota)
        admit, _, _ = c.check_submit(
            _req("a", max_new=100, tenant="u"), [], {"t": 16})
        assert admit

    def test_default_quota_and_anonymous_tenant(self):
        c = AdmissionController(default_quota=10)
        admit, reason, _ = c.check_submit(
            _req("a", max_new=8), [], {None: 8})
        assert not admit and reason == "over_quota"

    def test_deadline_shed_needs_evidence(self):
        cold = AdmissionController()
        admit, _, _ = cold.check_submit(
            _req("a", deadline=0.001), [], {})
        assert admit                     # cold predictor: optimistic
        hot = AdmissionController(predictor=ServiceTimePredictor(
            default_ttft=1.0, default_tpot=0.1, min_count=99))
        admit, reason, _ = hot.check_submit(
            _req("a", max_new=10, deadline=0.5, t_submit=0.0), [], {})
        assert not admit and reason == "deadline"
        # a generous deadline admits
        admit, _, _ = hot.check_submit(
            _req("a", max_new=10, deadline=10.0, t_submit=0.0), [], {})
        assert admit
        # shed_on_deadline=False disables prediction
        off = AdmissionController(predictor=hot.predictor,
                                  shed_on_deadline=False)
        admit, _, _ = off.check_submit(
            _req("a", max_new=10, deadline=0.5, t_submit=0.0), [], {})
        assert admit

    def test_queue_bound_and_displacement(self):
        c = AdmissionController(max_queue=2)
        queue = [_req("q0", priority=1), _req("q1", priority=2)]
        # same-or-higher priority arrival displaces the least
        # important, NEWEST queued request
        admit, reason, victim = c.check_submit(
            _req("a", priority=0), queue, {})
        assert admit and reason == "queue_full" and victim is queue[1]
        # no lower-priority victim -> the arrival is shed
        admit, reason, victim = c.check_submit(
            _req("a", priority=2), queue, {})
        assert not admit and reason == "queue_full" and victim is None

    def test_displacement_tie_breaks_newest(self):
        c = AdmissionController(max_queue=3)
        queue = [_req("q0", priority=2), _req("q1", priority=2),
                 _req("q2", priority=2)]
        _, _, victim = c.check_submit(_req("a", priority=0), queue, {})
        assert victim is queue[2]        # ties on priority: newest goes

    def test_check_queued(self):
        pred = ServiceTimePredictor(default_tpot=0.1, min_count=99)
        c = AdmissionController(predictor=pred)
        # 10 tokens -> 1s predicted remaining; 0.5s of slack left
        assert c.check_queued(_req("a", max_new=10, deadline=100.5),
                              now=100.0) == "deadline"
        assert c.check_queued(_req("a", max_new=10, deadline=102.0),
                              now=100.0) is None
        assert c.check_queued(_req("a", max_new=10), now=100.0) is None

    def test_validation(self):
        with pytest.raises(ValueError, match="max_queue"):
            AdmissionController(max_queue=0)
        with pytest.raises(ValueError, match="quota"):
            AdmissionController(quotas={"t": 0})
        with pytest.raises(ValueError, match="default_quota"):
            AdmissionController(default_quota=0)

    def test_shed_completion_reason_coded(self):
        with pytest.raises(ValueError, match="reason"):
            ShedCompletion("r", np.zeros(1, np.int32), "nope", 0.0, 1.0)
        s = ShedCompletion("r", np.zeros(1, np.int32), "queue_full",
                           0.0, 1.0)
        assert s.n_generated == 0 and s.tokens.shape == (0,)
        assert s.status == "shed" and s.reason in SHED_REASONS


@pytest.fixture(scope="module")
def engine(mini_adapter, mini_params):
    return ServingEngine(mini_adapter, mini_params, n_slots=8,
                         horizon=160, max_prompt=16, block=8,
                         round_tokens=4)


@pytest.fixture()
def registry():
    reg = MetricsRegistry(enabled=True)
    prev = set_registry(reg)
    yield reg
    set_registry(prev)


def _clear_admission(engine):
    engine.admission = None


class TestEngineAdmission:
    def test_submit_returns_typed_reject_and_records_it(self, engine,
                                                        registry):
        engine.reset()
        engine.admission = AdmissionController(max_queue=1)
        try:
            rng = np.random.RandomState(0)
            r1 = engine.submit(rng.randint(0, 64, 6), max_new=4)
            assert isinstance(r1, str)
            r2 = engine.submit(rng.randint(0, 64, 6), max_new=4)
            assert isinstance(r2, ShedCompletion)
            assert r2.reason == "queue_full"
            recs = engine.request_records()
            assert recs and recs[-1] is r2
            snap = engine.metrics_snapshot()
            assert snap["serve/shed_total"]["value"] == 1
            assert snap["serve/shed_queue_full"]["value"] == 1
            assert engine.stats()["shed"] == {"queue_full": 1}
            comps = engine.run(max_steps=500)
            assert [c.status for c in comps] == ["ok"]
        finally:
            _clear_admission(engine)

    def test_displacement_sheds_victim_not_arrival(self, engine):
        engine.reset()
        try:
            rng = np.random.RandomState(1)
            # fill every slot so the queue actually holds
            blockers = [engine.submit(rng.randint(0, 64, 6), max_new=24)
                        for _ in range(8)]
            assert all(isinstance(b, str) for b in blockers)
            engine.step()
            engine.admission = AdmissionController(max_queue=2)
            lo1 = engine.submit(rng.randint(0, 64, 6), max_new=4,
                                priority=2)
            lo2 = engine.submit(rng.randint(0, 64, 6), max_new=4,
                                priority=2)
            hi = engine.submit(rng.randint(0, 64, 6), max_new=4,
                               priority=0)
            assert isinstance(hi, str)
            out = engine.run(max_steps=1000)
            sheds = [c for c in out if isinstance(c, ShedCompletion)]
            assert len(sheds) == 1 and sheds[0].rid == lo2
            assert sheds[0].reason == "queue_full"
            assert "displaced" in sheds[0].detail
            served = {c.rid for c in out if not isinstance(
                c, ShedCompletion)}
            assert hi in served and lo1 in served
        finally:
            _clear_admission(engine)

    def test_tenant_quota_inflight_released_on_completion(self, engine):
        engine.reset()
        engine.admission = AdmissionController(quotas={"t": 8})
        try:
            rng = np.random.RandomState(2)
            a = engine.submit(rng.randint(0, 64, 6), max_new=8,
                              tenant="t")
            assert isinstance(a, str)
            b = engine.submit(rng.randint(0, 64, 6), max_new=1,
                              tenant="t")
            assert isinstance(b, ShedCompletion)
            assert b.reason == "over_quota"
            engine.run(max_steps=500)       # a completes, quota frees
            c = engine.submit(rng.randint(0, 64, 6), max_new=8,
                              tenant="t")
            assert isinstance(c, str)
            engine.run(max_steps=500)
        finally:
            _clear_admission(engine)

    def test_predictive_deadline_shed_at_submit(self, engine):
        engine.reset()
        engine.admission = AdmissionController(
            predictor=ServiceTimePredictor(default_ttft=10.0,
                                           default_tpot=1.0,
                                           min_count=99))
        try:
            r = engine.submit(np.arange(4) % 64, max_new=8, timeout=0.5)
            assert isinstance(r, ShedCompletion)
            assert r.reason == "deadline"
        finally:
            _clear_admission(engine)

    def test_queued_timeout_sheds_not_ages(self, engine):
        engine.reset()
        rng = np.random.RandomState(3)
        # all slots busy; the deadlined request waits in queue
        for _ in range(8):
            engine.submit(rng.randint(0, 64, 6), max_new=24)
        engine.step()
        doomed = engine.submit(rng.randint(0, 64, 6), max_new=4,
                               timeout=1e-4)
        time.sleep(2e-3)
        out = engine.run(max_steps=1000)
        sheds = [c for c in out if isinstance(c, ShedCompletion)]
        assert [s.rid for s in sheds] == [doomed]
        assert sheds[0].reason == "timeout"
        assert engine.stats()["shed"] == {"timeout": 1}

    def test_midstream_timeout_partial_tokens(self, engine, oracle,
                                              registry):
        engine.reset()
        rng = np.random.RandomState(4)
        p = rng.randint(0, 64, 8)
        rid = engine.submit(p, max_new=30)
        engine.step()
        engine.step()
        (s,) = [s for s in range(8) if engine._slot_req[s] is not None]
        engine._slot_req[s].deadline = time.perf_counter() - 1.0
        comps = engine.run(max_steps=500)
        (c,) = comps
        assert c.rid == rid and c.status == "timeout"
        assert 0 < c.n_generated < 30
        # the partial tokens are a PREFIX of the solo decode — a
        # timeout truncates, never corrupts
        np.testing.assert_array_equal(c.tokens,
                                      oracle(p, 30)[:c.n_generated])
        snap = engine.metrics_snapshot()
        assert snap["serve/timeouts"]["value"] == 1
        assert engine.stats()["timeouts"] == 1
        assert engine.stats()["wasted_tokens"] == c.n_generated

    def test_cancel_queued_and_active(self, engine, registry):
        engine.reset()
        rng = np.random.RandomState(5)
        for _ in range(8):
            engine.submit(rng.randint(0, 64, 6), max_new=16)
        engine.step()
        queued = engine.submit(rng.randint(0, 64, 6), max_new=4)
        active = engine.admit_log[0]
        assert engine.cancel(queued) and engine.cancel(active)
        assert not engine.cancel("nope")
        assert not engine.cancel(queued)    # already drained
        out = engine.run(max_steps=1000)
        sheds = {c.rid for c in out if isinstance(c, ShedCompletion)}
        assert sheds == {queued}
        by_rid = {c.rid: c for c in out
                  if not isinstance(c, ShedCompletion)}
        assert by_rid[active].status == "cancelled"
        assert engine.stats()["cancelled"] == 1
        assert engine.stats()["shed"] == {"cancelled": 1}
        snap = engine.metrics_snapshot()
        assert snap["serve/cancelled"]["value"] == 1
        assert snap["serve/shed_cancelled"]["value"] == 1

    def test_cancel_after_done_does_not_relabel(self, engine):
        """Racing cancel() against completion: a row that already
        finished its decode (done, awaiting eviction) must NOT be
        relabelled cancelled — the caller gets False and the served
        completion stays ok."""
        engine.reset()
        rid = engine.submit(np.arange(6) % 64, max_new=4)
        engine.step()               # admit + round: budget reached
        (s,) = [s for s in range(engine.n_slots)
                if engine._slot_req[s] is not None]
        assert engine._done[s]      # finished, not yet evicted
        assert not engine.cancel(rid)
        (c,) = engine.run(max_steps=200)
        assert c.status == "ok" and c.n_generated == 4
        assert engine.stats()["cancelled"] == 0

    def test_ttft_tpot_feed_attached_predictor(self, engine):
        engine.reset()
        ctrl = AdmissionController()
        engine.admission = ctrl
        try:
            rng = np.random.RandomState(6)
            for _ in range(4):
                engine.submit(rng.randint(0, 64, 6), max_new=6)
            engine.run(max_steps=500)
            assert ctrl.predictor.ttft_hist.count == 4
            assert ctrl.predictor.tpot_hist.count == 4
        finally:
            _clear_admission(engine)

    def test_timeout_validation(self, engine):
        engine.reset()
        with pytest.raises(ValueError, match="not both"):
            engine.submit(np.arange(4) % 64, max_new=4, timeout=1.0,
                          deadline=time.perf_counter() + 1)
        with pytest.raises(ValueError, match="timeout"):
            engine.submit(np.arange(4) % 64, max_new=4, timeout=0.0)


class TestDeterministicPolicies:
    def test_spf_ties_break_by_submit_order(self, engine):
        engine.reset()
        rng = np.random.RandomState(7)
        # 12 equal-length prompts: spf must degrade to exact FCFS
        rids = [engine.submit(rng.randint(0, 64, 6), max_new=4)
                for _ in range(12)]
        engine.set_policy("spf")
        try:
            engine.run(max_steps=500)
            assert engine.admit_log == rids
        finally:
            engine.set_policy("fcfs")

    def test_deadline_policy_orders_by_slack(self, engine):
        engine.reset()
        engine.set_policy("deadline")
        engine.admission = AdmissionController(
            predictor=ServiceTimePredictor(default_ttft=0.0,
                                           default_tpot=0.0,
                                           min_count=99))
        try:
            rng = np.random.RandomState(8)
            # saturate slots so ordering among the queued is visible
            blockers = [engine.submit(rng.randint(0, 64, 6),
                                      max_new=12) for _ in range(8)]
            engine.step()
            loose = engine.submit(rng.randint(0, 64, 6), max_new=4,
                                  timeout=500.0)
            tight = engine.submit(rng.randint(0, 64, 6), max_new=4,
                                  timeout=400.0)
            none_ = engine.submit(rng.randint(0, 64, 6), max_new=4)
            engine.run(max_steps=1000)
            admits = engine.admit_log
            assert admits[:8] == blockers
            order = [admits.index(r) for r in (tight, loose, none_)]
            assert order == sorted(order)   # tightest slack first,
        finally:                            # deadline-less last
            engine.set_policy("fcfs")
            _clear_admission(engine)

    def test_deadline_policy_priority_classes_first(self, engine):
        engine.reset()
        engine.set_policy("deadline")
        try:
            rng = np.random.RandomState(9)
            blockers = [engine.submit(rng.randint(0, 64, 6),
                                      max_new=12) for _ in range(8)]
            engine.step()
            # class 1 with a tight deadline loses to class 0 without
            lo = engine.submit(rng.randint(0, 64, 6), max_new=4,
                               priority=1, timeout=300.0)
            hi = engine.submit(rng.randint(0, 64, 6), max_new=4,
                               priority=0)
            engine.run(max_steps=1000)
            assert engine.admit_log.index(hi) \
                < engine.admit_log.index(lo)
        finally:
            engine.set_policy("fcfs")

    def test_deadline_policy_ties_break_by_submit_order(self, engine):
        engine.reset()
        engine.set_policy("deadline")
        try:
            rng = np.random.RandomState(10)
            blockers = [engine.submit(rng.randint(0, 64, 6),
                                      max_new=12) for _ in range(8)]
            del blockers
            engine.step()
            # identical (priority, no-deadline) keys: submit order
            rids = [engine.submit(rng.randint(0, 64, 6), max_new=4)
                    for _ in range(6)]
            engine.run(max_steps=1000)
            tail = [r for r in engine.admit_log if r in set(rids)]
            assert tail == rids
        finally:
            engine.set_policy("fcfs")

    def test_seeded_trace_admits_identically_twice(self, engine):
        engine.set_policy("deadline")
        try:
            logs = []
            for _ in range(2):
                engine.reset()
                rng = np.random.RandomState(11)
                for _ in range(14):
                    engine.submit(
                        rng.randint(0, 64, rng.randint(2, 16)),
                        max_new=int(rng.randint(4, 12)),
                        timeout=float(rng.uniform(200, 400)))
                engine.run(max_steps=1000)
                logs.append(list(engine.admit_log))
            assert logs[0] == logs[1]
        finally:
            engine.set_policy("fcfs")


class TestWeightedFairQueuing:
    """Deficit-round-robin tenant scheduling (ROADMAP admission open
    end #2): quotas bound in-flight, WFQ decides who goes NEXT."""

    def test_weighted_token_shares(self):
        ctrl = AdmissionController(tenant_weights={"a": 2.0, "b": 1.0})
        queue = [_req(f"a{i}", max_new=8, tenant="a") for i in range(30)]
        queue += [_req(f"b{i}", max_new=8, tenant="b") for i in range(30)]
        served = {"a": 0, "b": 0}
        for _ in range(30):
            pick = ctrl.wfq_pick(queue)
            ctrl.wfq_charge(pick)           # the engine's admit step
            queue.remove(pick)
            served[pick.tenant] += pick.max_new
        assert served["a"] == 2 * served["b"]

    def test_failed_admission_is_not_charged(self):
        """A pick whose admission fails downstream (pool full) leaves
        the request queued and costs the tenant NOTHING: repeated
        picks re-select the same head without debiting, and the
        weighted shares stay intact once capacity frees."""
        ctrl = AdmissionController(tenant_weights={"a": 1.0, "b": 1.0})
        queue = [_req(f"a{i}", max_new=8, tenant="a") for i in range(4)]
        queue += [_req(f"b{i}", max_new=8, tenant="b") for i in range(4)]
        first = ctrl.wfq_pick(queue)
        # admission fails repeatedly: same head, no deficit drain
        for _ in range(5):
            assert ctrl.wfq_pick(queue) is first
        d_before = dict(ctrl._wfq_deficit)
        assert ctrl.wfq_pick(queue) is first
        assert ctrl._wfq_deficit == d_before
        served = {"a": 0, "b": 0}
        for _ in range(8):
            pick = ctrl.wfq_pick(queue)
            ctrl.wfq_charge(pick)
            queue.remove(pick)
            served[pick.tenant] += pick.max_new
        assert served["a"] == served["b"]

    def test_transient_high_priority_keeps_lower_class_credit(self):
        """A passing priority-0 request must not wipe the DRR credit
        of still-queued lower-class tenants."""
        ctrl = AdmissionController()
        lo = [_req(f"a{i}", max_new=8, priority=1, tenant="a")
              for i in range(3)]
        pick = ctrl.wfq_pick(lo)            # tenant a accrues credit
        assert pick.tenant == "a"
        hi = _req("hi", max_new=4, priority=0, tenant="c")
        assert ctrl.wfq_pick(lo + [hi]) is hi
        assert "a" in ctrl._wfq_deficit     # credit survived

    def test_fcfs_within_tenant_and_priority_class_gate(self):
        ctrl = AdmissionController()
        hi = _req("hi", priority=0, tenant="b")
        queue = [_req("a0", priority=1, tenant="a"),
                 _req("a1", priority=1, tenant="a"), hi]
        # class 0 present: only its requests are candidates
        assert ctrl.wfq_pick(queue) is hi
        queue.remove(hi)
        first = ctrl.wfq_pick(queue)
        assert first.rid == "a0"        # submit order within tenant

    def test_deterministic_given_trace(self):
        def run():
            ctrl = AdmissionController(
                tenant_weights={"a": 1.5, "b": 1.0})
            queue = [_req(f"{t}{i}", max_new=4 + (i % 3) * 4, tenant=t)
                     for i in range(10) for t in ("a", "b", "c")]
            picks = []
            while queue:
                p = ctrl.wfq_pick(queue)
                ctrl.wfq_charge(p)
                queue.remove(p)
                picks.append(p.rid)
            return picks

        assert run() == run()

    def test_starvation_freedom_in_engine(self, engine):
        """A flood from tenant A cannot starve tenant B: with WFQ,
        B's first admission lands within one tenant rotation of the
        first post-flood slot, not after A's whole backlog."""
        engine.reset()
        engine.admission = AdmissionController()
        engine.set_policy("wfq")
        try:
            rng = np.random.RandomState(20)
            # fill all slots, then flood the queue from tenant A
            blockers = [engine.submit(rng.randint(0, 64, 6),
                                      max_new=16, tenant="a")
                        for _ in range(8)]
            del blockers
            engine.step()
            flood = [engine.submit(rng.randint(0, 64, 6), max_new=8,
                                   tenant="a") for _ in range(16)]
            late = [engine.submit(rng.randint(0, 64, 6), max_new=8,
                                  tenant="b") for _ in range(4)]
            engine.run(max_steps=2000)
            order = [r for r in engine.admit_log
                     if r in set(flood) | set(late)]
            # every B request admits before the flood's second half
            worst_b = max(order.index(r) for r in late)
            assert worst_b < len(order) - 1 and worst_b <= 9, order
            # and interleaving really alternates near the front
            assert any(r in set(late) for r in order[:3])
        finally:
            engine.set_policy("fcfs")
            _clear_admission(engine)

    def test_wfq_without_controller_raises(self, engine):
        engine.reset()
        engine.set_policy("wfq")
        try:
            engine.submit(np.arange(4) % 64, max_new=4)
            with pytest.raises(ValueError, match="AdmissionController"):
                engine.step()
        finally:
            engine.set_policy("fcfs")
            engine.reset()

    def test_weight_validation(self):
        with pytest.raises(ValueError, match="weight"):
            AdmissionController(tenant_weights={"a": 0.0})
        with pytest.raises(ValueError, match="default_weight"):
            AdmissionController(default_weight=-1.0)
        with pytest.raises(ValueError, match="wfq_quantum"):
            AdmissionController(wfq_quantum=0.0)


class TestQuotaRetryAfter:
    """ISSUE 14 satellite: over_quota sheds carry a retry_after from
    the tenant's predicted in-flight drain — the same backoff hint
    capacity sheds already quote — without disturbing the taxonomy."""

    def test_over_quota_carries_drain_hint(self, engine, registry):
        engine.reset()
        pred = ServiceTimePredictor(quantile=50.0)
        for _ in range(10):
            pred.observe_tpot(0.01)          # 10 ms/token, warm
        engine.admission = AdmissionController(quotas={"t": 8},
                                               predictor=pred)
        try:
            rng = np.random.RandomState(21)
            ok = engine.submit(rng.randint(0, 64, 6), max_new=8,
                               tenant="t")
            assert isinstance(ok, str)
            shed = engine.submit(rng.randint(0, 64, 6), max_new=4,
                                 tenant="t")
            assert isinstance(shed, ShedCompletion)
            assert shed.reason == "over_quota"
            # 4 tokens over quota across 8 slots at 10 ms/token
            assert shed.retry_after == pytest.approx(
                0.01 * 4 / 8, rel=1e-6)
            # taxonomy intact: reason-coded AND totalled
            snap = engine.metrics_snapshot()
            assert snap["serve/shed_over_quota"]["value"] == 1
            assert snap["serve/shed_total"]["value"] == 1
            engine.run(max_steps=500)
        finally:
            _clear_admission(engine)

    def test_cold_predictor_gives_no_hint(self, engine):
        engine.reset()
        engine.admission = AdmissionController(quotas={"t": 8})
        try:
            rng = np.random.RandomState(22)
            engine.submit(rng.randint(0, 64, 6), max_new=8, tenant="t")
            shed = engine.submit(rng.randint(0, 64, 6), max_new=4,
                                 tenant="t")
            assert isinstance(shed, ShedCompletion)
            assert shed.reason == "over_quota"
            assert shed.retry_after is None
            engine.run(max_steps=500)
        finally:
            _clear_admission(engine)

    def test_unlimited_tenant_never_hints(self, engine):
        engine.reset()
        pred = ServiceTimePredictor()
        for _ in range(10):
            pred.observe_tpot(0.01)
        engine.admission = AdmissionController(predictor=pred)
        try:
            rng = np.random.RandomState(23)
            r = engine.submit(rng.randint(0, 64, 6), max_new=8,
                              tenant="t")
            assert isinstance(r, str)      # no quota -> no shed at all
            engine.run(max_steps=500)
        finally:
            _clear_admission(engine)
