"""Speculative draft/verify serving decoder.

The exactness ladder: greedy speculative output EXACTLY equals the
target-only greedy decode (the draft changes speed, never content) —
pinned both against the decoder's own target loop and against
conftest's engine-independent oracle; sampled runs replay
bit-identically from their seed; acceptance accounting is the honest
observability (a self-draft accepts everything, a random draft
almost nothing)."""

import jax
import numpy as np
import pytest

from chainermn_tpu.parallel import MeshConfig
from chainermn_tpu.serving import (
    MiniLMAdapter,
    MiniLMConfig,
    SamplingParams,
    SpeculativeDecoder,
    init_minilm,
)


@pytest.fixture(scope="module")
def draft(mini_cfg):
    cfg = MiniLMConfig(vocab_size=mini_cfg.vocab_size, d_model=16,
                       n_heads=2, d_head=8, d_ff=32, n_layers=1,
                       max_pos=mini_cfg.max_pos)
    params = init_minilm(jax.random.PRNGKey(9), cfg)
    return MiniLMAdapter(MeshConfig(data=1, devices=jax.devices()[:1]), cfg), params


@pytest.fixture(scope="module")
def solo_target(mini_cfg, mini_params):
    return MiniLMAdapter(MeshConfig(data=1, devices=jax.devices()[:1]), mini_cfg), mini_params


@pytest.fixture(scope="module")
def decoder(draft, solo_target):
    (da, dp), (ta, tp) = draft, solo_target
    return SpeculativeDecoder(da, dp, ta, tp, k=3, max_prompt=16,
                              horizon=96)


class TestGreedyExactness:
    def test_equals_target_only_decode(self, decoder):
        rng = np.random.RandomState(0)
        for _ in range(6):
            p = rng.randint(0, 64, rng.randint(2, 17))
            n = int(rng.randint(4, 25))
            res = decoder.generate(p, n)
            np.testing.assert_array_equal(
                res.tokens, decoder.target_decode(p, n),
                err_msg="speculative greedy diverged from target-only")
            assert res.drafted == res.rounds * decoder.k
            assert 0 <= res.accepted <= res.drafted

    def test_equals_engine_oracle(self, decoder, oracle):
        """The same tokens the serving suite's solo oracle produces —
        the right-aligned layout changes nothing."""
        rng = np.random.RandomState(1)
        for _ in range(4):
            p = rng.randint(0, 64, rng.randint(2, 17))
            np.testing.assert_array_equal(decoder.generate(p, 12).tokens,
                                          oracle(p, 12))

    def test_self_draft_accepts_everything(self, solo_target):
        ta, tp = solo_target
        dec = SpeculativeDecoder(ta, tp, ta, tp, k=4, max_prompt=16,
                                 horizon=96)
        res = dec.generate(np.arange(8) % 64, 16)
        assert res.acceptance_rate == 1.0
        assert res.rounds == -(-16 // (dec.k + 1))   # k+1 per round

    def test_eos_stops_early(self, decoder, oracle):
        rng = np.random.RandomState(2)
        # an eos that provably occurs mid-decode
        p = rng.randint(0, 64, 8)
        eos = int(oracle(p, 12)[4])
        dec = SpeculativeDecoder(decoder.draft, decoder.d_params,
                                 decoder.target, decoder.t_params,
                                 k=3, max_prompt=16, horizon=96,
                                 eos_id=eos)
        res = dec.generate(p, 12)
        ref = dec.target_decode(p, 12)
        np.testing.assert_array_equal(res.tokens, ref)
        assert res.tokens.shape[0] <= 12
        if eos in ref:
            assert res.tokens[-1] == eos

    def test_validation(self, draft, solo_target):
        (da, dp), (ta, tp) = draft, solo_target
        with pytest.raises(ValueError, match="k="):
            SpeculativeDecoder(da, dp, ta, tp, k=0, max_prompt=8,
                               horizon=32)
        with pytest.raises(ValueError, match="horizon"):
            SpeculativeDecoder(da, dp, ta, tp, k=2, max_prompt=32,
                               horizon=32)
        bad_cfg = MiniLMConfig(vocab_size=32, d_model=16, n_heads=2,
                               d_head=8, d_ff=32, n_layers=1)
        bad = MiniLMAdapter(MeshConfig(data=1, devices=jax.devices()[:1]), bad_cfg)
        with pytest.raises(ValueError, match="vocab"):
            SpeculativeDecoder(bad, init_minilm(jax.random.PRNGKey(0),
                                                bad_cfg),
                               ta, tp, k=2, max_prompt=8, horizon=32)
        dec = SpeculativeDecoder(da, dp, ta, tp, k=2, max_prompt=8,
                                 horizon=32)
        with pytest.raises(ValueError, match="max_new"):
            dec.generate(np.arange(4), 100)


class TestSampledSpeculation:
    def test_replay_determinism(self, decoder):
        rng = np.random.RandomState(3)
        p = rng.randint(0, 64, 10)
        sp = SamplingParams(temperature=0.9, top_k=16, top_p=0.95,
                            seed=42)
        a = decoder.generate(p, 16, sampling=sp)
        b = decoder.generate(p, 16, sampling=sp)
        np.testing.assert_array_equal(a.tokens, b.tokens)
        assert a.rounds == b.rounds and a.accepted == b.accepted

    def test_different_seeds_differ(self, decoder):
        rng = np.random.RandomState(4)
        p = rng.randint(0, 64, 10)
        outs = [decoder.generate(
            p, 16, sampling=SamplingParams(temperature=1.5, seed=s)
        ).tokens for s in range(6)]
        assert any(not np.array_equal(outs[0], o) for o in outs[1:])

    def test_self_draft_sampled_accepts_everything(self, solo_target):
        """Draft == target: p_d′ == p_t′, so the acceptance test
        u < p_t/p_d = 1 always passes — the Leviathan identity's
        degenerate corner is a sharp accounting check."""
        ta, tp = solo_target
        dec = SpeculativeDecoder(ta, tp, ta, tp, k=3, max_prompt=16,
                                 horizon=96)
        res = dec.generate(np.arange(8) % 64, 12,
                           sampling=SamplingParams(temperature=1.0,
                                                   seed=5))
        assert res.acceptance_rate == 1.0


class TestObservability:
    def test_metrics_and_spans(self, decoder):
        from chainermn_tpu.utils.metrics import get_registry
        from chainermn_tpu.utils.telemetry import (
            TraceRecorder,
            get_recorder,
            set_recorder,
        )

        reg = get_registry()
        reg.enable()
        prev = set_recorder(TraceRecorder(capacity=4096, enabled=True))
        try:
            reg.clear()
            res = decoder.generate(np.arange(10) % 64, 12)
            snap = reg.snapshot(prefix="serve/")
            assert snap["serve/spec_drafted"]["value"] == res.drafted
            assert snap["serve/spec_accepted"]["value"] == res.accepted
            names = {e["name"] for e in get_recorder().events()}
            assert "serve/draft" in names and "serve/verify" in names
        finally:
            set_recorder(prev)
            reg.clear()
            reg.disable()
