"""The flagship transformer behind the serving engine: token identity
against ``make_generate_fn``'s own ragged static decode on a DP×TP
mesh.  vma-gated like every TransformerConfig test (the engine itself
is exercised everywhere through MiniLM)."""

import numpy as np
import pytest

import jax

from chainermn_tpu.parallel import MeshConfig
from chainermn_tpu.serving import ServingEngine, TransformerAdapter
from chainermn_tpu.testing import requires_vma

pytestmark = requires_vma(
    "requires vma-typed shard_map (TransformerConfig refuses pre-vma jax)")

VOCAB, PMAX, NEW = 64, 8, 10


def _cfg():
    from chainermn_tpu.models import TransformerConfig

    return TransformerConfig(
        vocab_size=VOCAB, d_model=32, n_heads=4, d_head=8, d_ff=64,
        n_layers=2, max_seq=64, attention="local",
        pos_embedding="rope", dtype="float32", remat=False)


def test_engine_matches_static_generate_dp_tp():
    from chainermn_tpu.models import (
        init_transformer, make_generate_fn, shard_params,
    )

    cfg = _cfg()
    mc = MeshConfig(data=4, model=2)
    host = init_transformer(jax.random.PRNGKey(0), cfg)
    params = shard_params(mc, cfg, host)

    rng = np.random.RandomState(0)
    lens = [3, 8, 5, 6]
    prompts = [rng.randint(0, VOCAB, n).astype(np.int32) for n in lens]

    # static oracle: one ragged right-aligned batch through generate
    max_len = PMAX + NEW
    batch = np.zeros((4, PMAX), np.int32)
    for b, p in enumerate(prompts):
        batch[b, PMAX - p.shape[0]:] = p
    gen = make_generate_fn(mc, cfg, max_len=max_len)
    ref = np.asarray(gen(params, batch, prompt_lens=np.asarray(lens)))

    adapter = TransformerAdapter(mc, cfg)
    eng = ServingEngine(adapter, host, n_slots=4, horizon=64,
                        max_prompt=PMAX, block=8, round_tokens=4)
    rids = [eng.submit(p, max_new=NEW) for p in prompts]
    comps = {c.rid: c for c in eng.run(max_steps=500)}
    for b, rid in enumerate(rids):
        np.testing.assert_array_equal(
            comps[rid].tokens, ref[b, PMAX:],
            err_msg=f"row {b} diverged from the static ragged decode")


def test_adapter_rejects_moe_and_seq():
    import dataclasses

    from chainermn_tpu.models import TransformerConfig

    cfg = _cfg()
    with pytest.raises(ValueError, match="MoE"):
        TransformerAdapter(
            MeshConfig(data=8),
            dataclasses.replace(cfg, moe=True, n_experts=2))
    with pytest.raises(ValueError, match="seq"):
        TransformerAdapter(MeshConfig(data=4, seq=2), cfg)
