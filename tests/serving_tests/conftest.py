"""Shared MiniLM fixtures for the serving-engine suite.

Session-scoped model/adapter (compiles are the cost here, not compute)
plus an independent greedy oracle: a plain python loop over the same
adapter's pure step/prefill functions — no shard_map, no engine code —
so engine-vs-oracle token identity actually pins the scheduler, not
two copies of one bug."""

import weakref

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from chainermn_tpu.parallel import MeshConfig
from chainermn_tpu.serving import (
    MiniLMAdapter,
    MiniLMConfig,
    init_minilm,
)

VOCAB = 64


@pytest.fixture(scope="session", autouse=True)
def _engine_registry():
    """Track every engine the suite constructs (weakly — fixtures may
    outlive tests) so the leak guard below can audit them all."""
    from chainermn_tpu.serving import engine as engine_mod

    registry = weakref.WeakSet()
    orig_init = engine_mod.ServingEngine.__init__

    def tracked_init(self, *args, **kwargs):
        orig_init(self, *args, **kwargs)
        registry.add(self)

    engine_mod.ServingEngine.__init__ = tracked_init
    try:
        yield registry
    finally:
        engine_mod.ServingEngine.__init__ = orig_init


@pytest.fixture(autouse=True)
def pool_leak_guard(_engine_registry):
    """Suite-wide refcount-leak fixture: after EVERY serving test,
    every engine that is idle (nothing queued, active, or staged) must
    account for all its pool blocks — free, or trie-cached with
    exactly the trie's reference.  A fork/eviction path that drops or
    double-counts a reference fails the suite here even if its own
    test never looked."""
    yield
    for eng in list(_engine_registry):
        if eng.idle and not eng._staged:
            problems = eng._alloc.leak_report()
            assert not problems, (
                f"pool leak after test (engine {eng!r}): {problems}")


@pytest.fixture(scope="session")
def mini_cfg():
    return MiniLMConfig(vocab_size=VOCAB, d_model=32, n_heads=2,
                        d_head=16, d_ff=64, n_layers=2, max_pos=256)


@pytest.fixture(scope="session")
def mini_params(mini_cfg):
    return init_minilm(jax.random.PRNGKey(0), mini_cfg)


@pytest.fixture(scope="session")
def mini_adapter(mini_cfg):
    return MiniLMAdapter(MeshConfig(data=8), mini_cfg)


@pytest.fixture(scope="session")
def oracle(mini_adapter, mini_params):
    """``oracle(prompt, max_new, eos=-1) -> (n,) generated tokens`` —
    the solo static greedy decode every engine request must match."""
    ad, params = mini_adapter, mini_params
    cache = {}

    def run(prompt, max_new, eos=-1):
        key = (bytes(np.asarray(prompt, np.int32)), int(max_new),
               int(eos))
        if key in cache:
            return cache[key]
        prompt = np.asarray(prompt, np.int32)
        p = prompt.shape[0]
        caches = ad.make_cache(1, p + max_new)
        offs = jnp.zeros((1,), jnp.int32)
        if p > 1:
            caches = ad.prefill(
                params, caches, jnp.asarray(prompt[None, :p - 1]), offs)
        tok = jnp.asarray(prompt[-1:], jnp.int32)
        out = []
        for t in range(p - 1, p - 1 + max_new):
            logits, caches = ad.step(params, caches, tok, jnp.int32(t),
                                     offs)
            tok = jnp.argmax(logits, -1).astype(jnp.int32)
            out.append(int(tok[0]))
            if eos >= 0 and out[-1] == eos:
                break
        cache[key] = np.asarray(out, np.int32)
        return cache[key]

    return run


@pytest.fixture(scope="session")
def ragged_trace():
    """Factory: (prompt, max_new) pairs with ragged lengths/budgets."""

    def make(rng, n, vocab=VOCAB, max_prompt=16, min_new=4, max_new=24):
        return [(rng.randint(0, vocab, rng.randint(2, max_prompt + 1)),
                 int(rng.randint(min_new, max_new + 1)))
                for _ in range(n)]

    return make
