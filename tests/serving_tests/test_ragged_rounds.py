"""Ragged-round token identity (the tentpole's safety rail): per-row
position clocks advance every slot on its own origin-0 lane, chunked
prefill interleaves prompt staging into live decode rounds, and
speculation runs as a per-row round mode — and NONE of it may move a
single token.  Every case here pins engine output against conftest's
engine-independent solo oracle (greedy) or the keyed replay oracle
(sampled), across staggered long/short admits, chunk budgets, draft
qualities, and mixed sampling."""

import jax
import numpy as np
import pytest

from chainermn_tpu.serving import (
    MiniLMAdapter,
    MiniLMConfig,
    ServingEngine,
    init_minilm,
)
from chainermn_tpu.serving.sampling import SamplingParams

VOCAB = 64


def _check_parity(comps, rids, oracle, eos=-1):
    by_rid = {c.rid: c for c in comps}
    assert sorted(by_rid) == sorted(r for r, _, _ in rids)
    for rid, prompt, max_new in rids:
        np.testing.assert_array_equal(
            by_rid[rid].tokens, oracle(prompt, max_new, eos=eos),
            err_msg=f"request {rid} diverged from its solo decode")


@pytest.fixture(scope="module")
def draft_pair(mini_adapter):
    """An UNTRAINED draft (acceptance near zero) sharing the target's
    MeshConfig instance: token identity must hold regardless of draft
    quality, so the worst draft is the strongest witness."""
    cfg = MiniLMConfig(vocab_size=VOCAB, d_model=16, n_heads=2,
                       d_head=8, d_ff=32, n_layers=1, max_pos=256)
    params = init_minilm(jax.random.PRNGKey(99), cfg)
    return MiniLMAdapter(mini_adapter.mesh_cfg, cfg), params


@pytest.fixture(scope="module")
def self_draft(mini_adapter, mini_params):
    """The target drafting for itself: acceptance exactly 1.0 — the
    other extreme of the acceptance range."""
    return mini_adapter, mini_params


class TestChunkedPrefill:
    def test_staggered_long_short_admits(self, mini_adapter,
                                         mini_params, oracle):
        """The TTFT-independence scenario as a correctness case: long
        prompts admitted mid-stream stage one chunk per round while
        short requests decode — tokens of BOTH populations must equal
        their solo decodes."""
        eng = ServingEngine(mini_adapter, mini_params, n_slots=8,
                            horizon=160, max_prompt=64, block=8,
                            round_tokens=4, prefill_chunk=1)
        rng = np.random.RandomState(0)
        short = [(rng.randint(0, VOCAB, rng.randint(2, 9)),
                  int(rng.randint(6, 14))) for _ in range(6)]
        long = [(rng.randint(0, VOCAB, rng.randint(40, 65)),
                 int(rng.randint(6, 14))) for _ in range(4)]
        rids = [(eng.submit(p, max_new=n), p, n) for p, n in short[:4]]
        comps = []
        # interleave long-prompt submits while shorts decode: the
        # long prompts MUST take the chunk-per-round path
        for p, n in long + short[4:]:
            comps.extend(eng.step())
            rids.append((eng.submit(p, max_new=n), p, n))
        comps.extend(eng.run(max_steps=4000))
        assert eng.stats()["chunk_prefills"] >= len(long)
        _check_parity(comps, rids, oracle)

    @pytest.mark.parametrize("prefill_chunk", [1, 2, 4])
    def test_chunk_budget_sweep(self, mini_adapter, mini_params,
                                oracle, prefill_chunk):
        """Every per-round chunk budget stages the same tokens."""
        eng = ServingEngine(mini_adapter, mini_params, n_slots=8,
                            horizon=160, max_prompt=32, block=8,
                            round_tokens=4,
                            prefill_chunk=prefill_chunk)
        rng = np.random.RandomState(prefill_chunk)
        trace = [(rng.randint(0, VOCAB, rng.randint(2, 33)),
                  int(rng.randint(4, 16))) for _ in range(12)]
        rids = [(eng.submit(p, max_new=n), p, n) for p, n in trace]
        comps = eng.run(max_steps=4000)
        _check_parity(comps, rids, oracle)

    def test_chunked_with_prefix_sharing_and_eos(self, mini_adapter,
                                                 mini_params, oracle):
        """Chunked admission over trie-shared prefixes with EOS
        freezing mid-round: the full cross product the seed suite
        pinned, now under ragged clocks."""
        rng = np.random.RandomState(3)
        system = rng.randint(0, VOCAB, 12)
        trace = [(np.concatenate([system,
                                  rng.randint(0, VOCAB,
                                              rng.randint(2, 20))]),
                  int(rng.randint(6, 14))) for _ in range(10)]
        eos = int(oracle(trace[0][0], trace[0][1])[2])
        eng = ServingEngine(mini_adapter, mini_params, n_slots=8,
                            horizon=160, max_prompt=32, block=8,
                            round_tokens=4, eos_id=eos,
                            prefix_sharing=True, prefill_chunk=1)
        rids = [(eng.submit(p, max_new=n), p, n) for p, n in trace]
        comps = eng.run(max_steps=4000)
        _check_parity(comps, rids, oracle, eos=eos)
        assert eng.stats()["prefix_hit_rate"] > 0


class TestSpeculativeRounds:
    @pytest.mark.parametrize("which", ["untrained", "self"])
    def test_greedy_identity_any_draft(self, mini_adapter,
                                       mini_params, oracle,
                                       draft_pair, self_draft, which):
        """Per-row speculative rounds commit the target's own argmax
        stream whatever the draft proposes: identical tokens at
        acceptance ~0 (untrained draft) and exactly 1 (self-draft)."""
        d_ad, d_params = draft_pair if which == "untrained" \
            else self_draft
        eng = ServingEngine(mini_adapter, mini_params, n_slots=8,
                            horizon=160, max_prompt=16, block=8,
                            round_tokens=4, draft_adapter=d_ad,
                            draft_params=d_params, spec_k=3)
        rng = np.random.RandomState(5)
        trace = [(rng.randint(0, VOCAB, rng.randint(2, 17)),
                  int(rng.randint(4, 20))) for _ in range(12)]
        rids = [(eng.submit(p, max_new=n), p, n) for p, n in trace]
        comps = eng.run(max_steps=4000)
        _check_parity(comps, rids, oracle)
        st = eng.stats()
        assert st["spec_drafted"] > 0
        if which == "self":
            # self-draft: every drafted token verifies, except drafts
            # clipped by a row's remaining budget at its last round
            assert st["spec_accepted"] >= 0.9 * st["spec_drafted"]

    def test_spec_with_eos_and_staggered_admits(self, mini_adapter,
                                                mini_params, oracle,
                                                draft_pair):
        d_ad, d_params = draft_pair
        rng = np.random.RandomState(6)
        trace = [(rng.randint(0, VOCAB, rng.randint(2, 17)),
                  int(rng.randint(8, 20))) for _ in range(12)]
        eos = int(oracle(trace[0][0], trace[0][1])[2])
        eng = ServingEngine(mini_adapter, mini_params, n_slots=8,
                            horizon=160, max_prompt=16, block=8,
                            round_tokens=4, eos_id=eos,
                            draft_adapter=d_ad, draft_params=d_params,
                            spec_k=4)
        rids = [(eng.submit(p, max_new=n), p, n) for p, n in trace[:6]]
        comps = []
        for p, n in trace[6:]:
            comps.extend(eng.step())
            rids.append((eng.submit(p, max_new=n), p, n))
        comps.extend(eng.run(max_steps=4000))
        _check_parity(comps, rids, oracle, eos=eos)

    def test_sampled_requests_fall_back_and_replay(self, mini_adapter,
                                                   mini_params,
                                                   oracle, draft_pair):
        """Spec rounds are defined against the target argmax, so
        rounds with sampled rows take the keyed sampled program — and
        the sampled tokens still replay schedule-independently while
        greedy rows keep oracle identity."""
        d_ad, d_params = draft_pair
        rng = np.random.RandomState(7)
        greedy = [(rng.randint(0, VOCAB, rng.randint(2, 17)),
                   int(rng.randint(4, 12))) for _ in range(6)]
        sampled = [(rng.randint(0, VOCAB, rng.randint(2, 17)),
                    int(rng.randint(4, 12)),
                    SamplingParams(temperature=0.8, top_k=10,
                                   seed=40 + i)) for i in range(4)]

        def run_once():
            eng = ServingEngine(mini_adapter, mini_params, n_slots=8,
                                horizon=160, max_prompt=16, block=8,
                                round_tokens=4, draft_adapter=d_ad,
                                draft_params=d_params, spec_k=3)
            g = [(eng.submit(p, max_new=n), p, n) for p, n in greedy]
            s = [eng.submit(p, max_new=n, sampling=sp)
                 for p, n, sp in sampled]
            comps = {c.rid: c for c in eng.run(max_steps=4000)}
            return eng, g, s, comps

        eng1, g1, s1, comps1 = run_once()
        _check_parity([comps1[r] for r, _, _ in g1], g1, oracle)
        eng2, _, s2, comps2 = run_once()
        for r1, r2 in zip(s1, s2):
            np.testing.assert_array_equal(
                comps1[r1].tokens, comps2[r2].tokens,
                err_msg="sampled tokens changed across runs")
