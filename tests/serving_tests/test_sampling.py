"""Keyed sampling in the decode round.

The determinism contract: greedy requests stay token-identical to the
engine-independent solo oracle even when sampled requests share their
rounds (greedy IS the exactness oracle), and sampled requests replay
bit-identically from (seed, params, prompt) under ANY scheduling —
different policies, different batch compositions, different slots."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from chainermn_tpu.serving import SamplingParams, ServingEngine
from chainermn_tpu.serving.sampling import (
    filter_logits,
    fold_keys,
    sample_tokens,
)

_NEG_CUT = -1e29        # anything below = filtered


class TestFilters:
    def test_top_k(self):
        lg = jnp.asarray([[1.0, 4.0, 3.0, 2.0]])
        out = np.asarray(filter_logits(lg, jnp.asarray([2]),
                                       jnp.asarray([1.0])))[0]
        assert list(out > _NEG_CUT) == [False, True, True, False]

    def test_top_k_zero_disables(self):
        lg = jnp.asarray([[1.0, 4.0, 3.0, 2.0]])
        out = np.asarray(filter_logits(lg, jnp.asarray([0]),
                                       jnp.asarray([1.0])))[0]
        assert (out > _NEG_CUT).all()

    def test_top_p(self):
        # softmax of [ln8, ln4, ln2, ln1] = [8,4,2,1]/15
        lg = jnp.log(jnp.asarray([[8.0, 4.0, 2.0, 1.0]]))
        out = np.asarray(filter_logits(lg, jnp.asarray([0]),
                                       jnp.asarray([0.75])))[0]
        # cum-before: 0, 8/15(0.53), 12/15(0.8), 14/15 -> keep first 2
        assert list(out > _NEG_CUT) == [True, True, False, False]
        # at least one token always survives even for tiny p
        out = np.asarray(filter_logits(lg, jnp.asarray([0]),
                                       jnp.asarray([1e-6])))[0]
        assert (out > _NEG_CUT).sum() == 1

    def test_per_row_parameters(self):
        lg = jnp.asarray([[1.0, 4.0, 3.0, 2.0],
                          [1.0, 4.0, 3.0, 2.0]])
        out = np.asarray(filter_logits(lg, jnp.asarray([1, 3]),
                                       jnp.asarray([1.0, 1.0])))
        assert (out[0] > _NEG_CUT).sum() == 1
        assert (out[1] > _NEG_CUT).sum() == 3

    def test_greedy_rows_take_argmax(self):
        lg = jnp.asarray([[0.1, 0.9], [0.9, 0.1]])
        keys = jnp.zeros((2, 2), jnp.uint32)
        toks = sample_tokens(lg, keys, jnp.asarray([0.0, 0.0]),
                             jnp.asarray([0, 0]),
                             jnp.asarray([1.0, 1.0]))
        assert list(np.asarray(toks)) == [1, 0]

    def test_vmap_matches_solo(self):
        """The replay oracle's load-bearing property: batched sampling
        is bitwise the solo call."""
        rng = np.random.RandomState(0)
        lg = jnp.asarray(rng.randn(4, 32).astype(np.float32))
        keys = fold_keys(
            jnp.stack([jax.random.PRNGKey(i) for i in range(4)]),
            jnp.arange(4, dtype=jnp.int32))
        batched = sample_tokens(lg, keys, jnp.full((4,), 0.8),
                                jnp.full((4,), 8, jnp.int32),
                                jnp.full((4,), 0.9))
        for i in range(4):
            solo = sample_tokens(lg[i:i + 1], keys[i:i + 1],
                                 jnp.asarray([0.8]),
                                 jnp.asarray([8], jnp.int32),
                                 jnp.asarray([0.9]))
            assert int(solo[0]) == int(batched[i])

    def test_params_validation(self):
        with pytest.raises(ValueError, match="temperature"):
            SamplingParams(temperature=0.0)
        with pytest.raises(ValueError, match="top_k"):
            SamplingParams(top_k=-1)
        with pytest.raises(ValueError, match="top_p"):
            SamplingParams(top_p=0.0)


def _sampled_oracle(adapter, params, prompt, max_new, sp, eos=-1):
    """Engine-independent replay: solo decode with the same key
    stream and the same sampling functions the round program uses."""
    prompt = np.asarray(prompt, np.int32)
    p = prompt.shape[0]
    caches = adapter.make_cache(1, p + max_new)
    offs = jnp.zeros((1,), jnp.int32)
    if p > 1:
        caches = adapter.prefill(params, caches,
                                 jnp.asarray(prompt[None, :p - 1]),
                                 offs)
    tok = jnp.asarray(prompt[-1:], jnp.int32)
    root = jnp.asarray(sp.key())[None]
    out = []
    for t in range(p - 1, p - 1 + max_new):
        logits, caches = adapter.step(params, caches, tok,
                                      jnp.int32(t), offs)
        # token index of the PRODUCED token: t + 1 - offset (= i+1
        # counting the prompt's last token as index p-1... the engine
        # folds by t + 1 - offset with offset = position of token 0)
        keys = fold_keys(root, jnp.asarray([t + 1], jnp.int32))
        tok = sample_tokens(logits, keys,
                            jnp.asarray([sp.temperature]),
                            jnp.asarray([sp.top_k], jnp.int32),
                            jnp.asarray([sp.top_p]))
        out.append(int(tok[0]))
        if eos >= 0 and out[-1] == eos:
            break
    return np.asarray(out, np.int32)


class TestEngineSampling:
    @pytest.fixture(scope="class")
    def engine(self, mini_adapter, mini_params):
        return ServingEngine(mini_adapter, mini_params, n_slots=8,
                             horizon=160, max_prompt=16, block=8,
                             round_tokens=4)

    def test_sampled_replay_across_scheduling(self, engine,
                                              ragged_trace):
        """Same requests, two different schedules (fcfs vs spf, and a
        different submission interleaving) — sampled tokens identical:
        the key stream depends on the request alone."""
        rng = np.random.RandomState(10)
        trace = ragged_trace(rng, 12)
        sps = [SamplingParams(temperature=0.9, top_k=12, top_p=0.95,
                              seed=100 + i) for i in range(len(trace))]
        runs = []
        for policy in ("fcfs", "spf"):
            engine.reset()
            engine.set_policy(policy)
            try:
                rids = [engine.submit(p, max_new=n, sampling=sp)
                        for (p, n), sp in zip(trace, sps)]
                comps = {c.rid: c for c in engine.run(max_steps=2000)}
                runs.append({r: comps[r].tokens for r in rids})
            finally:
                engine.set_policy("fcfs")
        for rid in runs[0]:
            np.testing.assert_array_equal(
                runs[0][rid], runs[1][rid],
                err_msg=f"{rid} sampled tokens changed with the "
                        "schedule")

    def test_sampled_matches_solo_replay_oracle(self, engine,
                                                mini_adapter,
                                                mini_params):
        engine.reset()
        rng = np.random.RandomState(11)
        cases = [(rng.randint(0, 64, rng.randint(2, 17)), 8,
                  SamplingParams(temperature=0.8, top_k=10,
                                 top_p=0.9, seed=7 + i))
                 for i in range(4)]
        rids = [engine.submit(p, max_new=n, sampling=sp)
                for p, n, sp in cases]
        comps = {c.rid: c for c in engine.run(max_steps=2000)}
        for rid, (p, n, sp) in zip(rids, cases):
            ref = _sampled_oracle(mini_adapter, mini_params, p, n, sp)
            np.testing.assert_array_equal(
                comps[rid].tokens, ref,
                err_msg=f"{rid} diverged from its (key, params) "
                        "replay")

    def test_greedy_rows_stay_exact_in_mixed_rounds(self, engine,
                                                    oracle,
                                                    ragged_trace):
        """Greedy requests sharing rounds with sampled ones keep the
        engine's original guarantee — token-identical to the solo
        oracle."""
        engine.reset()
        rng = np.random.RandomState(12)
        trace = ragged_trace(rng, 8)
        rids = []
        for i, (p, n) in enumerate(trace):
            sp = SamplingParams(temperature=1.2, seed=i) \
                if i % 2 else None
            rids.append((engine.submit(p, max_new=n, sampling=sp),
                         p, n, sp))
        comps = {c.rid: c for c in engine.run(max_steps=2000)}
        assert engine.stats()["rounds"] > 0
        for rid, p, n, sp in rids:
            if sp is None:
                np.testing.assert_array_equal(
                    comps[rid].tokens, oracle(p, n),
                    err_msg=f"greedy {rid} corrupted by sampled "
                            "round-mates")

    def test_all_greedy_uses_original_program(self, engine,
                                              ragged_trace):
        """No sampled rows live -> the engine dispatches the ORIGINAL
        greedy round program (the byte-identical path)."""
        engine.reset()
        trace = ragged_trace(np.random.RandomState(13), 4)
        for p, n in trace:
            engine.submit(p, max_new=n)
        engine.run(max_steps=500)
        assert engine._n_sampled_active == 0

    def test_sampled_with_eos_freezes(self, mini_adapter, mini_params,
                                      oracle, ragged_trace):
        """EOS semantics under sampling: a sampled row emitting eos
        freezes and pads; its replay oracle agrees."""
        rng = np.random.RandomState(14)
        trace = ragged_trace(rng, 4, min_new=8)
        eos = int(oracle(trace[0][0], trace[0][1])[2])
        eng = ServingEngine(mini_adapter, mini_params, n_slots=8,
                            horizon=160, max_prompt=16, block=8,
                            round_tokens=4, eos_id=eos, pad_id=0)
        cases = [(p, n, SamplingParams(temperature=1.0, seed=50 + i))
                 for i, (p, n) in enumerate(trace)]
        rids = [eng.submit(p, max_new=n, sampling=sp)
                for p, n, sp in cases]
        comps = {c.rid: c for c in eng.run(max_steps=2000)}
        for rid, (p, n, sp) in zip(rids, cases):
            ref = _sampled_oracle(mini_adapter, mini_params, p, n, sp,
                                  eos=eos)
            np.testing.assert_array_equal(comps[rid].tokens, ref)

    def test_submit_rejects_non_sampling_params(self, engine):
        engine.reset()
        with pytest.raises(ValueError, match="SamplingParams"):
            engine.submit(np.arange(4) % 64, max_new=4,
                          sampling={"temperature": 1.0})
