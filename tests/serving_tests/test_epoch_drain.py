"""Serving epoch drains (docs/SERVING.md "Epoch drains"): ahead of a
live resize the engine must stop admission (shedding with a predicted
``retry_after``), retire or timeout-evict its active rows with
oracle-prefix partials, keep queued requests' places, and re-open under
the NEW epoch — while a front-end that slept through the resize gets a
typed ``stale_epoch`` reject instead of service under moved
assumptions.  Token identity stays pinned by the conftest oracle
throughout: a drain changes WHEN rows are served, never WHAT."""

import time

import numpy as np
import pytest

from chainermn_tpu.serving import (
    AdmissionController,
    ServiceTimePredictor,
    ServingEngine,
    ShedCompletion,
)
from chainermn_tpu.utils.metrics import MetricsRegistry, set_registry


@pytest.fixture()
def engine(mini_adapter, mini_params):
    return ServingEngine(mini_adapter, mini_params, n_slots=8,
                         horizon=160, max_prompt=16, block=8,
                         round_tokens=4)


@pytest.fixture()
def registry():
    reg = MetricsRegistry(enabled=True)
    prev = set_registry(reg)
    yield reg
    set_registry(prev)


def _warm_admission(tpot=0.01):
    """A controller whose predictor answers from defaults — drain
    sheds need a retry_after without waiting for live observations."""
    return AdmissionController(
        predictor=ServiceTimePredictor(default_ttft=0.1,
                                       default_tpot=tpot))


class TestStaleEpoch:
    def test_stale_submit_shed_current_admitted(self, engine, registry):
        rng = np.random.RandomState(0)
        engine.epoch = 3
        s = engine.submit(rng.randint(0, 64, 6), max_new=4, epoch=2)
        assert isinstance(s, ShedCompletion)
        assert s.reason == "stale_epoch" and "3" in s.detail
        # retrying is pointless until the caller re-learns the world
        assert s.retry_after is None
        assert engine.stats()["shed"] == {"stale_epoch": 1}
        assert registry.counter(
            "serve/shed_stale_epoch").value == 1
        # the correct epoch — and no epoch at all (opt-in check) — admit
        assert isinstance(
            engine.submit(rng.randint(0, 64, 6), max_new=4, epoch=3),
            str)
        assert isinstance(
            engine.submit(rng.randint(0, 64, 6), max_new=4), str)

    def test_newer_epoch_is_transient_not_stale(self, engine):
        """A front-end that already learned the NEW epoch while this
        engine's ``complete_drain`` hasn't run yet is EARLY, not wrong:
        it must get the transient ``"draining"`` verdict (retry), never
        the terminal re-learn-the-world ``"stale_epoch"``."""
        rng = np.random.RandomState(2)
        engine.epoch = 3
        s = engine.submit(rng.randint(0, 64, 6), max_new=4, epoch=4)
        assert isinstance(s, ShedCompletion)
        assert s.reason == "draining" and "behind" in s.detail
        # and DURING a drain, any epoch mismatch is the drain's shed
        engine._draining = True
        s2 = engine.submit(rng.randint(0, 64, 6), max_new=4, epoch=2)
        assert s2.reason == "draining"

    def test_epoch_rides_stats_and_persists_reset(self, engine):
        engine.epoch = 5
        assert engine.stats()["epoch"] == 5
        engine.reset()
        assert engine.epoch == 5        # the world didn't change back


class TestDrain:
    def test_drain_retires_active_reopens_under_new_epoch(
            self, engine, oracle, registry):
        rng = np.random.RandomState(1)
        reqs = [(rng.randint(0, 64, rng.randint(2, 10)),
                 int(rng.randint(4, 10))) for _ in range(10)]
        rids = [engine.submit(p, max_new=m) for p, m in reqs]
        engine.step()                   # 8 slots fill, 2 stay queued
        assert engine.n_active == 8 and len(engine._queue) == 2
        done = engine.drain()
        # every active row retired naturally — "ok", oracle-identical
        assert engine.n_active == 0 and engine.draining
        ok = {c.rid: c for c in done if c.status == "ok"}
        assert len(ok) == 8
        for rid, (p, m) in zip(rids, reqs):
            if rid in ok:
                np.testing.assert_array_equal(
                    ok[rid].tokens, oracle(p, m))
        # queued rows held their place, nothing admitted during drain
        assert len(engine._queue) == 2
        assert engine.stats()["drains"] == 1
        assert registry.counter("serve/drains").value == 1
        # a submit mid-drain is shed "draining"
        s = engine.submit(rng.randint(0, 64, 6), max_new=4)
        assert isinstance(s, ShedCompletion) and s.reason == "draining"
        # re-open under the new epoch: the held queue serves, tokens
        # oracle-identical — the drain changed nothing about WHAT
        engine.complete_drain(epoch=1)
        assert not engine.draining and engine.epoch == 1
        out = {c.rid: c for c in engine.run(max_steps=500)}
        for rid, (p, m) in zip(rids, reqs):
            if rid not in ok:
                assert out[rid].status == "ok"
                np.testing.assert_array_equal(
                    out[rid].tokens, oracle(p, m))

    def test_drain_timeout_evicts_oracle_prefix_partials(
            self, engine, oracle, registry):
        rng = np.random.RandomState(2)
        prompts = [rng.randint(0, 64, 8) for _ in range(4)]
        rids = [engine.submit(p, max_new=60) for p in prompts]
        engine.step()
        assert engine.n_active == 4
        t0 = time.perf_counter()
        done = engine.drain(timeout=0.005)
        assert time.perf_counter() - t0 < 30.0
        by_rid = {c.rid: c for c in done}
        assert set(by_rid) == set(rids)
        n_timeout = sum(1 for c in done if c.status == "timeout")
        assert n_timeout >= 1           # 60-token budgets can't finish
        for rid, p in zip(rids, prompts):
            c = by_rid[rid]
            # partials are a verified PREFIX of the solo decode
            np.testing.assert_array_equal(
                c.tokens, oracle(p, 60)[:c.n_generated])
        assert engine.n_active == 0

    def test_drain_max_steps_bounds_the_loop(self, engine):
        rng = np.random.RandomState(3)
        engine.submit(rng.randint(0, 64, 6), max_new=50)
        engine.step()
        engine.drain(max_steps=2)       # returns without retiring
        assert engine.n_active == 1 and engine.draining
        engine.drain(timeout=0.01)      # second call finishes the job
        assert engine.n_active == 0

    def test_drain_shed_carries_predicted_retry_after(self, engine,
                                                      registry):
        rng = np.random.RandomState(4)
        engine.admission = _warm_admission(tpot=0.01)
        rid = engine.submit(rng.randint(0, 64, 6), max_new=20)
        engine.step()
        engine.drain(timeout=0.01)
        backlog = engine._backlog_tokens()
        s = engine.submit(rng.randint(0, 64, 6), max_new=4)
        assert s.reason == "draining"
        assert s.retry_after == pytest.approx(
            0.01 * backlog / engine.n_slots)
        engine.complete_drain()
        engine.run(max_steps=200)
        del rid

    def test_drain_shed_retry_after_none_when_cold(self, engine):
        rng = np.random.RandomState(5)
        engine.admission = AdmissionController()   # cold predictor
        engine.drain()
        s = engine.submit(rng.randint(0, 64, 6), max_new=4)
        assert s.reason == "draining" and s.retry_after is None
        # and with no admission controller at all
        engine.admission = None
        s2 = engine.submit(rng.randint(0, 64, 6), max_new=4)
        assert s2.reason == "draining" and s2.retry_after is None

    def test_queue_full_shed_carries_retry_after(self, engine):
        """The ROADMAP admission open end: capacity sheds quote the
        predictor's queue-drain estimate, not just drain-mode ones."""
        rng = np.random.RandomState(6)
        ctrl = _warm_admission(tpot=0.02)
        ctrl.max_queue = 1
        engine.admission = ctrl
        engine.submit(rng.randint(0, 64, 6), max_new=8)   # queued
        backlog = engine._backlog_tokens() + 4
        s = engine.submit(rng.randint(0, 64, 6), max_new=4)
        assert s.reason == "queue_full"
        assert s.retry_after == pytest.approx(
            0.02 * backlog / engine.n_slots, rel=0.5)

    def test_complete_drain_epoch_monotonic(self, engine):
        engine.epoch = 4
        engine.drain()
        with pytest.raises(ValueError, match="backwards"):
            engine.complete_drain(epoch=3)
        assert engine.draining          # the bad call changed nothing
        engine.complete_drain(epoch=4)  # same epoch is fine
        assert not engine.draining and engine.epoch == 4


class TestQueueCarryOver:
    def test_export_import_preserves_order_and_timestamps(
            self, mini_adapter, mini_params, engine, oracle):
        rng = np.random.RandomState(7)
        reqs = [(rng.randint(0, 64, rng.randint(2, 10)),
                 int(rng.randint(4, 10))) for _ in range(10)]
        rids = [engine.submit(p, max_new=m, tenant="t") for p, m in reqs]
        engine.step()
        engine.drain()                  # 8 served, 2 still queued
        carried = engine.export_queue()
        assert [r.rid for r in carried] == rids[8:]
        assert all(r.t_submit > 0 for r in carried)
        assert len(engine._queue) == 0
        # staged pool rows were freed with the queue (trie-cached
        # prefix blocks may stay resident — that retention is the
        # prefix cache; the refcount audit proves nothing leaked)
        assert not engine._alloc.rows()
        assert not engine._alloc.leak_report()

        new_engine = ServingEngine(
            mini_adapter, mini_params, n_slots=8, horizon=160,
            max_prompt=16, block=8, round_tokens=4, epoch=1)
        new_engine.import_queue(carried)
        # tenant in-flight accounting moved with the queue
        assert new_engine._tenant_tokens["t"] == sum(
            m for _, m in reqs[8:])
        out = {c.rid: c for c in new_engine.run(max_steps=500)}
        for rid, (p, m) in zip(rids[8:], reqs[8:]):
            assert out[rid].status == "ok"
            np.testing.assert_array_equal(out[rid].tokens, oracle(p, m))
        # queue-wait stayed honest: served under the new engine, waited
        # since the ORIGINAL submit
        assert all(out[r].queue_wait > 0 for r in rids[8:])

    def test_import_rejects_duplicate_rid(self, engine):
        rng = np.random.RandomState(8)
        rid = engine.submit(rng.randint(0, 64, 6), max_new=4)
        (req,) = engine.export_queue()
        engine.import_queue([req])      # round-trips fine
        with pytest.raises(ValueError, match="already live"):
            engine.import_queue([req])
        del rid

    def test_import_advances_auto_rid_counter(
            self, mini_adapter, mini_params, engine):
        """Imported auto rids ("r<n>") join the new engine's namespace:
        the rid counter must advance past them, or the n-th NATIVE
        submit after the handover regenerates an imported id and raises
        "already live" at an ordinary caller."""
        rng = np.random.RandomState(9)
        for _ in range(10):
            engine.submit(rng.randint(0, 64, 6), max_new=4)
        engine.step()
        engine.drain()                  # 8 served; r8, r9 still queued
        carried = engine.export_queue()
        assert [r.rid for r in carried] == ["r8", "r9"]
        new_engine = ServingEngine(
            mini_adapter, mini_params, n_slots=8, horizon=160,
            max_prompt=16, block=8, round_tokens=4, epoch=1)
        new_engine.import_queue(carried)
        native = [new_engine.submit(rng.randint(0, 64, 6), max_new=4)
                  for _ in range(12)]
        assert all(isinstance(r, str) for r in native)
        assert len(set(native) | {r.rid for r in carried}) == \
            len(native) + len(carried)
