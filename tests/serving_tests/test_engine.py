"""Continuous-batching engine: scheduling exactness and machinery.

The load-bearing property is TOKEN IDENTITY — every admitted request's
greedy tokens equal its solo static decode, whatever shared its rounds
(ragged prompts, staggered admissions, EOS freezes, tight horizons,
gang mode).  The oracle is conftest's plain-loop decode over the same
adapter functions, independent of all engine code."""

import time

import numpy as np
import pytest

from chainermn_tpu.parallel import MeshConfig
from chainermn_tpu.serving import (
    AdmissionController,
    MiniLMAdapter,
    ServingEngine,
    ShedCompletion,
)
from chainermn_tpu.serving.engine import Request
from chainermn_tpu.utils.telemetry import (
    TraceRecorder,
    get_recorder,
    set_recorder,
)



def _check_parity(comps, trace_rids, oracle, eos=-1):
    by_rid = {c.rid: c for c in comps}
    assert sorted(by_rid) == sorted(r for r, _, _ in trace_rids)
    for rid, prompt, max_new in trace_rids:
        ref = oracle(prompt, max_new, eos=eos)
        got = by_rid[rid].tokens
        np.testing.assert_array_equal(
            got, ref, err_msg=f"request {rid} diverged from its solo "
                              f"static decode")


def _submit_all(eng, trace):
    return [(eng.submit(p, max_new=n), p, n) for p, n in trace]


@pytest.fixture(scope="module")
def engine(mini_adapter, mini_params):
    """One engine reused across tests via reset() (compiles dominate)."""
    return ServingEngine(mini_adapter, mini_params, n_slots=8,
                         horizon=160, max_prompt=16, block=8,
                         round_tokens=4)


class TestParity:
    def test_continuous_matches_solo(self, engine, oracle, ragged_trace):
        engine.reset()
        trace = ragged_trace(np.random.RandomState(0), 20)
        rids = _submit_all(engine, trace)
        comps = engine.run(max_steps=2000)
        _check_parity(comps, rids, oracle)
        # more requests than slots: admission really happened
        # mid-stream, after other rows were evicted
        assert any(
            c2.t_admit > c1.t_done for c1 in comps for c2 in comps)

    def test_staggered_arrivals(self, engine, oracle, ragged_trace):
        engine.reset()
        rng = np.random.RandomState(1)
        trace = ragged_trace(rng, 14)
        rids = _submit_all(engine, trace[:6])
        comps = []
        for p, n in trace[6:]:
            comps.extend(engine.step())
            rids.append((engine.submit(p, max_new=n), p, n))
        comps.extend(engine.run(max_steps=2000))
        _check_parity(comps, rids, oracle)

    def test_eos_and_pad_cross_products(self, mini_adapter, mini_params,
                                        oracle, ragged_trace):
        # choose an eos that provably occurs: a mid-stream token of the
        # first request's own solo decode
        rng = np.random.RandomState(2)
        trace = ragged_trace(rng, 10, min_new=8)
        eos = int(oracle(trace[0][0], trace[0][1])[2])
        stopped = sum(
            eos in oracle(p, n)[:-1] or oracle(p, n, eos=eos).shape[0] < n
            for p, n in trace)
        assert stopped >= 1      # the suite really exercises freezing
        for pad in (0, eos):     # pad != eos and the HF pad==eos setup
            eng = ServingEngine(mini_adapter, mini_params, n_slots=8,
                                horizon=160, max_prompt=16, block=8,
                                round_tokens=4, eos_id=eos, pad_id=pad)
            rids = _submit_all(eng, trace)
            comps = eng.run(max_steps=2000)
            _check_parity(comps, rids, oracle, eos=eos)

    def test_tight_horizon_serves_forever(self, mini_adapter,
                                          mini_params, oracle,
                                          ragged_trace):
        # the horizon that forced the old rebase shift: origin-0 rows
        # only need prompt + max_new <= horizon per REQUEST, so a
        # 24-request trace over horizon=40 drains with zero shifts
        eng = ServingEngine(mini_adapter, mini_params, n_slots=8,
                            horizon=40, max_prompt=16, block=8,
                            round_tokens=4)
        trace = ragged_trace(np.random.RandomState(3), 24, min_new=12,
                             max_new=20)
        rids = _submit_all(eng, trace)
        comps = eng.run(max_steps=4000)
        assert "rebases" not in eng.stats()   # the program is gone
        _check_parity(comps, rids, oracle)

    def test_gang_mode_matches_solo_and_waves(self, engine, oracle,
                                              ragged_trace):
        engine.reset()
        engine.gang = True
        try:
            trace = ragged_trace(np.random.RandomState(4), 12)
            rids = _submit_all(engine, trace)
            comps = engine.run(max_steps=2000)
            _check_parity(comps, rids, oracle)
            # static batching: the second wave admits only after every
            # first-wave row drained
            wave1 = set(engine.admit_log[:8])
            first_done = {c.rid: c.t_done for c in comps}
            wave2_admits = [c.t_admit for c in comps
                            if c.rid not in wave1]
            assert wave2_admits and min(wave2_admits) >= max(
                first_done[r] for r in wave1)
        finally:
            engine.gang = False


class TestScheduling:
    def test_fcfs_order(self, engine, ragged_trace):
        engine.reset()
        trace = ragged_trace(np.random.RandomState(5), 12)
        rids = _submit_all(engine, trace)
        engine.run(max_steps=2000)
        assert engine.admit_log[:8] == [r for r, _, _ in rids[:8]]

    def test_shortest_prompt_first(self, engine, ragged_trace):
        engine.reset()
        engine.set_policy("spf")
        try:
            trace = ragged_trace(np.random.RandomState(6), 12)
            rids = _submit_all(engine, trace)
            engine.run(max_steps=2000)
            lens = {r: p.shape[0] for r, p, _ in rids}
            first = [lens[r] for r in engine.admit_log[:8]]
            shortest = sorted(lens.values())[:8]
            assert sorted(first) == shortest
            assert first == sorted(first)   # admitted ascending
        finally:
            engine.set_policy("fcfs")

    def test_custom_policy_callable(self, mini_adapter, mini_params,
                                    ragged_trace):
        picks = []

        def longest_budget(queue, eng):
            req = max(queue, key=lambda r: r.max_new)
            picks.append(req.rid)
            return req

        eng = ServingEngine(mini_adapter, mini_params, n_slots=8,
                            horizon=160, max_prompt=16, block=8,
                            round_tokens=4, policy=longest_budget)
        trace = ragged_trace(np.random.RandomState(7), 10)
        _submit_all(eng, trace)
        eng.run(max_steps=2000)
        assert picks and eng.admit_log[:len(picks)] == picks[:len(
            eng.admit_log)]

    def test_bad_policy_rejected(self, mini_adapter, mini_params):
        with pytest.raises(ValueError, match="policy"):
            ServingEngine(mini_adapter, mini_params, n_slots=8,
                          horizon=160, max_prompt=16, policy="lifo")

    def test_policy_returning_non_queue_request_raises(self, engine):
        """A callable policy that fabricates a request (or returns a
        stale one) must fail loudly at the pick, not admit garbage."""
        engine.reset()
        rogue = Request("ghost", np.arange(4, dtype=np.int32), 4)
        engine.set_policy(lambda queue, eng: rogue)
        try:
            engine.submit(np.arange(4) % 64, max_new=4)
            with pytest.raises(ValueError,
                               match="not in the queue"):
                engine.step()
        finally:
            engine.set_policy("fcfs")
            engine.reset()

    def test_submit_validation_rejects_degenerate_requests(self,
                                                           engine):
        engine.reset()
        with pytest.raises(ValueError, match="prompt length"):
            engine.submit(np.zeros(0, np.int32))
        with pytest.raises(ValueError, match="max_new"):
            engine.submit(np.zeros(4, np.int32), max_new=0)
        with pytest.raises(ValueError, match="max_new"):
            engine.submit(np.zeros(4, np.int32), max_new=-3)
        assert engine.idle          # nothing leaked into the queue

    def test_pool_backpressure_victim_steal_with_shedding(
            self, mini_adapter, mini_params, oracle, ragged_trace):
        """The PR 8 steal path × the admission layer: a one-chunk pool
        forces the admission path to steal ahead-staged blocks while a
        controller is attached and a hopeless deadline sheds — tokens
        of everything SERVED stay exact, the shed is typed, nothing
        deadlocks."""
        eng = ServingEngine(mini_adapter, mini_params, n_slots=8,
                            horizon=160, max_prompt=16, block=8,
                            pool_blocks=2, round_tokens=4, policy="spf",
                            prefill_ahead=4,
                            admission=AdmissionController(max_queue=64))
        rng = np.random.RandomState(12)
        blockers = ragged_trace(rng, 8, min_new=16, max_new=20)
        rids = [(eng.submit(p, max_new=n), p, n) for p, n in blockers]
        for _ in range(2):
            eng.step()              # all slots busy; ahead-staging runs
        long_p = rng.randint(0, 64, 16)
        short_p = rng.randint(0, 64, 3)
        rids.append((eng.submit(long_p, max_new=6), long_p, 6))
        rids.append((eng.submit(short_p, max_new=6), short_p, 6))
        doomed = eng.submit(rng.randint(0, 64, 4), max_new=6,
                            timeout=1e-4)
        time.sleep(2e-3)
        out = eng.run(max_steps=2000)
        sheds = [c for c in out if isinstance(c, ShedCompletion)]
        assert [s.rid for s in sheds] == [doomed]
        assert sheds[0].reason == "timeout"
        comps = [c for c in out if not isinstance(c, ShedCompletion)]
        _check_parity(comps, rids, oracle)

    def test_pool_backpressure_steals_ahead_staging(
            self, mini_adapter, mini_params, oracle, ragged_trace):
        # pool holds exactly ONE full prompt chunk: prefill-ahead
        # stages the queue head; shortest-prompt-first then admits a
        # DIFFERENT request, which must steal the staged blocks and
        # re-stage — nothing deadlocks, tokens stay exact
        eng = ServingEngine(mini_adapter, mini_params, n_slots=8,
                            horizon=160, max_prompt=16, block=8,
                            pool_blocks=2, round_tokens=4, policy="spf",
                            prefill_ahead=4)
        rng = np.random.RandomState(8)
        blockers = ragged_trace(rng, 8, min_new=16, max_new=20)
        rids = _submit_all(eng, blockers)
        for _ in range(2):
            eng.step()              # all slots busy; ahead-staging runs
        long_p = rng.randint(0, 64, 16)
        short_p = rng.randint(0, 64, 3)
        rids.append((eng.submit(long_p, max_new=6), long_p, 6))
        rids.append((eng.submit(short_p, max_new=6), short_p, 6))
        comps = eng.run(max_steps=2000)
        _check_parity(comps, rids, oracle)


class TestMachinery:
    def test_admit_staging_is_copied(self, engine):
        """The deferred-device_put aliasing regression (the
        iterators.prefetch hazard): everything handed to a jitted call
        from the reused staging buffers must be a fresh copy."""
        engine.reset()
        st = engine._lprompt_staging
        c = engine._staging_copy(st)
        assert c is not st and not np.shares_memory(c, st)
        # behavioural: the staged entry survives the staging buffer
        # being rewritten by the NEXT admission
        rng = np.random.RandomState(9)
        p1 = rng.randint(0, 64, 10)
        rid1 = engine.submit(p1, max_new=4)
        rec = get_recorder()
        req1 = engine._queue[0]
        assert engine._stage(req1, rec, steal=False)
        staged_prompt = engine._staged[rid1][1]
        engine._lprompt_staging[:] = -7     # simulate the next rewrite
        assert not np.shares_memory(staged_prompt,
                                    engine._lprompt_staging)
        # left-aligned staging: token i at row position i
        assert staged_prompt[len(p1) - 1] == p1[-1]
        engine.reset()

    def test_back_to_back_admits_share_staging_safely(self, engine,
                                                      oracle):
        engine.reset()
        rng = np.random.RandomState(10)
        p1, p2 = rng.randint(0, 64, 12), rng.randint(0, 64, 12)
        rids = [(engine.submit(p1, max_new=8), p1, 8),
                (engine.submit(p2, max_new=8), p2, 8)]
        comps = engine.run(max_steps=500)
        _check_parity(comps, rids, oracle)

    def test_telemetry_spans_and_counters(self, mini_adapter,
                                          mini_params, ragged_trace):
        prev = set_recorder(TraceRecorder(capacity=8192, enabled=True))
        try:
            eng = ServingEngine(mini_adapter, mini_params, n_slots=8,
                                horizon=160, max_prompt=16, block=8,
                                round_tokens=4)
            trace = ragged_trace(np.random.RandomState(11), 10)
            _submit_all(eng, trace)
            eng.run(max_steps=2000)
            events = get_recorder().events()
            names = {e["name"] for e in events}
            for required in ("serve/admit", "serve/prefill",
                             "serve/decode_round", "serve/evict"):
                assert required in names, names
            depth = [e for e in events
                     if e["name"] == "serve/queue_depth"]
            assert depth and any(e["dur"] > 0 for e in depth)
            admits = [e for e in events if e["name"] == "serve/admit"]
            assert len(admits) == len(trace)
            assert all("rid" in e["meta"] and "slot" in e["meta"]
                       for e in admits)
            # chrome export round-trips (Perfetto via merge_traces is
            # pinned in util_tests; here: serve events survive export)
            chrome = get_recorder().chrome_events()
            assert any(e.get("name") == "serve/decode_round"
                       for e in chrome)
        finally:
            set_recorder(prev)

    def test_completion_metadata(self, engine):
        engine.reset()
        p = np.arange(5) % 64
        t0 = time.perf_counter()
        rid = engine.submit(p, max_new=6)
        comps = engine.run(max_steps=500)
        (c,) = comps
        assert c.rid == rid and c.n_generated == 6
        assert t0 <= c.t_submit <= c.t_admit <= c.t_first <= c.t_done
        assert c.ttft >= 0
        st = engine.stats()
        assert st["useful_tokens"] == 6 and st["rounds"] >= 2

    def test_validation(self, mini_adapter, mini_params, mini_cfg):
        with pytest.raises(ValueError, match="multiple"):
            ServingEngine(mini_adapter, mini_params, n_slots=6,
                          horizon=160, max_prompt=16)
        with pytest.raises(ValueError, match="horizon"):
            ServingEngine(mini_adapter, mini_params, n_slots=8,
                          horizon=16, max_prompt=16)
        eng = ServingEngine(mini_adapter, mini_params, n_slots=8,
                            horizon=160, max_prompt=16, block=8)
        with pytest.raises(ValueError, match="prompt length"):
            eng.submit(np.zeros(17, np.int32))
        with pytest.raises(ValueError, match="max_new"):
            eng.submit(np.zeros(4, np.int32), max_new=1000)
        eng.submit(np.zeros(4, np.int32), max_new=4, request_id="dup")
        with pytest.raises(ValueError, match="already live"):
            eng.submit(np.zeros(4, np.int32), max_new=4,
                       request_id="dup")
        with pytest.raises(ValueError, match="batch axes"):
            MiniLMAdapter(MeshConfig(data=4, model=2), mini_cfg)
