"""Fleet chaos drills: every failure mode the ``FleetRouter``
promises to absorb — replica kill mid-decode, slow replica (hedge
wins), flapping replica (damped out of rotation), brown-out (priority
sheds) — scripted through ``FaultPlan``'s fleet actions and pinned to
the two fleet invariants: ZERO lost non-shed requests (each fleet id
delivered exactly once) and token output bitwise-identical to the
engine-independent solo oracle, whatever hedges, retries, and
failovers raced underneath (docs/RESILIENCE.md, fleet rows).

Plus the satellite units riding the same PR: the CRC-guarded prefix
snapshot (cache export/import for warm rejoin) and the
queue-POSITION-conditioned admission wait (the ``--max-queue 0``
over-shed fix)."""

import json
import time
import zlib

import numpy as np
import pytest

from chainermn_tpu.serving import (
    AdmissionController,
    FleetRouter,
    Request,
    RetryBudget,
    ServingEngine,
    ShedCompletion,
    load_prefix_snapshot,
    prefix_snapshot,
)
from chainermn_tpu.testing import FaultInjector, FaultPlan
from chainermn_tpu.utils.metrics import MetricsRegistry, set_registry

VOCAB = 64


@pytest.fixture()
def registry():
    reg = MetricsRegistry(enabled=True)
    prev = set_registry(reg)
    yield reg
    set_registry(prev)


def _engine(mini_adapter, mini_params, **kw):
    kw.setdefault("n_slots", 8)
    kw.setdefault("horizon", 96)
    kw.setdefault("max_prompt", 48)
    kw.setdefault("block", 4)
    kw.setdefault("pool_blocks", 256)
    return ServingEngine(mini_adapter, mini_params, **kw)


def _trace(rng, n, lo_new=4, hi_new=16, max_prompt=16):
    return [(rng.randint(0, VOCAB, rng.randint(2, max_prompt)),
             int(rng.randint(lo_new, hi_new)))
            for _ in range(n)]


def _assert_exactly_once_ok(router, reqs, oracle):
    """The two fleet invariants, asserted together: every submitted
    fleet id delivered exactly once with status ok, tokens bitwise
    the solo oracle's."""
    by = {}
    for r in router.request_records():
        assert r.rid not in by, f"duplicate delivery for {r.rid}"
        by[r.rid] = r
    for fid, prompt, max_new in reqs:
        r = by[fid]
        assert r.status == "ok", \
            (fid, r.status, getattr(r, "detail", ""))
        np.testing.assert_array_equal(
            np.asarray(r.tokens), oracle(prompt, max_new),
            err_msg=f"{fid} diverged from the solo oracle")


class TestFleetRouting:
    def test_routes_completes_and_reports(self, mini_adapter,
                                          mini_params, oracle,
                                          registry):
        router = FleetRouter([_engine(mini_adapter, mini_params),
                              _engine(mini_adapter, mini_params)])
        rng = np.random.RandomState(0)
        reqs = [(router.submit(p, n), p, n)
                for p, n in _trace(rng, 10)]
        router.run(max_steps=300)
        assert router.idle
        _assert_exactly_once_ok(router, reqs, oracle)
        assert registry.snapshot()["fleet/route"]["value"] >= 10
        # the statusz section contract: status() is JSON-safe
        json.dumps(router.status())

    def test_prefix_placement_follows_the_cache(
            self, mini_adapter, mini_params):
        router = FleetRouter([_engine(mini_adapter, mini_params),
                              _engine(mini_adapter, mini_params)])
        shared = np.arange(16, dtype=np.int32) % VOCAB
        fid = router.submit(shared, 4, session="conv")
        router.run(max_steps=100)
        home = router._sessions["conv"]
        # a cache-sharing follow-up routes to the SAME replica both
        # by prefix score and by session affinity
        follow = np.concatenate(
            [shared, np.array([3, 1], np.int32)])
        fid2 = router.submit(follow, 4, session="conv")
        assert router._sessions["conv"] == home
        router.run(max_steps=100)
        eng = router._by_name[home].engine
        assert {fid, fid2} <= {c.rid for c in eng.request_records()}
        assert eng._alloc.stats()["prefix_hits"] > 0

    def test_cancel_pending_and_dispatched(self, mini_adapter,
                                           mini_params):
        router = FleetRouter([_engine(mini_adapter, mini_params)])
        fid = router.submit(np.arange(8, dtype=np.int32), 24)
        assert router.cancel(fid)
        router.run(max_steps=100)
        recs = router.request_records()
        assert [r.rid for r in recs].count(fid) == 1
        assert recs[-1].status in ("shed", "cancelled")
        assert not router.cancel("f999")


@pytest.mark.drill
class TestKillDrill:
    def test_kill_mid_decode_exactly_once_token_identical(
            self, mini_adapter, mini_params, oracle, registry):
        """THE acceptance drill: one of two replicas crashes
        mid-trace.  Queued requests migrate via export/import, active
        rows re-dispatch from their committed prefixes, and every
        request still completes exactly once, token-bitwise the solo
        oracle."""
        router = FleetRouter([_engine(mini_adapter, mini_params),
                              _engine(mini_adapter, mini_params)])
        rng = np.random.RandomState(1)
        # oversubscribe 2x8 slots so the kill catches BOTH queued and
        # active requests on the dying replica
        reqs = [(router.submit(p, n), p, n)
                for p, n in _trace(rng, 28)]
        inj = FaultInjector(FaultPlan(fleet_kill_at_step=2,
                                      fleet_kill_replica=0))
        inj.attach_fleet(router)
        router.run(max_steps=500)
        assert router.idle
        assert ("fleet_kill", 2) in inj.fired
        assert router.n_failovers == 1
        assert router.n_migrated > 0, \
            "the kill must catch queued requests (queue migration arm)"
        assert router.n_retries > 0, \
            "the kill must catch active rows (committed re-dispatch)"
        _assert_exactly_once_ok(router, reqs, oracle)
        snap = registry.snapshot()
        assert snap["fleet/failover"]["value"] == 1
        assert router._by_name["replica0"].state == "dead"
        # the dead engine was reset: clean pool, ready to revive
        assert not router._by_name["replica0"].engine._alloc \
            .leak_report()

    def test_revive_rejoins_warm(self, mini_adapter, mini_params,
                                 oracle, registry):
        """A killed replica revived with its death-time prefix
        snapshot rejoins WARM: the prefixes its cache held are cached
        again before it takes traffic."""
        router = FleetRouter([_engine(mini_adapter, mini_params),
                              _engine(mini_adapter, mini_params)],
                             rejoin_hold=1)
        shared = (np.arange(24, dtype=np.int32) * 3) % VOCAB
        fid = router.submit(shared, 4)
        router.run(max_steps=100)
        served = [h for h in router.replicas
                  if fid in {c.rid for c in
                             h.engine.request_records()}][0]
        idx = router.replicas.index(served)
        inj = FaultInjector(FaultPlan(fleet_kill_at_step=0,
                                      fleet_kill_replica=idx))
        inj.attach_fleet(router)
        router.step()
        assert served.state == "dead"
        router.revive(served.name)
        assert served.state == "rejoining"
        run = served.engine._alloc._trie.lookup_run(shared)
        assert len(run) * served.engine.block >= \
            (shared.shape[0] // served.engine.block) \
            * served.engine.block - served.engine.block, \
            "rejoined replica must hold the snapshot prefixes again"


@pytest.mark.drill
class TestHedgeDrill:
    def test_slow_replica_hedge_wins_no_duplicates(
            self, mini_adapter, mini_params, oracle, registry):
        """A stalling replica's request is hedged onto the healthy
        one; the hedge wins, the loser is cancelled, delivery stays
        exactly-once and token-identical."""
        router = FleetRouter([_engine(mini_adapter, mini_params),
                              _engine(mini_adapter, mini_params)],
                             hedge_after=0.01)
        prompt = np.arange(10, dtype=np.int32)
        # replica0 is the empty-fleet placement winner; stall it
        inj = FaultInjector(FaultPlan(fleet_slow_at_step=0,
                                      fleet_slow_replica=0,
                                      fleet_slow_seconds=0.15,
                                      fleet_slow_steps=30))
        inj.attach_fleet(router)
        fid = router.submit(prompt, 8)
        router.run(max_steps=200)
        assert router.idle
        assert any(k == "fleet_slow" for k, _ in inj.fired)
        assert router.n_hedges == 1
        assert router.n_hedge_won + router.n_hedge_lost == 1
        recs = [r for r in router.request_records() if r.rid == fid]
        assert len(recs) == 1 and recs[0].status == "ok"
        np.testing.assert_array_equal(np.asarray(recs[0].tokens),
                                      oracle(prompt, 8))
        snap = registry.snapshot()
        won = snap.get("fleet/hedge_won", {"value": 0})["value"]
        lost = snap.get("fleet/hedge_lost", {"value": 0})["value"]
        assert won + lost == 1

    def test_hedge_denied_when_budget_empty(self, mini_adapter,
                                            mini_params, registry):
        router = FleetRouter(
            [_engine(mini_adapter, mini_params),
             _engine(mini_adapter, mini_params)],
            hedge_after=0.0,
            retry_budget=RetryBudget(capacity=1, refill=0.0))
        router.retry_budget.tokens = 0.0
        fid = router.submit(np.arange(6, dtype=np.int32), 4)
        router.run(max_steps=200)
        assert router.n_hedges == 0
        assert router.retry_budget.denied >= 1
        assert router.request_records()[-1].rid == fid


@pytest.mark.drill
class TestFlapDrill:
    def test_flapping_replica_is_damped(self, mini_adapter,
                                        mini_params, oracle,
                                        registry):
        """A crash-looping replica's rejoin hold must GROW
        exponentially (flap damping) while the stable replica serves
        every request to oracle-identical completion."""
        router = FleetRouter([_engine(mini_adapter, mini_params),
                              _engine(mini_adapter, mini_params)],
                             rejoin_hold=1, flap_damping=2.0,
                             warm_on_rejoin=False)
        inj = FaultInjector(FaultPlan(fleet_flap_at_step=1,
                                      fleet_flap_replica=0,
                                      fleet_flap_count=3))
        inj.attach_fleet(router)
        rng = np.random.RandomState(2)
        reqs = [(router.submit(p, n), p, n)
                for p, n in _trace(rng, 12, lo_new=16, hi_new=24)]
        router.run(max_steps=500)
        assert router.idle
        h = router._by_name["replica0"]
        kills = [f for f in inj.fired if f[0] == "fleet_flap_kill"]
        revives = [f for f in inj.fired
                   if f[0] == "fleet_flap_revive"]
        assert len(kills) >= 2 and len(revives) >= 2
        assert h.deaths == len(kills)
        # damping: the LAST applied hold is rejoin_hold * 2**(k-1)
        assert h.rejoin_hold == min(router.max_hold,
                                    2 ** (h.deaths - 1))
        _assert_exactly_once_ok(router, reqs, oracle)


@pytest.mark.drill
class TestBrownOutDrill:
    def test_brown_out_sheds_low_priority_only(
            self, mini_adapter, mini_params, oracle, registry):
        """With the fleet saturated past the brown-out threshold,
        arriving LOW-priority traffic sheds ``"overload"`` at the
        door while the protected class completes untouched."""
        engines = [_engine(mini_adapter, mini_params,
                           admission=AdmissionController())
                   for _ in range(2)]
        router = FleetRouter(engines, brown_out_after=1e-4,
                             protect_priority=0)
        rng = np.random.RandomState(3)
        # evidence first: the predictors must SEE service before any
        # brown-out verdict (shedding needs evidence, fleet-wide);
        # top the histograms up past min_count deterministically
        warm = [(router.submit(p, n), p, n)
                for p, n in _trace(rng, 10)]
        router.run(max_steps=300)
        _assert_exactly_once_ok(router, warm, oracle)
        for eng in engines:
            for _ in range(eng.admission.predictor.min_count):
                eng.admission.predictor.observe_tpot(0.01)
        # saturate with protected traffic, then arrive low-priority
        load = [(router.submit(p, n, priority=0), p, n)
                for p, n in _trace(rng, 20, lo_new=12, hi_new=16)]
        assert router.predicted_queue_wait() > router.brown_out_after
        lowly = router.submit(np.arange(8, dtype=np.int32), 8,
                              priority=1)
        assert isinstance(lowly, ShedCompletion)
        assert lowly.reason == "overload"
        assert "brown-out" in lowly.detail
        protected = router.submit(np.arange(8, dtype=np.int32), 8,
                                  priority=0)
        assert not isinstance(protected, ShedCompletion)
        router.run(max_steps=500)
        assert router.idle
        _assert_exactly_once_ok(router, load, oracle)
        assert registry.snapshot()["fleet/sheds"]["value"] >= 1


@pytest.mark.drill
class TestRetryBudgetDrill:
    def test_persistent_failure_stays_inside_budget(
            self, mini_adapter, mini_params, registry):
        """A replica that dies EVERY time it serves (persistent
        failure) must burn retries only up to the fleet budget, then
        degrade to a shed — never a retry storm, never a hang."""
        router = FleetRouter(
            [_engine(mini_adapter, mini_params)],
            rejoin_hold=0, warm_on_rejoin=False,
            retry_budget=RetryBudget(capacity=2, refill=0.0),
            max_retries=10)
        inj = FaultInjector(FaultPlan(fleet_flap_at_step=0,
                                      fleet_flap_replica=0,
                                      fleet_flap_count=50))
        inj.attach_fleet(router)
        fid = router.submit(np.arange(8, dtype=np.int32), 8)
        router.run(max_steps=100)
        assert router.idle
        recs = [r for r in router.request_records() if r.rid == fid]
        assert len(recs) == 1
        assert recs[0].status == "shed"
        assert router.retry_budget.spent <= 2
        assert router.retry_budget.denied >= 1
        assert router.n_retries <= 2
        assert registry.snapshot()["fleet/retries"]["value"] <= 2


class TestPrefixSnapshot:
    def test_roundtrip_maximal_prefixes(self):
        from chainermn_tpu.serving import PrefixTrie

        t = PrefixTrie(4)
        toks = np.arange(12, dtype=np.int32)
        for j, bid in enumerate((10, 11, 12)):
            t.insert(toks, j, bid)
        t.insert(np.full((4,), 9, np.int32), 0, 13)
        snap = prefix_snapshot(t)
        assert snap["format_version"] == 1
        # only MAXIMAL prefixes ship (ancestors reconstruct on insert)
        assert sorted(map(len, snap["prefixes"])) == [4, 12]
        back = load_prefix_snapshot(snap)
        assert any(np.array_equal(p, toks) for p in back)
        json.dumps(snap)        # snapshot-rideable: JSON-safe

    def test_crc_guard_and_version_gate(self):
        from chainermn_tpu.serving import PrefixTrie

        t = PrefixTrie(4)
        t.insert(np.arange(8, dtype=np.int32), 0, 1)
        snap = prefix_snapshot(t)
        corrupt = dict(snap)
        corrupt["prefixes"] = [[7, 7, 7, 7]]
        with pytest.raises(ValueError, match="CRC"):
            load_prefix_snapshot(corrupt)
        future = dict(snap, format_version=99)
        assert load_prefix_snapshot(future) == []

    def test_engine_import_warms_cache(self, mini_adapter,
                                       mini_params):
        a = _engine(mini_adapter, mini_params)
        prompt = (np.arange(20, dtype=np.int32) * 5) % VOCAB
        a.submit(prompt, 4)
        a.run(max_steps=100)
        snap = prefix_snapshot(a._alloc)
        assert snap["prefixes"]
        b = _engine(mini_adapter, mini_params)
        n = b.import_prefixes(load_prefix_snapshot(snap))
        assert n > 0
        assert b.idle
        assert len(b._alloc._trie.lookup_run(prompt)) > 0
        # idempotent: importing again warms nothing new
        assert b.import_prefixes(load_prefix_snapshot(snap)) == 0


class _FrozenClock:
    """Manually advanced time source — hedge/backoff deadlines fire
    exactly when the test says so."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _anchored_prompt(anchor, n_replicas=2, block=4, length=8, salt=0):
    """A distinct prompt whose leading-block hash anchors placement
    on ``replicas[anchor]`` (mirrors the router's cold-prefix
    affinity), so multi-replica drills place deterministically."""
    i = salt
    while True:
        p = (np.arange(length, dtype=np.int32) * 7 + i) % VOCAB
        lead = np.ascontiguousarray(p[:block], np.int32).tobytes()
        if zlib.crc32(lead) % n_replicas == anchor:
            return p
        i += 1


@pytest.mark.drill
class TestHedgeFailoverRaces:
    """The hedge x failover interaction drills: a dead replica's
    exported queue can hold hedge copies whose twin is alive
    elsewhere — those must ride the surviving copy, never migrate
    into a duplicate-rid crash or deliver with a dropped prefix."""

    def test_hedge_copy_queued_on_dead_replica(
            self, mini_adapter, mini_params, oracle, registry):
        """Primary active on the survivor, hedge copy QUEUED on the
        replica that dies: failover must not migrate the orphan
        hedge onto the replica its twin already occupies
        (previously: import_queue 'already live' -> blind re-dispatch
        -> uncaught ValueError out of step()).

        Both replicas' slots are SATURATED before the clock jump so
        the hedge copies actually queue (a free slot admits a submit
        eagerly, which would dodge the export_queue path)."""
        clk = _FrozenClock()
        router = FleetRouter(
            [_engine(mini_adapter, mini_params),
             _engine(mini_adapter, mini_params)],
            hedge_after=5.0, clock=clk,
            retry_budget=RetryBudget(capacity=32))
        n = router.replicas[0].engine.n_slots
        reqs = []
        for anchor in (1, 0):           # replica1's primaries first
            for i in range(n):
                p = _anchored_prompt(anchor, salt=1000 * anchor + i)
                reqs.append((router.submit(p, 16), p, 16))
        for _ in range(3 * n):
            if all(h.engine.n_active == n for h in router.replicas):
                break
            router.step()
        assert all(h.engine.n_active == n for h in router.replicas)
        clk.t = 10.0                    # past hedge_after: hedge all
        router.step()
        r1 = router._by_name["replica1"]
        queued_hedges = [q.rid for q in r1.engine._queue]
        assert queued_hedges, "drill needs hedge copies QUEUED on " \
                              "the dying replica"
        for rid in queued_hedges:       # ...whose twin is live on r0
            assert "replica0" in router._flights[rid].dispatches
        real = router._step_replica
        state = {"killed": False}

        def crashing(h):
            if h.name == "replica1" and not state["killed"]:
                state["killed"] = True
                raise RuntimeError("injected crash under hedge")
            return real(h)

        router._step_replica = crashing
        router.step()                   # the death + failover tick
        assert r1.state == "dead"
        # the orphan hedge copies were NOT planted on the survivor's
        # engine as duplicates; the flights ride their live copies
        for rid in queued_hedges:
            fl = router._flights.get(rid)
            if fl is not None:
                assert list(fl.dispatches) == ["replica0"]
        router.run(max_steps=800)
        assert router.idle
        _assert_exactly_once_ok(router, reqs, oracle)

    def test_completed_prefix_retry_never_overruns_max_new(
            self, mini_adapter, mini_params, oracle):
        """A retry of a flight whose committed prefix already fills
        ``max_new`` must DELIVER the prefix, not submit with
        ``max(remaining, 1)`` and grow a max_new+1 token stream."""
        from chainermn_tpu.serving.fleet import _Flight

        router = FleetRouter([_engine(mini_adapter, mini_params)])
        prompt = np.arange(8, dtype=np.int32)
        full = np.asarray(oracle(prompt, 4), np.int32)
        fl = _Flight(fid="fq", prompt=prompt, max_new=4, t_submit=0.0)
        fl.committed = full
        router._flights["fq"] = fl
        router._retry_or_shed(fl, 0.0, [])
        router.run(max_steps=50)
        recs = [r for r in router.request_records() if r.rid == "fq"]
        assert len(recs) == 1 and recs[0].status == "ok"
        toks = np.asarray(recs[0].tokens)
        assert toks.shape[0] <= 4, \
            f"token budget overrun: {toks.shape[0]} > max_new=4"
        np.testing.assert_array_equal(toks, full)

    def test_refused_hedge_refunds_the_retry_budget(
            self, mini_adapter, mini_params, registry):
        """A hedge candidate that sheds the submit must hand the
        budget token back — previously the same flight re-spent one
        every step while the replica kept refusing, draining the
        budget with zero hedges placed."""
        clk = _FrozenClock()
        e0 = _engine(mini_adapter, mini_params)
        e1 = _engine(mini_adapter, mini_params,
                     admission=AdmissionController(max_queue=1))
        router = FleetRouter([e0, e1], hedge_after=5.0, clock=clk)
        # fill replica1 out-of-band: every slot active + a full
        # queue, so every hedge submit there sheds "queue_full"
        # (admit one per step — the queue bound is 1, and prefill
        # admits one request per tick)
        for i in range(e1.n_slots):
            r = e1.submit(np.full((4,), 5 + i, np.int32), 48)
            assert not isinstance(r, ShedCompletion)
            while e1.n_active <= i:
                e1.step()
        assert e1.n_active == e1.n_slots
        r = e1.submit(np.full((4,), 40, np.int32), 48)
        assert not isinstance(r, ShedCompletion)
        fid = router.submit(_anchored_prompt(0, salt=0), 8)
        assert list(router._flights[fid].dispatches) == ["replica0"]
        cap = router.retry_budget.capacity
        clk.t = 10.0
        for _ in range(3):              # three refused hedge scans
            router.step()
        assert router.retry_budget.tokens == cap, \
            "refused hedges must not drain the retry budget"
        assert router.retry_budget.spent == 0
        assert router.n_hedges == 0
        router.run(max_steps=300)
        recs = [r for r in router.request_records() if r.rid == fid]
        assert len(recs) == 1 and recs[0].status == "ok"


class TestFleetAccountingAndBounds:
    def test_all_candidates_refused_counts_as_fleet_shed(
            self, mini_adapter, mini_params, registry):
        """The dispatch-time every-replica-refused verdict must hit
        ``n_sheds`` / ``fleet/sheds`` like every other shed path."""
        router = FleetRouter(
            [_engine(mini_adapter, mini_params,
                     admission=AdmissionController(max_queue=1))])
        keep = router.submit(np.arange(6, dtype=np.int32), 8)
        refused = router.submit(np.arange(6, dtype=np.int32) + 1, 8)
        assert isinstance(refused, str)     # shed delivers via step()
        assert router.n_sheds == 1
        assert registry.snapshot()["fleet/sheds"]["value"] == 1
        router.run(max_steps=200)
        by = {r.rid: r for r in router.request_records()}
        assert by[refused].status == "shed"
        assert by[refused].reason == "queue_full"
        assert by[keep].status == "ok"
        assert [r.rid for r in router.request_records()].count(
            refused) == 1

    def test_session_homes_are_lru_bounded(self, mini_adapter,
                                           mini_params):
        router = FleetRouter([_engine(mini_adapter, mini_params)],
                             max_sessions=2)
        for i, sess in enumerate(("s0", "s1", "s2")):
            router.submit(np.arange(6, dtype=np.int32) + i, 4,
                          session=sess)
        assert set(router._sessions) == {"s1", "s2"}
        router.submit(np.arange(6, dtype=np.int32) + 9, 4,
                      session="s1")       # touch: s1 is young again
        router.submit(np.arange(6, dtype=np.int32) + 10, 4,
                      session="s3")
        assert set(router._sessions) == {"s1", "s3"}
        router.run(max_steps=300)

    def test_max_records_bounds_retention(self, mini_adapter,
                                          mini_params):
        router = FleetRouter([_engine(mini_adapter, mini_params)],
                             max_records=2)
        fids = [router.submit(np.arange(6, dtype=np.int32) + i, 4)
                for i in range(3)]
        router.run(max_steps=300)
        assert router.idle
        recs = router.request_records()
        assert len(recs) == 2           # oldest aged out
        assert {r.rid for r in recs} <= set(fids)
        # idempotent-delivery memory is retained regardless
        assert len(router._delivered) == 3

    def test_engine_import_queue_is_all_or_nothing(
            self, mini_adapter, mini_params):
        eng = _engine(mini_adapter, mini_params)
        eng.submit(np.arange(4, dtype=np.int32), 4,
                   request_id="dup")
        batch = [Request("fresh", np.arange(4, dtype=np.int32), 4,
                         t_submit=0.0),
                 Request("dup", np.arange(4, dtype=np.int32) + 1, 4,
                         t_submit=0.0)]
        with pytest.raises(ValueError, match="already live"):
            eng.import_queue(batch)
        assert [r.rid for r in eng._queue] == ["dup"], \
            "a refused import must leave the queue untouched"
        with pytest.raises(ValueError, match="already live"):
            eng.import_queue([
                Request("twin", np.arange(4, dtype=np.int32), 4,
                        t_submit=0.0),
                Request("twin", np.arange(4, dtype=np.int32), 4,
                        t_submit=0.0)])
        assert [r.rid for r in eng._queue] == ["dup"]

    def test_retry_budget_refund(self):
        b = RetryBudget(capacity=2, refill=0.0)
        assert b.try_spend() and b.try_spend()
        assert b.tokens == 0.0 and b.spent == 2
        b.refund()
        assert b.tokens == 1.0 and b.spent == 1
        b.refund()
        b.refund()                      # never past capacity / below 0
        assert b.tokens == 2.0 and b.spent == 0


class TestQueuePositionAdmission:
    """The ``ServiceTimePredictor`` over-shed fix: predicted queue
    wait conditions on the POSITION the scheduling policy would give
    the arrival, not the whole queue."""

    @staticmethod
    def _hot_controller():
        ctrl = AdmissionController()
        for _ in range(10):
            ctrl.predictor.observe_service_ttft(0.01)
            ctrl.predictor.observe_tpot(0.01)
        return ctrl

    def _queue(self, n, max_new=100):
        return [Request(f"q{i}", np.arange(4, dtype=np.int32),
                        max_new, t_submit=0.0) for i in range(n)]

    def test_ahead_tokens_narrows_the_wait(self):
        ctrl = self._hot_controller()
        req = Request("new", np.arange(4, dtype=np.int32), 8,
                      t_submit=time.perf_counter(),
                      deadline=time.perf_counter() + 0.5)
        deep = self._queue(20)
        # whole-queue charge: 2000 backlog tokens at 10ms/tok over 8
        # slots ~ 2.5s wait -> shed
        admit, reason, _ = ctrl.check_submit(req, deep, {}, n_slots=8)
        assert not admit and reason == "deadline"
        # position-conditioned: the policy serves it FIRST -> feasible
        admit, reason, _ = ctrl.check_submit(req, deep, {}, n_slots=8,
                                             ahead_tokens=0)
        assert admit and reason is None

    def test_engine_policy_positions(self, mini_adapter, mini_params):
        eng = _engine(mini_adapter, mini_params, policy="deadline",
                      admission=self._hot_controller())
        eng._queue = self._queue(6)       # deadline-less backlog
        urgent = Request("u", np.arange(4, dtype=np.int32), 8,
                         t_submit=time.perf_counter(),
                         deadline=time.perf_counter() + 0.5)
        # deadline policy ranks the urgent arrival ahead of every
        # deadline-less queued request: nothing ahead of it
        assert eng._ahead_tokens(urgent) == 0
        eng.set_policy("fcfs")
        assert eng._ahead_tokens(urgent) == 600
        eng.set_policy("spf")
        short = Request("s", np.arange(2, dtype=np.int32), 8,
                        t_submit=0.0)
        assert eng._ahead_tokens(short) == 0
        eng.set_policy(lambda q, e: q[0])     # custom: unknowable
        assert eng._ahead_tokens(urgent) is None
        eng._queue = []

    def test_unbounded_queue_urgent_submit_admits(
            self, mini_adapter, mini_params):
        """The observed ``--max-queue 0`` (unbounded) symptom, end to
        end: under the deadline policy, an URGENT feasible-deadline
        arrival behind a deep deadline-less backlog must admit — the
        old whole-queue wait charge shed it "deadline" off a backlog
        it would never stand behind."""
        eng = _engine(mini_adapter, mini_params, policy="deadline",
                      admission=self._hot_controller())
        fillers = [eng.submit(np.arange(4, dtype=np.int32), 16)
                   for _ in range(24)]
        assert all(not isinstance(r, ShedCompletion)
                   for r in fillers)
        res = eng.submit(np.arange(8, dtype=np.int32), 8,
                         timeout=2.0)
        assert not isinstance(res, ShedCompletion), \
            f"admissible urgent request shed: {res.reason}"
        eng.run(max_steps=300)
