"""Ledger-backed serving invariants (ISSUE 15): zero steady-state
recompiles in the decode loop post-warm — pinned through the program
ledger, which records exactly the signature set that decides a jit
retrace — and the per-(prefix,suffix)-split verify-retrace budget
(docs/SERVING.md "The verify-retrace budget")."""

import numpy as np
import pytest

from chainermn_tpu.serving import ServingEngine
from chainermn_tpu.serving.sampling import SamplingParams
from chainermn_tpu.utils.programs import ProgramLedger, set_ledger


@pytest.fixture()
def ledger():
    led = ProgramLedger(enabled=True)
    prev = set_ledger(led)
    try:
        yield led
    finally:
        set_ledger(prev)


def _serve(eng, rng, n, max_new=(4, 12), sampled_every=0):
    for i in range(n):
        sp = None
        if sampled_every and i % sampled_every == 0:
            sp = SamplingParams(temperature=0.8, top_k=8, seed=i)
        eng.submit(rng.randint(1, 60, size=rng.randint(3, 14)),
                   max_new=rng.randint(*max_new), sampling=sp)
    out = []
    while not eng.idle:
        out.extend(eng.step())
    return out


class TestZeroSteadyStateRecompile:
    def test_decode_loop_post_warm(self, mini_adapter, mini_params,
                                   ledger):
        """The acceptance invariant: after a warmup pass has exercised
        every program the engine serves with (greedy + sampled rounds,
        prefill, admit, rebase via warm()), steady ragged traffic —
        different prompt lengths, budgets, sampling mixes, admissions
        mid-stream — compiles NOTHING.  The ledger proves it: its
        signature sets are exactly what decides a jit retrace, so
        steady_retraces == 0 IS the no-recompile property."""
        eng = ServingEngine(mini_adapter, mini_params, n_slots=8,
                            horizon=160, max_prompt=16, block=8,
                            round_tokens=4)
        eng.warm()
        rng = np.random.RandomState(0)
        # warmup covers BOTH round programs: a pure-greedy pass (the
        # all-greedy rounds run serve/round) then a sampled mix
        warm = _serve(eng, rng, 8)
        warm += _serve(eng, rng, 6, sampled_every=2)
        assert len(warm) == 14
        warm_compiles = ledger.compiles("serve/")
        assert warm_compiles >= 7     # init, pool, rebase, prefill,
        #                               admit, round, round_sampled
        stats = ledger.label_stats()
        assert "serve/round" in stats
        assert "serve/round_sampled" in stats

        eng.mark_steady()
        steady = _serve(eng, rng, 20, sampled_every=4)
        assert len(steady) == 20
        assert ledger.steady_retraces("serve/") == 0, \
            ledger.entries(scope="serve/")
        assert ledger.compiles("serve/") == warm_compiles

    def test_shape_leak_is_caught(self, mini_adapter, mini_params,
                                  ledger):
        """The invariant's teeth: a genuinely new program shape after
        mark_steady IS counted — the zero above is not vacuous."""
        eng = ServingEngine(mini_adapter, mini_params, n_slots=8,
                            horizon=160, max_prompt=16, block=8,
                            round_tokens=4)
        eng.warm()
        rng = np.random.RandomState(1)
        _serve(eng, rng, 6)
        eng.mark_steady()
        # the first SAMPLED request after warmup that never saw a
        # sampled round: serve/round_sampled must compile now
        _serve(eng, rng, 3, sampled_every=1)
        assert ledger.steady_retraces("serve/") >= 1
        entry = ledger.entries(scope="serve/round_sampled")[0]
        assert entry["steady"] is True and entry["diff"] is None


class TestVerifyRetraceBudget:
    def test_one_compile_per_prefix_suffix_split(self, mini_adapter,
                                                 mini_params, ledger):
        """The suffix-prefill program's shapes vary per (prefix,
        suffix) BLOCK split, so it retraces per distinct split — and
        only per distinct split: the ledger bounds the compile count
        by the split set, and a repeated split costs nothing (the
        SERVING.md verify-retrace budget)."""
        eng = ServingEngine(mini_adapter, mini_params, n_slots=8,
                            horizon=160, max_prompt=16, block=4,
                            round_tokens=4, prefix_sharing=True)
        eng.warm()
        system = np.arange(1, 9, dtype=np.int32)       # 2 full blocks
        splits = set()

        def submit_with_suffix(suffix_tokens):
            prompt = np.concatenate(
                [system, np.asarray(suffix_tokens, np.int32)])
            n_shared = min(len(system) // eng.block,
                           len(prompt) // eng.block)
            n_blocks = -(-len(prompt) // eng.block)
            if n_blocks > n_shared:
                splits.add((n_shared, n_blocks - n_shared))
            eng.submit(prompt, max_new=4)
            while not eng.idle:
                eng.step()

        submit_with_suffix([20, 21])            # split (2, 1)
        submit_with_suffix([22, 23, 24])        # split (2, 1) again
        before = ledger.compiles("serve/suffix_prefill")
        submit_with_suffix([25])                # (2, 1) third time
        assert ledger.compiles("serve/suffix_prefill") == before
        submit_with_suffix([26] * 6)            # split (2, 2): fresh
        stats = ledger.label_stats().get("serve/suffix_prefill")
        assert stats is not None, ledger.label_stats()
        assert stats["compiles"] <= len(splits)
        # the retrace attribution names the changing leaves as shapes
        entries = ledger.entries(scope="serve/suffix_prefill")
        diffs = [e["diff"] for e in entries if e["diff"] is not None]
        assert diffs and all(d["kinds"] == ["shape"] for d in diffs)

    def test_suffix_compile_exemplar_links_to_request(
            self, mini_adapter, mini_params, ledger):
        """The compile→trace link: a suffix-prefill compile caused by
        a traced request carries that request's trace id as its
        ledger exemplar (the /programz row points at the causal
        request, the compile/seconds exemplar resolves in its
        timeline)."""
        from chainermn_tpu.utils.telemetry import RequestTraceStore

        eng = ServingEngine(mini_adapter, mini_params, n_slots=8,
                            horizon=160, max_prompt=16, block=4,
                            round_tokens=4, prefix_sharing=True,
                            traces=RequestTraceStore(sample_rate=1.0))
        eng.warm()
        system = np.arange(1, 9, dtype=np.int32)       # 2 full blocks
        eng.submit(np.concatenate([system,
                                   np.asarray([30, 31], np.int32)]),
                   max_new=4, trace_id="cold-req")
        while not eng.idle:
            eng.step()
        eng.submit(np.concatenate([system,
                                   np.asarray([40, 41], np.int32)]),
                   max_new=4, trace_id="hit-req")
        while not eng.idle:
            eng.step()
        entries = ledger.entries(scope="serve/suffix_prefill")
        assert entries, ledger.label_stats()
        assert entries[-1]["exemplar"] in ("cold-req", "hit-req")
        # and the staging exemplar never leaks past the stage
        assert ledger.exemplar is None
