"""Ledger-backed serving invariants (ISSUE 15, re-pinned for ragged
rounds): zero steady-state recompiles in the decode loop post-warm —
pinned through the program ledger, which records exactly the
signature set that decides a jit retrace — and the chunk-prefill
ONE-compile budget across every chunk position and (prefix, suffix)
block split (docs/SERVING.md "The verify-retrace budget")."""

import numpy as np
import pytest

from chainermn_tpu.serving import (
    MiniLMAdapter,
    MiniLMConfig,
    ServingEngine,
    init_minilm,
)
from chainermn_tpu.serving.sampling import SamplingParams
from chainermn_tpu.utils.programs import ProgramLedger, set_ledger

import jax


@pytest.fixture()
def ledger():
    led = ProgramLedger(enabled=True)
    prev = set_ledger(led)
    try:
        yield led
    finally:
        set_ledger(prev)


@pytest.fixture(scope="module")
def draft_pair(mini_adapter):
    """A cheap draft sharing the target's MeshConfig INSTANCE (the
    engine validates mesh identity, not equality)."""
    cfg = MiniLMConfig(vocab_size=64, d_model=16, n_heads=2, d_head=8,
                       d_ff=32, n_layers=1, max_pos=256)
    params = init_minilm(jax.random.PRNGKey(7), cfg)
    return MiniLMAdapter(mini_adapter.mesh_cfg, cfg), params


def _serve(eng, rng, n, max_new=(4, 12), sampled_every=0):
    for i in range(n):
        sp = None
        if sampled_every and i % sampled_every == 0:
            sp = SamplingParams(temperature=0.8, top_k=8, seed=i)
        eng.submit(rng.randint(1, 60, size=rng.randint(3, 14)),
                   max_new=rng.randint(*max_new), sampling=sp)
    out = []
    while not eng.idle:
        out.extend(eng.step())
    return out


class TestZeroSteadyStateRecompile:
    def test_decode_loop_post_warm(self, mini_adapter, mini_params,
                                   ledger):
        """The acceptance invariant: after a warmup pass has exercised
        every program the engine serves with (greedy + sampled rounds,
        chunked prefill via warm(), admit), steady ragged traffic —
        different prompt lengths, budgets, chunk positions, sampling
        mixes, admissions mid-stream — compiles NOTHING.  The ledger
        proves it: its signature sets are exactly what decides a jit
        retrace, so steady_retraces == 0 IS the no-recompile
        property."""
        eng = ServingEngine(mini_adapter, mini_params, n_slots=8,
                            horizon=160, max_prompt=16, block=8,
                            round_tokens=4)
        eng.warm()
        rng = np.random.RandomState(0)
        # warmup covers BOTH round programs: a pure-greedy pass (the
        # all-greedy rounds run serve/round) then a sampled mix
        warm = _serve(eng, rng, 8)
        warm += _serve(eng, rng, 6, sampled_every=2)
        assert len(warm) == 14
        warm_compiles = ledger.compiles("serve/")
        assert warm_compiles >= 6     # init, pool, chunk_prefill,
        #                               admit, round, round_sampled
        stats = ledger.label_stats()
        assert "serve/round" in stats
        assert "serve/round_sampled" in stats
        assert "serve/chunk_prefill" in stats

        eng.mark_steady()
        steady = _serve(eng, rng, 20, sampled_every=4)
        assert len(steady) == 20
        assert ledger.steady_retraces("serve/") == 0, \
            ledger.entries(scope="serve/")
        assert ledger.compiles("serve/") == warm_compiles

    def test_spec_rounds_post_warm(self, mini_adapter, mini_params,
                                   draft_pair, ledger):
        """Speculation as a round mode obeys the same invariant: with
        a draft attached, warm() + one greedy pass compile the spec
        round and draft programs, and steady ragged greedy traffic
        compiles nothing further."""
        d_ad, d_params = draft_pair
        eng = ServingEngine(mini_adapter, mini_params, n_slots=8,
                            horizon=160, max_prompt=16, block=8,
                            round_tokens=4, draft_adapter=d_ad,
                            draft_params=d_params, spec_k=3)
        eng.warm()
        rng = np.random.RandomState(2)
        warm = _serve(eng, rng, 8)
        assert len(warm) == 8
        stats = ledger.label_stats()
        assert "serve/round_spec" in stats
        assert "serve/draft_prefill" in stats
        warm_compiles = ledger.compiles("serve/")

        eng.mark_steady()
        steady = _serve(eng, rng, 16)
        assert len(steady) == 16
        assert ledger.steady_retraces("serve/") == 0, \
            ledger.entries(scope="serve/")
        assert ledger.compiles("serve/") == warm_compiles
        assert eng.spec_drafted > 0    # spec rounds actually ran

    def test_shape_leak_is_caught(self, mini_adapter, mini_params,
                                  ledger):
        """The invariant's teeth: a genuinely new program shape after
        mark_steady IS counted — the zero above is not vacuous."""
        eng = ServingEngine(mini_adapter, mini_params, n_slots=8,
                            horizon=160, max_prompt=16, block=8,
                            round_tokens=4)
        eng.warm()
        rng = np.random.RandomState(1)
        _serve(eng, rng, 6)
        eng.mark_steady()
        # the first SAMPLED request after warmup that never saw a
        # sampled round: serve/round_sampled must compile now
        _serve(eng, rng, 3, sampled_every=1)
        assert ledger.steady_retraces("serve/") >= 1
        entry = ledger.entries(scope="serve/round_sampled")[0]
        assert entry["steady"] is True and entry["diff"] is None


class TestChunkPrefillBudget:
    def test_one_compile_for_all_splits(self, mini_adapter,
                                        mini_params, ledger):
        """The chunk-prefill program takes FIXED operand shapes (the
        start position is a traced scalar), so ONE compile — paid at
        warm() — covers every chunk of every prompt at every (prefix,
        suffix) block split.  The per-split retrace budget the old
        suffix-prefill program paid is gone."""
        eng = ServingEngine(mini_adapter, mini_params, n_slots=8,
                            horizon=160, max_prompt=16, block=4,
                            round_tokens=4, prefix_sharing=True)
        eng.warm()
        after_warm = ledger.compiles("serve/chunk_prefill")
        assert after_warm == 1, ledger.label_stats()
        system = np.arange(1, 9, dtype=np.int32)       # 2 full blocks

        def submit_with_suffix(suffix_tokens):
            prompt = np.concatenate(
                [system, np.asarray(suffix_tokens, np.int32)])
            eng.submit(prompt, max_new=4)
            while not eng.idle:
                eng.step()

        submit_with_suffix([20, 21])            # split (2, 1)
        submit_with_suffix([22, 23, 24])        # split (2, 1) again
        submit_with_suffix([25])                # (2, 1) third time
        submit_with_suffix([26] * 6)            # split (2, 2): fresh
        submit_with_suffix(np.arange(30, 38))   # no shared prefix
        assert ledger.compiles("serve/chunk_prefill") == after_warm, \
            ledger.entries(scope="serve/chunk_prefill")
        assert eng.stats()["prefix_hit_rate"] > 0

    def test_chunk_compile_exemplar_links_to_request(
            self, mini_adapter, mini_params, ledger):
        """The compile→trace link: a chunk-prefill compile caused by a
        traced request (no warm() here, so the FIRST staging pays it)
        carries that request's trace id as its ledger exemplar (the
        /programz row points at the causal request)."""
        from chainermn_tpu.utils.telemetry import RequestTraceStore

        eng = ServingEngine(mini_adapter, mini_params, n_slots=8,
                            horizon=160, max_prompt=16, block=4,
                            round_tokens=4, prefix_sharing=True,
                            traces=RequestTraceStore(sample_rate=1.0))
        eng.submit(np.arange(1, 11, dtype=np.int32), max_new=4,
                   trace_id="cold-req")
        while not eng.idle:
            eng.step()
        eng.submit(np.arange(40, 46, dtype=np.int32), max_new=4,
                   trace_id="hit-req")
        while not eng.idle:
            eng.step()
        entries = ledger.entries(scope="serve/chunk_prefill")
        assert entries, ledger.label_stats()
        assert entries[-1]["exemplar"] == "cold-req"
        # and the staging exemplar never leaks past the stage
        assert ledger.exemplar is None
