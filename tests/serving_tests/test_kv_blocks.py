"""Block-paged KV cache primitives: free-list allocator bookkeeping and
the scatter/gather/insert/shift device ops the engine's programs are
built from (exercised here on plain arrays — the ops are pure jnp, the
same code path shard_map traces)."""

import jax.numpy as jnp
import numpy as np
import pytest

from chainermn_tpu.serving import kv_blocks as kvb


class TestAllocator:
    def test_alloc_free_roundtrip(self):
        a = kvb.BlockAllocator(8, 4)
        assert a.n_free == 8 and a.utilization == 0.0
        ids = a.alloc("r0", 3)
        assert len(ids) == 3 and len(set(ids)) == 3
        assert a.n_free == 5 and a.table("r0") == ids
        assert a.utilization == pytest.approx(3 / 8)
        assert a.free_row("r0") == 3
        assert a.n_free == 8
        # idempotent: unknown rows free nothing
        assert a.free_row("r0") == 0

    def test_all_or_nothing(self):
        a = kvb.BlockAllocator(4, 2)
        assert a.alloc("big", 5) is None
        assert a.n_free == 4            # nothing taken
        a.alloc("r0", 3)
        assert a.alloc("r1", 2) is None
        assert a.n_free == 1

    def test_no_double_ownership(self):
        a = kvb.BlockAllocator(6, 2)
        i0 = a.alloc("r0", 2)
        i1 = a.alloc("r1", 4)
        assert not set(i0) & set(i1)
        with pytest.raises(ValueError):
            a.alloc("r0", 1)

    def test_padded_table_right_aligned(self):
        a = kvb.BlockAllocator(8, 4)
        ids = a.alloc("r", 2)
        t = a.padded_table("r", 5)
        assert t.dtype == np.int32 and t.shape == (5,)
        assert list(t[:3]) == [-1, -1, -1]
        assert list(t[3:]) == ids
        with pytest.raises(ValueError):
            a.padded_table("r", 1)

    def test_blocks_needed(self):
        assert kvb.blocks_needed(0, 4) == 0
        assert kvb.blocks_needed(1, 4) == 1
        assert kvb.blocks_needed(4, 4) == 1
        assert kvb.blocks_needed(5, 4) == 2
        with pytest.raises(ValueError):
            kvb.blocks_needed(-1, 4)

    def test_validation(self):
        with pytest.raises(ValueError):
            kvb.BlockAllocator(0, 4)
        a = kvb.BlockAllocator(2, 2)
        with pytest.raises(ValueError):
            a.alloc("r", -1)


def _chunk(pq=8, layers=2, rest=(3,), seed=0):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randn(layers, 1, pq, *rest).astype(np.float32))


class TestDeviceOps:
    def test_chunk_blocks_scatter_gather_roundtrip(self):
        block, nb = 4, 6
        chunk = _chunk(pq=8)
        pool = jnp.zeros((2, nb, block, 3), jnp.float32)
        blocks = kvb.chunk_to_blocks(chunk, block)
        assert blocks.shape == (2, 2, block, 3)
        ids = jnp.asarray([5, 2], jnp.int32)
        valid = jnp.asarray([True, True])
        pool = kvb.scatter_chunk(pool, blocks, ids, valid)
        out = kvb.gather_blocks(pool, ids)
        np.testing.assert_array_equal(out, chunk)
        # physical placement really is scattered
        np.testing.assert_array_equal(pool[:, 5], chunk[:, 0, :4])
        np.testing.assert_array_equal(pool[:, 2], chunk[:, 0, 4:])

    def test_scatter_invalid_ids_are_noops(self):
        block = 4
        chunk = _chunk(pq=8)
        pool0 = jnp.asarray(
            np.random.RandomState(1).randn(2, 4, block, 3), jnp.float32)
        blocks = kvb.chunk_to_blocks(chunk, block)
        ids = jnp.asarray([-1, 3], jnp.int32)
        pool = kvb.scatter_chunk(pool0, blocks, ids,
                                 jnp.asarray([False, True]))
        # the invalid entry must leave every block untouched
        np.testing.assert_array_equal(pool[:, 0], pool0[:, 0])
        np.testing.assert_array_equal(pool[:, 3], chunk[:, 0, 4:])

    def test_scatter_invalid_entry_never_collides_with_block_zero(self):
        # the allocator legitimately hands out block 0; a pad entry
        # sharing an index with it (via clamping) would make the
        # winner backend-defined — invalid entries must be dropped,
        # not clamped, so the real write always lands
        block = 4
        chunk = _chunk(pq=8, seed=3)
        pool0 = jnp.asarray(
            np.random.RandomState(4).randn(2, 4, block, 3), jnp.float32)
        blocks = kvb.chunk_to_blocks(chunk, block)
        ids = jnp.asarray([-1, 0], jnp.int32)       # pad + REAL block 0
        pool = kvb.scatter_chunk(pool0, blocks, ids,
                                 jnp.asarray([False, True]))
        np.testing.assert_array_equal(pool[:, 0], chunk[:, 0, 4:])
        np.testing.assert_array_equal(pool[:, 1:], pool0[:, 1:])

    def test_chunk_to_blocks_validation(self):
        with pytest.raises(ValueError):
            kvb.chunk_to_blocks(jnp.zeros((2, 2, 8, 3)), 4)  # 2 rows
        with pytest.raises(ValueError):
            kvb.chunk_to_blocks(jnp.zeros((2, 1, 7, 3)), 4)  # 7 % 4

    def test_insert_chunk_masked(self):
        cache = jnp.zeros((2, 4, 16, 3), jnp.float32)
        chunk = _chunk(pq=8, seed=2)
        out = kvb.insert_chunk(cache, chunk, jnp.int32(1), jnp.int32(5),
                               jnp.asarray(True))
        np.testing.assert_array_equal(out[:, 1, 5:13], chunk[:, 0])
        assert float(jnp.abs(out[:, 0]).sum()) == 0.0
        # masked write (the non-owning shard's path) changes nothing
        out2 = kvb.insert_chunk(cache, chunk, jnp.int32(1), jnp.int32(5),
                                jnp.asarray(False))
        np.testing.assert_array_equal(out2, cache)

    def test_shift_positions(self):
        comp = jnp.asarray(
            np.arange(2 * 3 * 8 * 1).reshape(2, 3, 8, 1), jnp.float32)
        out = kvb.shift_positions(comp, jnp.int32(3))
        np.testing.assert_array_equal(out[:, :, :5], comp[:, :, 3:])
        # clamped tail repeats the last position
        np.testing.assert_array_equal(out[:, :, 5:],
                                      jnp.repeat(comp[:, :, 7:], 3, 2))
