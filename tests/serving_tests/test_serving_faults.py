"""Serving-tier fault drills: each injected fault (slow decode round,
decode-round exception, pool exhaustion) must complete its drill with
the engine still serving the remaining slots and the failure visible
in metrics — no hang, no crash (docs/RESILIENCE.md, serving rows).

Faults are scripted through ``FaultPlan``'s serving actions and
applied by ``FaultInjector.attach_engine`` — host-side wrappers over
the round/staging dispatch, the same deterministic-injection
discipline as the training drills.  Engines are WARMED before a drill
(first-use compiles take seconds and would eat any realistic
deadline budget)."""

import numpy as np
import pytest

from chainermn_tpu.serving import ServingEngine, ShedCompletion
from chainermn_tpu.testing import FaultInjector, FaultPlan
from chainermn_tpu.utils.metrics import MetricsRegistry, set_registry


@pytest.fixture()
def registry():
    reg = MetricsRegistry(enabled=True)
    prev = set_registry(reg)
    yield reg
    set_registry(prev)


def _warmed_engine(mini_adapter, mini_params, **kw):
    eng = ServingEngine(mini_adapter, mini_params, n_slots=8,
                        horizon=160, max_prompt=16, block=8,
                        round_tokens=4, **kw)
    rng = np.random.RandomState(99)
    for _ in range(2):
        eng.submit(rng.randint(0, 64, 8), max_new=4)
    eng.run(max_steps=200)
    eng.warm()
    eng.reset()
    return eng


def _ragged_submit(eng, rng, n, max_new=10, **kw):
    return [eng.submit(rng.randint(0, 64, rng.randint(2, 16)),
                       max_new=max_new, **kw) for _ in range(n)]


class TestRoundFailure:
    def test_raise_quarantines_newest_and_keeps_serving(
            self, mini_adapter, mini_params, oracle, registry):
        eng = _warmed_engine(mini_adapter, mini_params)
        inj = FaultInjector(FaultPlan(serve_raise_at_round=1))
        inj.attach_engine(eng)
        rng = np.random.RandomState(0)
        trace = [(rng.randint(0, 64, rng.randint(2, 16)),
                  int(rng.randint(6, 12))) for _ in range(6)]
        rids = [(eng.submit(p, max_new=n), p, n) for p, n in trace]
        comps = eng.run(max_steps=500)       # no hang, no crash
        assert ("serve_raise", 1) in inj.fired
        by = {c.rid: c for c in comps}
        statuses = sorted(c.status for c in comps)
        assert statuses == ["ok"] * 5 + ["quarantined"]
        # the quarantined row is the NEWEST admission of the failed
        # round's batch, and its record names the injected error
        bad = [c for c in comps if c.status == "quarantined"][0]
        assert "injected decode-round failure" in bad.detail
        # everyone else still got their exact solo tokens
        for rid, p, n in rids:
            if by[rid].status == "ok":
                np.testing.assert_array_equal(by[rid].tokens,
                                              oracle(p, n))
        assert eng.n_quarantined == 1
        snap = eng.metrics_snapshot()
        assert snap["serve/quarantined"]["value"] == 1
        assert snap["serve/round_failures"]["value"] == 1

    def test_persistent_failure_drains_without_hanging(
            self, mini_adapter, mini_params):
        """Every round raising is the worst case: the engine must
        degrade one quarantine per step until empty — never hang,
        never crash."""
        eng = _warmed_engine(mini_adapter, mini_params)

        real = eng._round_fn

        def always_fail(*a, **k):
            raise RuntimeError("persistent adapter fault")

        eng._round_fn = always_fail
        rng = np.random.RandomState(1)
        _ragged_submit(eng, rng, 4)
        comps = eng.run(max_steps=200)
        assert sorted(c.status for c in comps) == ["quarantined"] * 4
        assert eng.idle
        eng._round_fn = real
        # the engine still serves after the fault clears
        _ragged_submit(eng, rng, 2, max_new=4)
        comps = eng.run(max_steps=200)
        assert [c.status for c in comps] == ["ok", "ok"]


class TestSlowRound:
    def test_delay_turns_into_timeouts_not_hang(
            self, mini_adapter, mini_params, registry):
        eng = _warmed_engine(mini_adapter, mini_params)
        inj = FaultInjector(FaultPlan(serve_delay_at_round=1,
                                      serve_delay_seconds=0.4))
        inj.attach_engine(eng)
        rng = np.random.RandomState(2)
        # generous for the warmed round cadence, fatal under the stall
        _ragged_submit(eng, rng, 8, max_new=12, timeout=0.3)
        comps = eng.run(max_steps=500)
        assert ("serve_delay", 1) in inj.fired
        assert len(comps) == 8
        timeouts = [c for c in comps
                    if getattr(c, "status", "") == "timeout"]
        assert timeouts                     # the stall is VISIBLE
        assert eng.stats()["timeouts"] == len(timeouts)
        snap = eng.metrics_snapshot()
        assert snap["serve/timeouts"]["value"] == len(timeouts)
        # and the engine is healthy afterwards
        eng.submit(rng.randint(0, 64, 8), max_new=4)
        assert [c.status for c in eng.run(max_steps=200)] == ["ok"]


class TestPoolExhaustion:
    def test_exhaustion_backpressures_then_recovers(
            self, mini_adapter, mini_params, oracle, registry):
        eng = _warmed_engine(mini_adapter, mini_params,
                             prefill_ahead=0)
        inj = FaultInjector(FaultPlan(serve_exhaust_pool_at_admit=8,
                                      serve_exhaust_pool_rounds=3))
        inj.attach_engine(eng)
        rng = np.random.RandomState(3)
        trace = [(rng.randint(0, 64, rng.randint(2, 16)), 8)
                 for _ in range(16)]
        rids = [(eng.submit(p, max_new=n), p, n) for p, n in trace]
        comps = eng.run(max_steps=2000)
        kinds = [k for k, *_ in inj.fired]
        assert "serve_pool_exhaust" in kinds
        assert "serve_pool_release" in kinds     # recovery half
        # nothing lost, nothing corrupted: every request served
        # exactly once the pool came back
        assert len(comps) == 16
        by = {c.rid: c for c in comps}
        for rid, p, n in rids:
            assert by[rid].status == "ok"
            np.testing.assert_array_equal(by[rid].tokens, oracle(p, n))

    def test_exhaustion_with_deadlines_sheds_fast(
            self, mini_adapter, mini_params, registry):
        """With deadlines attached, a held pool converts queued work
        into timely ``timeout`` sheds instead of unbounded aging —
        and the already-admitted rows keep serving throughout."""
        eng = _warmed_engine(mini_adapter, mini_params,
                             prefill_ahead=0)
        # hold the pool effectively forever: the drill's point is
        # that deadlines bound the damage WITHOUT the pool coming back
        inj = FaultInjector(FaultPlan(serve_exhaust_pool_at_admit=8,
                                      serve_exhaust_pool_rounds=10**9))
        inj.attach_engine(eng)
        rng = np.random.RandomState(4)
        first = _ragged_submit(eng, rng, 8, max_new=12, timeout=30.0)
        for _ in range(2):
            eng.step()                   # all 8 admitted and decoding
        starved = _ragged_submit(eng, rng, 4, max_new=8, timeout=0.1)
        # the starved queue spins cheap host-only steps until the
        # deadlines expire — give the step budget real headroom
        comps = eng.run(max_steps=100_000)
        by = {c.rid: c for c in comps}
        # admitted rows finished OK while the pool was held
        assert all(by[r].status == "ok" for r in first)
        # starved rows shed as timeouts, queue drained, no hang
        assert all(isinstance(by[r], ShedCompletion)
                   and by[r].reason == "timeout" for r in starved)
        assert eng.idle
        snap = eng.metrics_snapshot()
        assert snap["serve/shed_timeout"]["value"] == 4


class TestStageFailure:
    def test_poison_prompt_quarantined_queue_flows(
            self, mini_adapter, mini_params, oracle, registry):
        """A prefill failure is attributable to ONE request: it is
        shed ``quarantined`` and the rest of the queue is admitted
        normally."""
        eng = _warmed_engine(mini_adapter, mini_params,
                             prefill_ahead=0)
        rng = np.random.RandomState(5)
        poison_rid = {}

        real_stage = eng._stage

        def stage_wrapper(req, rec, steal, idle=True):
            if req.rid == poison_rid.get("rid"):
                raise RuntimeError("injected prefill failure")
            return real_stage(req, rec, steal, idle=idle)

        eng._stage = stage_wrapper
        trace = [(rng.randint(0, 64, rng.randint(2, 16)), 6)
                 for _ in range(10)]
        rids = [(eng.submit(p, max_new=n), p, n) for p, n in trace]
        poison_rid["rid"] = rids[3][0]
        comps = eng.run(max_steps=1000)
        by = {c.rid: c for c in comps}
        bad = by[rids[3][0]]
        assert isinstance(bad, ShedCompletion)
        assert bad.reason == "quarantined" and "prefill" in bad.detail
        for rid, p, n in rids:
            if rid != rids[3][0]:
                assert by[rid].status == "ok"
                np.testing.assert_array_equal(by[rid].tokens,
                                              oracle(p, n))
        # queue-side termination: counted in the shed taxonomy ONLY
        # (serve/quarantined covers mid-stream evictions — disjoint)
        assert eng.stats()["shed"]["quarantined"] == 1
        assert eng.n_quarantined == 0
        snap = eng.metrics_snapshot()
        assert "serve/quarantined" not in snap
        assert snap["serve/shed_quarantined"]["value"] == 1
        assert snap["serve/shed_total"]["value"] == 1

    def test_fault_plan_serving_fields_round_trip(self):
        plan = FaultPlan(serve_delay_at_round=3,
                         serve_delay_seconds=0.5,
                         serve_raise_at_round=7,
                         serve_exhaust_pool_at_admit=2,
                         serve_exhaust_pool_rounds=9)
        assert FaultPlan.from_json(plan.to_json()) == plan
