"""Serving metrics & SLO surface: the engine's request records carry
the derived latency fields, the ``serve/*`` registry wiring records at
the points that hold the timestamps, and ``SLOReport`` percentiles
over a run reproduce raw numpy within rounding (the equivalence
``bench_serving``'s dedup leans on)."""

import numpy as np
import pytest

from chainermn_tpu.serving import ServingEngine, SLOReport
from chainermn_tpu.utils.metrics import (
    Histogram,
    MetricsRegistry,
    set_registry,
)


@pytest.fixture(scope="module")
def engine(mini_adapter, mini_params):
    return ServingEngine(mini_adapter, mini_params, n_slots=8,
                         horizon=160, max_prompt=16, block=8,
                         round_tokens=4)


@pytest.fixture()
def registry():
    reg = MetricsRegistry(enabled=True)
    prev = set_registry(reg)
    yield reg
    set_registry(prev)


def _run_trace(engine, rng, n=12):
    engine.reset()
    for _ in range(n):
        prompt = rng.randint(0, 64, rng.randint(2, 12))
        engine.submit(prompt, max_new=int(rng.randint(4, 16)))
    comps = engine.run(max_steps=2000)
    assert len(comps) == n
    return comps


class TestRequestRecords:
    def test_records_expose_derived_fields(self, engine):
        comps = _run_trace(engine, np.random.RandomState(0))
        recs = engine.request_records()
        assert [r.rid for r in recs] == [c.rid for c in comps]
        for r in recs:
            assert r.queue_wait == r.t_admit - r.t_submit >= 0
            assert r.ttft == r.t_first - r.t_submit > 0
            assert r.e2e == r.t_done - r.t_submit >= r.ttft
            assert r.tpot == (r.t_done - r.t_first) \
                / max(r.n_generated - 1, 1) >= 0

    def test_reset_clears_records(self, engine):
        _run_trace(engine, np.random.RandomState(1), n=4)
        assert len(engine.request_records()) == 4
        engine.reset()
        assert engine.request_records() == []

    def test_record_history_bounded(self, mini_adapter, mini_params):
        """A long-running server must not grow the completion list
        without bound: the ring keeps the newest record_history."""
        eng = ServingEngine(mini_adapter, mini_params, n_slots=8,
                            horizon=160, max_prompt=16, block=8,
                            round_tokens=4, record_history=5)
        comps = _run_trace(eng, np.random.RandomState(6), n=8)
        recs = eng.request_records()
        assert len(recs) == 5
        assert [r.rid for r in recs] == [c.rid for c in comps[-5:]]


class TestRegistryWiring:
    def test_serve_metrics_recorded_at_lifecycle_points(self, engine,
                                                        registry):
        n = 10
        _run_trace(engine, np.random.RandomState(2), n=n)
        snap = engine.metrics_snapshot()
        assert snap["serve/submitted"]["value"] == n
        assert snap["serve/admits"]["value"] == n
        assert snap["serve/evictions"]["value"] == n
        for name in ("serve/queue_wait", "serve/ttft", "serve/tpot",
                     "serve/e2e"):
            assert snap[name]["type"] == "histogram"
            assert snap[name]["count"] == n, name
        # histograms hold the SAME numbers the request records derive
        recs = engine.request_records()
        h = Histogram.from_snapshot(snap["serve/ttft"])
        assert h.percentile(50) == pytest.approx(
            float(np.percentile([r.ttft for r in recs], 50)))
        assert snap["serve/generated_tokens"]["value"] \
            == sum(r.n_generated for r in recs)
        # queue depth gauge saw the initial burst
        assert snap["serve/queue_depth"]["max"] >= 1

    def test_disabled_registry_records_nothing_but_records_live(
            self, engine):
        comps = _run_trace(engine, np.random.RandomState(3), n=4)
        assert engine.metrics_snapshot() == {}
        assert len(engine.request_records()) == len(comps) == 4


class TestSLOReport:
    def test_percentiles_reproduce_numpy(self, engine):
        comps = _run_trace(engine, np.random.RandomState(4), n=16)
        slo = SLOReport(percentiles=(50, 95, 99))
        slo.add_arm("run", engine.request_records())
        s = slo.summary()["run"]
        for field in ("queue_wait", "ttft", "tpot", "e2e"):
            vals = [getattr(c, field) for c in comps]
            assert s[field]["count"] == len(vals)
            for q in (50, 95, 99):
                assert s[field][f"p{q}"] == pytest.approx(
                    float(np.percentile(vals, q)), rel=1e-9), \
                    (field, q)

    def test_multi_arm_render_and_json(self, engine, tmp_path):
        slo = SLOReport(percentiles=(50, 99))
        _run_trace(engine, np.random.RandomState(5), n=6)
        slo.add_arm("continuous", engine.request_records())
        engine.gang = True
        try:
            _run_trace(engine, np.random.RandomState(5), n=6)
        finally:
            engine.gang = False
        slo.add_arm("static", engine.request_records())
        assert slo.arms == ("continuous", "static")
        table = slo.render()
        for token in ("continuous", "static", "ttft", "p99_ms"):
            assert token in table
        import json

        path = slo.write_json(str(tmp_path / "slo.json"))
        doc = json.load(open(path))
        assert set(doc["arms"]) == {"continuous", "static"}
        assert doc["arms"]["static"]["ttft"]["count"] == 6
        # gang mode queues harder: its mean queue wait is no better
        cont = slo.summary()["continuous"]["queue_wait"]["mean"]
        stat = slo.summary()["static"]["queue_wait"]["mean"]
        assert stat >= cont * 0.5   # sanity, not a perf claim

    def test_dict_records_accepted(self):
        slo = SLOReport(percentiles=(50,))
        slo.add_arm("a", [{"queue_wait": 0.1, "ttft": 0.2,
                           "tpot": 0.01, "e2e": 0.5}])
        assert slo.summary()["a"]["e2e"]["p50"] == pytest.approx(0.5)
