"""Serving metrics & SLO surface: the engine's request records carry
the derived latency fields, the ``serve/*`` registry wiring records at
the points that hold the timestamps, and ``SLOReport`` percentiles
over a run reproduce raw numpy within rounding (the equivalence
``bench_serving``'s dedup leans on)."""

import numpy as np
import pytest

from chainermn_tpu.serving import ServingEngine, SLOReport
from chainermn_tpu.utils.metrics import (
    Histogram,
    MetricsRegistry,
    set_registry,
)


@pytest.fixture(scope="module")
def engine(mini_adapter, mini_params):
    return ServingEngine(mini_adapter, mini_params, n_slots=8,
                         horizon=160, max_prompt=16, block=8,
                         round_tokens=4)


@pytest.fixture()
def registry():
    reg = MetricsRegistry(enabled=True)
    prev = set_registry(reg)
    yield reg
    set_registry(prev)


def _run_trace(engine, rng, n=12):
    engine.reset()
    for _ in range(n):
        prompt = rng.randint(0, 64, rng.randint(2, 12))
        engine.submit(prompt, max_new=int(rng.randint(4, 16)))
    comps = engine.run(max_steps=2000)
    assert len(comps) == n
    return comps


class TestRequestRecords:
    def test_records_expose_derived_fields(self, engine):
        comps = _run_trace(engine, np.random.RandomState(0))
        recs = engine.request_records()
        assert [r.rid for r in recs] == [c.rid for c in comps]
        for r in recs:
            assert r.queue_wait == r.t_admit - r.t_submit >= 0
            assert r.ttft == r.t_first - r.t_submit > 0
            assert r.e2e == r.t_done - r.t_submit >= r.ttft
            assert r.tpot == (r.t_done - r.t_first) \
                / max(r.n_generated - 1, 1) >= 0

    def test_reset_clears_records(self, engine):
        _run_trace(engine, np.random.RandomState(1), n=4)
        assert len(engine.request_records()) == 4
        engine.reset()
        assert engine.request_records() == []

    def test_record_history_bounded(self, mini_adapter, mini_params):
        """A long-running server must not grow the completion list
        without bound: the ring keeps the newest record_history."""
        eng = ServingEngine(mini_adapter, mini_params, n_slots=8,
                            horizon=160, max_prompt=16, block=8,
                            round_tokens=4, record_history=5)
        comps = _run_trace(eng, np.random.RandomState(6), n=8)
        recs = eng.request_records()
        assert len(recs) == 5
        assert [r.rid for r in recs] == [c.rid for c in comps[-5:]]


class TestRegistryWiring:
    def test_serve_metrics_recorded_at_lifecycle_points(self, engine,
                                                        registry):
        n = 10
        _run_trace(engine, np.random.RandomState(2), n=n)
        snap = engine.metrics_snapshot()
        assert snap["serve/submitted"]["value"] == n
        assert snap["serve/admits"]["value"] == n
        assert snap["serve/evictions"]["value"] == n
        for name in ("serve/queue_wait", "serve/ttft", "serve/tpot",
                     "serve/e2e"):
            assert snap[name]["type"] == "histogram"
            assert snap[name]["count"] == n, name
        # histograms hold the SAME numbers the request records derive
        recs = engine.request_records()
        h = Histogram.from_snapshot(snap["serve/ttft"])
        assert h.percentile(50) == pytest.approx(
            float(np.percentile([r.ttft for r in recs], 50)))
        assert snap["serve/generated_tokens"]["value"] \
            == sum(r.n_generated for r in recs)
        # queue depth gauge saw the initial burst
        assert snap["serve/queue_depth"]["max"] >= 1

    def test_disabled_registry_records_nothing_but_records_live(
            self, engine):
        comps = _run_trace(engine, np.random.RandomState(3), n=4)
        assert engine.metrics_snapshot() == {}
        assert len(engine.request_records()) == len(comps) == 4


class TestSLOReport:
    def test_percentiles_reproduce_numpy(self, engine):
        comps = _run_trace(engine, np.random.RandomState(4), n=16)
        slo = SLOReport(percentiles=(50, 95, 99))
        slo.add_arm("run", engine.request_records())
        s = slo.summary()["run"]
        for field in ("queue_wait", "ttft", "tpot", "e2e"):
            vals = [getattr(c, field) for c in comps]
            assert s[field]["count"] == len(vals)
            for q in (50, 95, 99):
                assert s[field][f"p{q}"] == pytest.approx(
                    float(np.percentile(vals, q)), rel=1e-9), \
                    (field, q)

    def test_multi_arm_render_and_json(self, engine, tmp_path):
        slo = SLOReport(percentiles=(50, 99))
        _run_trace(engine, np.random.RandomState(5), n=6)
        slo.add_arm("continuous", engine.request_records())
        engine.gang = True
        try:
            _run_trace(engine, np.random.RandomState(5), n=6)
        finally:
            engine.gang = False
        slo.add_arm("static", engine.request_records())
        assert slo.arms == ("continuous", "static")
        table = slo.render()
        for token in ("continuous", "static", "ttft", "p99_ms"):
            assert token in table
        import json

        path = slo.write_json(str(tmp_path / "slo.json"))
        doc = json.load(open(path))
        assert set(doc["arms"]) == {"continuous", "static"}
        assert doc["arms"]["static"]["ttft"]["count"] == 6
        # gang mode queues harder: its typical queue wait is no
        # better.  MEDIANS, not means — one loaded-host scheduling
        # burst against a single continuous-arm request skews a
        # 6-sample mean past any margin (observed in CI)
        cont = slo.summary()["continuous"]["queue_wait"]["p50"]
        stat = slo.summary()["static"]["queue_wait"]["p50"]
        assert stat >= cont * 0.5   # sanity, not a perf claim

    def test_dict_records_accepted(self):
        slo = SLOReport(percentiles=(50,))
        slo.add_arm("a", [{"queue_wait": 0.1, "ttft": 0.2,
                           "tpot": 0.01, "e2e": 0.5}])
        assert slo.summary()["a"]["e2e"]["p50"] == pytest.approx(0.5)


class TestSLOSkipsAndAttainment:
    """Shed and timed-out records have no TTFT (or none of the latency
    fields at all): the report must skip-count them per arm — never
    observe a None — and the SLO column must score goodput from
    fully-served records only."""

    def test_none_and_missing_fields_skip_counted(self):
        from chainermn_tpu.serving import ShedCompletion

        served = [{"queue_wait": 0.01, "ttft": 0.1 * (i + 1),
                   "tpot": 0.01, "e2e": 0.2 * (i + 1)}
                  for i in range(4)]
        timed_out = {"queue_wait": 0.01, "ttft": None, "tpot": None,
                     "e2e": 0.9, "status": "timeout"}
        shed = ShedCompletion("s0", np.zeros(2, np.int32),
                              "queue_full", 0.0, 0.1)
        slo = SLOReport(percentiles=(50,))
        slo.add_arm("mix", served + [timed_out, shed])
        s = slo.summary()["mix"]
        # percentiles over the PRESENT values only, numpy-identical
        assert s["ttft"]["count"] == 4
        assert s["ttft"]["p50"] == pytest.approx(float(np.percentile(
            [r["ttft"] for r in served], 50)))
        assert s["e2e"]["count"] == 5       # timeout rows have e2e
        # the skips are REPORTED, per field
        assert s["skipped"] == {"queue_wait": 1, "ttft": 2,
                                "tpot": 2, "e2e": 1}
        assert slo.skipped("mix")["ttft"] == 2

    def test_partial_completion_properties_skip_not_raise(self):
        """An engine Completion evicted before its first token has
        t_admit/t_first None — its derived properties must read as
        None (skipped), not raise out of the report."""
        from chainermn_tpu.serving import Completion

        c = Completion(rid="r", prompt=np.zeros(2, np.int32),
                       tokens=np.zeros(0, np.int32), t_submit=1.0,
                       t_admit=None, t_first=None, t_done=2.0,
                       slot=0, status="timeout")
        assert c.queue_wait is None and c.ttft is None \
            and c.tpot is None
        assert c.e2e == pytest.approx(1.0)
        slo = SLOReport(percentiles=(50,))
        slo.add_arm("a", [c])
        assert slo.summary()["a"]["skipped"]["ttft"] == 1

    def test_attainment_and_goodput_scalar_target(self):
        recs = [
            {"e2e": 0.2, "n_generated": 10},                  # attains
            {"e2e": 0.9, "n_generated": 10},                  # late
            {"e2e": 0.1, "n_generated": 7,
             "status": "timeout"},                            # not ok
            {"e2e": None, "n_generated": 0, "status": "shed"},
        ]
        slo = SLOReport(percentiles=(50,))
        slo.add_arm("arm", recs, slo=0.5)
        s = slo.summary()["arm"]["slo"]
        assert s["scored"] == 4 and s["attained"] == 1
        assert s["attainment"] == pytest.approx(0.25)
        assert s["goodput_tokens"] == 10
        assert s["shed"] == 1
        assert "attained" in slo.render() and "goodput" in slo.render()

    def test_attainment_callable_target_with_exemption(self):
        recs = [{"rid": "a", "e2e": 0.2, "n_generated": 5},
                {"rid": "b", "e2e": 0.2, "n_generated": 5},
                {"rid": "c", "e2e": 0.2, "n_generated": 5}]
        targets = {"a": 0.5, "b": 0.1, "c": None}   # c exempt
        slo = SLOReport(percentiles=(50,))
        slo.add_arm("arm", recs, slo=lambda r: targets[r["rid"]])
        s = slo.summary()["arm"]["slo"]
        assert s["scored"] == 2 and s["attained"] == 1
        assert s["goodput_tokens"] == 5

    def test_unscored_batch_leaves_scored_arm_consistent(self):
        """Accumulating a batch WITHOUT slo= into a previously scored
        arm folds its latencies in but leaves the slo block untouched
        — attainment and shed counts must cover one population."""
        slo = SLOReport(percentiles=(50,))
        slo.add_arm("a", [{"e2e": 0.2, "n_generated": 3}], slo=0.5)
        before = dict(slo.summary()["a"]["slo"])
        slo.add_arm("a", [{"e2e": 0.4, "n_generated": 9},
                          {"e2e": None, "status": "shed"}])
        after = slo.summary()["a"]
        assert after["slo"] == before
        assert after["e2e"]["count"] == 2       # latencies DID fold in

    def test_unscored_arm_has_no_slo_block(self):
        slo = SLOReport(percentiles=(50,))
        slo.add_arm("a", [{"e2e": 0.1}])
        assert "slo" not in slo.summary()["a"]
        # json round-trips with the new blocks
        doc = slo.to_dict()
        assert doc["arms"]["a"]["skipped"]["ttft"] == 1
