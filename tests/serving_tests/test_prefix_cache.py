"""Copy-on-write prefix sharing: refcount lifecycle at the unit level
(no jax) and the engine-level exactness ladder — greedy decode with
sharing ON is token-identical to the engine-independent solo oracle
AND to the sharing-OFF engine, while prefill compute and row-held pool
pressure actually drop for shared-prefix traffic."""

import numpy as np
import pytest

from chainermn_tpu.serving import ServingEngine
from chainermn_tpu.serving.prefix_cache import RefcountedBlockPool
from chainermn_tpu.utils.telemetry import get_recorder


def _tok(*ids):
    return np.asarray(ids, np.int32)


class TestRefcountLifecycle:
    def test_cold_stage_then_hit(self):
        pool = RefcountedBlockPool(16, 4)
        t = _tok(*range(10))            # 2 full blocks + 1 partial
        plan = pool.stage("a", t)
        assert plan.n_shared == 0 and plan.n_new == 3
        assert pool.insert_cached("a", t) == 2     # partials never cache
        plan_b = pool.stage("b", t)
        assert plan_b.n_shared == 2 and plan_b.n_new == 1
        # the shared blocks are the SAME physical ids
        assert pool.table("b")[:2] == pool.table("a")[:2]
        assert pool.table("b")[2] != pool.table("a")[2]
        assert pool.n_hits == 2 and pool.n_prefilled == 4

    def test_hit_across_lengths_and_divergence(self):
        pool = RefcountedBlockPool(16, 4)
        a = _tok(*range(8))
        pool.stage("a", a)
        pool.insert_cached("a", a)
        # longer prompt sharing both full blocks
        b = np.concatenate([a, _tok(50, 51, 52)])
        plan = pool.stage("b", b)
        assert plan.n_shared == 2 and plan.n_new == 1
        # divergence INSIDE the second block: only block 0 shared
        c = np.concatenate([a[:6], _tok(60, 61)])
        plan = pool.stage("c", c)
        assert plan.n_shared == 1 and plan.n_new == 1

    def test_free_row_is_refcounted_and_idempotent(self):
        pool = RefcountedBlockPool(8, 4)
        t = _tok(*range(8))
        pool.stage("a", t)
        pool.insert_cached("a", t)
        pool.stage("b", t)              # full hit, shares both blocks
        shared = pool.table("a")[0]
        assert pool.refcount(shared) == 3     # a + b + trie
        assert pool.free_row("a") == 0        # nothing came FREE
        assert pool.refcount(shared) == 2
        # double free: unknown row frees nothing, refs untouched
        assert pool.free_row("a") == 0
        assert pool.refcount(shared) == 2
        assert pool.free_row("b") == 0        # trie still holds them
        assert pool.n_free == 6 and pool.n_cached == 2
        assert not pool.leak_report()

    def test_shared_block_eviction_refuses(self):
        pool = RefcountedBlockPool(8, 4)
        t = _tok(*range(8))
        pool.stage("a", t)
        pool.insert_cached("a", t)
        bid = pool.table("a")[0]
        with pytest.raises(RuntimeError, match="refcount"):
            pool.evict_block(bid)             # a + trie hold it
        pool.free_row("a")
        pool.evict_block(bid)                 # trie-only now: allowed
        assert pool.refcount(bid) == 0
        assert bid in pool._free

    def test_reclaim_drops_lru_cache_only(self):
        pool = RefcountedBlockPool(2, 4)
        old = _tok(*range(4))
        pool.stage("old", old)
        pool.insert_cached("old", old)
        pool.free_row("old")                  # cache-only now
        new = _tok(*range(40, 48))            # needs both blocks
        plan = pool.stage("new", new)         # must reclaim the LRU one
        assert plan is not None and pool.n_reclaimed == 1
        assert pool.n_cached == 0
        # blocks a live row holds are untouchable
        assert pool.reclaim(10) == 0
        assert pool.n_free == 0
        pool.free_row("new")
        assert not pool.leak_report()

    def test_fork_on_write(self):
        pool = RefcountedBlockPool(8, 4)
        t = _tok(*range(8))
        pool.stage("a", t)
        pool.insert_cached("a", t)
        pool.stage("b", t)
        shared = pool.table("b")[0]
        forked = pool.fork_for_write("b", 0)
        assert forked is not None and forked != shared
        assert pool.table("b")[0] == forked
        assert pool.table("a")[0] == shared   # original undisturbed
        assert pool.refcount(shared) == 2     # a + trie
        assert pool.refcount(forked) == 1
        # a private block needs no fork
        assert pool.fork_for_write("b", 0) is None
        assert pool.n_forks == 1
        pool.free_row("a")
        pool.free_row("b")
        assert not pool.leak_report()

    def test_leak_report_catches_imbalance(self):
        pool = RefcountedBlockPool(4, 4)
        pool.stage("a", _tok(*range(4)))
        bid = pool.table("a")[0]
        pool._refs[bid] += 1                  # simulate a leaked ref
        assert any("refcount" in p for p in pool.leak_report())

    def test_share_false_degenerates(self):
        pool = RefcountedBlockPool(8, 4, share=False)
        t = _tok(*range(8))
        pool.stage("a", t)
        assert pool.insert_cached("a", t) == 0
        plan = pool.stage("b", t)
        assert plan.n_shared == 0 and plan.n_new == 2
        assert pool.free_row("a") == 2        # everything comes free


def _shared_trace(rng, n, prefix, vocab=64, max_extra=8, min_new=4,
                  max_new=12):
    """Requests sharing a common system-prompt prefix with ragged
    divergent suffixes (the workload prefix sharing exists for)."""
    out = []
    for _ in range(n):
        extra = rng.randint(1, max_extra + 1)
        p = np.concatenate([prefix, rng.randint(0, vocab, extra)]) \
            .astype(np.int32)
        out.append((p, int(rng.randint(min_new, max_new + 1))))
    return out


class TestEngineSharing:
    @pytest.fixture()
    def engines(self, mini_adapter, mini_params):
        on = ServingEngine(mini_adapter, mini_params, n_slots=8,
                           horizon=160, max_prompt=16, block=8,
                           round_tokens=4, pool_blocks=48,
                           prefix_sharing=True)
        off = ServingEngine(mini_adapter, mini_params, n_slots=8,
                            horizon=160, max_prompt=16, block=8,
                            round_tokens=4, pool_blocks=48,
                            prefix_sharing=False)
        return on, off

    def test_sharing_on_matches_oracle_and_off(self, engines, oracle):
        on, off = engines
        rng = np.random.RandomState(0)
        prefix = rng.randint(0, 64, 8).astype(np.int32)  # one full block
        trace = _shared_trace(rng, 10, prefix)
        results = {}
        for eng in (on, off):
            rids = [(eng.submit(p, max_new=n), p, n) for p, n in trace]
            comps = {c.rid: c for c in eng.run(max_steps=2000)}
            for rid, p, n in rids:
                np.testing.assert_array_equal(
                    comps[rid].tokens, oracle(p, n),
                    err_msg=f"{rid} (sharing={eng.prefix_sharing}) "
                            "diverged from solo decode")
            results[eng.prefix_sharing] = {
                r: comps[r].tokens for r, _, _ in rids}
        # ON ≡ OFF token-for-token (same rids across the two engines)
        for rid in results[True]:
            np.testing.assert_array_equal(results[True][rid],
                                          results[False][rid])
        # and sharing actually HAPPENED: hits, fewer prefilled blocks,
        # lower row-held pool pressure
        assert on.stats()["prefix_hits"] > 0
        assert off.stats()["prefix_hits"] == 0
        assert on.stats()["prefix_prefilled"] \
            < off.stats()["prefix_prefilled"]
        assert on._alloc.peak_row_blocks <= off._alloc.peak_row_blocks

    def test_full_hit_skips_prefill_entirely(self, mini_adapter,
                                             mini_params, oracle):
        eng = ServingEngine(mini_adapter, mini_params, n_slots=8,
                            horizon=160, max_prompt=16, block=8,
                            round_tokens=4, prefix_sharing=True)
        rng = np.random.RandomState(1)
        p = rng.randint(0, 64, 16).astype(np.int32)   # 2 full blocks
        r1 = eng.submit(p, max_new=6)
        c1 = {c.rid: c for c in eng.run(max_steps=500)}
        prefilled_after_first = eng.stats()["prefix_prefilled"]
        r2 = eng.submit(p, max_new=6)
        c2 = {c.rid: c for c in eng.run(max_steps=500)}
        # identical prompt: zero new blocks prefilled the second time
        assert eng.stats()["prefix_prefilled"] == prefilled_after_first
        assert eng.stats()["prefix_hits"] >= 2
        ref = oracle(p, 6)
        np.testing.assert_array_equal(c1[r1].tokens, ref)
        np.testing.assert_array_equal(c2[r2].tokens, ref)

    def test_fork_block_device_copy_keeps_tokens_exact(
            self, mini_adapter, mini_params, oracle):
        """The COW fork primitive end-to-end: fork a staged request's
        shared block, then admit — the forked copy must carry the same
        K/V (tokens stay oracle-exact) while the original keeps its
        other holders."""
        eng = ServingEngine(mini_adapter, mini_params, n_slots=8,
                            horizon=160, max_prompt=16, block=8,
                            round_tokens=4, prefix_sharing=True)
        rng = np.random.RandomState(2)
        p = rng.randint(0, 64, 12).astype(np.int32)
        r1 = eng.submit(p, max_new=6)
        out1 = {c.rid: c for c in eng.run(max_steps=500)}
        # second request hits the cached full block; fork it while
        # staged, BEFORE admission
        r2 = eng.submit(p, max_new=6)
        req2 = eng._queue[0]
        assert eng._stage(req2, get_recorder(), steal=False)
        shared = eng._alloc.table(r2)[0]
        assert eng._alloc.refcount(shared) > 1
        forked = eng.fork_block(r2, 0)
        assert forked != shared
        out2 = {c.rid: c for c in eng.run(max_steps=500)}
        ref = oracle(p, 6)
        np.testing.assert_array_equal(out1[r1].tokens, ref)
        np.testing.assert_array_equal(out2[r2].tokens, ref)

    def test_steal_under_pressure_with_sharing(self, mini_adapter,
                                               mini_params, oracle):
        """Tight pool + shared prefixes: the steal/reclaim paths keep
        every served request exact and leak nothing."""
        eng = ServingEngine(mini_adapter, mini_params, n_slots=8,
                            horizon=160, max_prompt=16, block=8,
                            pool_blocks=4, round_tokens=4,
                            prefill_ahead=4, prefix_sharing=True)
        rng = np.random.RandomState(3)
        prefix = rng.randint(0, 64, 8).astype(np.int32)
        trace = _shared_trace(rng, 12, prefix, min_new=8, max_new=16)
        rids = [(eng.submit(p, max_new=n), p, n) for p, n in trace]
        comps = {c.rid: c for c in eng.run(max_steps=4000)}
        for rid, p, n in rids:
            assert comps[rid].status == "ok"
            np.testing.assert_array_equal(comps[rid].tokens,
                                          oracle(p, n))
        assert not eng._alloc.leak_report()
