"""The request-scoped ops plane end to end (ISSUE 13 acceptance):

- exemplar→trace round trip: drive the engine with a FaultPlan slow
  round, read ``serve/ttft`` p99's exemplar from the registry, and
  resolve it to a retained request trace holding that request's
  prefill/decode spans;
- timeout traces always retained, with the terminal ``timeout`` span
  on the timeline;
- the disabled path (no store) allocates nothing per request;
- ``request_records()`` ring overflow at the ``record_history`` cap:
  oldest dropped, derived latency fields intact, ``SLOReport`` over
  the overflowed ring correct;
- ``/statusz`` + ``/tracez`` served from a LIVE engine mid-decode.
"""

import json
import urllib.request

import numpy as np
import pytest

from chainermn_tpu.serving import (
    AdmissionController,
    ServingEngine,
    SLOReport,
)
from chainermn_tpu.testing import FaultInjector, FaultPlan
from chainermn_tpu.utils.metrics import (
    MetricsRegistry,
    set_registry,
)
from chainermn_tpu.utils.telemetry import RequestTraceStore


@pytest.fixture()
def registry():
    reg = MetricsRegistry(enabled=True)
    prev = set_registry(reg)
    yield reg
    set_registry(prev)


def _engine(mini_adapter, mini_params, warm=False, **kw):
    eng = ServingEngine(mini_adapter, mini_params, n_slots=8,
                        horizon=160, max_prompt=16, block=8,
                        round_tokens=4, **kw)
    if warm:
        rng = np.random.RandomState(99)
        for _ in range(2):
            eng.submit(rng.randint(0, 64, 8), max_new=4)
        eng.run(max_steps=200)
        eng.warm()
        eng.reset()
    return eng


class TestTracingDisabledPath:
    def test_no_store_no_trace_allocations(self, mini_adapter,
                                           mini_params, registry):
        eng = _engine(mini_adapter, mini_params)
        assert eng.traces is None
        rid = eng.submit(np.arange(2, 8), max_new=4)
        req = eng._queue[0]
        assert req.trace_id is None and req.spans is None
        comps = eng.run(max_steps=200)
        assert comps[0].trace_id is None
        # exemplar=None rides the observe path without retaining one
        assert registry.histogram("serve/ttft").exemplar_for(99) is None
        assert rid == comps[0].rid

    def test_env_gate_builds_store(self, mini_adapter, mini_params,
                                   monkeypatch):
        from chainermn_tpu.serving.engine import _trace_store_from_env

        monkeypatch.delenv("CHAINERMN_TPU_REQUEST_TRACE",
                           raising=False)
        assert _trace_store_from_env() is None
        monkeypatch.setenv("CHAINERMN_TPU_REQUEST_TRACE", "1")
        monkeypatch.setenv("CHAINERMN_TPU_REQUEST_TRACE_SAMPLE", "0.5")
        monkeypatch.setenv("CHAINERMN_TPU_REQUEST_TRACE_CAPACITY", "32")
        monkeypatch.setenv("CHAINERMN_TPU_REQUEST_TRACE_SLO", "0.25")
        store = _trace_store_from_env()
        assert store is not None
        assert (store.capacity, store.sample_rate, store.slo_e2e) \
            == (32, 0.5, 0.25)
        # a typo'd knob degrades to the default, never crashes
        monkeypatch.setenv("CHAINERMN_TPU_REQUEST_TRACE_SAMPLE", "oops")
        assert _trace_store_from_env().sample_rate == 0.05


class TestTracedLifecycle:
    def test_ok_request_timeline(self, mini_adapter, mini_params,
                                 registry):
        store = RequestTraceStore(capacity=64, sample_rate=1.0)
        eng = _engine(mini_adapter, mini_params, traces=store)
        rid = eng.submit(np.arange(2, 9), max_new=6)
        comps = eng.run(max_steps=200)
        assert len(comps) == 1 and comps[0].rid == rid
        tid = comps[0].trace_id
        assert tid is not None
        tr = store.get(tid)
        assert tr is not None and tr["status"] == "ok"
        names = [s["name"] for s in tr["spans"]]
        for expected in ("prefill", "queue_wait", "admit",
                         "decode_round", "evict"):
            assert expected in names, names
        # spans are time-ordered enough to read causally: queue_wait
        # starts at submit, evict ends last
        by = {s["name"]: s for s in tr["spans"]}
        assert by["queue_wait"]["t0"] <= by["admit"]["t0"]
        assert tr["e2e"] == pytest.approx(comps[0].e2e)
        # the exemplar on every serve/* histogram resolves to a trace
        for metric in ("serve/ttft", "serve/queue_wait", "serve/e2e",
                       "serve/tpot"):
            ex = registry.histogram(metric).exemplar_for(99)
            assert ex is not None
            assert store.get(ex[0]) is not None

    def test_caller_trace_id_propagates(self, mini_adapter,
                                        mini_params, registry):
        store = RequestTraceStore(capacity=16, sample_rate=1.0)
        eng = _engine(mini_adapter, mini_params, traces=store)
        eng.submit(np.arange(2, 8), max_new=4, trace_id="front-42")
        comps = eng.run(max_steps=200)
        assert comps[0].trace_id == "front-42"
        assert store.get("front-42")["rid"] == comps[0].rid
        assert registry.histogram("serve/ttft").exemplar_for(99)[0] \
            == "front-42"

    def test_decode_round_spans_sampled(self, mini_adapter,
                                        mini_params):
        store = RequestTraceStore(capacity=16, sample_rate=1.0)
        eng = _engine(mini_adapter, mini_params, traces=store,
                      trace_decode_every=1000)
        eng.submit(np.arange(2, 8), max_new=20)
        comps = eng.run(max_steps=400)
        tr = store.get(comps[0].trace_id)
        rounds = [s for s in tr["spans"] if s["name"] == "decode_round"]
        # a 20-token decode takes 5 rounds of 4; with the sampling
        # cadence out of reach only the FIRST round (the TTFT cause)
        # is on the timeline
        assert len(rounds) == 1

    def test_shed_trace_always_kept(self, mini_adapter, mini_params,
                                    registry):
        store = RequestTraceStore(capacity=16, sample_rate=0.0)
        ctrl = AdmissionController(max_queue=1)
        eng = _engine(mini_adapter, mini_params, traces=store,
                      admission=ctrl, gang=True)
        # fill the queue, then overflow it: the overflow is shed
        # "queue_full" at submit and its trace retained despite rate 0
        eng.submit(np.arange(2, 8), max_new=4)
        eng.submit(np.arange(2, 8), max_new=4)      # queued (slots free)
        shed = eng.submit(np.arange(2, 9), max_new=4)
        assert not isinstance(shed, str)
        assert shed.reason == "queue_full"
        assert shed.trace_id is not None
        tr = store.get(shed.trace_id)
        assert tr is not None and tr["status"] == "shed"
        assert tr["reason"] == "queue_full"
        names = [s["name"] for s in tr["spans"]]
        assert names == ["queue_wait", "shed"]
        eng.run(max_steps=400)


class TestExemplarTraceRoundTrip:
    """The acceptance drill: FaultPlan slow round → serve/ttft p99
    exemplar → retained trace with the request's actual spans."""

    def test_slow_round_p99_resolves_to_victim_trace(
            self, mini_adapter, mini_params, registry):
        # tail-only retention: ok traces are kept ONLY when they
        # violate the e2e SLO — which the delay victims do
        store = RequestTraceStore(capacity=64, sample_rate=0.0,
                                  slo_e2e=0.2)
        eng = _engine(mini_adapter, mini_params, warm=True)
        eng.traces = store          # armed AFTER the warm run
        registry.clear()            # drop warm-run compile latencies
        inj = FaultInjector(FaultPlan(serve_delay_at_round=1,
                                      serve_delay_seconds=0.5))
        inj.attach_engine(eng)
        rng = np.random.RandomState(3)
        # wave 1 fills every slot for ONE fast round; wave 2 queues
        # behind it and gets admitted into round 1 — the delayed one —
        # so the delay lands exactly on wave 2's first tokens
        for _ in range(8):
            eng.submit(rng.randint(0, 64, rng.randint(2, 16)),
                       max_new=4)
        for _ in range(2):
            eng.submit(rng.randint(0, 64, rng.randint(2, 16)),
                       max_new=8)
        comps = eng.run(max_steps=400)
        assert ("serve_delay", 1) in inj.fired
        assert all(c.status == "ok" for c in comps)
        # the p99 exemplar is a delay victim...
        ex = registry.histogram("serve/ttft").exemplar_for(99)
        assert ex is not None
        trace_id, ttft_value, _ = ex
        assert ttft_value > 0.5
        # ...and resolves to its retained causal timeline
        tr = store.get(trace_id)
        assert tr is not None
        assert tr["slo_violated"] is True
        names = [s["name"] for s in tr["spans"]]
        for expected in ("prefill", "queue_wait", "admit",
                         "decode_round", "evict"):
            assert expected in names, names
        # the slow round itself is on the timeline (the victim's first
        # round was the delayed one)
        slow = max(s["dur"] for s in tr["spans"]
                   if s["name"] == "decode_round")
        assert slow > 0.5
        # fast requests were NOT retained (tail-based, not keep-all)
        fast = [c for c in comps if c.e2e < 0.2]
        assert fast, "expected some fast completions"
        assert all(store.get(c.trace_id) is None for c in fast)

    def test_timeout_trace_contains_terminal_span(
            self, mini_adapter, mini_params, registry):
        store = RequestTraceStore(capacity=64, sample_rate=0.0)
        eng = _engine(mini_adapter, mini_params, warm=True,
                      traces=store)
        inj = FaultInjector(FaultPlan(serve_delay_at_round=1,
                                      serve_delay_seconds=0.5))
        inj.attach_engine(eng)
        eng.submit(np.arange(2, 10), max_new=12, timeout=0.25)
        comps = eng.run(max_steps=400)
        victim = [c for c in comps if c.status == "timeout"]
        assert victim, [c.status for c in comps]
        tr = store.get(victim[0].trace_id)
        assert tr is not None and tr["status"] == "timeout"
        names = [s["name"] for s in tr["spans"]]
        for expected in ("prefill", "decode_round", "timeout",
                         "evict"):
            assert expected in names, names


class TestProtectiveOverloadShed:
    def test_overload_shed_outside_shed_total(self, mini_adapter,
                                              mini_params, registry):
        """Protective sheds count in serve/shed_overload only:
        serve/shed_total is the burn-rate rules' bad feed, and the
        alert's own deliberate sheds must not keep the alert burning
        after the real cause stops (the self-sustain loop).  They are
        also transient — the reject carries retry_after semantics,
        not a terminal verdict."""
        eng = _engine(
            mini_adapter, mini_params,
            admission=AdmissionController(alert_advisor=lambda: True,
                                          overload_retry_after=30.0))
        shed = eng.submit(np.arange(4), max_new=4, priority=1)
        assert shed.status == "shed" and shed.reason == "overload"
        # the hint is the operator's alert-window figure, never the
        # backlog estimate (an empty queue would hint ~0 and invite a
        # retry storm mid-protection)
        assert shed.retry_after == 30.0
        assert registry.counter("serve/shed_overload").value == 1
        assert registry.counter("serve/shed_total").value == 0
        # ...and out of serve/submitted (the rules' total feed):
        # counting protective sheds as zero-bad traffic would dilute
        # the bad fraction and self-extinguish the alert mid-burst
        assert registry.counter("serve/submitted").value == 0
        # a cold predictor has no estimate, but the class 0 request
        # still passes while the advisory fires
        rid = eng.submit(np.arange(4), max_new=4, priority=0)
        assert isinstance(rid, str)


class TestRecordRingOverflow:
    """Satellite: request_records() at the record_history cap."""

    def test_oldest_dropped_derived_fields_intact(
            self, mini_adapter, mini_params):
        eng = _engine(mini_adapter, mini_params, record_history=6)
        rng = np.random.RandomState(11)
        comps = []
        for _ in range(10):
            eng.submit(rng.randint(0, 64, rng.randint(2, 12)),
                       max_new=int(rng.randint(4, 10)))
        comps = eng.run(max_steps=800)
        assert len(comps) == 10
        recs = eng.request_records()
        assert len(recs) == 6
        assert [r.rid for r in recs] == [c.rid for c in comps[-6:]]
        for r in recs:
            assert r.queue_wait == pytest.approx(
                r.t_admit - r.t_submit)
            assert r.ttft == pytest.approx(r.t_first - r.t_submit)
            assert r.e2e == pytest.approx(r.t_done - r.t_submit)
            assert r.tpot == pytest.approx(
                (r.t_done - r.t_first) / max(r.n_generated - 1, 1))

    def test_slo_report_over_overflowed_ring(self, mini_adapter,
                                             mini_params):
        eng = _engine(mini_adapter, mini_params, record_history=6)
        rng = np.random.RandomState(12)
        for _ in range(10):
            eng.submit(rng.randint(0, 64, rng.randint(2, 12)),
                       max_new=int(rng.randint(4, 10)))
        comps = eng.run(max_steps=800)
        report = SLOReport()
        report.add_arm("ring", eng.request_records(), slo=1e9)
        s = report.summary()["ring"]
        # the report covers exactly the ring's survivors...
        assert s["e2e"]["count"] == 6
        assert s["slo"]["scored"] == 6
        assert s["slo"]["attained"] == 6
        # ...and its percentiles equal raw numpy over those survivors
        tail = [c.e2e for c in comps[-6:]]
        for q in (50, 95, 99):
            assert s["e2e"][f"p{q:g}"] == pytest.approx(
                float(np.percentile(tail, q)))

    def test_sheds_count_in_ring_and_skip_in_report(
            self, mini_adapter, mini_params):
        ctrl = AdmissionController(max_queue=2)
        eng = _engine(mini_adapter, mini_params, record_history=4,
                      admission=ctrl, gang=True)
        rng = np.random.RandomState(13)
        sheds = 0
        for _ in range(8):
            out = eng.submit(rng.randint(0, 64, 6), max_new=4)
            sheds += not isinstance(out, str)
        assert sheds > 0
        eng.run(max_steps=400)
        recs = eng.request_records()
        assert len(recs) == 4       # ring holds completions AND sheds
        report = SLOReport()
        report.add_arm("mix", recs, slo=1e9)
        s = report.summary()["mix"]
        n_shed = sum(1 for r in recs if r.status == "shed")
        assert s["slo"]["shed"] == n_shed
        assert s["skipped"]["ttft"] >= n_shed


class TestStatuszLiveEngine:
    def test_endpoints_reflect_live_engine(self, mini_adapter,
                                           mini_params, registry):
        from chainermn_tpu.utils.statusz import StatuszServer

        store = RequestTraceStore(capacity=16, sample_rate=1.0)
        eng = _engine(mini_adapter, mini_params, traces=store)
        srv = StatuszServer(registry=registry).attach_engine(eng)
        try:
            srv.start()
            rng = np.random.RandomState(5)
            for _ in range(4):
                eng.submit(rng.randint(0, 64, 8), max_new=8)
            # a few steps in: slots live, decode mid-flight
            for _ in range(2):
                eng.step()
            assert eng.n_active > 0
            doc = json.load(urllib.request.urlopen(
                srv.url("/statusz"), timeout=5))
            serving = doc["sections"]["serving"]
            assert serving["active_slots"] == eng.n_active
            assert serving["epoch"] == 0
            assert serving["draining"] is False
            assert serving["traces"]["capacity"] == 16
            assert doc["counters"]["serve/submitted"] == 4.0
            with urllib.request.urlopen(srv.url("/healthz"),
                                        timeout=5) as r:
                assert r.status == 200
            eng.run(max_steps=400)
            tz = json.load(urllib.request.urlopen(
                srv.url("/tracez"), timeout=5))
            assert tz["stores"][0]["retained"] == 4
            one = tz["traces"][0]["trace_id"]
            full = json.load(urllib.request.urlopen(
                srv.url(f"/tracez?trace_id={one}"), timeout=5))
            assert any(s["name"] == "evict"
                       for s in full["trace"]["spans"])
        finally:
            srv.stop()
