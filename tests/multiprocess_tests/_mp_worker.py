"""Worker program for the multi-process test harness.

The reference ran its whole suite under ``mpiexec -n 2`` (SURVEY.md §4).
The TPU-native analogue: N OS processes, each with one CPU device,
joined into one JAX distributed world via
``jax.distributed.initialize`` — exercising every ``inter_size > 1``
branch (gloo collectives, the coordination-service KV object channel,
cross-process checkpoint agreement) that single-process tests cannot
reach.

Invoked by the ``mp_run`` fixture as::

    python _mp_worker.py <coordinator_addr> <num_procs> <proc_id> <scenario>

A scenario is a function ``scenario_<name>(comm)`` below; workers exit 0
on success and print tracebacks to stderr on failure.
"""

import os
import sys
import tempfile

# Pin to CPU before any jax import: the container's sitecustomize pins
# JAX to a TPU plugin whose backend init can hang (see tests/conftest.py).
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


# --------------------------------------------------------------------- #
# scenarios
# --------------------------------------------------------------------- #

def scenario_topology(comm):
    """The rank-model contract (SURVEY.md §5): rank = first owned global
    device index, inter_rank = process index, intra_rank = LOCAL index."""
    assert comm.size == jax.device_count()
    assert comm.inter_size == jax.process_count()
    assert comm.inter_rank == jax.process_index()
    own = [d for d in jax.devices() if d.process_index == jax.process_index()]
    assert comm.rank == jax.devices().index(own[0])
    # intra_rank is an index into jax.local_devices(), NOT a global id —
    # with one device per process it must be 0 on EVERY process.
    assert comm.intra_rank == 0, comm.intra_rank
    ranks = comm.allgather_obj(comm.rank)
    assert sorted(ranks) == list(range(comm.inter_size)), ranks


def scenario_obj_collectives(comm):
    import chainermn_tpu.communicators.tpu_xla as tx

    r = comm.inter_rank
    # bcast_obj: root's object everywhere
    assert comm.bcast_obj({"v": r} if r == 0 else None, root=0) == {"v": 0}
    # multi-frame path: shrink the frame so a modest payload chunks
    old = tx._OBJ_FRAME_BYTES
    tx._OBJ_FRAME_BYTES = 1024
    try:
        big = bytes(range(256)) * 40  # 10240 bytes -> 10 frames
        assert comm.bcast_obj(big if r == 0 else None, root=0) == big
        # asymmetric payload sizes across processes
        mine = "x" * (100 + 5000 * r)
        out = comm.allgather_obj(mine)
        assert [len(s) for s in out] == [100 + 5000 * p
                                         for p in range(comm.inter_size)]
    finally:
        tx._OBJ_FRAME_BYTES = old
    # allreduce_obj over nested structures
    red = comm.allreduce_obj({"loss": float(r), "n": 1}, op="sum")
    ws = comm.inter_size
    assert red == {"loss": sum(range(ws)) * 1.0, "n": ws}
    assert comm.allreduce_obj(2.0, op="mean") == 2.0
    # gather_obj: only root's process gets the list
    got = comm.gather_obj(r * 10, root=0)
    if r == 0:
        assert got == [p * 10 for p in range(ws)]
    else:
        assert got is None
    # scatter_obj
    objs = [f"piece{p}" for p in range(ws)] if r == 0 else None
    assert comm.scatter_obj(objs, root=0) == f"piece{r}"
    comm.barrier()


def scenario_p2p_obj(comm):
    from chainermn_tpu.communicators import _obj_channel

    r = comm.inter_rank
    peer_rank = 1 - r  # device rank == process rank here (1 dev/proc)
    # ordered multi-message exchange, both directions
    if r == 0:
        comm.send_obj({"msg": 1}, dest=1)
        comm.send_obj([2, "two"], dest=1)
        assert comm.recv_obj(source=1) == "reply"
    else:
        assert comm.recv_obj(source=0) == {"msg": 1}
        assert comm.recv_obj(source=0) == [2, "two"]
        comm.send_obj("reply", dest=0)
    comm.barrier()
    # multi-frame p2p: shrink the KV frame so the payload chunks
    old = _obj_channel.FRAME_BYTES
    _obj_channel.FRAME_BYTES = 512
    try:
        payload = np.arange(4096, dtype=np.int64)  # ~32 KiB pickled
        if r == 0:
            comm.send_obj(payload, dest=1)
        else:
            got = comm.recv_obj(source=0)
            np.testing.assert_array_equal(got, payload)
    finally:
        _obj_channel.FRAME_BYTES = old
    comm.barrier()
    # oversize single object raises the named error
    old_cap = _obj_channel.MAX_OBJ_BYTES
    _obj_channel.MAX_OBJ_BYTES = 100
    try:
        if r == 0:
            try:
                comm.send_obj("y" * 1000, dest=1)
            except _obj_channel.DataSizeError:
                pass
            else:
                raise AssertionError("DataSizeError not raised")
    finally:
        _obj_channel.MAX_OBJ_BYTES = old_cap
    comm.barrier()


def scenario_array_collectives(comm):
    """The jitted shard_map collectives over a process-spanning mesh."""
    ws = comm.size
    x = np.arange(ws * 3, dtype=np.float32).reshape(ws, 3)
    out = comm.allreduce(x, op="sum")
    expect = np.broadcast_to(x.sum(0), (ws, 3))
    local = np.asarray(out.addressable_shards[0].data)
    np.testing.assert_allclose(
        local, expect[comm.rank : comm.rank + 1])
    out = comm.bcast(x, root=1)
    local = np.asarray(out.addressable_shards[0].data)
    np.testing.assert_allclose(local, x[1:2])


def scenario_scatter_dataset(comm):
    from chainermn_tpu import scatter_dataset

    data = list(range(103))
    shard = scatter_dataset(data, comm, shuffle=True, seed=7)
    lens = comm.allgather_obj(len(shard))
    assert len(set(lens)) == 1, f"unequal shard lengths {lens}"
    all_idx = comm.allgather_obj(sorted(shard.indices.tolist()))
    covered = set()
    for idx in all_idx:
        covered.update(idx)
    assert covered == set(range(103))


def scenario_checkpoint(comm):
    from chainermn_tpu import create_multi_node_checkpointer

    class FakeUpdater:
        def __init__(self):
            self.iteration = 0
            self.params = {"w": np.zeros(3)}
            self.opt_state = {"m": np.zeros(3)}
            self.state = None

    # every process must agree on the directory: created by proc 0,
    # broadcast to the rest (node-local disks would each make their own)
    path = comm.bcast_obj(
        tempfile.mkdtemp(prefix="cmn_ckpt_") if comm.inter_rank == 0
        else None, root=0)
    cp = create_multi_node_checkpointer(comm, path)
    cp._cleanup = lambda keep: None  # keep both sets alive for the test
    up = FakeUpdater()
    for it in (5, 10):
        up.iteration = it
        up.params = {"w": np.full(3, float(it))}
        cp.save(up)
    # wreck iteration 10 on process 1 only -> latest COMMON set is 5
    if comm.inter_rank == 1:
        os.remove(os.path.join(path, f"snapshot_iter_10.1"))
    comm.barrier()
    fresh = FakeUpdater()
    cp2 = create_multi_node_checkpointer(comm, path)
    resumed = cp2.maybe_load(fresh)
    assert resumed == 5, f"expected agreement on 5, got {resumed}"
    np.testing.assert_allclose(fresh.params["w"], 5.0)
    comm.barrier()


def scenario_evaluator(comm):
    from chainermn_tpu import create_multi_node_evaluator

    class LocalEval:
        name = "validation"

        def __init__(self, value):
            self._value = value

        def evaluate(self, params):
            return {"acc": self._value}

    # process r reports acc=r; the multi-node wrapper must average
    ev = create_multi_node_evaluator(LocalEval(float(comm.inter_rank)), comm)
    obs = ev.evaluate(None)
    ws = comm.inter_size
    assert abs(obs["acc"] - sum(range(ws)) / ws) < 1e-9, obs


def scenario_broadcast_iterator(comm):
    from chainermn_tpu import SerialIterator, create_multi_node_iterator

    # only the master process can see the "real" data source
    if comm.inter_rank == 0:
        base = SerialIterator(list(range(10)), batch_size=4,
                              repeat=False, shuffle=True, seed=3)
    else:
        base = SerialIterator([None] * 10, batch_size=4, repeat=False)
    it = create_multi_node_iterator(base, comm, rank_master=0)
    batches = []
    for batch in it:
        batches.append(batch)
    gathered = comm.allgather_obj(batches)
    for other in gathered[1:]:
        assert other == gathered[0], "slave batches diverge from master"
    assert sorted(sum(gathered[0], [])) == list(range(10))


def scenario_observation_aggregator(comm):
    from chainermn_tpu.extensions import ObservationAggregator

    class FakeTrainer:
        def __init__(self):
            self.observation = {}

    agg = ObservationAggregator(comm)
    tr = FakeTrainer()
    tr.observation = {"loss": float(comm.inter_rank + 1)}
    agg.observe(tr)
    ws = comm.inter_size
    expect = sum(range(1, ws + 1)) / ws
    assert abs(tr.observation["loss"] - expect) < 1e-9, tr.observation


SCENARIOS = {
    name[len("scenario_"):]: fn
    for name, fn in list(globals().items())
    if name.startswith("scenario_")
}


def main():
    addr, n, i, scenario = (sys.argv[1], int(sys.argv[2]), int(sys.argv[3]),
                            sys.argv[4])
    import chainermn_tpu

    chainermn_tpu.init_distributed(
        coordinator_address=addr, num_processes=n, process_id=i)
    comm = chainermn_tpu.create_communicator("tpu_xla")
    SCENARIOS[scenario](comm)
    print(f"WORKER_OK {i} {scenario}", flush=True)


if __name__ == "__main__":
    main()
