"""Worker program for the multi-process test harness.

The reference ran its whole suite under ``mpiexec -n 2`` (SURVEY.md §4).
The TPU-native analogue: N OS processes, each with one CPU device,
joined into one JAX distributed world via
``jax.distributed.initialize`` — exercising every ``inter_size > 1``
branch (gloo collectives, the coordination-service KV object channel,
cross-process checkpoint agreement) that single-process tests cannot
reach.

Invoked by the ``mp_run`` fixture as::

    python _mp_worker.py <coordinator_addr> <num_procs> <proc_id> <scenario>

A scenario is a function ``scenario_<name>(comm)`` below; workers exit 0
on success and print tracebacks to stderr on failure.
"""

import os
import sys
import tempfile

# Pin to CPU before any jax import: the container's sitecustomize pins
# JAX to a TPU plugin whose backend init can hang (see tests/conftest.py).
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


# --------------------------------------------------------------------- #
# scenarios
# --------------------------------------------------------------------- #

def scenario_topology(comm):
    """The rank-model contract (SURVEY.md §5): rank = first owned global
    device index, inter_rank = process index, intra_rank = LOCAL index."""
    assert comm.size == jax.device_count()
    assert comm.inter_size == jax.process_count()
    assert comm.inter_rank == jax.process_index()
    own = [d for d in jax.devices() if d.process_index == jax.process_index()]
    assert comm.rank == jax.devices().index(own[0])
    # intra_rank is an index into jax.local_devices(), NOT a global id —
    # with one device per process it must be 0 on EVERY process.
    assert comm.intra_rank == 0, comm.intra_rank
    ranks = comm.allgather_obj(comm.rank)
    assert sorted(ranks) == list(range(comm.inter_size)), ranks


def scenario_obj_collectives(comm):
    import chainermn_tpu.communicators.tpu_xla as tx

    r = comm.inter_rank
    # bcast_obj: root's object everywhere
    assert comm.bcast_obj({"v": r} if r == 0 else None, root=0) == {"v": 0}
    # multi-frame path: shrink the frame so a modest payload chunks
    old = tx._OBJ_FRAME_BYTES
    tx._OBJ_FRAME_BYTES = 1024
    try:
        big = bytes(range(256)) * 40  # 10240 bytes -> 10 frames
        assert comm.bcast_obj(big if r == 0 else None, root=0) == big
        # asymmetric payload sizes across processes
        mine = "x" * (100 + 5000 * r)
        out = comm.allgather_obj(mine)
        assert [len(s) for s in out] == [100 + 5000 * p
                                         for p in range(comm.inter_size)]
    finally:
        tx._OBJ_FRAME_BYTES = old
    # allreduce_obj over nested structures
    red = comm.allreduce_obj({"loss": float(r), "n": 1}, op="sum")
    ws = comm.inter_size
    assert red == {"loss": sum(range(ws)) * 1.0, "n": ws}
    assert comm.allreduce_obj(2.0, op="mean") == 2.0
    # gather_obj: only root's process gets the list
    got = comm.gather_obj(r * 10, root=0)
    if r == 0:
        assert got == [p * 10 for p in range(ws)]
    else:
        assert got is None
    # scatter_obj
    objs = [f"piece{p}" for p in range(ws)] if r == 0 else None
    assert comm.scatter_obj(objs, root=0) == f"piece{r}"
    comm.barrier()


def scenario_p2p_obj(comm):
    from chainermn_tpu.communicators import _obj_channel

    r = comm.inter_rank
    peer_rank = 1 - r  # device rank == process rank here (1 dev/proc)
    # ordered multi-message exchange, both directions
    if r == 0:
        comm.send_obj({"msg": 1}, dest=1)
        comm.send_obj([2, "two"], dest=1)
        assert comm.recv_obj(source=1) == "reply"
    else:
        assert comm.recv_obj(source=0) == {"msg": 1}
        assert comm.recv_obj(source=0) == [2, "two"]
        comm.send_obj("reply", dest=0)
    comm.barrier()
    # multi-frame p2p: shrink the KV frame so the payload chunks
    old = _obj_channel.FRAME_BYTES
    _obj_channel.FRAME_BYTES = 512
    try:
        payload = np.arange(4096, dtype=np.int64)  # ~32 KiB pickled
        if r == 0:
            comm.send_obj(payload, dest=1)
        else:
            got = comm.recv_obj(source=0)
            np.testing.assert_array_equal(got, payload)
    finally:
        _obj_channel.FRAME_BYTES = old
    comm.barrier()
    # oversize single object raises the named error
    old_cap = _obj_channel.MAX_OBJ_BYTES
    _obj_channel.MAX_OBJ_BYTES = 100
    try:
        if r == 0:
            try:
                comm.send_obj("y" * 1000, dest=1)
            except _obj_channel.DataSizeError:
                pass
            else:
                raise AssertionError("DataSizeError not raised")
    finally:
        _obj_channel.MAX_OBJ_BYTES = old_cap
    comm.barrier()


def scenario_array_collectives(comm):
    """The jitted shard_map collectives over a process-spanning mesh."""
    ws = comm.size
    x = np.arange(ws * 3, dtype=np.float32).reshape(ws, 3)
    out = comm.allreduce(x, op="sum")
    expect = np.broadcast_to(x.sum(0), (ws, 3))
    local = np.asarray(out.addressable_shards[0].data)
    np.testing.assert_allclose(
        local, expect[comm.rank : comm.rank + 1])
    out = comm.bcast(x, root=1)
    local = np.asarray(out.addressable_shards[0].data)
    np.testing.assert_allclose(local, x[1:2])


def scenario_scatter_dataset(comm):
    from chainermn_tpu import scatter_dataset

    data = list(range(103))
    shard = scatter_dataset(data, comm, shuffle=True, seed=7)
    lens = comm.allgather_obj(len(shard))
    assert len(set(lens)) == 1, f"unequal shard lengths {lens}"
    all_idx = comm.allgather_obj(sorted(shard.indices.tolist()))
    covered = set()
    for idx in all_idx:
        covered.update(idx)
    assert covered == set(range(103))


def scenario_checkpoint(comm):
    from chainermn_tpu import create_multi_node_checkpointer

    class FakeUpdater:
        def __init__(self):
            self.iteration = 0
            self.params = {"w": np.zeros(3)}
            self.opt_state = {"m": np.zeros(3)}
            self.state = None

    # every process must agree on the directory: created by proc 0,
    # broadcast to the rest (node-local disks would each make their own)
    path = comm.bcast_obj(
        tempfile.mkdtemp(prefix="cmn_ckpt_") if comm.inter_rank == 0
        else None, root=0)
    cp = create_multi_node_checkpointer(comm, path)
    cp._cleanup = lambda keep: None  # keep both sets alive for the test
    up = FakeUpdater()
    for it in (5, 10):
        up.iteration = it
        up.params = {"w": np.full(3, float(it))}
        cp.save(up)
    # wreck iteration 10 on process 1 only -> latest COMMON set is 5
    if comm.inter_rank == 1:
        os.remove(os.path.join(path, f"snapshot_iter_10.1"))
    comm.barrier()
    fresh = FakeUpdater()
    cp2 = create_multi_node_checkpointer(comm, path)
    resumed = cp2.maybe_load(fresh)
    assert resumed == 5, f"expected agreement on 5, got {resumed}"
    np.testing.assert_allclose(fresh.params["w"], 5.0)
    comm.barrier()


def scenario_fallback_resume(comm):
    """Corruption drill across REAL processes: flip bytes in ONE rank's
    newest shard — the verified-set agreement must fall back to the
    previous complete set on EVERY process, and the damaged file must be
    quarantined (``*.corrupt``), not deleted."""
    from chainermn_tpu import create_multi_node_checkpointer
    from chainermn_tpu.testing import corrupt_file

    class FakeUpdater:
        def __init__(self):
            self.iteration = 0
            self.params = {"w": np.zeros(3)}
            self.opt_state = {"m": np.zeros(3)}
            self.state = None

    path = comm.bcast_obj(
        tempfile.mkdtemp(prefix="cmn_fbck_") if comm.inter_rank == 0
        else None, root=0)
    cp = create_multi_node_checkpointer(comm, path, history=2)
    up = FakeUpdater()
    for it in (5, 10):
        up.iteration = it
        up.params = {"w": np.full(3, float(it))}
        cp.save(up)
    # wreck iteration 10's BYTES on process 1 only (the file still
    # exists — presence-based agreement alone would wrongly pick 10)
    if comm.inter_rank == 1:
        corrupt_file(os.path.join(path, "snapshot_iter_10.1"), seed=7)
    comm.barrier()
    fresh = FakeUpdater()
    cp2 = create_multi_node_checkpointer(comm, path, history=2)
    resumed = cp2.maybe_load(fresh)
    assert resumed == 5, f"expected fallback to 5, got {resumed}"
    np.testing.assert_allclose(fresh.params["w"], 5.0)
    if comm.inter_rank == 1:
        assert os.path.exists(
            os.path.join(path, "snapshot_iter_10.1.corrupt"))
        assert not os.path.exists(
            os.path.join(path, "snapshot_iter_10.1"))
    else:
        # the healthy rank keeps its (verified) iteration-10 shard
        assert os.path.exists(
            os.path.join(path, f"snapshot_iter_10.{comm.inter_rank}"))
    comm.barrier()


def _kv_barrier(comm, channel):
    """Coordination-service barrier: works wherever the JAX distributed
    runtime does, including hosts whose CPU backend cannot run
    cross-process XLA collectives (which is also why the watchdog's own
    heartbeats ride the KV store, not a collective)."""
    channel.allgather(None, list(range(comm.inter_size)),
                      comm.inter_rank)


def scenario_watchdog_stall(comm):
    """Watchdog drill across REAL processes: rank 1 stalls past the
    threshold.  Its OWN monitor fires a local-stall report (stack dump +
    JSON) within one check interval, and the SURVIVOR (rank 0) detects
    the dead peer through the cross-process KV heartbeats.  Deliberately
    touches NO XLA collectives — failure detection must keep working
    exactly when the data plane is wedged."""
    import time

    from chainermn_tpu.communicators._obj_channel import KVObjectChannel
    from chainermn_tpu.extensions import TrainingWatchdog
    from chainermn_tpu.utils.metrics import MetricsRegistry, set_registry

    chan = KVObjectChannel(tag="wdtest")
    r = comm.inter_rank
    # enabled registry + a rank-unique marker: the survivor's stall
    # report must embed a MERGED metrics snapshot that includes the
    # DEAD peer's last KV-published state (no collective involved)
    set_registry(MetricsRegistry(enabled=True))
    from chainermn_tpu.utils.metrics import get_registry

    get_registry().inc(f"drill/rank{r}_marker")
    reports = []
    wd = TrainingWatchdog(
        stall_timeout=1.0, check_interval=0.25, comm=comm,
        on_stall=reports.append,
        report_path=os.path.join(tempfile.mkdtemp(), "stall.json"))
    wd.start()
    for i in range(4):          # healthy phase: everyone beats
        wd.heartbeat(iteration=i)
        time.sleep(0.15)
    assert not reports, f"false positive during healthy phase: {reports}"
    _kv_barrier(comm, chan)
    t0 = time.monotonic()
    if r == 1:
        time.sleep(2.6)         # the stalled rank: beats stop
    else:
        while time.monotonic() - t0 < 2.6:
            wd.heartbeat(iteration=99)
            time.sleep(0.15)
    wd.stop()
    if r == 1:
        local = [rep for rep in reports if rep["kind"] == "local-stall"]
        assert local, f"stalled rank never self-reported: {reports}"
        assert local[0]["seconds_since_heartbeat"] > 1.0
        assert local[0]["threads"], "report carries no thread stacks"
        assert os.path.exists(wd.report_path)
    else:
        peer = [rep for rep in reports if 1 in rep["stalled_peers"]]
        assert peer, (
            f"survivor never detected the stalled peer: {reports}")
        assert peer[0]["peer_heartbeat_ages_s"][1] > 1.0
        # the hung job's last Prometheus state ships with the
        # diagnosis: the merged snapshot holds BOTH ranks' markers —
        # the dead peer's via its KV-published snapshot
        assert peer[0]["metrics_enabled"] is True
        assert "drill/rank0_marker" in peer[0]["metrics"], \
            sorted(peer[0]["metrics"])
        assert "drill/rank1_marker" in peer[0]["metrics"], \
            sorted(peer[0]["metrics"])
        assert "drill_rank1_marker" in peer[0]["metrics_prom"]
    _kv_barrier(comm, chan)


def scenario_checkpoint_async(comm):
    """Async checkpointer across real processes: overlapped writes, the
    join-then-barrier GC ordering, and resume agreement."""
    from chainermn_tpu import create_multi_node_checkpointer

    class FakeUpdater:
        def __init__(self):
            self.iteration = 0
            self.params = {"w": np.zeros(3)}
            self.opt_state = {"m": np.zeros(3)}
            self.state = None

    path = comm.bcast_obj(
        tempfile.mkdtemp(prefix="cmn_ackpt_") if comm.inter_rank == 0
        else None, root=0)
    cp = create_multi_node_checkpointer(comm, path, async_write=True)
    up = FakeUpdater()
    for it in (5, 10, 15):
        up.iteration = it
        up.params = {"w": np.full(3, float(it))}
        cp.save(up)
    cp.finalize()
    comm.barrier()
    # GC: only the newest complete set remains on every process
    mine = sorted(fn for fn in os.listdir(path)
                  if fn.endswith(f".{comm.inter_rank}"))
    assert mine == ["snapshot_iter_15." + str(comm.inter_rank)], mine
    fresh = FakeUpdater()
    cp2 = create_multi_node_checkpointer(comm, path)
    assert cp2.maybe_load(fresh) == 15
    np.testing.assert_allclose(fresh.params["w"], 15.0)
    comm.barrier()


def scenario_evaluator(comm):
    from chainermn_tpu import create_multi_node_evaluator

    class LocalEval:
        name = "validation"

        def __init__(self, value):
            self._value = value

        def evaluate(self, params):
            return {"acc": self._value}

    # process r reports acc=r; the multi-node wrapper must average
    ev = create_multi_node_evaluator(LocalEval(float(comm.inter_rank)), comm)
    obs = ev.evaluate(None)
    ws = comm.inter_size
    assert abs(obs["acc"] - sum(range(ws)) / ws) < 1e-9, obs


def scenario_broadcast_iterator(comm):
    from chainermn_tpu import SerialIterator, create_multi_node_iterator

    # only the master process can see the "real" data source
    if comm.inter_rank == 0:
        base = SerialIterator(list(range(10)), batch_size=4,
                              repeat=False, shuffle=True, seed=3)
    else:
        base = SerialIterator([None] * 10, batch_size=4, repeat=False)
    it = create_multi_node_iterator(base, comm, rank_master=0)
    batches = []
    for batch in it:
        batches.append(batch)
    gathered = comm.allgather_obj(batches)
    for other in gathered[1:]:
        assert other == gathered[0], "slave batches diverge from master"
    assert sorted(sum(gathered[0], [])) == list(range(10))


def scenario_observation_aggregator(comm):
    from chainermn_tpu.extensions import ObservationAggregator

    class FakeTrainer:
        def __init__(self):
            self.observation = {}

    agg = ObservationAggregator(comm)
    tr = FakeTrainer()
    tr.observation = {"loss": float(comm.inter_rank + 1)}
    agg.observe(tr)
    ws = comm.inter_size
    expect = sum(range(1, ws + 1)) / ws
    assert abs(tr.observation["loss"] - expect) < 1e-9, tr.observation


def scenario_split(comm):
    """MPI_Comm_split analogue across processes: even/odd device split
    produces working sub-communicators whose obj collectives stay inside
    the split (the reference's split tests, SURVEY.md §4).  Run with ≥4
    processes so each subgroup spans >1 process — the whole-world
    multihost collectives would deadlock there; the KV group path must
    carry them."""
    ws = comm.size
    colors = np.arange(ws) % 2
    sub = comm.split(colors, np.arange(ws))
    expect = [i for i in range(ws) if i % 2 == comm.rank % 2]
    assert sub.size == len(expect), (sub.size, expect)
    # sub-communicator topology: my rank within my color group
    assert sub.rank == expect.index(comm.rank)
    # obj collectives scope to the subgroup (distinct KV lanes per split)
    vals = sub.allgather_obj(comm.rank)
    assert vals == expect, (vals, expect)
    # subgroup bcast: root is the subgroup's OWN rank 0 (global device
    # rank expect[0]); both halves broadcast concurrently without
    # cross-talk or deadlock
    got = sub.bcast_obj(f"from{comm.rank}" if sub.rank == 0 else None,
                        root=0)
    assert got == f"from{expect[0]}", got
    # repeated rounds: the lazy-GC key lifecycle must keep lanes ordered
    for round_no in range(3):
        red = sub.allreduce_obj({"r": float(comm.rank), "n": 1}, op="sum")
        assert red == {"r": float(sum(expect)), "n": len(expect)}, red
        sub.barrier()
    # re-created communicator with the SAME member set: the incarnation
    # counter must give it a fresh KV namespace (seq numbers restart at 0
    # and must not read the first incarnation's still-live keys)
    sub2 = comm.split(colors, np.arange(ws))
    vals2 = sub2.allgather_obj(("fresh", comm.rank))
    assert vals2 == [("fresh", p) for p in expect], vals2


def scenario_snapshot(comm):
    """multi_node_snapshot across real processes: writer rank persists
    one logical snapshot, the barrier protects readers, and
    load_snapshot restores it on EVERY process."""
    from chainermn_tpu import multi_node_snapshot
    from chainermn_tpu.extensions.snapshot import load_snapshot

    class FakeUpdater:
        def __init__(self):
            self.iteration = 7
            self.params = {"w": np.full(2, 3.25)}
            self.opt_state = {"m": np.ones(2)}
            self.state = None

    class FakeTrainer:
        def __init__(self, out):
            self.updater = FakeUpdater()
            self.out = out
            self.observation = {}

    out = comm.bcast_obj(
        tempfile.mkdtemp(prefix="cmn_snap_") if comm.inter_rank == 0
        else None, root=0)
    snap = multi_node_snapshot(comm)
    snap(FakeTrainer(out))          # writer writes snapshot_iter_7
    fresh = FakeTrainer(out)
    fresh.updater.iteration = 0
    fresh.updater.params = {"w": np.zeros(2)}
    it = load_snapshot(fresh.updater,
                       os.path.join(out, "snapshot_iter_7"), fresh)
    assert it == 7, it
    np.testing.assert_allclose(fresh.updater.params["w"], 3.25)
    comm.barrier()


def scenario_allreduce_persistent(comm):
    """BN-running-stats averaging across processes (the reference's
    AllreducePersistentValues)."""
    from chainermn_tpu.extensions import AllreducePersistentValues

    class FakeUpdater:
        def __init__(self, r):
            self.params = {"persistent": {"bn_mean": np.full(3, float(r))}}

    class FakeTrainer:
        def __init__(self, r):
            self.updater = FakeUpdater(r)

    tr = FakeTrainer(comm.inter_rank)
    AllreducePersistentValues(comm)(tr)
    ws = comm.inter_size
    np.testing.assert_allclose(
        tr.updater.params["persistent"]["bn_mean"],
        sum(range(ws)) / ws)


def scenario_dp_train(comm):
    """End-to-end: a jitted DP train step over the PROCESS-SPANNING mesh
    — per-process batches, pmean'd grads, params provably in sync (the
    reference's whole raison d'être, §3.1, across real processes)."""
    import jax.numpy as jnp
    import optax
    from jax.sharding import PartitionSpec as P

    from chainermn_tpu import create_multi_node_optimizer

    ws = comm.size
    rng = np.random.RandomState(0)              # same on every process
    w_true = rng.randn(4, 2).astype(np.float32)
    xs = rng.randn(ws, 32, 4).astype(np.float32)
    ys = np.einsum("rbi,ij->rbj", xs, w_true)

    params = {"w": jnp.zeros((4, 2))}
    opt = create_multi_node_optimizer(optax.sgd(0.2), comm)
    state = jax.jit(opt.init)(params)

    def step(p, s, x, y):
        x, y = x[0], y[0]
        loss, g = jax.value_and_grad(
            lambda q: jnp.mean((x @ q["w"] - y) ** 2))(p)
        u, s = opt.update(g, s, p)
        return optax.apply_updates(p, u), s, jax.lax.pmean(
            loss, comm.axis_name)

    f = jax.jit(jax.shard_map(
        step, mesh=comm.mesh,
        in_specs=(P(), P(), P(comm.axis_name), P(comm.axis_name)),
        out_specs=(P(), P(), P())))
    # global batch sharded over the world: this process feeds its shard
    sh = jax.sharding.NamedSharding(comm.mesh, P(comm.axis_name))
    gx = jax.device_put(jnp.asarray(xs), sh)
    gy = jax.device_put(jnp.asarray(ys), sh)
    losses = []
    for _ in range(60):
        params, state, loss = f(params, state, gx, gy)
        losses.append(float(jax.block_until_ready(loss)))
    assert losses[-1] < 1e-2, losses[-1]
    # every process must hold identical params
    w_all = comm.allgather_obj(np.asarray(params["w"]).tolist())
    for other in w_all[1:]:
        assert other == w_all[0], "params diverged across processes"


def scenario_shuffle_datablock(comm):
    """Cross-process block shuffle: unequal per-process blocks come out
    globally shuffled, balanced, and complete — the examples really move
    between processes (each block starts disjoint)."""
    from chainermn_tpu.datasets import shuffle_data_blocks

    r, n = comm.inter_rank, comm.inter_size
    # disjoint, unequal blocks: proc r holds r*100 .. r*100 + (10 - 2r)
    sizes = [10 - 2 * j for j in range(n)]
    block = list(range(r * 100, r * 100 + sizes[r]))
    out = shuffle_data_blocks(comm, block, seed=5)

    gathered = comm.allgather_obj(out)
    merged = sorted(x for row in gathered for x in row)
    expected = sorted(
        x for j in range(n) for x in range(j * 100, j * 100 + sizes[j]))
    assert merged == expected, merged
    # balanced: near-equal split of the total
    total = sum(sizes)
    assert {len(row) for row in gathered} <= {total // n, -(-total // n)}, \
        [len(x) for x in gathered]
    # actually mixed across processes: each output spans several blocks
    assert len({x // 100 for x in out}) > 1, out
    # alltoall_obj round-trip sanity on its own
    back = comm.alltoall_obj([f"{r}->{j}" for j in range(comm.inter_size)])
    assert back == [f"{j}->{r}" for j in range(comm.inter_size)], back


def scenario_zero1_checkpoint(comm):
    """ZeRO-1 over a PROCESS-SPANNING mesh: the optimizer state is not
    fully addressable by either process, so checkpointing exercises the
    gather-on-save path; resume must agree across processes."""
    import jax.numpy as jnp
    import optax

    import chainermn_tpu as cmn
    from chainermn_tpu.extensions import create_multi_node_checkpointer
    from chainermn_tpu.models import init_mlp, mlp_apply, \
        softmax_cross_entropy

    path = comm.bcast_obj(
        tempfile.mkdtemp(prefix="zero1ck_")
        if comm.inter_rank == 0 else None, root=0)

    def make_updater():
        rng = np.random.RandomState(0)          # same data on all procs
        data = [(rng.randn(4).astype(np.float32), np.int32(i % 2))
                for i in range(64)]
        it = cmn.SerialIterator(data, 16, shuffle=True, seed=1)
        params = init_mlp(jax.random.PRNGKey(0), [4, 8, 2])
        opt = cmn.create_multi_node_optimizer(
            optax.adam(5e-2), comm, zero1=True)

        def loss_fn(p, x, y):
            return softmax_cross_entropy(mlp_apply(p, x), y)

        return cmn.StandardUpdater(it, opt, loss_fn, params, comm)

    upd = make_updater()
    assert upd.zero1
    # state spans both processes' devices
    leaf = jax.tree.leaves(upd.opt_state)[0]
    assert not leaf.is_fully_addressable
    for _ in range(3):
        upd.update()

    cp = create_multi_node_checkpointer(comm, path)
    cp.save(upd)

    upd2 = make_updater()
    loaded = create_multi_node_checkpointer(comm, path)
    assert loaded.maybe_load(upd2) == 3
    # params agree across processes and match the saved run
    w = comm.allgather_obj(
        np.asarray(jax.tree.leaves(upd2.params)[0]).tolist())
    assert w[0] == w[-1]
    np.testing.assert_allclose(
        np.asarray(jax.tree.leaves(upd2.params)[0]),
        np.asarray(jax.tree.leaves(upd.params)[0]), rtol=1e-6)
    # the restored run continues without error
    upd2.update()

    # async writer path: device pull + collective gather happen on the
    # main thread before the writer thread starts — must not crash or
    # deadlock on the process-spanning state
    cp_async = create_multi_node_checkpointer(
        comm, path, name="async", async_write=True)
    cp_async.save(upd2)
    cp_async.finalize()
    assert cp_async._agreed_inventory()[0] == [4]

    # writer-only snapshot: ALL ranks join the collective gather before
    # rank 0 writes (a writer-only gather would deadlock the barrier)
    from chainermn_tpu.extensions import multi_node_snapshot

    class _Tr:
        updater = upd2
        out = path
        observation = {}

    multi_node_snapshot(comm)(_Tr())
    import os

    assert os.path.exists(os.path.join(path, "snapshot_iter_4")) \
        or comm.inter_rank != 0


def scenario_preemption(comm):
    """The preemption flag is OR-reduced COLLECTIVELY: only process 0
    'receives' the signal, yet every process must checkpoint the same
    iteration and stop — exercising the ``inter_size > 1`` branch of
    ``PreemptionCheckpointer._global_flag`` with real processes."""
    import optax

    import chainermn_tpu as cmn
    from chainermn_tpu.extensions import (
        PreemptionCheckpointer,
        create_multi_node_checkpointer,
    )
    from chainermn_tpu.models import init_mlp, mlp_apply, \
        softmax_cross_entropy

    # every process must agree on the directory (rank 0 decides)
    path = comm.bcast_obj(
        tempfile.mkdtemp(prefix="preempt_")
        if comm.inter_rank == 0 else None, root=0)

    rng = np.random.RandomState(0)
    data = [(rng.randn(4).astype(np.float32), np.int32(i % 2))
            for i in range(64)]
    it = cmn.SerialIterator(data, 16, shuffle=True, seed=1)
    params = init_mlp(jax.random.PRNGKey(0), [4, 8, 2])
    opt = cmn.create_multi_node_optimizer(optax.sgd(0.05), comm)

    def loss_fn(p, x, y):
        return softmax_cross_entropy(mlp_apply(p, x), y)

    upd = cmn.StandardUpdater(it, opt, loss_fn, params, comm)
    trainer = cmn.Trainer(upd, (50, "epoch"), out=path)
    cp = create_multi_node_checkpointer(comm, path)
    pre = PreemptionCheckpointer(cp, comm, signals=())
    trainer.extend(pre)

    @cmn.training.make_extension(trigger=(1, "iteration"), priority=999)
    def fake_signal(tr):
        # ONLY process 0 sees the signal; the others learn of it
        # through the collective flag reduce
        if comm.inter_rank == 0 and tr.updater.iteration == 3:
            pre.signaled = True

    trainer.extend(fake_signal)
    trainer.run()

    assert upd.iteration == 3, upd.iteration
    assert "preemption" in (trainer.stop_reason or ""), trainer.stop_reason
    # all processes agreed on the checkpointed iteration
    iters = comm.allgather_obj(cp._agreed_inventory()[0])
    assert all(x == [3] for x in iters), iters


def scenario_fsdp_train(comm):
    """ZeRO-3/FSDP over a PROCESS-SPANNING data axis: the flagship
    transformer's fsdp layout puts each process's device on a 1/N param
    shard, the per-layer gathers cross the process boundary, and the
    losses must match the replicated run exactly."""
    import dataclasses

    import jax.numpy as jnp
    import optax

    from chainermn_tpu.models import (
        TransformerConfig, init_transformer, make_train_step, shard_params,
    )
    from chainermn_tpu.parallel import MeshConfig
    from chainermn_tpu.training import shard_opt_state

    B, T = 4, 8
    dense = TransformerConfig(
        vocab_size=32, d_model=16, n_heads=2, d_head=8, d_ff=32,
        n_layers=2, max_seq=T, attention="local", dtype="float32",
        remat=False)
    mc = MeshConfig(data=comm.size, devices=jax.devices())
    toks = jnp.asarray(
        np.random.RandomState(0).randint(0, 32, (B, T + 1)), jnp.int32)

    def train(cfg, steps=2):
        params = shard_params(
            mc, cfg, init_transformer(jax.random.PRNGKey(0), cfg))
        opt = optax.adam(1e-2)
        opt_state = shard_opt_state(opt, params)
        step = make_train_step(mc, cfg, opt)
        out = []
        for _ in range(steps):
            params, opt_state, loss = step(
                params, opt_state, toks[:, :T], toks[:, 1:])
            out.append(float(jax.block_until_ready(loss)))
        return out, params

    fsdp_losses, placed = train(dataclasses.replace(dense, fsdp=True))
    # this process's device really holds only its 1/N slice at rest
    w1 = placed["blocks"]["w1"]
    assert w1.addressable_shards[0].data.shape[2] == 16 // comm.size, \
        w1.addressable_shards[0].data.shape
    dense_losses, _ = train(dense)
    np.testing.assert_allclose(fsdp_losses, dense_losses,
                               rtol=1e-5, atol=1e-5)
    # every process must agree on the loss trajectory
    all_losses = comm.allgather_obj(fsdp_losses)
    for other in all_losses[1:]:
        np.testing.assert_allclose(other, all_losses[0],
                                   rtol=1e-6, atol=1e-6)


def _gather_rows(comm, got, dtype=np.int32):
    """Reassemble a batch-sharded decode output across processes: each
    process contributes its own shard KEYED BY ITS ROW OFFSET — device
    order need not follow process order, so process index must never
    decide row placement."""
    shard = got.addressable_shards[0]
    row0 = shard.index[0].start or 0
    alls = dict(comm.allgather_obj(
        (int(row0), np.asarray(shard.data).tolist())))
    return np.concatenate(
        [np.asarray(alls[r], dtype) for r in sorted(alls)], axis=0)


def _tiny_cfg(**kw):
    """The shared tiny transformer of the data-plane scenarios — one
    definition so every scenario provably tests the same model."""
    from chainermn_tpu.models import TransformerConfig

    base = dict(vocab_size=32, d_model=16, n_heads=2, d_head=8,
                d_ff=32, n_layers=2, max_seq=8, attention="local",
                dtype="float32", remat=False)
    base.update(kw)
    return TransformerConfig(**base)


def _tiny_transformer_losses(mc, cfg, steps=2):
    """Shared driver for the TP/PP data-plane scenarios: init, shard,
    run ``steps`` train steps on the given mesh, return the losses."""
    import jax.numpy as jnp
    import optax

    from chainermn_tpu.models import (
        init_transformer, make_train_step, shard_params,
    )
    from chainermn_tpu.training import shard_opt_state

    B, T = 4, 8
    pipe = mc.mesh.shape.get("pipe", 1)
    toks = jnp.asarray(
        np.random.RandomState(0).randint(0, cfg.vocab_size, (B, T + 1)),
        jnp.int32)
    params = shard_params(
        mc, cfg, init_transformer(jax.random.PRNGKey(0), cfg, pipe))
    opt = optax.adam(1e-2)
    opt_state = shard_opt_state(opt, params)
    step = make_train_step(mc, cfg, opt)
    out = []
    for _ in range(steps):
        params, opt_state, loss = step(
            params, opt_state, toks[:, :T], toks[:, 1:])
        out.append(float(jax.block_until_ready(loss)))
    return out


def scenario_tp_train(comm):
    """Tensor parallelism ACROSS the process boundary: 2 processes × 1
    device, ``model=2`` — every layer's column→row psum is a real
    cross-process collective.  The loss trajectory must equal a
    process-LOCAL single-device oracle (same init, same data)."""
    from chainermn_tpu.parallel import MeshConfig

    assert jax.process_count() == 2 and len(jax.local_devices()) == 1
    cfg = _tiny_cfg()

    tp_losses = _tiny_transformer_losses(
        MeshConfig(model=2, data=1, devices=jax.devices()), cfg)
    # local oracle: this process's own device, no sharded axes
    oracle = _tiny_transformer_losses(
        MeshConfig(data=1, devices=[jax.local_devices()[0]]), cfg)
    np.testing.assert_allclose(tp_losses, oracle, rtol=1e-5, atol=1e-5)
    all_losses = comm.allgather_obj(tp_losses)
    for other in all_losses[1:]:
        np.testing.assert_allclose(other, all_losses[0],
                                   rtol=1e-6, atol=1e-6)


def scenario_pp_train(comm):
    """Pipeline parallelism ACROSS the process boundary: 2 processes × 2
    devices, ``MeshConfig(pipe=2, model=2)`` — pipe is the mesh-major
    axis, so each stage's ppermute activation hand-off crosses the
    process boundary while each stage's TP psum stays process-local
    (the production layout).  Also runs ``MeshConfig(model=2, data=2)``
    — the VERDICT-named shape, whose grad allreduce spans processes —
    and checks both against the process-local single-device oracle."""
    import dataclasses

    from chainermn_tpu.parallel import MeshConfig

    assert jax.process_count() == 2 and len(jax.local_devices()) == 2
    base = _tiny_cfg()
    oracle = _tiny_transformer_losses(
        MeshConfig(data=1, devices=[jax.local_devices()[0]]), base)

    for axes, cfg in (
        (dict(pipe=2, model=2, data=1),
         dataclasses.replace(base, num_microbatches=2)),
        (dict(pipe=2, model=2, data=1),
         dataclasses.replace(base, num_microbatches=2,
                             pipeline_schedule="1f1b")),
        (dict(model=2, data=2), base),
    ):
        losses = _tiny_transformer_losses(
            MeshConfig(devices=jax.devices(), **axes), cfg)
        np.testing.assert_allclose(
            losses, oracle, rtol=1e-5, atol=1e-5,
            err_msg=f"{axes} {cfg.pipeline_schedule}")
        all_losses = comm.allgather_obj(losses)
        for other in all_losses[1:]:
            np.testing.assert_allclose(other, all_losses[0],
                                       rtol=1e-6, atol=1e-6)


def scenario_decode(comm):
    """Model-parallel DECODE across the process boundary: 2 processes ×
    1 device.  Two meshes: ``seq=2`` (sequence-parallel KV — every
    generated token's pmax/psum softmax merge is a real cross-process
    collective) and ``model=2`` with ``vocab_parallel`` (the embedding
    lookup psum and the logits all-gather cross processes).  Greedy
    tokens must be IDENTICAL to the process-local single-device decode
    — sampling amplifies any logit drift into divergent sequences, so
    exact token equality is the right bar."""
    import dataclasses

    from chainermn_tpu.models import (
        init_transformer, make_generate_fn, shard_params,
    )
    from chainermn_tpu.parallel import MeshConfig

    assert jax.process_count() == 2 and len(jax.local_devices()) == 1
    base = _tiny_cfg()
    host = init_transformer(jax.random.PRNGKey(2), base)
    import jax.numpy as jnp

    prompt = jnp.asarray(
        np.random.RandomState(3).randint(0, base.vocab_size, (4, 3)),
        jnp.int32)

    one = MeshConfig(data=1, devices=[jax.local_devices()[0]])
    ref = np.asarray(
        make_generate_fn(one, base, max_len=8)(
            shard_params(one, base, host), prompt))

    for name, axes, cfg in (
        ("seq-kv", dict(seq=2, data=1), base),
        ("vocab-parallel", dict(model=2, data=1),
         dataclasses.replace(base, vocab_parallel=True)),
    ):
        mc = MeshConfig(devices=jax.devices(), **axes)
        got = np.asarray(
            make_generate_fn(mc, cfg, max_len=8)(
                shard_params(mc, cfg, host), prompt))
        np.testing.assert_array_equal(
            got, ref, err_msg=f"cross-process {name} decode diverged")
        all_toks = comm.allgather_obj(got.tolist())
        assert all(t == all_toks[0] for t in all_toks[1:]), \
            f"{name}: processes disagree on generated tokens"

    # padded + eos over a cross-process data axis: the early-stop
    # while-loop's pmax flag and the per-row pad masks span the
    # boundary; tokens must equal the process-local padded oracle
    lens = np.asarray([3, 1, 2, 3])
    padded = np.full((4, 3), 7, np.int32)
    rng = np.random.RandomState(8)
    for b, L in enumerate(lens):
        padded[b, 3 - L:] = rng.randint(0, base.vocab_size, L)
    pl = jnp.asarray(padded)
    kw = dict(max_len=8, eos_id=5, pad_id=0)
    ref2 = np.asarray(
        make_generate_fn(one, base, **kw)(
            shard_params(one, base, host), pl, prompt_lens=lens))
    mc = MeshConfig(data=2, devices=jax.devices())
    sh = mc.sharding(("data", "expert"))
    got = make_generate_fn(mc, base, **kw)(
        shard_params(mc, base, host), jax.device_put(pl, sh),
        prompt_lens=jax.device_put(jnp.asarray(lens, jnp.int32), sh))
    full = _gather_rows(comm, got)
    np.testing.assert_array_equal(
        full, ref2, err_msg="cross-process padded+eos decode diverged")


def scenario_speculative_decode(comm):
    """Speculative decoding ACROSS the process boundary: 2 processes ×
    1 device, ``data=2`` — the per-round acceptance pmin and the
    verify-chunk collectives run inside a cross-process while_loop.
    Tokens must equal the process-local greedy oracle, and both
    processes must agree on the acceptance statistic."""
    from chainermn_tpu.models import (
        init_transformer, make_generate_fn,
        make_speculative_generate_fn, shard_params,
    )
    from chainermn_tpu.parallel import MeshConfig

    assert jax.process_count() == 2 and len(jax.local_devices()) == 1
    cfg = _tiny_cfg(n_layers=4)
    d_cfg = _tiny_cfg(n_layers=2)
    host = init_transformer(jax.random.PRNGKey(4), cfg)
    d_host = init_transformer(jax.random.PRNGKey(5), d_cfg)
    import jax.numpy as jnp

    prompt = jnp.asarray(
        np.random.RandomState(6).randint(0, cfg.vocab_size, (4, 3)),
        jnp.int32)

    one = MeshConfig(data=1, devices=[jax.local_devices()[0]])
    ref = np.asarray(
        make_generate_fn(one, cfg, max_len=8)(
            shard_params(one, cfg, host), prompt))

    mc = MeshConfig(data=2, devices=jax.devices())
    spec = make_speculative_generate_fn(
        mc, cfg, d_cfg, k=2, max_len=8, with_stats=True)
    # the batch spans the process boundary: feed the sharded global
    # array (dp_train's pattern), reassemble the sharded output over
    # the object channel for the equality check — keyed by each
    # shard's OWN row offset, not process index (device order need
    # not follow process order)
    sh = mc.sharding(("data", "expert"))
    params = shard_params(mc, cfg, host)
    got, mean_acc = spec(params,
                         shard_params(mc, d_cfg, d_host),
                         jax.device_put(prompt, sh))
    full = _gather_rows(comm, got)
    np.testing.assert_array_equal(
        full, ref, err_msg="cross-process speculative decode diverged")
    accs = comm.allgather_obj(float(mean_acc))
    assert all(abs(a - accs[0]) < 1e-6 for a in accs), \
        f"processes disagree on acceptance: {accs}"

    # --- NONZERO accepted prefix across the mesh (VERDICT r4 #3): a
    # self-draft's proposals all verify, so the accept/commit path
    # (_commit_round with n_acc > 0) provably crosses the process
    # boundary — the random-draft phase above only witnesses the
    # all-reject corrective path
    self_spec = make_speculative_generate_fn(
        mc, cfg, cfg, k=2, max_len=8, with_stats=True)
    got_sd, acc_sd = self_spec(params, params,
                               jax.device_put(prompt, sh))
    full_sd = _gather_rows(comm, got_sd)
    np.testing.assert_array_equal(
        full_sd, ref, err_msg="self-draft speculative diverged")
    assert float(acc_sd) >= 1.0, \
        f"self-draft must accept a nonzero prefix, got {float(acc_sd)}"

    # --- padded + eos composition: ragged rows and the early-stop
    # done flags ride the same cross-process while_loop
    lens = np.asarray([3, 1, 2, 3])
    padded = np.full((4, 3), 7, np.int32)
    rng = np.random.RandomState(13)
    for b, L in enumerate(lens):
        padded[b, 3 - L:] = rng.randint(0, cfg.vocab_size, L)
    pl = jnp.asarray(padded)
    kw = dict(max_len=8, eos_id=5, pad_id=0)
    ref_pe = np.asarray(
        make_generate_fn(one, cfg, **kw)(
            shard_params(one, cfg, host), pl, prompt_lens=lens))
    spec_pe = make_speculative_generate_fn(
        mc, cfg, d_cfg, k=2, **kw)
    got_pe = spec_pe(params, shard_params(mc, d_cfg, d_host),
                     jax.device_put(pl, sh),
                     prompt_lens=jax.device_put(
                         jnp.asarray(lens, jnp.int32), sh))
    np.testing.assert_array_equal(
        _gather_rows(comm, got_pe), ref_pe,
        err_msg="cross-process speculative padded+eos diverged")


def scenario_speculative_sampling(comm):
    """Speculative SAMPLING across the process boundary: the per-round
    acceptance pmin, the shard-decorrelated PRNG fold, and the
    while-loop key carry all span processes.  Same-key runs must be
    deterministic, processes must agree on the acceptance statistic,
    and different keys must draw different sequences."""
    import dataclasses

    from chainermn_tpu.models import (
        init_transformer, make_speculative_generate_fn, shard_params,
    )
    from chainermn_tpu.parallel import MeshConfig

    assert jax.process_count() == 2 and len(jax.local_devices()) == 1
    cfg = _tiny_cfg(n_layers=4)
    d_cfg = dataclasses.replace(cfg, n_layers=2)
    host = init_transformer(jax.random.PRNGKey(11), cfg)
    d_host = dict(host, blocks=jax.tree.map(
        lambda a: a[:, :2], host["blocks"]))
    import jax.numpy as jnp

    prompt = jnp.asarray(
        np.random.RandomState(12).randint(0, cfg.vocab_size, (4, 3)),
        jnp.int32)
    mc = MeshConfig(data=2, devices=jax.devices())
    sh = mc.sharding(("data", "expert"))
    spec = make_speculative_generate_fn(
        mc, cfg, d_cfg, k=2, max_len=8, temperature=1.0,
        with_stats=True)
    params = shard_params(mc, cfg, host)
    d_params = shard_params(mc, d_cfg, d_host)
    gp = jax.device_put(prompt, sh)
    a1, acc = spec(params, d_params, gp, key=jax.random.PRNGKey(3))
    a2, _ = spec(params, d_params, gp, key=jax.random.PRNGKey(3))
    b1, _ = spec(params, d_params, gp, key=jax.random.PRNGKey(4))
    ra1, ra2, rb1 = (_gather_rows(comm, t) for t in (a1, a2, b1))
    np.testing.assert_array_equal(ra1, ra2,
                                  err_msg="same key, different tokens")
    assert not np.array_equal(ra1, rb1), "keys ignored"
    assert (ra1 >= 0).all() and (ra1 < cfg.vocab_size).all()
    np.testing.assert_array_equal(ra1[:, :3], np.asarray(prompt))
    accs = comm.allgather_obj(float(acc))
    assert all(abs(x - accs[0]) < 1e-6 for x in accs), accs

    # --- top-k/top-p composition: the truncated draft/target pair's
    # acceptance pmin crosses the boundary.  Checked here: same-key
    # determinism, vocab-range sanity, and cross-process agreement on
    # the acceptance statistic; the truncated-support and distribution
    # identities are pinned by the single-device statistical test
    # (test_sampling_filters_distribution_matches_target)
    TOPK = 6
    fspec = make_speculative_generate_fn(
        mc, cfg, d_cfg, k=2, max_len=8, temperature=1.0,
        top_k=TOPK, top_p=0.9, with_stats=True)
    f1, facc = fspec(params, d_params, gp, key=jax.random.PRNGKey(5))
    f2, _ = fspec(params, d_params, gp, key=jax.random.PRNGKey(5))
    rf1, rf2 = (_gather_rows(comm, t) for t in (f1, f2))
    np.testing.assert_array_equal(
        rf1, rf2, err_msg="filtered sampling not deterministic")
    assert (rf1 >= 0).all() and (rf1 < cfg.vocab_size).all()
    faccs = comm.allgather_obj(float(facc))
    assert all(abs(x - faccs[0]) < 1e-6 for x in faccs), faccs

    # --- ragged + eos composition under SAMPLING: per-row offsets and
    # the done flags ride the cross-process while_loop with the key
    # carry; same-key determinism and prompt preservation across the
    # boundary (per-row content exactness is pinned single-device)
    lens = np.asarray([3, 1, 2, 3])
    padded = np.full((4, 3), 7, np.int32)
    rng = np.random.RandomState(17)
    for b, L in enumerate(lens):
        padded[b, 3 - L:] = rng.randint(0, cfg.vocab_size, L)
    pl = jax.device_put(jnp.asarray(padded), sh)
    gl = jax.device_put(jnp.asarray(lens, jnp.int32), sh)
    pspec = make_speculative_generate_fn(
        mc, cfg, d_cfg, k=2, max_len=8, temperature=1.0,
        eos_id=5, pad_id=0, with_stats=True)
    p1, pacc = pspec(params, d_params, pl, key=jax.random.PRNGKey(6),
                     prompt_lens=gl)
    p2, _ = pspec(params, d_params, pl, key=jax.random.PRNGKey(6),
                  prompt_lens=gl)
    rp1, rp2 = (_gather_rows(comm, t) for t in (p1, p2))
    np.testing.assert_array_equal(
        rp1, rp2, err_msg="padded sampling not deterministic")
    np.testing.assert_array_equal(rp1[:, :3], padded)
    paccs = comm.allgather_obj(float(pacc))
    assert all(abs(x - paccs[0]) < 1e-6 for x in paccs), paccs


def scenario_lookup_decode(comm):
    """Prompt-lookup decoding ACROSS the process boundary: data=2 over
    2 single-device processes — the n-gram matcher is row-local but
    the acceptance pmin and verify-chunk collectives span processes.
    Tokens must equal the process-local greedy oracle."""
    from chainermn_tpu.models import (
        init_transformer, make_generate_fn, make_lookup_generate_fn,
        shard_params,
    )
    from chainermn_tpu.parallel import MeshConfig

    assert jax.process_count() == 2 and len(jax.local_devices()) == 1
    cfg = _tiny_cfg()
    host = init_transformer(jax.random.PRNGKey(7), cfg)
    import jax.numpy as jnp

    prompt = jnp.asarray(
        np.random.RandomState(9).randint(0, cfg.vocab_size, (4, 3)),
        jnp.int32)
    one = MeshConfig(data=1, devices=[jax.local_devices()[0]])
    ref = np.asarray(
        make_generate_fn(one, cfg, max_len=8)(
            shard_params(one, cfg, host), prompt))

    mc = MeshConfig(data=2, devices=jax.devices())
    sh = mc.sharding(("data", "expert"))
    params = shard_params(mc, cfg, host)
    got, mean_acc = make_lookup_generate_fn(
        mc, cfg, k=2, ngram=2, max_len=8, with_stats=True)(
        params, jax.device_put(prompt, sh))
    full = _gather_rows(comm, got)
    np.testing.assert_array_equal(
        full, ref, err_msg="cross-process lookup decode diverged")
    accs = comm.allgather_obj(float(mean_acc))
    assert all(abs(a - accs[0]) < 1e-6 for a in accs), accs

    # --- padded + eos composition over the same mesh
    lens = np.asarray([3, 2, 2, 3])
    padded = np.full((4, 3), 7, np.int32)
    rng = np.random.RandomState(14)
    for b, L in enumerate(lens):
        padded[b, 3 - L:] = rng.randint(0, cfg.vocab_size, L)
    pl = jnp.asarray(padded)
    kw = dict(max_len=8, eos_id=5, pad_id=0)
    ref_pe = np.asarray(
        make_generate_fn(one, cfg, **kw)(
            shard_params(one, cfg, host), pl, prompt_lens=lens))
    got_pe = make_lookup_generate_fn(mc, cfg, k=2, ngram=2, **kw)(
        params, jax.device_put(pl, sh),
        prompt_lens=jax.device_put(jnp.asarray(lens, jnp.int32), sh))
    np.testing.assert_array_equal(
        _gather_rows(comm, got_pe), ref_pe,
        err_msg="cross-process lookup padded+eos diverged")


def scenario_beam_search(comm):
    """Beam search ACROSS the process boundary: data=2 over 2
    single-device processes.  The per-step cache-reorder gather — the
    most layout-sensitive decode path (beams reindex their row's cache
    every step) — runs on batch-sharded rows, with ragged prompts'
    per-row offsets riding through the reorders.  Tokens AND scores
    must equal the process-local single-device oracle."""
    from chainermn_tpu.models import (
        init_transformer, make_beam_search_fn, shard_params,
    )
    from chainermn_tpu.parallel import MeshConfig

    assert jax.process_count() == 2 and len(jax.local_devices()) == 1
    cfg = _tiny_cfg()
    host = init_transformer(jax.random.PRNGKey(15), cfg)
    import jax.numpy as jnp

    lens = np.asarray([3, 1, 2, 3])
    padded = np.full((4, 3), 7, np.int32)
    rng = np.random.RandomState(16)
    for b, L in enumerate(lens):
        padded[b, 3 - L:] = rng.randint(0, cfg.vocab_size, L)
    pl = jnp.asarray(padded)
    kw = dict(beam_size=2, max_len=8, eos_id=5, length_penalty=0.6)

    one = MeshConfig(data=1, devices=[jax.local_devices()[0]])
    ref_t, ref_s = make_beam_search_fn(one, cfg, **kw)(
        shard_params(one, cfg, host), pl, prompt_lens=lens)

    mc = MeshConfig(data=2, devices=jax.devices())
    sh = mc.sharding(("data", "expert"))
    got_t, got_s = make_beam_search_fn(mc, cfg, **kw)(
        shard_params(mc, cfg, host), jax.device_put(pl, sh),
        prompt_lens=jax.device_put(jnp.asarray(lens, jnp.int32), sh))
    np.testing.assert_array_equal(
        _gather_rows(comm, got_t), np.asarray(ref_t),
        err_msg="cross-process beam tokens diverged")
    np.testing.assert_allclose(
        _gather_rows(comm, got_s, dtype=np.float32), np.asarray(ref_s),
        rtol=1e-5, atol=1e-5,
        err_msg="cross-process beam scores diverged")


def scenario_sp_ep_train(comm):
    """Sequence parallelism (ring attention's ppermute chain) and
    expert parallelism (Switch MoE's all-to-alls) ACROSS the process
    boundary: 2 processes x 1 device, seq=2 then expert=2 — the
    remaining collective kinds (ppermute-over-seq, all-to-all) join
    psum (tp_train) and pipe-ppermute (pp_train) in executed
    cross-process coverage.  Loss trajectories must equal the
    process-local single-device oracle."""
    import dataclasses

    from chainermn_tpu.parallel import MeshConfig

    assert jax.process_count() == 2 and len(jax.local_devices()) == 1
    base = _tiny_cfg()
    oracle = _tiny_transformer_losses(
        MeshConfig(data=1, devices=[jax.local_devices()[0]]), base)

    ring = dataclasses.replace(base, attention="ring")
    ring_losses = _tiny_transformer_losses(
        MeshConfig(seq=2, data=1, devices=jax.devices()), ring)
    np.testing.assert_allclose(ring_losses, oracle, rtol=1e-5, atol=1e-5,
                               err_msg="cross-process ring attention")
    all_ring = comm.allgather_obj(ring_losses)
    for other in all_ring[1:]:
        np.testing.assert_allclose(other, all_ring[0],
                                   rtol=1e-6, atol=1e-6)

    moe = dataclasses.replace(base, moe=True, n_experts=2)
    moe_oracle = _tiny_transformer_losses(
        MeshConfig(data=1, devices=[jax.local_devices()[0]]), moe)
    losses = _tiny_transformer_losses(
        MeshConfig(expert=2, data=1, devices=jax.devices()), moe)
    # step 1 is reduction-order-exact; later steps tolerate top-1
    # routing flips (a near-tie router logit can resolve differently
    # across mesh layouts after the first update — discrete routing,
    # not a transport bug; observed delta ~1e-3 relative)
    np.testing.assert_allclose(losses[:1], moe_oracle[:1],
                               rtol=1e-5, atol=1e-5,
                               err_msg="cross-process MoE all-to-all")
    np.testing.assert_allclose(losses, moe_oracle, rtol=5e-3,
                               err_msg="cross-process MoE diverged "
                                       "beyond routing-flip noise")

    all_losses = comm.allgather_obj(losses)
    for other in all_losses[1:]:
        np.testing.assert_allclose(other, all_losses[0],
                                   rtol=1e-6, atol=1e-6)


def scenario_vocab_tp_loss_chunk_train(comm):
    """Chunked-vocab cross-entropy COMPOSED with Megatron vocab TP,
    across the process boundary: model=2 over 2 single-device
    processes, so the per-chunk CE reductions and the vocab-sharded
    embedding/head collectives are real cross-process traffic.  Both
    features are exact rearrangements of the softmax, so the loss
    trajectory must equal a process-local single-device oracle with
    NEITHER enabled."""
    from chainermn_tpu.parallel import MeshConfig

    assert jax.process_count() == 2 and len(jax.local_devices()) == 1
    oracle = _tiny_transformer_losses(
        MeshConfig(data=1, devices=[jax.local_devices()[0]]),
        _tiny_cfg())
    losses = _tiny_transformer_losses(
        MeshConfig(model=2, data=1, devices=jax.devices()),
        _tiny_cfg(loss_chunk=8, vocab_parallel=True))
    np.testing.assert_allclose(losses, oracle, rtol=1e-5, atol=1e-5)
    all_losses = comm.allgather_obj(losses)
    for other in all_losses[1:]:
        np.testing.assert_allclose(other, all_losses[0],
                                   rtol=1e-6, atol=1e-6)


def scenario_alltoall_window(comm):
    """8-process alltoall_obj: the windowed pairwise-lane path (send
    look-ahead over the KV channel) must deliver every payload to the
    right peer at window sizes below, at, and above the round count
    (n-1 = 7) — window=1 being the strictly-alternating legacy
    pattern."""
    r = comm.inter_rank
    n = comm.inter_size
    assert n == 8, n
    for window in (1, 3, 8):
        sent = [{"from": r, "to": j, "w": window,
                 "pad": "x" * (50 * r + j)} for j in range(n)]
        got = comm.alltoall_obj(sent, window=window)
        assert [g["from"] for g in got] == list(range(n)), got
        assert all(g["to"] == r and g["w"] == window for g in got), got
        assert [len(g["pad"]) for g in got] == [50 * p + r
                                                for p in range(n)], got
    comm.barrier()


def scenario_elastic_membership(comm):
    """Membership epochs + generation fencing across REAL processes,
    entirely on the coordination-service KV store (no XLA collectives —
    membership must be agreeable exactly when the data plane died):
    survivors agree an epoch-numbered record collectively, fence their
    object channels to it, and a message published under the OLD
    generation is REJECTED (typed ``StaleGenerationError``) while the
    lane stays usable for current-generation traffic."""
    from chainermn_tpu.communicators._obj_channel import (
        KVObjectChannel,
        StaleGenerationError,
    )
    from chainermn_tpu.training.elastic import ElasticMembership

    me, n = comm.inter_rank, comm.inter_size
    boot = KVObjectChannel(tag="elastic-boot")
    # share the durable membership dir without array collectives
    path = boot.allgather(
        tempfile.mkdtemp(prefix="elastic_mp_") if me == 0 else None,
        list(range(n)), me)[0]

    m = ElasticMembership(comm, path=path)
    rec = m.agree()
    assert rec.epoch == 1 and rec.world_size == n, rec
    assert rec.members == list(range(n)), rec
    assert rec.rank_of(me) == me

    # rank 0 publishes BEFORE fencing — the pre-resize incarnation's
    # traffic, still sitting on the store when the new epoch starts
    chan = KVObjectChannel(tag="elastic-data")
    if me == 0:
        chan.send("stale-traffic", src=0, dst=1)
    m.fence(chan)
    assert chan.generation == rec.epoch
    if me == 0:
        # post-fence traffic rides the agreed generation
        chan.send({"epoch": rec.epoch}, src=0, dst=1)
    if me == 1:
        try:
            got = chan.recv(src=0, dst=1)
            raise AssertionError(
                f"stale-generation message was consumed: {got!r}")
        except StaleGenerationError:
            pass
        # the lane advanced past the rejected message — the fenced
        # world's own traffic is delivered normally
        assert chan.recv(src=0, dst=1) == {"epoch": 1}

    # a relaunch (fresh membership object, persisted file) bumps the
    # epoch past every incarnation that ever agreed one
    rec2 = ElasticMembership(comm, path=path).agree()
    assert rec2.epoch == 2, rec2
    rows = boot.allgather((rec.epoch, rec2.epoch), list(range(n)), me)
    assert all(r == (1, 2) for r in rows), rows


def scenario_preemption_sigterm(comm):
    """The PreemptionCheckpointer end-to-end FaultPlan drill: a REAL
    ``SIGTERM`` on ONE process only → the preemption flag OR-reduces
    collectively → both ranks save the SAME iteration and stop clean →
    resume bitwise-matches an uninterrupted run.

    Deliberately touches no cross-process XLA collectives: each process
    trains on its own local device over identical data (states are
    bitwise-identical by construction) while the flag OR-reduce,
    checkpoint agreement, and barriers ride the coordination-service KV
    channel — the preemption path must work exactly where the data
    plane cannot."""
    import optax

    import chainermn_tpu as cmn
    from chainermn_tpu.communicators._obj_channel import KVObjectChannel
    from chainermn_tpu.extensions import (
        PreemptionCheckpointer,
        create_multi_node_checkpointer,
    )
    from chainermn_tpu.models import init_mlp, mlp_apply, \
        softmax_cross_entropy
    from chainermn_tpu.testing import FaultInjector, FaultPlan

    me, n = comm.inter_rank, comm.inter_size

    class KVComm:
        """Control-plane communicator facade over the KV store only."""

        def __init__(self, tag):
            self._chan = KVObjectChannel(tag=tag)

        inter_rank = property(lambda self: jax.process_index())
        inter_size = property(lambda self: jax.process_count())
        size = property(lambda self: jax.process_count())
        mesh = None

        def allgather_obj(self, obj):
            return self._chan.allgather(
                obj, list(range(self.inter_size)), self.inter_rank)

        def barrier(self):
            self.allgather_obj(None)

    boot = KVObjectChannel(tag="presig-boot")
    path = boot.allgather(
        tempfile.mkdtemp(prefix="presig_") if me == 0 else None,
        list(range(n)), me)[0]

    local = cmn.create_communicator(
        "tpu_xla", devices=jax.local_devices())
    rng = np.random.RandomState(0)      # identical data on every rank
    data = [(rng.randn(4).astype(np.float32), np.int32(i % 2))
            for i in range(64)]

    def make_trainer(out, stop=12):
        it = cmn.SerialIterator(data, 16, shuffle=True, seed=5)
        params = init_mlp(jax.random.PRNGKey(0), [4, 8, 2])
        opt = cmn.create_multi_node_optimizer(optax.sgd(0.05), local)

        def loss_fn(p, x, y):
            return softmax_cross_entropy(mlp_apply(p, x), y)

        upd = cmn.StandardUpdater(it, opt, loss_fn, params, local)
        return cmn.Trainer(upd, (stop, "iteration"),
                           out=os.path.join(path, out))

    # arm A: the uninterrupted oracle
    ref = make_trainer("ref")
    ref.run()
    assert ref.updater.iteration == 12
    ref_params = jax.tree.map(np.asarray, ref.updater.params)

    # arm B: rank 0 gets a real SIGTERM at iteration 4; everyone else
    # learns of it through the collective flag reduce on the next tick
    kv1 = KVComm("presig-cp1")
    t1 = make_trainer("drill")
    cp = create_multi_node_checkpointer(
        kv1, os.path.join(path, "ckpt"))
    t1.extend(PreemptionCheckpointer(cp, kv1))
    inj = FaultInjector(
        FaultPlan(sigterm_at_iteration=4, sigterm_rank=0), comm=kv1)
    t1.extend(inj)
    t1.run()
    if me == 0:
        assert ("sigterm", 4) in inj.fired, inj.fired
    else:
        assert not inj.fired, inj.fired
    assert "preemption" in (t1.stop_reason or ""), t1.stop_reason
    assert t1.updater.iteration == 5, t1.updater.iteration
    iters = kv1.allgather_obj(sorted(cp._local_iterations()))
    assert all(x == [5] for x in iters), iters

    # arm C: resume and finish — bitwise vs the oracle
    kv2 = KVComm("presig-cp2")
    t2 = make_trainer("resume")
    cp2 = create_multi_node_checkpointer(
        kv2, os.path.join(path, "ckpt"))
    assert cp2.maybe_load(t2.updater, t2) == 5
    assert cp2.last_resume_mode == "exact"
    t2.run()
    assert t2.updater.iteration == 12
    for a, b in zip(jax.tree.leaves(ref_params),
                    jax.tree.leaves(jax.tree.map(
                        np.asarray, t2.updater.params))):
        np.testing.assert_array_equal(
            a, b, err_msg="resumed params differ from the "
                          "uninterrupted run")
    kv2.barrier()


def scenario_resize_live(comm):
    """The LIVE-resize control plane across REAL processes, KV-only
    (the data plane may be mid-reconfiguration, so nothing here may
    ride an array collective): an intent posted by ONE rank
    (``post_resize_intent``) is seen by every rank, the OR-agreement
    resolves identically everywhere, the membership epoch bumps and
    fences channel generations so pre-resize traffic is REJECTED, and
    the consumed intent is cleared.  The mesh re-formation itself is
    single-process (tests/extension_tests/test_live_resize.py) or
    TPU-gated — this drill is the cross-process half."""
    from chainermn_tpu.communicators._obj_channel import (
        KVObjectChannel,
        StaleGenerationError,
    )
    from chainermn_tpu.training.elastic import (
        ElasticMembership,
        ResizeController,
        post_resize_intent,
    )

    me, n = comm.inter_rank, comm.inter_size
    boot = KVObjectChannel(tag="resize-boot")
    path = boot.allgather(
        tempfile.mkdtemp(prefix="resize_mp_") if me == 0 else None,
        list(range(n)), me)[0]
    membership = ElasticMembership(comm, path=path)
    ctrl = ResizeController(
        comm_factory=lambda w: comm, optimizer_factory=lambda c: None,
        membership=membership)

    # only the LAST rank posts the intent — every rank must still see
    # it (external tooling posts from wherever it runs)
    assert ctrl._kv_intent(comm) is None
    if me == n - 1:
        post_resize_intent(n, reason="mp drill")
    _kv_barrier(comm, boot)
    assert ctrl._kv_intent(comm) == n

    # the controller's boundary agreement: a rank with NO local intent
    # resolves to the same world as the poster.  KV-only here — this
    # container's CPU backend has no cross-process array collectives,
    # which is exactly the situation the control plane must survive
    mine = ctrl._kv_intent(comm) if me == n - 1 else None
    rows = boot.allgather(mine, list(range(n)), me)
    seen = [r for r in rows if r is not None]
    assert seen and max(seen) == n, rows

    # epoch + fence: the step the live resize performs before the mesh
    # re-forms — stale-generation traffic must bounce afterwards
    rec = membership.agree()
    assert rec.epoch == 1 and rec.members == list(range(n)), rec
    chan = KVObjectChannel(tag="resize-data")
    if me == 0:
        chan.send("pre-resize", src=0, dst=1)   # old-generation traffic
    membership.fence(chan)
    assert chan.generation == rec.epoch
    if me == 0:
        chan.send({"epoch": rec.epoch}, src=0, dst=1)
    if me == 1:
        try:
            got = chan.recv(src=0, dst=1)
            raise AssertionError(
                f"pre-resize message survived the fence: {got!r}")
        except StaleGenerationError:
            pass
        assert chan.recv(src=0, dst=1) == {"epoch": 1}

    # the agreed intent is consumed by EVERY rank (idempotent delete —
    # the controller clears before its collectives so no rank can
    # re-read a stale intent on its next cadence tick)
    ctrl._clear_kv_intent(comm)
    _kv_barrier(comm, boot)
    assert ctrl._kv_intent(comm) is None
    _kv_barrier(comm, boot)


SCENARIOS = {
    name[len("scenario_"):]: fn
    for name, fn in list(globals().items())
    if name.startswith("scenario_")
}


def main():
    addr, n, i, scenario = (sys.argv[1], int(sys.argv[2]), int(sys.argv[3]),
                            sys.argv[4])
    import chainermn_tpu

    chainermn_tpu.init_distributed(
        coordinator_address=addr, num_processes=n, process_id=i)
    comm = chainermn_tpu.create_communicator("tpu_xla")
    SCENARIOS[scenario](comm)
    print(f"WORKER_OK {i} {scenario}", flush=True)


if __name__ == "__main__":
    main()
