"""Harness that spawns real multi-process JAX CPU clusters per scenario —
the TPU-native ``mpiexec -n 2`` (SURVEY.md §4: the reference ran its whole
suite under mpiexec; here each worker is an OS process with one CPU device
joined via ``jax.distributed.initialize``)."""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = os.path.join(os.path.dirname(__file__), "_mp_worker.py")
_REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", ".."))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _worker_env(devices_per_proc: int = 1) -> dict:
    env = dict(os.environ)
    # plain CPU devices; scrub TPU-plugin and parent-test mesh settings
    # so each worker builds its own world
    for k in list(env):
        if k.startswith(("TPU_", "LIBTPU", "PJRT_", "JAX_", "XLA_")):
            env.pop(k)
    env["JAX_PLATFORMS"] = "cpu"
    if devices_per_proc > 1:
        # multi-device processes: global device ids interleave as
        # (proc 0: 0..d-1), (proc 1: d..2d-1), ... so mesh-minor axes
        # stay process-local and mesh-major axes span the boundary
        env["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={devices_per_proc}")
    env["PYTHONPATH"] = _REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    return env


@pytest.fixture(scope="session")
def mp_run():
    """Run ``scenario`` across ``nprocs`` real processes; fail the test on
    any non-zero worker exit, with both workers' output in the report."""

    def run(scenario: str, nprocs: int = 2, timeout: int = 180,
            devices_per_proc: int = 1):
        addr = f"localhost:{_free_port()}"
        env = _worker_env(devices_per_proc)
        procs = [
            subprocess.Popen(
                [sys.executable, _WORKER, addr, str(nprocs), str(i),
                 scenario],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=env, cwd=_REPO_ROOT)
            for i in range(nprocs)
        ]
        outputs, codes = [], []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=timeout)
                outputs.append(out)
                codes.append(p.returncode)
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            for p in procs:
                out, _ = p.communicate()
                outputs.append(out)
            pytest.fail(
                f"scenario {scenario!r} timed out after {timeout}s "
                "(likely a cross-process collective deadlock)\n"
                + "\n---\n".join(outputs))
        if any(codes):
            report = "\n".join(
                f"--- worker {i} rc={codes[i]} ---\n{outputs[i]}"
                for i in range(nprocs))
            pytest.fail(f"scenario {scenario!r} failed:\n{report}")
        for i, out in enumerate(outputs):
            assert f"WORKER_OK {i} {scenario}" in out, (
                f"worker {i} exited 0 without the OK marker:\n{out}")

    return run
