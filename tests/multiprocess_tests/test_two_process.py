"""Two-process cluster tests — every ``inter_size > 1`` code path that
single-process tests cannot reach, run as real OS processes (the
reference's ``mpiexec -n 2 pytest``; SURVEY.md §4)."""

import pytest


@pytest.mark.multiprocess
class TestTwoProcess:
    def test_topology_contract(self, mp_run):
        mp_run("topology")

    def test_obj_collectives(self, mp_run):
        mp_run("obj_collectives")

    def test_p2p_obj_channel(self, mp_run):
        mp_run("p2p_obj")

    def test_array_collectives(self, mp_run):
        mp_run("array_collectives")

    def test_scatter_dataset(self, mp_run):
        mp_run("scatter_dataset")

    def test_checkpoint_agreement_resume(self, mp_run):
        mp_run("checkpoint")

    def test_checkpoint_async(self, mp_run):
        mp_run("checkpoint_async")

    def test_fallback_resume(self, mp_run):
        # one rank's shard bytes flipped -> every process falls back to
        # the previous verified set; damaged file quarantined
        mp_run("fallback_resume")

    @pytest.mark.drill
    def test_watchdog_stall(self, mp_run):
        # rank 1 stalls past the threshold: self-report + survivor
        # detection through the cross-process KV heartbeats
        mp_run("watchdog_stall", timeout=240)

    def test_evaluator_averaging(self, mp_run):
        mp_run("evaluator")

    def test_broadcast_iterator(self, mp_run):
        mp_run("broadcast_iterator")

    def test_observation_aggregator(self, mp_run):
        mp_run("observation_aggregator")

    def test_split(self, mp_run):
        # 4 processes: each even/odd subgroup spans 2 processes, forcing
        # the KV group collectives (whole-world ones would deadlock)
        mp_run("split", nprocs=4)

    def test_vocab_tp_loss_chunk_train(self, mp_run):
        # chunked-vocab CE + vocab-parallel embedding over model=2
        # spanning processes, loss-equal to the process-local oracle
        mp_run("vocab_tp_loss_chunk_train", timeout=300)

    def test_alltoall_window(self, mp_run):
        # 8 processes: the windowed pairwise-lane alltoall at window
        # sizes below, at, and above the round count
        mp_run("alltoall_window", nprocs=8, timeout=300)

    def test_snapshot(self, mp_run):
        mp_run("snapshot")

    def test_allreduce_persistent(self, mp_run):
        mp_run("allreduce_persistent")

    def test_dp_train_step(self, mp_run):
        mp_run("dp_train")

    def test_preemption_collective_flag(self, mp_run):
        mp_run("preemption")

    @pytest.mark.drill
    def test_elastic_membership(self, mp_run):
        # epoch-numbered membership agreement + generation fencing over
        # the KV store only; a stale-generation message is REJECTED
        mp_run("elastic_membership", timeout=240)

    @pytest.mark.drill
    def test_preemption_sigterm_drill(self, mp_run):
        # real SIGTERM on one process -> OR-reduced collective save ->
        # both ranks stop clean -> resume bitwise-matches uninterrupted
        mp_run("preemption_sigterm", timeout=300)

    @pytest.mark.drill
    def test_resize_live_control_plane(self, mp_run):
        # live-resize coordination KV-only: one rank's posted intent
        # agreed by all -> epoch bump + generation fence rejects
        # pre-resize traffic -> intent consumed once
        mp_run("resize_live", timeout=240)

    def test_zero1_checkpoint(self, mp_run):
        mp_run("zero1_checkpoint")

    def test_fsdp_train(self, mp_run):
        mp_run("fsdp_train")

    def test_tp_train(self, mp_run):
        # per-layer TP psum crosses the process boundary (model=2 over
        # 2 single-device processes)
        mp_run("tp_train")

    def test_pp_train(self, mp_run):
        # 2 procs x 2 devices: pipe (mesh-major) ppermute crosses the
        # process boundary; model stays local; + the model=2,data=2 shape
        mp_run("pp_train", devices_per_proc=2, timeout=300)

    def test_sp_ep_train(self, mp_run):
        # ring-attention ppermute chain and MoE all-to-alls cross the
        # process boundary (seq=2 / expert=2 over 2 processes)
        mp_run("sp_ep_train", timeout=300)

    def test_decode(self, mp_run):
        # per-token seq-KV softmax merges and vocab-parallel lookup/
        # gather collectives cross the process boundary; tokens equal
        # the process-local oracle exactly
        mp_run("decode", timeout=300)

    def test_speculative_decode(self, mp_run):
        # the acceptance pmin + verify-chunk collectives run inside a
        # cross-process while_loop; tokens equal the local oracle
        mp_run("speculative_decode", timeout=300)

    def test_speculative_sampling(self, mp_run):
        # acceptance pmin + shard-decorrelated keys + while-loop key
        # carry across the boundary; same-key determinism
        mp_run("speculative_sampling", timeout=300)

    def test_lookup_decode(self, mp_run):
        # the draft-free proposer: row-local n-gram matching, shared
        # acceptance pmin and verify chunk across the boundary; plus
        # the padded+eos composition phase
        mp_run("lookup_decode", timeout=300)

    def test_beam_search(self, mp_run):
        # the per-step cache-reorder gather over batch-sharded ragged
        # rows; tokens AND scores equal the local oracle
        mp_run("beam_search", timeout=300)

    def test_shuffle_datablock(self, mp_run):
        mp_run("shuffle_datablock")

    def test_shuffle_datablock_four_process(self, mp_run):
        # n>2 exercises the staggered pairwise exchange rounds
        mp_run("shuffle_datablock", nprocs=4)
