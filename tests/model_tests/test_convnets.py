"""Convnet zoo: shapes, finiteness, DP-train smoke for each arch."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from chainermn_tpu.models import (
    ConvNetConfig,
    convnet_apply,
    init_convnet,
    softmax_cross_entropy,
)
from chainermn_tpu.parallel import MeshConfig

from chainermn_tpu.testing import requires_vma as _requires_vma

# Pre-vma shard_map (old check_rep) cannot express what these tests pin:
# grads of replicated outputs taken inside shard_map over-count by the
# axis size, replicated out_specs can't be inferred through gathers, and
# scan carries may not gain replication.  vma typing (jax >= 0.7) is the
# semantic fix; on older jax the cases below are undefined, not wrong.
requires_vma = _requires_vma("requires vma-typed shard_map AD semantics")

B, HW, C = 8, 32, 8


@pytest.mark.parametrize("arch", ["alex", "nin", "vgg16", "googlenet"])
def test_forward_shape(arch):
    cfg = ConvNetConfig(arch=arch, num_classes=C, dtype="float32",
                        head="gap")
    params = init_convnet(jax.random.PRNGKey(0), cfg)
    x = jnp.asarray(np.random.RandomState(0).randn(B, HW, HW, 3),
                    jnp.float32)
    logits = convnet_apply(cfg, params, x)
    assert logits.shape == (B, C)
    assert logits.dtype == jnp.float32
    assert np.isfinite(np.asarray(logits)).all()


def test_unknown_arch_rejected():
    with pytest.raises(ValueError):
        ConvNetConfig(arch="resnext")


@requires_vma
def test_dp_step_reduces_loss():
    import optax

    cfg = ConvNetConfig(arch="nin", num_classes=4, dtype="float32",
                        head="gap")
    params = init_convnet(jax.random.PRNGKey(0), cfg)
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(16, HW, HW, 3), jnp.float32)
    y = jnp.asarray(rng.randint(0, 4, 16))
    mc = MeshConfig(data=8)
    opt = optax.adam(3e-3)
    opt_state = opt.init(params)

    grad_fn = jax.shard_map(
        lambda p, xx, yy: jax.value_and_grad(
            lambda q: jax.lax.pmean(
                softmax_cross_entropy(convnet_apply(cfg, q, xx), yy),
                "data"))(p),
        mesh=mc.mesh, in_specs=(P(), P("data"), P("data")),
        out_specs=(P(), P()))

    @jax.jit
    def step(p, s):
        loss, g = grad_fn(p, x, y)
        u, s = opt.update(g, s, p)
        return optax.apply_updates(p, u), s, loss

    losses = []
    for _ in range(10):
        params, opt_state, loss = step(params, opt_state)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


@pytest.mark.parametrize("arch,fin", [("alex", 9216), ("vgg16", 25088)])
def test_reference_flatten_head_parity(arch, fin):
    """head="flatten" at the native insize reproduces the reference FC
    fan-ins (alex 256*6*6=9216 @227, vgg16 512*7*7=25088 @224) and a
    consistent end-to-end shape (checked via eval_shape, no FLOPs)."""
    cfg = ConvNetConfig(arch=arch, num_classes=C, dtype="float32")
    params = init_convnet(jax.random.PRNGKey(0), cfg)
    fc = [p for p in params if p and p["w"].ndim == 2][0]
    assert fc["w"].shape == (fin, 4096)
    out = jax.eval_shape(
        lambda p, x: convnet_apply(cfg, p, x), params,
        jax.ShapeDtypeStruct((2, cfg.insize, cfg.insize, 3), jnp.float32))
    assert out.shape == (2, C)


def test_googlenet_aux_heads():
    """Reference geometry at 224px: aux taps flatten 4·4·128=2048, all
    three logit sets have class shape (checked via eval_shape); with_aux
    on other archs raises."""
    cfg = ConvNetConfig(arch="googlenet", num_classes=C, dtype="float32")
    params = init_convnet(jax.random.PRNGKey(0), cfg)
    assert params["aux_4a"]["fc1"]["w"].shape == (2048, 1024)
    assert params["fc"]["w"].shape == (1024, C)
    outs = jax.eval_shape(
        lambda p, x: convnet_apply(cfg, p, x, with_aux=True), params,
        jax.ShapeDtypeStruct((2, 224, 224, 3), jnp.float32))
    assert [o.shape for o in outs] == [(2, C)] * 3

    with pytest.raises(ValueError, match="with_aux"):
        convnet_apply(ConvNetConfig(arch="alex"), [], None, with_aux=True)


def test_googlenet_gap_aux_small_input():
    """Size-robust head: aux classifiers GAP (fc1 128->1024) and run at
    32px with finite values."""
    cfg = ConvNetConfig(arch="googlenet", num_classes=C, dtype="float32",
                        head="gap")
    params = init_convnet(jax.random.PRNGKey(0), cfg)
    assert params["aux_4a"]["fc1"]["w"].shape == (128, 1024)
    x = jnp.asarray(np.random.RandomState(0).randn(2, HW, HW, 3),
                    jnp.float32)
    logits, a1, a2 = convnet_apply(cfg, params, x, with_aux=True)
    for o in (logits, a1, a2):
        assert o.shape == (2, C)
        assert np.isfinite(np.asarray(o)).all()


def test_flatten_head_rejects_collapsing_size():
    with pytest.raises(ValueError, match="collapses"):
        init_convnet(jax.random.PRNGKey(0),
                     ConvNetConfig(arch="alex", num_classes=C,
                                   image_size=32))
    with pytest.raises(ValueError, match="224"):
        init_convnet(jax.random.PRNGKey(0),
                     ConvNetConfig(arch="googlenet", num_classes=C,
                                   image_size=112))
