"""ZeRO-3 / FSDP (``TransformerConfig(fsdp=True)``): parameters, grads
and optimiser state shard over ``data`` at rest; each layer all-gathers
its weights just-in-time and AD reduce-scatters the grads.  Sharding is
an implementation detail — training must match the dense (replicated)
run numerically on every mesh it composes with."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from chainermn_tpu.models import (
    TransformerConfig,
    init_transformer,
    make_generate_fn,
    make_train_step,
    shard_params,
)
from chainermn_tpu.parallel import MeshConfig
from chainermn_tpu.training import shard_opt_state

from chainermn_tpu.testing import requires_vma as _requires_vma

# The flagship transformer's custom VJPs read jax.typeof(...).vma to
# place their psums; TransformerConfig deliberately refuses to construct
# on pre-vma jax (models/transformer.py).  Nothing in this module can
# run without it.
pytestmark = _requires_vma(
    "requires vma-typed shard_map (TransformerConfig refuses pre-vma jax)")

VOCAB, B, T = 64, 8, 16


def tiny_cfg(**kw):
    base = dict(
        vocab_size=VOCAB, d_model=32, n_heads=4, d_head=8, d_ff=64,
        n_layers=2, max_seq=T, attention="local", dtype="float32",
        remat=False,
    )
    base.update(kw)
    return TransformerConfig(**base)


def _tokens(seed=0):
    return jnp.asarray(
        np.random.RandomState(seed).randint(0, VOCAB, (B, T + 1)),
        jnp.int32)


def _train(cfg, mc, steps=3):
    params = shard_params(
        mc, cfg,
        init_transformer(jax.random.PRNGKey(0), cfg,
                         mc.mesh.shape.get("pipe", 1)))
    opt = optax.adam(1e-2)
    opt_state = shard_opt_state(opt, params)
    step = make_train_step(mc, cfg, opt)
    toks = _tokens()
    losses = []
    for _ in range(steps):
        params, opt_state, loss = step(
            params, opt_state, toks[:, :T], toks[:, 1:])
        losses.append(float(loss))
    if cfg.fsdp:
        # moments must STAY shard-width through the jitted update
        assert opt_state[0].mu["blocks"]["w1"].sharding.spec == \
            params["blocks"]["w1"].sharding.spec
    return losses, jax.tree.map(
        lambda a: np.asarray(jax.device_get(a)), params)


# every parallel axis FSDP has to compose with: pure DP, TP+SP (ring),
# EP/MoE, GPipe, and the 1F1B schedule
CASES = [
    (dict(data=8), {}),
    (dict(data=2, model=2, seq=2), dict(attention="ring")),
    (dict(data=4, expert=2), dict(moe=True, n_experts=4)),
    (dict(data=2, pipe=2, model=2),
     dict(n_layers=4, num_microbatches=2)),
    (dict(data=4, pipe=2),
     dict(n_layers=4, num_microbatches=2, pipeline_schedule="1f1b")),
    (dict(data=4, pipe=2),
     dict(n_layers=8, num_microbatches=2,
          pipeline_schedule="interleaved", virtual_pipe=2)),
]


@pytest.mark.parametrize(
    "axes,extra", CASES, ids=[str(a) for a, _ in CASES])
def test_fsdp_matches_dense(axes, extra):
    mc = MeshConfig(**axes)
    dense = tiny_cfg(**extra)
    losses_d, params_d = _train(dense, mc)
    losses_f, params_f = _train(
        dataclasses.replace(dense, fsdp=True), mc)
    np.testing.assert_allclose(losses_f, losses_d, rtol=1e-5, atol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            a, b, rtol=2e-5, atol=2e-5),
        params_f, params_d)


def test_fsdp_at_rest_sharding():
    """The point of ZeRO-3: each device holds 1/N of every matrix (and
    its grads/moments follow).  Check the placed arrays' local shards."""
    mc = MeshConfig(data=8)
    cfg = tiny_cfg(fsdp=True)
    params = shard_params(
        mc, cfg, init_transformer(jax.random.PRNGKey(0), cfg))
    w1 = params["blocks"]["w1"]           # (1, L, D, F)
    local = w1.addressable_shards[0].data.shape
    assert local == (1, cfg.n_layers, cfg.d_model // 8, cfg.d_ff), local
    wo = params["blocks"]["wo"]           # (1, L, H, Dh, D)
    assert wo.addressable_shards[0].data.shape[-1] == cfg.d_model // 8
    # embeddings and norms stay replicated
    assert params["embed"].addressable_shards[0].data.shape == \
        params["embed"].shape
    assert params["blocks"]["ln1"].addressable_shards[0].data.shape == \
        params["blocks"]["ln1"].shape
    # ZeRO-3's other 2/3: optimiser moments must be shard-width too —
    # plain jit(init) would replicate them (zeros_like carries no data
    # dependence for sharding propagation); shard_opt_state pins them
    opt_state = shard_opt_state(optax.adam(1e-2), params)
    mu_w1 = opt_state[0].mu["blocks"]["w1"]
    assert mu_w1.addressable_shards[0].data.shape == \
        (1, cfg.n_layers, cfg.d_model // 8, cfg.d_ff)


def test_fsdp_bf16_wire_dtype_trains():
    """bf16 gathers/reduce-scatters (the allreduce_grad_dtype analogue)
    stay close to the fp32-wire run and the loss still falls."""
    mc = MeshConfig(data=8)
    losses_f, _ = _train(tiny_cfg(fsdp=True), mc)
    losses_b, _ = _train(
        tiny_cfg(fsdp=True, fsdp_wire_dtype="bfloat16"), mc)
    assert losses_b[-1] < losses_b[0]
    np.testing.assert_allclose(losses_b, losses_f, rtol=0.05, atol=0.05)


def test_fsdp_decode_raises():
    mc = MeshConfig(data=8)
    with pytest.raises(ValueError, match="fsdp is a training-path"):
        make_generate_fn(mc, tiny_cfg(fsdp=True), max_len=T)


def test_fsdp_wire_dtype_requires_fsdp():
    with pytest.raises(ValueError, match="fsdp=False"):
        tiny_cfg(fsdp_wire_dtype="bfloat16")


def test_fsdp_dmodel_divisibility():
    mc = MeshConfig(data=8)
    cfg = tiny_cfg(fsdp=True, d_model=36)
    with pytest.raises(ValueError, match="divisible by the data"):
        make_train_step(mc, cfg, optax.adam(1e-2))


def test_moe_fsdp_at_rest_sharding():
    """MoE expert stacks also rest at 1/N d_model width (loss parity
    with dense is CASES[2] in test_fsdp_matches_dense)."""
    mc = MeshConfig(data=2, expert=2, devices=jax.devices()[:4])
    cfg = tiny_cfg(moe=True, n_experts=4, fsdp=True)
    params = shard_params(
        mc, cfg, init_transformer(jax.random.PRNGKey(0), cfg))
    w1 = params["blocks"]["w1"]           # (pipe, L, E, D, F)
    assert w1.addressable_shards[0].data.shape[3] == cfg.d_model // 2, \
        w1.addressable_shards[0].data.shape
