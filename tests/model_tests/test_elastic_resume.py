"""Elastic mesh resume: a training state snapshotted on one topology
must continue on a different one — different axis sizes, a different
pipe grouping (blocks regrouped), a different at-rest layout (fsdp) —
with the same loss trajectory.  Beyond the reference: ChainerMN's
checkpointer required restart at the identical world size."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from chainermn_tpu.models import (
    TransformerConfig,
    init_transformer,
    make_train_step,
    regroup_blocks,
    reshard_train_state,
    shard_params,
)
from chainermn_tpu.parallel import MeshConfig

from chainermn_tpu.testing import requires_vma as _requires_vma

# Pre-vma shard_map (old check_rep) cannot express what these tests pin:
# grads of replicated outputs taken inside shard_map over-count by the
# axis size, replicated out_specs can't be inferred through gathers, and
# scan carries may not gain replication.  vma typing (jax >= 0.7) is the
# semantic fix; on older jax the cases below are undefined, not wrong.
requires_vma = _requires_vma("requires vma-typed shard_map AD semantics")

VOCAB, B, T = 64, 8, 16


def tiny_cfg(**kw):
    base = dict(
        vocab_size=VOCAB, d_model=32, n_heads=4, d_head=8, d_ff=64,
        n_layers=4, max_seq=T, attention="local", dtype="float32",
        remat=False,
    )
    base.update(kw)
    return TransformerConfig(**base)


def tokens(seed=0):
    return jnp.asarray(
        np.random.RandomState(seed).randint(0, VOCAB, (B, T + 1)),
        jnp.int32)


def _layer_tagged_blocks(n_layers, pipe, virtual=1):
    """Toy block stack whose single leaf's value IS its global layer
    index, grouped the way init_transformer groups real blocks."""
    layers = jnp.arange(n_layers, dtype=jnp.float32)[:, None]  # base (1,)
    if virtual > 1:
        lpc = n_layers // (pipe * virtual)
        return {"w": layers.reshape(virtual, pipe, lpc, 1).swapaxes(0, 1)}
    return {"w": layers.reshape(pipe, n_layers // pipe, 1)}


@pytest.mark.parametrize("src,dst", [
    ((1, 1), (2, 1)),
    ((2, 1), (4, 1)),
    ((1, 1), (2, 2)),
    ((2, 2), (1, 1)),
    ((2, 2), (4, 1)),
])
def test_regroup_blocks_preserves_layer_order(src, dst):
    L = 8
    a = _layer_tagged_blocks(L, *src)
    b = regroup_blocks(a, src[0], dst[0], src[1], dst[1])
    expect = _layer_tagged_blocks(L, *dst)
    np.testing.assert_array_equal(np.asarray(b["w"]),
                                  np.asarray(expect["w"]))
    # round trip back is the identity
    back = regroup_blocks(b, dst[0], src[0], dst[1], src[1])
    np.testing.assert_array_equal(np.asarray(back["w"]),
                                  np.asarray(a["w"]))


def test_regroup_blocks_shape_mismatch_raises():
    a = _layer_tagged_blocks(8, 2)
    with pytest.raises(ValueError, match="from_pipe"):
        regroup_blocks(a, 4, 2)
    with pytest.raises(ValueError, match="divisible"):
        regroup_blocks(a, 2, 3)


def _run_steps(step, params, opt_state, toks, n):
    x, y = toks[:, :T], toks[:, 1:]
    losses = []
    for _ in range(n):
        params, opt_state, loss = step(params, opt_state, x, y)
        losses.append(float(loss))
    return params, opt_state, losses


RESUME_TARGETS = [
    ("data8", dict(), dict(data=8)),
    ("pipe2_gpipe", dict(num_microbatches=2), dict(pipe=2, data=2)),
    ("pipe2_interleaved",
     dict(pipeline_schedule="interleaved", virtual_pipe=2,
          num_microbatches=2),
     dict(pipe=2, data=2)),
    ("fsdp", dict(fsdp=True), dict(data=8)),
    ("tp_seq", dict(attention="ring"), dict(model=2, seq=2, data=2)),
    # the embed re-lays from replicated to vocab-sharded on resume
    ("vocab_tp", dict(attention="ring", vocab_parallel=True),
     dict(model=2, seq=2, data=2)),
]


@pytest.mark.parametrize(
    "name,cfg_kw,axes", RESUME_TARGETS,
    ids=[t[0] for t in RESUME_TARGETS])
@requires_vma
def test_elastic_resume_matches_uninterrupted(name, cfg_kw, axes):
    """Train on a data=4 mesh, snapshot mid-run, reshard to a different
    topology and continue: the loss trajectory must match the
    uninterrupted data=4 run (schedules/shardings are implementation
    details of the same math)."""
    toks = tokens(7)
    opt = optax.adam(1e-2)

    cfg_a = tiny_cfg()
    mc_a = MeshConfig(data=4, devices=jax.devices()[:4])
    params = shard_params(
        mc_a, cfg_a, init_transformer(jax.random.PRNGKey(0), cfg_a))
    opt_state = jax.jit(opt.init)(params)
    step_a = make_train_step(mc_a, cfg_a, opt)
    params, opt_state, pre = _run_steps(step_a, params, opt_state, toks, 2)

    # host snapshot, BEFORE the donated buffers are consumed further
    host_p = jax.tree.map(np.asarray, params)
    host_o = jax.tree.map(np.asarray, opt_state)

    # uninterrupted continuation on mesh A
    _, _, ref = _run_steps(step_a, params, opt_state, toks, 3)

    # resharded continuation on mesh B
    cfg_b = tiny_cfg(**cfg_kw)
    n_dev = int(np.prod(list(axes.values())))
    mc_b = MeshConfig(**axes, devices=jax.devices()[:n_dev])
    pipe_b = axes.get("pipe", 1)
    p_b, o_b = reshard_train_state(
        mc_b, cfg_b, opt, host_p, host_o, from_pipe=1)
    assert pipe_b == mc_b.mesh.shape.get("pipe", 1)
    step_b = make_train_step(mc_b, cfg_b, opt)
    _, _, got = _run_steps(step_b, p_b, o_b, toks, 3)

    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=1e-5,
                               err_msg=f"resume target {name}")
