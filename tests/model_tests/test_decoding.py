"""KV-cache autoregressive decoding: step-by-step cached logits must
match the full (non-cached) forward at every position, greedy generation
must be self-consistent, and the cache must carry GQA's shared-head
width.  Covers single-device, DP+TP meshes, GQA, and virtual-pipe
packed params."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from chainermn_tpu.models import (
    TransformerConfig,
    init_transformer,
    make_forward_fn,
    make_generate_fn,
    shard_params,
)
from chainermn_tpu.models.decoding import _decode_step
from chainermn_tpu.parallel import MeshConfig

from chainermn_tpu.testing import requires_vma as _requires_vma

# The flagship transformer's custom VJPs read jax.typeof(...).vma to
# place their psums; TransformerConfig deliberately refuses to construct
# on pre-vma jax (models/transformer.py).  Nothing in this module can
# run without it.
pytestmark = _requires_vma(
    "requires vma-typed shard_map (TransformerConfig refuses pre-vma jax)")

VOCAB, B, T = 64, 4, 16


def tiny_cfg(**kw):
    base = dict(
        vocab_size=VOCAB, d_model=32, n_heads=4, d_head=8, d_ff=64,
        n_layers=2, max_seq=T, attention="local", dtype="float32",
        remat=False,
    )
    base.update(kw)
    return TransformerConfig(**base)


def prompt(seed=0, length=T):
    return jnp.asarray(
        np.random.RandomState(seed).randint(0, VOCAB, (B, length)),
        jnp.int32)


def _cached_logits_all_positions(cfg, params, toks, mc):
    """Teacher-forced decode: feed toks one at a time through the cached
    step, collecting the logits at each position."""
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from chainermn_tpu.models import param_specs

    def body(params, toks):
        Bl, Tl = toks.shape
        mp = 1
        for d in ("model",):
            mp *= lax.axis_size(d)
        Hkvl = cfg.kv_heads // mp
        from chainermn_tpu.models.decoding import _make_cache

        R = lax.axis_size("seq")
        caches = _make_cache(cfg, Bl, Tl // R, Hkvl, cfg.n_layers)

        def step(caches, t):
            logits, caches = _decode_step(cfg, params, caches,
                                          toks[:, t], t)
            return caches, logits

        _, logits = lax.scan(step, caches, jnp.arange(Tl))
        return logits.transpose(1, 0, 2)      # (B, T, V)

    fn = jax.jit(jax.shard_map(
        body, mesh=mc.mesh,
        in_specs=(param_specs(cfg), P(("data", "expert"))),
        out_specs=P(("data", "expert"))))
    return fn(params, toks)


@pytest.mark.parametrize("axes,kw", [
    (dict(data=1), {}),
    (dict(data=4, model=2), {}),
    (dict(data=4, model=2), dict(n_kv_heads=2)),
    (dict(data=2, seq=2), {}),
    (dict(data=2, seq=2, model=2), dict(n_kv_heads=2)),
    (dict(data=2, seq=2), dict(attention_window=6)),
], ids=["single", "dp-tp", "gqa-tp", "seq-kv", "seq-kv-gqa-tp",
        "seq-kv-window"])
def test_cached_matches_full_forward(axes, kw):
    cfg = tiny_cfg(**kw)
    n_dev = int(np.prod(list(axes.values())))
    mc = MeshConfig(**axes, devices=jax.devices()[:n_dev])
    host = init_transformer(jax.random.PRNGKey(0), cfg)
    toks = prompt()
    # oracle on a seq=1 mesh: attention="local" under a real seq axis
    # would be shard-local, not full causal
    one = MeshConfig(data=1, devices=jax.devices()[:1])
    full = make_forward_fn(one, cfg)(shard_params(one, cfg, host), toks)
    cached = _cached_logits_all_positions(
        cfg, shard_params(mc, cfg, host), toks, mc)
    np.testing.assert_allclose(
        np.asarray(cached), np.asarray(full), rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("top_k", [1, 2])
def test_moe_cached_matches_full_forward(top_k):
    """MoE decode must route the way the model was TRAINED (a top-2
    checkpoint decoded top-1 silently diverges): with ample capacity the
    teacher-forced cached logits equal the training forward for both
    router modes."""
    cfg = tiny_cfg(moe=True, n_experts=2, router_top_k=top_k,
                   capacity_factor=4.0)
    mc = MeshConfig(data=2, expert=2, devices=jax.devices()[:4])
    params = shard_params(
        mc, cfg, init_transformer(jax.random.PRNGKey(3), cfg))
    toks = prompt(seed=5)
    full = make_forward_fn(mc, cfg)(params, toks)
    cached = _cached_logits_all_positions(cfg, params, toks, mc)
    np.testing.assert_allclose(
        np.asarray(cached), np.asarray(full), rtol=2e-4, atol=2e-4)


def test_greedy_generation_consistent():
    """Greedy generate: every generated token must be the argmax of the
    full forward logits over its prefix (self-consistency oracle)."""
    cfg = tiny_cfg()
    mc = MeshConfig(data=1, devices=jax.devices()[:1])
    params = shard_params(
        mc, cfg, init_transformer(jax.random.PRNGKey(0), cfg))
    Plen = 4
    p = prompt(length=Plen)
    gen = make_generate_fn(mc, cfg, max_len=12)
    out = gen(params, p)
    assert out.shape == (B, 12)
    np.testing.assert_array_equal(np.asarray(out[:, :Plen]),
                                  np.asarray(p))
    fwd = make_forward_fn(mc, cfg)
    out_np = np.asarray(out)
    for t in range(Plen, 12):
        prefix = jnp.asarray(
            np.pad(out_np[:, :t], ((0, 0), (0, T - t))), jnp.int32)
        logits = np.asarray(fwd(params, prefix))[:, t - 1]
        np.testing.assert_array_equal(out_np[:, t],
                                      logits.argmax(-1))


def test_sampling_needs_key_and_differs():
    cfg = tiny_cfg()
    mc = MeshConfig(data=1, devices=jax.devices()[:1])
    params = shard_params(
        mc, cfg, init_transformer(jax.random.PRNGKey(0), cfg))
    gen = make_generate_fn(mc, cfg, max_len=12, temperature=1.0)
    with pytest.raises(ValueError, match="PRNG"):
        gen(params, prompt(length=4))
    a = gen(params, prompt(length=4), key=jax.random.PRNGKey(1))
    b = gen(params, prompt(length=4), key=jax.random.PRNGKey(2))
    assert not np.array_equal(np.asarray(a), np.asarray(b))


def test_filter_logits_masks_expected_sets():
    from chainermn_tpu.models.decoding import _NEG, _filter_logits

    logits = jnp.log(jnp.asarray([[0.5, 0.3, 0.15, 0.05]]))
    # top_k keeps exactly the k best
    out = np.asarray(_filter_logits(logits, 2, 1.0))
    assert (out[0, :2] > _NEG / 2).all() and (out[0, 2:] <= _NEG / 2).all()
    # nucleus: the first rank reaching 0.7 mass is included, rest cut
    # (0.7 sits strictly between the 0.5 and 0.8 cumulative masses, so
    # fp32 rounding of the log->softmax->cumsum roundtrip can't flip
    # membership at the boundary)
    out = np.asarray(_filter_logits(logits, 0, 0.7))
    assert (out[0, :2] > _NEG / 2).all() and (out[0, 2:] <= _NEG / 2).all()
    # k beyond the vocab is a no-op, not an index error
    np.testing.assert_array_equal(
        np.asarray(_filter_logits(logits, 99, 1.0)), np.asarray(logits))
    # off-filters are the identity
    np.testing.assert_array_equal(
        np.asarray(_filter_logits(logits, 0, 1.0)), np.asarray(logits))
    # filters compose: top_k=1 dominates a loose nucleus
    out = np.asarray(_filter_logits(logits, 1, 0.99))
    assert (out[0, 1:] <= _NEG / 2).all()
    # the sampling path filters AFTER temperature (HF convention): a
    # hot temperature flattens the distribution and WIDENS the nucleus
    # — at T=4 the 0.7-mass set grows from 2 tokens to 3
    out = np.asarray(_filter_logits(logits / 4.0, 0, 0.7))
    assert (out[0, :3] > _NEG / 2).all() and out[0, 3] <= _NEG / 2


def test_top_k1_sampling_is_greedy():
    """top_k=1 sampling must reproduce greedy token-for-token at any
    temperature (only the argmax survives the filter)."""
    cfg = tiny_cfg()
    mc = MeshConfig(data=1, devices=jax.devices()[:1])
    params = shard_params(
        mc, cfg, init_transformer(jax.random.PRNGKey(0), cfg))
    p = prompt(length=4)
    greedy = make_generate_fn(mc, cfg, max_len=12)(params, p)
    topk1 = make_generate_fn(
        mc, cfg, max_len=12, temperature=5.0, top_k=1)(
        params, p, key=jax.random.PRNGKey(7))
    np.testing.assert_array_equal(np.asarray(topk1), np.asarray(greedy))


def test_sampling_filter_validation():
    cfg = tiny_cfg()
    mc = MeshConfig(data=1, devices=jax.devices()[:1])
    with pytest.raises(ValueError, match="temperature"):
        make_generate_fn(mc, cfg, max_len=12, top_k=5)
    with pytest.raises(ValueError, match="top_p"):
        make_generate_fn(mc, cfg, max_len=12, temperature=1.0, top_p=0.0)


def test_decode_mesh_validation():
    cfg = tiny_cfg()
    # seq-KV blocks the cache over seq: max_len must divide evenly
    with pytest.raises(ValueError, match="divisible by the seq"):
        make_generate_fn(MeshConfig(seq=2, data=4), cfg, max_len=T - 1)
    with pytest.raises(ValueError, match="max_len"):
        make_generate_fn(
            MeshConfig(data=1, devices=jax.devices()[:1]), cfg,
            max_len=T + 1)


def test_seq_kv_generate_matches_single_device():
    """Greedy generation with the KV cache blocked over the seq axis is
    token-identical to single-device decode (the R× cache capacity is
    an implementation detail, not a semantics change)."""
    cfg = tiny_cfg()
    host = init_transformer(jax.random.PRNGKey(4), cfg)
    p = prompt(seed=9, length=4)

    one = MeshConfig(data=1, devices=jax.devices()[:1])
    ref = make_generate_fn(one, cfg, max_len=12)(
        shard_params(one, cfg, host), p)

    mc = MeshConfig(data=2, seq=4)
    got = make_generate_fn(mc, cfg, max_len=12)(
        shard_params(mc, cfg, host), p)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


@pytest.mark.parametrize("axes,kw", [
    (dict(data=1), {}),
    (dict(data=2, seq=2), dict(n_kv_heads=2)),
    (dict(pipe=2, data=2), {}),
    (dict(data=1), dict(moe=True, n_experts=2, capacity_factor=4.0)),
], ids=["single", "seq-kv-gqa", "pipe", "moe"])
def test_batched_prefill_matches_per_token(axes, kw):
    """Batched prefill (one multi-token chunk through _decode_step)
    must leave the cache in exactly the state the per-token scan does:
    the next step's logits are identical.

    The MoE case pins capacity_factor=4.0 DELIBERATELY: at ample
    capacity nothing drops and the two prefills are exact; at a finite
    factor chunk routing shares one B·Tq slot budget (training-forward
    semantics) while per-token stepping budgets per position, so drops
    can differ — a documented semantics choice (see _decode_step),
    not an equivalence this test could assert."""
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from chainermn_tpu.models import param_specs
    from chainermn_tpu.models.decoding import _decode_step, _make_cache

    cfg = tiny_cfg(**kw)
    pipe = axes.get("pipe", 1)
    n_dev = int(np.prod(list(axes.values())))
    mc = MeshConfig(**axes, devices=jax.devices()[:n_dev])
    params = shard_params(
        mc, cfg, init_transformer(jax.random.PRNGKey(6), cfg, pipe))
    toks = prompt(seed=11)

    def body(params, tk):
        Bl, Tn = tk.shape
        R = lax.axis_size("seq")
        Hkvl = cfg.kv_heads // lax.axis_size("model")
        Ll = jax.tree.leaves(params["blocks"])[0].shape[1]

        def run(batched):
            caches = _make_cache(cfg, Bl, Tn // R, Hkvl, Ll)
            if batched:
                _, caches = _decode_step(
                    cfg, params, caches, tk[:, :Tn - 1], 0,
                    with_logits=False)
            else:
                def stepf(c, t):
                    _, c = _decode_step(cfg, params, c, tk[:, t], t)
                    return c, None

                caches, _ = lax.scan(stepf, caches, jnp.arange(Tn - 1))
            logits, _ = _decode_step(
                cfg, params, caches, tk[:, Tn - 1], Tn - 1)
            return logits

        return run(True), run(False)

    fn = jax.jit(jax.shard_map(
        body, mesh=mc.mesh,
        in_specs=(param_specs(cfg), P(("data", "expert"))),
        out_specs=(P(("data", "expert")), P(("data", "expert")))))
    a, b = fn(params, toks)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=2e-4, atol=2e-4)


def test_seq_kv_beam_matches_single_device():
    """Beam search with the length-blocked cache: token- and
    score-identical to the seq=1 oracle (the beam path reorders caches
    per step — the reorder must commute with the seq blocking)."""
    from chainermn_tpu.models import make_beam_search_fn

    cfg = tiny_cfg()
    host = init_transformer(jax.random.PRNGKey(5), cfg)
    p = prompt(seed=10, length=4)

    one = MeshConfig(data=1, devices=jax.devices()[:1])
    ot, os_ = make_beam_search_fn(one, cfg, beam_size=2, max_len=T)(
        shard_params(one, cfg, host), p)

    mc = MeshConfig(data=2, seq=2, devices=jax.devices()[:4])
    gt, gs = make_beam_search_fn(mc, cfg, beam_size=2, max_len=T)(
        shard_params(mc, cfg, host), p)
    np.testing.assert_array_equal(np.asarray(gt), np.asarray(ot))
    np.testing.assert_allclose(np.asarray(gs), np.asarray(os_),
                               rtol=1e-5, atol=1e-6)


class TestEosEarlyStop:
    """eos_id early stopping: frozen rows pad, unfrozen rows are
    bit-identical to the no-eos run (per-row computations are
    independent), prompt eos is ignored, and the sharded while-loop's
    pmax stop flag agrees across meshes."""

    PAD = 7

    def _expected(self, ref, Plen, eos):
        exp = np.asarray(ref).copy()
        for b in range(exp.shape[0]):
            hits = np.where(exp[b, Plen:] == eos)[0]
            if hits.size:
                exp[b, Plen + hits[0] + 1:] = self.PAD
        return exp

    def _run(self, axes, n_dev):
        cfg = tiny_cfg()
        host = init_transformer(jax.random.PRNGKey(6), cfg)
        # a prompt CONTAINING candidate eos values must not freeze rows
        p = prompt(seed=20, length=4)
        one = MeshConfig(data=1, devices=jax.devices()[:1])
        ref = np.asarray(
            make_generate_fn(one, cfg, max_len=T)(
                shard_params(one, cfg, host), p))
        # eos = a token some row actually generates mid-sequence
        eos = int(ref[0, 6])
        mc = MeshConfig(**axes, devices=jax.devices()[:n_dev])
        got = np.asarray(
            make_generate_fn(mc, cfg, max_len=T, eos_id=eos,
                             pad_id=self.PAD)(
                shard_params(mc, cfg, host), p))
        np.testing.assert_array_equal(got, self._expected(ref, 4, eos))
        return ref, p, host, cfg

    def test_single_device_freeze_and_pad(self):
        self._run(dict(data=1), 1)

    def test_sharded_batch_mesh(self):
        # rows finish at different times across shards; the pmax stop
        # flag must keep every shard stepping until the global last row
        self._run(dict(data=2, model=2), 4)

    def test_eos_never_fires_matches_plain(self):
        cfg = tiny_cfg()
        host = init_transformer(jax.random.PRNGKey(6), cfg)
        p = prompt(seed=21, length=4)
        one = MeshConfig(data=1, devices=jax.devices()[:1])
        params = shard_params(one, cfg, host)
        ref = np.asarray(
            make_generate_fn(one, cfg, max_len=T)(params, p))
        unused = [v for v in range(VOCAB)
                  if v not in np.asarray(ref)][0]
        got = np.asarray(
            make_generate_fn(one, cfg, max_len=T, eos_id=unused,
                             pad_id=self.PAD)(params, p))
        np.testing.assert_array_equal(got, ref)

    def test_validation(self):
        cfg = tiny_cfg()
        one = MeshConfig(data=1, devices=jax.devices()[:1])
        with pytest.raises(ValueError, match="eos_id"):
            make_generate_fn(one, cfg, max_len=T, eos_id=VOCAB)
        with pytest.raises(ValueError, match="pad_id"):
            make_generate_fn(one, cfg, max_len=T, eos_id=1,
                             pad_id=VOCAB)
        # pad MAY alias eos (HF GPT-2 convention: pad_token ==
        # eos_token) — trim-at-first-eos disambiguates, so this must
        # build without error
        make_generate_fn(one, cfg, max_len=T, eos_id=1, pad_id=1)


class TestPaddedPrompts:
    """Left-padded variable-length prompts: every row must generate
    exactly the tokens its UNPADDED solo run would — per-row position
    origins and the pad-slot attention mask together make padding
    invisible to the model."""

    def _rows_vs_solo(self, cfg, axes, n_dev):
        host = init_transformer(jax.random.PRNGKey(7), cfg)
        P_len, G = 6, 6                     # prompt slots, new tokens
        rng = np.random.RandomState(30)
        lens = np.asarray([6, 4, 2, 5])
        rows = [rng.randint(0, VOCAB, (n,)).astype(np.int32)
                for n in lens]
        padded = np.full((B, P_len), 63, np.int32)   # junk pad tokens
        for b, r in enumerate(rows):
            padded[b, P_len - lens[b]:] = r

        mc = MeshConfig(**axes, devices=jax.devices()[:n_dev])
        got = np.asarray(
            make_generate_fn(mc, cfg, max_len=P_len + G)(
                shard_params(mc, cfg, host), jnp.asarray(padded),
                prompt_lens=lens))
        one = MeshConfig(data=1, devices=jax.devices()[:1])
        sparams = shard_params(one, cfg, host)
        for b, r in enumerate(rows):
            solo = np.asarray(
                make_generate_fn(one, cfg, max_len=lens[b] + G)(
                    sparams, jnp.tile(r, (B, 1))))
            np.testing.assert_array_equal(
                got[b, P_len:], solo[0, lens[b]:],
                err_msg=f"row {b} (len {lens[b]})")

    def test_rope_single_device(self):
        self._rows_vs_solo(tiny_cfg(pos_embedding="rope"),
                           dict(data=1), 1)

    def test_learned_positions(self):
        self._rows_vs_solo(tiny_cfg(), dict(data=1), 1)

    def test_tp_sharded_mesh(self):
        self._rows_vs_solo(tiny_cfg(pos_embedding="rope"),
                           dict(data=2, model=2), 4)

    def test_window_attention(self):
        # slot distance == per-row distance, so the sliding window
        # needs no offset — pin that claim
        self._rows_vs_solo(tiny_cfg(pos_embedding="rope",
                                    attention_window=4),
                           dict(data=1), 1)

    def test_int8_kv_cache_composes(self):
        """Padded rows with an int8 KV cache still decode row-for-row
        identically to their int8 solo runs — quantisation is
        per-(token, head), so the pad-slot masking and per-row
        position origins are orthogonal to it."""
        self._rows_vs_solo(
            tiny_cfg(pos_embedding="rope", kv_cache_dtype="int8"),
            dict(data=1), 1)

    def test_beam_search_int8_kv_padded_rows_match_solo(self):
        """Beam search × int8 KV cache × ragged prompts: the per-step
        cache-reorder gather maps uniformly over the cache tuple, so
        the int8 values AND their per-(token, head) scales follow each
        hypothesis — every row's beam TOKENS equal its int8-KV solo
        run.  Scores get a quantisation-width tolerance: the padded
        program prefills through the cache-attending path (deeper
        layers' prompt K/V derive from attention over DEQUANTIZED int8
        reads) while the solo run's fast path attends the raw chunk —
        an inherent ~1e-3 divergence on cumulative log-probs, not a
        reorder bug."""
        self._beam_padded_vs_solo(
            tiny_cfg(pos_embedding="rope", kv_cache_dtype="int8"),
            score_rtol=1e-3, score_atol=1e-2)

    def test_beam_search_padded_rows_match_solo(self):
        """Beam search with prompt_lens: every row's K hypotheses and
        scores equal its unpadded solo beam run — the per-row offsets
        ride through the beam reorder gathers untouched."""
        self._beam_padded_vs_solo(tiny_cfg(pos_embedding="rope"))

    def _beam_padded_vs_solo(self, cfg, score_rtol=1e-5,
                             score_atol=1e-5):
        from chainermn_tpu.models import make_beam_search_fn

        host = init_transformer(jax.random.PRNGKey(7), cfg)
        P_len, G, K = 6, 6, 2
        rng = np.random.RandomState(32)
        lens = np.asarray([6, 4, 2, 5])
        rows = [rng.randint(0, VOCAB, (n,)).astype(np.int32)
                for n in lens]
        padded = np.full((B, P_len), 63, np.int32)
        for b, r in enumerate(rows):
            padded[b, P_len - lens[b]:] = r

        one = MeshConfig(data=1, devices=jax.devices()[:1])
        params = shard_params(one, cfg, host)
        toks, scores = make_beam_search_fn(
            one, cfg, beam_size=K, max_len=P_len + G)(
            params, jnp.asarray(padded), prompt_lens=lens)
        for b, r in enumerate(rows):
            st, ss = make_beam_search_fn(
                one, cfg, beam_size=K, max_len=lens[b] + G)(
                params, jnp.tile(r, (B, 1)))
            np.testing.assert_array_equal(
                np.asarray(toks)[b, :, P_len:],
                np.asarray(st)[0, :, lens[b]:],
                err_msg=f"row {b}")
            np.testing.assert_allclose(
                np.asarray(scores)[b], np.asarray(ss)[0],
                rtol=score_rtol, atol=score_atol)

    def test_equal_lens_match_plain_path(self):
        """prompt_lens = full length everywhere must reproduce the
        plain (unpadded) program token-for-token."""
        cfg = tiny_cfg(pos_embedding="rope")
        host = init_transformer(jax.random.PRNGKey(7), cfg)
        one = MeshConfig(data=1, devices=jax.devices()[:1])
        params = shard_params(one, cfg, host)
        p = prompt(seed=31, length=5)
        gen = make_generate_fn(one, cfg, max_len=12)
        np.testing.assert_array_equal(
            np.asarray(gen(params, p,
                           prompt_lens=np.full(B, 5))),
            np.asarray(gen(params, p)))

    def test_validation(self):
        cfg = tiny_cfg()
        one = MeshConfig(data=1, devices=jax.devices()[:1])
        gen = make_generate_fn(one, cfg, max_len=12)
        params = shard_params(
            one, cfg, init_transformer(jax.random.PRNGKey(0), cfg))
        with pytest.raises(ValueError, match="prompt_lens"):
            gen(params, prompt(length=4), prompt_lens=np.zeros(B, int))
        with pytest.raises(ValueError, match="prompt_lens"):
            gen(params, prompt(length=4), prompt_lens=np.full(B, 9))
        with pytest.raises(ValueError, match="sequence-parallel"):
            make_generate_fn(
                MeshConfig(seq=2, data=4), cfg, max_len=16)(
                shard_params(MeshConfig(seq=2, data=4), cfg,
                             init_transformer(jax.random.PRNGKey(0),
                                              cfg)),
                prompt(length=4), prompt_lens=np.full(B, 4))


class TestSpeculative:
    """Greedy speculative decoding: the draft model affects SPEED only
    — output must be token-identical to the target's own greedy decode
    no matter how good or bad the draft is.

    Targets are TRAINED briefly first: the chunk-verify computes the
    same logits as per-token stepping up to fp reassociation, and a
    random-init model's argmax gaps sit inside that noise — a few SGD
    steps make the argmax decisive (the realistic regime; near-tie
    flips are an fp artifact, not a speculative-logic property)."""

    def _trained_host(self, cfg, seed):
        import optax

        from chainermn_tpu.models import make_train_step

        one = MeshConfig(data=1, devices=jax.devices()[:1])
        params = shard_params(
            one, cfg, init_transformer(jax.random.PRNGKey(seed), cfg))
        opt = optax.adam(1e-2)
        st = jax.jit(opt.init)(params)
        step = make_train_step(one, cfg, opt)
        rng = np.random.RandomState(seed)
        x = jnp.asarray(
            (np.arange(B * (T + 1)).reshape(B, T + 1) * 7 + 3) % VOCAB,
            jnp.int32)
        for _ in range(30):
            params, st, _ = step(params, st, x[:, :T], x[:, 1:])
        return jax.tree.map(np.asarray, params)

    def _target_greedy(self, cfg, host, p, max_len):
        one = MeshConfig(data=1, devices=jax.devices()[:1])
        return np.asarray(
            make_generate_fn(one, cfg, max_len=max_len)(
                shard_params(one, cfg, host), p))

    @pytest.mark.parametrize("k", [1, 3, 5])
    def test_perfect_draft_matches_greedy(self, k):
        """Draft == target: every proposal verifies, rounds stride k+1
        — and the tokens are exactly the greedy sequence."""
        from chainermn_tpu.models import make_speculative_generate_fn

        cfg = tiny_cfg()
        host = self._trained_host(cfg, 0)
        p = prompt(seed=12, length=4)
        ref = self._target_greedy(cfg, host, p, T)

        one = MeshConfig(data=1, devices=jax.devices()[:1])
        spec = make_speculative_generate_fn(one, cfg, cfg, k=k,
                                            max_len=T, with_stats=True)
        params = shard_params(one, cfg, host)
        got, mean_acc = spec(params, params, p)
        np.testing.assert_array_equal(np.asarray(got), ref)
        # a perfect draft's proposals all verify: acceptance == k
        assert float(mean_acc) == pytest.approx(k), float(mean_acc)

    def test_weak_draft_still_matches_greedy(self, ):
        """A DIFFERENT (shallower, differently-initialised) draft:
        acceptance is partial and the corrective path runs — output
        still exactly the target's greedy tokens."""
        from chainermn_tpu.models import make_speculative_generate_fn

        cfg = tiny_cfg(n_layers=4)
        d_cfg = tiny_cfg(n_layers=2)
        host = self._trained_host(cfg, 0)
        d_host = self._trained_host(d_cfg, 9)
        p = prompt(seed=13, length=4)
        ref = self._target_greedy(cfg, host, p, T)

        one = MeshConfig(data=1, devices=jax.devices()[:1])
        spec = make_speculative_generate_fn(one, cfg, d_cfg, k=3,
                                            max_len=T, with_stats=True)
        got, mean_acc = spec(shard_params(one, cfg, host),
                             shard_params(one, d_cfg, d_host), p)
        np.testing.assert_array_equal(np.asarray(got), ref)
        assert 0.0 <= float(mean_acc) <= 3.0

    def test_tp_mesh_matches_greedy(self):
        from chainermn_tpu.models import make_speculative_generate_fn

        cfg = tiny_cfg(n_layers=4)
        d_cfg = tiny_cfg(n_layers=2)
        host = self._trained_host(cfg, 1)
        d_host = self._trained_host(d_cfg, 8)
        p = prompt(seed=14, length=4)
        ref = self._target_greedy(cfg, host, p, T)

        mc = MeshConfig(data=2, model=2, devices=jax.devices()[:4])
        spec = make_speculative_generate_fn(mc, cfg, d_cfg, k=3,
                                            max_len=T)
        got = np.asarray(spec(shard_params(mc, cfg, host),
                              shard_params(mc, d_cfg, d_host), p))
        np.testing.assert_array_equal(got, ref)

    def test_vocab_parallel_mesh_matches_greedy(self):
        """Speculative decode over Megatron vocab TP: the verify
        chunk's (B, k+1, V/M) logits shards all-gather to full width
        before the argmax compare — tokens equal the plain (non-vp)
        greedy oracle exactly."""
        import dataclasses

        from chainermn_tpu.models import make_speculative_generate_fn

        cfg = tiny_cfg(n_layers=4)
        d_cfg = tiny_cfg(n_layers=2)
        host = self._trained_host(cfg, 1)
        d_host = self._trained_host(d_cfg, 8)
        p = prompt(seed=14, length=4)
        ref = self._target_greedy(cfg, host, p, T)

        vp = dataclasses.replace(cfg, vocab_parallel=True)
        d_vp = dataclasses.replace(d_cfg, vocab_parallel=True)
        mc = MeshConfig(data=2, model=2, devices=jax.devices()[:4])
        got = np.asarray(make_speculative_generate_fn(
            mc, vp, d_vp, k=3, max_len=T)(
            shard_params(mc, vp, host),
            shard_params(mc, d_vp, d_host), p))
        np.testing.assert_array_equal(got, ref)

    def test_pipe_mesh_matches_greedy(self):
        """PP-decode composes: the verify chunk rides the S-phase
        ppermute hand-off with stage-masked cache writes."""
        from chainermn_tpu.models import make_speculative_generate_fn

        cfg = tiny_cfg(n_layers=4)
        d_cfg = tiny_cfg(n_layers=2)
        host = self._trained_host(cfg, 2)
        d_host = self._trained_host(d_cfg, 7)
        p = prompt(seed=15, length=4)
        ref = self._target_greedy(cfg, host, p, T)

        from chainermn_tpu.models import regroup_blocks

        mc = MeshConfig(pipe=2, data=2, devices=jax.devices()[:4])
        spec = make_speculative_generate_fn(mc, cfg, d_cfg, k=3,
                                            max_len=T)
        got = np.asarray(spec(
            shard_params(mc, cfg, dict(host, blocks=regroup_blocks(
                host["blocks"], 1, 2))),
            shard_params(mc, d_cfg, dict(d_host, blocks=regroup_blocks(
                d_host["blocks"], 1, 2))), p))
        np.testing.assert_array_equal(got, ref)

    def test_int8_matches_int8_greedy(self):
        """Weight-only int8 target + draft: tokens equal the int8
        target's own greedy decode (int8 changes the logits, so the
        oracle is the QUANTIZED greedy run)."""
        from chainermn_tpu.models import (
            make_speculative_generate_fn, quantize_params_int8)

        cfg = tiny_cfg(n_layers=4)
        d_cfg = tiny_cfg(n_layers=2)
        host = quantize_params_int8(cfg, self._trained_host(cfg, 3))
        d_host = quantize_params_int8(d_cfg, self._trained_host(d_cfg, 6))
        p = prompt(seed=16, length=4)

        one = MeshConfig(data=1, devices=jax.devices()[:1])
        ref = np.asarray(
            make_generate_fn(one, cfg, max_len=T, quantized=True)(
                shard_params(one, cfg, host), p))
        spec = make_speculative_generate_fn(
            one, cfg, d_cfg, k=3, max_len=T, quantized=True,
            draft_quantized=True)
        got = np.asarray(spec(shard_params(one, cfg, host),
                              shard_params(one, d_cfg, d_host), p))
        np.testing.assert_array_equal(got, ref)

    def test_int8_kv_cache_matches_int8_kv_greedy(self):
        """Speculative decode over an int8 KV cache: the verify
        chunk's writes quantize per-(token, head) exactly like the
        per-token oracle's, and both read back dequantized — tokens
        equal the int8-KV greedy run (that quantized run is the right
        oracle; int8-KV changes the logits)."""
        import dataclasses

        from chainermn_tpu.models import make_speculative_generate_fn

        cfg = tiny_cfg(n_layers=4, kv_cache_dtype="int8")
        d_cfg = tiny_cfg(n_layers=2, kv_cache_dtype="int8")
        host = self._trained_host(
            dataclasses.replace(cfg, kv_cache_dtype=""), 3)
        d_host = self._trained_host(
            dataclasses.replace(d_cfg, kv_cache_dtype=""), 6)
        p = prompt(seed=19, length=4)
        one = MeshConfig(data=1, devices=jax.devices()[:1])
        params = shard_params(one, cfg, host)
        ref = np.asarray(
            make_generate_fn(one, cfg, max_len=T)(params, p))
        got = np.asarray(make_speculative_generate_fn(
            one, cfg, d_cfg, k=3, max_len=T)(
            params, shard_params(one, d_cfg, d_host), p))
        np.testing.assert_array_equal(got, ref)

    def test_truncated_cheap_draft_speeds_and_matches(self):
        """The ``bench_decode.py --cheap-draft`` construction at test
        scale: a target whose deep-layer residual outputs are damped, a
        draft made of its first layers + shared embed/final norm.  The
        draft's function then tracks the target's (the regime a trained
        big-model draft earns — a 30-step tiny model's truncated prefix
        is NOT predictive on its own, acceptance 0.0, verified while
        writing this test), so this pins the two properties the bench
        row rests on: acceptance well above the random floor, and
        token-exact greedy output regardless."""
        from chainermn_tpu.models import make_speculative_generate_fn

        cfg = tiny_cfg(n_layers=4)
        d_cfg = tiny_cfg(n_layers=2)
        host = self._trained_host(cfg, 0)

        def damp(name, a):
            if name not in ("wo", "w2"):
                return a
            scale = np.where(np.arange(a.shape[1]) < 2, 1.0,
                             0.003).astype(a.dtype)
            return a * scale.reshape(1, -1, *([1] * (a.ndim - 2)))

        host = dict(host, blocks={
            k: damp(k, v) for k, v in host["blocks"].items()})
        d_host = dict(host, blocks=jax.tree.map(
            lambda a: a[:, :2], host["blocks"]))
        p = prompt(seed=17, length=4)
        ref = self._target_greedy(cfg, host, p, T)

        one = MeshConfig(data=1, devices=jax.devices()[:1])
        spec = make_speculative_generate_fn(one, cfg, d_cfg, k=4,
                                            max_len=T, with_stats=True)
        got, mean_acc = spec(shard_params(one, cfg, host),
                             shard_params(one, d_cfg, d_host), p)
        np.testing.assert_array_equal(np.asarray(got), ref)
        assert float(mean_acc) > 2.0, float(mean_acc)

    def test_sampling_distribution_matches_target(self):
        """Speculative SAMPLING must be distribution-identical to
        sampling the target directly (the Leviathan/Chen guarantee).
        First generated token vs the target's TRUE softmax (forward
        pass), 1000 samples over fixed seeds — deterministic, cannot
        flake, and tight enough to catch the batch-min-cut bug this
        test originally found (committing a fresh p_t draw instead of
        the accepted proposal at an early cut measured TV 0.156 here;
        the exact scheme measures ~0.077 against ~0.085 expected
        noise)."""
        from chainermn_tpu.models import make_speculative_generate_fn

        cfg = tiny_cfg(n_layers=2)
        d_cfg = tiny_cfg(n_layers=1)
        host = self._trained_host(cfg, 0)
        d_host = self._trained_host(d_cfg, 9)
        one = MeshConfig(data=1, devices=jax.devices()[:1])
        params = shard_params(one, cfg, host)
        d_params = shard_params(one, d_cfg, d_host)
        # identical rows: each call yields B exact samples of the
        # first generated token (per-row randomness is independent;
        # the shared batch-min cut only shapes later ROUND boundaries)
        row = np.random.RandomState(50).randint(0, VOCAB, 4)
        p = jnp.asarray(np.tile(row, (B, 1)), jnp.int32)
        TEMP, CALLS = 1.5, 250

        fwd = make_forward_fn(one, cfg)
        full = jnp.asarray(np.pad(np.asarray(p), ((0, 0), (0, T - 4))))
        true_p = np.exp(jax.nn.log_softmax(
            np.asarray(fwd(params, full))[0, 3] / TEMP))
        spec = make_speculative_generate_fn(
            one, cfg, d_cfg, k=2, max_len=5, temperature=TEMP)
        h = np.zeros(VOCAB)
        for i in range(CALLS):
            out = np.asarray(
                spec(params, d_params, p, key=jax.random.PRNGKey(i)))
            for b in range(B):
                h[out[b, 4]] += 1
        n = CALLS * B
        tv = 0.5 * np.abs(h / n - true_p).sum()
        noise = 0.5 * np.sqrt(2 * true_p / (np.pi * n)).sum()
        assert tv < 1.6 * noise + 0.02, (tv, noise)

    def test_sampling_runs_sharded_and_needs_key(self):
        from chainermn_tpu.models import make_speculative_generate_fn

        cfg = tiny_cfg(n_layers=4)
        d_cfg = tiny_cfg(n_layers=2)
        host = self._trained_host(cfg, 1)
        d_host = self._trained_host(d_cfg, 8)
        mc = MeshConfig(data=2, model=2, devices=jax.devices()[:4])
        spec = make_speculative_generate_fn(
            mc, cfg, d_cfg, k=3, max_len=T, temperature=0.8,
            with_stats=True)
        params = shard_params(mc, cfg, host)
        d_params = shard_params(mc, d_cfg, d_host)
        p = prompt(seed=51, length=4)
        with pytest.raises(ValueError, match="PRNG"):
            spec(params, d_params, p)
        a, acc_a = spec(params, d_params, p, key=jax.random.PRNGKey(1))
        b, _ = spec(params, d_params, p, key=jax.random.PRNGKey(2))
        assert (np.asarray(a) < VOCAB).all()
        assert 0.0 <= float(acc_a) <= 3.0
        # prompt preserved, different keys draw different sequences
        np.testing.assert_array_equal(np.asarray(a)[:, :4], np.asarray(p))
        assert not np.array_equal(np.asarray(a), np.asarray(b))

    def test_validation(self):
        from chainermn_tpu.models import make_speculative_generate_fn

        cfg = tiny_cfg()
        one = MeshConfig(data=1, devices=jax.devices()[:1])
        with pytest.raises(ValueError, match="k="):
            make_speculative_generate_fn(one, cfg, cfg, k=0)
        with pytest.raises(ValueError, match="vocab"):
            make_speculative_generate_fn(
                one, cfg, tiny_cfg(vocab_size=VOCAB * 2))
        with pytest.raises(ValueError, match="seq"):
            make_speculative_generate_fn(
                MeshConfig(seq=2, data=4), cfg, cfg)
        with pytest.raises(ValueError, match="temperature"):
            make_speculative_generate_fn(one, cfg, cfg,
                                         temperature=-1.0)
        # filters truncate SAMPLING — greedy spec must reject them
        with pytest.raises(ValueError, match="top_k/top_p"):
            make_speculative_generate_fn(one, cfg, cfg, top_k=4)
        with pytest.raises(ValueError, match="eos_id"):
            make_speculative_generate_fn(one, cfg, cfg, eos_id=VOCAB)

    def test_eos_matches_generate_eos(self):
        """eos early stop composes with greedy speculation: output
        token-identical to make_generate_fn's eos run (first eos kept,
        tail padded), with a draft bad enough that the corrective path
        runs across the freeze boundary."""
        from chainermn_tpu.models import make_speculative_generate_fn

        cfg = tiny_cfg(n_layers=4)
        d_cfg = tiny_cfg(n_layers=2)
        host = self._trained_host(cfg, 0)
        d_host = self._trained_host(d_cfg, 9)
        p = prompt(seed=18, length=4)
        one = MeshConfig(data=1, devices=jax.devices()[:1])
        params = shard_params(one, cfg, host)
        d_params = shard_params(one, d_cfg, d_host)
        plain = self._target_greedy(cfg, host, p, T)
        eos, PAD = int(plain[0, 6]), 7    # row 0 emits eos mid-run
        ref = np.asarray(make_generate_fn(
            one, cfg, max_len=T, eos_id=eos, pad_id=PAD)(params, p))
        assert (ref[0] == PAD).any()      # the freeze actually fires
        got = np.asarray(make_speculative_generate_fn(
            one, cfg, d_cfg, k=3, max_len=T, eos_id=eos, pad_id=PAD)(
            params, d_params, p))
        np.testing.assert_array_equal(got, ref)

    def test_eos_sharded_mesh_matches(self):
        """Rows freeze at different times across data shards: the
        pmax'd stop flag and the frozen rows' forced-k acceptance must
        keep every shard in lockstep to the global last row."""
        from chainermn_tpu.models import make_speculative_generate_fn

        cfg = tiny_cfg(n_layers=4)
        d_cfg = tiny_cfg(n_layers=2)
        host = self._trained_host(cfg, 0)
        d_host = self._trained_host(d_cfg, 9)
        p = prompt(seed=18, length=4)
        one = MeshConfig(data=1, devices=jax.devices()[:1])
        plain = self._target_greedy(cfg, host, p, T)
        eos, PAD = int(plain[0, 6]), 7
        ref = np.asarray(make_generate_fn(
            one, cfg, max_len=T, eos_id=eos, pad_id=PAD)(
            shard_params(one, cfg, host), p))
        mc = MeshConfig(data=2, model=2, devices=jax.devices()[:4])
        got = np.asarray(make_speculative_generate_fn(
            mc, cfg, d_cfg, k=3, max_len=T, eos_id=eos, pad_id=PAD)(
            shard_params(mc, cfg, host),
            shard_params(mc, d_cfg, d_host), p))
        np.testing.assert_array_equal(got, ref)

    def test_padded_prompts_match_generate_padded(self):
        """Variable-length prompts ride through the draft steps and
        verify chunks: token-identical to make_generate_fn's padded
        greedy run on the same rows."""
        from chainermn_tpu.models import make_speculative_generate_fn

        cfg = tiny_cfg(n_layers=4, pos_embedding="rope")
        d_cfg = tiny_cfg(n_layers=2, pos_embedding="rope")
        host = self._trained_host(cfg, 0)
        d_host = self._trained_host(d_cfg, 9)
        P_len = 4
        lens = np.asarray([4, 3, 2, 4])
        rng = np.random.RandomState(33)
        padded = np.full((B, P_len), 63, np.int32)
        for b, n in enumerate(lens):
            padded[b, P_len - n:] = rng.randint(0, VOCAB, (n,))
        padded = jnp.asarray(padded)
        one = MeshConfig(data=1, devices=jax.devices()[:1])
        params = shard_params(one, cfg, host)
        d_params = shard_params(one, d_cfg, d_host)
        ref = np.asarray(make_generate_fn(one, cfg, max_len=T)(
            params, padded, prompt_lens=lens))
        got = np.asarray(make_speculative_generate_fn(
            one, cfg, d_cfg, k=3, max_len=T)(
            params, d_params, padded, prompt_lens=lens))
        np.testing.assert_array_equal(got, ref)

    def test_eos_and_padded_compose(self):
        """The full serving shape at once: ragged prompts AND eos early
        stop, still token-identical to the plain generator."""
        from chainermn_tpu.models import make_speculative_generate_fn

        cfg = tiny_cfg(n_layers=4, pos_embedding="rope")
        d_cfg = tiny_cfg(n_layers=2, pos_embedding="rope")
        host = self._trained_host(cfg, 0)
        d_host = self._trained_host(d_cfg, 9)
        P_len = 4
        lens = np.asarray([4, 3, 2, 4])
        rng = np.random.RandomState(34)
        padded = np.full((B, P_len), 63, np.int32)
        for b, n in enumerate(lens):
            padded[b, P_len - n:] = rng.randint(0, VOCAB, (n,))
        padded = jnp.asarray(padded)
        one = MeshConfig(data=1, devices=jax.devices()[:1])
        params = shard_params(one, cfg, host)
        d_params = shard_params(one, d_cfg, d_host)
        plain = np.asarray(make_generate_fn(one, cfg, max_len=T)(
            params, padded, prompt_lens=lens))
        eos, PAD = int(plain[0, 6]), 7
        ref = np.asarray(make_generate_fn(
            one, cfg, max_len=T, eos_id=eos, pad_id=PAD)(
            params, padded, prompt_lens=lens))
        got = np.asarray(make_speculative_generate_fn(
            one, cfg, d_cfg, k=3, max_len=T, eos_id=eos, pad_id=PAD)(
            params, d_params, padded, prompt_lens=lens))
        np.testing.assert_array_equal(got, ref)

    def test_sampling_padded_eos_runs_and_freezes(self):
        """Speculative SAMPLING × ragged prompts × eos: same-key
        determinism, prompts preserved in place, and every token after
        a row's first generated eos is pad (the distribution identity
        itself is pinned by the statistical tests; this pins the
        composition's bookkeeping)."""
        from chainermn_tpu.models import make_speculative_generate_fn

        cfg = tiny_cfg(n_layers=2, pos_embedding="rope")
        d_cfg = tiny_cfg(n_layers=1, pos_embedding="rope")
        host = self._trained_host(cfg, 0)
        d_host = self._trained_host(d_cfg, 9)
        one = MeshConfig(data=1, devices=jax.devices()[:1])
        params = shard_params(one, cfg, host)
        d_params = shard_params(one, d_cfg, d_host)
        P_len = 4
        lens = np.asarray([4, 3, 2, 4])
        rng = np.random.RandomState(37)
        padded = np.full((B, P_len), 63, np.int32)
        for b, n in enumerate(lens):
            padded[b, P_len - n:] = rng.randint(0, VOCAB, (n,))
        padded = jnp.asarray(padded)
        EOS, PAD = 5, 7
        spec = make_speculative_generate_fn(
            one, cfg, d_cfg, k=2, max_len=T, temperature=1.0,
            top_k=16, eos_id=EOS, pad_id=PAD)
        a = np.asarray(spec(params, d_params, padded,
                            key=jax.random.PRNGKey(3),
                            prompt_lens=lens))
        b2 = np.asarray(spec(params, d_params, padded,
                             key=jax.random.PRNGKey(3),
                             prompt_lens=lens))
        np.testing.assert_array_equal(a, b2)
        np.testing.assert_array_equal(a[:, :P_len], np.asarray(padded))
        assert (a < VOCAB).all() and (a >= 0).all()
        for b_i in range(B):
            gen = a[b_i, P_len:]
            hits = np.where(gen == EOS)[0]
            if hits.size:
                assert (gen[hits[0] + 1:] == PAD).all(), a[b_i]

    def test_sampling_filters_distribution_matches_target(self):
        """Speculative sampling with top-k/top-p must match sampling
        the target directly WITH the same filters (truncate both
        p_draft and p_target, renormalize, exact residual) — same
        statistical design as the unfiltered test."""
        from chainermn_tpu.models import make_speculative_generate_fn
        from chainermn_tpu.models.decoding import _filter_logits

        cfg = tiny_cfg(n_layers=2)
        d_cfg = tiny_cfg(n_layers=1)
        host = self._trained_host(cfg, 0)
        d_host = self._trained_host(d_cfg, 9)
        one = MeshConfig(data=1, devices=jax.devices()[:1])
        params = shard_params(one, cfg, host)
        d_params = shard_params(one, d_cfg, d_host)
        row = np.random.RandomState(50).randint(0, VOCAB, 4)
        p = jnp.asarray(np.tile(row, (B, 1)), jnp.int32)
        TEMP, TOPK, TOPP, CALLS = 1.5, 12, 0.9, 250

        fwd = make_forward_fn(one, cfg)
        full = jnp.asarray(np.pad(np.asarray(p), ((0, 0), (0, T - 4))))
        logits = np.asarray(fwd(params, full))[0, 3][None] / TEMP
        true_p = np.asarray(jax.nn.softmax(
            _filter_logits(jnp.asarray(logits), TOPK, TOPP)))[0]
        spec = make_speculative_generate_fn(
            one, cfg, d_cfg, k=2, max_len=5, temperature=TEMP,
            top_k=TOPK, top_p=TOPP)
        h = np.zeros(VOCAB)
        for i in range(CALLS):
            out = np.asarray(
                spec(params, d_params, p, key=jax.random.PRNGKey(i)))
            for b in range(B):
                h[out[b, 4]] += 1
        n = CALLS * B
        # every sample must live inside the target's truncated support
        assert h[true_p <= 0].sum() == 0, "sample outside the nucleus"
        tv = 0.5 * np.abs(h / n - true_p).sum()
        noise = 0.5 * np.sqrt(2 * true_p / (np.pi * n)).sum()
        assert tv < 1.6 * noise + 0.02, (tv, noise)


class TestLookupDecoding:
    """Prompt-lookup decoding: exact-greedy output no matter what the
    n-gram matcher proposes, and real acceptance on the workloads it
    exists for (repetitive/copying text)."""

    def _trained(self, cfg, seed=0):
        return TestSpeculative._trained_host(
            TestSpeculative(), cfg, seed)

    @pytest.mark.parametrize("k,ngram", [(2, 1), (4, 2), (3, 3)])
    def test_matches_greedy(self, k, ngram):
        from chainermn_tpu.models import make_lookup_generate_fn

        cfg = tiny_cfg()
        host = self._trained(cfg)
        p = prompt(seed=40, length=4)
        one = MeshConfig(data=1, devices=jax.devices()[:1])
        params = shard_params(one, cfg, host)
        ref = np.asarray(
            make_generate_fn(one, cfg, max_len=T)(params, p))
        got, acc = make_lookup_generate_fn(
            one, cfg, k=k, ngram=ngram, max_len=T, with_stats=True)(
            params, p)
        np.testing.assert_array_equal(np.asarray(got), ref)
        assert 0.0 <= float(acc) <= k

    def test_repetitive_sequence_accepts(self):
        """The trained tiny model emits short repeats ("60 60 60 60");
        with IDENTICAL rows (acceptance is batch-min — mixed batches
        clamp to the worst row) lookup proposals must land at least
        once, proving the matcher finds real earlier occurrences."""
        from chainermn_tpu.models import make_lookup_generate_fn

        cfg = tiny_cfg()
        host = self._trained(cfg)
        one = MeshConfig(data=1, devices=jax.devices()[:1])
        params = shard_params(one, cfg, host)
        row = np.random.RandomState(40).randint(0, VOCAB, 4)
        p = jnp.asarray(np.tile(row, (B, 1)), jnp.int32)
        ref = np.asarray(
            make_generate_fn(one, cfg, max_len=T)(params, p))
        got, acc = make_lookup_generate_fn(
            one, cfg, k=3, ngram=2, max_len=T, with_stats=True)(
            params, p)
        np.testing.assert_array_equal(np.asarray(got), ref)
        assert float(acc) > 0.05, float(acc)

    def test_tp_mesh_matches_greedy(self):
        from chainermn_tpu.models import make_lookup_generate_fn

        cfg = tiny_cfg(n_layers=4)
        host = self._trained(cfg, 1)
        p = prompt(seed=42, length=4)
        one = MeshConfig(data=1, devices=jax.devices()[:1])
        ref = np.asarray(
            make_generate_fn(one, cfg, max_len=T)(
                shard_params(one, cfg, host), p))
        mc = MeshConfig(data=2, model=2, devices=jax.devices()[:4])
        got = np.asarray(make_lookup_generate_fn(
            mc, cfg, k=3, ngram=2, max_len=T)(
            shard_params(mc, cfg, host), p))
        np.testing.assert_array_equal(got, ref)

    def test_vocab_parallel_mesh_matches_greedy(self):
        """Lookup decoding over Megatron vocab TP (shared
        _verify_and_commit with speculative: the sharded verify
        logits all-gather before the argmax compare)."""
        import dataclasses

        from chainermn_tpu.models import make_lookup_generate_fn

        cfg = tiny_cfg(n_layers=4)
        host = self._trained(cfg, 1)
        p = prompt(seed=42, length=4)
        one = MeshConfig(data=1, devices=jax.devices()[:1])
        ref = np.asarray(
            make_generate_fn(one, cfg, max_len=T)(
                shard_params(one, cfg, host), p))
        vp = dataclasses.replace(cfg, vocab_parallel=True)
        mc = MeshConfig(data=2, model=2, devices=jax.devices()[:4])
        got = np.asarray(make_lookup_generate_fn(
            mc, vp, k=3, ngram=2, max_len=T)(
            shard_params(mc, vp, host), p))
        np.testing.assert_array_equal(got, ref)

    def test_pipe_mesh_matches_greedy(self):
        """Lookup decoding over pipe-parallel decode: the verify chunk
        rides the S-phase ppermute hand-off with stage-masked cache
        writes, the matcher stays host-side row-local."""
        from chainermn_tpu.models import (
            make_lookup_generate_fn, regroup_blocks)

        cfg = tiny_cfg(n_layers=4)
        host = self._trained(cfg, 2)
        p = prompt(seed=45, length=4)
        one = MeshConfig(data=1, devices=jax.devices()[:1])
        ref = np.asarray(
            make_generate_fn(one, cfg, max_len=T)(
                shard_params(one, cfg, host), p))
        mc = MeshConfig(pipe=2, data=2, devices=jax.devices()[:4])
        got = np.asarray(make_lookup_generate_fn(
            mc, cfg, k=3, ngram=2, max_len=T)(
            shard_params(mc, cfg, dict(host, blocks=regroup_blocks(
                host["blocks"], 1, 2))), p))
        np.testing.assert_array_equal(got, ref)

    def test_int8_weights_match_int8_greedy(self):
        """Lookup decoding over weight-only int8: exact vs the int8
        greedy oracle (int8 changes the logits, so the quantized run
        is the right reference)."""
        from chainermn_tpu.models import (
            make_lookup_generate_fn, quantize_params_int8)

        cfg = tiny_cfg(n_layers=4)
        host = quantize_params_int8(cfg, self._trained(cfg, 2))
        p = prompt(seed=43, length=4)
        one = MeshConfig(data=1, devices=jax.devices()[:1])
        params = shard_params(one, cfg, host)
        ref = np.asarray(
            make_generate_fn(one, cfg, max_len=T, quantized=True)(
                params, p))
        got = np.asarray(make_lookup_generate_fn(
            one, cfg, k=3, ngram=2, max_len=T, quantized=True)(
            params, p))
        np.testing.assert_array_equal(got, ref)

    def test_validation(self):
        from chainermn_tpu.models import make_lookup_generate_fn

        cfg = tiny_cfg()
        one = MeshConfig(data=1, devices=jax.devices()[:1])
        with pytest.raises(ValueError, match="k="):
            make_lookup_generate_fn(one, cfg, k=0)
        with pytest.raises(ValueError, match="seq"):
            make_lookup_generate_fn(MeshConfig(seq=2, data=4), cfg)
        with pytest.raises(ValueError, match="eos_id"):
            make_lookup_generate_fn(one, cfg, eos_id=VOCAB)
        # prompt shorter than the ngram window fails at trace time
        gen = make_lookup_generate_fn(one, cfg, k=2, ngram=4, max_len=T)
        params = shard_params(
            one, cfg, init_transformer(jax.random.PRNGKey(0), cfg))
        with pytest.raises(ValueError, match="ngram"):
            gen(params, prompt(length=2))

    def test_eos_matches_generate_eos(self):
        """eos early stop composes with lookup decoding: output
        token-identical to make_generate_fn's eos run."""
        from chainermn_tpu.models import make_lookup_generate_fn

        cfg = tiny_cfg(n_layers=4)
        host = self._trained(cfg, 1)
        p = prompt(seed=44, length=4)
        one = MeshConfig(data=1, devices=jax.devices()[:1])
        params = shard_params(one, cfg, host)
        plain = np.asarray(
            make_generate_fn(one, cfg, max_len=T)(params, p))
        eos, PAD = int(plain[0, 6]), 7
        ref = np.asarray(make_generate_fn(
            one, cfg, max_len=T, eos_id=eos, pad_id=PAD)(params, p))
        assert (ref[0] == PAD).any()
        got = np.asarray(make_lookup_generate_fn(
            one, cfg, k=3, ngram=2, max_len=T, eos_id=eos,
            pad_id=PAD)(params, p))
        np.testing.assert_array_equal(got, ref)

    def test_padded_prompts_match_generate_padded(self):
        """Ragged prompts through the lookup matcher: windows touching
        pad slots propose garbage, verification keeps the output
        token-identical to the plain padded generator."""
        from chainermn_tpu.models import make_lookup_generate_fn

        cfg = tiny_cfg(n_layers=4, pos_embedding="rope")
        host = self._trained(cfg, 1)
        P_len = 4
        lens = np.asarray([4, 3, 2, 4])
        rng = np.random.RandomState(35)
        padded = np.full((B, P_len), 63, np.int32)
        for b, n in enumerate(lens):
            padded[b, P_len - n:] = rng.randint(0, VOCAB, (n,))
        padded = jnp.asarray(padded)
        one = MeshConfig(data=1, devices=jax.devices()[:1])
        params = shard_params(one, cfg, host)
        ref = np.asarray(make_generate_fn(one, cfg, max_len=T)(
            params, padded, prompt_lens=lens))
        got = np.asarray(make_lookup_generate_fn(
            one, cfg, k=3, ngram=2, max_len=T)(
            params, padded, prompt_lens=lens))
        np.testing.assert_array_equal(got, ref)

    def test_eos_and_padded_compose(self):
        from chainermn_tpu.models import make_lookup_generate_fn

        cfg = tiny_cfg(n_layers=4, pos_embedding="rope")
        host = self._trained(cfg, 1)
        P_len = 4
        lens = np.asarray([4, 3, 2, 4])
        rng = np.random.RandomState(36)
        padded = np.full((B, P_len), 63, np.int32)
        for b, n in enumerate(lens):
            padded[b, P_len - n:] = rng.randint(0, VOCAB, (n,))
        padded = jnp.asarray(padded)
        one = MeshConfig(data=1, devices=jax.devices()[:1])
        params = shard_params(one, cfg, host)
        plain = np.asarray(make_generate_fn(one, cfg, max_len=T)(
            params, padded, prompt_lens=lens))
        eos, PAD = int(plain[0, 6]), 7
        ref = np.asarray(make_generate_fn(
            one, cfg, max_len=T, eos_id=eos, pad_id=PAD)(
            params, padded, prompt_lens=lens))
        got = np.asarray(make_lookup_generate_fn(
            one, cfg, k=3, ngram=2, max_len=T, eos_id=eos,
            pad_id=PAD)(params, padded, prompt_lens=lens))
        np.testing.assert_array_equal(got, ref)


def test_virtual_pipe_packed_params_decode():
    """Params packed for the interleaved schedule (pipe=1, V=2) decode
    identically to flat packing."""
    cfg_flat = tiny_cfg(n_layers=4)
    cfg_v = tiny_cfg(n_layers=4, pipeline_schedule="interleaved",
                     virtual_pipe=2, num_microbatches=1)
    mc = MeshConfig(data=1, devices=jax.devices()[:1])
    params_flat = init_transformer(jax.random.PRNGKey(0), cfg_flat)
    params_v = init_transformer(jax.random.PRNGKey(0), cfg_v)
    toks = prompt()
    a = _cached_logits_all_positions(
        cfg_flat, shard_params(mc, cfg_flat, params_flat), toks, mc)
    b = _cached_logits_all_positions(
        cfg_v, shard_params(mc, cfg_v, params_v), toks, mc)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=1e-5, atol=1e-5)


class TestBeamSearch:
    def test_beam1_equals_greedy(self):
        from chainermn_tpu.models import make_beam_search_fn

        cfg = tiny_cfg()
        mc = MeshConfig(data=1, devices=jax.devices()[:1])
        params = shard_params(
            mc, cfg, init_transformer(jax.random.PRNGKey(0), cfg))
        p = prompt(length=4)
        greedy = make_generate_fn(mc, cfg, max_len=12)(params, p)
        beams, scores = make_beam_search_fn(
            mc, cfg, beam_size=1, max_len=12)(params, p)
        np.testing.assert_array_equal(
            np.asarray(beams[:, 0]), np.asarray(greedy))
        assert np.isfinite(np.asarray(scores)).all()

    def test_finds_exhaustive_argmax(self):
        """Small vocab, short horizon: a wide beam must recover the true
        argmax sequence found by brute-force enumeration."""
        from itertools import product

        from chainermn_tpu.models import make_beam_search_fn

        V, Plen, G = 6, 2, 3          # 6^3 = 216 continuations
        cfg = tiny_cfg(vocab_size=V, max_seq=Plen + G)
        mc = MeshConfig(data=1, devices=jax.devices()[:1])
        params = shard_params(
            mc, cfg, init_transformer(jax.random.PRNGKey(3), cfg))
        B = 2
        p = jnp.asarray(
            np.random.RandomState(1).randint(0, V, (B, Plen)), jnp.int32)

        # brute force: score every continuation with the full forward
        fwd = make_forward_fn(mc, cfg)
        conts = np.array(list(product(range(V), repeat=G)), np.int32)
        best = np.zeros((B, G), np.int32)
        best_score = np.full(B, -np.inf)
        for cont in conts:
            seq = np.concatenate(
                [np.asarray(p), np.tile(cont, (B, 1))], axis=1)
            logits = np.asarray(fwd(params, jnp.asarray(seq)))
            logp = jax.nn.log_softmax(jnp.asarray(logits), -1)
            s = np.zeros(B)
            for g in range(G):
                s += np.asarray(
                    logp[np.arange(B), Plen - 1 + g, seq[:, Plen + g]])
            upd = s > best_score
            best[upd] = cont
            best_score[upd] = s[upd]

        beams, scores = make_beam_search_fn(
            mc, cfg, beam_size=V * V, max_len=Plen + G)(params, p)
        np.testing.assert_array_equal(
            np.asarray(beams[:, 0, Plen:]), best)
        np.testing.assert_allclose(
            np.asarray(scores[:, 0]), best_score, rtol=1e-4, atol=1e-4)

    def test_eos_freezes_hypotheses(self):
        from chainermn_tpu.models import make_beam_search_fn

        cfg = tiny_cfg()
        mc = MeshConfig(data=1, devices=jax.devices()[:1])
        params = shard_params(
            mc, cfg, init_transformer(jax.random.PRNGKey(0), cfg))
        p = prompt(length=3)
        # every token is "eos": all beams finish immediately after one
        # expansion and scores stay frozen (finite, sorted descending)
        gen = make_beam_search_fn(
            mc, cfg, beam_size=3, max_len=10, eos_id=0,
            length_penalty=0.6)
        beams, scores = gen(params, p)
        assert beams.shape == (B, 3, 10)
        s = np.asarray(scores)
        assert (np.diff(s, axis=1) <= 1e-6).all(), s

    def test_dp_tp_mesh(self):
        from chainermn_tpu.models import make_beam_search_fn

        cfg = tiny_cfg(n_kv_heads=2)
        mc = MeshConfig(data=4, model=2)
        params = shard_params(
            mc, cfg, init_transformer(jax.random.PRNGKey(0), cfg))
        one = MeshConfig(data=1, devices=jax.devices()[:1])
        params_one = shard_params(
            one, cfg, init_transformer(jax.random.PRNGKey(0), cfg))
        p = prompt(length=4)
        a, sa = make_beam_search_fn(
            mc, cfg, beam_size=2, max_len=10)(params, p)
        b, sb = make_beam_search_fn(
            one, cfg, beam_size=2, max_len=10)(params_one, p)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_allclose(np.asarray(sa), np.asarray(sb),
                                   rtol=1e-4, atol=1e-4)

    def test_int8_weights_mesh_matches_single(self):
        """Beam search over weight-only int8 on a DP+TP mesh:
        tokens+scores equal the single-device int8 beam run (int8
        changes the logits, so the quantized single-device run is the
        right oracle)."""
        from chainermn_tpu.models import (
            make_beam_search_fn, quantize_params_int8)

        cfg = tiny_cfg()
        host = quantize_params_int8(
            cfg, init_transformer(jax.random.PRNGKey(0), cfg))
        p = prompt(length=4)
        one = MeshConfig(data=1, devices=jax.devices()[:1])
        b, sb = make_beam_search_fn(
            one, cfg, beam_size=2, max_len=10, quantized=True)(
            shard_params(one, cfg, host), p)
        mc = MeshConfig(data=2, model=2, devices=jax.devices()[:4])
        a, sa = make_beam_search_fn(
            mc, cfg, beam_size=2, max_len=10, quantized=True)(
            shard_params(mc, cfg, host), p)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_allclose(np.asarray(sa), np.asarray(sb),
                                   rtol=1e-4, atol=1e-4)


def test_pp_decode_matches_single_device():
    """Pipe-parallel decode: layers + KV cache stage-sharded over
    pipe=2, S-phase ppermute hand-off — generated tokens must equal the
    pipe=1 oracle exactly (greedy argmax)."""
    cfg = tiny_cfg(n_layers=4)
    toks = prompt(length=6)

    one = MeshConfig(data=1, devices=jax.devices()[:1])
    p_flat = init_transformer(jax.random.PRNGKey(0), cfg)
    oracle = make_generate_fn(one, cfg, max_len=T)(
        shard_params(one, cfg, p_flat), toks)

    mc = MeshConfig(pipe=2, data=2, model=2)
    p_pipe = init_transformer(jax.random.PRNGKey(0), cfg, 2)
    got = make_generate_fn(mc, cfg, max_len=T)(
        shard_params(mc, cfg, p_pipe), toks)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(oracle))


def test_pp_decode_beam_and_guards():
    """Beam search rides the same pipe-parallel step; virtual-pipe and
    seq meshes stay clearly rejected."""
    from chainermn_tpu.models import make_beam_search_fn

    cfg = tiny_cfg(n_layers=4)
    toks = prompt(length=6)
    one = MeshConfig(data=1, devices=jax.devices()[:1])
    p_flat = init_transformer(jax.random.PRNGKey(0), cfg)
    ot, os_ = make_beam_search_fn(one, cfg, beam_size=2, max_len=T)(
        shard_params(one, cfg, p_flat), toks)

    mc = MeshConfig(pipe=2, data=4)
    p_pipe = init_transformer(jax.random.PRNGKey(0), cfg, 2)
    gt, gs = make_beam_search_fn(mc, cfg, beam_size=2, max_len=T)(
        shard_params(mc, cfg, p_pipe), toks)
    np.testing.assert_array_equal(np.asarray(gt), np.asarray(ot))
    np.testing.assert_allclose(np.asarray(gs), np.asarray(os_),
                               rtol=1e-5, atol=1e-6)

    with pytest.raises(ValueError, match="virtual_pipe"):
        make_generate_fn(
            mc, tiny_cfg(n_layers=4, virtual_pipe=2,
                         pipeline_schedule="interleaved"), max_len=T)


def test_generate_row_state_pins_frozen_row_semantics():
    """``with_row_state=True`` exposes the while-carry's per-row done
    bitmap and decoded length (only the all-rows-done scalar used to
    escape, as the loop exit).  ``gen_len`` must count exactly the
    real generated tokens — the eos included, the frozen tail's
    padding excluded — and ``done`` must mark exactly the eos-stopped
    rows, pinned here against the eos-less run's prefix."""
    cfg = tiny_cfg()
    mc = MeshConfig(data=1, devices=jax.devices()[:1])
    host = init_transformer(jax.random.PRNGKey(0), cfg)
    from chainermn_tpu.models import shard_params as _sp

    params = _sp(mc, cfg, host)
    toks = prompt(length=4)
    Plen = 4
    plain = np.asarray(
        make_generate_fn(mc, cfg, max_len=T)(params, toks))
    # an eos that provably fires: row 0's own third generated token
    eos = int(plain[0, Plen + 2])
    pad = 0 if eos != 0 else 1
    gen = make_generate_fn(mc, cfg, max_len=T, eos_id=eos, pad_id=pad,
                           with_row_state=True)
    out, done, lens = (np.asarray(x) for x in gen(params, toks))
    assert out.shape == (B, T)
    assert done.shape == (B,) and done.dtype == bool
    assert lens.shape == (B,) and lens.dtype == np.int32
    assert done[0]              # the crafted eos stopped row 0
    for b in range(B):
        region = out[b, Plen:]
        n = int(lens[b])
        if done[b]:
            assert region[n - 1] == eos       # eos kept AND counted
            assert not np.any(region[:n - 1] == eos)
            assert np.all(region[n:] == pad)  # frozen tail is padding
        else:
            assert n == T - Plen              # ran to the buffer end
        # up to each row's own end, row state and tokens agree with
        # the eos-less decode (freezing never rewrites real output)
        np.testing.assert_array_equal(out[b, :Plen + n],
                                      plain[b, :Plen + n])
    # eos disabled: the scan path reports full-length rows, none done
    gen2 = make_generate_fn(mc, cfg, max_len=T, with_row_state=True)
    out2, done2, lens2 = (np.asarray(x) for x in gen2(params, toks))
    np.testing.assert_array_equal(out2, plain)
    assert not done2.any() and np.all(lens2 == T - Plen)
