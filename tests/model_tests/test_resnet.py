"""ResNet: shape/finiteness plus the cross-replica-BN equivalence — a
data-sharded forward with ``axis_name="data"`` must match one device
seeing the whole batch (the MultiNodeBatchNormalization contract)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from chainermn_tpu.models import ResNetConfig, init_resnet, resnet_apply
from chainermn_tpu.parallel import MeshConfig

CFG = ResNetConfig(depth=50, num_classes=10, width=8, dtype="float32")
B, HW = 16, 32


def images(seed=0):
    return jnp.asarray(
        np.random.RandomState(seed).randn(B, HW, HW, 3), jnp.float32)


def test_forward_shape_and_state():
    params, state = init_resnet(jax.random.PRNGKey(0), CFG)
    logits, new_state = resnet_apply(CFG, params, state, images())
    assert logits.shape == (B, 10)
    assert np.isfinite(np.asarray(logits)).all()
    # every BN layer's running stats were updated exactly once
    n = jax.tree.leaves(jax.tree.map(lambda s: s.n, new_state,
                                     is_leaf=lambda x: hasattr(x, "n")))
    assert all(int(x) == 1 for x in n)


def test_eval_mode_uses_running_stats():
    params, state = init_resnet(jax.random.PRNGKey(0), CFG)
    logits, new_state = resnet_apply(
        CFG, params, state, images(), train=False)
    assert jax.tree.all(jax.tree.map(
        lambda a, b: (np.asarray(a) == np.asarray(b)).all(),
        state, new_state))
    assert np.isfinite(np.asarray(logits)).all()


def test_sync_bn_matches_single_device():
    params, state = init_resnet(jax.random.PRNGKey(0), CFG)
    x = images(1)

    ref, ref_state = resnet_apply(CFG, params, state, x, train=True)

    mc = MeshConfig(data=8)
    sharded = jax.jit(
        jax.shard_map(
            lambda p, s, xx: resnet_apply(
                CFG, p, s, xx, train=True, axis_name="data"),
            mesh=mc.mesh,
            in_specs=(P(), P(), P("data")),
            out_specs=(P("data"), P()),
        ))
    out, out_state = sharded(params, state, x)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-4)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
        ref_state, out_state)
