"""GQA/MQA (beyond-reference): n_kv_heads < n_heads shares K/V heads
across query-head groups.  Semantics oracle: a GQA model must produce
bit-matching logits to an MHA model whose K/V projections are the GQA
ones repeated per group; and sharded runs (TP over heads, ring over seq)
must match the single-device GQA run."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from chainermn_tpu.models import (
    TransformerConfig,
    init_transformer,
    make_forward_fn,
    make_train_step,
    shard_params,
)
from chainermn_tpu.parallel import MeshConfig

from chainermn_tpu.testing import requires_vma as _requires_vma

# The flagship transformer's custom VJPs read jax.typeof(...).vma to
# place their psums; TransformerConfig deliberately refuses to construct
# on pre-vma jax (models/transformer.py).  Nothing in this module can
# run without it.
pytestmark = _requires_vma(
    "requires vma-typed shard_map (TransformerConfig refuses pre-vma jax)")

VOCAB, B, T = 64, 8, 16


def gqa_cfg(**kw):
    base = dict(
        vocab_size=VOCAB, d_model=32, n_heads=4, n_kv_heads=2, d_head=8,
        d_ff=64, n_layers=2, max_seq=T, attention="local",
        dtype="float32", remat=False,
    )
    base.update(kw)
    return TransformerConfig(**base)


def tokens(seed=0):
    return jnp.asarray(
        np.random.RandomState(seed).randint(0, VOCAB, (B, T + 1)),
        jnp.int32)


def one_chip(cfg, params, toks):
    mc = MeshConfig(data=1, devices=jax.devices()[:1])
    return make_forward_fn(mc, cfg)(params, toks)


def to_mha_params(cfg, params):
    """Repeat each kv head over its query-head group => equivalent MHA."""
    rep = cfg.n_heads // cfg.kv_heads

    def convert(blk):
        wq = blk["wq"]                       # (P, L, D, H, Dh)
        wkv = jnp.repeat(blk["wkv"], rep, axis=-2)  # (P, L, D, 2, H, Dh)
        wqkv = jnp.concatenate([wq[:, :, :, None], wkv], axis=3)
        return {k: v for k, v in blk.items() if k not in ("wq", "wkv")} \
            | {"wqkv": wqkv}

    blocks = params["blocks"]
    return dict(params, blocks=convert(blocks))


def test_invalid_head_grouping_raises():
    with pytest.raises(ValueError, match="multiple"):
        gqa_cfg(n_heads=4, n_kv_heads=3)


def test_matches_mha_with_repeated_kv():
    cfg = gqa_cfg()
    params = init_transformer(jax.random.PRNGKey(0), cfg)
    toks = tokens()[:, :T]
    got = one_chip(cfg, params, toks)

    mha = gqa_cfg(n_kv_heads=0)
    ref = one_chip(mha, to_mha_params(cfg, params), toks)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("axes,attn", [
    (dict(model=2, data=4), "local"),
    (dict(seq=4, data=2), "ring"),
    # ulysses with seq(4) > n_kv_heads(2): the over-split path
    # replicates shared heads up to lcm for the exchange
    (dict(seq=4, data=2), "ulysses"),
    (dict(pipe=2, model=2, data=2), "local"),
], ids=str)
def test_sharded_matches_single_device(axes, attn):
    pipe = axes.get("pipe", 1)
    cfg = gqa_cfg(
        attention=attn,
        num_microbatches=2 if pipe > 1 else 1,
    )
    params = init_transformer(jax.random.PRNGKey(0), cfg, pipe_size=pipe)
    toks = tokens()[:, :T]

    ref_params = params if pipe == 1 else dict(
        params, blocks=jax.tree.map(
            lambda a: a.reshape(1, -1, *a.shape[2:]), params["blocks"]))
    ref = one_chip(gqa_cfg(), ref_params, toks)

    mc = MeshConfig(**axes)
    out = make_forward_fn(mc, cfg)(shard_params(mc, cfg, params), toks)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_mqa_tp_mesh_raises_clear_error():
    """MQA (1 kv head) cannot shard over model=2 — the error must be an
    actionable ValueError at build time, not a GSPMD placement failure."""
    cfg = gqa_cfg(n_kv_heads=1)
    mc = MeshConfig(model=2, data=4)
    params = init_transformer(jax.random.PRNGKey(0), cfg)
    with pytest.raises(ValueError, match="n_kv_heads"):
        shard_params(mc, cfg, params)
    with pytest.raises(ValueError, match="n_kv_heads"):
        make_forward_fn(mc, cfg)


def test_negative_kv_heads_rejected():
    with pytest.raises(ValueError, match="n_kv_heads"):
        gqa_cfg(n_heads=8, n_kv_heads=-2)


def test_grouped_ring_and_ulysses_match_repeated_kv():
    """The attention cores read shared heads in place: grouped K/V into
    ring/ulysses must equal MHA cores fed group-repeated K/V."""
    from jax.sharding import PartitionSpec as P

    from chainermn_tpu.parallel import MeshConfig as MC
    from chainermn_tpu.parallel.ring_attention import (
        local_attention, ring_attention)
    from chainermn_tpu.parallel.ulysses import ulysses_attention

    B, T, H, G, D = 2, 16, 4, 2, 8
    r = np.random.RandomState(0)
    q = jnp.asarray(r.randn(B, T, H, D), jnp.float32)
    k = jnp.asarray(r.randn(B, T, G, D), jnp.float32)
    v = jnp.asarray(r.randn(B, T, G, D), jnp.float32)
    k_rep = jnp.repeat(k, H // G, axis=2)
    v_rep = jnp.repeat(v, H // G, axis=2)

    ref = local_attention(q, k_rep, v_rep, causal=True)
    got_local = local_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(got_local), np.asarray(ref), rtol=1e-5, atol=1e-5)

    # ring: any ring size; ulysses: S | G moves true-width K/V, S > G
    # (over-split, G=2 on seq=4) replicates shared heads up to lcm —
    # both boundary sides must reproduce the oracle
    for fn, axes in ((ring_attention, dict(seq=4, data=2)),
                     (ulysses_attention, dict(seq=2, data=4)),
                     (ulysses_attention, dict(seq=4, data=2))):
        mc = MC(**axes)
        got = jax.jit(jax.shard_map(
            lambda q, k, v: fn(q, k, v, axis_name="seq", causal=True),
            mesh=mc.mesh,
            in_specs=P(None, "seq"), out_specs=P(None, "seq"),
        ))(q, k, v)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=2e-4, atol=2e-4,
            err_msg=f"{fn.__name__} {axes}")

    # MQA (G=1) on seq=4: maximal surplus factor, still exact
    k1, v1 = k[:, :, :1], v[:, :, :1]
    ref1 = local_attention(q, jnp.repeat(k1, H, axis=2),
                           jnp.repeat(v1, H, axis=2), causal=True)
    mc = MC(seq=4, data=2)
    got = jax.jit(jax.shard_map(
        lambda q, k, v: ulysses_attention(
            q, k, v, axis_name="seq", causal=True),
        mesh=mc.mesh,
        in_specs=P(None, "seq"), out_specs=P(None, "seq"),
    ))(q, k1, v1)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref1), rtol=2e-4, atol=2e-4,
        err_msg="ulysses MQA over-split")


def test_mqa_train_step_learns():
    """MQA (1 kv head): a few train steps reduce loss and touch wkv."""
    cfg = gqa_cfg(n_kv_heads=1)
    mc = MeshConfig(data=8)
    params = shard_params(
        mc, cfg, init_transformer(jax.random.PRNGKey(0), cfg))
    opt = optax.adam(1e-2)
    opt_state = jax.jit(opt.init)(params)
    step = make_train_step(mc, cfg, opt)
    toks = tokens()
    wkv0 = np.asarray(params["blocks"]["wkv"])
    losses = []
    for _ in range(10):
        params, opt_state, loss = step(
            params, opt_state, toks[:, :T], toks[:, 1:])
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
    assert not np.allclose(np.asarray(params["blocks"]["wkv"]), wkv0)
