"""RoPE (pos_embedding="rope"): relative-position property at the core,
sharded paths (ring/zigzag/ulysses/TP) equal to the single-device oracle,
cached decode equal to the full forward, and 1F1B schedule equivalence —
rope must be a drop-in for the learned table on every path."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from chainermn_tpu.models import (
    TransformerConfig,
    apply_rope,
    init_transformer,
    make_forward_fn,
    make_train_step,
    shard_params,
)
from chainermn_tpu.parallel import MeshConfig

from chainermn_tpu.testing import requires_vma as _requires_vma

# The flagship transformer's custom VJPs read jax.typeof(...).vma to
# place their psums; TransformerConfig deliberately refuses to construct
# on pre-vma jax (models/transformer.py).  Nothing in this module can
# run without it.
pytestmark = _requires_vma(
    "requires vma-typed shard_map (TransformerConfig refuses pre-vma jax)")

VOCAB, B, T = 64, 8, 16


def rope_cfg(**kw):
    base = dict(
        vocab_size=VOCAB, d_model=32, n_heads=4, d_head=8, d_ff=64,
        n_layers=2, max_seq=T, attention="local", dtype="float32",
        remat=False, pos_embedding="rope",
    )
    base.update(kw)
    return TransformerConfig(**base)


def tokens(seed=0):
    return jnp.asarray(
        np.random.RandomState(seed).randint(0, VOCAB, (B, T + 1)),
        jnp.int32)


def one_chip(cfg, params, toks):
    mc = MeshConfig(data=1, devices=jax.devices()[:1])
    return make_forward_fn(mc, cfg)(params, toks)


def test_odd_d_head_rejected():
    with pytest.raises(ValueError, match="even d_head"):
        rope_cfg(d_head=7)


def test_no_pos_param():
    cfg = rope_cfg()
    params = init_transformer(jax.random.PRNGKey(0), cfg)
    assert "pos" not in params


def test_relative_position_property():
    """QK scores after rope depend only on position DIFFERENCES: shifting
    all absolute positions by a constant leaves every dot unchanged."""
    r = np.random.RandomState(0)
    q = jnp.asarray(r.randn(2, 6, 4, 8), jnp.float32)
    k = jnp.asarray(r.randn(2, 6, 4, 8), jnp.float32)
    pos = jnp.arange(6)

    def scores(shift):
        qq = apply_rope(q, pos + shift)
        kk = apply_rope(k, pos + shift)
        return jnp.einsum("bthd,bshd->bhts", qq, kk)

    np.testing.assert_allclose(
        np.asarray(scores(0)), np.asarray(scores(37)),
        rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("axes,kw", [
    (dict(seq=4, data=2), dict(attention="ring")),
    (dict(seq=4, data=2), dict(attention="ring", seq_layout="zigzag")),
    (dict(seq=2, data=4), dict(attention="ulysses")),
    (dict(model=4, data=2), {}),
], ids=["ring", "ring-zigzag", "ulysses", "tp"])
def test_sharded_matches_single_device(axes, kw):
    cfg = rope_cfg(**kw)
    params = init_transformer(jax.random.PRNGKey(0), cfg)
    toks = tokens()[:, :T]
    ref = one_chip(rope_cfg(), params, toks)
    mc = MeshConfig(**axes)
    out = make_forward_fn(mc, cfg)(shard_params(mc, cfg, params), toks)
    got = np.asarray(out)
    if kw.get("seq_layout") == "zigzag":
        from chainermn_tpu.parallel.ring_attention import zigzag_indices

        perm = zigzag_indices(axes["seq"], T).reshape(-1)
        # zigzag configs consume/produce permuted token order; compare in
        # the permuted frame
        ref = np.asarray(ref)[:, perm]
        toks_p = np.asarray(toks)[:, perm]
        out_p = make_forward_fn(mc, cfg)(
            shard_params(mc, cfg, params), jnp.asarray(toks_p))
        got = np.asarray(out_p)
        np.testing.assert_allclose(got, ref, rtol=3e-4, atol=3e-4)
        return
    np.testing.assert_allclose(got, np.asarray(ref), rtol=3e-4, atol=3e-4)


def test_cached_decode_matches_forward():
    from tests.model_tests.test_decoding import (
        _cached_logits_all_positions)

    cfg = rope_cfg(n_kv_heads=2)
    mc = MeshConfig(data=1, devices=jax.devices()[:1])
    params = shard_params(
        mc, cfg, init_transformer(jax.random.PRNGKey(0), cfg))
    toks = tokens()[:B // 2, :T]
    full = make_forward_fn(mc, cfg)(params, toks)
    cached = _cached_logits_all_positions(cfg, params, toks, mc)
    np.testing.assert_allclose(
        np.asarray(cached), np.asarray(full), rtol=2e-4, atol=2e-4)


def test_1f1b_rope_matches_gpipe():
    mc = MeshConfig(pipe=2, data=4)
    toks = tokens()
    x, y = toks[:, :T], toks[:, 1:]
    results = {}
    for sched in ("gpipe", "1f1b"):
        cfg = rope_cfg(n_layers=2, pipeline_schedule=sched,
                       num_microbatches=2)
        params = shard_params(
            mc, cfg, init_transformer(jax.random.PRNGKey(0), cfg, 2))
        opt = optax.sgd(0.1)
        opt_state = jax.jit(opt.init)(params)
        step = make_train_step(mc, cfg, opt)
        losses = []
        for _ in range(3):
            params, opt_state, loss = step(params, opt_state, x, y)
            losses.append(float(loss))
        results[sched] = losses
    np.testing.assert_allclose(
        results["gpipe"], results["1f1b"], rtol=1e-5, atol=1e-6)
