"""Vocab-parallel embedding + LM head (Megatron-style vocab TP): the
sharded-vocab forward, loss, gradients (especially the weight-tied
embed shards), training trajectory, and decode must all match the
replicated-embedding oracle — the M× smaller head is an implementation
detail, not a semantics change."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from chainermn_tpu.models import (
    TransformerConfig,
    init_transformer,
    make_forward_fn,
    make_generate_fn,
    make_train_step,
    shard_params,
)
from chainermn_tpu.models.transformer import lm_loss, param_specs
from chainermn_tpu.parallel import MeshConfig

from chainermn_tpu.testing import requires_vma as _requires_vma

# The flagship transformer's custom VJPs read jax.typeof(...).vma to
# place their psums; TransformerConfig deliberately refuses to construct
# on pre-vma jax (models/transformer.py).  Nothing in this module can
# run without it.
pytestmark = _requires_vma(
    "requires vma-typed shard_map (TransformerConfig refuses pre-vma jax)")

VOCAB, B, T = 64, 8, 16


def tiny_cfg(**kw):
    base = dict(
        vocab_size=VOCAB, d_model=32, n_heads=4, d_head=8, d_ff=64,
        n_layers=2, max_seq=T, attention="local", dtype="float32",
        remat=False,
    )
    base.update(kw)
    return TransformerConfig(**base)


def tokens(seed=0):
    return jnp.asarray(
        np.random.RandomState(seed).randint(0, VOCAB, (B, T + 1)),
        jnp.int32)


def _grads(cfg, mc, params, x, y):
    specs = param_specs(cfg)
    fn = jax.jit(jax.shard_map(
        lambda p, xx, yy: jax.value_and_grad(
            lambda q: jax.lax.pmean(
                lm_loss(cfg, q, xx, yy),
                ("data", "expert", "seq")))(p),
        mesh=mc.mesh,
        in_specs=(specs, P(("data", "expert"), "seq"),
                  P(("data", "expert"), "seq")),
        out_specs=(P(), specs)))
    loss, g = fn(params, x, y)
    return float(loss), jax.tree.map(np.asarray, g)


def test_forward_matches_replicated():
    cfg_vp = tiny_cfg(vocab_parallel=True)
    host = init_transformer(jax.random.PRNGKey(0), cfg_vp)
    toks = tokens()[:, :T]

    one = MeshConfig(data=1, devices=jax.devices()[:1])
    ref = make_forward_fn(one, tiny_cfg())(
        shard_params(one, tiny_cfg(), host), toks)

    mc = MeshConfig(model=4, data=2)
    out = make_forward_fn(mc, cfg_vp)(
        shard_params(mc, cfg_vp, host), toks)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_loss_and_grads_match_replicated():
    """Same mesh, vocab_parallel on vs off: loss equal and every grad
    equal — the embed grad comes back as (V/M, D) shards that must
    concatenate to the replicated run's full (V, D) gradient."""
    toks = tokens(1)
    x, y = toks[:, :T], toks[:, 1:]
    mc = MeshConfig(model=4, data=2)
    host = init_transformer(jax.random.PRNGKey(1), tiny_cfg())

    l_rep, g_rep = _grads(
        tiny_cfg(), mc, shard_params(mc, tiny_cfg(), host), x, y)
    cfg_vp = tiny_cfg(vocab_parallel=True)
    l_vp, g_vp = _grads(
        cfg_vp, mc, shard_params(mc, cfg_vp, host), x, y)

    assert abs(l_rep - l_vp) < 1e-5
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            a, b, rtol=1e-5, atol=1e-6), g_rep, g_vp)


@pytest.mark.parametrize("sched,axes,kw", [
    ("gpipe", dict(model=4, data=2), {}),
    ("1f1b", dict(pipe=2, model=2, data=2), {}),
    ("gpipe", dict(model=4, data=2), dict(fsdp=True)),
    ("gpipe", dict(expert=2, model=2, data=2),
     dict(moe=True, n_experts=4, router_top_k=2)),
], ids=["gpipe", "1f1b", "fsdp", "moe-top2"])
def test_train_step_matches_replicated(sched, axes, kw):
    toks = tokens(2)
    x, y = toks[:, :T], toks[:, 1:]
    mc = MeshConfig(**axes)
    pipe = axes.get("pipe", 1)

    losses = {}
    for vp in (False, True):
        cfg = tiny_cfg(
            n_layers=4, vocab_parallel=vp, pipeline_schedule=sched,
            num_microbatches=2 if pipe > 1 else 1, **kw)
        params = shard_params(
            mc, cfg, init_transformer(jax.random.PRNGKey(0), cfg, pipe))
        opt = optax.adam(1e-2)
        st = jax.jit(opt.init)(params)
        step = make_train_step(mc, cfg, opt)
        p, s, ls = params, st, []
        for _ in range(3):
            p, s, loss = step(p, s, x, y)
            ls.append(float(loss))
        losses[vp] = ls
    np.testing.assert_allclose(losses[True], losses[False],
                               rtol=1e-5, atol=1e-6)


def test_generate_matches_replicated():
    cfg_vp = tiny_cfg(vocab_parallel=True)
    host = init_transformer(jax.random.PRNGKey(3), cfg_vp)
    p = tokens(4)[:, :4]

    one = MeshConfig(data=1, devices=jax.devices()[:1])
    ref = make_generate_fn(one, tiny_cfg(), max_len=12)(
        shard_params(one, tiny_cfg(), host), p)

    mc = MeshConfig(model=4, data=2)
    got = make_generate_fn(mc, cfg_vp, max_len=12)(
        shard_params(mc, cfg_vp, host), p)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_bf16_train_tracks_replicated_bf16():
    """The production dtype: _vp_head's custom VJP casts the logits
    cotangent to bf16 for both grad matmuls — the loss trajectory must
    track the replicated-head bf16 run within bf16 noise."""
    toks = tokens(8)
    x, y = toks[:, :T], toks[:, 1:]
    mc = MeshConfig(model=4, data=2)

    losses = {}
    for vp in (False, True):
        cfg = tiny_cfg(dtype="bfloat16", vocab_parallel=vp)
        # fresh deterministic init per run: the donated step buffers
        # may alias a shared host array (see the DP-vs-single test)
        params = shard_params(
            mc, cfg, init_transformer(jax.random.PRNGKey(2), cfg))
        opt = optax.sgd(0.1)
        st = jax.jit(opt.init)(params)
        step = make_train_step(mc, cfg, opt)
        p, s, ls = params, st, []
        for _ in range(5):
            p, s, loss = step(p, s, x, y)
            ls.append(float(loss))
        losses[vp] = ls
    assert np.isfinite(losses[True]).all()
    np.testing.assert_allclose(losses[True], losses[False],
                               rtol=0.03, atol=0.02)


def test_int8_generate_matches_replicated_int8():
    """Weight-only int8 decode under vocab TP: the sharded rows and
    their dequant scales ride one psum; tokens match the replicated
    int8 run exactly."""
    from chainermn_tpu.models import quantize_params_int8

    cfg_vp = tiny_cfg(vocab_parallel=True)
    host = quantize_params_int8(
        cfg_vp, init_transformer(jax.random.PRNGKey(5), cfg_vp))
    p = tokens(6)[:, :4]

    one = MeshConfig(data=1, devices=jax.devices()[:1])
    ref = make_generate_fn(one, tiny_cfg(), max_len=12, quantized=True)(
        shard_params(one, tiny_cfg(), host), p)

    mc = MeshConfig(model=4, data=2)
    got = make_generate_fn(mc, cfg_vp, max_len=12, quantized=True)(
        shard_params(mc, cfg_vp, host), p)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_loss_chunk_composes_with_vocab_parallel():
    """loss_chunk + vocab_parallel COMPOSE (r4): live logits shrink to
    (B, chunk, V/M).  Loss AND every gradient — including the embed
    shards' — must equal the plain replicated-head run on the same
    mesh; chunk == T pins the C=1 edge."""
    toks = tokens(5)
    x, y = toks[:, :T], toks[:, 1:]
    mc = MeshConfig(model=4, data=2)
    host = init_transformer(jax.random.PRNGKey(4), tiny_cfg())

    l_rep, g_rep = _grads(
        tiny_cfg(), mc, shard_params(mc, tiny_cfg(), host), x, y)
    for chunk in (4, T):
        cfg = tiny_cfg(vocab_parallel=True, loss_chunk=chunk)
        l_c, g_c = _grads(cfg, mc, shard_params(mc, cfg, host), x, y)
        assert abs(l_rep - l_c) < 1e-5, (chunk, l_rep, l_c)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                a, b, rtol=1e-5, atol=1e-6), g_rep, g_c)


def test_loss_chunk_vocab_parallel_needs_divisible_T():
    cfg = tiny_cfg(vocab_parallel=True, loss_chunk=5)  # 5 does not | 16
    mc = MeshConfig(model=4, data=2)
    with pytest.raises(ValueError, match="divide the local sequence"):
        make_train_step(mc, cfg, optax.sgd(0.1))(
            shard_params(mc, cfg,
                         init_transformer(jax.random.PRNGKey(0), cfg)),
            jax.jit(optax.sgd(0.1).init)(
                shard_params(mc, cfg, init_transformer(
                    jax.random.PRNGKey(0), cfg))),
            tokens()[:, :T], tokens()[:, 1:])


def test_vocab_parallel_validation():
    cfg = tiny_cfg(vocab_parallel=True, vocab_size=62)
    with pytest.raises(ValueError, match="divisible"):
        make_forward_fn(MeshConfig(model=4, data=2), cfg)
