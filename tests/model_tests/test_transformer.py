"""Flagship transformer: every parallelism axis, checked against the
single-device oracle (the multi-axis run must be numerically identical —
SPMD sharding is an implementation detail, not a semantics change)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from chainermn_tpu.models import (
    TransformerConfig,
    init_transformer,
    make_forward_fn,
    make_train_step,
    shard_params,
)
from chainermn_tpu.parallel import MeshConfig

from chainermn_tpu.testing import requires_vma as _requires_vma

# The flagship transformer's custom VJPs read jax.typeof(...).vma to
# place their psums; TransformerConfig deliberately refuses to construct
# on pre-vma jax (models/transformer.py).  Nothing in this module can
# run without it.
pytestmark = _requires_vma(
    "requires vma-typed shard_map (TransformerConfig refuses pre-vma jax)")

VOCAB, B, T = 64, 8, 16


def tiny_cfg(**kw):
    base = dict(
        vocab_size=VOCAB, d_model=32, n_heads=4, d_head=8, d_ff=64,
        n_layers=2, max_seq=T, attention="local", dtype="float32",
        remat=False,
    )
    base.update(kw)
    return TransformerConfig(**base)


def tokens(seed=0):
    return jnp.asarray(
        np.random.RandomState(seed).randint(0, VOCAB, (B, T + 1)),
        jnp.int32)


def oracle_logits(cfg, params, toks):
    one = MeshConfig(data=1, devices=jax.devices()[:1])
    return make_forward_fn(one, cfg)(params, toks)


MESHES = [
    dict(data=8),
    dict(model=4, data=2),
    dict(seq=4, data=2),
    dict(pipe=2, data=4),
    dict(pipe=2, model=2, seq=2, data=1),
]


@pytest.mark.parametrize(
    "axes", MESHES, ids=[str(m) for m in MESHES])
def test_forward_matches_oracle(axes):
    pipe = axes.get("pipe", 1)
    cfg = tiny_cfg(
        attention="ring" if axes.get("seq", 1) > 1 else "local",
        num_microbatches=2 if pipe > 1 else 1,
    )
    params = init_transformer(jax.random.PRNGKey(0), cfg, pipe_size=pipe)
    toks = tokens()[:, :T]

    ref_params = params if pipe == 1 else dict(
        params, blocks=jax.tree.map(
            lambda a: a.reshape(1, -1, *a.shape[2:]), params["blocks"]))
    ref = oracle_logits(tiny_cfg(), ref_params, toks)

    mc = MeshConfig(**axes)
    out = make_forward_fn(mc, cfg)(shard_params(mc, cfg, params), toks)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_remat_policy_grad_equivalence():
    """remat_policy='dots' must be a pure scheduling choice: grads equal
    the remat='full' and remat=False paths bit-for-bit (fp32)."""
    from jax.sharding import PartitionSpec as P

    from chainermn_tpu.models.transformer import lm_loss, param_specs

    toks = tokens()
    x, y = toks[:, :T], toks[:, 1:]
    batch_spec = P(("data", "expert"), "seq")
    one = MeshConfig(data=1, devices=jax.devices()[:1])
    grads = {}
    for name, kw in (("none", dict(remat=False)),
                     ("full", dict(remat=True)),
                     ("dots", dict(remat=True, remat_policy="dots"))):
        cfg = tiny_cfg(**kw)
        params = init_transformer(jax.random.PRNGKey(0), cfg)
        specs = param_specs(cfg)
        grad_fn = jax.jit(jax.shard_map(
            lambda p, xx, yy: jax.grad(
                lambda q: lm_loss(cfg, q, xx, yy))(p),
            mesh=one.mesh,
            in_specs=(specs, batch_spec, batch_spec),
            out_specs=specs))
        grads[name] = grad_fn(params, x, y)
    for name in ("full", "dots"):
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-6),
            grads["none"], grads[name])

    with pytest.raises(ValueError, match="remat_policy"):
        tiny_cfg(remat_policy="everything")


def test_ulysses_matches_oracle():
    cfg = tiny_cfg(attention="ulysses")
    params = init_transformer(jax.random.PRNGKey(0), cfg)
    toks = tokens()[:, :T]
    ref = oracle_logits(tiny_cfg(), params, toks)
    mc = MeshConfig(seq=4, data=2)
    out = make_forward_fn(mc, cfg)(shard_params(mc, cfg, params), toks)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_moe_runs_and_balances():
    cfg = tiny_cfg(moe=True, n_experts=4)
    mc = MeshConfig(expert=4, data=2)
    params = shard_params(
        mc, cfg, init_transformer(jax.random.PRNGKey(0), cfg))
    logits = make_forward_fn(mc, cfg)(params, tokens()[:, :T])
    assert logits.shape == (B, T, VOCAB)
    assert np.isfinite(np.asarray(logits)).all()


def test_moe_top2_trains_and_matches_balance():
    """GShard-style top-2 routing composes with EP: the train step runs
    on an expert mesh, loss decreases, aux stays finite."""
    cfg = tiny_cfg(moe=True, n_experts=4, router_top_k=2)
    mc = MeshConfig(expert=4, data=2)
    params = shard_params(
        mc, cfg, init_transformer(jax.random.PRNGKey(0), cfg))
    opt = optax.adam(1e-2)
    opt_state = jax.jit(opt.init)(params)
    step = make_train_step(mc, cfg, opt)
    toks = tokens()
    x, y = toks[:, :T], toks[:, 1:]
    losses = []
    for _ in range(10):
        params, opt_state, loss = step(params, opt_state, x, y)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.8, losses
    with pytest.raises(ValueError, match="router_top_k"):
        tiny_cfg(moe=True, n_experts=4, router_top_k=5)


@pytest.mark.parametrize("axes", [
    dict(data=8),
    dict(pipe=2, model=2, seq=2),
    dict(expert=2, model=2, data=2),
])
def test_train_step_reduces_loss(axes):
    pipe = axes.get("pipe", 1)
    cfg = tiny_cfg(
        attention="ring" if axes.get("seq", 1) > 1 else "local",
        moe=axes.get("expert", 1) > 1,
        n_experts=4,
        num_microbatches=2 if pipe > 1 else 1,
    )
    mc = MeshConfig(**axes)
    params = shard_params(
        mc, cfg, init_transformer(jax.random.PRNGKey(0), cfg, pipe))
    opt = optax.adam(1e-2)
    opt_state = jax.jit(opt.init)(params)
    step = make_train_step(mc, cfg, opt)
    toks = tokens()
    x, y = toks[:, :T], toks[:, 1:]
    losses = []
    for _ in range(10):
        params, opt_state, loss = step(params, opt_state, x, y)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.8, losses


def test_grads_match_data_parallel_vs_single():
    """DP-sharded batch gives the same gradient as one device seeing the
    whole batch — the multi_node_mean_grad equivalence (SURVEY §3.1)."""
    cfg = tiny_cfg()
    toks = tokens(3)
    x, y = toks[:, :T], toks[:, 1:]
    opt = optax.sgd(0.1)

    def run(mc):
        # fresh deterministic init per run: the donated step buffers may
        # alias a shared host array, so runs must not reuse one pytree
        p = shard_params(
            mc, cfg, init_transformer(jax.random.PRNGKey(1), cfg))
        st = jax.jit(opt.init)(p)
        p2, _, loss = make_train_step(mc, cfg, opt)(p, st, x, y)
        return jax.tree.map(np.asarray, p2), float(loss)

    p_dp, l_dp = run(MeshConfig(data=8))
    p_1, l_1 = run(MeshConfig(data=1, devices=jax.devices()[:1]))
    assert abs(l_dp - l_1) < 1e-5
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5),
        p_dp, p_1)


def test_flash_attention_matches_oracle():
    """attention="flash" (Pallas kernel, interpreted off-TPU) must equal
    the XLA local-attention oracle through the full model."""
    cfg = tiny_cfg(attention="flash")
    params = init_transformer(jax.random.PRNGKey(0), cfg)
    toks = tokens()[:, :T]
    ref = oracle_logits(tiny_cfg(), params, toks)
    mc = MeshConfig(data=8)
    out = make_forward_fn(mc, cfg)(shard_params(mc, cfg, params), toks)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_flash_bwd_block_override_train_step_exact():
    """flash_bwd_block_q/k retune the backward kernels' tiling only:
    a train step (loss AND updated params) must be bit-comparable to
    the default tiling — adoption of a sweep winner is purely a perf
    decision."""
    import optax

    from chainermn_tpu.models import make_train_step

    toks = tokens()[:, :T + 1]

    def one_step(cfg):
        mc = MeshConfig(data=1, devices=jax.devices()[:1])
        params = shard_params(
            mc, cfg, init_transformer(jax.random.PRNGKey(0), cfg))
        opt = optax.sgd(1e-2)
        st = jax.jit(opt.init)(params)
        step = make_train_step(mc, cfg, opt)
        params, st, loss = step(params, st, toks[:, :T], toks[:, 1:])
        return jax.tree.map(np.asarray, params), float(loss)

    p_a, l_a = one_step(tiny_cfg(attention="flash"))
    p_b, l_b = one_step(tiny_cfg(attention="flash",
                                 flash_bwd_block_q=16,
                                 flash_bwd_block_k=32))
    assert l_a == l_b
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-6,
                                                atol=2e-7),
        p_a, p_b)


def test_zigzag_ring_matches_oracle():
    """seq_layout="zigzag": tokens fed through the zigzag permutation
    must yield (after un-permuting) the same logits as the contiguous
    oracle — position embeddings and causal masking follow the layout."""
    from chainermn_tpu.parallel.ring_attention import zigzag_indices

    S = 4
    cfg = tiny_cfg(attention="ring", seq_layout="zigzag")
    params = init_transformer(jax.random.PRNGKey(0), cfg)
    toks = tokens()[:, :T]
    ref = oracle_logits(tiny_cfg(), params, toks)

    perm = zigzag_indices(S, T).reshape(-1)
    inv = np.empty_like(perm)
    inv[perm] = np.arange(T)
    mc = MeshConfig(seq=S, data=2)
    out = make_forward_fn(mc, cfg)(
        shard_params(mc, cfg, params), toks[:, perm])
    np.testing.assert_allclose(
        np.asarray(out)[:, inv], np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_zigzag_requires_ring():
    cfg = tiny_cfg(attention="ulysses", seq_layout="zigzag")
    params = init_transformer(jax.random.PRNGKey(0), cfg)
    mc = MeshConfig(seq=4, data=2)
    with pytest.raises(ValueError, match="zigzag"):
        make_forward_fn(mc, cfg)(
            shard_params(mc, cfg, params), tokens()[:, :T])
