"""Transformer 1F1B schedule: the in-schedule-loss train step must be
numerically equivalent to the GPipe train step (same math, different
schedule), including weight-tied embedding gradients, and must train."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from chainermn_tpu.models import (
    TransformerConfig,
    init_transformer,
    make_train_step,
    shard_params,
)
from chainermn_tpu.parallel import MeshConfig

from chainermn_tpu.testing import requires_vma as _requires_vma

# The flagship transformer's custom VJPs read jax.typeof(...).vma to
# place their psums; TransformerConfig deliberately refuses to construct
# on pre-vma jax (models/transformer.py).  Nothing in this module can
# run without it.
pytestmark = _requires_vma(
    "requires vma-typed shard_map (TransformerConfig refuses pre-vma jax)")

VOCAB, B, T = 64, 8, 16


def tiny_cfg(**kw):
    base = dict(
        vocab_size=VOCAB, d_model=32, n_heads=4, d_head=8, d_ff=64,
        n_layers=4, max_seq=T, attention="local", dtype="float32",
        remat=False, num_microbatches=4,
    )
    base.update(kw)
    return TransformerConfig(**base)


def tokens(seed=0):
    return jnp.asarray(
        np.random.RandomState(seed).randint(0, VOCAB, (B, T + 1)),
        jnp.int32)


@pytest.mark.parametrize("axes,M", [
    (dict(pipe=2, data=4), 2),
    (dict(pipe=4, data=2), 4),
    (dict(pipe=2, model=2, seq=2, data=1), 4),
])
def test_1f1b_step_matches_gpipe(axes, M):
    pipe = axes["pipe"]
    mc = MeshConfig(**axes)
    toks = tokens()
    x, y = toks[:, :T], toks[:, 1:]

    results = {}
    for sched in ("gpipe", "1f1b"):
        cfg = tiny_cfg(
            pipeline_schedule=sched, num_microbatches=M,
            attention="ring" if axes.get("seq", 1) > 1 else "local")
        params = shard_params(
            mc, cfg, init_transformer(jax.random.PRNGKey(0), cfg, pipe))
        opt = optax.sgd(0.1)
        opt_state = jax.jit(opt.init)(params)
        step = make_train_step(mc, cfg, opt)
        p, s, losses = params, opt_state, []
        for _ in range(3):
            p, s, loss = step(p, s, x, y)
            losses.append(float(loss))
        results[sched] = (losses, p)

    np.testing.assert_allclose(
        results["1f1b"][0], results["gpipe"][0], rtol=1e-4, atol=1e-5,
        err_msg="1F1B loss trajectory diverges from GPipe")
    for a, b in zip(jax.tree.leaves(results["1f1b"][1]),
                    jax.tree.leaves(results["gpipe"][1])):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=1e-4,
            err_msg="1F1B parameters diverge from GPipe after 3 steps")


def test_1f1b_moe_matches_gpipe():
    """EP + PP(1F1B): the Switch balancing loss and its gradients must
    ride the 1F1B schedule — loss trajectory and parameters must match
    the GPipe schedule, which differentiates loss + 0.01*aux."""
    mc = MeshConfig(pipe=2, expert=2, data=2)
    toks = tokens()
    x, y = toks[:, :T], toks[:, 1:]

    results = {}
    for sched in ("gpipe", "1f1b"):
        cfg = tiny_cfg(pipeline_schedule=sched, moe=True, n_experts=4,
                       num_microbatches=2)
        params = shard_params(
            mc, cfg, init_transformer(jax.random.PRNGKey(0), cfg, 2))
        opt = optax.sgd(0.1)
        opt_state = jax.jit(opt.init)(params)
        step = make_train_step(mc, cfg, opt)
        p, s, losses = params, opt_state, []
        for _ in range(3):
            p, s, loss = step(p, s, x, y)
            losses.append(float(loss))
        results[sched] = (losses, p)

    np.testing.assert_allclose(
        results["1f1b"][0], results["gpipe"][0], rtol=1e-4, atol=1e-5,
        err_msg="MoE 1F1B loss trajectory diverges from GPipe")
    for a, b in zip(jax.tree.leaves(results["1f1b"][1]),
                    jax.tree.leaves(results["gpipe"][1])):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=1e-4,
            err_msg="MoE 1F1B parameters diverge from GPipe (aux "
                    "gradients lost or double-counted in the schedule)")


def test_moe_aux_survives_gpipe_pipelining():
    """VERDICT weak #6: the Switch balancing loss must not be dropped
    when pipelined — a pipelined MoE step must see a nonzero aux
    (observable as a loss difference vs aux-free)."""
    from chainermn_tpu.models.transformer import lm_loss
    from jax.sharding import PartitionSpec as P
    from chainermn_tpu.models import param_specs

    cfg = tiny_cfg(moe=True, n_experts=4, num_microbatches=2)
    mc = MeshConfig(pipe=2, expert=2, data=2)
    params = shard_params(
        mc, cfg, init_transformer(jax.random.PRNGKey(0), cfg, 2))
    toks = tokens()

    def fwd_loss(p, xx, yy):
        return jax.lax.pmean(
            lm_loss(cfg, p, xx, yy), ("data", "expert", "seq"))

    loss = jax.jit(jax.shard_map(
        fwd_loss, mesh=mc.mesh,
        in_specs=(param_specs(cfg), P(("data", "expert"), "seq"),
                  P(("data", "expert"), "seq")),
        out_specs=P()))(params, toks[:, :T], toks[:, 1:])

    # recompute with the aux term explicitly removed: the pipelined aux
    # must be present (loss includes 0.01*aux > 0 for random routing)
    from chainermn_tpu.models.transformer import transformer_forward

    def fwd_aux(p, xx):
        _, aux = transformer_forward(cfg, p, xx)
        return jax.lax.pmean(aux, ("data", "expert", "seq"))

    aux = jax.jit(jax.shard_map(
        fwd_aux, mesh=mc.mesh,
        in_specs=(param_specs(cfg), P(("data", "expert"), "seq")),
        out_specs=P()))(params, toks[:, :T])
    assert float(aux) > 0.0, "pipelined MoE aux loss was dropped"
    assert np.isfinite(float(loss))
