"""Chunked-vocab cross-entropy (``loss_chunk``): the LM head + loss in
token chunks via a custom VJP must be a pure memory/scheduling choice —
loss and every gradient (crucially the psum'd weight-tied embedding
cotangent) equal the whole-shard-logits path."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from chainermn_tpu.models import (
    TransformerConfig,
    init_transformer,
    make_train_step,
    shard_params,
)
from chainermn_tpu.models.transformer import lm_loss, param_specs
from chainermn_tpu.parallel import MeshConfig

from chainermn_tpu.testing import requires_vma as _requires_vma

# The flagship transformer's custom VJPs read jax.typeof(...).vma to
# place their psums; TransformerConfig deliberately refuses to construct
# on pre-vma jax (models/transformer.py).  Nothing in this module can
# run without it.
pytestmark = _requires_vma(
    "requires vma-typed shard_map (TransformerConfig refuses pre-vma jax)")

VOCAB, B, T = 64, 8, 16


def tiny_cfg(**kw):
    base = dict(
        vocab_size=VOCAB, d_model=32, n_heads=4, d_head=8, d_ff=64,
        n_layers=2, max_seq=T, attention="local", dtype="float32",
        remat=False,
    )
    base.update(kw)
    return TransformerConfig(**base)


def tokens(seed=0):
    return jnp.asarray(
        np.random.RandomState(seed).randint(0, VOCAB, (B, T + 1)),
        jnp.int32)


def _grads(cfg, mc, params, x, y):
    specs = param_specs(cfg)
    fn = jax.jit(jax.shard_map(
        lambda p, xx, yy: jax.value_and_grad(
            lambda q: jax.lax.pmean(
                lm_loss(cfg, q, xx, yy),
                ("data", "expert", "seq")))(p),
        mesh=mc.mesh,
        in_specs=(specs, P(("data", "expert"), "seq"),
                  P(("data", "expert"), "seq")),
        out_specs=(P(), specs)))
    loss, g = fn(params, x, y)
    return float(loss), jax.tree.map(np.asarray, g)


@pytest.mark.parametrize("chunk", [4, 8, 16])
def test_chunked_matches_whole_shard_single_device(chunk):
    """fp32 single device: chunk size must not change loss or grads
    beyond summation-order noise (chunk=T exercises the C=1 edge)."""
    toks = tokens()
    x, y = toks[:, :T], toks[:, 1:]
    one = MeshConfig(data=1, devices=jax.devices()[:1])
    params = init_transformer(jax.random.PRNGKey(0), tiny_cfg())

    l0, g0 = _grads(tiny_cfg(), one, params, x, y)
    lc, gc = _grads(tiny_cfg(loss_chunk=chunk), one, params, x, y)
    assert abs(l0 - lc) < 1e-6
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            a, b, rtol=1e-5, atol=1e-6), g0, gc)


def test_chunked_embed_grad_psum_under_dp():
    """The single end-of-scan psum in _head_nll_bwd must reproduce the
    whole-shard path's embed gradient when the batch spans a real data
    axis (the vma-discipline correctness check)."""
    toks = tokens(1)
    x, y = toks[:, :T], toks[:, 1:]
    cfg = tiny_cfg(loss_chunk=4)
    mc = MeshConfig(data=8)
    params = shard_params(
        mc, cfg, init_transformer(jax.random.PRNGKey(1), cfg))
    l_dp, g_dp = _grads(cfg, mc, params, x, y)

    one = MeshConfig(data=1, devices=jax.devices()[:1])
    ref = init_transformer(jax.random.PRNGKey(1), tiny_cfg())
    l_1, g_1 = _grads(tiny_cfg(), one, ref, x, y)

    assert abs(l_dp - l_1) < 1e-5
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            a, b, rtol=1e-5, atol=1e-6), g_dp, g_1)


def test_chunked_train_step_matches_seq_sharded():
    """Sequence-sharded mesh: loss_chunk divides the LOCAL shard length
    (T/seq); the chunked train step tracks the whole-shard one."""
    toks = tokens(2)
    x, y = toks[:, :T], toks[:, 1:]
    mc = MeshConfig(seq=4, data=2)

    losses = {}
    for chunk in (0, 2):
        cfg = tiny_cfg(attention="ring", loss_chunk=chunk)
        params = shard_params(
            mc, cfg, init_transformer(jax.random.PRNGKey(0), cfg))
        opt = optax.sgd(0.1)
        st = jax.jit(opt.init)(params)
        step = make_train_step(mc, cfg, opt)
        p, s, ls = params, st, []
        for _ in range(3):
            p, s, loss = step(p, s, x, y)
            ls.append(float(loss))
        losses[chunk] = ls
    np.testing.assert_allclose(losses[2], losses[0], rtol=1e-5, atol=1e-6)


def test_chunked_rides_1f1b_schedule():
    """loss_chunk applies inside the 1F1B in-schedule loss_fn too."""
    toks = tokens(3)
    x, y = toks[:, :T], toks[:, 1:]
    mc = MeshConfig(pipe=2, data=4)

    losses = {}
    for chunk in (0, 4):
        cfg = tiny_cfg(
            n_layers=4, pipeline_schedule="1f1b", num_microbatches=2,
            loss_chunk=chunk)
        params = shard_params(
            mc, cfg, init_transformer(jax.random.PRNGKey(0), cfg, 2))
        opt = optax.sgd(0.1)
        st = jax.jit(opt.init)(params)
        step = make_train_step(mc, cfg, opt)
        p, s, ls = params, st, []
        for _ in range(3):
            p, s, loss = step(p, s, x, y)
            ls.append(float(loss))
        losses[chunk] = ls
    np.testing.assert_allclose(losses[4], losses[0], rtol=1e-5, atol=1e-6)


def test_loss_chunk_validation():
    with pytest.raises(ValueError, match="loss_chunk"):
        tiny_cfg(loss_chunk=-1)
    # non-divisor surfaces as a trace-time ValueError, not a shape error
    toks = tokens()
    x, y = toks[:, :T], toks[:, 1:]
    one = MeshConfig(data=1, devices=jax.devices()[:1])
    cfg = tiny_cfg(loss_chunk=5)
    params = init_transformer(jax.random.PRNGKey(0), cfg)
    with pytest.raises(ValueError, match="must divide"):
        _grads(cfg, one, params, x, y)
