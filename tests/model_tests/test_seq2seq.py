"""Seq2seq: mask semantics (padding is invisible), convergence, and the
variable-length-gradient DP equivalence the reference's seq2seq example
existed to demonstrate."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from chainermn_tpu.models.seq2seq import (
    EOS,
    PAD,
    Seq2seqConfig,
    init_seq2seq,
    seq2seq_loss,
    seq2seq_translate,
)
from chainermn_tpu.parallel import MeshConfig


from chainermn_tpu.testing import requires_vma as _requires_vma

# Pre-vma shard_map (old check_rep) cannot express what these tests pin:
# grads of replicated outputs taken inside shard_map over-count by the
# axis size, replicated out_specs can't be inferred through gathers, and
# scan carries may not gain replication.  vma typing (jax >= 0.7) is the
# semantic fix; on older jax the cases below are undefined, not wrong.
requires_vma = _requires_vma("requires vma-typed shard_map AD semantics")

CFG = Seq2seqConfig(
    src_vocab=20, tgt_vocab=20, d_embed=16, d_hidden=16, n_layers=2)


def ragged_batch(n, max_len=8, seed=0):
    rng = np.random.RandomState(seed)
    src = np.full((n, max_len), PAD, np.int32)
    tgt = np.full((n, max_len + 1), PAD, np.int32)
    for i in range(n):
        ln = rng.randint(2, max_len + 1)
        s = rng.randint(3, 20, size=ln)
        src[i, :ln] = s
        tgt[i, :ln] = s[::-1]
        tgt[i, ln] = EOS
    return jnp.asarray(src), jnp.asarray(tgt)


def test_loss_finite_and_padding_invariant():
    params = init_seq2seq(jax.random.PRNGKey(0), CFG)
    src, tgt = ragged_batch(8)
    loss = seq2seq_loss(CFG, params, src, tgt)
    assert np.isfinite(float(loss))

    # extra all-PAD columns must not change the loss (mask semantics)
    pad_s = jnp.full((8, 4), PAD, jnp.int32)
    pad_t = jnp.full((8, 4), PAD, jnp.int32)
    loss2 = seq2seq_loss(
        CFG, params,
        jnp.concatenate([src, pad_s], axis=1),
        jnp.concatenate([tgt, pad_t], axis=1))
    np.testing.assert_allclose(float(loss), float(loss2), rtol=1e-5)


def test_reverse_task_converges_and_translates():
    import optax

    params = init_seq2seq(jax.random.PRNGKey(0), CFG)
    opt = optax.adam(5e-3)
    opt_state = opt.init(params)
    src, tgt = ragged_batch(32, seed=1)

    @jax.jit
    def step(p, s):
        loss, g = jax.value_and_grad(
            lambda q: seq2seq_loss(CFG, q, src, tgt))(p)
        u, s = opt.update(g, s, p)
        return optax.apply_updates(p, u), s, loss

    losses = []
    for _ in range(150):
        params, opt_state, loss = step(params, opt_state)
        losses.append(float(loss))
    assert losses[-1] < 0.3 * losses[0], (losses[0], losses[-1])

    out = np.asarray(seq2seq_translate(CFG, params, src, max_len=9))
    ref = np.asarray(tgt)
    token_acc = (out == ref)[ref != PAD].mean()
    assert token_acc > 0.5, token_acc
    # PAD-after-EOS contract
    for row in out:
        hit = np.where(row == EOS)[0]
        if hit.size:
            assert (row[hit[0] + 1:] == PAD).all()


@requires_vma
def test_dp_grads_match_single_device_on_ragged_batch():
    """The reference's 'variable-length allreduce': data-sharded ragged
    batches produce the same *weighted* global gradient as one device.
    Per-shard losses are means over unequal token counts, so the global
    loss is the token-weighted combination — exactly what a per-token
    global mean on one device computes."""
    params = init_seq2seq(jax.random.PRNGKey(2), CFG)
    src, tgt = ragged_batch(16, seed=3)
    mc = MeshConfig(data=8)

    def local_tokens(s, t):
        return (t != PAD).sum(dtype=jnp.float32)

    def sharded(p, s, t):
        ntok = local_tokens(s, t)
        w = ntok / jax.lax.psum(ntok, "data")
        loss = seq2seq_loss(CFG, p, s, t)
        g = jax.grad(
            lambda q: jax.lax.psum(seq2seq_loss(CFG, q, s, t) * w, "data")
        )(p)
        return jax.lax.psum(loss * w, "data"), g

    f = jax.jit(jax.shard_map(
        sharded, mesh=mc.mesh,
        in_specs=(P(), P("data"), P("data")),
        out_specs=(P(), P())))
    loss_dp, g_dp = f(params, src, tgt)

    loss_1, g_1 = jax.value_and_grad(
        lambda q: seq2seq_loss(CFG, q, src, tgt))(params)
    np.testing.assert_allclose(float(loss_dp), float(loss_1), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=1e-6),
        g_dp, g_1)
