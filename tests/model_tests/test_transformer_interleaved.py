"""Transformer interleaved-1F1B schedule: virtual_pipe>1 must match the
GPipe train step numerically (same math, interleaved schedule), with the
forward path and weight-tied grads intact, and must train."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from chainermn_tpu.models import (
    TransformerConfig,
    init_transformer,
    make_forward_fn,
    make_train_step,
    shard_params,
)
from chainermn_tpu.parallel import MeshConfig

from chainermn_tpu.testing import requires_vma as _requires_vma

# The flagship transformer's custom VJPs read jax.typeof(...).vma to
# place their psums; TransformerConfig deliberately refuses to construct
# on pre-vma jax (models/transformer.py).  Nothing in this module can
# run without it.
pytestmark = _requires_vma(
    "requires vma-typed shard_map (TransformerConfig refuses pre-vma jax)")

VOCAB, B, T = 64, 8, 16


def tiny_cfg(**kw):
    base = dict(
        vocab_size=VOCAB, d_model=32, n_heads=4, d_head=8, d_ff=64,
        n_layers=8, max_seq=T, attention="local", dtype="float32",
        remat=False, num_microbatches=4,
    )
    base.update(kw)
    return TransformerConfig(**base)


def tokens(seed=0):
    return jnp.asarray(
        np.random.RandomState(seed).randint(0, VOCAB, (B, T + 1)),
        jnp.int32)


def test_virtual_pipe_requires_interleaved():
    with pytest.raises(ValueError, match="interleaved"):
        tiny_cfg(virtual_pipe=2)


@pytest.mark.parametrize("axes,V,M", [
    (dict(pipe=2, data=4), 2, 2),
    (dict(pipe=2, data=4), 4, 2),
    (dict(pipe=4, data=2), 2, 4),
    (dict(pipe=2, model=2, data=2), 2, 4),
])
def test_interleaved_step_matches_gpipe(axes, V, M):
    pipe = axes["pipe"]
    mc = MeshConfig(**axes)
    toks = tokens()
    x, y = toks[:, :T], toks[:, 1:]

    results = {}
    for sched, v in (("gpipe", 1), ("interleaved", V)):
        cfg = tiny_cfg(pipeline_schedule=sched, virtual_pipe=v,
                       num_microbatches=M)
        params = shard_params(
            mc, cfg, init_transformer(jax.random.PRNGKey(0), cfg, pipe))
        opt = optax.sgd(0.1)
        opt_state = jax.jit(opt.init)(params)
        step = make_train_step(mc, cfg, opt)
        p, s, losses = params, opt_state, []
        for _ in range(3):
            p, s, loss = step(p, s, x, y)
            losses.append(float(loss))
        results[sched] = (p, losses)

    # identical losses every step => identical grads through the
    # schedule (embed/pos/ln_f replicated leaves compare directly)
    np.testing.assert_allclose(
        results["gpipe"][1], results["interleaved"][1],
        rtol=1e-5, atol=1e-6)
    for leaf in ("embed", "pos", "ln_f"):
        np.testing.assert_allclose(
            np.asarray(results["interleaved"][0][leaf]),
            np.asarray(results["gpipe"][0][leaf]),
            rtol=1e-4, atol=1e-5, err_msg=leaf)
    # block params: gpipe blocks are (pipe, L/pipe, ...), interleaved
    # (pipe, V, L/(pipe*V), ...) with virtual-stage assignment — compare
    # layer-by-layer through the packing map g = c*pipe + s
    gp_blocks = jax.tree.map(
        lambda a: np.asarray(a), results["gpipe"][0]["blocks"])
    il_blocks = jax.tree.map(
        lambda a: np.asarray(a), results["interleaved"][0]["blocks"])
    lpc = tiny_cfg().n_layers // (pipe * V)
    lps = tiny_cfg().n_layers // pipe

    def layer_from_gpipe(tree, g_layer):
        return jax.tree.map(
            lambda a: a[g_layer // lps, g_layer % lps], tree)

    def layer_from_interleaved(tree, g_layer):
        g = g_layer // lpc          # virtual stage
        return jax.tree.map(
            lambda a: a[g % pipe, g // pipe, g_layer % lpc], tree)

    for L in range(tiny_cfg().n_layers):
        a = layer_from_gpipe(gp_blocks, L)
        b = layer_from_interleaved(il_blocks, L)
        for x1, x2 in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
            np.testing.assert_allclose(
                x1, x2, rtol=1e-4, atol=1e-5,
                err_msg=f"layer {L}")


def test_interleaved_moe_matches_gpipe():
    """EP + interleaved PP: the Switch balancing loss and its gradients
    must ride the interleaved schedule — loss trajectory must match the
    GPipe schedule (which differentiates loss + 0.01*aux)."""
    pipe, V, M = 2, 2, 2
    mc = MeshConfig(pipe=pipe, expert=2, data=2)
    toks = tokens()
    x, y = toks[:, :T], toks[:, 1:]

    results = {}
    for sched, v in (("gpipe", 1), ("interleaved", V)):
        cfg = tiny_cfg(pipeline_schedule=sched, virtual_pipe=v,
                       num_microbatches=M, moe=True, n_experts=4)
        params = shard_params(
            mc, cfg, init_transformer(jax.random.PRNGKey(0), cfg, pipe))
        opt = optax.sgd(0.1)
        opt_state = jax.jit(opt.init)(params)
        step = make_train_step(mc, cfg, opt)
        p, s, losses = params, opt_state, []
        for _ in range(3):
            p, s, loss = step(p, s, x, y)
            losses.append(float(loss))
        results[sched] = (p, losses)

    np.testing.assert_allclose(
        results["gpipe"][1], results["interleaved"][1],
        rtol=1e-4, atol=1e-5,
        err_msg="MoE interleaved loss trajectory diverges from GPipe "
                "(aux gradients lost or double-counted in the schedule)")


def test_interleaved_forward_matches_single_device():
    """The chunk-looped forward path reproduces the unpipelined oracle."""
    pipe, V = 2, 2
    cfg = tiny_cfg(pipeline_schedule="interleaved", virtual_pipe=V,
                   num_microbatches=2)
    params = init_transformer(jax.random.PRNGKey(0), cfg, pipe)
    toks = tokens()[:, :T]

    # repack interleaved (pipe, V, lpc, ...) into the flat oracle layout
    lpc = cfg.n_layers // (pipe * V)
    flat = jax.tree.map(
        lambda a: a.swapaxes(0, 1).reshape(1, -1, *a.shape[3:]),
        params["blocks"])
    oracle_params = dict(params, blocks=flat)
    one = MeshConfig(data=1, devices=jax.devices()[:1])
    ref = make_forward_fn(one, tiny_cfg())(oracle_params, toks)

    mc = MeshConfig(pipe=pipe, data=4)
    out = make_forward_fn(mc, cfg)(shard_params(mc, cfg, params), toks)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_interleaved_trains():
    cfg = tiny_cfg(pipeline_schedule="interleaved", virtual_pipe=2,
                   num_microbatches=4)
    mc = MeshConfig(pipe=4, data=2)
    params = shard_params(
        mc, cfg, init_transformer(jax.random.PRNGKey(0), cfg, 4))
    opt = optax.adam(1e-2)
    opt_state = jax.jit(opt.init)(params)
    step = make_train_step(mc, cfg, opt)
    toks = tokens()
    losses = []
    for _ in range(10):
        params, opt_state, loss = step(
            params, opt_state, toks[:, :T], toks[:, 1:])
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
