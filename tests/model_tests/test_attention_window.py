"""Sliding-window (Mistral-style) causal attention: every path — XLA
core, Pallas kernel (fwd+bwd), ring schedule (both layouts), Ulysses,
the flagship forward, and the KV-cached decode — must match a dense
oracle with an explicit band mask."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from chainermn_tpu.models import (
    TransformerConfig,
    init_transformer,
    make_forward_fn,
    shard_params,
)
from chainermn_tpu.ops.pallas_attention import flash_attention
from chainermn_tpu.parallel import MeshConfig
from chainermn_tpu.parallel.ring_attention import (
    local_attention,
    ring_attention,
)

from chainermn_tpu.testing import requires_vma as _requires_vma

# Pre-vma shard_map (old check_rep) cannot express what these tests pin:
# grads of replicated outputs taken inside shard_map over-count by the
# axis size, replicated out_specs can't be inferred through gathers, and
# scan carries may not gain replication.  vma typing (jax >= 0.7) is the
# semantic fix; on older jax the cases below are undefined, not wrong.
requires_vma = _requires_vma("requires vma-typed shard_map AD semantics")

W = 5
VOCAB, B, T = 64, 4, 16


def dense_banded_oracle(q, k, v, window):
    """Explicit band-mask softmax attention (the ground truth)."""
    s = jnp.einsum("bthd,bshd->bhts", q, k) * (q.shape[-1] ** -0.5)
    tq, tk = q.shape[1], k.shape[1]
    qpos = jnp.arange(tq)[:, None]
    kpos = jnp.arange(tk)[None, :]
    allow = (qpos >= kpos) & (qpos - kpos < window)
    s = jnp.where(allow[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhts,bshd->bthd", p, v)


def qkv(seed=0, t=T, h=4, d=8):
    r = np.random.RandomState(seed)
    return tuple(jnp.asarray(r.randn(B, t, h, d), jnp.float32)
                 for _ in range(3))


def test_local_attention_window_matches_oracle():
    q, k, v = qkv()
    got = local_attention(q, k, v, causal=True, window=W)
    ref = dense_banded_oracle(q, k, v, W)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    with pytest.raises(ValueError, match="causal"):
        local_attention(q, k, v, window=W)


@requires_vma
def test_flash_kernel_window_fwd_bwd():
    """Kernel (interpret mode) vs oracle, values AND grads — the block
    skipping must not drop in-window contributions."""
    q, k, v = qkv(t=32)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=True, window=W,
                            block_q=8, block_k=8, interpret=True)
        return jnp.sum(o.astype(jnp.float32) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(dense_banded_oracle(q, k, v, W) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(float(loss_flash(q, k, v)),
                               float(loss_ref(q, k, v)),
                               rtol=1e-4, atol=1e-4)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


@requires_vma
def test_flash_kernel_window_with_offsets():
    """The offset+window block-skip arithmetic (the ring-flash pairing's
    riskiest inequality): kernel with global offsets vs the XLA core at
    the same global positions, values and grads."""
    q, k, v = qkv(t=32)
    # staggered but never fully-masked: every q row keeps >=1 in-window
    # key (fully-masked rows are the documented kernel/XLA divergence)
    q_off, k_off = 66, 64

    def loss_flash(q, k, v):
        o = flash_attention(
            q, k, v, causal=True, window=W, q_offset=q_off,
            k_offset=k_off, block_q=8, block_k=8, interpret=True)
        return jnp.sum(o.astype(jnp.float32) ** 2)

    def loss_ref(q, k, v):
        o = local_attention(q, k, v, causal=True, window=W,
                            q_offset=q_off, k_offset=k_off)
        return jnp.sum(o.astype(jnp.float32) ** 2)

    np.testing.assert_allclose(float(loss_flash(q, k, v)),
                               float(loss_ref(q, k, v)),
                               rtol=1e-4, atol=1e-4)
    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_ring_window_larger_blocks_matches_oracle():
    """Ring with T_blk=32 (kernel-eligible block sizes) and windows both
    smaller and larger than a shard — exercises the truncated ring."""
    from jax.sharding import PartitionSpec as P

    t = 128
    q, k, v = qkv(t=t)
    mc = MeshConfig(seq=4, data=2)
    for w in (8, 48, 100):
        ref = dense_banded_oracle(q, k, v, w)
        got = jax.jit(jax.shard_map(
            lambda q, k, v, w=w: ring_attention(
                q, k, v, axis_name="seq", causal=True, window=w),
            mesh=mc.mesh, in_specs=P(None, "seq"),
            out_specs=P(None, "seq")))(q, k, v)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=2e-4, atol=2e-4,
            err_msg=f"window={w}")


@pytest.mark.parametrize("layout", ["contiguous", "zigzag"])
def test_ring_window_matches_oracle(layout):
    from jax.sharding import PartitionSpec as P

    from chainermn_tpu.parallel.ring_attention import zigzag_indices

    q, k, v = qkv()
    ref = dense_banded_oracle(q, k, v, W)
    mc = MeshConfig(seq=4, data=2)
    if layout == "zigzag":
        perm = zigzag_indices(4, T).reshape(-1)
        q, k, v = (t[:, perm] for t in (q, k, v))
        ref = ref[:, perm]
    got = jax.jit(jax.shard_map(
        lambda q, k, v: ring_attention(
            q, k, v, axis_name="seq", causal=True, window=W,
            layout=layout),
        mesh=mc.mesh, in_specs=P(None, "seq"),
        out_specs=P(None, "seq")))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def window_cfg(**kw):
    base = dict(
        vocab_size=VOCAB, d_model=32, n_heads=4, d_head=8, d_ff=64,
        n_layers=2, max_seq=T, attention="local", dtype="float32",
        remat=False, attention_window=W,
    )
    base.update(kw)
    return TransformerConfig(**base)


@pytest.mark.parametrize("axes,kw", [
    (dict(seq=4, data=2), dict(attention="ring")),
    (dict(seq=2, data=4), dict(attention="ulysses")),
], ids=["ring", "ulysses"])
@requires_vma
def test_windowed_model_sharded_matches_single(axes, kw):
    cfg = window_cfg(**kw)
    params = init_transformer(jax.random.PRNGKey(0), cfg)
    toks = jnp.asarray(
        np.random.RandomState(0).randint(0, VOCAB, (B, T)), jnp.int32)
    one = MeshConfig(data=1, devices=jax.devices()[:1])
    ref = make_forward_fn(one, window_cfg())(params, toks)
    # and the window genuinely changes the full-causal output
    full = make_forward_fn(one, window_cfg(attention_window=0))(
        params, toks)
    assert not np.allclose(np.asarray(ref), np.asarray(full), atol=1e-3)

    mc = MeshConfig(**axes)
    out = make_forward_fn(mc, cfg)(shard_params(mc, cfg, params), toks)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=3e-4, atol=3e-4)


@requires_vma
def test_windowed_decode_matches_forward():
    from tests.model_tests.test_decoding import (
        _cached_logits_all_positions)

    cfg = window_cfg()
    mc = MeshConfig(data=1, devices=jax.devices()[:1])
    params = shard_params(
        mc, cfg, init_transformer(jax.random.PRNGKey(0), cfg))
    toks = jnp.asarray(
        np.random.RandomState(1).randint(0, VOCAB, (B, T)), jnp.int32)
    full = make_forward_fn(mc, cfg)(params, toks)
    cached = _cached_logits_all_positions(cfg, params, toks, mc)
    np.testing.assert_allclose(np.asarray(cached), np.asarray(full),
                               rtol=2e-4, atol=2e-4)


@requires_vma
def test_negative_window_rejected():
    with pytest.raises(ValueError, match="attention_window"):
        window_cfg(attention_window=-1)
