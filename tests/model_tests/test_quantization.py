"""Weight-only int8 decoding: quantized logits must track the fp path
closely, generation must run on DP+TP meshes, and the quantize transform
must satisfy its per-channel error bound."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from chainermn_tpu.models import (
    TransformerConfig,
    init_transformer,
    make_beam_search_fn,
    make_generate_fn,
    param_specs,
    quantize_params_int8,
    shard_params,
)
from chainermn_tpu.models.decoding import _decode_step, _make_cache, _vary
from chainermn_tpu.parallel import MeshConfig

from chainermn_tpu.testing import requires_vma as _requires_vma

# The flagship transformer's custom VJPs read jax.typeof(...).vma to
# place their psums; TransformerConfig deliberately refuses to construct
# on pre-vma jax (models/transformer.py).  Nothing in this module can
# run without it.
pytestmark = _requires_vma(
    "requires vma-typed shard_map (TransformerConfig refuses pre-vma jax)")

VOCAB, B, T = 64, 4, 16


def tiny_cfg(**kw):
    base = dict(
        vocab_size=VOCAB, d_model=32, n_heads=4, d_head=8, d_ff=64,
        n_layers=2, max_seq=T, attention="local", dtype="float32",
        remat=False,
    )
    base.update(kw)
    return TransformerConfig(**base)


def prompt(seed=0, length=T):
    return jnp.asarray(
        np.random.RandomState(seed).randint(0, VOCAB, (B, length)),
        jnp.int32)


def test_quantize_error_bound():
    cfg = tiny_cfg()
    params = init_transformer(jax.random.PRNGKey(0), cfg)
    q = quantize_params_int8(cfg, params)
    # reconstruction error <= scale/2 per channel (round-to-nearest)
    w = np.asarray(params["blocks"]["w1"])          # (1, L, D, F)
    wq = np.asarray(q["blocks"]["w1"]).astype(np.float32)
    s = np.asarray(q["blocks"]["w1_scale"])          # (1, L, F)
    err = np.abs(wq * s[:, :, None, :] - w)
    assert (err <= s[:, :, None, :] * 0.5 + 1e-8).all()
    assert q["blocks"]["w1"].dtype == jnp.int8
    assert q["embed"].dtype == jnp.int8
    # non-quantized leaves pass through untouched
    np.testing.assert_array_equal(q["blocks"]["ln1"],
                                  params["blocks"]["ln1"])


def _decode_logits(cfg, params, toks, steps, quantized):
    """Teacher-forced cached decode of ``steps`` positions on a
    single-device mesh, with plain or quantized param specs."""
    mc = MeshConfig(data=1, devices=jax.devices()[:1])

    def body(params, toks):
        caches = _make_cache(cfg, B, T, cfg.kv_heads, cfg.n_layers)
        outs = []
        for t in range(steps):
            logits, caches = _decode_step(
                cfg, params, caches, toks[:, t], t)
            outs.append(logits)
        return jnp.stack(outs, 1)

    fn = jax.jit(jax.shard_map(
        body, mesh=mc.mesh,
        in_specs=(param_specs(cfg, quantized=quantized),
                  P(("data", "expert"))),
        out_specs=P(("data", "expert"))))
    return fn(shard_params(mc, cfg, params), toks)


def _assert_quantized_tracks_fp(cfg, seed, steps):
    params = init_transformer(jax.random.PRNGKey(seed), cfg)
    qparams = quantize_params_int8(cfg, params)
    toks = prompt(seed, steps)
    ref = _decode_logits(cfg, params, toks, steps, False)
    out = _decode_logits(cfg, qparams, toks, steps, True)
    # int8 per-channel weight error ~0.4%/layer; logits track within a
    # few percent of the logit RANGE on this tiny random model
    scale = float(jnp.max(jnp.abs(ref)))
    assert float(jnp.max(jnp.abs(out - ref))) < 0.05 * scale


@pytest.mark.parametrize("gqa", [False, True], ids=["mha", "gqa"])
def test_quantized_logits_close(gqa):
    _assert_quantized_tracks_fp(tiny_cfg(n_kv_heads=2 if gqa else 0),
                                seed=1, steps=4)


@pytest.mark.parametrize("axes", [dict(data=1), dict(data=4, model=2)],
                         ids=["single", "dp-tp"])
def test_quantized_generate_runs(axes):
    cfg = tiny_cfg(n_kv_heads=2, pos_embedding="rope")
    params = init_transformer(jax.random.PRNGKey(3), cfg)
    qparams = quantize_params_int8(cfg, params)
    mc = (MeshConfig(data=1, devices=jax.devices()[:1])
          if axes == dict(data=1) else MeshConfig(**axes))
    qparams = shard_params(mc, cfg, qparams)
    gen = make_generate_fn(mc, cfg, max_len=12, quantized=True)
    out = gen(qparams, prompt(4, 4))
    assert out.shape == (B, 12)
    assert (np.asarray(out) >= 0).all() and (np.asarray(out) < VOCAB).all()
    # prompt preserved
    np.testing.assert_array_equal(np.asarray(out[:, :4]),
                                  np.asarray(prompt(4, 4)))


def test_quantized_beam_search_runs():
    cfg = tiny_cfg()
    params = init_transformer(jax.random.PRNGKey(5), cfg)
    qparams = quantize_params_int8(cfg, params)
    mc = MeshConfig(data=1, devices=jax.devices()[:1])
    qparams = shard_params(mc, cfg, qparams)
    bs = make_beam_search_fn(mc, cfg, beam_size=3, max_len=10,
                             quantized=True)
    toks, scores = bs(qparams, prompt(6, 4))
    assert toks.shape == (B, 3, 10)
    # scores sorted best-first
    s = np.asarray(scores)
    assert (np.diff(s, axis=1) <= 1e-6).all()


def test_quantized_windowed_decode_logits_close():
    """int8 composes with sliding-window causal decode (the window mask
    lives in the attention path, orthogonal to weight storage)."""
    _assert_quantized_tracks_fp(
        tiny_cfg(n_kv_heads=2, attention_window=4, pos_embedding="rope"),
        seed=7, steps=6)


def test_moe_quantized_logits_close():
    """Expert stacks quantize per expert; router WEIGHTS stay fp (its
    inputs still carry quantization noise from earlier layers, so a
    near-tie between experts can flip routing — the tolerance below
    holds because such ties are rare, not impossible)."""
    cfg = tiny_cfg(moe=True, n_experts=2)
    params = init_transformer(jax.random.PRNGKey(9), cfg)
    q = quantize_params_int8(cfg, params)
    assert q["blocks"]["w1"].dtype == jnp.int8
    assert q["blocks"]["w1_scale"].shape == (1, 2, 2, 64)  # (pipe,L,E,F)
    assert q["blocks"]["router"].dtype == jnp.float32
    _assert_quantized_tracks_fp(cfg, seed=9, steps=4)


def test_moe_quantized_generate_runs():
    cfg = tiny_cfg(moe=True, n_experts=2)
    params = init_transformer(jax.random.PRNGKey(10), cfg)
    mc = MeshConfig(data=4, expert=2)
    qparams = shard_params(mc, cfg, quantize_params_int8(cfg, params))
    gen = make_generate_fn(mc, cfg, max_len=10, quantized=True)
    toks = jnp.asarray(
        np.random.RandomState(11).randint(0, VOCAB, (8, 4)), jnp.int32)
    out = gen(qparams, toks)
    assert out.shape == (8, 10)


class TestInt8KVCache:
    """kv_cache_dtype="int8": decode logits must track the fp-cache
    path within quantization noise, the speculative exact-greedy
    guarantee must survive (both paths read the SAME quantized cache),
    and the cache must actually be int8 with trailing-singleton
    scales."""

    def _cached_logits(self, cfg, params, toks, steps):
        mc = MeshConfig(data=1, devices=jax.devices()[:1])

        def body(params, toks):
            caches = _make_cache(cfg, B, T, cfg.kv_heads, cfg.n_layers)
            assert len(caches) == (4 if cfg.kv_cache_dtype else 2)
            if cfg.kv_cache_dtype:
                assert caches[0].dtype == jnp.int8
                assert caches[2].shape[-1] == 1
            outs = []
            for t in range(steps):
                logits, caches = _decode_step(
                    cfg, params, caches, toks[:, t], t)
                outs.append(logits)
            return jnp.stack(outs, 1)

        fn = jax.jit(jax.shard_map(
            body, mesh=mc.mesh,
            in_specs=(param_specs(cfg), P(("data", "expert"))),
            out_specs=P(("data", "expert"))))
        return fn(shard_params(mc, cfg, params), toks)

    @pytest.mark.parametrize("gqa", [False, True], ids=["mha", "gqa"])
    def test_logits_track_fp_cache(self, gqa):
        kw = dict(n_kv_heads=2 if gqa else 0)
        host = init_transformer(jax.random.PRNGKey(2), tiny_cfg(**kw))
        toks = prompt(2, 8)
        ref = self._cached_logits(tiny_cfg(**kw), host, toks, 8)
        out = self._cached_logits(
            tiny_cfg(kv_cache_dtype="int8", **kw), host, toks, 8)
        scale = float(jnp.max(jnp.abs(ref)))
        assert float(jnp.max(jnp.abs(out - ref))) < 0.05 * scale

    def test_generate_runs_on_tp_mesh(self):
        cfg = tiny_cfg(kv_cache_dtype="int8", n_kv_heads=2)
        mc = MeshConfig(data=2, model=2, devices=jax.devices()[:4])
        params = shard_params(
            mc, cfg, init_transformer(jax.random.PRNGKey(3), cfg))
        out = make_generate_fn(mc, cfg, max_len=12)(
            params, prompt(3, 4))
        assert out.shape == (B, 12)
        assert (np.asarray(out) < VOCAB).all()

    def test_seq_kv_blockwise_scales(self):
        """int8 cache + sequence-parallel KV: the blockwise prefill
        writes hit the scale arrays through the same mask machinery —
        tokens on the seq-KV mesh must equal the int8 single-device
        run exactly (quantisation is per-(token, head), so the layout
        cannot change it; fp-accuracy of int8 itself is pinned by
        test_logits_track_fp_cache)."""
        cfg8 = tiny_cfg(kv_cache_dtype="int8")
        cfg = tiny_cfg()
        host = init_transformer(jax.random.PRNGKey(4), cfg)
        p = prompt(4, 4)

        def gen(c, mc):
            return np.asarray(make_generate_fn(mc, c, max_len=12)(
                shard_params(mc, c, host), p))

        mc = MeshConfig(seq=2, data=2, devices=jax.devices()[:4])
        one = MeshConfig(data=1, devices=jax.devices()[:1])
        # int8 tokens on the seq-KV mesh == int8 tokens on one device
        # (quantisation is per-(token, head) — layout-independent)
        np.testing.assert_array_equal(gen(cfg8, mc), gen(cfg8, one))

    def test_speculative_stays_exact_greedy(self):
        """Both the per-token and the chunk-verify paths read back the
        SAME quantized cache entries, so the exact-greedy guarantee is
        preserved under int8 KV (vs the int8-cache greedy oracle)."""
        import dataclasses

        from chainermn_tpu.models import make_speculative_generate_fn

        cfg = tiny_cfg(kv_cache_dtype="int8", n_layers=4)
        d_cfg = dataclasses.replace(cfg, n_layers=2)
        one = MeshConfig(data=1, devices=jax.devices()[:1])
        host = init_transformer(jax.random.PRNGKey(5), cfg)
        d_host = dict(host, blocks=jax.tree.map(
            lambda a: a[:, :2], host["blocks"]))
        p = prompt(5, 4)
        params = shard_params(one, cfg, host)
        ref = np.asarray(
            make_generate_fn(one, cfg, max_len=12)(params, p))
        got = np.asarray(make_speculative_generate_fn(
            one, cfg, d_cfg, k=3, max_len=12)(
            params, shard_params(one, d_cfg, d_host), p))
        np.testing.assert_array_equal(got, ref)

    def test_validation(self):
        with pytest.raises(ValueError, match="kv_cache_dtype"):
            tiny_cfg(kv_cache_dtype="fp8")

    def test_bf16_quant_never_overflows_int8(self):
        """bf16 scales round below absmax/127, so the max element's
        ratio can land on +128 — the clip keeps every cached value in
        [-127, 127] (without it, wraparound backends sign-flip the
        LARGEST K/V component of ~17% of (token, head) rows)."""
        cfg = tiny_cfg(kv_cache_dtype="int8", dtype="bfloat16")
        mc = MeshConfig(data=1, devices=jax.devices()[:1])
        params = shard_params(
            mc, cfg, init_transformer(jax.random.PRNGKey(6), cfg))

        def body(params, toks):
            caches = _make_cache(cfg, B, T, cfg.kv_heads, cfg.n_layers)
            _, caches = _decode_step(cfg, params, caches, toks, 0,
                                     with_logits=False)
            # the cache is typed varying over every mesh axis: reduce
            # to invariant scalars for a P() output
            axes = ("pipe", "data", "expert", "model")
            return jnp.stack([
                jnp.stack((lax.pmin(jnp.min(c.astype(jnp.int32)), axes),
                           lax.pmax(jnp.max(c.astype(jnp.int32)), axes)))
                for c in caches[:2]])

        fn = jax.jit(jax.shard_map(
            body, mesh=mc.mesh,
            in_specs=(param_specs(cfg), P(("data", "expert"))),
            out_specs=P()))
        stats = np.asarray(fn(params, prompt(6, T)))
        assert stats.min() >= -127 and stats.max() <= 127, stats
