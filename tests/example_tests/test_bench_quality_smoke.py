"""bench_quality.py must WORK end-to-end before its first live TPU
window (VERDICT r4 weak #4: it was the one bench never executed —
discovering a harness bug during a rare live window would waste it).
This drives the real smoke config: corpus synthesis -> BPE train ->
half-run with checkpoint -> resume (marker asserted by the harness) ->
held-out byte perplexity, all in fresh interpreters exactly as the
babysitter launches it."""

import json
import os
import subprocess
import sys

_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", ".."))


def test_bench_quality_smoke_end_to_end():
    env = dict(os.environ)
    for k in list(env):
        if k.startswith(("TPU_", "LIBTPU", "PJRT_", "JAX_")):
            env.pop(k)
    # the suite's conftest pins an 8-virtual-device XLA_FLAGS for the
    # in-process mesh tests; the bench's train children run --mesh
    # data=1 and must see the plain host device config
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "bench_quality.py"),
         "--platform", "cpu", "--timeouts", "2400"],
        capture_output=True, text=True, timeout=2500, cwd=_ROOT, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = [l for l in proc.stdout.splitlines() if l.startswith("{")][-1]
    rec = json.loads(line)
    assert rec["metric"] == "lm_quality_heldout_byte_ppl"
    # learning happened: better than byte-uniform (256), and the
    # interruption+resume path demonstrably ran
    assert rec["value"] is not None and 1.0 < rec["value"] < 256.0
    assert rec["resume_verified"] is True
    assert not rec.get("cached"), "smoke must be a live run"
