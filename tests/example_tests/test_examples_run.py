"""Every example script must RUN end-to-end on the virtual CPU pod —
the reference's examples were its de-facto integration suite (run under
``mpiexec`` in CI, SURVEY.md §4); these are ours, exercised exactly as a
user would launch them (fresh interpreter, CLI flags, tiny settings)."""

import os
import subprocess
import sys

import pytest

_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", ".."))


def _example_env():
    env = dict(os.environ)
    for k in list(env):
        if k.startswith(("TPU_", "LIBTPU", "PJRT_", "JAX_")):
            env.pop(k)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _run_example(relpath, args, timeout=420, check=True):
    proc = subprocess.run(
        [sys.executable, os.path.join(_ROOT, relpath), "--platform", "cpu",
         *args],
        capture_output=True, text=True, timeout=timeout, cwd=_ROOT,
        env=_example_env())
    if not check:
        return proc
    assert proc.returncode == 0, (
        f"{relpath} failed rc={proc.returncode}\n--- stdout ---\n"
        f"{proc.stdout[-3000:]}\n--- stderr ---\n{proc.stderr[-3000:]}")
    return proc.stdout


@pytest.mark.parametrize("relpath,args", [
    ("examples/mnist/train_mnist.py",
     ["--epoch", "1", "--batchsize", "64"]),
    ("examples/mnist/train_mnist_model_parallel.py",
     ["--epoch", "1", "--batchsize", "64"]),
    ("examples/seq2seq/seq2seq.py",
     ["--epoch", "1", "--batchsize", "32", "--unit", "32"]),
    ("examples/imagenet/train_imagenet.py",
     ["--tiny", "--epoch", "1", "--batchsize", "64"]),
    # tier-1 budget (ISSUE 15): googlenet (~28s) and the lars
    # large-batch variant (~90s) are slow-marked — the resnet arch and
    # the plain large-batch recipe keep the example paths gated in
    # tier-1, and `-m slow` (or `-m ''`) still runs the full matrix
    pytest.param(
        "examples/imagenet/train_imagenet.py",
        ["--tiny", "--epoch", "1", "--batchsize", "64",
         "--arch", "googlenet"],
        marks=pytest.mark.slow),
    ("examples/imagenet/train_imagenet_large_batch.py",
     ["--tiny", "--epoch", "1", "--batchsize", "64"]),
    pytest.param(
        "examples/imagenet/train_imagenet_large_batch.py",
        ["--tiny", "--epoch", "1", "--batchsize", "64",
         "--optimizer", "lars", "--steps-per-execution", "2",
         "--resumable"],
        marks=pytest.mark.slow),
    ("examples/transformer/train_lm.py",
     ["--mesh", "data=8", "--steps", "12"]),
    ("examples/transformer/train_lm.py",
     ["--mesh", "data=2,model=2,seq=2", "--attention", "ring",
      "--n-kv-heads", "2", "--pos-embedding", "rope", "--steps", "8"]),
    ("examples/transformer/train_lm.py",
     ["--mesh", "pipe=2,data=4", "--schedule", "1f1b", "--steps", "8"]),
], ids=["mnist-dp", "mnist-mp", "seq2seq", "imagenet-resnet",
        "imagenet-googlenet", "imagenet-large-batch",
        "imagenet-large-batch-lars", "lm-dp", "lm-tp-sp-ring",
        "lm-pipe-1f1b"])
def test_example_runs(relpath, args, tmp_path):
    out = []
    if ("--out" not in args and "model_parallel" not in relpath
            and "train_lm" not in relpath):
        out = ["--out", str(tmp_path / "out")]
    _run_example(relpath, args + out)


@pytest.mark.parametrize("extra", [
    [], ["--beam", "3", "--int8"],
    ["--mesh", "data=4,model=2", "--n-kv-heads", "2",
     "--pos-embedding", "rope", "--temperature", "0.8"],
], ids=["greedy", "beam-int8", "tp-sampling"])
def test_generate_example(extra):
    out = _run_example("examples/transformer/generate.py",
                       ["--max-len", "16"] + extra)
    if "--beam" in extra:
        assert "beam 0" in out and "beam 2" in out
    else:
        assert "generated:" in out


def test_elastic_resume_across_meshes(tmp_path):
    """A checkpoint trained on a pure-DP mesh resumes on a pipelined
    mesh (blocks regrouped, Adam state re-laid) and keeps training —
    the reference could only restart at the identical world size."""
    ck = str(tmp_path / "ck")
    first = _run_example(
        "examples/transformer/train_lm.py",
        ["--mesh", "data=8", "--steps", "6", "--checkpoint", ck])
    assert "saved" in first
    out = _run_example(
        "examples/transformer/train_lm.py",
        ["--mesh", "pipe=2,data=4", "--steps", "12",
         "--checkpoint", ck])
    assert "regrouped checkpoint pipe=1/V=1 -> pipe=2/V=1" in out, out
    assert "resumed at step 6" in out, out


def test_generate_text_prompt_without_tokenizer_is_clean_error(tmp_path):
    """A text prompt file without --tokenizer must exit with a message
    pointing at --tokenizer, not a raw int() ValueError traceback."""
    pf = tmp_path / "prompts.txt"
    pf.write_text("the quick brown fox\njumps over the lazy dog\n")
    proc = _run_example("examples/transformer/generate.py",
                        ["--prompt-file", str(pf)], check=False)
    assert proc.returncode != 0
    assert "--tokenizer" in proc.stderr
    assert "Traceback" not in proc.stderr, proc.stderr[-2000:]


def test_train_then_generate_roundtrip(tmp_path):
    ck = str(tmp_path / "ck")
    _run_example("examples/transformer/train_lm.py",
                 ["--mesh", "data=8", "--steps", "10",
                  "--checkpoint", ck])
    out = _run_example("examples/transformer/generate.py",
                       ["--checkpoint", ck, "--vocab", "128",
                        "--max-len", "16"])
    assert "loaded" in out and "generated:" in out


def _epoch_rows(out):
    """Parse PrintReport lines 'epoch=N  main/loss=X ...' into
    {epoch: {field: float}}."""
    rows = {}
    for line in out.splitlines():
        if not line.startswith("epoch="):
            continue
        kv = dict(part.split("=", 1) for part in line.split())
        rows[int(kv.pop("epoch"))] = {
            k: float(v) for k, v in kv.items()}
    return rows


@pytest.mark.slow
def test_large_batch_interrupted_resume_matches_straight_run(tmp_path):
    """Example-scale resume equivalence (not just unit scale): stopping
    the large-batch recipe after epoch 1 and re-launching to epoch 2
    must reproduce the uninterrupted run's epoch-2 training loss —
    iterator position/RNG, LR-schedule step, and LogReport history all
    restored through the example's own --resumable path.

    Slow-marked (ISSUE 15 tier-1 budget): three full example launches
    (~104s) — resume equivalence itself stays tier-1-gated at unit
    scale (optimizer_tests/test_accum_resume.py, the checkpoint
    suite); this drill is the example-scale composition."""
    base = ["--tiny", "--batchsize", "64", "--resumable"]
    straight = _run_example(
        "examples/imagenet/train_imagenet_large_batch.py",
        base + ["--epoch", "2", "--out", str(tmp_path / "straight")])

    _run_example(
        "examples/imagenet/train_imagenet_large_batch.py",
        base + ["--epoch", "1", "--out", str(tmp_path / "resumed")])
    snaps = [f for f in os.listdir(tmp_path / "resumed")
             if f.startswith("snapshot_iter_")]
    assert snaps, "epoch-1 run wrote no snapshots — resume untestable"
    resumed = _run_example(
        "examples/imagenet/train_imagenet_large_batch.py",
        base + ["--epoch", "2", "--out", str(tmp_path / "resumed")])
    # guard against a vacuous pass: the relaunch is CLI-identical to
    # the straight run, so without this marker a silently-inert resume
    # path would retrain from scratch bit-identically and still match
    assert "resumed at iteration" in resumed, resumed[-1500:]

    a, b = _epoch_rows(straight), _epoch_rows(resumed)
    assert 2 in a and 2 in b, (a, b)
    for field in ("main/loss", "validation/loss", "validation/accuracy"):
        assert abs(a[2][field] - b[2][field]) <= 1e-5 * max(
            1.0, abs(a[2][field])), \
            f"epoch-2 {field}: straight {a[2][field]} vs resumed " \
            f"{b[2][field]} — resume diverged at example scale"


def test_pipe_trained_checkpoint_decodes_anywhere(tmp_path):
    """A pipe=2-trained checkpoint must decode on the default pipe=1
    mesh AND on a pipe=2 decode mesh (block regrouping is mesh-to-mesh,
    and PP-decode's stage-sharded step produces identical tokens)."""
    ck = str(tmp_path / "ck")
    _run_example("examples/transformer/train_lm.py",
                 ["--mesh", "pipe=2,data=4", "--steps", "8",
                  "--checkpoint", ck])
    outs = []
    for mesh in ("data=-1", "pipe=2,data=4"):
        out = _run_example("examples/transformer/generate.py",
                           ["--checkpoint", ck, "--vocab", "128",
                            "--max-len", "16", "--mesh", mesh])
        assert "loaded" in out and "generated:" in out
        outs.append(out[out.index("generated:"):])
    assert outs[0] == outs[1], "pipe decode diverges from pipe=1 decode"


def test_interleaved_trained_checkpoint_decodes(tmp_path):
    """An interleaved-trained checkpoint stores blocks (P, V, lpc, ...);
    decode must regroup via the recorded pipe/virtual metadata instead
    of a blind (pipe, -1) reshape (which would keep the wrong rank and
    scramble chunk-major layer order)."""
    ck = str(tmp_path / "ck")
    _run_example("examples/transformer/train_lm.py",
                 ["--mesh", "pipe=2,data=4", "--schedule", "interleaved",
                  "--steps", "8", "--checkpoint", ck])
    out = _run_example("examples/transformer/generate.py",
                       ["--checkpoint", ck, "--vocab", "128",
                        "--max-len", "16"])
    assert "loaded" in out and "generated:" in out


def test_lm_real_text_path(tmp_path):
    """The --text-file path must actually be exercised: a generated
    text file with strong byte structure trains end-to-end and the
    loss falls well below uniform-over-bytes entropy."""
    import math

    txt = tmp_path / "corpus.txt"
    # highly repetitive corpus: next-byte entropy far below ln(256)
    txt.write_bytes(b"the quick brown fox jumps over the lazy dog. "
                    * 800)
    out = _run_example(
        "examples/transformer/train_lm.py",
        ["--mesh", "data=8", "--steps", "30", "--vocab", "256",
         "--text-file", str(txt)])
    loss_line = next((ln for ln in out.splitlines()
                      if ln.startswith("loss ") and "->" in ln), None)
    assert loss_line, f"no loss summary line in output:\n{out[-1500:]}"
    last = float(loss_line.split("->")[1].split("over")[0])
    assert last < math.log(256) * 0.6, \
        f"byte LM barely learned the repetitive corpus: loss {last}"
    # the held-out tail (never trained on) must also be well-modelled
    ppl_line = next((ln for ln in out.splitlines()
                     if ln.startswith("held-out byte perplexity")), None)
    assert ppl_line, f"no held-out ppl line in output:\n{out[-1500:]}"
    ppl = float(ppl_line.split("perplexity")[1].split("(")[0])
    assert ppl < 100, f"held-out perplexity {ppl} barely beats uniform"


def test_lm_bpe_tokenizer_path(tmp_path):
    """--tokenizer-vocab: the BPE subword path trains end-to-end,
    persists bpe.json beside the checkpoint, reports BOTH token and
    byte perplexity, beats the byte-level run at equal steps on the
    byte-ppl scale (each step sees bytes-per-token times more text),
    and round-trips through generate.py --prompt-text."""
    txt = tmp_path / "corpus.txt"
    txt.write_bytes(b"the quick brown fox jumps over the lazy dog. "
                    b"a stitch in time saves nine for the early bird. "
                    * 500)
    ck = str(tmp_path / "ck")
    common = ["--mesh", "data=8", "--steps", "30", "--d-model", "32",
              "--n-layers", "2", "--text-file", str(txt)]
    out = _run_example(
        "examples/transformer/train_lm.py",
        common + ["--tokenizer-vocab", "512", "--checkpoint", ck])
    assert (tmp_path / "ck" / "bpe.json").exists()
    line = next(ln for ln in out.splitlines()
                if ln.startswith("held-out token perplexity"))
    byte_ppl = float(line.split("byte perplexity")[1].split("at")[0])
    out_bytes = _run_example(
        "examples/transformer/train_lm.py", common + ["--vocab", "256"])
    bl = next(ln for ln in out_bytes.splitlines()
              if ln.startswith("held-out byte perplexity"))
    byte_baseline = float(bl.split("perplexity")[1].split("(")[0])
    assert byte_ppl < byte_baseline, \
        f"BPE byte-ppl {byte_ppl} did not beat byte-level {byte_baseline}"
    # resume reuses the persisted merges rather than retraining
    out2 = _run_example(
        "examples/transformer/train_lm.py",
        common + ["--tokenizer-vocab", "512", "--checkpoint", ck,
                  "--steps", "32"])
    assert "loaded tokenizer" in out2 and "resumed at step 30" in out2
    # vocab printed by training (tokenizer ids padded to 128-multiple)
    vocab = next(ln for ln in out.splitlines()
                 if ln.startswith("model vocab")).split()[2]
    gen = _run_example(
        "examples/transformer/generate.py",
        ["--checkpoint", ck, "--tokenizer", str(tmp_path / "ck" /
                                                "bpe.json"),
         "--prompt-text", "the quick brown", "--vocab", vocab,
         "--d-model", "32", "--n-layers", "2", "--max-len", "16"])
    assert "generated text:" in gen and "the quick brown" in gen
    # variable-length batch: one prompt per line, right-aligned with
    # prompt_lens under the hood, per-row decoded text out
    pf = tmp_path / "prompts.txt"
    pf.write_text("the quick brown\na stitch in time saves\n" * 4)
    gen = _run_example(
        "examples/transformer/generate.py",
        ["--checkpoint", ck, "--tokenizer", str(tmp_path / "ck" /
                                                "bpe.json"),
         "--prompt-file", str(pf), "--vocab", vocab,
         "--d-model", "32", "--n-layers", "2", "--max-len", "16"])
    assert "row 0 text: 'the quick brown" in gen
    assert "row 7 text: 'a stitch in time saves" in gen


def test_mnist_real_npz_path(tmp_path):
    """The --mnist-npz file path must actually be exercised: a generated
    mnist.npz-shaped fixture trains end-to-end and beats chance."""
    import numpy as np

    rng = np.random.RandomState(0)
    protos = rng.randn(10, 784).astype("float32") * 40 + 128

    def split(n):
        y = (np.arange(n) % 10).astype("int64")
        x = np.clip(protos[y] + 25 * rng.randn(n, 784), 0, 255)
        return x.astype("uint8"), y

    x_train, y_train = split(1280)
    x_test, y_test = split(256)
    npz = tmp_path / "mnist.npz"
    np.savez(npz, x_train=x_train, y_train=y_train,
             x_test=x_test, y_test=y_test)
    out = _run_example(
        "examples/mnist/train_mnist.py",
        ["--epoch", "2", "--batchsize", "64", "--mnist-npz", str(npz),
         "--out", str(tmp_path / "out")])
    acc = float(out.strip().splitlines()[-1].split()[-1])
    assert acc > 0.5, f"npz-trained accuracy {acc} no better than chance"


# tier-1 budget (ISSUE 15): the serial-loader arm (~23s) is
# slow-marked; the native arm keeps the whole --train-npz file path
# AND the C++ iterator gated in tier-1
@pytest.mark.parametrize("loader", [
    pytest.param("serial", marks=pytest.mark.slow), "native",
], ids=["npz-serial", "npz-native"])
def test_imagenet_real_npz_path(tmp_path, loader):
    """--train-npz feeds real (generated) image files end-to-end; with
    --loader native the C++ NativeBatchIterator drives the SAME
    training loop through StandardUpdater."""
    import numpy as np

    rng = np.random.RandomState(0)
    n, image, classes = 256, 32, 8
    y = (np.arange(n) % classes).astype("int32")
    protos = rng.randn(classes, 8).astype("float32")
    x = 0.3 * rng.randn(n, image, image, 3).astype("float32")
    x[np.arange(n), :8, 0, 0] += protos[y]
    npz = tmp_path / "imagenet.npz"
    np.savez(npz, x=x, y=y)
    _run_example(
        "examples/imagenet/train_imagenet.py",
        ["--tiny", "--epoch", "1", "--batchsize", "64",
         "--train-npz", str(npz), "--loader", loader,
         "--out", str(tmp_path / "out")])


def test_train_lm_checkpoint_resume(tmp_path):
    """--checkpoint writes a resumable state; a second run restores it."""
    args = ["--mesh", "data=8", "--steps", "10",
            "--checkpoint", str(tmp_path / "ck")]
    _run_example("examples/transformer/train_lm.py", args)
    out = _run_example("examples/transformer/train_lm.py",
                       ["--mesh", "data=8", "--steps", "14",
                        "--checkpoint", str(tmp_path / "ck")])
    assert "resumed at step 10" in out
    # resuming past --steps is a clean no-op, not a crash
    out = _run_example("examples/transformer/train_lm.py",
                       ["--mesh", "data=8", "--steps", "14",
                        "--checkpoint", str(tmp_path / "ck")])
    assert "nothing to do" in out
