"""Every example script must RUN end-to-end on the virtual CPU pod —
the reference's examples were its de-facto integration suite (run under
``mpiexec`` in CI, SURVEY.md §4); these are ours, exercised exactly as a
user would launch them (fresh interpreter, CLI flags, tiny settings)."""

import os
import subprocess
import sys

import pytest

_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", ".."))


def _run_example(relpath, args, timeout=420):
    env = dict(os.environ)
    for k in list(env):
        if k.startswith(("TPU_", "LIBTPU", "PJRT_", "JAX_")):
            env.pop(k)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, os.path.join(_ROOT, relpath), "--platform", "cpu",
         *args],
        capture_output=True, text=True, timeout=timeout, cwd=_ROOT, env=env)
    assert proc.returncode == 0, (
        f"{relpath} failed rc={proc.returncode}\n--- stdout ---\n"
        f"{proc.stdout[-3000:]}\n--- stderr ---\n{proc.stderr[-3000:]}")
    return proc.stdout


@pytest.mark.parametrize("relpath,args", [
    ("examples/mnist/train_mnist.py",
     ["--epoch", "1", "--batchsize", "64"]),
    ("examples/mnist/train_mnist_model_parallel.py",
     ["--epoch", "1", "--batchsize", "64"]),
    ("examples/seq2seq/seq2seq.py",
     ["--epoch", "1", "--batchsize", "32", "--unit", "32"]),
    ("examples/imagenet/train_imagenet.py",
     ["--tiny", "--epoch", "1", "--batchsize", "64"]),
    ("examples/imagenet/train_imagenet.py",
     ["--tiny", "--epoch", "1", "--batchsize", "64",
      "--arch", "googlenet"]),
    ("examples/imagenet/train_imagenet_large_batch.py",
     ["--tiny", "--epoch", "1", "--batchsize", "64"]),
    ("examples/imagenet/train_imagenet_large_batch.py",
     ["--tiny", "--epoch", "1", "--batchsize", "64",
      "--optimizer", "lars", "--steps-per-execution", "2",
      "--resumable"]),
], ids=["mnist-dp", "mnist-mp", "seq2seq", "imagenet-resnet",
        "imagenet-googlenet", "imagenet-large-batch",
        "imagenet-large-batch-lars"])
def test_example_runs(relpath, args, tmp_path):
    out = []
    if "--out" not in args and "model_parallel" not in relpath:
        out = ["--out", str(tmp_path / "out")]
    _run_example(relpath, args + out)
