"""bench_lookup_real.py must work end-to-end before its first live
TPU window (the round-4 lesson from bench_quality: a bench's first
execution must never be a rare live window).  Drives the real flow at
reduced steps: docs corpus -> BPE + LM training -> three generate.py
--lookup-k measurements (trained quote + two held-out) -> acceptance
record."""

import json
import os
import subprocess
import sys

_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", ".."))


def test_bench_lookup_real_smoke_end_to_end():
    env = dict(os.environ)
    for k in list(env):
        if k.startswith(("TPU_", "LIBTPU", "PJRT_", "JAX_")):
            env.pop(k)
    # the suite conftest pins an 8-virtual-device XLA_FLAGS; the bench
    # children run --mesh data=1 and need the plain host config
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "bench_lookup_real.py"),
         "--platform", "cpu", "--steps", "60", "--timeouts", "1500"],
        capture_output=True, text=True, timeout=1600, cwd=_ROOT, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = [l for l in proc.stdout.splitlines() if l.startswith("{")][-1]
    rec = json.loads(line)
    assert rec["metric"] == "lookup_real_text_mean_accepted"
    assert rec["workload"] == "quote-trained"
    # the machinery produced a real measurement (the acceptance VALUE
    # depends on training; the smoke pins the harness, not the number)
    assert rec["value"] is not None and 0.0 <= rec["value"] <= rec["k"]
    assert rec["heldout_accepted"] is not None
    assert not rec.get("cached"), "smoke must be a live run"
