"""Native C++ loader: build, coverage/determinism, ring-buffer reuse,
pack/unpack round-trip, and fallback parity."""

import numpy as np
import pytest

from chainermn_tpu import native
from chainermn_tpu.native import (
    NativeBatchIterator,
    native_available,
    pack_arrays,
    unpack_arrays,
)

N, BS = 64, 16


def fields(n=N, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 7, 3).astype(np.float32)
    y = rng.randint(0, 10, size=n).astype(np.int32)
    return x, y


def test_native_builds():
    assert native_available(), native._build_error


def collect_epoch(it):
    xs, ys = [], []
    start = it.epoch
    while it.epoch == start:
        x, y = next(it)
        xs.append(x.copy())   # views are recycled — copy to keep
        ys.append(y.copy())
    return np.concatenate(xs), np.concatenate(ys)


def test_sequential_coverage_and_order():
    x, y = fields()
    it = NativeBatchIterator([x, y], BS, shuffle=False)
    gx, gy = collect_epoch(it)
    np.testing.assert_array_equal(gx, x)
    np.testing.assert_array_equal(gy, y)
    # second epoch repeats identically when not shuffling
    gx2, _ = collect_epoch(it)
    np.testing.assert_array_equal(gx2, x)


def test_shuffle_covers_and_differs_by_epoch():
    x, y = fields()
    it = NativeBatchIterator([x, y], BS, shuffle=True, seed=7)
    gx1, gy1 = collect_epoch(it)
    gx2, _ = collect_epoch(it)
    # same multiset of labels, different order across epochs
    np.testing.assert_array_equal(np.sort(gy1), np.sort(y))
    assert not np.array_equal(gx1, gx2)
    # label/image pairing preserved through the gather
    lookup = {xx.tobytes(): yy for xx, yy in zip(x, y)}
    for row, lab in zip(gx1, gy1):
        assert lookup[row.tobytes()] == lab


def test_shuffle_deterministic_given_seed():
    x, y = fields()
    a = NativeBatchIterator([x, y], BS, shuffle=True, seed=3)
    b = NativeBatchIterator([x, y], BS, shuffle=True, seed=3)
    for _ in range(8):
        xa, ya = next(a)
        xb, yb = next(b)
        np.testing.assert_array_equal(xa, xb)
        np.testing.assert_array_equal(ya, yb)


def test_ring_reuse_many_epochs():
    """More pops than slots — exercises release/recycle and ordering."""
    x, y = fields()
    it = NativeBatchIterator([x, y], BS, shuffle=True, seed=1,
                             n_slots=2, n_threads=3)
    seen = 0
    for _ in range(20):
        xb, yb = next(it)
        assert xb.shape == (BS, 7, 3)
        seen += len(yb)
    assert seen == 20 * BS
    assert it.epoch == 20 * BS // N


def test_non_repeating_stops():
    x, y = fields()
    it = NativeBatchIterator([x, y], BS, repeat=False)
    batches = list(it)
    assert len(batches) == N // BS
    it.reset()
    assert len(list(it)) == N // BS


def test_fallback_matches_native_sequential():
    x, y = fields()
    nat = NativeBatchIterator([x, y], BS, shuffle=False)
    fb = NativeBatchIterator([x, y], BS, shuffle=False)
    fb._handle, fb._lib = None, None   # force the numpy path
    for _ in range(6):
        xa, ya = next(nat)
        xb, yb = next(fb)
        np.testing.assert_array_equal(xa, xb)
        np.testing.assert_array_equal(ya, yb)


def test_pack_unpack_roundtrip():
    rng = np.random.RandomState(0)
    arrays = [rng.randn(13, 5).astype(np.float32),
              rng.randint(0, 100, size=(7,)).astype(np.int64),
              rng.randn(2, 3, 4).astype(np.float16)]
    packed = pack_arrays(arrays)
    assert packed.nbytes == sum(a.nbytes for a in arrays)
    outs = unpack_arrays(packed, arrays)
    for a, b in zip(arrays, outs):
        np.testing.assert_array_equal(a, b)
    with pytest.raises(ValueError):
        unpack_arrays(packed[:-1], arrays)


def test_validation_errors():
    x, y = fields()
    with pytest.raises(ValueError):
        NativeBatchIterator([], BS)
    with pytest.raises(ValueError):
        NativeBatchIterator([x, y[:10]], BS)
    with pytest.raises(ValueError):
        NativeBatchIterator([x[:8]], BS)


def test_fallback_shuffle_matches_native():
    """Seeded shuffle order must not depend on whether the C++ library
    is available — the fallback replicates mt19937_64 Fisher-Yates."""
    x, y = fields()
    nat = NativeBatchIterator([x, y], BS, shuffle=True, seed=11)
    fb = NativeBatchIterator([x, y], BS, shuffle=True, seed=11)
    fb._handle, fb._lib = None, None
    for _ in range(2 * (N // BS) + 1):   # crosses an epoch boundary
        xa, ya = next(nat)
        xb, yb = next(fb)
        np.testing.assert_array_equal(xa, xb)
        np.testing.assert_array_equal(ya, yb)


def test_matches_serial_iterator_batch_for_batch():
    """The trainer-facing contract: NativeBatchIterator + identity
    converter must hand StandardUpdater the SAME batch arrays as
    SerialIterator + default_converter (sequential order — the two
    shuffles are different algorithms by design)."""
    from chainermn_tpu import SerialIterator
    from chainermn_tpu.training import default_converter

    x, y = fields()
    data = list(zip(x, y))
    serial = SerialIterator(data, BS, shuffle=False)
    nat = NativeBatchIterator([x, y], BS, shuffle=False)
    for _ in range(2 * (N // BS) + 1):      # spans an epoch boundary
        sx, sy = default_converter(next(serial))
        nx, ny = next(nat)
        np.testing.assert_array_equal(nx, sx)
        np.testing.assert_array_equal(ny, sy)
