"""Worker for the kill→resume fault drills: one deterministic training
job per invocation, driven by a FaultPlan JSON.

    python _fault_worker.py <phase> <workdir> <plan_json> [<mode>]

Phases:
  ref    — run 6 epochs uninterrupted, write final params to ref.npz
  train  — run with the fault plan armed (a kill plan means this process
           dies mid-run; the parent asserts the SIGKILL exit)
  resume — maybe_load from the checkpoint, finish the 6 epochs, write
           final params to resumed.npz

``mode`` (default "full") selects the checkpoint flavour:
  full        — sync full-state-per-rank files (the PR 3 drills)
  shard_async — ZeRO-1 optimizer + shard-only covering sets streamed by
                the async background writer, so a SIGKILL can land
                MID-SET with the writer stalled by the plan's
                ``save_stall_after_files`` (docs/RESILIENCE.md
                "Scale-free snapshots")

``ref`` and ``resume`` must be BITWISE identical — the resilience
layer's whole claim (docs/RESILIENCE.md).
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"

from chainermn_tpu.testing import ensure_virtual_pod  # noqa: E402

ensure_virtual_pod(8)  # the drill runs on the same mesh as the suite

import jax  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

import chainermn_tpu as cmn  # noqa: E402
from chainermn_tpu.extensions import (  # noqa: E402
    create_multi_node_checkpointer,
)
from chainermn_tpu.testing import FaultInjector, FaultPlan  # noqa: E402
from chainermn_tpu.training import LogReport  # noqa: E402
from chainermn_tpu.utils import save_state  # noqa: E402


def _dataset(n=64):
    rng = np.random.RandomState(0)
    x = rng.randn(n, 4).astype(np.float32)
    w = rng.randn(4).astype(np.float32)
    y = (x @ w).astype(np.float32)
    return [(x[i], y[i]) for i in range(n)]


def _loss_fn(params, x, y):
    import jax.numpy as jnp

    pred = x @ params["w"] + params["b"]
    return jnp.mean((pred - y) ** 2)


def _build(comm, workdir, mode="full"):
    import jax.numpy as jnp

    it = cmn.SerialIterator(_dataset(), batch_size=16, shuffle=True,
                            seed=5)
    # shard_async drills ZeRO-1: covering sets need real shard leaves
    opt = cmn.create_multi_node_optimizer(
        optax.sgd(0.05), comm, zero1=(mode == "shard_async"))
    params = {"w": jnp.zeros(4), "b": jnp.zeros(())}
    up = cmn.StandardUpdater(it, opt, _loss_fn, params, comm)
    trainer = cmn.Trainer(up, stop_trigger=(6, "epoch"),
                          out=os.path.join(workdir, "out"))
    log = LogReport(trigger=(1, "epoch"))
    trainer.extend(log)
    if mode == "shard_async":
        # the scale-free flavour: shard-only covering sets streamed by
        # the background writer — the SIGKILL drill stalls the stream
        # (FaultPlan.save_stall_after_files) so the kill lands MID-SET
        cp = create_multi_node_checkpointer(
            comm, os.path.join(workdir, "ckpt"), async_write=True,
            shard_only=True, history=2)
    else:
        # sync writes: a SIGKILL one iteration after a save must find
        # that save durable (async overlap would race the kill — its
        # join-on-crash path is drilled separately by the
        # SIGTERM-mid-write test).  history=2: the corrupted-latest
        # drill needs the previous complete set on disk to fall back to.
        cp = create_multi_node_checkpointer(
            comm, os.path.join(workdir, "ckpt"), async_write=False,
            history=2)
    # save every 3 iterations — NOT aligned with the 4-iteration epoch,
    # so the kill lands mid-epoch, mid-shuffle
    trainer.extend(cp, trigger=(3, "iteration"))
    return trainer, up, cp, log


def main():
    phase, workdir, plan_json = sys.argv[1], sys.argv[2], sys.argv[3]
    mode = sys.argv[4] if len(sys.argv) > 4 else "full"
    comm = cmn.create_communicator("tpu_xla")
    trainer, up, cp, log = _build(comm, workdir, mode)
    if phase == "train":
        plan = FaultPlan.from_json(plan_json)
        trainer.extend(FaultInjector(plan, comm, checkpointer=cp))
    elif phase == "resume":
        resumed = cp.maybe_load(up, trainer)
        print(f"RESUMED_AT {resumed}", flush=True)
    trainer.run()
    final = {"params": up.params, "iteration": up.iteration,
             "log_losses": np.asarray(
                 [e["main/loss"] for e in log.log], np.float64)}
    name = {"ref": "ref.npz", "resume": "resumed.npz",
            "train": "train.npz"}[phase]
    save_state(os.path.join(workdir, name), final)
    print(f"PHASE_OK {phase} iter={up.iteration}", flush=True)


if __name__ == "__main__":
    main()
