"""PreemptionCheckpointer — a preemption signal must checkpoint at the
next iteration boundary, stop the trainer cleanly, and leave a snapshot a
fresh run's ``maybe_load`` resumes from."""

import os
import signal

import jax
import numpy as np
import optax
import pytest

import chainermn_tpu as cmn
from chainermn_tpu.extensions import (
    PreemptionCheckpointer,
    create_multi_node_checkpointer,
)
from chainermn_tpu.models import init_mlp, mlp_apply, softmax_cross_entropy


@pytest.fixture()
def comm():
    return cmn.create_communicator("tpu_xla")


def _dataset(n=64, dim=6, classes=3, seed=0):
    rng = np.random.RandomState(seed)
    return [(rng.randn(dim).astype(np.float32), np.int32(i % classes))
            for i in range(n)]


def _make_trainer(comm, out, epochs=50):
    it = cmn.SerialIterator(_dataset(), 16, shuffle=True, seed=3)
    params = init_mlp(jax.random.PRNGKey(0), [6, 12, 3])
    opt = cmn.create_multi_node_optimizer(optax.sgd(0.05), comm)

    def loss_fn(p, x, y):
        return softmax_cross_entropy(mlp_apply(p, x), y)

    upd = cmn.StandardUpdater(it, opt, loss_fn, params, comm)
    return cmn.Trainer(upd, (epochs, "epoch"), out=str(out))


class TestFailOnNonNumber:
    def test_raises_on_nan_loss(self, comm, tmp_path):
        from chainermn_tpu.extensions import FailOnNonNumber

        it = cmn.SerialIterator(_dataset(), 16, shuffle=True, seed=3)
        params = init_mlp(jax.random.PRNGKey(0), [6, 12, 3])
        # absurd LR: diverges to NaN within a few iterations
        opt = cmn.create_multi_node_optimizer(optax.sgd(1e9), comm)

        def loss_fn(p, x, y):
            return softmax_cross_entropy(mlp_apply(p, x), y)

        upd = cmn.StandardUpdater(it, opt, loss_fn, params, comm)
        trainer = cmn.Trainer(upd, (50, "epoch"), out=str(tmp_path))
        trainer.extend(FailOnNonNumber())
        with pytest.raises(RuntimeError, match="non-finite"):
            trainer.run()
        assert trainer.updater.iteration < 50 * 4

    def test_quiet_on_healthy_run(self, comm, tmp_path):
        from chainermn_tpu.extensions import FailOnNonNumber

        trainer = _make_trainer(comm, tmp_path, epochs=1)
        trainer.extend(FailOnNonNumber())
        trainer.run()
        assert trainer.updater.iteration == 4


class TestPreemption:
    def test_signal_checkpoints_and_stops(self, comm, tmp_path):
        trainer = _make_trainer(comm, tmp_path)
        cp = create_multi_node_checkpointer(comm, str(tmp_path))
        pre = PreemptionCheckpointer(cp, comm, signals=(signal.SIGUSR1,))
        trainer.extend(pre)

        @cmn.training.make_extension(trigger=(1, "iteration"), priority=999)
        def fake_preemption(tr):
            if tr.updater.iteration == 4:
                os.kill(os.getpid(), signal.SIGUSR1)

        trainer.extend(fake_preemption)
        trainer.run()

        # stopped long before the 50-epoch stop trigger, right after the
        # signal's iteration boundary
        assert trainer.updater.iteration == 4
        assert "preemption" in trainer.stop_reason
        assert pre.signaled

        # the snapshot is a normal checkpoint: a fresh job resumes from it
        trainer2 = _make_trainer(comm, tmp_path)
        cp2 = create_multi_node_checkpointer(comm, str(tmp_path))
        assert cp2.maybe_load(trainer2.updater, trainer2) == 4
        assert trainer2.updater.iteration == 4

    def test_async_writer_joined_before_exit(self, comm, tmp_path):
        """With async_write=True the preemption save overlaps the (now
        ending) loop; trainer.run's finalize must join the writer so the
        shard is complete on disk when the process exits."""
        trainer = _make_trainer(comm, tmp_path)
        cp = create_multi_node_checkpointer(
            comm, str(tmp_path), async_write=True)
        pre = PreemptionCheckpointer(cp, comm, signals=(signal.SIGUSR1,))
        trainer.extend(cp, trigger=(10**6, "iteration"))  # periodic: never
        trainer.extend(pre)

        @cmn.training.make_extension(trigger=(1, "iteration"), priority=999)
        def fake_preemption(tr):
            if tr.updater.iteration == 2:
                os.kill(os.getpid(), signal.SIGUSR1)

        trainer.extend(fake_preemption)
        trainer.run()
        assert trainer.updater.iteration == 2
        # the shard must be fully written and loadable NOW
        cp2 = create_multi_node_checkpointer(comm, str(tmp_path))
        trainer2 = _make_trainer(comm, tmp_path)
        assert cp2.maybe_load(trainer2.updater, trainer2) == 2

    def test_no_signal_no_interference(self, comm, tmp_path):
        trainer = _make_trainer(comm, tmp_path, epochs=2)
        cp = create_multi_node_checkpointer(comm, str(tmp_path))
        pre = PreemptionCheckpointer(cp, comm, signals=(signal.SIGUSR1,))
        trainer.extend(pre)
        trainer.run()
        assert trainer.updater.iteration == 8  # 64/16 * 2 epochs
        assert trainer.stop_reason is None
        assert not os.listdir(tmp_path) or not [
            f for f in os.listdir(tmp_path) if "snapshot" in f]

    def test_check_interval_defers_to_shared_cadence(self, comm, tmp_path):
        # check_interval=3: the collective flag check runs only on calls
        # 3, 6, ... — a signal at iteration 1 acts at iteration 3, so in
        # a multi-process job every rank enters the allgather on the
        # same call and checkpoints the same iteration.
        trainer = _make_trainer(comm, tmp_path)
        cp = create_multi_node_checkpointer(comm, str(tmp_path))
        pre = PreemptionCheckpointer(cp, comm, signals=(signal.SIGUSR1,),
                                     check_interval=3)
        trainer.extend(pre)

        @cmn.training.make_extension(trigger=(1, "iteration"), priority=999)
        def fake_preemption(tr):
            if tr.updater.iteration == 1:
                os.kill(os.getpid(), signal.SIGUSR1)

        trainer.extend(fake_preemption)
        trainer.run()
        assert trainer.updater.iteration == 3
        assert cp._agreed_inventory()[0] == [3]

    def test_no_spurious_trigger_fire_after_resume(self, comm, tmp_path):
        # (period=100, 'iteration') with a run resumed at iteration 4:
        # the next iterations (5, 6, ...) must NOT fire the trigger (the
        # crossing state is seeded from the restored iteration, not 0).
        trainer = _make_trainer(comm, tmp_path, epochs=1)
        cp = create_multi_node_checkpointer(comm, str(tmp_path))
        pre = PreemptionCheckpointer(cp, comm, signals=(signal.SIGUSR1,))
        trainer.extend(pre)

        @cmn.training.make_extension(trigger=(1, "iteration"), priority=999)
        def fake_preemption(tr):
            if tr.updater.iteration == 2:
                os.kill(os.getpid(), signal.SIGUSR1)

        trainer.extend(fake_preemption)
        trainer.run()
        assert trainer.updater.iteration == 2

        trainer2 = _make_trainer(comm, tmp_path, epochs=1)
        cp2 = create_multi_node_checkpointer(comm, str(tmp_path))
        assert cp2.maybe_load(trainer2.updater, trainer2) == 2
        fired = []

        @cmn.training.make_extension(trigger=(100, "iteration"))
        def probe(tr):
            fired.append(tr.updater.iteration)

        trainer2.extend(probe)
        trainer2.run()  # finishes the epoch: iterations 3, 4
        assert trainer2.updater.iteration == 4
        assert fired == []

    def test_handler_chained_and_restored(self, comm, tmp_path):
        hits = []
        prev = signal.signal(signal.SIGUSR2, lambda s, f: hits.append(s))
        try:
            trainer = _make_trainer(comm, tmp_path)
            cp = create_multi_node_checkpointer(comm, str(tmp_path))
            pre = PreemptionCheckpointer(cp, comm,
                                         signals=(signal.SIGUSR2,))

            @cmn.training.make_extension(trigger=(1, "iteration"),
                                         priority=999)
            def fake_preemption(tr):
                if tr.updater.iteration == 2:
                    os.kill(os.getpid(), signal.SIGUSR2)

            trainer.extend(pre)
            trainer.extend(fake_preemption)
            trainer.run()
            # the pre-existing handler was chained, not replaced
            assert hits == [signal.SIGUSR2]
            # finalize (ran in trainer.run) restored it
            assert signal.getsignal(signal.SIGUSR2) is not pre._handler
        finally:
            signal.signal(signal.SIGUSR2, prev)
