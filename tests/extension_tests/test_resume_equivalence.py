"""Kill-and-resume equivalence: a run interrupted mid-epoch and resumed
from a checkpoint must produce EXACTLY the loss/log curve of an
uninterrupted run — the reference's whole-trainer-serialization contract
(chainermn/extensions/checkpoint.py + chainer.serializers; SURVEY §3.5).

This is the acceptance test for resume completeness: iterator position,
epoch bookkeeping, shuffle RNG, and LogReport history must all survive.
"""

import numpy as np
import jax.numpy as jnp
import optax
import pytest

from chainermn_tpu import (
    SerialIterator,
    StandardUpdater,
    Trainer,
    create_communicator,
    create_multi_node_checkpointer,
    create_multi_node_optimizer,
)
from chainermn_tpu.training import LogReport


def _make_dataset(n=64):
    rng = np.random.RandomState(0)
    x = rng.randn(n, 4).astype(np.float32)
    w = rng.randn(4).astype(np.float32)
    y = (x @ w).astype(np.float32)
    return [(x[i], y[i]) for i in range(n)]


def _loss_fn(params, x, y):
    pred = x @ params["w"] + params["b"]
    return jnp.mean((pred - y) ** 2)


def _build(comm, tmpdir, seed=5, async_write=False):
    data = _make_dataset()
    it = SerialIterator(data, batch_size=16, shuffle=True, seed=seed)
    opt = create_multi_node_optimizer(optax.sgd(0.05), comm)
    params = {"w": jnp.zeros(4), "b": jnp.zeros(())}
    up = StandardUpdater(it, opt, _loss_fn, params, comm)
    trainer = Trainer(up, stop_trigger=(6, "epoch"), out=str(tmpdir / "out"))
    log = LogReport(trigger=(1, "epoch"))
    trainer.extend(log)
    cp = create_multi_node_checkpointer(comm, str(tmpdir / "ckpt"),
                                        async_write=async_write)
    # save every 3 iterations — NOT aligned with the 4-iteration epoch, so
    # resumes land mid-epoch and mid-shuffle
    trainer.extend(cp, trigger=(3, "iteration"))
    return trainer, up, cp, log


class TestResumeEquivalence:
    @pytest.fixture()
    def comm(self):
        return create_communicator("tpu_xla")

    @pytest.mark.parametrize("async_write", [False, True],
                             ids=["sync", "async"])
    def test_interrupted_equals_uninterrupted(self, comm, tmp_path,
                                              async_write):
        # reference run: 6 epochs straight through
        t_ref, up_ref, _, log_ref = _build(comm, tmp_path / "ref")
        t_ref.run()
        ref_curve = [(e["iteration"], e["main/loss"]) for e in log_ref.log]
        ref_w = np.asarray(up_ref.params["w"])

        # interrupted run: stop after epoch ~2.5 (iteration 10; last
        # checkpoint fired at iteration 9 — mid-epoch, mid-shuffle)
        t1, up1, cp1, _ = _build(comm, tmp_path / "killed",
                                 async_write=async_write)
        t1._stop_period = 2.5
        t1.run()
        assert up1.iteration == 10
        cp1.finalize()   # flush the in-flight async write, if any

        # resume in a FRESH trainer (new process simulation) and finish
        t2, up2, cp2, log2 = _build(comm, tmp_path / "killed")
        resumed = cp2.maybe_load(up2, t2)
        assert resumed == 9
        assert up2.iteration == 9
        # iterator must resume mid-epoch, not restart it
        assert 2.0 < up2.epoch_detail < 3.0
        t2.run()

        got_curve = [(e["iteration"], e["main/loss"]) for e in log2.log]
        assert [i for i, _ in got_curve] == [i for i, _ in ref_curve]
        np.testing.assert_allclose(
            [l for _, l in got_curve], [l for _, l in ref_curve],
            rtol=1e-6, atol=1e-7,
            err_msg="resumed loss/log curve diverges from uninterrupted run")
        np.testing.assert_allclose(
            np.asarray(up2.params["w"]), ref_w, rtol=1e-6, atol=1e-7)

    def test_resume_at_aligned_epoch_trigger(self, comm, tmp_path):
        """Checkpoint trigger ALIGNED with the LogReport epoch trigger:
        the checkpointer (lowest priority) must capture the POST-flush
        LogReport, so no epoch's log entry is lost across resume."""
        def build(root):
            t, up, cp, log = _build(comm, root)
            # re-extend checkpointer on the same tick as LogReport
            t._extensions = [e for e in t._extensions
                             if e.ext is not cp]
            t.extend(cp, trigger=(4, "iteration"))  # 4 it == 1 epoch
            return t, up, cp, log

        t_ref, up_ref, _, log_ref = build(tmp_path / "ref")
        t_ref.run()
        ref_curve = [(e["iteration"], e["main/loss"]) for e in log_ref.log]

        t1, _, _, _ = build(tmp_path / "killed")
        t1._stop_period = 2.0  # stops exactly after the iteration-8 save
        t1.run()

        t2, up2, cp2, log2 = build(tmp_path / "killed")
        assert cp2.maybe_load(up2, t2) == 8
        # both epoch entries must already be in the restored log
        assert [e["iteration"] for e in log2.log] == [4, 8]
        t2.run()
        got_curve = [(e["iteration"], e["main/loss"]) for e in log2.log]
        assert [i for i, _ in got_curve] == [i for i, _ in ref_curve]
        np.testing.assert_allclose(
            [l for _, l in got_curve], [l for _, l in ref_curve],
            rtol=1e-6, atol=1e-7)

    def test_resize_mismatch_skips_iterator_restore(self, comm, tmp_path):
        """A snapshot whose iterator order indexes a differently-sized
        shard must NOT be restored onto the new iterator (resize-safe
        multi_node_snapshot contract) — params still resume."""
        from chainermn_tpu.training._resume import (
            collect_train_state, restore_train_state)

        t, up, _, _ = _build(comm, tmp_path)
        state = collect_train_state(up, t)
        # simulate a resume at a different world size: shard is half
        t2, up2, _, _ = _build(comm, tmp_path / "resized")
        up2.iterator.dataset = _make_dataset(32)
        up2.iterator.reset()
        before = up2.iterator.state_dict()
        restore_train_state(state, up2, t2)
        after = up2.iterator.state_dict()
        assert len(after["order"]) == 32, "stale 64-entry order restored"
        np.testing.assert_array_equal(after["order"], before["order"])

    def test_orphan_shard_gc(self, comm, tmp_path):
        """Stale shards from a dead run are swept on the next save."""
        import os

        t, up, cp, _ = _build(comm, tmp_path)
        path = tmp_path / "ckpt"
        path.mkdir(exist_ok=True)
        # a pre-crash orphan: right name pattern, superseded iteration
        orphan = path / f"snapshot_iter_1.{comm.inter_rank}"
        orphan.write_bytes(b"stale")
        t._stop_period = 1.0
        t.run()  # fires the checkpointer at iteration 3
        assert not orphan.exists(), "orphaned shard survived GC"
        kept = [f for f in os.listdir(path) if f.startswith("snapshot")]
        assert kept == [f"snapshot_iter_3.{comm.inter_rank}"]
