"""Live in-run resize (docs/RESILIENCE.md "Live elastic training"):
``ResizeController`` must resize a RUNNING job at a step boundary —
training continuing in the same process — with a trajectory
BITWISE-equal to the save/restart-at-pause path PR 10 already proved.
8-device CPU mesh shrink/grow (the tested path; cross-process
redistribution stays TPU-gated)."""

import jax
import numpy as np
import optax
import pytest

import chainermn_tpu as cmn
from chainermn_tpu.extensions import create_multi_node_checkpointer
from chainermn_tpu.models import init_mlp, mlp_apply, softmax_cross_entropy
from chainermn_tpu.testing import FaultInjector, FaultPlan
from chainermn_tpu.training.elastic import ResizeController

_N, _DIM, _CLASSES, _BATCH = 96, 6, 3, 16


def _dataset():
    rng = np.random.RandomState(0)
    return [(rng.randn(_DIM).astype(np.float32), np.int32(i % _CLASSES))
            for i in range(_N)]


def _make_updater(comm, **kwargs):
    it = cmn.SerialIterator(_dataset(), _BATCH, shuffle=True, seed=7)
    params = init_mlp(jax.random.PRNGKey(0), [_DIM, 12, _CLASSES])
    opt = _opt_factory(comm)

    def loss_fn(p, x, y):
        return softmax_cross_entropy(mlp_apply(p, x), y)

    return cmn.StandardUpdater(it, opt, loss_fn, params, comm, **kwargs)


def _world_comm(n):
    return cmn.create_communicator("tpu_xla", devices=jax.devices()[:n])


def _opt_factory(comm):
    return cmn.create_multi_node_optimizer(
        optax.adam(5e-2), comm, zero1=True)


def _host(tree):
    return jax.tree.map(np.asarray, tree)


def _run_losses(upd, n):
    losses = []
    for _ in range(n):
        upd.update()
        losses.append(float(upd.observation["main/loss"]))
    return losses


def _assert_tree_equal(a, b, msg=""):
    jax.tree.map(
        lambda x, y: np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y), err_msg=msg), a, b)


class TestLiveResizeEquivalence:
    def test_8_4_8_bitwise_equals_save_restart_at_pause(self, tmp_path):
        """The acceptance drill.  Arm A: train@8, SAVE, restart@4,
        train, save, restart@8, train — the PR 10 path.  Arm B: the
        same schedule through ``ResizeController.resize`` with the
        process never exiting.  Every loss and the final params must be
        BITWISE identical: a live resize IS a save/restart at the pause
        point, minus the restart."""
        # arm A: save/restart
        comm8 = _world_comm(8)
        upd_a = _make_updater(comm8)
        for _ in range(2):
            upd_a.update()
        cp8 = create_multi_node_checkpointer(
            comm8, str(tmp_path / "a"), elastic=True)
        cp8.save(upd_a)
        comm4 = _world_comm(4)
        upd_a4 = _make_updater(comm4)
        cp4 = create_multi_node_checkpointer(
            comm4, str(tmp_path / "a"), elastic=True)
        assert cp4.maybe_load(upd_a4) == 2
        losses_a4 = _run_losses(upd_a4, 3)
        cp4.save(upd_a4)
        upd_a8 = _make_updater(_world_comm(8))
        cp8b = create_multi_node_checkpointer(
            _world_comm(8), str(tmp_path / "a"), elastic=True)
        assert cp8b.maybe_load(upd_a8) == 5
        losses_a8 = _run_losses(upd_a8, 3)

        # arm B: the live path, same process end to end
        upd_b = _make_updater(_world_comm(8))
        trainer = cmn.Trainer(upd_b, (100, "epoch"),
                              out=str(tmp_path / "b"))
        ctrl = ResizeController(_world_comm, _opt_factory)
        for _ in range(2):
            upd_b.update()
        ctrl.resize(trainer, 4)
        assert upd_b.comm.size == 4 and upd_b.iteration == 2
        losses_b4 = _run_losses(upd_b, 3)
        ctrl.resize(trainer, 8)
        assert upd_b.comm.size == 8
        losses_b8 = _run_losses(upd_b, 3)

        np.testing.assert_array_equal(
            np.asarray(losses_b4, np.float64),
            np.asarray(losses_a4, np.float64),
            err_msg="live 8->4 trajectory diverged from save/restart")
        np.testing.assert_array_equal(
            np.asarray(losses_b8, np.float64),
            np.asarray(losses_a8, np.float64),
            err_msg="live 4->8 trajectory diverged from save/restart")
        _assert_tree_equal(upd_b.params, _host(upd_a8.params),
                           "final params differ between the arms")
        _assert_tree_equal(upd_b.opt_state, _host(upd_a8.opt_state),
                           "final opt_state differs between the arms")
        # both resizes recorded with their pause cost
        assert [r["world"] for r in ctrl.resizes] == [4, 8]
        assert all(r["pause_s"] > 0 for r in ctrl.resizes)

    def test_same_world_resize_is_epoch_only_and_bitwise(self, tmp_path):
        """An 8->8 'resize' (a membership churn that ends at the same
        world) must skip the re-layout and leave the trajectory exactly
        untouched — the epoch still bumps so stale traffic fences."""
        upd_ref = _make_updater(_world_comm(8))
        ref = _run_losses(upd_ref, 5)

        upd = _make_updater(_world_comm(8))
        trainer = cmn.Trainer(upd, (100, "epoch"), out=str(tmp_path))
        ctrl = ResizeController(_world_comm, _opt_factory)
        got = _run_losses(upd, 2)
        ctrl.resize(trainer, 8)
        assert ctrl.epoch == 1
        got += _run_losses(upd, 3)
        np.testing.assert_array_equal(
            np.asarray(got, np.float64), np.asarray(ref, np.float64),
            err_msg="same-world resize perturbed the trajectory")


class TestController:
    def test_request_fires_at_next_boundary_and_training_continues(
            self, tmp_path):
        upd = _make_updater(_world_comm(8))
        trainer = cmn.Trainer(upd, (6, "iteration"), out=str(tmp_path))
        ctrl = ResizeController(_world_comm, _opt_factory)
        trainer.extend(ctrl)
        ctrl.request(4)
        trainer.run()
        # the resize happened at a boundary and the run FINISHED on the
        # smaller world in the same process
        assert upd.iteration == 6 and upd.comm.size == 4
        (rec,) = ctrl.resizes
        assert rec["world"] == 4 and rec["iteration"] == 1
        assert ctrl._requested is None      # intent consumed

    def test_fault_plan_drill_arms_controller_same_tick(self, tmp_path):
        """``FaultPlan.resize_live_at_iteration`` composes: the
        injector (priority 1) arms the controller, the controller
        (priority 0) resizes at the very end of the SAME tick."""
        upd = _make_updater(_world_comm(8))
        trainer = cmn.Trainer(upd, (7, "iteration"), out=str(tmp_path))
        ctrl = ResizeController(_world_comm, _opt_factory)
        inj = FaultInjector(
            FaultPlan(resize_live_at_iteration=3, resize_live_to=4),
            upd.comm, resize_controller=ctrl)
        trainer.extend(inj)
        trainer.extend(ctrl)
        trainer.run()
        assert ("resize_live", 3, 4) in inj.fired
        (rec,) = ctrl.resizes
        assert rec == {"iteration": 3, "world": 4, "epoch": 1,
                       "pause_s": rec["pause_s"]}
        assert upd.iteration == 7 and upd.comm.size == 4

    def test_drill_without_controller_is_a_loud_error(self, tmp_path):
        upd = _make_updater(_world_comm(8))
        trainer = cmn.Trainer(upd, (4, "iteration"), out=str(tmp_path))
        inj = FaultInjector(
            FaultPlan(resize_live_at_iteration=2, resize_live_to=4),
            upd.comm)
        trainer.extend(inj)
        with pytest.raises(RuntimeError, match="resize_controller"):
            trainer.run()

    def test_resize_drops_step_cache_and_retunes(self, tmp_path):
        upd = _make_updater(_world_comm(8))
        trainer = cmn.Trainer(upd, (100, "epoch"), out=str(tmp_path))
        ctrl = ResizeController(_world_comm, _opt_factory)
        upd.update()
        assert upd._step_cache
        old_comm = upd.comm
        ctrl.resize(trainer, 4)
        # everything baked against the old mesh is gone; the next
        # update compiles fresh programs for the new world
        assert not upd._step_cache and upd.comm is not old_comm
        assert upd.optimizer is not None
        upd.update()
        assert upd._step_cache

    def test_drain_engines_and_on_resize_hook_sequencing(self, tmp_path):
        calls = []

        class FakeEngine:
            def drain(self, timeout=None):
                calls.append(("drain", timeout))
                return ["partial"]

        def hook(c, new_comm, epoch):
            calls.append(("on_resize", new_comm.size, epoch))

        upd = _make_updater(_world_comm(8))
        trainer = cmn.Trainer(upd, (100, "epoch"), out=str(tmp_path))
        ctrl = ResizeController(_world_comm, _opt_factory,
                                drain_engines=(FakeEngine(),),
                                drain_timeout=1.5, on_resize=hook)
        upd.update()
        ctrl.resize(trainer, 4)
        # engines drained BEFORE the world moved; the hook ran last,
        # already under the new world + epoch
        assert calls == [("drain", 1.5), ("on_resize", 4, 1)]
        assert ctrl.drained == ["partial"]

    def test_request_validation(self):
        ctrl = ResizeController(_world_comm, _opt_factory)
        with pytest.raises(ValueError, match="world_size"):
            ctrl.request(0)

    def test_rebind_world_refuses_zero1_switch(self, tmp_path):
        upd = _make_updater(_world_comm(8))
        upd.update()
        comm4 = _world_comm(4)
        plain = cmn.create_multi_node_optimizer(
            optax.adam(5e-2), comm4, zero1=False)
        with pytest.raises(ValueError, match="zero1"):
            upd.rebind_world(comm4, plain)

    def test_post_resize_intent_needs_distributed_runtime(self):
        from chainermn_tpu.training.elastic import post_resize_intent

        with pytest.raises(RuntimeError, match="distributed"):
            post_resize_intent(4)

    def test_registered_checkpointer_follows_the_resize(self, tmp_path):
        """A periodic checkpointer EXTENSION must ride the live resize:
        its post-resize saves stamp the NEW world's topology and write
        the NEW world's shard-only part set (a stale comm would label
        them with the pre-resize world — and a multi-process save
        would run collectives on a dead mesh).  The later same-world
        resume must therefore be EXACT, not a relayout."""
        comm8 = _world_comm(8)
        upd = _make_updater(comm8)
        trainer = cmn.Trainer(upd, (6, "iteration"), out=str(tmp_path))
        cp = create_multi_node_checkpointer(
            comm8, str(tmp_path), async_write=True, elastic=True,
            shard_only=True, history=2)
        trainer.extend(cp, trigger=(2, "iteration"))
        ctrl = ResizeController(_world_comm, _opt_factory)
        trainer.extend(ctrl)
        ctrl.request(4)
        trainer.run()
        cp.finalize()
        assert cp.comm.size == 4          # the extension followed
        parts = sorted(p.name for p in tmp_path.glob("*iter_6*"))
        assert parts and all(p.endswith("of4") for p in parts), parts
        from chainermn_tpu.utils.serialization import read_topology

        assert read_topology(str(tmp_path / parts[0]))["world_size"] == 4
        upd2 = _make_updater(_world_comm(4))
        cp2 = create_multi_node_checkpointer(
            _world_comm(4), str(tmp_path), elastic=True,
            shard_only=True, history=2)
        assert cp2.maybe_load(upd2) == 6
        assert cp2.last_resume_mode == "exact"
        _assert_tree_equal(_host(upd.params), _host(upd2.params),
                           "post-resize covering set drifted")

    def test_preemption_checkpointer_follows_the_resize(self, tmp_path):
        """PreemptionCheckpointer rebinds both its flag-OR comm and the
        wrapped checkpointer (once — the wrapped cp's rebind is
        idempotent when it is ALSO registered directly)."""
        from chainermn_tpu.extensions import PreemptionCheckpointer

        comm8 = _world_comm(8)
        upd = _make_updater(comm8)
        trainer = cmn.Trainer(upd, (4, "iteration"), out=str(tmp_path))
        cp = create_multi_node_checkpointer(
            comm8, str(tmp_path), elastic=True)
        pc = PreemptionCheckpointer(cp, comm8)
        trainer.extend(cp, trigger=(2, "iteration"))
        trainer.extend(pc)
        ctrl = ResizeController(_world_comm, _opt_factory)
        trainer.extend(ctrl)
        ctrl.request(4)
        trainer.run()
        assert pc.comm.size == 4 and cp.comm.size == 4
        assert pc.comm is cp.comm is upd.comm


class TestPrefetchComposition:
    def test_resize_rewraps_prefetch_feed_bitwise(self, tmp_path):
        """A prefetching feed survives the resize: the lookahead is
        returned to the base iterator, the feed re-wraps over the new
        communicator, and the trajectory stays bitwise-equal to the
        unprefetched live-resize run."""
        ref = _make_updater(_world_comm(8))
        trainer_r = cmn.Trainer(ref, (100, "epoch"),
                                out=str(tmp_path / "r"))
        ctrl_r = ResizeController(_world_comm, _opt_factory)
        ref_losses = _run_losses(ref, 2)
        ctrl_r.resize(trainer_r, 4)
        ref_losses += _run_losses(ref, 3)

        # max_inflight=1: the pipelined default (2) reports RETIRED
        # losses once the pipeline fills — correct, but lagged, so the
        # per-step comparison below needs the synchronous observation
        upd = _make_updater(_world_comm(8), prefetch=True,
                            max_inflight=1)
        from chainermn_tpu.iterators import PrefetchIterator

        assert isinstance(upd.iterator, PrefetchIterator)
        trainer = cmn.Trainer(upd, (100, "epoch"),
                              out=str(tmp_path / "p"))
        ctrl = ResizeController(_world_comm, _opt_factory)
        got = _run_losses(upd, 2)
        ctrl.resize(trainer, 4)
        assert isinstance(upd.iterator, PrefetchIterator)
        assert upd.comm.size == 4
        got += _run_losses(upd, 3)
        upd.finalize()
        np.testing.assert_array_equal(
            np.asarray(got, np.float64),
            np.asarray(ref_losses, np.float64),
            err_msg="prefetch feed lost its position across the resize")
        _assert_tree_equal(upd.params, _host(ref.params),
                           "prefetch-arm params diverged")

    def test_rebind_carries_prebuilt_prefetch_converter(self, tmp_path):
        """A PRE-BUILT prefetcher may carry its own converter while the
        updater's sits at the default; the resize's re-wrap must keep
        the prefetcher's, or post-resize batches are converted
        differently and trajectory equivalence silently breaks."""
        from chainermn_tpu.iterators import PrefetchIterator
        from chainermn_tpu.iterators.prefetch import default_converter

        calls = []

        def conv(batch):
            calls.append(1)
            return default_converter(batch)

        comm = _world_comm(8)
        base = cmn.SerialIterator(_dataset(), _BATCH, shuffle=True,
                                  seed=7)
        feed = PrefetchIterator(base, comm, converter=conv)
        params = init_mlp(jax.random.PRNGKey(0), [_DIM, 12, _CLASSES])

        def loss_fn(p, x, y):
            return softmax_cross_entropy(mlp_apply(p, x), y)

        upd = cmn.StandardUpdater(feed, _opt_factory(comm), loss_fn,
                                  params, comm, prefetch=True,
                                  max_inflight=1)
        trainer = cmn.Trainer(upd, (100, "epoch"), out=str(tmp_path))
        ctrl = ResizeController(_world_comm, _opt_factory)
        _run_losses(upd, 2)
        before = len(calls)
        assert before > 0
        ctrl.resize(trainer, 4)
        assert upd.iterator._converter is conv
        _run_losses(upd, 2)
        upd.finalize()
        assert len(calls) > before
