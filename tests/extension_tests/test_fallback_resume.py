"""Fallback resume: a corrupted shard in the newest snapshot set must
send ``maybe_load`` to the previous complete+verified set, quarantine the
damaged file as ``*.corrupt`` (never GC-delete it), and log what was
skipped — on the 8-device CPU mesh (tests/conftest.py)."""

import logging
import os

import numpy as np
import pytest

import chainermn_tpu as cmn
from chainermn_tpu.extensions import create_multi_node_checkpointer
from chainermn_tpu.testing import corrupt_file


class FakeUpdater:
    def __init__(self):
        self.iteration = 0
        self.params = {"w": np.zeros(3)}
        self.opt_state = {"m": np.zeros(3)}
        self.state = None


@pytest.fixture()
def ckpt(comm, tmp_path):
    cp = create_multi_node_checkpointer(comm, str(tmp_path))
    cp._cleanup = lambda keep: None  # keep every set alive for the drills
    up = FakeUpdater()
    for it in (5, 10, 15):
        up.iteration = it
        up.params = {"w": np.full(3, float(it))}
        cp.save(up)
    return cp, tmp_path


class TestFallbackResume:
    def test_corrupt_latest_falls_back(self, comm, ckpt, caplog):
        _, path = ckpt
        corrupt_file(str(path / "snapshot_iter_15.0"), seed=1)
        fresh = FakeUpdater()
        cp2 = create_multi_node_checkpointer(comm, str(path))
        with caplog.at_level(logging.WARNING,
                             "chainermn_tpu.extensions.checkpoint"):
            assert cp2.maybe_load(fresh) == 10
        np.testing.assert_allclose(fresh.params["w"], 10.0)
        # quarantined, not deleted: the bytes stay for diagnosis
        assert (path / "snapshot_iter_15.0.corrupt").exists()
        assert not (path / "snapshot_iter_15.0").exists()
        # and the skip is logged by iteration number
        assert any("15" in r.message and "fallback" in r.message
                   for r in caplog.records)

    def test_two_corrupt_sets_fall_back_twice(self, comm, ckpt):
        _, path = ckpt
        corrupt_file(str(path / "snapshot_iter_15.0"), seed=1)
        corrupt_file(str(path / "snapshot_iter_10.0"), seed=2)
        fresh = FakeUpdater()
        cp2 = create_multi_node_checkpointer(comm, str(path))
        assert cp2.maybe_load(fresh) == 5
        np.testing.assert_allclose(fresh.params["w"], 5.0)
        assert (path / "snapshot_iter_15.0.corrupt").exists()
        assert (path / "snapshot_iter_10.0.corrupt").exists()

    def test_all_corrupt_resumes_fresh(self, comm, ckpt, caplog):
        _, path = ckpt
        for it in (5, 10, 15):
            corrupt_file(str(path / f"snapshot_iter_{it}.0"), seed=it)
        fresh = FakeUpdater()
        cp2 = create_multi_node_checkpointer(comm, str(path))
        with caplog.at_level(logging.WARNING,
                             "chainermn_tpu.extensions.checkpoint"):
            assert cp2.maybe_load(fresh) is None
        assert fresh.iteration == 0  # untouched — a true fresh start
        assert len([f for f in os.listdir(path)
                    if ".corrupt" in f]) == 3
        assert any("starting fresh" in r.message for r in caplog.records)

    def test_gc_never_touches_quarantined_files(self, comm, ckpt):
        cp, path = ckpt
        corrupt_file(str(path / "snapshot_iter_15.0"), seed=1)
        fresh = FakeUpdater()
        cp2 = create_multi_node_checkpointer(comm, str(path))
        assert cp2.maybe_load(fresh) == 10
        # next save runs REAL GC (no stub): superseded good shards go,
        # the quarantined file stays
        fresh.iteration = 20
        fresh.params = {"w": np.full(3, 20.0)}
        cp2.save(fresh)
        names = sorted(os.listdir(path))
        assert "snapshot_iter_15.0.corrupt" in names
        assert "snapshot_iter_10.0" not in names
        assert "snapshot_iter_20.0" in names

    def test_quarantine_name_collision_gets_suffix(self, comm, tmp_path):
        cp = create_multi_node_checkpointer(comm, str(tmp_path))
        up = FakeUpdater()
        up.iteration = 3
        cp.save(up)
        target = tmp_path / "snapshot_iter_3.0"
        (tmp_path / "snapshot_iter_3.0.corrupt").write_bytes(b"older")
        q = cp._quarantine(str(target))
        assert q.endswith(".corrupt1")
        assert (tmp_path / "snapshot_iter_3.0.corrupt1").exists()

    def test_history_gc_keeps_n_newest_sets(self, comm, tmp_path):
        """``history=2`` retains the two newest complete sets (the
        fallback headroom knob); ``history=1`` is the old keep-latest."""
        cp = create_multi_node_checkpointer(comm, str(tmp_path),
                                            history=2)
        up = FakeUpdater()
        for it in (3, 6, 9):
            up.iteration = it
            up.params = {"w": np.full(3, float(it))}
            cp.save(up)
        names = sorted(f for f in os.listdir(tmp_path)
                       if f.startswith("snapshot"))
        assert names == ["snapshot_iter_6.0", "snapshot_iter_9.0"]

    def test_history_gc_protects_agreed_sets_not_local_inventory(
            self, comm, tmp_path, monkeypatch):
        """With ``history=2`` the protected iterations come from the
        cross-rank AGREEMENT: a set a (simulated) peer no longer holds —
        e.g. it quarantined its shard — must not consume a protection
        slot here, or the ranks would each keep a different pair and no
        older set would stay complete anywhere."""
        cp = create_multi_node_checkpointer(comm, str(tmp_path),
                                            history=2)
        stash, cp._cleanup = cp._cleanup, lambda keep: None
        up = FakeUpdater()
        for it in (5, 10):
            up.iteration = it
            cp.save(up)
        cp._cleanup = stash
        # simulate a peer whose iteration-10 shard was quarantined: the
        # presence agreement excludes 10, so protection must fall on
        # {15, 5} — NOT this rank's local {15, 10}.  The agreement rows
        # are (inventory, streaming) pairs since the async-GC fix.
        monkeypatch.setattr(
            cp.comm, "allgather_obj",
            lambda obj: ([obj, (obj[0] - {10}, obj[1])]
                         if isinstance(obj, tuple) else [obj]))
        up.iteration = 15
        cp.save(up)
        names = sorted(f for f in os.listdir(tmp_path)
                       if f.startswith("snapshot"))
        assert names == ["snapshot_iter_15.0", "snapshot_iter_5.0"]

    def test_history_gc_never_protects_orphans_newer_than_keep(
            self, comm, tmp_path):
        """An orphan shard NEWER than the agreed-complete set (a dead
        run that got further than this run's resume point) must not
        consume a history slot — protecting it would evict an older
        COMPLETE set and destroy the fallback headroom."""
        cp = create_multi_node_checkpointer(comm, str(tmp_path),
                                            history=2)
        up = FakeUpdater()
        for it in (3, 6):
            up.iteration = it
            cp.save(up)
        # forge a newer orphan (bypassing save), then save the real 9
        from chainermn_tpu.utils import save_state

        save_state(str(tmp_path / "snapshot_iter_99.0"),
                   {"iteration": 99, "world_size": 1,
                    "params": up.params, "opt_state": up.opt_state})
        up.iteration = 9
        cp.save(up)
        names = sorted(f for f in os.listdir(tmp_path)
                       if f.startswith("snapshot"))
        # 99 reaped (never agreed complete), 6 and 9 protected
        assert names == ["snapshot_iter_6.0", "snapshot_iter_9.0"]

    def test_racing_deletion_falls_back_without_quarantine(
            self, comm, ckpt, monkeypatch):
        """A shard that vanishes between the inventory listing and its
        checked load (a peer's concurrent GC on a shared filesystem) is
        treated as unavailable — resume falls back, and nothing is
        misread as corruption (no ``*.corrupt`` appears)."""
        import chainermn_tpu.extensions.checkpoint as ckpt_mod

        _, path = ckpt
        cp2 = create_multi_node_checkpointer(comm, str(path))
        real_load = ckpt_mod.load_state_with_topology

        def racing_load(p):
            if p.endswith("snapshot_iter_15.0"):
                os.remove(p)  # the race: file disappears underneath us
            return real_load(p)

        monkeypatch.setattr(ckpt_mod, "load_state_with_topology",
                            racing_load)
        fresh = FakeUpdater()
        assert cp2.maybe_load(fresh) == 10
        np.testing.assert_allclose(fresh.params["w"], 10.0)
        assert not any(".corrupt" in f for f in os.listdir(path))

    def test_quarantine_never_counts_against_history(self, comm,
                                                     tmp_path):
        """GC × quarantine interplay: a ``*.corrupt`` set must neither
        occupy a ``history=N`` protection slot (it is not a usable
        fallback target — counting it would silently shrink the real
        headroom) nor ever be evicted, including across the
        collective-agreement ``_cleanup`` that runs after a fallback
        resume."""
        cp = create_multi_node_checkpointer(comm, str(tmp_path),
                                            history=2)
        stash, cp._cleanup = cp._cleanup, lambda keep: None
        up = FakeUpdater()
        for it in (5, 10, 15):
            up.iteration = it
            up.params = {"w": np.full(3, float(it))}
            cp.save(up)
        cp._cleanup = stash
        corrupt_file(str(tmp_path / "snapshot_iter_15.0"), seed=1)

        # fallback resume quarantines 15 and restores 10
        fresh = FakeUpdater()
        cp2 = create_multi_node_checkpointer(comm, str(tmp_path),
                                             history=2)
        assert cp2.maybe_load(fresh) == 10
        assert (tmp_path / "snapshot_iter_15.0.corrupt").exists()

        # the next save runs the REAL collective-agreement _cleanup:
        # protection must fall on the two newest USABLE sets {20, 10} —
        # the quarantined 15 takes no slot and is not evicted
        fresh.iteration = 20
        fresh.params = {"w": np.full(3, 20.0)}
        cp2.save(fresh)
        names = sorted(os.listdir(tmp_path))
        assert "snapshot_iter_15.0.corrupt" in names
        assert "snapshot_iter_10.0" in names, (
            "the quarantined set consumed a history slot: the usable "
            "fallback set 10 was evicted")
        assert "snapshot_iter_20.0" in names
        assert "snapshot_iter_5.0" not in names

        # and it survives further GC cycles indefinitely
        fresh.iteration = 25
        fresh.params = {"w": np.full(3, 25.0)}
        cp2.save(fresh)
        names = sorted(os.listdir(tmp_path))
        assert "snapshot_iter_15.0.corrupt" in names
        assert sorted(n for n in names if n.endswith(".0")) == [
            "snapshot_iter_20.0", "snapshot_iter_25.0"]

    def test_quarantine_preserved_after_fallback_resume_roundtrip(
            self, comm, tmp_path):
        """A second resume AFTER the fallback must elect the surviving
        set without touching the quarantined bytes — post-mortem
        evidence outlives any number of resume cycles."""
        cp = create_multi_node_checkpointer(comm, str(tmp_path),
                                            history=2)
        stash, cp._cleanup = cp._cleanup, lambda keep: None
        up = FakeUpdater()
        for it in (5, 10):
            up.iteration = it
            up.params = {"w": np.full(3, float(it))}
            cp.save(up)
        cp._cleanup = stash
        corrupt_file(str(tmp_path / "snapshot_iter_10.0"), seed=3)
        before = None
        for _ in range(2):
            fresh = FakeUpdater()
            loader = create_multi_node_checkpointer(
                comm, str(tmp_path), history=2)
            assert loader.maybe_load(fresh) == 5
            q = tmp_path / "snapshot_iter_10.0.corrupt"
            assert q.exists()
            blob = q.read_bytes()
            if before is not None:
                assert blob == before, "quarantined bytes changed"
            before = blob

    def test_clean_sets_resume_unchanged(self, comm, ckpt):
        """No corruption → identical behaviour to the old presence-only
        agreement (newest set restores)."""
        _, path = ckpt
        fresh = FakeUpdater()
        cp2 = create_multi_node_checkpointer(comm, str(path))
        assert cp2.maybe_load(fresh) == 15
        np.testing.assert_allclose(fresh.params["w"], 15.0)
