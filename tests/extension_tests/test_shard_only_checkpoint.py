"""Shard-only covering sets + async streaming through the REAL
checkpointer (docs/RESILIENCE.md "Scale-free snapshots"): a ZeRO-1
job's shard-only set must resume BITWISE-identical to a full per-rank
save, async + shard-only must load bitwise-equal to a sync full save,
aggregate set bytes must stop scaling with world size, a partial or
corrupt set must fall back to the newest set that covers, and a set the
background writer is still streaming must never count toward — nor be
evicted by — ``history=N`` (the GC × async-save race).  8-device CPU
mesh (tests/conftest.py)."""

import logging
import os
import threading

import jax
import numpy as np
import optax
import pytest

import chainermn_tpu as cmn
from chainermn_tpu.extensions import create_multi_node_checkpointer
from chainermn_tpu.models import init_mlp, mlp_apply, softmax_cross_entropy
from chainermn_tpu.testing import corrupt_file

_N, _DIM, _CLASSES, _BATCH = 96, 6, 3, 16


def _dataset():
    rng = np.random.RandomState(0)
    return [(rng.randn(_DIM).astype(np.float32), np.int32(i % _CLASSES))
            for i in range(_N)]


def _make_updater(comm, zero1=True):
    it = cmn.SerialIterator(_dataset(), _BATCH, shuffle=True, seed=7)
    params = init_mlp(jax.random.PRNGKey(0), [_DIM, 12, _CLASSES])
    opt = cmn.create_multi_node_optimizer(
        optax.adam(5e-2), comm, zero1=zero1)

    def loss_fn(p, x, y):
        return softmax_cross_entropy(mlp_apply(p, x), y)

    return cmn.StandardUpdater(it, opt, loss_fn, params, comm)


def _world_comm(n):
    return cmn.create_communicator("tpu_xla", devices=jax.devices()[:n])


def _host(tree):
    return jax.tree.map(np.asarray, tree)


def _assert_tree_equal(a, b, msg=""):
    jax.tree.map(
        lambda x, y: np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y), err_msg=msg), a, b)


def _part_files(path, it):
    return sorted(f for f in os.listdir(path)
                  if f.startswith(f"snapshot_iter_{it}.s"))


def _trained(comm, steps=3):
    upd = _make_updater(comm)
    for _ in range(steps):
        upd.update()
    return upd


class TestShardOnlySets:
    def test_resume_bitwise_equal_to_full_save(self, tmp_path):
        """One trained state saved both ways must restore identically:
        the covering set IS the snapshot, just laid out differently."""
        comm = _world_comm(8)
        upd = _trained(comm)
        full_dir, shard_dir = tmp_path / "full", tmp_path / "shard"
        create_multi_node_checkpointer(
            comm, str(full_dir), elastic=True).save(upd)
        cp_s = create_multi_node_checkpointer(
            comm, str(shard_dir), elastic=True, shard_only=True)
        cp_s.save(upd)
        # the set really is per-member parts, not per-rank full files
        assert len(_part_files(shard_dir, upd.iteration)) == 8

        ref, got = _make_updater(comm), _make_updater(comm)
        assert create_multi_node_checkpointer(
            comm, str(full_dir), elastic=True).maybe_load(ref) == 3
        cp2 = create_multi_node_checkpointer(
            comm, str(shard_dir), elastic=True, shard_only=True)
        assert cp2.maybe_load(got) == 3
        assert cp2.last_resume_mode == "exact"
        _assert_tree_equal(got.params, _host(ref.params),
                           "shard-set params differ from full save")
        _assert_tree_equal(got.opt_state, _host(ref.opt_state),
                           "shard-set opt_state differs from full save")

    def test_set_bytes_scale_free(self, tmp_path):
        """Aggregate covering-set bytes must be ~1x the state — the
        full-state-per-rank layout costs ~world x (the ROADMAP's
        'snapshot cost stops scaling' claim, asserted not plotted).
        A model big enough that state, not per-file npz overhead,
        carries the bytes."""
        comm = _world_comm(8)
        it = cmn.SerialIterator(_dataset(), _BATCH, shuffle=True, seed=7)
        params = init_mlp(jax.random.PRNGKey(0), [_DIM, 512, _CLASSES])
        opt = cmn.create_multi_node_optimizer(
            optax.adam(5e-2), comm, zero1=True)

        def loss_fn(p, x, y):
            return softmax_cross_entropy(mlp_apply(p, x), y)

        upd = cmn.StandardUpdater(it, opt, loss_fn, params, comm)
        upd.update()
        shard_dir, full_dir = tmp_path / "s", tmp_path / "f"
        cp = create_multi_node_checkpointer(
            comm, str(shard_dir), elastic=True, shard_only=True)
        cp.save(upd)
        # what an 8-process world writes today: the complete state per
        # rank — one file of it is the 1x yardstick, the 8-process
        # aggregate is 8x that
        cp_f = create_multi_node_checkpointer(comm, str(full_dir),
                                              elastic=True)
        cp_f.save(upd)
        full_one = os.path.getsize(
            os.path.join(full_dir, f"snapshot_iter_{upd.iteration}.0"))
        shard_total = sum(
            os.path.getsize(os.path.join(shard_dir, f))
            for f in _part_files(shard_dir, upd.iteration))
        # covering set ~ one full file (+ small per-part meta); the
        # per-rank layout would be 8 * full_one
        assert shard_total < 1.5 * full_one, (
            f"covering set costs {shard_total} bytes vs {full_one} for "
            "ONE full file — shard-only sets should not duplicate state")
        assert shard_total < 0.25 * 8 * full_one

    def test_async_shard_only_bitwise_equal_to_sync_full(self, tmp_path):
        """The acceptance pin: async + shard-only loads bitwise-equal
        to a sync full save of the same state."""
        comm = _world_comm(8)
        upd = _trained(comm)
        sync_dir, async_dir = tmp_path / "sync", tmp_path / "async"
        create_multi_node_checkpointer(
            comm, str(sync_dir), elastic=True).save(upd)
        cp_a = create_multi_node_checkpointer(
            comm, str(async_dir), elastic=True, shard_only=True,
            async_write=True)
        cp_a.save(upd)
        assert upd.iteration in cp_a._streaming  # still in flight
        cp_a.finalize()                          # join: set complete
        assert upd.iteration not in cp_a._streaming

        ref, got = _make_updater(comm), _make_updater(comm)
        create_multi_node_checkpointer(
            comm, str(sync_dir), elastic=True).maybe_load(ref)
        assert create_multi_node_checkpointer(
            comm, str(async_dir), elastic=True,
            shard_only=True).maybe_load(got) == 3
        _assert_tree_equal(got.params, _host(ref.params))
        _assert_tree_equal(got.opt_state, _host(ref.opt_state),
                           "async shard-only differs from sync full")

    def test_shrink_resume_relayouts_from_covering_set(self, tmp_path):
        """The elastic composition: a world-8 covering set re-lays onto
        world=4 exactly like a full snapshot would."""
        from chainermn_tpu.training.elastic import (
            gather_zero1_leaves,
            shard_zero1_leaves,
            topology_signature,
        )

        comm8 = _world_comm(8)
        upd8 = _trained(comm8)
        cp8 = create_multi_node_checkpointer(
            comm8, str(tmp_path), elastic=True, shard_only=True)
        cp8.save(upd8)
        layouts8 = topology_signature(
            comm8, params=upd8.params, opt_state=upd8.opt_state,
            zero1=True)["opt_leaves"]
        full8 = gather_zero1_leaves(_host(upd8.opt_state), layouts8)

        comm4 = _world_comm(4)
        upd4 = _make_updater(comm4)
        cp4 = create_multi_node_checkpointer(
            comm4, str(tmp_path), elastic=True, shard_only=True)
        assert cp4.maybe_load(upd4) == 3
        assert cp4.last_resume_mode == "relayout"
        _assert_tree_equal(upd4.params, _host(upd8.params))
        _assert_tree_equal(
            _host(upd4.opt_state),
            shard_zero1_leaves(full8, layouts8, 4),
            "covering-set relayout differs from a from-scratch shard")


class TestShardSetFallback:
    def _two_sets(self, comm, tmp_path):
        upd = _make_updater(comm)
        cp = create_multi_node_checkpointer(
            comm, str(tmp_path), elastic=True, shard_only=True,
            history=2)
        upd.update()
        cp.save(upd)            # set 1
        upd.update()
        upd.update()
        cp.save(upd)            # set 3
        return cp, upd

    def test_partial_set_falls_back_to_previous_complete(
            self, comm, tmp_path, caplog):
        """A missing member part (the crash-mid-stream shape) makes the
        set invisible to the inventory — resume restores the previous
        complete set without even reading the partial one."""
        _, upd = self._two_sets(comm, tmp_path)
        ref3 = _host(upd.params)
        os.remove(tmp_path / _part_files(tmp_path, 3)[5])
        got = _make_updater(comm)
        cp2 = create_multi_node_checkpointer(
            comm, str(tmp_path), elastic=True, shard_only=True)
        assert cp2.maybe_load(got) == 1
        got_leaf = np.asarray(jax.tree.leaves(got.params)[0])
        ref_leaf = np.asarray(jax.tree.leaves(ref3)[0])
        assert not np.array_equal(got_leaf, ref_leaf), (
            "fallback restored set 3's params — the partial set was "
            "treated as complete")

    def test_corrupt_part_quarantined_and_falls_back(
            self, comm, tmp_path, caplog):
        """A corrupt part fails the whole set (zero redundancy): the
        damaged file is quarantined ``*.corrupt`` and resume falls back
        — the PR 3 semantics, multi-file."""
        self._two_sets(comm, tmp_path)
        victim = _part_files(tmp_path, 3)[2]
        corrupt_file(str(tmp_path / victim), seed=9)
        got = _make_updater(comm)
        cp2 = create_multi_node_checkpointer(
            comm, str(tmp_path), elastic=True, shard_only=True)
        with caplog.at_level(logging.WARNING,
                             "chainermn_tpu.extensions.checkpoint"):
            assert cp2.maybe_load(got) == 1
        assert (tmp_path / f"{victim}.corrupt").exists()
        assert not (tmp_path / victim).exists()

    def test_iteration_shards_skips_part_files(self, comm, tmp_path):
        """The elastic borrow path reads FULL per-rank shards only: a
        shard-only part file sharing the iteration (a mode switch, or a
        peer's mid-quarantine rescan) must be skipped by the scan, not
        crash it with ``int(None)`` mid-agreement."""
        cp = create_multi_node_checkpointer(
            comm, str(tmp_path), elastic=True)
        for fn in ("snapshot_iter_3.0", "snapshot_iter_3.s1of8",
                   "snapshot_iter_3.s0of8"):
            (tmp_path / fn).write_bytes(b"x")
        assert [r for r, _ in cp._iteration_shards(3)] == [0]

    def test_mixed_full_and_shard_sets_interoperate(self, comm,
                                                    tmp_path):
        """A directory holding a full set AND a newer covering set
        resumes from the newest loadable one of either shape — the two
        layouts share one namespace and one agreement."""
        upd = _make_updater(comm)
        cp_full = create_multi_node_checkpointer(
            comm, str(tmp_path), elastic=True, history=2)
        upd.update()
        cp_full.save(upd)                       # full set @1
        upd.update()
        cp_shard = create_multi_node_checkpointer(
            comm, str(tmp_path), elastic=True, shard_only=True,
            history=2)
        cp_shard.save(upd)                      # covering set @2
        ref2 = _host(upd.params)
        got = _make_updater(comm)
        cp2 = create_multi_node_checkpointer(
            comm, str(tmp_path), elastic=True)
        assert cp2.maybe_load(got) == 2
        _assert_tree_equal(got.params, ref2)
        # and with the newest set crippled, the FULL set still covers
        os.remove(tmp_path / _part_files(tmp_path, 2)[0])
        got1 = _make_updater(comm)
        cp3 = create_multi_node_checkpointer(
            comm, str(tmp_path), elastic=True)
        assert cp3.maybe_load(got1) == 1


class TestStreamingGCProtection:
    """The GC × async-save race (ISSUE 12 satellite): a set the
    background writer is still streaming must never count toward — nor
    be evicted by — ``history=N``, with the protection agreed
    collectively (the streaming sets ride the same allgather as the
    inventory)."""

    def _stalled_checkpointer(self, comm, tmp_path, history=2):
        cp = create_multi_node_checkpointer(
            comm, str(tmp_path), elastic=True, shard_only=True,
            async_write=True, history=history)
        gate = threading.Event()
        first_landed = threading.Event()
        real = cp._write_part
        state = {"files": 0}

        def stalled(path, tree, topology, shard_part):
            if state["files"] >= 1:      # first file lands, rest wait
                gate.wait(timeout=30)
            real(path, tree, topology, shard_part)
            state["files"] += 1
            first_landed.set()

        cp._write_part = stalled
        return cp, gate, first_landed, state

    def test_streaming_set_neither_counts_nor_evicts(self, comm,
                                                     tmp_path):
        comm8 = _world_comm(8)
        upd = _make_updater(comm8)
        cp, gate, first_landed, wstate = self._stalled_checkpointer(
            comm8, tmp_path)
        upd.update()
        gate.set()
        cp.save(upd)                 # set 1 (completes: gate open)
        cp._join_pending(barrier_and_gc=True)
        gate.clear()
        first_landed.clear()
        wstate["files"] = 0          # the stall is per-SET
        upd.update()
        upd.update()
        cp.save(upd)                 # set 3: writer stalls mid-stream
        try:
            assert first_landed.wait(timeout=30), (
                "writer thread never landed the first part file")
            assert 3 in cp._streaming
            # a streaming set is invisible to the inventory: a resume
            # scan right now must not see a half-renamed set as real
            assert 3 not in cp._local_iterations()
            common, streaming = cp._agreed_inventory()
            assert 3 in streaming and 3 not in common
            # GC under the race: set 3 must not count toward history=2
            # (that would displace complete set 1) and must not be
            # evicted (that would race the writer's renames)
            cp._cleanup(keep=3)
            assert _part_files(tmp_path, 1), (
                "GC evicted the only complete fallback set while the "
                "newer set was still streaming")
            assert _part_files(tmp_path, 3), (
                "GC deleted files out from under the background writer")
        finally:
            gate.set()
        cp.finalize()                # join: set 3 agreed complete
        assert 3 not in cp._streaming
        assert 3 in cp._local_iterations()
        # both sets survive under history=2; a third save now reaps 1
        upd.update()
        cp.save(upd)
        cp.finalize()
        assert not _part_files(tmp_path, 1)
        assert _part_files(tmp_path, 3) and _part_files(tmp_path, 4)

    def test_streaming_set_not_resumable_until_joined(self, comm,
                                                      tmp_path):
        """A SECOND process (simulated: a fresh checkpointer over the
        same directory) must not resume from a set whose completion was
        never agreed — completeness comes from the agreement, not from
        squinting at the directory mid-rename."""
        comm8 = _world_comm(8)
        upd = _make_updater(comm8)
        cp, gate, first_landed, wstate = self._stalled_checkpointer(
            comm8, tmp_path)
        upd.update()
        gate.set()
        cp.save(upd)
        cp._join_pending(barrier_and_gc=True)
        gate.clear()
        first_landed.clear()
        wstate["files"] = 0          # the stall is per-SET
        upd.update()
        upd.update()
        cp.save(upd)                 # set 3 streaming, stalled
        try:
            assert first_landed.wait(timeout=30)
            got = _make_updater(comm8)
            cp2 = create_multi_node_checkpointer(
                comm8, str(tmp_path), elastic=True, shard_only=True)
            # the fresh checkpointer's scan sees set 3's partial files
            # but the set does not tile -> not in its inventory
            assert cp2.maybe_load(got) == 1
        finally:
            gate.set()
        cp.finalize()
