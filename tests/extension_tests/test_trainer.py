"""Trainer / updater / evaluator integration — the minimum end-to-end DP
slice (SURVEY §7 step 2) as a test: MNIST-shaped problem must converge and
all extension plumbing must fire."""

import os

import jax
import numpy as np
import optax
import pytest

import chainermn_tpu as cmn
from chainermn_tpu.models import (accuracy, init_mlp, mlp_apply,
                                  softmax_cross_entropy)


@pytest.fixture()
def comm():
    return cmn.create_communicator("tpu_xla")


def toy_problem(n=512, dim=16, classes=4, seed=0):
    """Same class prototypes for every seed (it's one problem); ``seed``
    only varies the noise, so train/test splits share the distribution."""
    protos = np.random.RandomState(42).randn(classes, dim).astype(
        np.float32) * 2
    rng = np.random.RandomState(seed)
    data = [(protos[i % classes] + 0.2 * rng.randn(dim).astype(np.float32),
             np.int32(i % classes)) for i in range(n)]
    return data


class TestEndToEnd:
    def test_mnist_style_training_converges(self, comm, tmp_path):
        train = cmn.scatter_dataset(toy_problem(), comm, shuffle=True, seed=0)
        test = cmn.scatter_dataset(toy_problem(seed=9), comm)
        train_it = cmn.SerialIterator(train, 64, shuffle=True, seed=1)
        test_it = cmn.SerialIterator(test, 64, repeat=False)

        params = init_mlp(jax.random.PRNGKey(0), [16, 32, 4])
        opt = cmn.create_multi_node_optimizer(optax.sgd(0.1), comm)

        def loss_fn(p, x, y):
            return softmax_cross_entropy(mlp_apply(p, x), y)

        def metrics_fn(p, x, y):
            logits = mlp_apply(p, x)
            return {"loss": softmax_cross_entropy(logits, y),
                    "accuracy": accuracy(logits, y)}

        updater = cmn.StandardUpdater(train_it, opt, loss_fn, params, comm)
        trainer = cmn.Trainer(updater, (3, "epoch"), out=str(tmp_path))
        ev = cmn.create_multi_node_evaluator(
            cmn.Evaluator(test_it, metrics_fn, comm), comm)
        trainer.extend(ev, trigger=(1, "epoch"))
        log = cmn.LogReport(trigger=(1, "epoch"))
        trainer.extend(log)
        trainer.run()

        assert updater.iteration == 8 * 3  # 512/64 per epoch
        final = log.log[-1]
        assert final["validation/accuracy"] > 0.95
        assert os.path.exists(tmp_path / "log")

    def test_extension_trigger_counts(self, comm, tmp_path):
        train = toy_problem(128)
        it = cmn.SerialIterator(train, 32)
        params = init_mlp(jax.random.PRNGKey(0), [16, 4])
        opt = cmn.create_multi_node_optimizer(optax.sgd(0.01), comm)

        def loss_fn(p, x, y):
            return softmax_cross_entropy(mlp_apply(p, x), y)

        updater = cmn.StandardUpdater(it, opt, loss_fn, params, comm)
        trainer = cmn.Trainer(updater, (2, "epoch"), out=str(tmp_path))
        fired = {"epoch": 0, "iter": 0}

        @cmn.training.make_extension(trigger=(1, "epoch"))
        def on_epoch(tr):
            fired["epoch"] += 1

        @cmn.training.make_extension(trigger=(2, "iteration"))
        def on_iter(tr):
            fired["iter"] += 1

        trainer.extend(on_epoch)
        trainer.extend(on_iter)
        trainer.run()
        assert fired["epoch"] == 2
        assert fired["iter"] == 4  # 8 iterations / every 2

    def test_double_buffered_training_still_converges(self, comm, tmp_path):
        train = cmn.scatter_dataset(toy_problem(), comm, shuffle=True, seed=0)
        it = cmn.SerialIterator(train, 64, shuffle=True, seed=1)
        params = init_mlp(jax.random.PRNGKey(0), [16, 32, 4])
        opt = cmn.create_multi_node_optimizer(
            optax.sgd(0.1), comm, double_buffering=True)

        def loss_fn(p, x, y):
            return softmax_cross_entropy(mlp_apply(p, x), y)

        updater = cmn.StandardUpdater(it, opt, loss_fn, params, comm)
        trainer = cmn.Trainer(updater, (4, "epoch"), out=str(tmp_path))
        trainer.run()
        # evaluate manually
        test = toy_problem(seed=7)
        ev = cmn.Evaluator(cmn.SerialIterator(test, 64, repeat=False),
                           lambda p, x, y: {"acc": accuracy(mlp_apply(p, x), y)},
                           comm)
        out = ev.evaluate(updater.params)
        assert out["acc"] > 0.9

    def test_loopback_world_runs_too(self, tmp_path):
        """Whole stack on a size-1 communicator (single-chip path)."""
        lb = cmn.create_communicator("loopback")
        train = toy_problem(64)
        it = cmn.SerialIterator(train, 16)
        params = init_mlp(jax.random.PRNGKey(0), [16, 4])
        opt = cmn.create_multi_node_optimizer(optax.sgd(0.05), lb)

        def loss_fn(p, x, y):
            return softmax_cross_entropy(mlp_apply(p, x), y)

        updater = cmn.StandardUpdater(it, opt, loss_fn, params, lb)
        trainer = cmn.Trainer(updater, (8, "iteration"), out=str(tmp_path))
        trainer.run()
        assert updater.iteration == 8

    def test_finalize_runs_when_update_raises(self, comm, tmp_path):
        """A crash mid-loop must still finalize extensions — an in-flight
        async checkpoint write would otherwise be lost with the process
        (and the checkpointer must skip its barrier during unwind)."""
        train = toy_problem(64)
        it = cmn.SerialIterator(train, 16)
        params = init_mlp(jax.random.PRNGKey(0), [16, 4])
        opt = cmn.create_multi_node_optimizer(optax.sgd(0.05), comm)

        def loss_fn(p, x, y):
            return softmax_cross_entropy(mlp_apply(p, x), y)

        updater = cmn.StandardUpdater(it, opt, loss_fn, params, comm)
        trainer = cmn.Trainer(updater, (20, "iteration"),
                              out=str(tmp_path))
        cp = cmn.create_multi_node_checkpointer(
            comm, str(tmp_path / "ckpt"), async_write=True)
        trainer.extend(cp, trigger=(2, "iteration"))

        real_update = updater.update

        def exploding_update():
            if updater.iteration >= 3:
                raise RuntimeError("simulated mid-training crash")
            real_update()

        updater.update = exploding_update
        with pytest.raises(RuntimeError, match="simulated"):
            trainer.run()
        # the iteration-2 async write survived the crash
        fresh = cmn.StandardUpdater(
            cmn.SerialIterator(train, 16), opt, loss_fn,
            init_mlp(jax.random.PRNGKey(1), [16, 4]), comm)
        resumed = cmn.create_multi_node_checkpointer(
            comm, str(tmp_path / "ckpt")).maybe_load(fresh)
        assert resumed == 2
