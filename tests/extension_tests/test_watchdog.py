"""TrainingWatchdog: step-boundary heartbeats arm a monitor thread that
must stay silent on a healthy run, fire a structured stall report within
one check interval of a stall crossing the threshold, and shut down
cleanly with the trainer (no leaked threads)."""

import json
import threading
import time

import jax
import numpy as np
import optax
import pytest

import chainermn_tpu as cmn
from chainermn_tpu.extensions import TrainingWatchdog
from chainermn_tpu.models import init_mlp, mlp_apply, softmax_cross_entropy


def _dataset(n=64, dim=6, classes=3, seed=0):
    rng = np.random.RandomState(seed)
    return [(rng.randn(dim).astype(np.float32), np.int32(i % classes))
            for i in range(n)]


def _make_trainer(comm, out, epochs=2):
    it = cmn.SerialIterator(_dataset(), 16, shuffle=True, seed=3)
    params = init_mlp(jax.random.PRNGKey(0), [6, 12, 3])
    opt = cmn.create_multi_node_optimizer(optax.sgd(0.05), comm)

    def loss_fn(p, x, y):
        return softmax_cross_entropy(mlp_apply(p, x), y)

    upd = cmn.StandardUpdater(it, opt, loss_fn, params, comm)
    return cmn.Trainer(upd, (epochs, "epoch"), out=str(out))


class TestWatchdogUnit:
    def test_stall_fires_within_one_check_interval(self, tmp_path):
        reports = []
        wd = TrainingWatchdog(stall_timeout=0.4, check_interval=0.1,
                              on_stall=reports.append,
                              report_path=str(tmp_path / "stall.json"))
        wd.start()
        try:
            wd.heartbeat(iteration=7)
            deadline = time.monotonic() + 0.4 + 0.1 + 0.3  # +slack
            while not reports and time.monotonic() < deadline:
                time.sleep(0.02)
        finally:
            wd.stop()
        assert wd.stall_count == 1
        rep = reports[0]
        assert rep["kind"] == "local-stall"
        assert rep["iteration"] == 7
        assert rep["seconds_since_heartbeat"] > 0.4
        # the structured report carries every thread's Python stack
        assert any("MainThread" in k for k in rep["threads"])
        on_disk = json.load(open(tmp_path / "stall.json"))
        assert on_disk["kind"] == "local-stall"

    def test_stall_report_embeds_merged_metrics(self, tmp_path):
        """A hung job's last Prometheus state ships with the diagnosis:
        the stall report carries the merged metrics snapshot and its
        exposition text alongside the trace tail."""
        from chainermn_tpu.utils.metrics import (
            MetricsRegistry,
            set_registry,
        )

        prev = set_registry(MetricsRegistry(enabled=True))
        try:
            reports = []
            wd = TrainingWatchdog(stall_timeout=0.2, check_interval=0.05,
                                  on_stall=reports.append,
                                  report_path=str(tmp_path / "s.json"))
            wd.start()
            try:
                wd.heartbeat(iteration=3)   # records watchdog/heartbeats
                deadline = time.monotonic() + 1.0
                while not reports and time.monotonic() < deadline:
                    time.sleep(0.02)
            finally:
                wd.stop()
            rep = reports[0]
            assert rep["metrics_enabled"] is True
            assert rep["metrics"]["watchdog/heartbeats"]["value"] == 1
            assert "watchdog/stalls" in rep["metrics"]
            assert "watchdog_heartbeats" in rep["metrics_prom"]
            assert 'rank="merged"' in rep["metrics_prom"]
            # and the on-disk report serialized it too
            on_disk = json.load(open(tmp_path / "s.json"))
            assert on_disk["metrics"]["watchdog/heartbeats"]["value"] == 1
        finally:
            set_registry(prev)

    def test_stall_report_metrics_disabled_registry(self, tmp_path):
        """Registry off (the production default): the report still
        carries the keys, empty — never an exception path."""
        reports = []
        wd = TrainingWatchdog(stall_timeout=0.15, check_interval=0.05,
                              on_stall=reports.append,
                              report_path=str(tmp_path / "s.json"))
        wd.start()
        try:
            wd.heartbeat(iteration=1)
            deadline = time.monotonic() + 1.0
            while not reports and time.monotonic() < deadline:
                time.sleep(0.02)
        finally:
            wd.stop()
        assert reports[0]["metrics_enabled"] is False
        assert reports[0]["metrics"] == {}

    def test_not_armed_before_first_heartbeat(self, tmp_path):
        """Compile time before step 1 must never false-fire."""
        wd = TrainingWatchdog(stall_timeout=0.1, check_interval=0.05,
                              report_path=str(tmp_path / "s.json"))
        wd.start()
        time.sleep(0.3)
        wd.stop()
        assert wd.stall_count == 0

    def test_one_report_per_stall_episode(self, tmp_path):
        reports = []
        wd = TrainingWatchdog(stall_timeout=0.15, check_interval=0.05,
                              on_stall=reports.append,
                              report_path=str(tmp_path / "s.json"))
        wd.start()
        try:
            wd.heartbeat(iteration=1)
            time.sleep(0.5)          # one long stall, many check ticks
            assert wd.stall_count == 1
            wd.heartbeat(iteration=2)  # recovery re-arms the reporter
            time.sleep(0.4)
        finally:
            wd.stop()
        assert wd.stall_count == 2
        assert [r["iteration"] for r in reports] == [1, 2]

    def test_peer_stall_reported_once_per_episode(self, tmp_path):
        """A permanently dead peer must produce ONE peer-stall report,
        not a stack dump every check interval for the rest of the job;
        a recovered peer re-arms its slot."""
        reports = []
        wd = TrainingWatchdog(stall_timeout=0.2, check_interval=0.05,
                              on_stall=reports.append,
                              report_path=str(tmp_path / "s.json"))
        ages = {"now": {1: 9.9}}
        wd._peer_ages = lambda: dict(ages["now"])
        wd.start()
        try:
            deadline = time.monotonic() + 0.6
            while time.monotonic() < deadline:  # this rank stays healthy
                wd.heartbeat(iteration=1)
                time.sleep(0.02)
            assert len(reports) == 1, reports
            assert reports[0]["kind"] == "peer-stall"
            assert reports[0]["stalled_peers"] == {1: 9.9}
            # peer recovers, then stalls again -> a second report
            ages["now"] = {1: 0.0}
            t0 = time.monotonic()
            while time.monotonic() - t0 < 0.2:
                wd.heartbeat(iteration=2)
                time.sleep(0.02)
            ages["now"] = {1: 7.7}
            t0 = time.monotonic()
            while time.monotonic() - t0 < 0.3 and len(reports) < 2:
                wd.heartbeat(iteration=3)
                time.sleep(0.02)
        finally:
            wd.stop()
        assert len(reports) == 2
        # peer-only reports never consumed the local stall episode
        assert all(r["kind"] == "peer-stall" for r in reports)

    def test_never_published_peer_is_aged_from_monitor_start(
            self, tmp_path, monkeypatch):
        """A rank wedged BEFORE its first heartbeat (the PJRT-init hang
        class) never appears in the KV directory — survivors must age
        it from monitor start and report it, not treat it as
        invisible."""
        from types import SimpleNamespace

        reports = []
        wd = TrainingWatchdog(stall_timeout=0.2, check_interval=0.05,
                              on_stall=reports.append,
                              report_path=str(tmp_path / "s.json"))
        wd.comm = SimpleNamespace(inter_size=2, inter_rank=0)
        fake_kv = SimpleNamespace(key_value_dir_get=lambda prefix: [
            ("watchdog/hb/0", "5,123.0")])  # only OUR rank ever beat
        monkeypatch.setattr(TrainingWatchdog, "_kv",
                            property(lambda self: fake_kv))
        wd.start()
        try:
            deadline = time.monotonic() + 0.6
            while not reports and time.monotonic() < deadline:
                wd.heartbeat(iteration=0)
                time.sleep(0.02)
        finally:
            wd.stop()
        assert reports, "never-published peer was never detected"
        assert reports[0]["kind"] == "peer-stall"
        assert 1 in reports[0]["stalled_peers"]

    def test_bad_config_rejected(self):
        with pytest.raises(ValueError):
            TrainingWatchdog(stall_timeout=0)
        with pytest.raises(ValueError):
            TrainingWatchdog(stall_timeout=10, check_interval=-1)

    def test_on_stall_exception_swallowed(self, tmp_path):
        def boom(report):
            raise RuntimeError("metrics push failed")

        wd = TrainingWatchdog(stall_timeout=0.1, check_interval=0.05,
                              on_stall=boom,
                              report_path=str(tmp_path / "s.json"))
        wd.start()
        try:
            wd.heartbeat()
            time.sleep(0.35)
        finally:
            wd.stop()
        assert wd.stall_count >= 1  # survived the callback crash


class TestWatchdogTrainer:
    def test_healthy_run_no_report_and_no_thread_leak(self, comm,
                                                      tmp_path):
        before = {t.ident for t in threading.enumerate()}
        trainer = _make_trainer(comm, tmp_path)
        wd = TrainingWatchdog(stall_timeout=60, comm=comm)
        trainer.extend(wd)
        trainer.run()
        assert wd.stall_count == 0
        assert wd.report_path == str(tmp_path / "stall_report.json")
        leaked = [t for t in threading.enumerate()
                  if t.ident not in before
                  and t.name == "training-watchdog"]
        assert not leaked, "finalize did not stop the monitor thread"

    def test_stalled_step_reports_with_iteration(self, comm, tmp_path):
        trainer = _make_trainer(comm, tmp_path)
        reports = []
        wd = TrainingWatchdog(stall_timeout=0.3, check_interval=0.1,
                              on_stall=reports.append)
        trainer.extend(wd)

        @cmn.training.make_extension(trigger=(1, "iteration"), priority=5)
        def stall(tr):
            if tr.updater.iteration == 3:
                time.sleep(0.8)  # wedge one step past the threshold

        trainer.extend(stall)
        trainer.run()
        assert wd.stall_count == 1
        assert reports[0]["iteration"] == 3
        assert reports[0]["kind"] == "local-stall"
        report = json.load(open(tmp_path / "stall_report.json"))
        assert report["seconds_since_heartbeat"] > 0.3
