"""Evaluator remainder handling: tails that don't divide the world size
are padded to ONE fixed bucket (pad rows = copies of row 0) and their
true means recovered by real-row weighting — so evaluation compiles at
most twice per batch arity no matter how many distinct tail lengths an
epoch produces, while every validation example still contributes with
exactly its old weight.
"""

import jax
import numpy as np
import pytest

import chainermn_tpu as cmn
from chainermn_tpu.models import (accuracy, init_mlp, mlp_apply,
                                  softmax_cross_entropy)


@pytest.fixture()
def comm():
    return cmn.create_communicator("tpu_xla")


def _data(n, seed=0):
    rng = np.random.RandomState(seed)
    return [(rng.randn(6).astype(np.float32), np.int32(i % 3))
            for i in range(n)]


def _metrics_fn(p, x, y):
    logits = mlp_apply(p, x)
    return {"loss": softmax_cross_entropy(logits, y),
            "accuracy": accuracy(logits, y)}


def _reference(params, data):
    x = np.stack([d[0] for d in data])
    y = np.stack([d[1] for d in data])
    import jax.numpy as jnp

    return {k: float(v) for k, v in _metrics_fn(
        params, jnp.asarray(x), jnp.asarray(y)).items()}


def test_remainder_metrics_exact(comm):
    """Batches of 20 over 8 devices leave 4-row remainders (and a final
    5-row one): padded evaluation must reproduce the plain full-dataset
    means to float tolerance."""
    params = init_mlp(jax.random.PRNGKey(0), [6, 12, 3])
    data = _data(45)
    ev = cmn.Evaluator(cmn.SerialIterator(data, 20, repeat=False),
                       _metrics_fn, comm)
    out = ev.evaluate(params)
    ref = _reference(params, data)
    for k in ref:
        assert out[k] == pytest.approx(ref[k], rel=1e-4), (k, out, ref)


def test_many_tail_shapes_one_executable(comm):
    """Every remainder length 1..world-1 must reuse the SAME cached
    remainder entry (the old path retraced per distinct tail length)."""
    params = init_mlp(jax.random.PRNGKey(0), [6, 12, 3])
    ev = cmn.Evaluator(cmn.SerialIterator(_data(8), 8, repeat=False),
                       _metrics_fn, comm)
    for r in range(1, comm.size):
        data = _data(8 + r, seed=r)
        ev.iterator = cmn.SerialIterator(data, 8 + r, repeat=False)
        out = ev.evaluate(params)
        ref = _reference(params, data)
        for k in ref:
            assert out[k] == pytest.approx(ref[k], rel=1e-4), (r, k)
    # one sharded main step + one padded remainder step per arity
    assert len(ev._step_cache) == 2, sorted(
        ev._step_cache, key=str)


def test_divisible_batches_never_touch_remainder(comm):
    params = init_mlp(jax.random.PRNGKey(0), [6, 12, 3])
    ev = cmn.Evaluator(cmn.SerialIterator(_data(64), 16, repeat=False),
                       _metrics_fn, comm)
    ev.evaluate(params)
    assert list(ev._step_cache) == [2]
