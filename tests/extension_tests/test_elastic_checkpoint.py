"""Elastic resume drills (docs/RESILIENCE.md "Elastic resume"): a
ZeRO-1 training job snapshotted at world=8 must resume at world=4 and
world=2 with BITWISE-identical re-laid-out state — proven against a
from-scratch gather — and the continued run's loss trajectory must
match the uninterrupted world-8 run (reduction order is the only
difference).  Same-topology resumes must stay on the exact path and
never re-lay anything.  Single-process mesh resize on the 8-device
virtual pod, so the whole drill runs everywhere."""

import logging
import os

import jax
import numpy as np
import optax
import pytest

import chainermn_tpu as cmn
from chainermn_tpu.extensions import create_multi_node_checkpointer
from chainermn_tpu.models import init_mlp, mlp_apply, softmax_cross_entropy
from chainermn_tpu.testing import FaultInjector, FaultPlan
from chainermn_tpu.training.elastic import (
    ElasticMembership,
    RelayoutError,
    gather_zero1_leaves,
    relayout_state,
    same_topology,
    shard_zero1_leaves,
    topology_signature,
)

_DATA_SEED = 0
_N, _DIM, _CLASSES, _BATCH = 96, 6, 3, 16


def _dataset():
    rng = np.random.RandomState(_DATA_SEED)
    return [(rng.randn(_DIM).astype(np.float32), np.int32(i % _CLASSES))
            for i in range(_N)]


def _make_updater(comm, zero1=True):
    it = cmn.SerialIterator(_dataset(), _BATCH, shuffle=True, seed=7)
    params = init_mlp(jax.random.PRNGKey(0), [_DIM, 12, _CLASSES])
    opt = cmn.create_multi_node_optimizer(
        optax.adam(5e-2), comm, zero1=zero1)

    def loss_fn(p, x, y):
        return softmax_cross_entropy(mlp_apply(p, x), y)

    return cmn.StandardUpdater(it, opt, loss_fn, params, comm)


def _world_comm(n):
    return cmn.create_communicator("tpu_xla", devices=jax.devices()[:n])


def _host(tree):
    return jax.tree.map(np.asarray, tree)


def _run_losses(upd, n):
    losses = []
    for _ in range(n):
        upd.update()
        losses.append(float(upd.observation["main/loss"]))
    return losses


def _opt_layouts(comm, upd):
    return topology_signature(
        comm, params=upd.params, opt_state=upd.opt_state,
        zero1=True)["opt_leaves"]


def _assert_tree_equal(a, b, msg=""):
    jax.tree.map(
        lambda x, y: np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y), err_msg=msg), a, b)


class TestElasticDrill:
    def test_save_at_8_resume_at_4_then_2_bitwise(self, tmp_path):
        """The acceptance drill: snapshot at world=8, resume at 4 then
        2.  At every hop the re-laid state must be bitwise what a
        from-scratch sharding of the gathered state would hold, params
        must be bitwise-identical, and continued training must track
        the uninterrupted world-8 trajectory."""
        comm8 = _world_comm(8)
        upd8 = _make_updater(comm8)
        cp8 = create_multi_node_checkpointer(comm8, str(tmp_path),
                                             elastic=True)

        # hop 0: the FaultPlan shrink action saves + stops the trainer
        trainer = cmn.Trainer(upd8, (100, "epoch"), out=str(tmp_path))
        inj = FaultInjector(
            FaultPlan(resize_at_iteration=4, resize_to=4), comm8,
            checkpointer=cp8)
        trainer.extend(inj)
        trainer.run()
        assert ("resize", 4, 4) in inj.fired
        assert "elastic resize" in trainer.stop_reason
        # the stop is clean: exactly 4 iterations ran
        assert upd8.iteration == 4

        saved_params = _host(upd8.params)
        layouts8 = _opt_layouts(comm8, upd8)
        full8 = gather_zero1_leaves(_host(upd8.opt_state), layouts8)

        # uninterrupted continuation at world=8 (the trajectory oracle)
        ref_losses = _run_losses(upd8, 6)

        # hop 1: resume at world=4 through the re-layout path
        comm4 = _world_comm(4)
        upd4 = _make_updater(comm4)
        cp4 = create_multi_node_checkpointer(comm4, str(tmp_path),
                                             elastic=True)
        assert cp4.maybe_load(upd4) == 4
        assert cp4.last_resume_mode == "relayout"
        _assert_tree_equal(upd4.params, saved_params,
                           "params must load bitwise at world=4")
        # re-laid state == from-scratch sharding of the gathered state
        _assert_tree_equal(
            _host(upd4.opt_state),
            shard_zero1_leaves(full8, layouts8, 4),
            "relayout at 4 differs from a from-scratch shard")
        # and gathers back to the identical full state
        _assert_tree_equal(
            gather_zero1_leaves(_host(upd4.opt_state),
                                _opt_layouts(comm4, upd4)),
            full8, "gathered state changed across the 8->4 hop")

        got4 = _run_losses(upd4, 3)
        np.testing.assert_allclose(
            got4, ref_losses[:3], rtol=2e-4, atol=1e-5,
            err_msg="world-4 continuation diverged from the "
                    "uninterrupted world-8 trajectory")

        # hop 2: save at world=4, resume at world=2
        cp4.save(upd4)
        layouts4 = _opt_layouts(comm4, upd4)
        full4 = gather_zero1_leaves(_host(upd4.opt_state), layouts4)
        comm2 = _world_comm(2)
        upd2 = _make_updater(comm2)
        cp2 = create_multi_node_checkpointer(comm2, str(tmp_path),
                                             elastic=True)
        assert cp2.maybe_load(upd2) == 7
        assert cp2.last_resume_mode == "relayout"
        _assert_tree_equal(upd2.params, _host(upd4.params))
        _assert_tree_equal(
            _host(upd2.opt_state), shard_zero1_leaves(full4, layouts4, 2),
            "relayout at 2 differs from a from-scratch shard")

        got2 = _run_losses(upd2, 3)
        np.testing.assert_allclose(
            got2, ref_losses[3:6], rtol=2e-4, atol=1e-5,
            err_msg="world-2 continuation diverged from the "
                    "uninterrupted world-8 trajectory")
        # the drill actually trained: the trajectory moved
        assert ref_losses[0] != ref_losses[-1]

    def test_grow_resume_2_to_8_bitwise(self, tmp_path):
        """The grow direction: a world-2 snapshot re-lays onto world=8
        (stack leaves replicate out, shard leaves re-split)."""
        comm2 = _world_comm(2)
        upd2 = _make_updater(comm2)
        _run_losses(upd2, 3)
        cp2 = create_multi_node_checkpointer(comm2, str(tmp_path),
                                            elastic=True)
        cp2.save(upd2)
        layouts2 = _opt_layouts(comm2, upd2)
        full2 = gather_zero1_leaves(_host(upd2.opt_state), layouts2)

        comm8 = _world_comm(8)
        upd8 = _make_updater(comm8)
        cp8 = create_multi_node_checkpointer(comm8, str(tmp_path),
                                             elastic=True)
        assert cp8.maybe_load(upd8) == 3
        assert cp8.last_resume_mode == "relayout"
        _assert_tree_equal(upd8.params, _host(upd2.params))
        _assert_tree_equal(
            _host(upd8.opt_state), shard_zero1_leaves(full2, layouts2, 8))
        upd8.update()  # the grown world trains on

    def test_same_topology_resume_stays_exact_and_bitwise(self,
                                                          tmp_path):
        """elastic=True with an UNCHANGED topology must never enter the
        re-layout path: the resume is the plain bitwise one."""
        comm8 = _world_comm(8)
        upd = _make_updater(comm8)
        _run_losses(upd, 3)
        cp = create_multi_node_checkpointer(comm8, str(tmp_path),
                                            elastic=True)
        cp.save(upd)
        upd2 = _make_updater(comm8)
        cp2 = create_multi_node_checkpointer(comm8, str(tmp_path),
                                             elastic=True)
        assert cp2.maybe_load(upd2) == 3
        assert cp2.last_resume_mode == "exact"
        _assert_tree_equal(upd2.params, _host(upd.params))
        _assert_tree_equal(upd2.opt_state, _host(upd.opt_state))

    def test_non_elastic_checkpointer_refuses_topology_change(
            self, tmp_path):
        comm8 = _world_comm(8)
        upd = _make_updater(comm8)
        _run_losses(upd, 2)
        cp = create_multi_node_checkpointer(comm8, str(tmp_path))
        cp.save(upd)
        comm4 = _world_comm(4)
        upd4 = _make_updater(comm4)
        cp4 = create_multi_node_checkpointer(comm4, str(tmp_path))
        with pytest.raises(RuntimeError, match="elastic=True"):
            cp4.maybe_load(upd4)

    def test_relayout_drops_snapshot_riding_plan(self, tmp_path, caplog):
        """The tuned exchange plan rides the snapshot for bitwise
        same-topology resume; a topology change must INVALIDATE it so
        resume re-tunes instead of replaying a stale program."""
        topo8 = {"format": 1, "world_size": 8, "inter_size": 1,
                 "axis_names": ["world"], "mesh_shape": [8],
                 "zero1": False}
        topo4 = dict(topo8, world_size=4, mesh_shape=[4])
        state = {"iteration": 5, "params": {"w": np.ones(3)},
                 "opt_state": {"m": np.ones(3)},
                 "train_state": {"exchange_plan": {"strategy": "fused"},
                                 "updater": {"epoch_detail": 1.0}}}
        with caplog.at_level(logging.INFO,
                             "chainermn_tpu.training.elastic"):
            out = relayout_state(state, topo8, topo4)
        assert "exchange_plan" not in out["train_state"]
        assert out["train_state"]["updater"] == {"epoch_detail": 1.0}
        # the input state is not mutated
        assert "exchange_plan" in state["train_state"]
        assert any("exchange plan" in r.message for r in caplog.records)


class TestRelayoutUnit:
    def _layouts(self):
        # flattened-leaf order is the dict's sorted-key order:
        # count (stack), lr (rep), mu (shard)
        return [{"kind": "stack"}, {"kind": "rep"},
                {"kind": "shard", "size": 10}]

    def _state(self, world):
        s = -(-10 // world)
        flat = np.zeros(world * s, np.float32)
        flat[:10] = np.arange(10, dtype=np.float32) + 1
        return {"mu": flat.reshape(world, s),
                "count": np.full((world,), 7, np.int32),
                "lr": np.float32(0.5)}

    @pytest.mark.parametrize("src,dst", [(8, 4), (8, 2), (2, 8),
                                         (4, 3), (3, 4), (8, 8)])
    def test_roundtrip_matches_from_scratch(self, src, dst):
        topo_s = {"zero1": True, "world_size": src,
                  "opt_leaves": self._layouts()}
        topo_d = {"zero1": True, "world_size": dst}
        state = {"opt_state": self._state(src)}
        out = relayout_state(state, topo_s, topo_d)
        expect = self._state(dst)
        for k in ("mu", "count", "lr"):
            np.testing.assert_array_equal(out["opt_state"][k], expect[k])
            assert np.asarray(out["opt_state"][k]).dtype \
                == np.asarray(expect[k]).dtype

    def test_unidentified_differing_stack_refuses(self):
        """A member-stacked leaf whose rows differ but that the layout
        record calls 'stack' must refuse the re-slice: silently keeping
        row 0 would corrupt state whose layout is unknown."""
        topo_s = {"zero1": True, "world_size": 4,
                  "opt_leaves": [{"kind": "stack"}]}
        bad = {"opt_state": {"x": np.arange(4, dtype=np.float32)}}
        with pytest.raises(RelayoutError, match="rows differ"):
            relayout_state(bad, topo_s, {"zero1": True, "world_size": 2})

    def test_zero1_mode_mismatch_refuses(self):
        with pytest.raises(RelayoutError, match="zero1"):
            relayout_state({}, {"zero1": True, "world_size": 8,
                                "opt_leaves": []},
                           {"zero1": False, "world_size": 4})

    def test_leaf_count_mismatch_refuses(self):
        topo_s = {"zero1": True, "world_size": 4,
                  "opt_leaves": [{"kind": "rep"}]}
        state = {"opt_state": {"a": np.zeros(2), "b": np.zeros(2)}}
        with pytest.raises(RelayoutError, match="leaves"):
            relayout_state(state, topo_s,
                           {"zero1": True, "world_size": 2})

    def test_same_topology_comparisons(self):
        a = {"format": 1, "world_size": 8, "inter_size": 1,
             "axis_names": ["world"], "mesh_shape": [8], "zero1": True}
        assert same_topology(a, dict(a))
        assert not same_topology(a, dict(a, world_size=4))
        assert not same_topology(a, dict(a, zero1=False))
        assert not same_topology(a, None)
        assert not same_topology(None, a)


class TestMembershipSingleProcess:
    def test_epochs_bump_and_persist(self, comm, tmp_path):
        m1 = ElasticMembership(comm, path=str(tmp_path))
        rec1 = m1.agree()
        assert rec1.epoch == 1 and rec1.members == [0]
        assert os.path.exists(tmp_path / "membership.json")
        # a later incarnation (fresh object — fresh process in real
        # life) reads the persisted epoch and bumps past it
        m2 = ElasticMembership(comm, path=str(tmp_path))
        assert m2.stored_epoch() == 1
        rec2 = m2.agree()
        assert rec2.epoch == 2

    def test_note_stop_persists_without_agree(self, comm, tmp_path):
        m = ElasticMembership(comm, path=str(tmp_path))
        m.agree()
        m.note_stop(reason="preemption", iteration=42)
        import json

        payload = json.loads((tmp_path / "membership.json").read_text())
        assert payload["stopped"]["reason"] == "preemption"
        assert payload["stopped"]["iteration"] == 42
        assert payload["epoch"] == 1

    def test_fence_before_agree_raises(self, comm, tmp_path):
        m = ElasticMembership(comm, path=str(tmp_path))
        with pytest.raises(RuntimeError, match="agree"):
            m.fence(comm)

    def test_fence_sets_channel_generation(self, comm, tmp_path):
        from chainermn_tpu.communicators._obj_channel import (
            KVObjectChannel,
        )

        m = ElasticMembership(comm, path=str(tmp_path))
        rec = m.agree()
        chan = KVObjectChannel(tag="fence-test")
        assert m.fence(chan, comm) == rec.epoch
        assert chan.generation == rec.epoch
        assert comm._obj_channel.generation == rec.epoch
