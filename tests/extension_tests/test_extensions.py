"""Extension-layer tests — analogue of the reference's ``extension_tests``
(checkpointer save/GC/resume agreement, snapshot, observation aggregation,
persistent-value allreduce, global except hook install).
"""

import os
import sys

import jax.numpy as jnp
import numpy as np
import pytest

from chainermn_tpu.extensions import (
    AllreducePersistentValues,
    ObservationAggregator,
    add_global_except_hook,
    create_multi_node_checkpointer,
    multi_node_snapshot,
)
from chainermn_tpu.extensions.snapshot import load_snapshot
from chainermn_tpu.utils.serialization import load_state, save_state


class FakeUpdater:
    def __init__(self, seed=0):
        rng = np.random.RandomState(seed)
        self.params = {"w": jnp.asarray(rng.randn(3, 2).astype(np.float32)),
                       "b": jnp.zeros((2,), jnp.float32)}
        self.opt_state = {"mu": jnp.ones((3, 2), jnp.float32)}
        self.iteration = 0
        self.observation = {}


class FakeTrainer:
    def __init__(self, updater, out):
        self.updater = updater
        self.out = str(out)
        self.observation = {}


class TestSerialization:
    def test_roundtrip(self, tmp_path):
        tree = {"a": jnp.arange(6.0).reshape(2, 3),
                "b": [np.int64(7), {"c": jnp.ones((4,), jnp.bfloat16)}]}
        p = str(tmp_path / "s.npz")
        save_state(p, tree)
        out = load_state(p)
        np.testing.assert_array_equal(out["a"], np.arange(6.0).reshape(2, 3))
        assert int(out["b"][0]) == 7
        assert out["b"][1]["c"].dtype == jnp.bfloat16

    def test_atomic_no_partial_file(self, tmp_path):
        p = str(tmp_path / "s.npz")
        save_state(p, {"x": jnp.ones(3)})
        assert not os.path.exists(p + ".tmp")


class TestCheckpointer:
    def test_fresh_start_returns_none(self, comm, tmp_path):
        cp = create_multi_node_checkpointer(comm, str(tmp_path))
        assert cp.maybe_load(FakeUpdater()) is None

    def test_save_resume_roundtrip(self, comm, tmp_path):
        cp = create_multi_node_checkpointer(comm, str(tmp_path))
        up = FakeUpdater(seed=1)
        up.iteration = 42
        cp.save(up)

        fresh = FakeUpdater(seed=2)
        resumed = create_multi_node_checkpointer(
            comm, str(tmp_path)).maybe_load(fresh)
        assert resumed == 42
        assert fresh.iteration == 42
        np.testing.assert_array_equal(fresh.params["w"], up.params["w"])
        np.testing.assert_array_equal(fresh.opt_state["mu"],
                                      up.opt_state["mu"])

    def test_gc_keeps_only_latest(self, comm, tmp_path):
        cp = create_multi_node_checkpointer(comm, str(tmp_path))
        up = FakeUpdater()
        for it in (10, 20, 30):
            up.iteration = it
            cp.save(up)
        files = sorted(os.listdir(tmp_path))
        assert files == ["snapshot_iter_30.0"]

    def test_resumes_latest_common(self, comm, tmp_path):
        cp = create_multi_node_checkpointer(comm, str(tmp_path))
        up = FakeUpdater()
        up.iteration = 5
        cp.save(up)
        up.iteration = 9
        cp.save(up)
        fresh = FakeUpdater(seed=3)
        assert create_multi_node_checkpointer(
            comm, str(tmp_path)).maybe_load(fresh) == 9

    def test_partial_newer_set_not_chosen(self, comm, tmp_path, monkeypatch):
        """The intersection logic: an iteration visible locally but missing
        on another (simulated) process must be excluded from resume."""
        cp = create_multi_node_checkpointer(comm, str(tmp_path))
        up = FakeUpdater()
        up.iteration = 9
        cp.save(up)
        # forge a NEWER shard for this rank only (bypassing save's GC) —
        # as if this process wrote iteration 99 but a peer's shard is lost
        save_state(str(tmp_path / "snapshot_iter_99.0"),
                   {"iteration": 99, "world_size": 1,
                    "params": up.params, "opt_state": up.opt_state})
        loader = create_multi_node_checkpointer(comm, str(tmp_path))
        assert loader._local_iterations() == {9, 99}
        # simulate a peer that only holds iteration 9 (presence rides a
        # set; the later load-verdict allgather is a bool and passes
        # through the one-element fallback)
        monkeypatch.setattr(
            loader.comm, "allgather_obj",
            lambda obj: [obj, {9}] if isinstance(obj, set) else [obj])
        fresh = FakeUpdater(seed=3)
        assert loader.maybe_load(fresh) == 9
        assert fresh.iteration == 9

    def test_world_size_mismatch_raises(self, comm, tmp_path):
        cp = create_multi_node_checkpointer(comm, str(tmp_path))
        up = FakeUpdater()
        up.iteration = 7
        cp.save(up)
        loader = create_multi_node_checkpointer(comm, str(tmp_path))
        # rewrite the shard claiming it came from a 4-process world
        from chainermn_tpu.utils.serialization import load_state, save_state
        p = str(tmp_path / "snapshot_iter_7.0")
        state = load_state(p)
        state["world_size"] = np.int64(4)
        save_state(p, state)
        with pytest.raises(RuntimeError, match="world size"):
            loader.maybe_load(FakeUpdater())

    def test_async_save_resume_roundtrip(self, comm, tmp_path):
        cp = create_multi_node_checkpointer(
            comm, str(tmp_path), async_write=True)
        up = FakeUpdater(seed=1)
        up.iteration = 42
        cp.save(up)
        # mutate AFTER save returns: the async copy must have snapshotted
        up.params = {"w": up.params["w"] * 0, "b": up.params["b"]}
        cp.finalize()

        fresh = FakeUpdater(seed=2)
        resumed = create_multi_node_checkpointer(
            comm, str(tmp_path)).maybe_load(fresh)
        assert resumed == 42
        np.testing.assert_array_equal(
            fresh.params["w"], FakeUpdater(seed=1).params["w"])

    def test_async_snapshot_isolated_from_inplace_mutation(
            self, comm, tmp_path):
        """Host-numpy state mutated IN PLACE right after save() must not
        leak into the written snapshot (device_get aliases numpy leaves;
        the async path must copy)."""
        cp = create_multi_node_checkpointer(
            comm, str(tmp_path), async_write=True)
        up = FakeUpdater()
        up.params = {"w": np.full(3, 1.0)}   # host numpy: aliasing risk
        up.opt_state = {"m": np.zeros(3)}
        up.iteration = 8
        cp.save(up)
        up.params["w"] *= 999.0              # in-place, post-save
        cp.finalize()
        fresh = FakeUpdater()
        assert create_multi_node_checkpointer(
            comm, str(tmp_path)).maybe_load(fresh) == 8
        np.testing.assert_allclose(fresh.params["w"], 1.0)

    def test_async_gc_on_next_save(self, comm, tmp_path):
        """Joining at save N+1 agrees set N complete and reaps older."""
        cp = create_multi_node_checkpointer(
            comm, str(tmp_path), async_write=True)
        up = FakeUpdater()
        for it in (10, 20, 30):
            up.iteration = it
            cp.save(up)
        cp.finalize()
        files = sorted(os.listdir(tmp_path))
        assert files == ["snapshot_iter_30.0"], files

    def test_async_resume_joins_pending(self, comm, tmp_path):
        """maybe_load right after an async save must see that save."""
        cp = create_multi_node_checkpointer(
            comm, str(tmp_path), async_write=True)
        up = FakeUpdater(seed=4)
        up.iteration = 7
        cp.save(up)
        fresh = FakeUpdater(seed=5)
        assert cp.maybe_load(fresh) == 7
        np.testing.assert_array_equal(fresh.params["w"], up.params["w"])

    def test_async_write_error_surfaces(self, comm, tmp_path):
        # a regular FILE where the snapshot directory should be makes the
        # writer thread's makedirs fail (permission tricks don't work for
        # root); the error must surface at the next join
        blocked = tmp_path / "blocked"
        blocked.write_text("not a directory")
        cp = create_multi_node_checkpointer(
            comm, str(blocked), async_write=True)
        up = FakeUpdater()
        up.iteration = 1
        cp.save(up)
        with pytest.raises(RuntimeError, match="async checkpoint"):
            cp.finalize()

    def test_trainer_extension_protocol(self, comm, tmp_path):
        cp = create_multi_node_checkpointer(comm, str(tmp_path))
        up = FakeUpdater()
        up.iteration = 3
        cp(FakeTrainer(up, tmp_path))  # __call__(trainer)
        assert os.path.exists(tmp_path / "snapshot_iter_3.0")


class TestMultiNodeSnapshot:
    def test_write_and_load(self, comm, tmp_path):
        snap = multi_node_snapshot(comm)
        up = FakeUpdater(seed=4)
        up.iteration = 17
        snap(FakeTrainer(up, tmp_path))
        path = os.path.join(str(tmp_path), "snapshot_iter_17")
        assert os.path.exists(path)

        fresh = FakeUpdater(seed=5)
        assert load_snapshot(fresh, path) == 17
        np.testing.assert_array_equal(fresh.params["w"], up.params["w"])


class _TwoProcComm:
    """Host-side fake of a 2-process world for the object-path extensions
    (the real multi-host path needs >1 JAX processes, out of scope for unit
    tests — the reference similarly skipped size<2)."""

    inter_size = 2
    inter_rank = 0

    def __init__(self, peer_obs):
        self._peer = peer_obs

    def allreduce_obj(self, obj, op="sum"):
        assert op == "sum"
        import jax
        return jax.tree.map(lambda a, b: a + b, obj, self._peer)

    def allgather_obj(self, obj):
        return [obj, self._peer]


class TestObservationAggregator:
    def test_single_process_noop(self, comm):
        agg = ObservationAggregator(comm)
        tr = FakeTrainer(FakeUpdater(), "out")
        tr.observation = {"main/loss": 2.0}
        agg.observe(tr)
        assert tr.observation == {"main/loss": 2.0}

    def test_two_process_mean(self):
        agg = ObservationAggregator(_TwoProcComm({"main/loss": 4.0}))
        tr = FakeTrainer(FakeUpdater(), "out")
        tr.observation = {"main/loss": 2.0, "note": "text"}
        agg.observe(tr)
        assert tr.observation["main/loss"] == pytest.approx(3.0)
        assert tr.observation["note"] == "text"

    def test_divergent_keys_averaged_over_reporters(self):
        """A key reported by only some processes (rank-0-only extensions)
        must be averaged over the reporting ranks, not crash or be diluted
        by non-reporters."""
        agg = ObservationAggregator(
            _TwoProcComm({"main/loss": 4.0, "peer_only": 10.0}))
        tr = FakeTrainer(FakeUpdater(), "out")
        tr.observation = {"main/loss": 2.0, "local_only": 6.0}
        agg.observe(tr)
        assert tr.observation["main/loss"] == pytest.approx(3.0)
        assert tr.observation["local_only"] == pytest.approx(6.0)
        assert tr.observation["peer_only"] == pytest.approx(10.0)


class TestAllreducePersistent:
    def test_two_process_mean(self):
        peer = {"bn": {"mean": np.full((3,), 4.0, np.float32)}}
        comm = _TwoProcComm(peer)
        ext = AllreducePersistentValues(comm)
        up = FakeUpdater()
        up.params = {"w": up.params["w"],
                     "persistent": {"bn": {"mean": np.full((3,), 2.0,
                                                          np.float32)}}}
        ext.allreduce_persistent(up)
        np.testing.assert_allclose(
            up.params["persistent"]["bn"]["mean"], np.full((3,), 3.0))

    def test_no_persistent_is_noop(self, comm):
        ext = AllreducePersistentValues(comm)
        up = FakeUpdater()
        before = up.params
        ext.allreduce_persistent(up)
        assert up.params is before


class TestGlobalExceptHook:
    def test_install_idempotent(self):
        prev = sys.excepthook
        try:
            add_global_except_hook()
            first = sys.excepthook
            add_global_except_hook()
            assert sys.excepthook is first
            assert first is not prev
        finally:
            sys.excepthook = prev

    def test_single_process_delegates(self, capsys):
        calls = []
        prev = sys.excepthook
        try:
            sys.excepthook = lambda *a: calls.append(a)
            import chainermn_tpu.extensions.global_except_hook as geh
            geh._installed = False
            add_global_except_hook()
            try:
                raise RuntimeError("boom")
            except RuntimeError:
                sys.excepthook(*sys.exc_info())
            assert len(calls) == 1  # delegated to previous hook, no exit
            err = capsys.readouterr().err
            assert "Uncaught exception on process 0" in err
            assert "boom" in err
        finally:
            sys.excepthook = prev
            geh._installed = False


class TestStatefulCheckpoint:
    """Non-trainable model state (BN running stats) must survive both
    serialization paths — it lives on updater.state, not in params."""

    def _stateful_updater(self, seed):
        up = FakeUpdater(seed=seed)
        rng = np.random.RandomState(seed + 100)
        up.state = {"bn": {"mean": jnp.asarray(rng.randn(4), jnp.float32),
                           "var": jnp.ones((4,), jnp.float32)}}
        return up

    def test_checkpointer_roundtrips_model_state(self, comm, tmp_path):
        cp = create_multi_node_checkpointer(comm, str(tmp_path))
        up = self._stateful_updater(seed=1)
        up.iteration = 7
        cp.save(up)

        fresh = self._stateful_updater(seed=2)
        it = create_multi_node_checkpointer(
            comm, str(tmp_path)).maybe_load(fresh)
        assert it == 7
        np.testing.assert_array_equal(
            np.asarray(fresh.state["bn"]["mean"]),
            np.asarray(up.state["bn"]["mean"]))

    def test_snapshot_roundtrips_model_state(self, comm, tmp_path):
        from chainermn_tpu.extensions import multi_node_snapshot

        up = self._stateful_updater(seed=3)
        up.iteration = 4
        trainer = FakeTrainer(up, tmp_path)
        multi_node_snapshot(comm, "snap_{iteration}")(trainer)

        fresh = self._stateful_updater(seed=4)
        load_snapshot(fresh, os.path.join(str(tmp_path), "snap_4"))
        np.testing.assert_array_equal(
            np.asarray(fresh.state["bn"]["var"]),
            np.asarray(up.state["bn"]["var"]))
