"""Deterministic fault-injection drills (chainermn_tpu.testing.FaultPlan):

- SIGKILL at iteration N in a REAL subprocess, then resume — the
  continued run must be bitwise-identical to an uninterrupted one
  (params AND the per-epoch loss log);
- kill + corrupt-the-latest-shard composed: resume falls back to the
  previous verified set and STILL finishes bitwise-identical;
- SIGTERM mid-async-write rides the PreemptionCheckpointer (in-process);
- NaN injection drives FailOnNonNumber;
- delay-rank drives the watchdog.
"""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import chainermn_tpu as cmn
from chainermn_tpu.testing import FaultInjector, FaultPlan
from chainermn_tpu.utils import load_state

_WORKER = os.path.join(os.path.dirname(__file__), "_fault_worker.py")
_REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", ".."))


def _run_phase(phase, workdir, plan=None, expect_kill=False, timeout=240,
               mode="full"):
    env = dict(os.environ)
    for k in list(env):
        if k.startswith(("TPU_", "LIBTPU", "PJRT_", "JAX_", "XLA_")):
            env.pop(k)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    plan_json = (plan or FaultPlan()).to_json()
    proc = subprocess.run(
        [sys.executable, _WORKER, phase, str(workdir), plan_json, mode],
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd=_REPO_ROOT)
    if expect_kill:
        assert proc.returncode == -signal.SIGKILL, (
            f"expected SIGKILL death, got rc={proc.returncode}\n"
            f"{proc.stdout}\n{proc.stderr}")
    else:
        assert proc.returncode == 0, (
            f"phase {phase} failed rc={proc.returncode}\n"
            f"{proc.stdout}\n{proc.stderr}")
    return proc


def _final_state(workdir, name):
    st = load_state(os.path.join(str(workdir), name))
    return st


@pytest.mark.slow
class TestKillResumeBitwise:
    def test_kill_then_resume_matches_uninterrupted(self, tmp_path):
        ref_dir, kill_dir = tmp_path / "ref", tmp_path / "kill"
        ref_dir.mkdir(), kill_dir.mkdir()
        _run_phase("ref", ref_dir)
        # epoch = 4 iterations, checkpoints at 3,6,9,...; kill at 10 →
        # resume restores iteration 9, mid-epoch and mid-shuffle
        proc = _run_phase("train", kill_dir,
                          FaultPlan(kill_at_iteration=10),
                          expect_kill=True)
        assert "PHASE_OK" not in proc.stdout  # really died mid-run
        out = _run_phase("resume", kill_dir)
        assert "RESUMED_AT 9" in out.stdout
        ref = _final_state(ref_dir, "ref.npz")
        got = _final_state(kill_dir, "resumed.npz")
        assert int(got["iteration"]) == int(ref["iteration"]) == 24
        for k in ("w", "b"):
            np.testing.assert_array_equal(
                np.asarray(got["params"][k]), np.asarray(ref["params"][k]),
                err_msg=f"resumed {k} differs from uninterrupted run")
        np.testing.assert_array_equal(
            np.asarray(got["log_losses"]), np.asarray(ref["log_losses"]),
            err_msg="resumed loss log differs bitwise")

    def test_kill_plus_corrupt_latest_falls_back_and_matches(
            self, tmp_path):
        """The full corruption drill: kill at 10 (checkpoint 9 is the
        newest set), flip bytes in that newest shard, resume — fallback
        restores iteration 6 and the finished run is STILL bitwise-equal
        to the uninterrupted one."""
        from chainermn_tpu.testing import corrupt_file

        ref_dir, kill_dir = tmp_path / "ref", tmp_path / "kill"
        ref_dir.mkdir(), kill_dir.mkdir()
        _run_phase("ref", ref_dir)
        _run_phase("train", kill_dir, FaultPlan(kill_at_iteration=10),
                   expect_kill=True)
        newest = kill_dir / "ckpt" / "snapshot_iter_9.0"
        assert newest.exists()
        corrupt_file(str(newest), seed=4)
        out = _run_phase("resume", kill_dir)
        assert "RESUMED_AT 6" in out.stdout
        assert (kill_dir / "ckpt" / "snapshot_iter_9.0.corrupt").exists()
        ref = _final_state(ref_dir, "ref.npz")
        got = _final_state(kill_dir, "resumed.npz")
        for k in ("w", "b"):
            np.testing.assert_array_equal(
                np.asarray(got["params"][k]), np.asarray(ref["params"][k]))
        np.testing.assert_array_equal(
            np.asarray(got["log_losses"]), np.asarray(ref["log_losses"]))


@pytest.mark.slow
class TestKillMidShardOnlyAsyncSave:
    """The crash-during-shard-only-save drill (docs/RESILIENCE.md
    "Scale-free snapshots"): the background writer is stalled mid-SET
    (``save_stall_after_files``), a REAL SIGKILL lands while the
    covering set is partially on disk, and resume must treat the
    partial set as nonexistent — falling back to the previous complete
    set and finishing bitwise-identical to the uninterrupted run."""

    # sets at iterations 3/6/9 hold 8 parts each; files 0-16 (sets 3, 6
    # and the root part of set 9) land unstalled, every later part of
    # set 9 sleeps far longer than the two iterations the kill needs
    _PLAN = dict(kill_at_iteration=11, save_stall_after_files=17,
                 save_stall_seconds=120.0)

    @staticmethod
    def _set_parts(workdir, it):
        ckpt = workdir / "ckpt"
        return sorted(f for f in os.listdir(ckpt)
                      if f.startswith(f"snapshot_iter_{it}.s"))

    def test_partial_covering_set_falls_back_bitwise(self, tmp_path):
        ref_dir, kill_dir = tmp_path / "ref", tmp_path / "kill"
        ref_dir.mkdir(), kill_dir.mkdir()
        _run_phase("ref", ref_dir, mode="shard_async")
        proc = _run_phase("train", kill_dir, FaultPlan(**self._PLAN),
                          expect_kill=True, mode="shard_async")
        assert "PHASE_OK" not in proc.stdout
        # the kill landed MID-stream: set 9 is on disk but incomplete
        parts9 = self._set_parts(kill_dir, 9)
        assert 1 <= len(parts9) < 8, (
            f"expected a partial covering set, found {parts9}")
        assert len(self._set_parts(kill_dir, 6)) == 8
        out = _run_phase("resume", kill_dir, mode="shard_async")
        assert "RESUMED_AT 6" in out.stdout
        ref = _final_state(ref_dir, "ref.npz")
        got = _final_state(kill_dir, "resumed.npz")
        assert int(got["iteration"]) == int(ref["iteration"]) == 24
        for k in ("w", "b"):
            np.testing.assert_array_equal(
                np.asarray(got["params"][k]), np.asarray(ref["params"][k]),
                err_msg=f"resumed {k} differs from uninterrupted run")
        np.testing.assert_array_equal(
            np.asarray(got["log_losses"]), np.asarray(ref["log_losses"]),
            err_msg="resumed loss log differs bitwise")

    def test_composes_with_corrupt_newest_complete_set(self, tmp_path):
        """The PR 3 composition: partial set 9 AND a corrupt part in
        complete set 6 — resume quarantines the damaged part, votes set
        6 down, and restores set 3, still bitwise."""
        from chainermn_tpu.testing import corrupt_file

        ref_dir, kill_dir = tmp_path / "ref", tmp_path / "kill"
        ref_dir.mkdir(), kill_dir.mkdir()
        _run_phase("ref", ref_dir, mode="shard_async")
        _run_phase("train", kill_dir, FaultPlan(**self._PLAN),
                   expect_kill=True, mode="shard_async")
        victim = self._set_parts(kill_dir, 6)[3]
        corrupt_file(str(kill_dir / "ckpt" / victim), seed=6)
        out = _run_phase("resume", kill_dir, mode="shard_async")
        assert "RESUMED_AT 3" in out.stdout
        assert (kill_dir / "ckpt" / f"{victim}.corrupt").exists()
        ref = _final_state(ref_dir, "ref.npz")
        got = _final_state(kill_dir, "resumed.npz")
        for k in ("w", "b"):
            np.testing.assert_array_equal(
                np.asarray(got["params"][k]), np.asarray(ref["params"][k]))
        np.testing.assert_array_equal(
            np.asarray(got["log_losses"]), np.asarray(ref["log_losses"]))


class TestInProcessFaults:
    def _make_trainer(self, comm, out, epochs=50):
        import jax
        import optax

        from chainermn_tpu.models import (init_mlp, mlp_apply,
                                          softmax_cross_entropy)

        rng = np.random.RandomState(0)
        data = [(rng.randn(6).astype(np.float32), np.int32(i % 3))
                for i in range(64)]
        it = cmn.SerialIterator(data, 16, shuffle=True, seed=3)
        params = init_mlp(jax.random.PRNGKey(0), [6, 12, 3])
        opt = cmn.create_multi_node_optimizer(optax.sgd(0.05), comm)

        def loss_fn(p, x, y):
            return softmax_cross_entropy(mlp_apply(p, x), y)

        upd = cmn.StandardUpdater(it, opt, loss_fn, params, comm)
        return cmn.Trainer(upd, (epochs, "epoch"), out=str(out))

    def test_sigterm_mid_async_write_checkpoints_cleanly(self, comm,
                                                         tmp_path):
        """FaultPlan.sigterm_at_iteration composes with the preemption
        path: the injector (lowest priority) fires AFTER the async
        checkpointer started its write, the trapped SIGTERM sets the
        preemption flag, and the job stops with a complete, loadable
        snapshot."""
        from chainermn_tpu.extensions import (
            PreemptionCheckpointer,
            create_multi_node_checkpointer,
        )

        trainer = self._make_trainer(comm, tmp_path)
        cp = create_multi_node_checkpointer(comm, str(tmp_path),
                                            async_write=True)
        pre = PreemptionCheckpointer(cp, comm, signals=(signal.SIGTERM,))
        trainer.extend(cp, trigger=(4, "iteration"))
        trainer.extend(pre)
        inj = FaultInjector(FaultPlan(sigterm_at_iteration=4), comm)
        trainer.extend(inj)
        trainer.run()
        assert ("sigterm", 4) in inj.fired
        assert "preemption" in trainer.stop_reason
        # the shard is complete and loadable NOW (writer joined)
        cp2 = create_multi_node_checkpointer(comm, str(tmp_path))
        t2 = self._make_trainer(comm, tmp_path)
        assert cp2.maybe_load(t2.updater, t2) in (4, 5)

    def test_nan_injection_trips_fail_on_non_number(self, comm, tmp_path):
        from chainermn_tpu.extensions import FailOnNonNumber

        trainer = self._make_trainer(comm, tmp_path)
        trainer.extend(FailOnNonNumber())
        trainer.extend(FaultInjector(FaultPlan(nan_at_iteration=3), comm))
        with pytest.raises(RuntimeError, match="non-finite"):
            trainer.run()
        assert trainer.updater.iteration == 4  # NaN surfaced next step

    def test_delay_rank_trips_watchdog(self, comm, tmp_path):
        """The watchdog drill: one injected stall past the threshold
        produces a stall report within one check interval."""
        from chainermn_tpu.extensions import TrainingWatchdog

        trainer = self._make_trainer(comm, tmp_path, epochs=3)
        reports = []
        wd = TrainingWatchdog(stall_timeout=0.3, check_interval=0.1,
                              comm=comm, on_stall=reports.append)
        trainer.extend(wd)
        inj = FaultInjector(
            FaultPlan(delay_at_iteration=5, delay_rank=0,
                      delay_seconds=0.8), comm)
        trainer.extend(inj)
        t0 = time.monotonic()
        trainer.run()
        assert ("delay", 5) in inj.fired
        assert wd.stall_count >= 1
        assert reports[0]["kind"] == "local-stall"
        assert reports[0]["iteration"] == 5
        # fired DURING the stall (within one interval of the threshold),
        # not after the run ended
        assert time.monotonic() - t0 > 0.8
