"""Interleaved 1F1B (virtual pipeline stages) vs sequential oracle.

``pipeline_train_interleaved`` runs ``V`` model chunks per device with
Megatron's interleaved schedule (table-driven, dependency-asserted at
trace time).  It must numerically match a plain sequential chain of all
``S·V`` stages + loss under autodiff, and reduce to the plain 1F1B
results at ``V=1``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from chainermn_tpu.communicators._mesh_utils import make_world_mesh
from chainermn_tpu.parallel import stack_stage_params
from chainermn_tpu.parallel.pipeline import (
    _interleaved_tables,
    pipeline_train_1f1b,
    pipeline_train_interleaved,
)

AX = "world"


@pytest.fixture(scope="module")
def mesh():
    return make_world_mesh(axis_name=AX)


def _stage_apply(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _loss_fn(lp, y, tgt):
    pred = y @ lp["head"]
    return jnp.mean((pred - tgt) ** 2)


def _make(n_stages, dim, seed=0):
    rng = np.random.RandomState(seed)
    stages = [
        {"w": jnp.asarray(rng.randn(dim, dim).astype(np.float32) * 0.3),
         "b": jnp.asarray(rng.randn(dim).astype(np.float32) * 0.1)}
        for _ in range(n_stages)
    ]
    lp = {"head": jnp.asarray(rng.randn(dim, 2).astype(np.float32) * 0.3)}
    return stages, lp


def _pack_interleaved(stages, S, V):
    """Virtual stage ``g = c·S + s`` -> device s, chunk c: pack the
    ``(S·V, ...)`` stack as ``(S, V, ...)``."""
    stacked = stack_stage_params(stages)
    return jax.tree.map(
        lambda a: a.reshape(V, S, *a.shape[1:]).swapaxes(0, 1), stacked)


def _ref(stages, lp, x, y):
    def loss(stages, lp, x):
        h = x
        for p in stages:
            h = _stage_apply(p, h)
        return _loss_fn(lp, h, y)

    l, grads = jax.value_and_grad(loss, argnums=(0, 1, 2))(
        stages, lp, jnp.asarray(x))
    return l, grads


def _run(mesh, packed, lp, x, y, M, V):
    return jax.jit(jax.shard_map(
        lambda p, lpp, xs, ys: pipeline_train_interleaved(
            _stage_apply, _loss_fn, p, lpp, xs, ys,
            axis_name=AX, num_microbatches=M, num_chunks=V),
        mesh=mesh,
        in_specs=(P(AX), P(), P(), P()),
        out_specs=(P(), P(AX), P(), P())))(packed, lp, x, y)


class TestInterleaved:
    @pytest.mark.parametrize("V,M", [(2, 8), (2, 16), (4, 8)])
    def test_matches_sequential_oracle(self, mesh, V, M):
        S = mesh.devices.size
        dim, B = 5, 32
        stages, lp = _make(S * V, dim, seed=1)
        rng = np.random.RandomState(2)
        x = rng.randn(B, dim).astype(np.float32)
        y = rng.randn(B, 2).astype(np.float32)

        loss, gp, glp, dx = _run(
            mesh, _pack_interleaved(stages, S, V), lp, x, y, M, V)

        ref_loss, (ref_gs, ref_glp, ref_dx) = _ref(stages, lp, x, y)
        np.testing.assert_allclose(float(loss), float(ref_loss),
                                   rtol=1e-5, atol=1e-6)
        # gp comes back (S, V, ...) world-stacked; oracle is per virtual
        # stage g = c*S + s
        ref_packed = _pack_interleaved(ref_gs, S, V)
        for a, b in zip(jax.tree.leaves(gp),
                        jax.tree.leaves(ref_packed)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(glp["head"]), np.asarray(ref_glp["head"]),
            rtol=1e-3, atol=1e-5)
        np.testing.assert_allclose(np.asarray(dx), np.asarray(ref_dx),
                                   rtol=1e-3, atol=1e-5)

    def test_v1_equals_plain_1f1b(self, mesh):
        S = mesh.devices.size
        dim, B, M = 4, 16, 8
        stages, lp = _make(S, dim, seed=7)
        rng = np.random.RandomState(8)
        x = rng.randn(B, dim).astype(np.float32)
        y = rng.randn(B, 2).astype(np.float32)

        loss1, gp1, glp1, dx1 = _run(
            mesh, _pack_interleaved(stages, S, 1), lp, x, y, M, 1)
        loss2, gp2, glp2, dx2 = jax.jit(jax.shard_map(
            lambda p, lpp, xs, ys: pipeline_train_1f1b(
                _stage_apply, _loss_fn, p, lpp, xs, ys,
                axis_name=AX, num_microbatches=M),
            mesh=mesh,
            in_specs=(P(AX), P(), P(), P()),
            out_specs=(P(), P(AX), P(), P())))(
                stack_stage_params(stages), lp, x, y)

        np.testing.assert_allclose(float(loss1), float(loss2),
                                   rtol=1e-6, atol=1e-7)
        for a, b in zip(jax.tree.leaves(gp1), jax.tree.leaves(gp2)):
            np.testing.assert_allclose(
                np.asarray(a).reshape(np.asarray(b).shape),
                np.asarray(b), rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(dx1), np.asarray(dx2),
                                   rtol=1e-5, atol=1e-6)

    def test_microbatch_divisibility_enforced(self, mesh):
        S = mesh.devices.size
        with pytest.raises(ValueError, match="divisible"):
            _interleaved_tables(S, 2, S + 1)

    def test_bubble_shrinks_with_chunks(self):
        """The schedule's tick count (per-chunk units) divided by V —
        the model-time cost — must shrink as V grows."""
        S, M = 4, 16
        costs = []
        for V in (1, 2, 4):
            T = _interleaved_tables(S, V, M)[0]
            costs.append(T / V)
        assert costs[0] > costs[1] > costs[2], costs
