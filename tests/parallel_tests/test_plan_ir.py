"""Collective-plan IR (``ops/plan_ir.py``): program data model, and the
per-pattern bitwise parity suite — every enumerated candidate of every
pattern must move EXACTLY the bytes the legacy hard-coded lowering
moved, on the 8-device CPU mesh, including empty/int/bool leaves and
single-device degenerate meshes.

Parity here is ``np.array_equal`` (bitwise), not allclose: native
candidates are pure data movement, and wire candidates are compared to
the LEGACY wire path (same cast, same exemptions), so any mismatch is a
lowering bug, not noise.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

import chainermn_tpu  # noqa: F401 - installs the shard_map compat shim
from chainermn_tpu.ops import plan_ir
from chainermn_tpu.parallel.expert import expert_parallel_moe
from chainermn_tpu.parallel.fsdp import fsdp_gather
from chainermn_tpu.parallel.pipeline import pipeline_apply
from chainermn_tpu.parallel.ring_attention import ring_attention
from chainermn_tpu.utils.programs import (
    ProgramLedger,
    ledger_jit,
    set_ledger,
)

AX = "world"


def flat_mesh():
    return Mesh(np.array(jax.devices()), (AX,))


def run_spmd(fn, tree, mesh=None, spec=None):
    """Run ``fn`` on per-device copies of ``tree`` (world-stacked
    leading axis) and return the (identical) per-device outputs."""
    mesh = mesh if mesh is not None else flat_mesh()
    n = int(np.prod([s for s in np.asarray(mesh.devices).shape]))
    spec = spec if spec is not None else P(AX)

    def body(g):
        local = jax.tree.map(lambda a: a[0], g)
        out = fn(local)
        return jax.tree.map(lambda a: a[None], out)

    stacked = jax.tree.map(lambda a: jnp.stack([a] * n), tree)
    return jax.shard_map(body, mesh=mesh, in_specs=spec,
                         out_specs=spec)(stacked)


def assert_bitwise(got, want, label=""):
    gl, wl = jax.tree.leaves(got), jax.tree.leaves(want)
    assert len(gl) == len(wl)
    for g, w in zip(gl, wl):
        g, w = np.asarray(g), np.asarray(w)
        assert g.shape == w.shape and g.dtype == w.dtype, \
            (label, g.shape, w.shape, g.dtype, w.dtype)
        assert np.array_equal(g, w), label


# --------------------------------------------------------------------- #
# program data model
# --------------------------------------------------------------------- #


class TestProgramData:
    def test_step_and_program_roundtrip(self):
        prog = plan_ir.PlanProgram(
            "fsdp_gather", "fused/flat/bfloat16",
            (plan_ir.step("cast_wire", dtype="bfloat16"),
             plan_ir.step("fuse"),
             plan_ir.step("all_gather", axis="main")))
        d = prog.to_dict()
        back = plan_ir.PlanProgram.from_dict(d)
        assert back == prog
        assert back.to_dict() == d
        assert prog.wire_dtype == "bfloat16"

    def test_step_validates_op(self):
        with pytest.raises(ValueError, match="unknown plan primitive"):
            plan_ir.step("bogus_op")

    def test_ensure_program_accepts_dict_and_plan_like(self):
        prog = plan_ir.enumerate_pattern_programs("ring_permute")[0]
        assert plan_ir.ensure_program(prog.to_dict()) == prog

        class PlanLike:
            program = prog.to_dict()

        assert plan_ir.ensure_program(PlanLike(), "ring_permute") == prog
        with pytest.raises(ValueError, match="pattern"):
            plan_ir.ensure_program(prog, "fsdp_gather")

    def test_describe_payload_skips_none_dims(self):
        tree = {"w": jnp.zeros((4, 8)), "s": jnp.zeros((3,))}
        descs = plan_ir.describe_payload(tree, {"w": 1, "s": None})
        by_shape = {d.shape: d for d in descs}
        assert by_shape[(4, 8)].layout == 1
        assert by_shape[(3,)].layout is None

    def test_baseline_first_contract(self):
        """The FIRST enumerated program of every pattern is the
        legacy-equivalent native baseline — the parity reference and
        the autotuner's always-probed candidate."""
        firsts = {
            "fsdp_gather": "per_leaf/flat/native",
            "moe_all_to_all": "single/native",
            "ring_permute": "separate/native",
            "pipeline_edge": "direct/native",
        }
        kw = {"moe_all_to_all": {"shape": (8, 8, 4)}}
        for pattern, label in firsts.items():
            progs = plan_ir.enumerate_pattern_programs(
                pattern, **kw.get(pattern, {}))
            assert progs[0].label == label
            assert progs[0].wire_dtype is None


# --------------------------------------------------------------------- #
# fsdp gather
# --------------------------------------------------------------------- #


def _fsdp_payload():
    key = jax.random.PRNGKey(0)
    params = {
        "w": jax.random.normal(key, (8, 4, 6), jnp.float32),
        "b": jnp.arange(16, dtype=jnp.int32).reshape(8, 2),
        "flag": jnp.array([True, False] * 4).reshape(8, 1),
        "empty": jnp.zeros((8, 0, 3), jnp.float32),
        "scale": jnp.ones((3,), jnp.float32),   # unsharded passthrough
    }
    dims = {"w": 0, "b": 0, "flag": 0, "empty": 1, "scale": None}
    return params, dims


class TestFsdpGatherParity:
    def test_flat_programs_bitwise(self):
        params, dims = _fsdp_payload()
        want = run_spmd(
            lambda p: fsdp_gather(p, dims, axis_name=AX), params)
        want_wire = run_spmd(
            lambda p: fsdp_gather(p, dims, axis_name=AX,
                                  wire_dtype=jnp.bfloat16), params)
        progs = plan_ir.enumerate_pattern_programs(
            "fsdp_gather", wire_dtypes=(None, "bfloat16"))
        assert len(progs) == 4
        for prog in progs:
            got = run_spmd(
                lambda p, prog=prog: fsdp_gather(
                    p, dims, axis_name=AX, plan=prog), params)
            ref = want if prog.wire_dtype is None else want_wire
            assert_bitwise(got, ref, prog.label)

    def test_wire_exempts_non_float_leaves(self):
        """The satellite hazard: int/bool through a bf16 wire is silent
        corruption.  Both the legacy path and every IR wire candidate
        must ship non-float leaves at their native dtype — bitwise
        equal to the no-wire gather."""
        params, dims = _fsdp_payload()
        want = run_spmd(
            lambda p: fsdp_gather(p, dims, axis_name=AX), params)
        got = run_spmd(
            lambda p: fsdp_gather(p, dims, axis_name=AX,
                                  wire_dtype=jnp.bfloat16), params)
        for k in ("b", "flag"):
            assert_bitwise(got[k], want[k], f"legacy wire {k}")
        prog = [p for p in plan_ir.enumerate_pattern_programs(
            "fsdp_gather", wire_dtypes=("bfloat16",))
            if p.label == "fused/flat/bfloat16"][0]
        got_ir = run_spmd(
            lambda p: fsdp_gather(p, dims, axis_name=AX, plan=prog),
            params)
        for k in ("b", "flag"):
            assert_bitwise(got_ir[k], want[k], f"ir wire {k}")

    def test_hierarchical_bitwise_vs_axis_tuple(self):
        """Two-stage intra→inter gather equals the flat gather over the
        combined axis tuple (row-major device order) — bitwise."""
        devs = np.array(jax.devices()).reshape(2, 4)
        hmesh = Mesh(devs, ("inter", AX))
        spec = P(("inter", AX))
        key = jax.random.PRNGKey(1)
        params = {"w": jax.random.normal(key, (8, 16, 6), jnp.float32),
                  "b": jnp.arange(16, dtype=jnp.int32)}
        dims = {"w": 1, "b": 0}
        want = run_spmd(
            lambda p: fsdp_gather(p, dims, axis_name=("inter", AX)),
            params, mesh=hmesh, spec=spec)
        progs = [p for p in plan_ir.enumerate_pattern_programs(
            "fsdp_gather", allow_hierarchical=True)
            if "hier" in p.label]
        assert len(progs) == 2
        for prog in progs:
            got = run_spmd(
                lambda p, prog=prog: fsdp_gather(
                    p, dims, axis_name=AX, plan=prog,
                    inter_axis_name="inter"),
                params, mesh=hmesh, spec=spec)
            assert_bitwise(got, want, prog.label)

    def test_single_device_mesh(self):
        mesh = Mesh(np.array(jax.devices()[:1]), (AX,))
        params, dims = _fsdp_payload()
        want = run_spmd(
            lambda p: fsdp_gather(p, dims, axis_name=AX),
            params, mesh=mesh)
        for prog in plan_ir.enumerate_pattern_programs("fsdp_gather"):
            got = run_spmd(
                lambda p, prog=prog: fsdp_gather(
                    p, dims, axis_name=AX, plan=prog),
                params, mesh=mesh)
            assert_bitwise(got, want, prog.label)

    def test_unbound_inter_axis_raises(self):
        params, dims = _fsdp_payload()
        prog = [p for p in plan_ir.enumerate_pattern_programs(
            "fsdp_gather", allow_hierarchical=True)
            if "hier" in p.label][0]
        with pytest.raises(ValueError, match="bound no such axis"):
            run_spmd(
                lambda p: fsdp_gather(p, dims, axis_name=AX, plan=prog),
                params)


# --------------------------------------------------------------------- #
# moe all-to-all
# --------------------------------------------------------------------- #


class TestMoeAllToAllParity:
    def test_programs_bitwise_both_directions(self):
        key = jax.random.PRNGKey(2)
        slots = jax.random.normal(key, (8, 4, 16), jnp.float32)

        def legacy(x):
            h = lax.all_to_all(x, AX, split_axis=0, concat_axis=1,
                               tiled=True)
            return lax.all_to_all(h * 2.0, AX, split_axis=1,
                                  concat_axis=0, tiled=True)

        want = run_spmd(legacy, slots)
        progs = plan_ir.enumerate_pattern_programs(
            "moe_all_to_all", shape=(8, 4, 16))
        assert [p.label for p in progs] == \
            ["single/native", "split2/native", "split4/native",
             "split8/native"]
        for prog in progs:
            def ir(x, prog=prog):
                h = plan_ir.lower_moe_all_to_all(
                    prog, x, axis_name=AX, split_axis=0, concat_axis=1)
                return plan_ir.lower_moe_all_to_all(
                    prog, h * 2.0, axis_name=AX, split_axis=1,
                    concat_axis=0)

            assert_bitwise(run_spmd(ir, slots), want, prog.label)

    def test_int_payload_rides_wire_natively(self):
        slots = jnp.arange(8 * 2 * 8, dtype=jnp.int32).reshape(8, 2, 8)
        want = run_spmd(
            lambda x: lax.all_to_all(x, AX, split_axis=0, concat_axis=1,
                                     tiled=True), slots)
        progs = plan_ir.enumerate_pattern_programs(
            "moe_all_to_all", shape=(8, 2, 8),
            wire_dtypes=("bfloat16",))
        for prog in progs:
            got = run_spmd(
                lambda x, prog=prog: plan_ir.lower_moe_all_to_all(
                    prog, x, axis_name=AX, split_axis=0, concat_axis=1),
                slots)
            assert_bitwise(got, want, prog.label)

    def test_expert_moe_end_to_end(self):
        """The ported call site: ``expert_parallel_moe(a2a_plan=...)``
        is bitwise identical to the legacy lowering."""
        key = jax.random.PRNGKey(3)
        k1, k2, k3 = jax.random.split(key, 3)
        D, E, N = 8, 8, 16
        x = jax.random.normal(k1, (N, D), jnp.float32)
        router_w = jax.random.normal(k2, (D, E), jnp.float32)
        expert_params = {"w": jax.random.normal(k3, (1, D, D),
                                                jnp.float32)}

        def expert_fn(p, tokens):
            return tokens @ p["w"]

        def moe(plan):
            def f(tree):
                out, aux = expert_parallel_moe(
                    tree["x"], tree["r"], tree["ep"], expert_fn,
                    axis_name=AX, a2a_plan=plan)
                return {"out": out, "aux": aux}
            return f

        tree = {"x": x, "r": router_w, "ep": expert_params}
        want = run_spmd(moe(None), tree)
        for prog in plan_ir.enumerate_pattern_programs(
                "moe_all_to_all", shape=(E, 3, D)):
            # capacity = ceil(1.25 * 16 / 8) = 3 slots
            got = run_spmd(moe(prog), tree)
            assert_bitwise(got, want, prog.label)


# --------------------------------------------------------------------- #
# ring permute
# --------------------------------------------------------------------- #


class TestRingPermuteParity:
    def test_programs_bitwise(self):
        key = jax.random.PRNGKey(4)
        kv = {"k": jax.random.normal(key, (2, 5), jnp.float32),
              "v": jnp.arange(6, dtype=jnp.int32).reshape(2, 3)}
        ring = [(i, (i + 1) % 8) for i in range(8)]
        want = run_spmd(
            lambda t: jax.tree.map(
                lambda x: lax.ppermute(x, AX, perm=ring), t), kv)
        for prog in plan_ir.enumerate_pattern_programs("ring_permute"):
            def ir(t, prog=prog):
                k, v = plan_ir.lower_ring_permute(
                    prog, (t["k"], t["v"]), axis_name=AX)
                return {"k": k, "v": v}

            assert_bitwise(run_spmd(ir, kv), want, prog.label)

    def test_ring_attention_end_to_end(self):
        key = jax.random.PRNGKey(5)
        kq, kk, kv_ = jax.random.split(key, 3)
        q = jax.random.normal(kq, (1, 4, 2, 8), jnp.float32)
        k = jax.random.normal(kk, (1, 4, 2, 8), jnp.float32)
        v = jax.random.normal(kv_, (1, 4, 2, 8), jnp.float32)
        tree = {"q": q, "k": k, "v": v}

        def attn(plan):
            return lambda t: ring_attention(
                t["q"], t["k"], t["v"], axis_name=AX, causal=True,
                permute_plan=plan)

        want = run_spmd(attn(None), tree)
        for prog in plan_ir.enumerate_pattern_programs("ring_permute"):
            assert_bitwise(run_spmd(attn(prog), tree), want, prog.label)


# --------------------------------------------------------------------- #
# pipeline edges
# --------------------------------------------------------------------- #


class TestPipelineEdgeParity:
    @pytest.mark.parametrize("shift,wrap", [(1, False), (-1, False),
                                            (1, True), (-1, True)])
    def test_programs_bitwise(self, shift, wrap):
        act = jax.random.normal(jax.random.PRNGKey(6), (3, 4),
                                jnp.float32)
        if shift == 1:
            perm = [(i, i + 1) for i in range(7)]
            perm += [(7, 0)] if wrap else []
        else:
            perm = [(i + 1, i) for i in range(7)]
            perm += [(0, 7)] if wrap else []
        want = run_spmd(lambda x: lax.ppermute(x, AX, perm=perm), act)
        for prog in plan_ir.enumerate_pattern_programs("pipeline_edge"):
            got = run_spmd(
                lambda x, prog=prog: plan_ir.lower_pipeline_edge(
                    prog, x, axis_name=AX, shift=shift, wrap=wrap), act)
            assert_bitwise(got, want, (prog.label, shift, wrap))

    def test_pipeline_apply_end_to_end(self):
        rng = np.random.RandomState(7)
        dim, B = 4, 16
        stacked = {
            "w": jnp.asarray(rng.randn(8, dim, dim).astype(np.float32)
                             * 0.3),
            "b": jnp.asarray(rng.randn(8, dim).astype(np.float32)
                             * 0.1),
        }
        x = jnp.asarray(rng.randn(B, dim).astype(np.float32))

        def stage(p, h):
            return jnp.tanh(h @ p["w"] + p["b"])

        def run_case(plan):
            mesh = flat_mesh()
            return jax.shard_map(
                lambda p, xs: pipeline_apply(
                    stage, p, xs, axis_name=AX, num_microbatches=8,
                    edge_plan=plan),
                mesh=mesh, in_specs=(P(AX), P()),
                out_specs=P())(stacked, x)

        want = run_case(None)
        for prog in plan_ir.enumerate_pattern_programs("pipeline_edge"):
            assert_bitwise(run_case(prog), want, prog.label)


# --------------------------------------------------------------------- #
# ledger invariant
# --------------------------------------------------------------------- #


class TestLedgerInvariant:
    def test_ir_lowered_program_zero_steady_retraces(self):
        """The PR 15 invariant extends to IR-lowered programs: a
        ledger-labelled jit wrapping a plan lowering compiles once and
        never retraces at steady state."""
        led = ProgramLedger(enabled=True)
        prev = set_ledger(led)
        try:
            mesh = flat_mesh()
            params, dims = _fsdp_payload()
            prog = plan_ir.enumerate_pattern_programs("fsdp_gather")[1]
            stacked = jax.tree.map(lambda a: jnp.stack([a] * 8), params)

            def body(g):
                local = jax.tree.map(lambda a: a[0], g)
                out = fsdp_gather(local, dims, axis_name=AX, plan=prog)
                return jax.tree.map(lambda a: a[None], out)

            fn = ledger_jit(
                jax.shard_map(body, mesh=mesh, in_specs=P(AX),
                              out_specs=P(AX)),
                label="plan_ir/fsdp_gather")
            for _ in range(3):
                jax.block_until_ready(fn(stacked))
            assert led.compiles("plan_ir/") == 1
            assert led.steady_retraces("plan_ir/") == 0
        finally:
            set_ledger(prev)
