"""Parallel-strategy tests on the virtual 8-device CPU mesh.

Every strategy is verified against a single-device oracle: pipeline vs
sequential stage application (fwd + grads), ring/Ulysses attention vs
dense softmax attention (fwd + grads, causal and not), TP dense pair vs
plain matmul, MoE vs per-token dense expert application.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from chainermn_tpu.communicators._mesh_utils import make_named_mesh, make_world_mesh
from chainermn_tpu.parallel import (
    MeshConfig,
    column_parallel_dense,
    expert_parallel_moe,
    pipeline_apply,
    ring_attention,
    row_parallel_dense,
    stack_stage_params,
)
from chainermn_tpu.parallel.ring_attention import local_attention
from chainermn_tpu.parallel.ulysses import ulysses_attention

from chainermn_tpu.testing import requires_vma as _requires_vma

# Pre-vma shard_map (old check_rep) cannot express what these tests pin:
# grads of replicated outputs taken inside shard_map over-count by the
# axis size, replicated out_specs can't be inferred through gathers, and
# scan carries may not gain replication.  vma typing (jax >= 0.7) is the
# semantic fix; on older jax the cases below are undefined, not wrong.
requires_vma = _requires_vma("requires vma-typed shard_map AD semantics")

AX = "world"


@pytest.fixture(scope="module")
def mesh():
    return make_world_mesh(axis_name=AX)


def smap(mesh, fn, in_specs, out_specs):
    return jax.jit(
        jax.shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs))


class TestMeshConfig:
    def test_build_and_absorb(self):
        cfg = MeshConfig(data=-1, model=2, pipe=2)
        assert cfg.data == 2
        assert cfg.mesh.shape == {
            "pipe": 2, "data": 2, "expert": 1, "seq": 1, "model": 2}

    def test_all_axes_exist_at_size_one(self):
        cfg = MeshConfig(data=8)
        assert tuple(cfg.mesh.axis_names) == (
            "pipe", "data", "expert", "seq", "model")

    def test_bad_sizes_raise(self):
        with pytest.raises(ValueError):
            MeshConfig(data=3, model=3)
        with pytest.raises(ValueError):
            MeshConfig(data=-1, model=-1)


class TestTensorParallel:
    def test_column_row_pair_matches_dense(self, mesh):
        """Megatron MLP block: X·W1 → gelu → ·W2 with ONE psum."""
        n = 8
        rng = np.random.RandomState(0)
        x = rng.randn(4, 16).astype(np.float32)
        w1 = rng.randn(16, 32).astype(np.float32) * 0.1
        b1 = rng.randn(32).astype(np.float32) * 0.1
        w2 = rng.randn(32, 16).astype(np.float32) * 0.1
        b2 = rng.randn(16).astype(np.float32) * 0.1

        def tp_block(x, w1, b1, w2, b2):
            h = jax.nn.gelu(
                column_parallel_dense(x, w1, b1, axis_name=AX))
            return row_parallel_dense(h, w2, b2, axis_name=AX)

        # w1 column-sharded, b1 sharded, w2 row-sharded, b2 replicated
        out = smap(mesh, tp_block,
                   in_specs=(P(), P(None, AX), P(AX), P(AX, None), P()),
                   out_specs=P())(x, w1, b1, w2, b2)
        ref = jax.nn.gelu(x @ w1 + b1) @ w2 + b2
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)
        assert n == mesh.devices.size

    @requires_vma
    def test_tp_gradients_match(self, mesh):
        rng = np.random.RandomState(1)
        x = rng.randn(4, 8).astype(np.float32)
        w1 = rng.randn(8, 16).astype(np.float32) * 0.1
        w2 = rng.randn(16, 8).astype(np.float32) * 0.1

        def tp_loss(x, w1, w2):
            h = jax.nn.gelu(column_parallel_dense(x, w1, axis_name=AX))
            y = row_parallel_dense(h, w2, axis_name=AX)
            return jnp.sum(y**2)

        g1, g2 = smap(mesh, jax.grad(tp_loss, argnums=(1, 2)),
                      in_specs=(P(), P(None, AX), P(AX, None)),
                      out_specs=(P(None, AX), P(AX, None)))(x, w1, w2)

        def ref_loss(x, w1, w2):
            return jnp.sum((jax.nn.gelu(x @ w1) @ w2) ** 2)

        r1, r2 = jax.grad(ref_loss, argnums=(1, 2))(
            jnp.asarray(x), jnp.asarray(w1), jnp.asarray(w2))
        np.testing.assert_allclose(np.asarray(g1), np.asarray(r1),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(g2), np.asarray(r2),
                                   rtol=1e-4, atol=1e-5)


def _stage_apply(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _make_stages(n_stage, dim, seed=0):
    rng = np.random.RandomState(seed)
    return [
        {"w": jnp.asarray(rng.randn(dim, dim).astype(np.float32) * 0.3),
         "b": jnp.asarray(rng.randn(dim).astype(np.float32) * 0.1)}
        for _ in range(n_stage)
    ]


class TestPipeline:
    @pytest.mark.parametrize("microbatches", [8, 16])
    def test_forward_matches_sequential(self, mesh, microbatches):
        S = mesh.devices.size
        dim, B = 6, 32
        stages = _make_stages(S, dim)
        stacked = stack_stage_params(stages)
        x = np.random.RandomState(2).randn(B, dim).astype(np.float32)

        out = smap(
            mesh,
            lambda p, xs: pipeline_apply(
                _stage_apply, p, xs, axis_name=AX,
                num_microbatches=microbatches),
            in_specs=(P(AX), P()), out_specs=P())(stacked, x)

        ref = jnp.asarray(x)
        for p in stages:
            ref = _stage_apply(p, ref)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)

    def test_gradients_match_sequential(self, mesh):
        S = mesh.devices.size
        dim, B, M = 5, 16, 8
        stages = _make_stages(S, dim, seed=3)
        stacked = stack_stage_params(stages)
        x = np.random.RandomState(4).randn(B, dim).astype(np.float32)

        def dist_loss(p, xs):
            y = pipeline_apply(_stage_apply, p, xs, axis_name=AX,
                               num_microbatches=M)
            return jnp.sum(y**2)

        g = smap(mesh, jax.grad(dist_loss),
                 in_specs=(P(AX), P()), out_specs=P(AX))(stacked, x)

        def ref_loss(ps, xs):
            h = xs
            for p in ps:
                h = _stage_apply(p, h)
            return jnp.sum(h**2)

        g_ref = stack_stage_params(
            jax.grad(ref_loss)(stages, jnp.asarray(x)))
        for a, b in zip(jax.tree.leaves(g), jax.tree.leaves(g_ref)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-4)

    def test_single_stage_degenerate(self):
        """S=1 pipe axis: schedule reduces to plain micro-batched apply."""
        mesh1 = make_named_mesh({"one": 1}, devices=jax.devices()[:1])
        stages = _make_stages(1, 4, seed=5)
        stacked = stack_stage_params(stages)
        x = np.random.RandomState(6).randn(8, 4).astype(np.float32)
        out = jax.jit(jax.shard_map(
            lambda p, xs: pipeline_apply(
                _stage_apply, p, xs, axis_name="one", num_microbatches=4),
            mesh=mesh1, in_specs=(P("one"), P()), out_specs=P()))(stacked, x)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(_stage_apply(stages[0], x)),
            rtol=1e-5, atol=1e-6)

    def test_batch_not_divisible_raises(self, mesh):
        stacked = stack_stage_params(_make_stages(mesh.devices.size, 4))
        with pytest.raises(ValueError, match="not divisible"):
            smap(mesh,
                 lambda p, xs: pipeline_apply(
                     _stage_apply, p, xs, axis_name=AX, num_microbatches=7),
                 in_specs=(P(AX), P()), out_specs=P())(
                     stacked, np.zeros((16, 4), np.float32))


def _qkv(shape, seed):
    rng = np.random.RandomState(seed)
    return tuple(rng.randn(*shape).astype(np.float32) * 0.5
                 for _ in range(3))


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense_attention(self, mesh, causal):
        B, T, H, D = 2, 32, 4, 8
        q, k, v = _qkv((B, T, H, D), seed=7)

        out = smap(
            mesh,
            lambda a, b, c: ring_attention(a, b, c, axis_name=AX,
                                           causal=causal),
            in_specs=(P(None, AX), P(None, AX), P(None, AX)),
            out_specs=P(None, AX))(q, k, v)
        ref = local_attention(jnp.asarray(q), jnp.asarray(k),
                              jnp.asarray(v), causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    @requires_vma
    def test_gradients_match(self, mesh, causal):
        B, T, H, D = 1, 16, 2, 4
        q, k, v = _qkv((B, T, H, D), seed=8)

        def dist_loss(a, b, c):
            o = ring_attention(a, b, c, axis_name=AX, causal=causal)
            return jax.lax.psum(jnp.sum(o**2), AX)

        g = smap(mesh, jax.grad(dist_loss, argnums=(0, 1, 2)),
                 in_specs=(P(None, AX),) * 3,
                 out_specs=(P(None, AX),) * 3)(q, k, v)

        def ref_loss(a, b, c):
            return jnp.sum(local_attention(a, b, c, causal=causal) ** 2)

        g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        for a, b in zip(g, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-4)


class TestUlyssesAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense_attention(self, mesh, causal):
        B, T, H, D = 2, 32, 8, 4  # H divisible by 8 devices
        q, k, v = _qkv((B, T, H, D), seed=9)

        out = smap(
            mesh,
            lambda a, b, c: ulysses_attention(a, b, c, axis_name=AX,
                                              causal=causal),
            in_specs=(P(None, AX),) * 3,
            out_specs=P(None, AX))(q, k, v)
        ref = local_attention(jnp.asarray(q), jnp.asarray(k),
                              jnp.asarray(v), causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_head_divisibility_checked(self, mesh):
        q, k, v = _qkv((1, 16, 6, 4), seed=10)  # 6 heads, 8 devices
        with pytest.raises(ValueError, match="not divisible"):
            smap(mesh,
                 lambda a, b, c: ulysses_attention(a, b, c, axis_name=AX),
                 in_specs=(P(None, AX),) * 3,
                 out_specs=P(None, AX))(q, k, v)


def _expert_fn(params, tokens):
    return jax.nn.relu(tokens @ params["w1"]) @ params["w2"]


class TestExpertParallel:
    def test_matches_dense_top1(self, mesh):
        """Ample capacity + top-1: every token goes through exactly its
        argmax expert — compare against direct per-token application."""
        S = mesh.devices.size
        E, D, Dh, N = S, 8, 16, 64  # one expert per device
        rng = np.random.RandomState(11)
        x = rng.randn(N, D).astype(np.float32)
        router_w = rng.randn(D, E).astype(np.float32)
        experts = {
            "w1": jnp.asarray(rng.randn(E, D, Dh).astype(np.float32) * 0.3),
            "w2": jnp.asarray(rng.randn(E, Dh, D).astype(np.float32) * 0.3),
        }

        out, aux = smap(
            mesh,
            lambda xs, rw, ep: expert_parallel_moe(
                xs, rw, ep, _expert_fn, axis_name=AX,
                capacity_factor=float(E)),  # capacity = N: no drops
            in_specs=(P(AX), P(), P(AX)),
            out_specs=(P(AX), P()))(x, router_w, experts)

        probs = jax.nn.softmax(jnp.asarray(x) @ router_w, axis=-1)
        choice = np.asarray(probs.argmax(axis=-1))
        gate = np.asarray(probs.max(axis=-1))
        ref = np.stack([
            np.asarray(_expert_fn(
                jax.tree.map(lambda a: a[choice[i]], experts),
                jnp.asarray(x[i:i + 1])))[0] * gate[i]
            for i in range(N)
        ])
        np.testing.assert_allclose(np.asarray(out), ref,
                                   rtol=1e-3, atol=1e-4)
        assert float(aux) > 0

    def test_matches_dense_top2(self, mesh):
        """Ample capacity + top-2: every token is the gate-weighted sum
        of its two best experts with gates renormalised over the pair —
        compare against direct dense application."""
        S = mesh.devices.size
        E, D, Dh, N = S, 8, 16, 64
        rng = np.random.RandomState(21)
        x = rng.randn(N, D).astype(np.float32)
        router_w = rng.randn(D, E).astype(np.float32)
        experts = {
            "w1": jnp.asarray(rng.randn(E, D, Dh).astype(np.float32) * 0.3),
            "w2": jnp.asarray(rng.randn(E, Dh, D).astype(np.float32) * 0.3),
        }

        out, aux = smap(
            mesh,
            lambda xs, rw, ep: expert_parallel_moe(
                xs, rw, ep, _expert_fn, axis_name=AX,
                capacity_factor=float(E), top_k=2),
            in_specs=(P(AX), P(), P(AX)),
            out_specs=(P(AX), P()))(x, router_w, experts)

        probs = np.asarray(jax.nn.softmax(jnp.asarray(x) @ router_w, -1))
        order = np.argsort(-probs, axis=-1)[:, :2]       # (N, 2)
        ref = np.zeros_like(x)
        for i in range(N):
            e0, e1 = order[i]
            p0, p1 = probs[i, e0], probs[i, e1]
            y0 = np.asarray(_expert_fn(
                jax.tree.map(lambda a: a[e0], experts),
                jnp.asarray(x[i:i + 1])))[0]
            y1 = np.asarray(_expert_fn(
                jax.tree.map(lambda a: a[e1], experts),
                jnp.asarray(x[i:i + 1])))[0]
            ref[i] = (p0 * y0 + p1 * y1) / (p0 + p1)
        np.testing.assert_allclose(np.asarray(out), ref,
                                   rtol=1e-3, atol=1e-4)
        assert float(aux) > 0

    def test_top2_primary_wins_capacity(self, mesh):
        """Rank-0 assignments queue ahead of rank-1: when an expert's
        slots run out, the dropped assignments are secondaries."""
        S = mesh.devices.size
        rng = np.random.RandomState(22)
        N_local = 4
        x = rng.randn(N_local * S, 4).astype(np.float32)
        # every token's best expert is 0, second-best is 1
        router_w = np.zeros((4, S), np.float32)
        router_w[:, 0] = 10.0
        router_w[:, 1] = 5.0
        experts = {
            "w1": jnp.ones((S, 4, 8), jnp.float32),
            "w2": jnp.ones((S, 8, 4), jnp.float32),
        }
        # cap = ceil(cf·k·N/E) with cf=N_local·S/(2·N_local·S)=0.5 → half
        # the primary demand on expert 0: some primaries kept, ALL
        # secondaries on expert 0 would overflow anyway; expert 1 (pure
        # secondaries) has the same cap, so half the secondaries fit
        out, _ = smap(
            mesh,
            lambda xs, rw, ep: expert_parallel_moe(
                xs, rw, ep, _expert_fn, axis_name=AX,
                capacity_factor=0.5, top_k=2),
            in_specs=(P(AX), P(), P(AX)),
            out_specs=(P(AX), P()))(x, router_w, experts)
        # nothing NaN/Inf and at least one token got pure-primary output
        arr = np.asarray(out)
        assert np.isfinite(arr).all()
        assert (np.abs(arr).sum(axis=1) > 0).any()

    def test_capacity_drops_zero_tokens(self, mesh):
        """Tiny capacity: overflow tokens must come back as exact zeros."""
        S = mesh.devices.size
        rng = np.random.RandomState(12)
        x = rng.randn(32, 4).astype(np.float32)
        # router forces everyone to expert 0 → massive overflow
        router_w = np.zeros((4, S), np.float32)
        router_w[:, 0] = 10.0
        experts = {
            "w1": jnp.ones((S, 4, 8), jnp.float32),
            "w2": jnp.ones((S, 8, 4), jnp.float32),
        }
        out, _ = smap(
            mesh,
            lambda xs, rw, ep: expert_parallel_moe(
                xs, rw, ep, _expert_fn, axis_name=AX,
                capacity_factor=0.25),
            in_specs=(P(AX), P(), P(AX)),
            out_specs=(P(AX), P()))(x, router_w, experts)
        out = np.asarray(out)
        # cap = ceil(0.25 · 4 local tokens / 8 experts) → 1 slot per expert
        # per device; all tokens route to expert 0 → exactly 1 kept per
        # device, the rest come back as exact zeros (Switch drop semantics;
        # note a *kept* token can also legitimately output zero via relu)
        zero_rows = (np.abs(out).sum(axis=1) == 0).sum()
        assert zero_rows >= 32 - S  # every over-capacity token dropped
        nonzero_rows = (np.abs(out).sum(axis=1) > 0).sum()
        assert nonzero_rows <= S  # at most one kept slot per device

    def test_gradients_flow(self, mesh):
        S = mesh.devices.size
        rng = np.random.RandomState(13)
        x = rng.randn(16, 4).astype(np.float32)
        router_w = rng.randn(4, S).astype(np.float32)
        experts = {
            "w1": jnp.asarray(rng.randn(S, 4, 8).astype(np.float32) * 0.3),
            "w2": jnp.asarray(rng.randn(S, 8, 4).astype(np.float32) * 0.3),
        }

        def loss(ep, xs):
            out, aux = expert_parallel_moe(
                xs, router_w, ep, _expert_fn, axis_name=AX,
                capacity_factor=float(S))
            return jax.lax.psum(jnp.sum(out**2), AX) + 0.01 * aux

        g = smap(mesh, jax.grad(loss), in_specs=(P(AX), P(AX)),
                 out_specs=P(AX))(experts, x)
        for leaf in jax.tree.leaves(g):
            arr = np.asarray(leaf)
            assert np.isfinite(arr).all()
            assert np.abs(arr).sum() > 0


class TestRingFlash:
    """ring_attention(use_flash=True): Pallas per-pair kernels + exact
    log-space merge must equal full-sequence attention."""

    def _qkv(self, B=2, T=64, H=2, D=16, seed=0):
        rng = np.random.RandomState(seed)
        mk = lambda: jnp.asarray(
            rng.randn(B, T, H, D).astype(np.float32) * 0.5)
        return mk(), mk(), mk()

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention(self, causal):
        from chainermn_tpu.parallel.ring_attention import (
            local_attention, ring_attention)

        q, k, v = self._qkv()
        ref = local_attention(q, k, v, causal=causal)
        mc = MeshConfig(seq=8)
        f = jax.jit(jax.shard_map(
            lambda q, k, v: ring_attention(
                q, k, v, axis_name="seq", causal=causal, remat=False,
                use_flash=True, block_q=8, block_k=8, interpret=True),
            mesh=mc.mesh,
            in_specs=(P(None, "seq"),) * 3,
            out_specs=P(None, "seq")))
        out = f(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    def test_grads_match_xla_ring(self):
        from chainermn_tpu.parallel.ring_attention import ring_attention

        q, k, v = self._qkv(seed=1)
        mc = MeshConfig(seq=8)

        def make_loss(**kw):
            def loss(q, k, v):
                o = ring_attention(q, k, v, axis_name="seq", causal=True,
                                   remat=False, **kw)
                return jax.lax.psum(
                    jnp.sum(o * jnp.cos(o)), ("seq",))
            return jax.jit(jax.shard_map(
                jax.grad(loss, argnums=(0, 1, 2)),
                mesh=mc.mesh,
                in_specs=(P(None, "seq"),) * 3,
                out_specs=(P(None, "seq"),) * 3))

        g_flash = make_loss(use_flash=True, block_q=8, block_k=8,
                            interpret=True)(q, k, v)
        g_xla = make_loss()(q, k, v)
        for a, b in zip(g_flash, g_xla):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5)


class TestZigzagRing:
    """Load-balanced (Striped/zigzag) causal ring layout: device r holds
    chunks r and 2S-1-r; the result must equal dense attention gathered
    through the same permutation, for both the einsum and kernel paths."""

    def _global_qkv(self, B=2, Tg=64, H=2, D=16, seed=0):
        rng = np.random.RandomState(seed)
        mk = lambda: jnp.asarray(
            rng.randn(B, Tg, H, D).astype(np.float32) * 0.5)
        return mk(), mk(), mk()

    @pytest.mark.parametrize("use_flash", [False, True])
    def test_matches_dense_oracle(self, use_flash):
        from chainermn_tpu.parallel.ring_attention import (
            local_attention, ring_attention, zigzag_indices)

        S, Tg = 8, 64
        q, k, v = self._global_qkv(Tg=Tg)
        perm = zigzag_indices(S, Tg).reshape(-1)      # global -> zigzag
        qz, kz, vz = (t[:, perm] for t in (q, k, v))

        mc = MeshConfig(seq=S)
        f = jax.jit(jax.shard_map(
            lambda q, k, v: ring_attention(
                q, k, v, axis_name="seq", causal=True, remat=False,
                layout="zigzag", use_flash=use_flash, block_q=8,
                block_k=8, interpret=True),
            mesh=mc.mesh,
            in_specs=(P(None, "seq"),) * 3,
            out_specs=P(None, "seq")))
        out_z = np.asarray(f(qz, kz, vz))

        ref = np.asarray(local_attention(q, k, v, causal=True))
        # un-permute the zigzag output back to global order
        inv = np.empty_like(perm)
        inv[perm] = np.arange(Tg)
        np.testing.assert_allclose(out_z[:, inv], ref,
                                   rtol=2e-5, atol=2e-5)

    def test_grads_match_contiguous_ring(self):
        from chainermn_tpu.parallel.ring_attention import (
            ring_attention, zigzag_indices)

        S, Tg = 4, 32
        q, k, v = self._global_qkv(Tg=Tg, seed=3)
        perm = zigzag_indices(S, Tg).reshape(-1)
        inv = np.empty_like(perm)
        inv[perm] = np.arange(Tg)
        mc = MeshConfig(seq=S, data=2)

        def make_grads(layout, qkv):
            def loss(q, k, v):
                o = ring_attention(q, k, v, axis_name="seq", causal=True,
                                   remat=False, layout=layout)
                return jax.lax.psum(jnp.sum(o * jnp.sin(o)), ("seq",))
            g = jax.jit(jax.shard_map(
                jax.grad(loss, argnums=(0, 1, 2)),
                mesh=mc.mesh,
                in_specs=(P(None, "seq"),) * 3,
                out_specs=(P(None, "seq"),) * 3))(*qkv)
            return [np.asarray(t) for t in g]

        g_zig = make_grads("zigzag", (q[:, perm], k[:, perm], v[:, perm]))
        g_ref = make_grads("contiguous", (q, k, v))
        for a, b in zip(g_zig, g_ref):
            np.testing.assert_allclose(a[:, inv], b, rtol=5e-4, atol=1e-5)

    def test_bad_layout_rejected(self):
        from chainermn_tpu.parallel.ring_attention import ring_attention

        mc = MeshConfig(seq=2)
        with pytest.raises(ValueError, match="layout"):
            jax.jit(jax.shard_map(
                lambda q: ring_attention(q, q, q, axis_name="seq",
                                         layout="spiral"),
                mesh=mc.mesh, in_specs=(P(None, "seq"),),
                out_specs=P(None, "seq")))(
                    np.zeros((1, 8, 1, 4), np.float32))

    def test_zigzag_indices_cover(self):
        from chainermn_tpu.parallel.ring_attention import zigzag_indices

        idx = zigzag_indices(4, 64)
        assert idx.shape == (4, 16)
        assert sorted(idx.reshape(-1).tolist()) == list(range(64))
        # device 0 holds the first and the LAST chunk (balance property)
        assert idx[0, 0] == 0 and idx[0, -1] == 63
