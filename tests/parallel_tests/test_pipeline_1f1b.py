"""1F1B pipeline schedule vs sequential oracle and vs GPipe.

``pipeline_train_1f1b`` computes loss AND gradients in one scheduled
SPMD program (loss in-schedule — the placement that gives 1F1B its O(S)
activation memory).  It must numerically match a plain sequential
chain + loss under autodiff: loss value, stage-parameter grads,
loss-parameter grads, and input grads.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from chainermn_tpu.communicators._mesh_utils import make_world_mesh
from chainermn_tpu.parallel import stack_stage_params
from chainermn_tpu.parallel.pipeline import pipeline_apply, pipeline_train_1f1b

AX = "world"


@pytest.fixture(scope="module")
def mesh():
    return make_world_mesh(axis_name=AX)


def _stage_apply(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _loss_fn(lp, y, tgt):
    pred = y @ lp["head"]
    return jnp.mean((pred - tgt) ** 2)


def _make(S, dim, seed=0):
    rng = np.random.RandomState(seed)
    stages = [
        {"w": jnp.asarray(rng.randn(dim, dim).astype(np.float32) * 0.3),
         "b": jnp.asarray(rng.randn(dim).astype(np.float32) * 0.1)}
        for _ in range(S)
    ]
    lp = {"head": jnp.asarray(rng.randn(dim, 2).astype(np.float32) * 0.3)}
    return stages, lp


def _ref(stages, lp, x, y):
    def loss(stages, lp, x):
        h = x
        for p in stages:
            h = _stage_apply(p, h)
        return _loss_fn(lp, h, y)

    l, grads = jax.value_and_grad(loss, argnums=(0, 1, 2))(
        stages, lp, jnp.asarray(x))
    return l, grads


class TestPipeline1F1B:
    @pytest.mark.parametrize("M", [8, 16])
    def test_matches_sequential_oracle(self, mesh, M):
        S = mesh.devices.size
        dim, B = 5, 32
        stages, lp = _make(S, dim, seed=1)
        stacked = stack_stage_params(stages)
        rng = np.random.RandomState(2)
        x = rng.randn(B, dim).astype(np.float32)
        y = rng.randn(B, 2).astype(np.float32)

        loss, gp, glp, dx = jax.jit(jax.shard_map(
            lambda p, lpp, xs, ys: pipeline_train_1f1b(
                _stage_apply, _loss_fn, p, lpp, xs, ys,
                axis_name=AX, num_microbatches=M),
            mesh=mesh,
            in_specs=(P(AX), P(), P(), P()),
            out_specs=(P(), P(AX), P(), P())))(stacked, lp, x, y)

        ref_loss, (ref_gs, ref_glp, ref_dx) = _ref(stages, lp, x, y)
        # per-microbatch mean-loss: mean over M equals batch mean only up
        # to identical micro-batch sizes — here exact
        np.testing.assert_allclose(float(loss), float(ref_loss),
                                   rtol=1e-5, atol=1e-6)
        ref_stacked = stack_stage_params(ref_gs)
        for a, b in zip(jax.tree.leaves(gp), jax.tree.leaves(ref_stacked)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(glp["head"]), np.asarray(ref_glp["head"]),
            rtol=1e-3, atol=1e-5)
        np.testing.assert_allclose(np.asarray(dx), np.asarray(ref_dx),
                                   rtol=1e-3, atol=1e-5)

    def test_matches_gpipe_outer_grad(self, mesh):
        """1F1B and GPipe are the same math, differently scheduled."""
        S = mesh.devices.size
        dim, B, M = 4, 16, 8
        stages, lp = _make(S, dim, seed=7)
        stacked = stack_stage_params(stages)
        rng = np.random.RandomState(8)
        x = rng.randn(B, dim).astype(np.float32)
        y = rng.randn(B, 2).astype(np.float32)

        loss1, gp1, glp1, _ = jax.jit(jax.shard_map(
            lambda p, lpp, xs, ys: pipeline_train_1f1b(
                _stage_apply, _loss_fn, p, lpp, xs, ys,
                axis_name=AX, num_microbatches=M),
            mesh=mesh,
            in_specs=(P(AX), P(), P(), P()),
            out_specs=(P(), P(AX), P(), P())))(stacked, lp, x, y)

        def gpipe_loss(p, lpp, xs):
            out = pipeline_apply(_stage_apply, p, xs, axis_name=AX,
                                 num_microbatches=M)
            return _loss_fn(lpp, out, jnp.asarray(y))

        loss2, (gp2, glp2) = jax.jit(jax.shard_map(
            jax.value_and_grad(gpipe_loss, argnums=(0, 1)),
            mesh=mesh,
            in_specs=(P(AX), P(), P()),
            out_specs=(P(), (P(AX), P()))))(stacked, lp, x)

        np.testing.assert_allclose(float(loss1), float(loss2),
                                   rtol=1e-5, atol=1e-6)
        for a, b in zip(jax.tree.leaves(gp1), jax.tree.leaves(gp2)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(glp1["head"]), np.asarray(glp2["head"]),
            rtol=1e-3, atol=1e-5)


class TestPipelineAux:
    def test_moe_style_aux_survives_pipelining(self, mesh):
        """pipeline_apply(with_aux=True): per-stage aux from real ticks
        only, averaged over micro-batches — matches sequential sum."""
        S = mesh.devices.size
        dim, B, M = 4, 16, 8
        stages, _ = _make(S, dim, seed=11)
        stacked = stack_stage_params(stages)
        x = np.random.RandomState(12).randn(B, dim).astype(np.float32)

        def stage_aux(p, mb):
            out = _stage_apply(p, mb)
            return out, jnp.mean(out**2)  # batch-mean aux, like Switch

        out, aux = jax.jit(jax.shard_map(
            lambda p, xs: pipeline_apply(
                stage_aux, p, xs, axis_name=AX, num_microbatches=M,
                with_aux=True),
            mesh=mesh,
            in_specs=(P(AX), P()), out_specs=(P(), P())))(stacked, x)

        h = jnp.asarray(x)
        ref_aux = 0.0
        for p in stages:
            h, a = stage_aux(p, h)
            ref_aux += a
        np.testing.assert_allclose(np.asarray(out), np.asarray(h),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(float(aux), float(ref_aux),
                                   rtol=1e-4, atol=1e-5)
