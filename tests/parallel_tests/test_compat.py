"""Regression tests for ``parallel/_compat.py`` — the one-place
version-compat layer.  The failure mode it guards: an import chain
(`models.transformer` → `_compat`) raising ImportError on the installed
jax took 35 of 158 test files down *at collection* (the
``all_gather_invariant`` import had no fallback for jaxes that predate
the primitive).  These tests pin that every compat symbol resolves and
behaves on whatever jax is installed."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from chainermn_tpu.parallel import _compat

AX = "world"


def test_models_transformer_imports_cleanly():
    """THE regression: this exact import is the one 35 test files died
    on when _compat had no third fallback.  Run in a fresh interpreter
    so a warm ``sys.modules`` can't mask an import-time failure."""
    import os
    import subprocess
    import sys

    root = os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", ".."))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=root + os.pathsep + os.environ.get(
                   "PYTHONPATH", ""))
    proc = subprocess.run(
        [sys.executable, "-c", "import chainermn_tpu.models.transformer"],
        capture_output=True, text=True, timeout=240, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]


def test_compat_exports_resolve():
    for name in _compat.__all__:
        assert getattr(_compat, name) is not None


def test_jax_namespace_shims_installed():
    """Call sites across the package use the modern spellings directly —
    they must resolve regardless of jax version."""
    assert callable(jax.shard_map)
    assert callable(jax.typeof)
    assert callable(jax.lax.axis_size)
    assert callable(jax.lax.pcast)


def test_all_gather_invariant_gathers(comm):
    """The shim (or the real primitive) gathers a varying value into the
    identical full array on every member — and the result types as
    replicated (out_specs P() must be accepted)."""
    n = comm.size
    x = np.random.RandomState(0).randn(n, 3).astype(np.float32)

    f = jax.jit(jax.shard_map(
        lambda s: _compat.all_gather_invariant(
            s[:, 0], comm.axis_name, tiled=True),
        mesh=comm.mesh, in_specs=P(comm.axis_name), out_specs=P()))
    np.testing.assert_allclose(np.asarray(f(x)), x[:, 0], rtol=1e-6)


def test_axis_size_is_static(comm):
    """axis_size must fold to a python int under tracing — shapes
    (zero1 shard widths, pipeline stages) are built from it."""
    sizes = []

    def body(s):
        k = _compat.axis_size(comm.axis_name)
        sizes.append(k)
        return jnp.zeros((k,))[None]  # a SHAPE built from it

    out = jax.jit(jax.shard_map(
        body, mesh=comm.mesh, in_specs=P(comm.axis_name),
        out_specs=P(comm.axis_name)))(
            np.zeros((comm.size, 1), np.float32))
    assert sizes[0] == comm.size
    assert out.shape == (comm.size, comm.size)


def test_pcast_and_typeof_roundtrip(comm):
    """pcast retypes (or is the identity pre-vma) without changing
    values; typeof always exposes a ``vma`` set."""
    x = np.random.RandomState(1).randn(comm.size, 4).astype(np.float32)

    def body(s):
        v = _compat.pcast(s, (comm.axis_name,), to="varying")
        assert isinstance(_compat.typeof(v).vma, (frozenset, set, tuple))
        return v

    out = jax.jit(jax.shard_map(
        body, mesh=comm.mesh, in_specs=P(comm.axis_name),
        out_specs=P(comm.axis_name)))(x)
    np.testing.assert_allclose(np.asarray(out), x, rtol=1e-6)
