"""Unified sharded-state layer (``parallel.sharded_state``): per-leaf
layout signatures, the ZeRO-3 ``ShardedState`` plan, and the JIT
``LayerGatherStream``.

The layer's contract is that ONE signature table drives three
consumers — the plan-IR payload descriptors, elastic re-layout /
shard-only snapshots, and the memory accountant — so the tests here
drill each consumer against the same table: ZeRO-3 training parity
with the pure-DP oracle, zero steady-state recompiles for the streamed
step, and the world-8 → world-4 shard-only resume."""

import warnings
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

import chainermn_tpu as cmn
from chainermn_tpu.ops import plan_ir
from chainermn_tpu.parallel.sharded_state import (
    LeafLayout,
    ShardedState,
    gather_state_leaves,
    layout_records,
    shard_state_leaves,
    state_layout_table,
    zero_opt_layouts,
)
from chainermn_tpu.training import shard_opt_state, topology_signature
from chainermn_tpu.training.elastic import RelayoutError, relayout_state
from chainermn_tpu.utils import comm_model, serialization as ser
from chainermn_tpu.utils.metrics import MetricsRegistry, set_registry
from chainermn_tpu.utils.programs import (
    MemoryAccountant,
    ProgramLedger,
    ledger_jit,
    set_ledger,
)

AX = "world"


@pytest.fixture()
def comm():
    return cmn.create_communicator("tpu_xla", axis_name=AX)


@pytest.fixture()
def registry():
    reg = MetricsRegistry(enabled=True)
    prev = set_registry(reg)
    try:
        yield reg
    finally:
        set_registry(prev)


def _mlp_params():
    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    return {
        "l0": {"w": jax.random.normal(k1, (16, 64), jnp.float32) * 0.25,
               "b": jnp.zeros((64,))},
        "l1": {"w": jax.random.normal(k2, (64, 8), jnp.float32) * 0.125,
               "b": jnp.zeros((8,))},
    }


# --------------------------------------------------------------------- #
# the signature
# --------------------------------------------------------------------- #


class TestLeafLayout:
    def test_record_round_trip_matches_legacy_vocabulary(self):
        shard = LeafLayout(("mu", "w"), "shard", (8, 2), "float32", 8,
                           size=15)
        assert shard.to_record() == {"kind": "shard", "size": 15}
        assert LeafLayout(("c",), "stack", (8,), "int32", 8
                          ).to_record() == {"kind": "stack"}
        assert LeafLayout(("s",), "rep", (), "float32", 8
                          ).to_record() == {"kind": "rep"}
        fsdp = LeafLayout(("w",), "fsdp", (16, 64), "float32", 8, dim=1)
        assert fsdp.to_record() == {"kind": "fsdp", "dim": 1, "len": 64}
        back = LeafLayout.from_record(fsdp.to_record(), path=("w",),
                                      shape=(16, 64), dtype="float32",
                                      world=8)
        assert back.kind == "fsdp" and back.dim == 1

    def test_local_geometry(self):
        shard = LeafLayout(("m",), "shard", (8, 2), "float32", 8, size=15)
        assert shard.local_shape() == (2,)
        assert shard.local_shape(world=4) == (4,)
        assert shard.local_bytes() == 8
        fsdp = LeafLayout(("w",), "fsdp", (16, 64), "float32", 8, dim=1)
        assert fsdp.local_shape() == (16, 8)
        assert fsdp.local_bytes() == 16 * 8 * 4
        assert fsdp.global_bytes() == 16 * 64 * 4
        with pytest.raises(ValueError, match="not divisible"):
            fsdp.local_shape(world=5)

    def test_validation(self):
        with pytest.raises(ValueError, match="unknown layout kind"):
            LeafLayout((), "bogus", (), "float32", 8)
        with pytest.raises(ValueError, match="size="):
            LeafLayout(("x",), "shard", (8, 2), "float32", 8)
        with pytest.raises(ValueError, match="dim="):
            LeafLayout(("x",), "fsdp", (16, 64), "float32", 8)


class TestLayoutTable:
    def test_zero1_table_is_the_legacy_layout(self, comm):
        """The table IS ``_zero1_leaf_layout``'s vocabulary: the golden
        records a world-stacked adam carry has always stamped."""
        from chainermn_tpu.training.optimizers import (
            zero1_init,
            zero1_optimizer,
        )

        params = {"w": jnp.zeros((5, 3)), "b": jnp.zeros((7,))}
        opt = zero1_optimizer(optax.adam(1e-2), AX)
        state = zero1_init(opt, params, comm.mesh, AX)

        table = state_layout_table("zero1", params, state, world=8)
        recs = layout_records(table["opt_state"])
        # flattened order: count, mu{b,w}, nu{b,w}
        assert recs == [
            {"kind": "stack"},
            {"kind": "shard", "size": 7},
            {"kind": "shard", "size": 15},
            {"kind": "shard", "size": 7},
            {"kind": "shard", "size": 15},
        ]
        assert all(r == {"kind": "rep"}
                   for r in layout_records(table["params"]))
        # zero2 shares the layout verbatim (one table, two exchanges)
        assert layout_records(state_layout_table(
            "zero2", params, state, world=8)["opt_state"]) == recs

    def test_zero3_table(self):
        params = _mlp_params()
        ss_dims = {"l0": {"w": 1, "b": 0}, "l1": {"w": 0, "b": None}}
        state = optax.adam(1e-2).init(params)
        table = state_layout_table("zero3", params, state, world=8,
                                   dims=ss_dims, axis=AX)
        by_path = {l.path: l for l in table["params"]}
        assert by_path[("['l0']", "['w']")].kind == "fsdp"
        assert by_path[("['l0']", "['w']")].dim == 1
        assert by_path[("['l1']", "['b']")].kind == "rep"
        # moments mirror their param; count replicates
        kinds = {l.path: (l.kind, l.dim) for l in table["opt_state"]}
        assert kinds[("[0]", ".mu", "['l0']", "['w']")] == ("fsdp", 1)
        assert kinds[("[0]", ".count")] == ("rep", None)

    def test_zero3_requires_dims(self):
        with pytest.raises(ValueError, match="dims"):
            state_layout_table("zero3", {"w": jnp.zeros((8, 8))},
                               world=8)

    def test_unknown_mode(self):
        with pytest.raises(ValueError, match="unknown sharding mode"):
            state_layout_table("zero4", {}, world=8)

    def test_describe_state_payload(self):
        layouts = [
            LeafLayout(("w",), "fsdp", (16, 64), "float32", 8, dim=1),
            LeafLayout(("m",), "shard", (8, 2), "float32", 8, size=15),
            LeafLayout(("r",), "rep", (3,), "int32", 8),
        ]
        descs = plan_ir.describe_state_payload(layouts, 8)
        assert [d.shape for d in descs] == [(16, 8), (2,), (3,)]
        with pytest.raises(ValueError):
            plan_ir.describe_state_payload(
                [{"kind": "bogus", "shape": (2,), "dtype": "float32"}], 8)


class TestGatherShardLeaves:
    def test_round_trip(self):
        layouts = [{"kind": "shard", "size": 15}, {"kind": "stack"},
                   {"kind": "rep"}]
        tree = {"a": np.arange(16, dtype=np.float32).reshape(8, 2),
                "b": np.tile(np.arange(3.0), (8, 1)),
                "c": np.float32(7.0)}
        tree["a"][-1, -1] = 0  # the pad lane
        full = gather_state_leaves(tree, layouts)
        assert full["a"].shape == (15,)
        back = shard_state_leaves(full, layouts, 8)
        np.testing.assert_array_equal(back["a"], tree["a"])
        np.testing.assert_array_equal(back["b"], tree["b"])

    def test_unknown_kind_names_the_leaf(self):
        tree = {"mu": {"w": np.zeros((8, 2))}}
        with pytest.raises(RelayoutError, match=r"\['mu'\]\['w'\]"):
            gather_state_leaves(tree, [{"kind": "mystery"}])
        with pytest.raises(RelayoutError, match="mystery"):
            shard_state_leaves(tree, [{"kind": "mystery"}], 8)

    def test_relayout_state_names_the_offending_leaf(self):
        """Satellite 1: ``relayout_state`` raises a typed error naming
        the offending leaf path for unknown layout kinds — never a
        bare KeyError or a silent pass-through."""
        state = {"opt_state": {"mu": {"w1": np.zeros((8, 2))}}}
        topo_old = {"zero1": True, "world_size": 8,
                    "opt_leaves": [{"kind": "mystery"}]}
        topo_new = {"zero1": True, "world_size": 4}
        with pytest.raises(RelayoutError) as ei:
            relayout_state(state, topo_old, topo_new)
        msg = str(ei.value)
        assert "opt_state" in msg and "w1" in msg and "mystery" in msg

    def test_deprecated_shims_delegate_and_warn_once(self):
        from chainermn_tpu.training import elastic

        layouts = [{"kind": "shard", "size": 15}]
        tree = {"m": np.arange(16, dtype=np.float32).reshape(8, 2)}
        tree["m"][-1, -1] = 0  # the pad lane
        elastic._ZERO1_LEAVES_WARNED = False
        with pytest.warns(DeprecationWarning, match="sharded-state"):
            full = elastic.gather_zero1_leaves(tree, layouts)
        np.testing.assert_array_equal(
            full["m"], gather_state_leaves(tree, layouts)["m"])
        with warnings.catch_warnings():
            warnings.simplefilter("error")  # a second warn would raise
            back = elastic.shard_zero1_leaves(full, layouts, 8)
        np.testing.assert_array_equal(back["m"], tree["m"])


# --------------------------------------------------------------------- #
# ShardedState: placement, plan, accounting
# --------------------------------------------------------------------- #


class TestShardedState:
    def test_place_and_layouts(self, comm):
        params = _mlp_params()
        ss = ShardedState(params, comm)
        placed = ss.place(params)
        ss.init_opt_state(optax.adam(1e-2))
        # at rest each fsdp leaf holds 1/8 on every device
        assert placed["l0"]["w"].addressable_shards[0].data.shape \
            == (16, 8)
        table = ss.layouts()
        kinds = {l.path: l.kind for l in table["params"]}
        assert kinds[("['l0']", "['w']")] == "fsdp"
        # analytic local bytes: full tree is 16*64+64+64*8+8 floats;
        # every fsdp leaf counts 1/8, rep leaves count whole
        param_bytes = sum(l.local_bytes() for l in table["params"])
        assert param_bytes < sum(
            l.global_bytes() for l in table["params"]) / 4

    def test_init_opt_state_requires_place(self, comm):
        ss = ShardedState(_mlp_params(), comm)
        with pytest.raises(RuntimeError, match="place"):
            ss.init_opt_state(optax.adam(1e-2))

    def test_tune_serves_from_cache(self, comm, tmp_path):
        cache = str(tmp_path / "plans.json")
        params = _mlp_params()
        ss = ShardedState(params, comm)
        plan = ss.tune_gather_plan(comm, cache_path=cache, trials=1,
                                   warmup=1)
        assert not plan.from_cache and plan.n_probes > 0
        assert plan.program["pattern"] == "fsdp_gather"
        again = ShardedState(params, comm).tune_gather_plan(
            comm, cache_path=cache, trials=1, warmup=1)
        assert again.from_cache and again.n_probes == 0
        assert again.program == plan.program

    def test_variant_is_consumer_keyed(self, comm, tmp_path):
        """A foreign fsdp_gather tuning of the SAME payload must not
        serve the sharded-state call site (variant_extra rekeys)."""
        from chainermn_tpu.utils import autotune

        cache = str(tmp_path / "plans.json")
        params = _mlp_params()
        ss = ShardedState(params, comm)
        autotune.autotune_pattern_plan(
            comm, ss.local_template(), pattern="fsdp_gather",
            dims=ss.dims, cache_path=cache, trials=1, warmup=1)
        plan = ss.tune_gather_plan(comm, cache_path=cache, trials=1,
                                   warmup=1)
        assert not plan.from_cache

    def test_memory_accountant_measures_the_zero3_ratio(self, comm):
        """The headline claim, measured: at-rest param+opt bytes per
        chip under ZeRO-3 are far below the replicated baseline (the
        accountant counts replication N×)."""
        params = _mlp_params()
        acc = MemoryAccountant()
        ss = ShardedState(params, comm)
        ss.place(params)
        ss.init_opt_state(optax.adam(1e-2))
        ss.register_memory(acc, prefix="z3")

        rep = jax.tree.map(
            lambda p: jax.device_put(
                p, NamedSharding(comm.mesh, P())), params)
        acc.register("dp_params", rep)
        acc.register("dp_opt_state", shard_opt_state(optax.adam(1e-2),
                                                     rep))
        sample = acc.sample()
        z3 = sample["z3_params"] + sample["z3_opt_state"]
        dp = sample["dp_params"] + sample["dp_opt_state"]
        assert dp >= 2 * z3
        # ... and the analytic prediction agrees with the measurement
        assert z3 == ss.local_bytes() * comm.size

    def test_auto_window_adopts_model_depth(self, comm):
        ss = ShardedState(_mlp_params(), comm)
        got = ss.auto_window(layer_compute_s=10.0)
        # tiny gathers hide behind 10 s layers: double buffering
        assert got == ss.window == 2
        assert ss.auto_window(layer_compute_s=1e-12) == 4  # exposed


class TestChooseGatherPrefetchDepth:
    def test_regimes(self):
        # comm-bound: gather time >> compute -> deepest window
        assert comm_model.choose_gather_prefetch_depth(
            1e9, 8, 1e-3) == 4
        # compute-bound: classic double buffering is enough
        assert comm_model.choose_gather_prefetch_depth(
            1e6, 8, 1.0) == 2
        # single member: nothing to gather
        assert comm_model.choose_gather_prefetch_depth(
            1e9, 1, 1e-6) == 1
        # no compute measured yet: take the memory budget's max
        assert comm_model.choose_gather_prefetch_depth(
            1e6, 8, 0.0, max_window=3) == 3

    def test_link_overrides_scalars(self):
        slow = comm_model.LinkParams(latency_s=1e-3,
                                     bandwidth_bytes_per_s=1e6)
        assert comm_model.choose_gather_prefetch_depth(
            1e6, 8, 1e-3, link=slow) == 4

    def test_validation(self):
        with pytest.raises(ValueError, match=">= 0"):
            comm_model.choose_gather_prefetch_depth(-1, 8, 1e-3)
        with pytest.raises(ValueError, match="window bounds"):
            comm_model.choose_gather_prefetch_depth(
                1e6, 8, 1e-3, min_window=3, max_window=2)


# --------------------------------------------------------------------- #
# the streamed ZeRO-3 step
# --------------------------------------------------------------------- #


def _stream_forward(stream, x):
    for i in range(len(stream)):
        full = stream.layer(i)
        h = x @ full["w"] + full["b"]
        x = jax.nn.relu(h) if i < len(stream) - 1 else h
        x = stream.retire(i, x)
    return x


def _oracle_forward(params, x):
    h = jax.nn.relu(x @ params["l0"]["w"] + params["l0"]["b"])
    return h @ params["l1"]["w"] + params["l1"]["b"]


class TestLayerGatherStream:
    def test_forward_bitwise_matches_oracle(self, comm, registry):
        params = _mlp_params()
        ss = ShardedState(params, comm)
        placed = ss.place(params)
        x = jnp.asarray(np.random.RandomState(0).randn(32, 16),
                        jnp.float32)

        def fwd(p, xb):
            return _stream_forward(ss.gather_stream(p), xb)

        out = jax.jit(jax.shard_map(
            fwd, mesh=comm.mesh, in_specs=(ss.specs, P(AX)),
            out_specs=P(AX)))(placed, x)
        np.testing.assert_array_equal(
            np.asarray(out), np.asarray(_oracle_forward(params, x)))
        # one gather issued per layer, none served from a plan cache
        assert registry.counter("sharded/layer_gathers").value == 2
        assert registry.counter("sharded/plan_cache_gathers").value == 0

    def test_cached_plan_counts_on_programz(self, comm, registry,
                                            tmp_path):
        cache = str(tmp_path / "plans.json")
        params = _mlp_params()
        ss = ShardedState(params, comm)
        placed = ss.place(params)
        ss.tune_gather_plan(comm, cache_path=cache, trials=1, warmup=1)
        ss2 = ShardedState(params, comm)
        ss2.tune_gather_plan(comm, cache_path=cache, trials=1, warmup=1)
        assert ss2.plan_cell.plan.from_cache
        x = jnp.asarray(np.random.RandomState(0).randn(32, 16),
                        jnp.float32)

        out = jax.jit(jax.shard_map(
            lambda p, xb: _stream_forward(ss2.gather_stream(p), xb),
            mesh=comm.mesh, in_specs=(ss2.specs, P(AX)),
            out_specs=P(AX)))(placed, x)
        np.testing.assert_array_equal(
            np.asarray(out), np.asarray(_oracle_forward(params, x)))
        assert registry.counter("sharded/layer_gathers").value == 2
        assert registry.counter("sharded/plan_cache_gathers").value == 2

    def test_window_bounds_and_names(self, comm):
        params = _mlp_params()
        ss = ShardedState(params, comm, window=1)
        stream = ss.gather_stream(params)
        assert len(stream) == 2 and stream.names == ["l0", "l1"]
        assert stream.window == 1
        with pytest.raises(IndexError):
            stream.layer(2)


def _z3_train(comm, use_z3, steps=3, mesh=None, resume=None):
    """DP MLP regression, grads via per-rank scaled losses (no
    replicated-output grads — expressible on pre-vma shard_map); the
    update runs under plain jit so XLA propagates the at-rest
    shardings.  Returns (host params, losses, live state)."""
    mesh = mesh if mesh is not None else comm.mesh
    params = _mlp_params() if resume is None else resume[0]
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(32, 16), jnp.float32)
    y = jnp.asarray(rng.randn(32, 8), jnp.float32)
    opt = optax.adam(1e-2)

    if use_z3:
        ss = ShardedState(params, mesh=mesh, axis_name=AX)
        placed = ss.place(params)
        opt_state = (resume[1] if resume is not None
                     else shard_opt_state(opt, placed))
        specs = ss.specs
    else:
        placed = jax.tree.map(
            lambda p: jax.device_put(p, NamedSharding(mesh, P())),
            params)
        opt_state = opt.init(placed) if resume is None else resume[1]
        specs = jax.tree.map(lambda _: P(), params)

    def per_rank_loss(p, xb, yb):
        if use_z3:
            pred = _stream_forward(ss.gather_stream(p), xb)
        else:
            pred = _oracle_forward(p, xb)
        # local SUM over the rank's batch rows, scaled by the GLOBAL
        # count: the cross-rank sum of these is exactly the global
        # mean, so no replicated-output grad is ever taken
        return jnp.sum((pred - yb) ** 2) / (32 * 8)

    def grad_body(p, xb, yb):
        loss, g = jax.value_and_grad(per_rank_loss)(p, xb, yb)
        if use_z3:
            # fsdp leaves' grads are born sharded (the gather's AD
            # transpose is a psum_scatter — the cross-rank sum); the
            # replicated leaves still need their explicit sum
            g = jax.tree.map(
                lambda t, d: t if d is not None else jax.lax.psum(
                    t, AX), g, ss.dims)
        else:
            g = jax.tree.map(lambda t: jax.lax.psum(t, AX), g)
        return loss[None], g

    grad_fn = jax.shard_map(
        grad_body, mesh=mesh, in_specs=(specs, P(AX), P(AX)),
        out_specs=(P(AX), specs))

    @jax.jit
    def step(p, s, xb, yb):
        loss, g = grad_fn(p, xb, yb)
        u, s = opt.update(g, s, p)
        return optax.apply_updates(p, u), s, jnp.sum(loss)

    losses = []
    for _ in range(steps):
        placed, opt_state, loss = step(placed, opt_state, x, y)
        losses.append(float(loss))
    host = jax.tree.map(lambda a: np.asarray(jax.device_get(a)), placed)
    return host, losses, (placed, opt_state)


class TestZero3Training:
    def test_matches_dp_oracle(self, comm):
        """ZeRO-3 training through the layer-gather stream against the
        replicated pure-DP oracle: same losses, same parameters.  The
        gather's AD transpose (a reduce-scatter) IS the gradient
        exchange — grads are born sharded."""
        dp_host, dp_losses, _ = _z3_train(comm, use_z3=False)
        z3_host, z3_losses, _ = _z3_train(comm, use_z3=True)
        np.testing.assert_allclose(z3_losses, dp_losses, rtol=1e-5)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                a, b, rtol=2e-5, atol=2e-6), dp_host, z3_host)

    def test_zero_steady_state_recompiles(self, comm, tmp_path):
        """The streamed ZeRO-3 step with a cache-served plan compiles
        once and never retraces at steady state (the PR 15 ledger
        invariant extends to the unified layer)."""
        led = ProgramLedger(enabled=True)
        prev = set_ledger(led)
        try:
            params = _mlp_params()
            ss = ShardedState(params, comm)
            placed = ss.place(params)
            ss.tune_gather_plan(comm,
                                cache_path=str(tmp_path / "p.json"),
                                trials=1, warmup=1)
            opt = optax.adam(1e-2)
            opt_state = shard_opt_state(opt, placed)
            x = jnp.asarray(np.random.RandomState(0).randn(32, 16),
                            jnp.float32)
            y = jnp.asarray(np.random.RandomState(1).randn(32, 8),
                            jnp.float32)

            def per_rank_loss(p, xb, yb):
                pred = _stream_forward(ss.gather_stream(p), xb)
                return jnp.sum((pred - yb) ** 2) / (32 * 8)

            def grad_body(p, xb, yb):
                loss, g = jax.value_and_grad(per_rank_loss)(p, xb, yb)
                g = jax.tree.map(
                    lambda t, d: t if d is not None else jax.lax.psum(
                        t, AX), g, ss.dims)
                return loss[None], g

            grad_fn = jax.shard_map(
                grad_body, mesh=comm.mesh,
                in_specs=(ss.specs, P(AX), P(AX)),
                out_specs=(P(AX), ss.specs))

            def raw_step(p, s, xb, yb):
                _, g = grad_fn(p, xb, yb)
                u, s = opt.update(g, s, p)
                return optax.apply_updates(p, u), s

            step = ledger_jit(raw_step, label="sharded/zero3_step")
            for _ in range(4):
                placed, opt_state = jax.block_until_ready(
                    step(placed, opt_state, x, y))
            assert led.compiles("sharded/") == 1
            assert led.steady_retraces("sharded/") == 0
        finally:
            set_ledger(prev)


# --------------------------------------------------------------------- #
# shard-only snapshots: save at 8, assemble, resume at 4
# --------------------------------------------------------------------- #


class TestZero3ShardOnlySnapshot:
    def test_round_trip_and_resume_at_smaller_world(self, comm):
        # train a couple of steps at world 8 so the moments are real
        host8, losses8, (placed, opt_state) = _z3_train(
            comm, use_z3=True, steps=2)
        params = _mlp_params()
        ss = ShardedState(params, comm)
        table = ss.layouts(opt_state)
        topo8 = topology_signature(comm, sharding="zero3",
                                   layouts=table)
        assert topo8["sharding"] == "zero3"
        assert any(r["kind"] == "fsdp" for r in topo8["param_leaves"])
        assert any(r["kind"] == "fsdp" for r in topo8["opt_leaves"])

        state = {"params": placed, "opt_state": opt_state}
        parts = []
        for lo, hi, root in [(0, 4, True), (4, 8, False)]:
            part, rec = ser.build_shard_part(state, topo8, lo, hi,
                                             root=root)
            # fsdp entries push the record to the v2 format; the part
            # carries dim-sharded param rows too
            assert rec["format"] == ser.SHARD_PART_FORMAT == 2
            assert rec["fsdp_param_leaves"]
            if not root:
                assert part["param_shards"]
            parts.append((rec, part))

        # each member holds 1/2 of every fsdp leaf's shard dim
        root_part = parts[0][1]
        assert root_part["params"]["l0"]["w"].shape == (16, 32)

        assembled = ser.assemble_shard_state(parts)
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)),
            assembled["params"],
            jax.tree.map(lambda a: np.asarray(jax.device_get(a)),
                         placed))
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)),
            assembled["opt_state"],
            jax.tree.map(lambda a: np.asarray(jax.device_get(a)),
                         opt_state))

        # resume at world 4: re-lay (fsdp leaves pass through full
        # width), then the new placement re-slices the dims
        mesh4 = Mesh(np.asarray(jax.devices()[:4]), (AX,))
        stub4 = SimpleNamespace(size=4, inter_size=1, mesh=mesh4)
        topo4 = topology_signature(stub4, sharding="zero3")
        relaid = relayout_state(assembled, topo8, topo4)

        ss4 = ShardedState(relaid["params"], mesh=mesh4, axis_name=AX)
        placed4 = ss4.place(relaid["params"])
        opt_state4 = jax.tree.map(
            lambda a, ref: jax.device_put(jnp.asarray(a), ref.sharding),
            relaid["opt_state"], shard_opt_state(optax.adam(1e-2),
                                                 placed4))
        _, losses4, _ = _z3_train(comm, use_z3=True, steps=2,
                                  mesh=mesh4,
                                  resume=(relaid["params"],
                                          opt_state4))
        # training continues downhill from where world 8 left off
        assert losses4[-1] < losses8[0]

    def test_sliced_fsdp_leaf_is_refused(self, comm):
        """A part file's dim-sliced leaf must not re-enter relayout as
        if it were the assembled full leaf."""
        params = _mlp_params()
        ss = ShardedState(params, comm)
        placed = ss.place(params)
        opt_state = ss.init_opt_state(optax.adam(1e-2))
        table = ss.layouts()
        topo8 = topology_signature(comm, sharding="zero3",
                                   layouts=table)
        half = jax.tree.map(lambda a: np.asarray(jax.device_get(a)),
                            opt_state)
        sliced = {"opt_state": jax.tree.map(
            lambda a: a, half), "params": params}
        # slice one fsdp moment along its recorded dim
        mu = sliced["opt_state"][0].mu
        mu["l0"]["w"] = mu["l0"]["w"][:, :32]
        with pytest.raises(RelayoutError, match="assemble the covering"):
            relayout_state(sliced, topo8,
                           topology_signature(
                               SimpleNamespace(size=4, inter_size=1,
                                               mesh=None),
                               sharding="zero3"))

    def test_zero1_parts_keep_the_v1_format(self, comm):
        """Pure row-sharded sets still write format 1 — the on-disk
        contract PR 12 readers rely on."""
        from chainermn_tpu.training.optimizers import (
            zero1_init,
            zero1_optimizer,
        )

        params = {"w": jnp.zeros((5, 3))}
        opt = zero1_optimizer(optax.adam(1e-2), AX)
        state = zero1_init(opt, params, comm.mesh, AX)
        topo = topology_signature(comm, params, state, zero1=True)
        _, rec = ser.build_shard_part(
            {"params": params, "opt_state": state}, topo, 0, 4,
            root=True)
        assert rec["format"] == 1
        assert "fsdp_param_leaves" not in rec
