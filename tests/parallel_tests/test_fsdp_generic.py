"""Generic FSDP utilities (``parallel.fsdp``): dim selection, at-rest
specs, just-in-time gather — driven end-to-end on a hand-rolled MLP the
way a user model would, and checked against the replicated oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from chainermn_tpu.parallel import (
    MeshConfig,
    fsdp_dims,
    fsdp_gather,
    fsdp_specs,
)
from chainermn_tpu.training import shard_opt_state

from chainermn_tpu.testing import requires_vma as _requires_vma

# Pre-vma shard_map (old check_rep) cannot express what these tests pin:
# grads of replicated outputs taken inside shard_map over-count by the
# axis size, replicated out_specs can't be inferred through gathers, and
# scan carries may not gain replication.  vma typing (jax >= 0.7) is the
# semantic fix; on older jax the cases below are undefined, not wrong.
requires_vma = _requires_vma("requires vma-typed shard_map AD semantics")


def test_fsdp_dims_selection():
    params = {
        "w1": jnp.zeros((16, 64)),      # -> dim 1 (largest divisible)
        "w2": jnp.zeros((64, 16)),      # -> dim 0
        "b": jnp.zeros((7,)),           # 7 % 8 != 0 -> None
        "tiny": jnp.zeros((8,)),        # 8 == axis_size < min_size*8 -> None
        "scalar": jnp.zeros(()),        # -> None
    }
    dims = fsdp_dims(params, 8)
    assert dims == {"w1": 1, "w2": 0, "b": None, "tiny": None,
                    "scalar": None}


def test_fsdp_dims_skips_taken_dims():
    params = {"w": jnp.zeros((64, 64))}
    dims = fsdp_dims(params, 8, specs={"w": P("model", None)})
    assert dims == {"w": 1}
    with pytest.raises(ValueError, match="already sharded"):
        fsdp_specs(params, {"w": 0}, base_specs={"w": P("model", None)})


def test_fsdp_dims_skips_leaves_already_on_axis():
    # a leaf whose base spec already uses the FSDP axis (on any dim)
    # cannot take an FSDP dim — the axis may appear only once in a
    # PartitionSpec.  fsdp_dims(axis=...) skips it up front; without
    # axis=, fsdp_specs is the backstop that refuses the duplicate.
    params = {"w": jnp.zeros((64, 64)), "v": jnp.zeros((64, 64))}
    specs = {"w": P("data", None), "v": P("model", None)}
    dims = fsdp_dims(params, 8, specs=specs, axis="data")
    assert dims == {"w": None, "v": 1}
    out = fsdp_specs(params, dims, base_specs=specs)
    assert out == {"w": P("data", None), "v": P("model", "data")}
    with pytest.raises(ValueError, match="already appears"):
        fsdp_specs(params, {"w": 1, "v": None}, base_specs=specs)


def _mlp_init():
    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    return {
        "w1": jax.random.normal(k1, (16, 64), jnp.float32) * 0.25,
        "b1": jnp.zeros((64,)),
        "w2": jax.random.normal(k2, (64, 4), jnp.float32) * 0.125,
    }


def _train(use_fsdp, wire_dtype=None, steps=4):
    mc = MeshConfig(data=8)
    mesh = mc.mesh
    params = _mlp_init()
    dims = fsdp_dims(params, 8) if use_fsdp else jax.tree.map(
        lambda _: None, params)
    specs = fsdp_specs(params, dims)
    params = jax.tree.map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)),
        params, specs)
    opt = optax.adam(1e-2)
    opt_state = shard_opt_state(opt, params)

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(32, 16), jnp.float32)
    y = jnp.asarray(rng.randn(32, 4), jnp.float32)

    def loss_fn(p, xb, yb):
        full = fsdp_gather(p, dims, "data", wire_dtype=wire_dtype)
        h = jax.nn.relu(xb @ full["w1"] + full["b1"])
        return jnp.mean((h @ full["w2"] - yb) ** 2)

    # the make_train_step pattern: only the grad needs manual SPMD (the
    # gather wants a bound axis name); the elementwise optimiser update
    # runs under plain jit where XLA propagates the grads' shardings
    grad_fn = jax.shard_map(
        lambda p, xb, yb: jax.value_and_grad(
            lambda q: jax.lax.pmean(loss_fn(q, xb, yb), "data"))(p),
        mesh=mesh,
        in_specs=(specs, P("data"), P("data")),
        out_specs=(P(), specs),
    )

    @jax.jit
    def step(p, s, xb, yb):
        loss, g = grad_fn(p, xb, yb)
        u, s = opt.update(g, s, p)
        return optax.apply_updates(p, u), s, loss
    losses = []
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, x, y)
        losses.append(float(loss))
    return losses, jax.tree.map(
        lambda a: np.asarray(jax.device_get(a)), params), params


@requires_vma
def test_fsdp_mlp_matches_replicated():
    losses_d, final_d, _ = _train(False)
    losses_f, final_f, placed = _train(True)
    np.testing.assert_allclose(losses_f, losses_d, rtol=1e-5, atol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            a, b, rtol=2e-5, atol=2e-5),
        final_f, final_d)


def test_fsdp_mlp_at_rest_and_moments_sharded():
    _, _, placed = _train(True, steps=1)
    # w1 (16, 64) shards dim 1; each device holds 64/8 columns
    assert placed["w1"].addressable_shards[0].data.shape == (16, 8)
    opt_state = shard_opt_state(optax.adam(1e-2), placed)
    assert opt_state[0].mu["w1"].addressable_shards[0].data.shape \
        == (16, 8)


@requires_vma
def test_fsdp_mlp_bf16_wire_trains():
    losses, _, _ = _train(True, wire_dtype=jnp.bfloat16, steps=6)
    assert losses[-1] < losses[0]


def test_shard_opt_state_bare_array_params():
    """A bare jax.Array as the whole params 'tree': the state paths'
    EMPTY suffix must match it (regression: the suffix walk used to
    stop before the empty suffix and silently replicated the moments)."""
    mc = MeshConfig(data=8)
    p = jax.device_put(jnp.zeros((16, 64)),
                       NamedSharding(mc.mesh, P(None, "data")))
    state = shard_opt_state(optax.adam(1e-2), p)
    assert state[0].mu.addressable_shards[0].data.shape == (16, 8)
