"""Link-layer tests — analogue of the reference's ``link_tests`` battery
(sync BN numerical parity vs single-device BN over the whole batch;
MultiNodeChainList forward/backward vs a local sequential run).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from chainermn_tpu.communicators._mesh_utils import make_world_mesh
from chainermn_tpu.links import (
    MultiNodeChainList,
    init_batch_norm,
    multi_node_batch_normalization,
)

from chainermn_tpu.testing import requires_vma as _requires_vma

# Pre-vma shard_map (old check_rep) cannot express what these tests pin:
# grads of replicated outputs taken inside shard_map over-count by the
# axis size, replicated out_specs can't be inferred through gathers, and
# scan carries may not gain replication.  vma typing (jax >= 0.7) is the
# semantic fix; on older jax the cases below are undefined, not wrong.
requires_vma = _requires_vma("requires vma-typed shard_map AD semantics")

AX = "world"


@pytest.fixture(scope="module")
def mesh():
    return make_world_mesh(axis_name=AX)


def smap(mesh, fn, in_specs, out_specs):
    return jax.jit(
        jax.shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs))


class TestMultiNodeBatchNorm:
    def _local_bn(self, params, x, eps=2e-5):
        mean = x.mean(axis=tuple(range(x.ndim - 1)))
        var = x.var(axis=tuple(range(x.ndim - 1)))
        inv = params["gamma"] / np.sqrt(var + eps)
        return (x - mean) * inv + params["beta"]

    @pytest.mark.parametrize("shape", [(32, 6), (16, 4, 4, 3)])
    def test_matches_global_batch(self, mesh, shape):
        """BN over an 8-way-sharded batch == BN over the whole batch."""
        n = mesh.devices.size
        rng = np.random.RandomState(0)
        x = rng.randn(*shape).astype(np.float32) * 3 + 1
        params, state = init_batch_norm(shape[-1])

        def fn(xs):
            y, new_state = multi_node_batch_normalization(
                params, state, xs, axis_name=AX)
            return y, new_state

        y, new_state = smap(
            mesh, fn, in_specs=P(AX), out_specs=(P(AX), P()))(x)
        np.testing.assert_allclose(
            np.asarray(y), self._local_bn(params, x), rtol=2e-4, atol=2e-5)
        # running stats moved toward the global batch stats
        exp_mean = 0.1 * x.mean(axis=tuple(range(x.ndim - 1)))
        np.testing.assert_allclose(np.asarray(new_state.mean), exp_mean,
                                   rtol=1e-4, atol=1e-5)
        assert int(new_state.n) == 1
        assert x.shape[0] % n == 0

    def test_inference_uses_running_stats_no_collective(self, mesh):
        params, state = init_batch_norm(5)
        state = state._replace(mean=jnp.full((5,), 2.0),
                               var=jnp.full((5,), 4.0))
        x = np.random.RandomState(1).randn(8, 5).astype(np.float32)
        # train=False path never touches axis_name → runs outside shard_map
        y, new_state = multi_node_batch_normalization(
            params, state, jnp.asarray(x), axis_name=None, train=False)
        np.testing.assert_allclose(
            np.asarray(y), (x - 2.0) / np.sqrt(4.0 + 2e-5),
            rtol=1e-4, atol=1e-5)
        assert new_state is state

    @requires_vma
    def test_gradients_flow(self, mesh):
        params, state = init_batch_norm(4)
        x = np.random.RandomState(2).randn(16, 4).astype(np.float32)

        def loss(p, xs):
            y, _ = multi_node_batch_normalization(p, state, xs, axis_name=AX)
            return jax.lax.pmean(jnp.sum(y**2) , AX)

        g = smap(mesh, jax.grad(loss), in_specs=(P(), P(AX)),
                 out_specs=P())(params, x)
        assert np.isfinite(np.asarray(g["gamma"])).all()
        assert np.isfinite(np.asarray(g["beta"])).all()


def _dense_init(shape, seed):
    def init(key):
        del key
        rng = np.random.RandomState(seed)
        return {"w": jnp.asarray(rng.randn(*shape).astype(np.float32) * 0.1),
                "b": jnp.zeros((shape[1],), jnp.float32)}
    return init


def _dense_apply(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


class TestMultiNodeChainList:
    def _build(self, n_stage=3):
        mn = MultiNodeChainList(axis_name=AX)
        dims = [6, 5, 4, 3][: n_stage + 1]
        for i in range(n_stage):
            mn.add_link(
                _dense_init((dims[i], dims[i + 1]), seed=i), _dense_apply,
                owner=i,
                rank_in=None if i == 0 else i - 1,
                rank_out=None if i == n_stage - 1 else i + 1)
        return mn

    def test_forward_matches_sequential(self, mesh):
        mn = self._build()
        params = mn.init(jax.random.key(0))
        x = np.random.RandomState(3).randn(4, 6).astype(np.float32)

        y = smap(mesh, lambda xs: mn.apply(params, xs),
                 in_specs=P(), out_specs=P())(x)

        ref = jnp.asarray(x)
        for p in params:
            ref = _dense_apply(p, ref)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)

    def test_backward_matches_sequential(self, mesh):
        mn = self._build()
        params = mn.init(jax.random.key(0))
        x = np.random.RandomState(4).randn(4, 6).astype(np.float32)

        def dist_loss(ps, xs):
            return jnp.sum(mn.apply(ps, xs) ** 2)

        def local_loss(ps, xs):
            h = xs
            for p in ps:
                h = _dense_apply(p, h)
            return jnp.sum(h**2)

        g = smap(mesh,
                 lambda ps, xs: mn.reduce_grads(jax.grad(dist_loss)(ps, xs)),
                 in_specs=(P(), P()), out_specs=P())(params, x)
        g_ref = jax.grad(local_loss)(params, jnp.asarray(x))
        for a, b in zip(jax.tree.leaves(g), jax.tree.leaves(g_ref)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_multi_input_component(self, mesh):
        """Branch/join DAG: rank 0 fans out to ranks 1 and 2; rank 3 joins
        with rank_in=[1, 2] — the reference's list-valued rank_in."""
        mn = MultiNodeChainList(axis_name=AX)
        mn.add_link(_dense_init((4, 4), 0), _dense_apply,
                    owner=0, rank_out=[1, 2])
        mn.add_link(_dense_init((4, 4), 1), _dense_apply,
                    owner=1, rank_in=0, rank_out=3)
        mn.add_link(_dense_init((4, 4), 2), _dense_apply,
                    owner=2, rank_in=0, rank_out=3)
        mn.add_link(
            _dense_init((4, 4), 3),
            lambda p, a, b: _dense_apply(p, a + b),
            owner=3, rank_in=[1, 2])
        params = mn.init(jax.random.key(0))
        x = np.random.RandomState(5).randn(2, 4).astype(np.float32)

        y = smap(mesh, lambda xs: mn.apply(params, xs),
                 in_specs=P(), out_specs=P())(x)

        h0 = _dense_apply(params[0], jnp.asarray(x))
        h1 = _dense_apply(params[1], h0)
        h2 = _dense_apply(params[2], h0)
        ref = _dense_apply(params[3], h1 + h2)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)

    def test_unconsumed_message_raises(self, mesh):
        mn = MultiNodeChainList(axis_name=AX)
        mn.add_link(_dense_init((4, 4), 0), _dense_apply,
                    owner=0, rank_out=1)
        mn.add_link(_dense_init((4, 4), 1), _dense_apply,
                    owner=1, rank_in=None)  # never consumes 0→1
        params = mn.init(jax.random.key(0))
        with pytest.raises(ValueError, match="unconsumed"):
            smap(mesh, lambda xs: mn.apply(params, xs),
                 in_specs=P(), out_specs=P())(
                     np.zeros((2, 4), np.float32))

    def test_missing_message_raises(self, mesh):
        mn = MultiNodeChainList(axis_name=AX)
        mn.add_link(_dense_init((4, 4), 0), _dense_apply,
                    owner=0, rank_in=7)
        params = mn.init(jax.random.key(0))
        with pytest.raises(ValueError, match="no pending message"):
            smap(mesh, lambda xs: mn.apply(params, xs),
                 in_specs=P(), out_specs=P())(
                     np.zeros((2, 4), np.float32))
