"""create_multi_node_n_step_rnn — the layer-split multi-rank RNN must
match a sequential (single-"rank") run of the same stack exactly, in
forward and backward, and masked pad steps must carry state through."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from chainermn_tpu.communicators._mesh_utils import make_world_mesh
from chainermn_tpu.links import create_multi_node_n_step_rnn
from chainermn_tpu.links.n_step_rnn import _stage_apply

AX = "world"
B, T, D_IN, D_H = 4, 6, 5, 8


@pytest.fixture(scope="module")
def mesh():
    return make_world_mesh(axis_name=AX)


def smap(mesh, fn, in_specs, out_specs):
    return jax.jit(
        jax.shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs))


def _data(seed=0, ragged=False):
    rng = np.random.RandomState(seed)
    xs = rng.randn(B, T, D_IN).astype(np.float32)
    if ragged:
        lens = rng.randint(2, T + 1, size=B)
        mask = (np.arange(T)[None, :] < lens[:, None]).astype(np.float32)
        xs = xs * mask[:, :, None]
    else:
        mask = np.ones((B, T), np.float32)
    return jnp.asarray(xs), jnp.asarray(mask)


def _oracle(params_list, xs, mask, cell):
    """Sequential run: concatenate every stage's layers into one stack."""
    layers = [p for stage in params_list for p in stage]
    return _stage_apply(layers, xs, mask, cell)


@pytest.mark.parametrize("cell", ["lstm", "gru", "tanh"])
@pytest.mark.parametrize("ragged", [False, True])
def test_forward_matches_sequential(mesh, cell, ragged):
    chain = create_multi_node_n_step_rnn(
        4, D_IN, D_H, n_stages=4, cell=cell, axis_name=AX)
    params = chain.init(jax.random.PRNGKey(0))
    xs, mask = _data(ragged=ragged)

    ys, hy, cy = smap(
        mesh, lambda x, m: chain.apply(params, (x, m)),
        in_specs=(P(), P()), out_specs=P())(xs, mask)
    o_ys, o_hy, o_cy = _oracle(params, xs, mask, cell)

    np.testing.assert_allclose(np.asarray(ys), np.asarray(o_ys),
                               rtol=1e-5, atol=1e-6)
    # chain returns the LAST stage's (1-layer) final states
    np.testing.assert_allclose(np.asarray(hy), np.asarray(o_hy[-1:]),
                               rtol=1e-5, atol=1e-6)
    if cell == "lstm":
        np.testing.assert_allclose(np.asarray(cy), np.asarray(o_cy[-1:]),
                                   rtol=1e-5, atol=1e-6)


def test_backward_matches_sequential(mesh):
    chain = create_multi_node_n_step_rnn(
        4, D_IN, D_H, n_stages=2, cell="lstm", axis_name=AX)
    params = chain.init(jax.random.PRNGKey(1))
    xs, mask = _data(seed=3, ragged=True)

    def dist_loss(params, x, m):
        ys, _, _ = chain.apply(params, (x, m))
        return jnp.sum(ys ** 2)

    def dist_grads(params, x, m):
        g = jax.grad(dist_loss)(params, x, m)
        return chain.reduce_grads(g)

    g_dist = smap(mesh, dist_grads, in_specs=(P(), P(), P()),
                  out_specs=P())(params, xs, mask)

    def seq_loss(params):
        ys, _, _ = _oracle(params, xs, mask, "lstm")
        return jnp.sum(ys ** 2)

    g_seq = jax.grad(seq_loss)(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
        g_dist, g_seq)


def test_mask_carries_state_through_pads(mesh):
    """Final states of a padded sequence == final states of its truncated
    dense version (the ragged-NStepLSTM contract)."""
    chain = create_multi_node_n_step_rnn(
        2, D_IN, D_H, n_stages=2, cell="lstm", axis_name=AX)
    params = chain.init(jax.random.PRNGKey(2))

    rng = np.random.RandomState(5)
    t_real = 3
    xs_short = rng.randn(B, t_real, D_IN).astype(np.float32)
    xs_pad = np.concatenate(
        [xs_short, rng.randn(B, T - t_real, D_IN).astype(np.float32)],
        axis=1)
    mask_pad = np.concatenate(
        [np.ones((B, t_real), np.float32),
         np.zeros((B, T - t_real), np.float32)], axis=1)

    run = smap(mesh, lambda x, m: chain.apply(params, (x, m)),
               in_specs=(P(), P()), out_specs=P())
    _, hy_pad, cy_pad = run(jnp.asarray(xs_pad), jnp.asarray(mask_pad))
    _, hy_short, cy_short = run(
        jnp.asarray(xs_short), jnp.ones((B, t_real), jnp.float32))

    np.testing.assert_allclose(np.asarray(hy_pad), np.asarray(hy_short),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(cy_pad), np.asarray(cy_short),
                               rtol=1e-5, atol=1e-6)


def test_uneven_layer_split():
    chain = create_multi_node_n_step_rnn(5, D_IN, D_H, n_stages=3)
    params = chain.init(jax.random.PRNGKey(0))
    assert [len(p) for p in params] == [2, 2, 1]
    # first layer consumes d_in, all others d_hidden
    assert params[0][0]["w"].shape == (D_IN, 4 * D_H)
    assert params[0][1]["w"].shape == (D_H, 4 * D_H)


def test_validation():
    with pytest.raises(ValueError, match="cell"):
        create_multi_node_n_step_rnn(2, 4, 4, 2, cell="conv")
    with pytest.raises(ValueError, match="n_stages"):
        create_multi_node_n_step_rnn(2, 4, 4, 3)
