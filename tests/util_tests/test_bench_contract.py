"""The driver-gate contract for every bench script: print exactly ONE
JSON line with {"metric", "value", "unit", "vs_baseline"} — measured
values on success, value=null + an "error" diagnosis on failure — and
exit 0 either way.  A bench that crashes without JSON wastes an entire
round (round 1's BENCH_r01.json was a stack trace)."""

import json
import os
import subprocess
import sys

import pytest

_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", ".."))


def _run(script, args, timeout=600):
    env = dict(os.environ)
    for k in list(env):
        if k.startswith(("TPU_", "LIBTPU", "PJRT_", "JAX_", "XLA_")):
            env.pop(k)
    env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, os.path.join(_ROOT, script), *args],
        capture_output=True, text=True, timeout=timeout, cwd=_ROOT,
        env=env)
    return proc


def _assert_contract(proc, expect_value):
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, f"expected ONE JSON line, got: {lines}"
    rec = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in rec, rec
    if expect_value:
        assert rec["value"] is not None and rec["value"] > 0, rec
    else:
        assert rec["value"] is None and "error" in rec, rec
    return rec


def test_bench_resnet_success_contract():
    rec = _assert_contract(
        _run("bench.py", ["--platform", "cpu", "--batch", "4",
                          "--image", "32", "--warmup", "1",
                          "--iters", "2", "--timeouts", "420"]),
        expect_value=True)
    assert rec["unit"] == "images/sec/chip"


def test_bench_failure_still_prints_json():
    # an unknown platform makes the child crash fast; the parent must
    # still emit the one-line diagnosis and exit 0
    rec = _assert_contract(
        _run("bench.py", ["--platform", "definitely-not-a-backend",
                          "--timeouts", "120"]),
        expect_value=False)
    assert "attempt" in rec["error"]


@pytest.mark.parametrize("script,args,unit", [
    ("bench_transformer.py",
     ["--batch", "2", "--seq", "32", "--d-model", "32", "--n-layers", "1",
      "--n-heads", "2", "--warmup", "0", "--iters", "1",
      "--attention", "local"], "tokens/sec/chip"),
    ("bench_decode.py",
     ["--batch", "2", "--max-len", "32", "--n-layers", "1",
      "--d-model", "64", "--warmup", "0", "--iters", "1"], "tokens/sec"),
    ("bench_attention.py",
     ["--seq", "64", "--batch", "1", "--iters", "1"], "x"),
    ("bench_seq2seq.py",
     ["--batch", "8", "--vocab", "64", "--units", "16", "--max-src", "8",
      "--max-tgt", "8", "--warmup", "0", "--iters", "1",
      "--steps-per-call", "2"], "tokens/sec"),
], ids=["transformer", "decode", "attention", "seq2seq"])
def test_other_benches_contract(script, args, unit):
    rec = _assert_contract(
        _run(script, ["--platform", "cpu", *args, "--timeouts", "420"]),
        expect_value=True)
    assert rec["unit"] == unit
