"""The driver-gate contract for every bench script: print exactly ONE
JSON line with {"metric", "value", "unit", "vs_baseline"} — measured
values on success, value=null + an "error" diagnosis on failure — and
exit 0 either way.  A bench that crashes without JSON wastes an entire
round (round 1's BENCH_r01.json was a stack trace).

Real-hardware runs (no ``--platform`` override) additionally go through
the freshest-good measurement cache: success is recorded to
``BENCH_MEASURED.json`` with a timestamp, and a live failure emits the
freshest cached value for the metric with ``cached: true`` + the live
error instead of null (rounds 1 AND 2 recorded value=null because the
axon init hang outlasts any gate timeout).  Pinned-platform runs (all
the smoke tests here) bypass the cache entirely in both directions —
a toy CPU number must never pose as a hardware measurement, and a
smoke failure must report its own error."""

import json
import os
import subprocess
import sys

import pytest

_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", ".."))


def _run(script, args, timeout=600):
    env = dict(os.environ)
    for k in list(env):
        if k.startswith(("TPU_", "LIBTPU", "PJRT_", "JAX_", "XLA_")):
            env.pop(k)
    env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, os.path.join(_ROOT, script), *args],
        capture_output=True, text=True, timeout=timeout, cwd=_ROOT,
        env=env)
    return proc


def _assert_contract(proc, expect_value):
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, f"expected ONE JSON line, got: {lines}"
    rec = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in rec, rec
    if expect_value:
        assert rec["value"] is not None and rec["value"] > 0, rec
    else:
        assert rec["value"] is None and "error" in rec, rec
    return rec


def test_bench_resnet_success_contract():
    rec = _assert_contract(
        _run("bench.py", ["--platform", "cpu", "--batch", "4",
                          "--image", "32", "--warmup", "1",
                          "--iters", "2", "--timeouts", "420"]),
        expect_value=True)
    assert rec["unit"] == "images/sec/chip"


def test_bench_failure_still_prints_json():
    # an unknown platform makes the child crash fast; the parent must
    # still emit the one-line diagnosis and exit 0
    rec = _assert_contract(
        _run("bench.py", ["--platform", "definitely-not-a-backend",
                          "--timeouts", "120"]),
        expect_value=False)
    assert "attempt" in rec["error"]


def test_cache_records_and_falls_back(tmp_path, monkeypatch, capsys):
    """Real-platform semantics, driven through run_child_with_retries
    against a scratch cache: a success is recorded with a timestamp; a
    later total failure emits that cached value with cached:true + the
    live error; with no cache entry the failure stays value=null."""
    sys.path.insert(0, _ROOT)
    try:
        import _bench_common as bc
    finally:
        sys.path.pop(0)
    monkeypatch.setattr(bc, "CACHE_PATH", str(tmp_path / "cache.json"))

    ok_cmd = [sys.executable, "-c",
              "print('BENCH_RESULT ' + '{\"metric\": \"m\", \"value\": "
              "7.5, \"unit\": \"u\", \"vs_baseline\": 1.5}')"]
    bad_cmd = [sys.executable, "-c", "raise SystemExit(3)"]

    # no cache yet -> failure reports null + error
    assert bc.run_child_with_retries(bad_cmd, str(tmp_path), [30],
                                     "m", "u") == 0
    rec = json.loads(capsys.readouterr().out.strip())
    assert rec["value"] is None and "error" in rec

    # success records a timestamped entry
    assert bc.run_child_with_retries(ok_cmd, str(tmp_path), [30],
                                     "m", "u") == 0
    assert json.loads(capsys.readouterr().out.strip())["value"] == 7.5
    assert bc.freshest_cached("m")["timestamp"]
    assert bc.freshest_cached("other-metric") is None

    # failure now falls back to the cached value
    assert bc.run_child_with_retries(bad_cmd, str(tmp_path), [30],
                                     "m", "u") == 0
    rec = json.loads(capsys.readouterr().out.strip())
    assert rec["value"] == 7.5 and rec["cached"] is True
    assert rec["cached_timestamp"] and "live_error" in rec

    # pinned-platform semantics: use_cache=False neither records nor
    # falls back
    assert bc.run_child_with_retries(bad_cmd, str(tmp_path), [30],
                                     "m", "u", use_cache=False) == 0
    rec = json.loads(capsys.readouterr().out.strip())
    assert rec["value"] is None and "cached" not in rec

    # workload matching: a mismatched recorded field refuses the entry
    # (a toy hardware debug run can't stand in for the gate workload);
    # a field the entry never recorded passes (legacy leniency)
    bc.record_measurement({"metric": "m", "value": 9.0, "unit": "u",
                           "vs_baseline": 1.0, "batch": 4})
    assert bc.freshest_cached("m", {"batch": 4})["value"] == 9.0
    assert bc.freshest_cached("m", {"batch": 256})["value"] == 7.5
    assert bc.freshest_cached("m", {"image": 224})["value"] == 9.0

    # freshness bound: a timestamped entry older than the max age is
    # skipped; an untimestamped (legacy) entry passes
    bc.record_measurement({"metric": "old", "value": 1.0, "unit": "u",
                           "vs_baseline": 1.0,
                           "timestamp": "2020-01-01T00:00:00+00:00"})
    assert bc.freshest_cached("old") is None
    cache = json.load(open(bc.CACHE_PATH))
    cache["runs"].append({"metric": "old", "value": 2.0, "unit": "u",
                          "vs_baseline": 1.0})
    json.dump(cache, open(bc.CACHE_PATH, "w"))
    assert bc.freshest_cached("old")["value"] == 2.0


@pytest.mark.parametrize("script,args,unit", [
    ("bench_transformer.py",
     ["--batch", "2", "--seq", "32", "--d-model", "32", "--n-layers", "1",
      "--n-heads", "2", "--warmup", "0", "--iters", "1",
      "--attention", "local"], "tokens/sec/chip"),
    ("bench_decode.py",
     ["--batch", "2", "--max-len", "32", "--n-layers", "1",
      "--d-model", "64", "--warmup", "0", "--iters", "1"], "tokens/sec"),
    ("bench_attention.py",
     ["--seq", "64", "--batch", "1", "--iters", "1"], "x"),
    ("bench_seq2seq.py",
     ["--batch", "8", "--vocab", "64", "--units", "16", "--max-src", "8",
      "--max-tgt", "8", "--warmup", "0", "--iters", "1",
      "--steps-per-call", "2"], "tokens/sec"),
    ("bench_levers.py",
     ["--batch", "4", "--image", "32", "--warmup", "0",
      "--iters", "1"], "x"),
    ("bench_fused_allreduce.py",
     ["--n-layers", "4", "--d-model", "16", "--vocab", "256",
      "--rounds", "1", "--iters", "1"], "x"),
    ("bench_pipeline.py",
     ["--batch", "64", "--dim", "32", "--hidden", "64",
      "--host-delay-ms", "3", "--depth", "2", "--warmup", "1",
      "--iters", "4", "--rounds", "1"], "x"),
    ("bench_resilience.py",
     ["--batch", "64", "--dim", "32", "--hidden", "64", "--warmup", "1",
      "--iters", "4", "--rounds", "1"], "%"),
    ("bench_accum.py",
     ["--batch", "8", "--dim", "64", "--hidden", "128",
      "--accum-steps", "2", "--warmup", "1", "--iters", "3",
      "--rounds", "1"], "x"),
    ("bench_autotune.py",
     ["--n-layers", "4", "--d-model", "16", "--vocab", "256",
      "--trials", "1", "--rounds", "1", "--iters", "1",
      "--top-k", "4"], "x"),
    ("bench_plan_ir.py",
     ["--n-layers", "4", "--d-model", "16", "--vocab", "256",
      "--capacity", "4", "--slot-dim", "16", "--trials", "1",
      "--rounds", "1", "--iters", "1", "--top-k", "4"], "x"),
    ("bench_zero.py",
     ["--n-layers", "2", "--d-model", "64", "--vocab", "256",
      "--trials", "1", "--rounds", "1", "--iters", "1",
      "--top-k", "4"], "x"),
    ("bench_telemetry.py",
     ["--batch", "8", "--dim", "64", "--hidden", "128", "--warmup", "1",
      "--iters", "4", "--rounds", "1"], "x"),
    ("bench_metrics_registry.py",
     ["--batch", "8", "--dim", "64", "--hidden", "128", "--warmup", "1",
      "--iters", "4", "--rounds", "1"], "x"),
    ("bench_overlap.py",
     ["--batch", "8", "--dim", "48", "--hidden", "48", "--n-layers",
      "4", "--accum-steps", "2", "--warmup", "1", "--iters", "4",
      "--rounds", "1", "--trials", "1", "--min-frac", "0.4"], "x"),
    ("bench_overload.py",
     ["--requests", "12", "--slots", "8", "--horizon", "128",
      "--max-prompt", "16", "--block", "8", "--min-new", "4",
      "--max-new", "24", "--round-tokens", "2", "--d-model", "32",
      "--n-layers", "1", "--heads", "2", "--vocab", "64",
      "--rounds", "1"], "x"),
    ("bench_fleet.py",
     ["--replicas", "2", "--requests", "12", "--slots", "8",
      "--horizon", "128", "--max-prompt", "40", "--block", "8",
      "--shared-prefixes", "2", "--shared-prefix", "16",
      "--max-suffix", "4", "--min-new", "4", "--max-new", "16",
      "--round-tokens", "2", "--arrival-ms", "2.0",
      "--kill-at-step", "2", "--d-model", "32", "--n-layers", "1",
      "--heads", "2", "--vocab", "64", "--rounds", "1"], "x"),
    ("bench_elastic.py",
     ["--dim", "64", "--hidden", "64", "--batch", "16",
      "--rounds", "1"], "x"),
    ("bench_live_elastic.py",
     ["--dim", "64", "--hidden", "64", "--batch", "16",
      "--iters", "3", "--rounds", "1"], "x"),
    ("bench_obs_plane.py",
     ["--requests", "8", "--slots", "8", "--horizon", "128",
      "--max-prompt", "16", "--block", "8", "--min-new", "4",
      "--max-new", "12", "--round-tokens", "2", "--rounds", "1",
      "--reps", "1"], "x"),
    ("bench_programs.py",
     ["--batch", "8", "--dim", "64", "--hidden", "128", "--warmup",
      "1", "--iters", "4", "--rounds", "1"], "x"),
], ids=["transformer", "decode", "attention", "seq2seq", "levers",
        "fused_allreduce", "pipeline", "resilience", "accum",
        "autotune", "plan_ir", "zero", "telemetry", "metrics_registry", "overlap",
        "overload", "fleet", "elastic", "live_elastic", "obs_plane",
        "programs"])
def test_other_benches_contract(script, args, unit):
    rec = _assert_contract(
        _run(script, ["--platform", "cpu", *args, "--timeouts", "420"]),
        expect_value=True)
    assert rec["unit"] == unit


def test_serving_decode_tier_arms_contract():
    """The serving bench's contract row (ONE child covers the generic
    one-JSON-line contract, the ISSUE 14 decode-tier arms —
    prefix-share, sampled, speculative — AND the ragged-round arms:
    short-prompt TTFT independence under long-prompt co-admission,
    in-engine per-row speculation): exactness witnesses all zero,
    rates within range, self-draft acceptance exactly 1 (the
    machinery sanity anchor), and the in-run TTFT-independence assert
    must have held for the child to emit its line at all."""
    rec = _assert_contract(
        _run("bench_serving.py",
             ["--platform", "cpu", "--requests", "8", "--slots", "8",
              "--horizon", "128", "--max-prompt", "16", "--block", "8",
              "--min-new", "4", "--max-new", "24", "--round-tokens",
              "2", "--d-model", "32", "--n-layers", "1", "--heads",
              "2", "--vocab", "64", "--rounds", "1", "--decode-tier",
              "1", "--prefix-requests", "8", "--shared-prefix", "8",
              "--spec-prompts", "2", "--spec-new", "16",
              "--ragged-tier", "1", "--ragged-requests", "6",
              "--long-prompt", "48", "--ttft-noise-bar", "3.0",
              "--timeouts", "420"]),
        expect_value=True)
    for field in ("prefix_prefill_speedup", "prefix_hit_rate",
                  "prefix_pool_pressure_drop",
                  "prefix_share_peak_row_blocks",
                  "sampled_tokens_per_sec", "spec_tokens_per_sec",
                  "spec_acceptance_rate", "spec_vs_target_only",
                  "spec_selfdraft_acceptance_rate",
                  "ragged_short_ttft_solo_p50_ms",
                  "ragged_short_ttft_coadmit_p50_ms",
                  "lockstep_short_ttft_coadmit_p50_ms",
                  "ragged_ttft_coadmit_ratio",
                  "ragged_vs_lockstep_short_ttft",
                  "engine_spec_tokens_per_sec",
                  "engine_spec_vs_plain",
                  "engine_spec_acceptance_rate"):
        assert field in rec, field
    # the exactness ladder's bench-side witnesses
    assert rec["prefix_token_identity_mismatches"] == 0
    assert rec["sampled_replay_mismatches"] == 0
    assert rec["spec_identity_mismatches"] == 0
    assert rec["spec_selfdraft_identity_mismatches"] == 0
    assert rec["spec_selfdraft_acceptance_rate"] == 1.0
    assert 0.0 <= rec["prefix_hit_rate"] <= 1.0
    # ragged arms: per-row speculation may not move a token, long
    # co-admits staged through the chunk path
    assert rec["engine_spec_identity_mismatches"] == 0
    assert 0.0 <= rec["engine_spec_acceptance_rate"] <= 1.0
    assert rec["ragged_chunk_prefills"] >= 1


def test_breakdown_analyze_only_roofline():
    """--analyze-only: first-principles FLOPs/bytes with itemised
    terms, per-generation floors, and the headline claim SPEED.md
    leans on — the 300M bench config is COMPUTE-bound (intensity far
    past every TPU ridge), so no roofline ceiling excuses MFU."""
    import json
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, "bench_breakdown.py", "--platform", "cpu",
         "--analyze-only", "--no-record"],
        capture_output=True, text=True, timeout=300, cwd=_ROOT)
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["metric"] == "transformer_step_roofline"
    # terms must sum to the totals they itemise (GB rounding tolerance)
    assert abs(sum(rec["bytes_terms"].values()) * 1e9
               - rec["bytes"]) < 1e8
    f = rec["flops_terms"]
    assert rec["flops"] == pytest.approx(
        (1 + f["bwd_factor"] + f["remat_recompute_factor"])
        * (f["matmul_fwd"] + f["attention_fwd"]))
    for kind, roof in rec["rooflines"].items():
        assert roof["bound"] == "compute", (kind, roof)
        assert roof["mfu_ceiling"] == 1.0
        assert roof["step_floor_ms"] == roof["t_compute_ms"]
    assert rec["intensity_flops_per_byte"] > 1000


def test_decode_analyze_only_hbm_floor():
    """bench_decode --analyze-only: the analytic HBM decode floor
    behind SERVING.md's lever yardsticks — four quantization arms,
    int8 arms strictly faster (less HBM), parameter count matching
    the real initialized model's (pinned against the measured run's
    recorded n_params), and bytes consistent with the reported
    floor."""
    import json
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, "bench_decode.py", "--analyze-only"],
        capture_output=True, text=True, timeout=300, cwd=_ROOT)
    assert proc.returncode == 0, proc.stderr[-2000:]
    recs = [json.loads(l) for l in proc.stdout.strip().splitlines()
            if l.startswith("{")]
    assert len(recs) == 4
    by = {(r["int8"], r["kv_int8"]): r for r in recs}
    fp = by[(False, False)]
    assert fp["metric"] == "transformer_decode_hbm_floor_tokens_per_sec"
    # the eval_shape-derived parameter count equals the real model's
    # (the value the measured bench rows record)
    assert fp["n_params"] == 120_865_792
    # quantization strictly raises the floor, weights > cache at this
    # short context
    assert by[(True, False)]["value"] > fp["value"]
    assert by[(True, True)]["value"] > by[(True, False)]["value"]
    assert by[(False, True)]["value"] > fp["value"]
    assert fp["weight_bytes_gb"] > fp["cache_bytes_per_step_gb"]
    # floor arithmetic self-consistent: tokens/s = batch / step time
    step_s = (fp["weight_bytes_gb"] + fp["cache_bytes_per_step_gb"]) \
        / fp["hbm_gbps"]
    assert fp["value"] == pytest.approx(fp["batch"] / step_s, rel=0.01)
