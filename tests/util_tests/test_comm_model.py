"""comm_model: the HLO collective parser must recover the KNOWN byte
volumes of hand-built collectives, and the axis report must attribute a
DP step's gradient all-reduce to the data axis at parameter-count
scale."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from chainermn_tpu.parallel import MeshConfig
from chainermn_tpu.utils import (
    axis_collective_report,
    choose_prefetch_depth,
    collective_stats,
    stablehlo_collective_stats,
    wire_bytes_per_device,
)

from chainermn_tpu.testing import requires_vma as _requires_vma

# These two compile real model steps (bench.py's ResNet DP step, the
# flagship decode program); both need vma-typed shard_map — pre-vma
# check_rep can't infer their replicated out_specs / the transformer
# refuses to construct.
requires_vma = _requires_vma("compiled step requires vma-typed shard_map")


def _compile(fn, mesh, in_specs, out_specs, *args):
    return jax.jit(jax.shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
    )).lower(*args).compile()


def test_psum_bytes_counted():
    mc = MeshConfig(data=8)
    x = jnp.zeros((8, 128, 4), jnp.float32)
    compiled = _compile(
        lambda t: lax.psum(t, "data"), mc.mesh, P("data"), P(), x)
    stats = collective_stats(compiled)
    assert "all-reduce" in stats, stats
    st = stats["all-reduce"]
    # one all-reduce of the local (1,128,4) f32 block = 2048 bytes
    assert st.count == 1
    assert st.bytes == 128 * 4 * 4, st
    assert st.group_size == 8
    # ring wire cost: 2*s*(n-1)/n
    np.testing.assert_allclose(
        st.wire_bytes(), 2 * 2048 * 7 / 8)


def test_all_gather_and_permute_counted():
    mc = MeshConfig(data=8)
    x = jnp.zeros((8, 16), jnp.bfloat16)

    def f(t):
        g = lax.all_gather(t, "data", axis=0, tiled=True)   # (8,16) bf16
        p = lax.ppermute(t, "data",
                         perm=[(i, (i + 1) % 8) for i in range(8)])
        return jnp.reshape(
            jnp.sum(g.astype(jnp.float32))
            + jnp.sum(p.astype(jnp.float32)), (1,))

    compiled = _compile(f, mc.mesh, P("data"), P("data"), x)
    stats = collective_stats(compiled)
    # XLA may hoist the downstream f32 convert above the collective, so
    # the gathered tensor is (8,16) in bf16 OR f32 — both sizes valid
    assert stats["all-gather"].bytes in (8 * 16 * 2, 8 * 16 * 4), stats
    assert stats["all-gather"].count == 1
    assert stats["collective-permute"].count >= 1
    assert stats["collective-permute"].bytes >= 16 * 2


def test_stablehlo_region_ops_and_gather():
    """all_reduce/reduce_scatter carry a reduction REGION, so their
    result type sits on the region-closing line — the parser must not
    grab the replica_groups i64 attribute tensor instead."""
    mc = MeshConfig(data=8)
    x = jnp.zeros((8, 64, 4), jnp.float32)

    def f(t):
        s = lax.psum(t, "data")                     # all_reduce, region
        g = lax.all_gather(t, "data", axis=0, tiled=True)
        r = lax.psum_scatter(s, "data", scatter_dimension=1, tiled=True)
        return jnp.reshape(
            jnp.sum(s) + jnp.sum(g) + jnp.sum(r), (1,))

    txt = jax.jit(jax.shard_map(
        f, mesh=mc.mesh, in_specs=P("data"), out_specs=P("data"),
    )).lower(x).as_text()
    st = stablehlo_collective_stats(txt)
    # local block (1,64,4) f32 = 1024 B; all_gather result (8,64,4)
    assert st["all-reduce"].bytes == 64 * 4 * 4, st
    assert st["all-reduce"].group_size == 8
    assert st["all-gather"].bytes == 8 * 64 * 4 * 4, st
    # scattered result (1, 64/8, 4) f32
    assert st["reduce-scatter"].bytes == 8 * 4 * 4, st


def test_hlo_async_start_counts_result_only():
    """Async -start tuples carry (operand, result, context...); only
    the result buffer is the moved payload."""

    class Fake:
        def runtime_executable(self):
            raise RuntimeError("use as_text")

        def as_text(self):
            return (
                "  %ag = (f32[2,4], f32[16,4]) all-gather-start(%x), "
                "replica_groups={{0,1,2,3,4,5,6,7}}\n"
                "  %cp = (f32[2,4], f32[2,4], u32[], u32[]) "
                "collective-permute-start(%y), "
                "source_target_pairs={{0,1}}\n")

    st = collective_stats(Fake())
    assert st["all-gather"].bytes == 16 * 4 * 4, st
    assert st["collective-permute"].bytes == 2 * 4 * 4, st


def test_iota_replica_groups_and_unknown_size():
    from chainermn_tpu.utils.comm_model import CollectiveStats, _group_size

    assert _group_size("replica_groups=[8,1]<=[8]") == 1
    assert _group_size("replica_groups=[2,4]<=[8]") == 4
    assert _group_size("no groups here") is None
    st = CollectiveStats("all-reduce", count=1, bytes=100)
    with pytest.raises(ValueError, match="group size unknown"):
        st.wire_bytes()
    assert st.wire_bytes(axis_size=4) == 150.0


def test_choose_prefetch_depth():
    # device-bound: double buffering suffices no matter how cheap the
    # host is — extra depth is pure memory
    assert choose_prefetch_depth(0.0, 0.010) == 2
    assert choose_prefetch_depth(0.010, 0.010) == 2
    assert choose_prefetch_depth(0.002, 0.010) == 2
    # host-bound: budget ceil(rho * (1 + jitter)) + 1 slots of
    # burstiness absorption, clamped
    d3 = choose_prefetch_depth(0.015, 0.010)          # rho 1.5
    d6 = choose_prefetch_depth(0.030, 0.010)          # rho 3
    assert 2 < d3 <= d6 <= 8
    assert choose_prefetch_depth(1.0, 0.001) == 8     # clamps at max
    assert choose_prefetch_depth(
        1.0, 0.001, max_depth=16) == 16
    # fp-noise around the boundary must not flip regimes
    assert choose_prefetch_depth(0.010 + 1e-12, 0.010) == 2
    # zero device time is legitimate profiler output (fully-overlapped
    # pipeline, first-probe iteration): host-bound limit, not a crash
    assert choose_prefetch_depth(0.01, 0.0) == 8
    assert choose_prefetch_depth(0.01, 0.0, max_depth=5) == 5
    # both zero: no evidence either way -> classic double buffering
    assert choose_prefetch_depth(0.0, 0.0) == 2
    with pytest.raises(ValueError):
        choose_prefetch_depth(-0.01, 0.01)
    with pytest.raises(ValueError):
        choose_prefetch_depth(0.01, -0.01)
    with pytest.raises(ValueError):
        choose_prefetch_depth(0.01, 0.01, min_depth=4, max_depth=2)
    # bad bounds must raise even on the zero-guard path
    with pytest.raises(ValueError):
        choose_prefetch_depth(0.01, 0.0, min_depth=4, max_depth=2)


def test_choose_accum_steps():
    from chainermn_tpu.utils import choose_accum_steps

    # nothing to amortise on a 1-member axis / an empty grad tree
    assert choose_accum_steps(1 << 30, 1, 0.001) == 1
    assert choose_accum_steps(0, 8, 0.001) == 1
    # a fast interconnect against slow microbatches needs no window
    assert choose_accum_steps(1 << 20, 8, 1.0) == 1
    # monotone: more gradient bytes (or faster microbatches) -> deeper
    # windows; always clamped to max_accum
    m_small = choose_accum_steps(16 << 20, 8, 1e-4)
    m_big = choose_accum_steps(256 << 20, 8, 1e-4)
    assert 1 <= m_small <= m_big <= 64
    assert m_big > 1
    assert choose_accum_steps(1 << 34, 8, 1e-6) == 64       # clamps
    assert choose_accum_steps(1 << 34, 8, 1e-6, max_accum=16) == 16
    # the M the model picks must actually beat per-microbatch exchange:
    # exchange time amortised over M is <= comm_fraction of compute
    grad_bytes, n, t_micro = 64 << 20, 8, 1e-3
    m = choose_accum_steps(grad_bytes, n, t_micro, comm_fraction=0.1)
    t_ex = 2.0 * grad_bytes * (n - 1) / (n * 90e9)
    assert m >= t_ex / (0.1 * t_micro) or m == 64
    with pytest.raises(ValueError):
        choose_accum_steps(-1, 8, 1e-3)
    with pytest.raises(ValueError):
        choose_accum_steps(1 << 20, 8, 0.0)
    with pytest.raises(ValueError):
        choose_accum_steps(1 << 20, 8, 1e-3, comm_fraction=0.0)
    with pytest.raises(ValueError):
        choose_accum_steps(1 << 20, 8, 1e-3, max_accum=0)


def test_looped_collectives_and_accum_assert():
    """A collective inside a lax.scan body must be tallied as looped;
    one outside must not — and assert_accum_collectives must accept the
    window-fused shape and reject the per-microbatch shape."""
    from chainermn_tpu.utils import assert_accum_collectives

    mc = MeshConfig(data=8)
    xs = jnp.zeros((4, 8, 16), jnp.float32)     # (M, batch, dim)

    def fused_shape(t):
        # accumulate locally, exchange once AFTER the scan
        acc, _ = lax.scan(lambda a, x: (a + jnp.sum(x, 0), 0.0),
                          jnp.zeros((16,), jnp.float32), t)
        return lax.pmean(acc, "data")

    def per_micro_shape(t):
        # exchange INSIDE the scan body: M collectives per window.  The
        # carry init is psummed once OUTSIDE so its pre-vma replication
        # type matches the in-loop psum's output (a rep-gaining carry
        # refuses to compile on old check_rep); the loop placement is
        # what the parser must see either way.
        a0 = lax.psum(jnp.zeros((16,), jnp.float32), "data")

        def body(a, x):
            g = lax.psum(jnp.sum(x, 0), "data")
            return a + g, 0.0
        acc, _ = lax.scan(body, a0, t)
        return acc

    fused = collective_stats(_compile(
        fused_shape, mc.mesh, P(None, "data"), P(), xs))
    assert fused["all-reduce"].count == 1
    assert fused["all-reduce"].looped == 0
    assert assert_accum_collectives(fused, 16 * 4, 4 << 20, extra=0) == 1

    micro = collective_stats(_compile(
        per_micro_shape, mc.mesh, P(None, "data"), P(), xs))
    assert micro["all-reduce"].count >= 1
    assert micro["all-reduce"].looped >= 1
    with pytest.raises(AssertionError, match="inside a while body"):
        assert_accum_collectives(micro, 16 * 4, 4 << 20, extra=0)

    # budget violation: a window that somehow exchanges more than the
    # fused budget must trip even with zero looped sites
    with pytest.raises(AssertionError, match="budget"):
        assert_accum_collectives(fused, 16 * 4, 4 << 20, extra=-1)

    # the StableHLO (pre-legalisation) parser must attribute loop
    # placement the same way, so dtype-true stats can't silently pass
    # the zero-looped check for a per-microbatch program
    def lower_text(fn):
        return jax.jit(jax.shard_map(
            fn, mesh=mc.mesh, in_specs=P(None, "data"), out_specs=P(),
        )).lower(xs).as_text()

    sh_fused = stablehlo_collective_stats(lower_text(fused_shape))
    assert sh_fused["all-reduce"].looped == 0
    sh_micro = stablehlo_collective_stats(lower_text(per_micro_shape))
    assert sh_micro["all-reduce"].looped >= 1, sh_micro


def test_wire_formulas():
    assert wire_bytes_per_device("all-reduce", 100, 1) == 0
    assert wire_bytes_per_device("all-reduce", 100, 4) == 150.0
    assert wire_bytes_per_device("all-gather", 100, 4) == 75.0
    assert wire_bytes_per_device("collective-permute", 100, 4) == 100.0
    with pytest.raises(ValueError):
        wire_bytes_per_device("broadcast", 1, 2)


@requires_vma
def test_bench_resnet_dp_step_single_reduce():
    """Regression pin for the SCALING.md finding: bench.py's DP step
    must all-reduce each gradient ONCE.  The pre-fix step pmean'd grads
    that shard_map AD had already psummed — parsed exactly 2.000x the
    parameter bytes; re-introducing any double reduce trips this."""
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
    try:
        import bench as rbench
    finally:
        sys.path.pop(0)
    from chainermn_tpu.models import ResNetConfig, init_resnet

    # width=16 keeps the invariant (volumes are width-proportional)
    # while cutting the dominant XLA compile cost on this 1-core host
    cfg = ResNetConfig(depth=50, num_classes=100, width=16,
                       dtype="bfloat16")
    mc = MeshConfig(data=8, devices=jax.devices()[:8])
    params, state = init_resnet(jax.random.PRNGKey(0), cfg)
    opt = optax.sgd(0.1, momentum=0.9)
    opt_state = jax.jit(opt.init)(params)
    step = rbench.make_step(mc, cfg, opt, steps_per_call=1)
    x = jax.device_put(jnp.zeros((16, 32, 32, 3), jnp.bfloat16),
                       mc.sharding("data"))
    y = jax.device_put(jnp.zeros((16,), jnp.int32), mc.sharding("data"))
    compiled = step.lower((params, state, opt_state), x, y).compile()
    st = collective_stats(compiled)["all-reduce"]
    pb = sum(p.size * p.dtype.itemsize for p in jax.tree.leaves(params))
    sb = sum(p.size * p.dtype.itemsize for p in jax.tree.leaves(state))
    # fp32 grads + BN-stat pmeans, with a few % slack for loss scalars;
    # a double reduce would land at ~2x
    assert st.bytes >= pb, (st.bytes, pb)
    assert st.bytes <= (pb + sb) * 1.05, \
        f"DP step moves {st.bytes} all-reduce bytes for {pb} param " \
        f"bytes (+{sb} state) — double gradient reduce reintroduced?"


def test_axis_report_attributes_dp_gradient_allreduce():
    """A pmean-grads DP step's dominant collective must be an
    all-reduce of ~n_params floats on the data axis."""
    n_in, n_out = 64, 32
    w = jnp.zeros((n_in, n_out), jnp.float32)
    n_params = n_in * n_out

    def build(axes):
        mc = MeshConfig(**axes, devices=jax.devices()[:8])
        x = jnp.zeros((8, 4, n_in), jnp.float32)
        y = jnp.zeros((8, 4, n_out), jnp.float32)

        def step(w, x, y):
            x, y = x[0], y[0]
            g = jax.grad(lambda q: jnp.mean((x @ q - y) ** 2))(w)
            return w - 0.1 * lax.pmean(g, "data")

        fn = jax.jit(jax.shard_map(
            step, mesh=mc.mesh,
            in_specs=(P(), P("data"), P("data")), out_specs=P()))
        return fn, (w, x, y)

    report = axis_collective_report(build, {"data": 8})
    st = report["data"]["stats"]["all-reduce"]
    # the gradient all-reduce moves >= the parameter bytes; jax's vma
    # plumbing may emit a second (redundant) all-reduce when an
    # invariant output consumes the pmean — both are genuinely in the
    # compiled ENTRY, so the parser must report them (a SCALING.md-level
    # analysis would flag the duplication, not hide it)
    assert st.bytes >= n_params * 4, st
    assert st.bytes <= n_params * 4 * 2, st
    assert st.group_size == 8
    assert report["data"]["wire_bytes_per_device"] >= \
        2 * n_params * 4 * 7 / 8


@requires_vma
def test_decode_program_parses_per_token_slices():
    """The decode factories expose their jitted program (`._jitted`) and
    the parser recovers the per-token collective slices the SCALING.md
    section-6 model is built on: a TP decode shows the 2-per-layer
    row-parallel psums at (B_local, 1, D) f32 — 2P whole units across
    the generation + prefill while bodies (scaling_report.py dec_tp)."""
    from chainermn_tpu.models import (
        TransformerConfig, init_transformer, make_generate_fn,
        shard_params,
    )

    B, P_len, MAX = 4, 5, 12
    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, d_head=8, d_ff=64,
        n_layers=2, max_seq=MAX, attention="local",
        pos_embedding="rope", dtype="float32", remat=False)
    mc = MeshConfig(model=2, data=2, devices=jax.devices()[:4])
    params = shard_params(
        mc, cfg, init_transformer(jax.random.PRNGKey(0), cfg))
    prompt = jnp.zeros((B, P_len), jnp.int32)
    gen = make_generate_fn(mc, cfg, max_len=MAX)
    stats = collective_stats(
        gen._jitted.lower(params, prompt, jax.random.PRNGKey(0))
        .compile())
    st = stats["all-reduce"]
    unit = (B // 2) * cfg.d_model * 4          # (B_local, 1, D) f32
    assert st.bytes == 2 * P_len * unit, (st, unit)
    assert st.group_size == 2


# ------------------------------------------------------------------ #
# backward-overlap proof machinery (PR 7)
# ------------------------------------------------------------------ #


class _FakeCompiled:
    def __init__(self, text):
        self._text = text

    def runtime_executable(self):
        raise RuntimeError("use as_text")

    def as_text(self):
        return self._text


def test_async_depth_pairs_start_done():
    """A -start whose -done is scheduled with other instructions
    between the halves overlaps compute (async_depth 1); a
    back-to-back start;done pair overlaps nothing (0)."""
    from chainermn_tpu.utils import collective_stats as cs

    txt = """ENTRY %main (a: f32[8]) -> f32[8] {
  %ar = f32[1024]{0} all-reduce-start(%x), replica_groups={{0,1,2,3,4,5,6,7}}
  %d1 = f32[64,64]{1,0} dot(%p, %q)
  %d2 = f32[64,64]{1,0} dot(%p, %r)
  %ar.d = f32[1024]{0} all-reduce-done(%ar)
  %ag = f32[512]{0} all-gather-start(%y), replica_groups={{0,1,2,3,4,5,6,7}}
  %ag.d = f32[512]{0} all-gather-done(%ag)
}
"""
    st = cs(_FakeCompiled(txt))
    assert st["all-reduce"].async_depth == 1
    assert st["all-gather"].async_depth == 0
    # counts unaffected by the pairing bookkeeping
    assert st["all-reduce"].count == 1
    assert st["all-gather"].count == 1


def test_assert_overlap_positions_and_min_bytes():
    from chainermn_tpu.utils import assert_overlap_collectives

    def prog(collective_lines_before, after):
        body = ["ENTRY %main (a: f32[8]) -> f32[8] {"]
        body += ["  %d0 = f32[64,64]{1,0} dot(%p, %q)"]
        body += collective_lines_before
        body += ["  %d1 = f32[64,64]{1,0} dot(%p, %r)"]
        body += after
        body += ["}"]
        return _FakeCompiled("\n".join(body))

    ar = ("  %ar{i} = f32[1024]{{0}} all-reduce(%x{i}), "
          "replica_groups={{{{0,1,2,3,4,5,6,7}}}}")
    tiny = ("  %t = f32[] all-reduce(%l), "
            "replica_groups={{0,1,2,3,4,5,6,7}}")

    # 1 of 2 big collectives inside, the 4-byte loss pmean ignored
    rep = assert_overlap_collectives(
        prog([ar.format(i=0)], [ar.format(i=1), tiny]))
    assert rep == {"inside": 1, "total": 2, "frac": 0.5,
                   "async_depth": 0}
    # all big collectives after the last dot -> clustered
    with pytest.raises(AssertionError, match="cluster"):
        assert_overlap_collectives(
            prog([], [ar.format(i=0), ar.format(i=1)]))
    # nothing above the byte floor -> nothing to prove
    with pytest.raises(AssertionError, match="nothing to prove"):
        assert_overlap_collectives(prog([], [tiny]))
    # compute-free program -> nothing to prove either
    with pytest.raises(AssertionError, match="nothing to prove"):
        assert_overlap_collectives(_FakeCompiled(
            "ENTRY %main (a: f32[8]) -> f32[8] {\n"
            + ar.format(i=0) + "\n}\n"))


def test_overlap_exposed_time_model():
    from chainermn_tpu.utils import overlap_exposed_time

    buckets = [1 << 20] * 4
    n = 8
    kw = dict(latency_s=1e-5, bandwidth_bytes_per_s=1e9)
    t_wire_each = 2 * 1e-5 + 2 * (1 << 20) * (7 / 8) / 1e9
    t_ex = 4 * t_wire_each

    # no backward to hide under: eager and deferred both pay full T_ex
    assert overlap_exposed_time(buckets, n, 0.0, **kw) == \
        pytest.approx(t_ex)
    assert overlap_exposed_time(buckets, n, 0.0,
                                modes=["deferred"] * 4, **kw) == \
        pytest.approx(t_ex)

    # a long backward: the eager stream hides everything but the LAST
    # bucket (ready only when backward ends); window-end (all
    # deferred) still pays the full serial T_ex
    t_bwd = 10 * t_ex
    eager = overlap_exposed_time(buckets, n, t_bwd, **kw)
    deferred = overlap_exposed_time(buckets, n, t_bwd,
                                    modes=["deferred"] * 4, **kw)
    assert eager == pytest.approx(t_wire_each)
    assert deferred == pytest.approx(t_ex)
    assert eager < deferred

    # degenerate inputs
    assert overlap_exposed_time([], n, 1.0) == 0.0
    assert overlap_exposed_time(buckets, 1, 1.0) == 0.0
    with pytest.raises(ValueError, match="modes"):
        overlap_exposed_time(buckets, n, 1.0, modes=["eager"])
    with pytest.raises(ValueError, match="mode"):
        overlap_exposed_time(buckets, n, 1.0,
                             modes=["eager", "soon", "eager", "eager"])


def test_async_depth_dotted_suffix_names_pair_exactly():
    """XLA's .N suffixing makes one start's name a PREFIX of another's
    — the done-line match must be exact-token, or the wrong start is
    popped and the real pair orphaned."""
    from chainermn_tpu.utils import collective_stats as cs

    txt = """ENTRY %main (a: f32[8]) -> f32[8] {
  %all-reduce-start = f32[256]{0} all-reduce-start(%x), replica_groups={{0,1,2,3,4,5,6,7}}
  %all-reduce-start.1 = f32[256]{0} all-reduce-start(%y), replica_groups={{0,1,2,3,4,5,6,7}}
  %d1 = f32[64,64]{1,0} dot(%p, %q)
  %done.1 = f32[256]{0} all-reduce-done(%all-reduce-start.1)
  %d2 = f32[64,64]{1,0} dot(%p, %r)
  %done.0 = f32[256]{0} all-reduce-done(%all-reduce-start)
}
"""
    st = cs(_FakeCompiled(txt))
    # both pairs straddle at least one other instruction
    assert st["all-reduce"].async_depth == 2


def test_overlap_exposed_time_per_bucket_launches():
    """Mixed-via schedules price their launch costs truthfully: an
    all-"ar" stream (1 launch/bucket) costs one latency less per
    bucket than the rs→ag default in the latency-dominated regime."""
    from chainermn_tpu.utils import overlap_exposed_time

    buckets = [1024] * 6
    kw = dict(latency_s=1e-3, bandwidth_bytes_per_s=1e12)
    rs = overlap_exposed_time(buckets, 8, 0.0, **kw)
    ar = overlap_exposed_time(buckets, 8, 0.0,
                              launches_per_bucket=[1] * 6, **kw)
    assert rs == pytest.approx(ar + 6 * 1e-3)
    with pytest.raises(ValueError, match="launch counts"):
        overlap_exposed_time(buckets, 8, 0.0, launches_per_bucket=[1])
