"""Compile-and-memory plane (ISSUE 15): the XLA program ledger —
signature-diff retrace attribution, ring bound, disabled-path
discipline, steady-state marking and the retrace-storm alert — plus
the device-memory accountant's gauges, watermarks and deterministic
cross-rank merge, the /programz surface, and GoodputReport's compile
badput category."""

import json
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from chainermn_tpu.utils import programs
from chainermn_tpu.utils.alerts import AlertManager
from chainermn_tpu.utils.metrics import (
    GoodputReport,
    Histogram,
    MetricsRegistry,
    get_registry,
    set_registry,
)
from chainermn_tpu.utils.programs import (
    MemoryAccountant,
    ProgramLedger,
    abstract_signature,
    instrument,
    ledger_jit,
    retrace_storm_rule,
    set_ledger,
    signature_diff,
)
from chainermn_tpu.utils.statusz import StatuszServer
from chainermn_tpu.utils.telemetry import TraceRecorder, set_recorder


@pytest.fixture()
def ledger():
    """A fresh enabled ledger installed as the global one (the
    instrumented wrappers resolve the global per call)."""
    led = ProgramLedger(enabled=True)
    prev = set_ledger(led)
    try:
        yield led
    finally:
        set_ledger(prev)


@pytest.fixture()
def registry():
    reg = MetricsRegistry(enabled=True)
    prev = set_registry(reg)
    try:
        yield reg
    finally:
        set_registry(prev)


class TestSignatures:
    def test_leaf_signature_forms(self):
        _, sig = abstract_signature(
            (jnp.ones((2, 3), jnp.float32), 7, 2.5))
        # device arrays render dtype[shape]@sharding — sharding is
        # part of jit's cache key, so it is part of the ledger's
        assert sig[0].startswith("float32[2,3]")
        assert sig[1].startswith("py:") and sig[2].startswith("py:")
        _, sig = abstract_signature((np.ones((4,), np.int32),))
        assert sig[0] == "int32[4]"     # host arrays: no sharding

    def test_diff_none_on_first_compile(self):
        assert signature_diff(None, ("float32[2]",)) is None

    def test_diff_dtype_vs_shape_vs_type(self):
        old = ("float32[4,4]", "int32[8]", "float32[2]", "py:int")
        new = ("bfloat16[4,4]", "int32[8,2]", "py:int", "py:float")
        d = signature_diff(old, new)
        assert d["kinds"] == ["dtype", "shape", "type"]
        assert d["n_changed"] == 4
        by_leaf = {c["leaf"]: c["kind"] for c in d["changed"]}
        # a python-scalar TYPE change (py:int → py:float) is "type",
        # never a misleading array-dtype attribution
        assert by_leaf == {0: "dtype", 1: "shape", 2: "type",
                           3: "type"}

    def test_diff_structure_and_donation(self):
        d = signature_diff(("f32[2]",), ("f32[2]", "f32[4]"),
                           old_donate=(0,), new_donate=())
        assert "structure" in d["kinds"] and "donation" in d["kinds"]
        assert d["donate_from"] == [0] and d["donate_to"] == []

    def test_diff_bounds_changed_list(self):
        old = tuple(f"float32[{i}]" for i in range(32))
        new = tuple(f"float32[{i + 1}]" for i in range(32))
        d = signature_diff(old, new, max_changed=8)
        assert d["n_changed"] == 32 and len(d["changed"]) == 8


class TestLedger:
    def test_retrace_attribution(self, ledger, registry):
        f = ledger_jit(lambda x: x * 2, label="toy/double")
        f(jnp.ones((4,), jnp.float32))
        f(jnp.ones((4,), jnp.float32))      # signature hit
        f(jnp.ones((8,), jnp.float32))      # shape retrace
        f(jnp.ones((8,), jnp.bfloat16))     # dtype retrace
        assert ledger.compiles() == 3
        entries = ledger.entries()          # newest first
        assert [e["n"] for e in entries] == [3, 2, 1]
        assert entries[0]["diff"]["kinds"] == ["dtype"]
        assert entries[1]["diff"]["kinds"] == ["shape"]
        assert entries[2]["diff"] is None
        stats = ledger.label_stats()["toy/double"]
        assert stats["compiles"] == 3 and stats["calls"] == 4
        assert stats["steady_compiles"] == 0 and stats["programs"] == 3
        assert stats["compile_s"] == pytest.approx(
            ledger.total_compile_s)
        assert ledger.compile_seconds("toy/") == pytest.approx(
            ledger.total_compile_s)
        assert ledger.compile_seconds("serve/") == 0.0
        # the metrics fan-out
        assert registry.counter("compile/retraces").value == 3
        assert registry.counter(
            "compile/retraces_toy_double").value == 3
        assert registry.counter("compile/calls").value == 4
        assert registry.histogram("compile/seconds").count == 3

    def test_python_scalar_value_change_is_not_a_retrace(self, ledger,
                                                         registry):
        f = ledger_jit(lambda x, n: x + n, label="toy/scalar")
        f(jnp.ones((2,)), 1)
        f(jnp.ones((2,)), 2)    # value change, same abstract signature
        assert ledger.compiles() == 1

    def test_keyword_arguments_supported(self, ledger, registry):
        """jit callables take kwargs, so the drop-in wrapper must too
        — enabled AND disabled — and a kwarg's signature rides the
        key (same shapes, same kwarg name → one compile)."""
        f = ledger_jit(lambda x, n: x + n, label="toy/kw")
        f(jnp.ones((2,)), n=jnp.ones((2,)))
        f(jnp.ones((2,)), n=jnp.ones((2,)))
        assert ledger.compiles() == 1
        f(jnp.ones((4,)), n=jnp.ones((4,)))     # shape retrace
        assert ledger.compiles() == 2
        ledger.disable()
        out = f(jnp.zeros((2,)), n=jnp.ones((2,)))
        assert float(out.sum()) == 2.0

    def test_sharding_retrace_is_visible(self, ledger, registry):
        """jit keys on input sharding, so the ledger must too: the
        same shape/dtype arriving committed to a different layout is
        a recorded retrace whose diff says 'sharding' — the stale-
        mesh-feed storm must never read as healthy."""
        if jax.device_count() < 2:
            pytest.skip("needs a multi-device mesh")
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = jax.sharding.Mesh(np.array(jax.devices()), ("d",))
        f = ledger_jit(lambda x: x + 1, label="toy/shard")
        x = jnp.ones((8, 8), jnp.float32)
        f(jax.device_put(x, NamedSharding(mesh, P())))
        f(jax.device_put(x, NamedSharding(mesh, P())))      # hit
        assert ledger.compiles() == 1
        f(jax.device_put(x, NamedSharding(mesh, P("d"))))   # relayout
        assert ledger.compiles() == 2
        assert ledger.entries()[0]["diff"]["kinds"] == ["sharding"]

    def test_treedef_only_retrace_reads_as_structure(self, ledger,
                                                     registry):
        """A dict-key rename keeps leaf count and leaf signatures
        identical but changes the treedef — the recorded diff must
        say 'structure', not render empty (an empty diff reads as 'a
        rebuild, not a shape leak' — the opposite attribution)."""
        f = ledger_jit(lambda d: d[next(iter(d))], label="toy/tree")
        f({"a": jnp.ones((2,))})
        f({"b": jnp.ones((2,))})        # same leaves, renamed key
        assert ledger.compiles() == 2
        diff = ledger.entries()[0]["diff"]
        assert diff["kinds"] == ["structure"]
        assert diff["n_changed"] == 0

    def test_failed_first_call_releases_the_claim(self, ledger,
                                                  registry):
        """A first call that raises never materialized a program: the
        signature claim is released so a later retry's compile is
        still recorded."""
        f = ledger_jit(lambda x: x.reshape((3, 3)), label="toy/boom")
        with pytest.raises(TypeError):
            f(jnp.ones((4,)))           # 4 elements can't be (3, 3)
        assert ledger.compiles() == 0
        g = ledger_jit(lambda x: x * 2, label="toy/boom")
        g(jnp.ones((4,)))               # retry shape is recorded
        assert ledger.compiles() == 1

    def test_compile_span_lands_in_recorder(self, ledger, registry):
        rec = TraceRecorder(enabled=True)
        prev = set_recorder(rec)
        try:
            f = ledger_jit(lambda x: x + 1, label="toy/span")
            f(jnp.ones((2,)))
        finally:
            set_recorder(prev)
        names = [e["name"] for e in rec.events()]
        assert "compile/toy/span" in names

    def test_exemplar_rides_compile_seconds(self, ledger, registry):
        ledger.exemplar = "trace-abc"
        f = ledger_jit(lambda x: x + 1, label="toy/exemplar")
        f(jnp.ones((2,)))
        ledger.exemplar = None
        ex = registry.histogram("compile/seconds").exemplar_for(50)
        assert ex is not None and ex[0] == "trace-abc"
        # without a staged exemplar the label itself is the link
        f(jnp.ones((4,)))
        ex = registry.histogram("compile/seconds").exemplar_for(50)
        assert ex[0] in ("trace-abc", "toy/exemplar")

    def test_ring_bound(self, ledger, registry):
        small = ProgramLedger(capacity=4, enabled=True)
        prev = set_ledger(small)
        try:
            f = ledger_jit(lambda x: x * 1, label="toy/ring")
            for n in range(1, 8):
                f(jnp.ones((n,)))
        finally:
            set_ledger(prev)
        assert len(small) == 4
        assert small.dropped == 3
        # counters survive the wrap — the seen-set is not ring-bounded
        assert small.compiles() == 7
        assert small.label_stats()["toy/ring"]["programs"] == 7

    def test_disabled_path_records_nothing(self, registry):
        led = ProgramLedger(enabled=False)
        prev = set_ledger(led)
        try:
            f = ledger_jit(lambda x: x + 1, label="toy/off")
            f(jnp.ones((2,)))
            f(jnp.ones((4,)))
        finally:
            set_ledger(prev)
        # the PR 6/9 singleton discipline: nothing allocated or
        # retained — no ring entries, no label state, no counters
        assert len(led) == 0
        assert led.label_stats() == {}
        assert led.total_compile_s == 0.0
        assert registry.counter("compile/calls").value == 0
        assert registry.histogram("compile/seconds").count == 0

    def test_attribute_delegation(self, ledger, registry):
        f = ledger_jit(lambda x: x + 1, label="toy/lower")
        compiled = f.lower(jnp.ones((2,))).compile()
        assert compiled is not None

    def test_enable_mid_run_starts_recording(self, registry):
        led = ProgramLedger(enabled=False)
        prev = set_ledger(led)
        try:
            f = ledger_jit(lambda x: x + 1, label="toy/late")
            f(jnp.ones((2,)))
            assert led.compiles() == 0
            led.enable()
            # already jit-cached, but the LEDGER never saw the
            # signature: recorded as a compile (the ledger answers
            # "would jit retrace", and for the invariant tests the
            # conservative read is the safe one)
            f(jnp.ones((2,)))
            assert led.compiles() == 1
            f(jnp.ones((2,)))
            assert led.compiles() == 1
        finally:
            set_ledger(prev)


class TestSteadyState:
    def test_mark_steady_scopes(self, ledger, registry):
        f = ledger_jit(lambda x: x + 1, label="serve/round")
        g = ledger_jit(lambda x: x - 1, label="train/step")
        f(jnp.ones((2,)))
        g(jnp.ones((2,)))
        ledger.mark_steady("serve/")
        f(jnp.ones((4,)))       # steady violation
        g(jnp.ones((4,)))       # train/ not marked: plain retrace
        assert ledger.steady_retraces() == 1
        assert ledger.steady_retraces("serve/") == 1
        assert ledger.steady_retraces("train/") == 0
        assert registry.counter("compile/steady_retraces").value == 1
        assert ledger.entries(1)[0]["steady"] is False  # train newest
        ledger.clear_steady("serve/")
        f(jnp.ones((6,)))
        assert ledger.steady_retraces() == 1    # withdrawn

    def test_forget_re_records_a_rebuild(self, ledger, registry):
        """forget(scope): a rebuilt program's compile at a
        previously-seen signature IS re-recorded (the rebind_world /
        engine-rebuild hook), counters stay monotonic, and the steady
        declaration is withdrawn so the rebuild window never counts
        as a retrace storm."""
        f = ledger_jit(lambda x: x + 1, label="train/step")
        f(jnp.ones((4,)))
        ledger.mark_steady("train/")
        assert ledger.compiles("train/") == 1
        ledger.forget("train/")
        assert not ledger.is_steady("train/step")
        # the "rebuild": a NEW jit of the same shape
        g = ledger_jit(lambda x: x + 1, label="train/step")
        g(jnp.ones((4,)))
        assert ledger.compiles("train/") == 2       # monotonic
        assert ledger.steady_retraces("train/") == 0
        entry = ledger.entries(scope="train/")[0]
        # diff reads vs the pre-rebuild signature: no change — the
        # attribution IS "a rebuild, not a shape leak"
        assert entry["diff"]["n_changed"] == 0

    def test_retrace_storm_alert_drill(self, ledger, registry):
        """The acceptance drill: an injected shape-churn workload
        fires the retrace-storm rule; the steady workload stays
        quiet.  Fake clock — hours of window history in
        microseconds."""
        rule = retrace_storm_rule(budget=0.001,
                                  windows=((600.0, 60.0, 2.0),))
        mgr = AlertManager([rule], registry=registry,
                           clock=lambda: 0.0, min_total=1)
        f = ledger_jit(lambda x: x * 2, label="serve/round")
        f(jnp.ones((4,)))               # warmup compile
        ledger.mark_steady("serve/")

        t = [0.0]
        mgr.clock = lambda: t[0]
        # steady phase: two windows of signature-identical traffic
        for _ in range(100):
            t[0] += 10.0
            f(jnp.ones((4,)))
            mgr.tick()
        assert mgr.firing() == ()

        # shape churn: every call a fresh signature — a retrace storm
        fired = []
        for n in range(5, 105):
            t[0] += 10.0
            f(jnp.ones((n,)))
            fired.extend(mgr.tick())
        assert "retrace-storm" in mgr.firing()
        assert any(e["transition"] == "fired" for e in fired)

        # the churn stops: both windows drain and the alert resolves
        resolved = []
        for _ in range(200):
            t[0] += 10.0
            f(jnp.ones((4,)))
            resolved.extend(mgr.tick())
        assert mgr.firing() == ()
        assert any(e["transition"] == "resolved" for e in resolved)


class TestMemoryAccountant:
    def test_gauges_and_watermarks(self, registry):
        acc = MemoryAccountant()
        state = {"w": jnp.ones((16, 16), jnp.float32)}
        acc.register("params", lambda: state)
        out = acc.sample(registry)
        assert out["params"] >= 16 * 16 * 4
        first = out["params"]
        g = registry.gauge("memory/params_bytes")
        assert g.last == first and g.max == first
        # shrink: last follows, watermark holds
        state["w"] = jnp.ones((4, 4), jnp.float32)
        out = acc.sample(registry)
        assert out["params"] < first
        g = registry.gauge("memory/params_bytes")
        assert g.last == out["params"] and g.max == first
        rows = {r["subsystem"]: r for r in acc.table()}
        assert rows["params"]["high_watermark"] == first
        assert rows["total"]["bytes"] == out["params"]

    def test_replication_counts_per_shard(self, registry):
        """A replicated sharded array holds one copy per device — the
        accountant reports DEVICE bytes, not logical bytes."""
        n_dev = jax.device_count()
        if n_dev < 2:
            pytest.skip("needs a multi-device mesh")
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = jax.sharding.Mesh(np.array(jax.devices()), ("d",))
        x = jax.device_put(jnp.ones((8, 8), jnp.float32),
                           NamedSharding(mesh, P()))
        acc = MemoryAccountant()
        acc.register("replicated", [x])
        out = acc.sample(registry)
        assert out["replicated"] == 8 * 8 * 4 * n_dev

    def test_broken_root_degrades(self, registry):
        acc = MemoryAccountant()

        def broken():
            raise RuntimeError("boom")

        acc.register("bad", broken)
        out = acc.sample(registry)
        assert out["bad"] == 0
        rows = {r["subsystem"]: r for r in acc.table()}
        assert "boom" in rows["bad"]["error"]

    def test_cross_rank_merge_determinism(self):
        """Memory gauges merge max-of-{last,max}: folding the same
        per-rank snapshots in ANY order yields one identical merged
        registry — the rank-0-exposition safety property."""
        snaps = []
        for rank_bytes in (1024, 4096, 2048):
            reg = MetricsRegistry(enabled=True)
            reg.set("memory/params_bytes", rank_bytes)
            reg.set("memory/total_bytes", rank_bytes + 512)
            snaps.append(reg.snapshot())

        def fold(order):
            merged = MetricsRegistry(enabled=True)
            for i in order:
                merged.load(snaps[i])
            return merged.snapshot()

        import itertools

        folded = [fold(order)
                  for order in itertools.permutations(range(3))]
        assert all(f == folded[0] for f in folded)
        assert folded[0]["memory/params_bytes"]["max"] == 4096


class TestProgramz:
    def test_endpoint_serves_ledger_and_memory(self, ledger, registry):
        f = ledger_jit(lambda x: x + 1, label="serve/round")
        f(jnp.ones((2,)))
        f(jnp.ones((4,)))
        acc = MemoryAccountant()
        acc.register("pool", [jnp.ones((32,), jnp.float32)])
        srv = StatuszServer(ledger=ledger, accountant=acc,
                            registry=registry)
        srv.start()
        try:
            doc = json.loads(urllib.request.urlopen(
                srv.url("/programz"), timeout=5).read())
            assert doc["ledger"]["compiles"] == 2
            assert doc["programs"][0]["label"] == "serve/round"
            assert doc["programs"][0]["diff"]["kinds"] == ["shape"]
            mem = {r["subsystem"]: r for r in doc["memory"]}
            assert mem["pool"]["bytes"] == 128
            # the scrape refreshed the gauges too
            assert registry.gauge("memory/pool_bytes").last == 128
            # scope filter
            doc2 = json.loads(urllib.request.urlopen(
                srv.url("/programz?scope=train/"), timeout=5).read())
            assert doc2["programs"] == []
            # the route is advertised in the 404 routes list
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(srv.url("/nope"), timeout=5)
            assert exc.value.code == 404
            assert "/programz" in json.loads(
                exc.value.read())["routes"]
        finally:
            srv.stop()


class TestGoodputCompileBadput:
    def test_compile_badput_category(self, ledger, registry):
        rec = TraceRecorder(enabled=True)
        report = GoodputReport(recorder=rec, write=False,
                               registry=registry)
        report.initialize()
        # window 1: a compile happens (ledger accumulates its wall
        # time), inside a dispatch span that would otherwise bill it
        # as productive
        with rec.span("step/dispatch", cat="step"):
            f = ledger_jit(lambda x: (x * 2).sum(), label="train/step")
            jax.block_until_ready(f(jnp.ones((256, 256))))
        report()
        rep = report.last_report
        compile_s = rep["badput"]["compile_s"]
        assert compile_s > 0.0
        assert compile_s == pytest.approx(ledger.total_compile_s)
        # moved OUT of productive: productive + compile ≈ the span
        assert rep["productive_s"] >= 0.0
        assert registry.counter("goodput/compile_s").value == \
            pytest.approx(compile_s)
        # window 2: steady traffic, no compile — the category is zero
        with rec.span("step/dispatch", cat="step"):
            jax.block_until_ready(f(jnp.ones((256, 256))))
        report()
        assert report.last_report["badput"]["compile_s"] == 0.0

    def test_serving_compiles_do_not_bill_training(self, ledger,
                                                   registry):
        """A colocated serving engine's compiles (serve/*, spec/*)
        must never depress a TRAINING window's goodput — the compile
        delta is scoped to the training-side label prefixes."""
        rec = TraceRecorder(enabled=True)
        report = GoodputReport(recorder=rec, write=False,
                               registry=registry)
        report.initialize()
        g = ledger_jit(lambda x: x * 3, label="serve/round")
        jax.block_until_ready(g(jnp.ones((64, 64))))
        assert ledger.total_compile_s > 0
        report()
        assert report.last_report["badput"]["compile_s"] == 0.0

    def test_ledger_swap_resets_baseline(self, ledger, registry):
        rec = TraceRecorder(enabled=True)
        report = GoodputReport(recorder=rec, write=False,
                               registry=registry)
        report.initialize()
        f = ledger_jit(lambda x: x + 1, label="train/step")
        f(jnp.ones((2,)))
        report()
        assert report.last_report["badput"]["compile_s"] > 0
        # a fresh (cleared) ledger mid-run: the next window must not
        # difference against the stale larger baseline
        ledger.clear()
        report()
        assert report.last_report["badput"]["compile_s"] == 0.0
