"""hang_doctor's pure logic: the diagnosis drives the babysitter's
probe economy and is attached to judge-facing bench records
(_bench_common._outage_diagnosis), so its classification rules are
load-bearing and pinned here.  No probes run — everything below is
parse/verdict/window logic on synthetic records."""

import json
import sys
import time

# insert/import/pop, matching the sibling repo-root-importing tests:
# leaving the root on sys.path would let later imports resolve
# repo-root names (bench, examples, ...) collection-order-dependently
sys.path.insert(0, __file__.rsplit("/tests/", 1)[0])
try:
    import hang_doctor
finally:
    sys.path.pop(0)


def _rec(**kw):
    base = {"ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "variant": "default", "outcome": "timeout",
            "timeout_s": 420, "duration_s": 420.0,
            "jax_platforms": "axon",
            "stages": {"completed": [], "wedged_in": "devices"}}
    base.update(kw)
    return base


def test_parse_stages():
    out = ("STAGE import_jax start\nSTAGE import_jax done 0.1s\n"
           "STAGE devices start\n")
    s = hang_doctor._parse_stages(out)
    assert s["wedged_in"] == "devices"
    assert s["completed"] == ["import_jax done 0.1s"]
    done = out + "STAGE devices done 2.0s n=1 kind=x platform=axon\n"
    assert hang_doctor._parse_stages(done)["wedged_in"] is None


def test_child_platform():
    line = "STAGE devices done 1.2s n=1 kind=TPU v5e platform=axon\n"
    assert hang_doctor._child_platform(line) == "axon"
    assert hang_doctor._child_platform("STAGE devices start\n") is None


def test_is_tpu_record():
    assert hang_doctor.is_tpu_record({"jax_platforms": "axon"})
    assert hang_doctor.is_tpu_record({"jax_platforms": ""})
    assert not hang_doctor.is_tpu_record({"jax_platforms": "cpu"})
    # a child that silently fell back to CPU is not a TPU probe even
    # when the env targeted the TPU
    assert not hang_doctor.is_tpu_record(
        {"jax_platforms": "axon", "child_platform": "cpu"})


def test_is_terminal_exit():
    assert hang_doctor.is_terminal_exit(
        {"outcome": "exited rc=1", "duration_s": 1505.0})
    # fast failures (import errors etc.) are not the plugin's internal
    # retry budget expiring
    assert not hang_doctor.is_terminal_exit(
        {"outcome": "exited rc=1", "duration_s": 3.0})
    assert not hang_doctor.is_terminal_exit(
        {"outcome": "timeout", "duration_s": 2700.0})


def test_verdict_precedence():
    # terminal exit beats the timeout classification
    v = hang_doctor._verdict(
        [_rec(), _rec(outcome="exited rc=1", duration_s=1505.0)], 420)
    assert "UNAVAILABLE" in v
    # a default-variant success beats everything (intermittent)
    v = hang_doctor._verdict(
        [_rec(outcome="ok"), _rec(outcome="exited rc=1",
                                  duration_s=1505.0)], 0)
    assert "intermittent" in v
    # a knob-variant-only success implicates the knob, not luck
    v = hang_doctor._verdict(
        [_rec(), _rec(variant="no_remote_compile", outcome="ok")], 420)
    assert "no_remote_compile" in v and "implicated" in v
    # all-timeout: classification depends on the longest probe
    assert "slow-init not yet excluded" in hang_doctor._verdict(
        [_rec()], 420)
    assert "hang (outlasted" in hang_doctor._verdict(
        [_rec(timeout_s=2700)], 2700)
    # empty window with history names the history
    assert "older probes" in hang_doctor._verdict([], 0, total=5)


def test_spawn_failure_records_spawn_error(tmp_path, monkeypatch):
    """A Popen failure (ENOENT interpreter, fork EAGAIN) must still
    append a JSONL record with a spawn-error outcome instead of crashing
    run_probe without any trace (ADVICE r5)."""
    import os
    import subprocess

    jsonl = tmp_path / "d.jsonl"
    monkeypatch.setattr(hang_doctor, "JSONL", str(jsonl))
    monkeypatch.setattr(hang_doctor, "tcp_precheck", lambda: {})

    spawned = {}

    def boom(cmd, *a, **k):
        spawned["child"] = cmd[-1]
        raise FileNotFoundError(2, "No such file or directory",
                                "definitely-not-python")

    monkeypatch.setattr(subprocess, "Popen", boom)
    rec = hang_doctor.run_probe("default", timeout=5)
    assert rec["outcome"].startswith("spawn-error FileNotFoundError")
    lines = [json.loads(l) for l in jsonl.read_text().splitlines()]
    assert len(lines) == 1 and lines[0]["outcome"] == rec["outcome"]
    # THIS probe's temp child script was still cleaned up (only ours —
    # a concurrent real probe's script may legitimately exist in /tmp)
    assert not os.path.exists(spawned["child"]), spawned


def test_probe_child_script_carries_reaper_marker(tmp_path, monkeypatch):
    """The probe's temp script name carries the distinctive marker
    relaunch_babysitter.sh keys its orphan reaping on — a bare
    /tmp/tmp*.py must never be the only identity."""
    import subprocess

    monkeypatch.setattr(hang_doctor, "JSONL", str(tmp_path / "d.jsonl"))
    monkeypatch.setattr(hang_doctor, "tcp_precheck", lambda: {})
    seen = {}

    def fake_popen(cmd, **k):
        seen["script"] = cmd[-1]
        raise FileNotFoundError(2, "stop here")

    monkeypatch.setattr(subprocess, "Popen", fake_popen)
    hang_doctor.run_probe("default", timeout=5)
    assert "hang_doctor_probe_" in seen["script"]
    # and the babysitter greps for exactly that marker
    sh = open(hang_doctor.REPO + "/relaunch_babysitter.sh").read()
    assert "hang_doctor_probe_" in sh


def test_summarize_window_and_malformed_lines(tmp_path, monkeypatch):
    jsonl = tmp_path / "d.jsonl"
    summary = tmp_path / "d.json"
    monkeypatch.setattr(hang_doctor, "JSONL", str(jsonl))
    monkeypatch.setattr(hang_doctor, "SUMMARY", str(summary))
    stale_ok = _rec(ts="2026-07-01T00:00:00", outcome="ok")
    fresh_to = _rec()
    cpu_probe = _rec(outcome="ok", jax_platforms="cpu")
    with open(jsonl, "w") as f:
        f.write(json.dumps(stale_ok) + "\n")
        f.write("{corrupt json line\n")          # must be tolerated
        f.write(json.dumps(fresh_to) + "\n")
        f.write(json.dumps(cpu_probe) + "\n")    # must be excluded
    s = hang_doctor.summarize()
    # cpu probe excluded everywhere; stale ok counted in by_variant
    # but NOT in the windowed verdict
    assert s["total_probes"] == 2
    assert s["probes_in_window"] == 1
    assert "intermittent" not in s["verdict"]
    assert json.load(open(summary))["verdict"] == s["verdict"]
