"""chainermn_tpu.testing — the public harness helpers must drive real
clusters the same way the internal suite does."""

import textwrap

from chainermn_tpu.testing import ensure_virtual_pod, run_multiprocess


def test_ensure_virtual_pod_idempotent():
    # conftest already pinned this process to the 8-device CPU pod;
    # ensure_virtual_pod must accept that state, not fight it
    ensure_virtual_pod(8)
    import jax

    assert jax.device_count() == 8


def test_run_multiprocess_user_worker(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(textwrap.dedent("""
        import sys
        import chainermn_tpu as cmn

        addr, n, i = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
        cmn.init_distributed(
            coordinator_address=addr, num_processes=n, process_id=i)
        comm = cmn.create_communicator("tpu_xla")
        ranks = comm.allgather_obj(comm.inter_rank)
        assert ranks == list(range(n)), ranks
        print(f"worker {i} saw {ranks}")
    """))
    import os

    outs = run_multiprocess(
        str(worker), nprocs=2,
        pythonpath=os.path.abspath(
            os.path.join(os.path.dirname(__file__), "..", "..")))
    assert len(outs) == 2
    assert all("saw [0, 1]" in o for o in outs)


def test_run_multiprocess_reports_failure(tmp_path):
    worker = tmp_path / "boom.py"
    worker.write_text("import sys; sys.exit(3)\n")
    import pytest

    with pytest.raises(RuntimeError, match="rc=3"):
        run_multiprocess(str(worker), nprocs=2)
