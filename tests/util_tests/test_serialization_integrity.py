"""Checksummed snapshot serialization: round-trips stay exact, and every
flavour of on-disk damage (bit flips, truncation, stale CRCs, missing
payloads) surfaces as the typed ``SnapshotCorruptError`` instead of an
opaque npz/pickle crash — the contract the checkpointer's fallback
resume is built on (docs/RESILIENCE.md)."""

import pickle
import zlib

import numpy as np
import pytest

import chainermn_tpu.utils.serialization as ser
from chainermn_tpu.testing import corrupt_file
from chainermn_tpu.utils import (
    SnapshotCorruptError,
    load_state,
    save_state,
    verify_state,
)


def _tree():
    import ml_dtypes

    return {
        "w": np.arange(128, dtype=np.float32).reshape(8, 16),
        "b": np.ones(3, dtype=np.float64),
        "bf16": np.linspace(-2, 2, 16).astype(ml_dtypes.bfloat16),
        "step": np.int64(7),
        "nested": {"m": np.zeros(5, dtype=np.int32)},
    }


class TestRoundTrip:
    def test_save_verify_load(self, tmp_path):
        p = str(tmp_path / "snap")
        tree = _tree()
        save_state(p, tree)
        verify_state(p)  # must not raise
        got = load_state(p)
        for a, b in zip(_leaves(tree), _leaves(got)):
            assert a.dtype == b.dtype
            np.testing.assert_array_equal(
                a.view(np.uint8) if a.dtype.kind == "V" else a,
                b.view(np.uint8) if b.dtype.kind == "V" else b)

    def test_meta_records_crcs(self, tmp_path):
        p = str(tmp_path / "snap")
        save_state(p, _tree())
        with np.load(p, allow_pickle=False) as z:
            meta = pickle.loads(z["__meta__"].tobytes())
            assert len(meta["crcs"]) == len(meta["dtypes"]) == 5
            # the recorded CRCs really are the payloads' CRC32s
            for i, want in enumerate(meta["crcs"]):
                got = zlib.crc32(
                    np.ascontiguousarray(z[f"leaf_{i:05d}"]).tobytes())
                assert got & 0xFFFFFFFF == want


def _leaves(tree):
    import jax

    return [np.asarray(x) for x in jax.tree.leaves(tree)]


class TestCorruptionDetected:
    def test_bit_flip_mid_file(self, tmp_path):
        p = str(tmp_path / "snap")
        save_state(p, _tree())
        corrupt_file(p, n_bytes=4, seed=3)
        with pytest.raises(SnapshotCorruptError):
            verify_state(p)
        with pytest.raises(SnapshotCorruptError):
            load_state(p)

    def test_truncation(self, tmp_path):
        p = str(tmp_path / "snap")
        save_state(p, _tree())
        size = (tmp_path / "snap").stat().st_size
        with open(p, "r+b") as f:
            f.truncate(size // 2)
        with pytest.raises(SnapshotCorruptError):
            verify_state(p)
        with pytest.raises(SnapshotCorruptError):
            load_state(p)

    def test_not_an_archive(self, tmp_path):
        p = tmp_path / "snap"
        p.write_bytes(b"this is not an npz at all")
        with pytest.raises(SnapshotCorruptError, match="readable npz"):
            verify_state(str(p))

    def test_missing_file_is_not_corruption(self, tmp_path):
        """"Gone" propagates as FileNotFoundError, never as
        SnapshotCorruptError — callers racing a concurrent GC must be
        able to tell the two apart (the checkpointer skips the former
        and quarantines only the latter)."""
        p = str(tmp_path / "never-existed")
        with pytest.raises(FileNotFoundError):
            verify_state(p)
        with pytest.raises(FileNotFoundError):
            load_state(p)

    def test_stale_leaf_crc_caught_by_our_layer(self, tmp_path,
                                                monkeypatch):
        """The package's own CRC walk (not zipfile's) catches a snapshot
        whose recorded checksums don't match its payloads — the case a
        consistent rewrite (or a future non-zip container) would slip
        past the archive format's internal checks."""
        p = str(tmp_path / "snap")
        monkeypatch.setattr(ser, "_leaf_crc", lambda arr: 0xDEADBEEF)
        save_state(p, _tree())
        monkeypatch.undo()
        with pytest.raises(SnapshotCorruptError, match="CRC mismatch"):
            verify_state(p)
        with pytest.raises(SnapshotCorruptError, match="CRC mismatch"):
            load_state(p)

    def test_corrupt_file_helper_is_deterministic(self, tmp_path):
        a, b = tmp_path / "a", tmp_path / "b"
        a.write_bytes(bytes(range(256)) * 16)
        b.write_bytes(bytes(range(256)) * 16)
        pos_a = corrupt_file(str(a), n_bytes=6, seed=9)
        pos_b = corrupt_file(str(b), n_bytes=6, seed=9)
        assert pos_a == pos_b
        assert a.read_bytes() == b.read_bytes()
        assert a.read_bytes() != bytes(range(256)) * 16


class TestLegacyCompat:
    def test_pre_checksum_snapshot_still_loads(self, tmp_path):
        """Snapshots written before the CRC layer (no ``crcs`` in meta,
        no ``__meta_crc__`` member) load unchecked — resume across the
        version bump must not invalidate every existing checkpoint."""
        p = str(tmp_path / "legacy")
        import jax

        tree = {"w": np.arange(6, dtype=np.float32)}
        leaves, treedef = jax.tree.flatten(tree)
        payload = {f"leaf_{i:05d}": np.asarray(v)
                   for i, v in enumerate(leaves)}
        payload["__meta__"] = np.frombuffer(
            pickle.dumps({"treedef": treedef,
                          "dtypes": [str(v.dtype) for v in leaves]}),
            dtype=np.uint8)
        with open(p, "wb") as f:
            np.savez(f, **payload)
        verify_state(p)
        got = load_state(p)
        np.testing.assert_array_equal(got["w"], tree["w"])


class TestTopologyStamp:
    """Elastic-resume stamping: the topology signature rides __meta__
    (CRC-guarded like the rest of the record), is probe-readable
    without touching leaf payloads, and its absence — a pre-elastic
    snapshot — reads as None, never as an error."""

    def test_round_trip(self, tmp_path):
        from chainermn_tpu.utils import read_topology

        p = str(tmp_path / "snap")
        topo = {"format": 1, "world_size": 8, "inter_size": 1,
                "axis_names": ["world"], "mesh_shape": [8],
                "zero1": True,
                "opt_leaves": [{"kind": "shard", "size": 10},
                               {"kind": "stack"}]}
        save_state(p, _tree(), topology=topo)
        assert read_topology(p) == topo
        # the stamped tree itself still round-trips bitwise
        got = load_state(p)
        np.testing.assert_array_equal(got["w"], _tree()["w"])

    def test_unstamped_snapshot_reads_none(self, tmp_path):
        from chainermn_tpu.utils import read_topology

        p = str(tmp_path / "snap")
        save_state(p, _tree())
        assert read_topology(p) is None

    def test_damaged_archive_is_typed(self, tmp_path):
        import os

        from chainermn_tpu.utils import read_topology

        p = str(tmp_path / "snap")
        save_state(p, _tree(), topology={"world_size": 4})
        with open(p, "r+b") as f:      # truncate: kills the zip directory
            f.truncate(os.path.getsize(p) // 2)
        with pytest.raises(SnapshotCorruptError):
            read_topology(p)

    def test_missing_file_propagates(self, tmp_path):
        from chainermn_tpu.utils import read_topology

        with pytest.raises(FileNotFoundError):
            read_topology(str(tmp_path / "nope"))


class TestShardParts:
    """Shard-only covering-set primitives (``build_shard_part`` /
    ``assemble_shard_state``): a set of per-member parts must reassemble
    BITWISE into the state a full save would have written, and every
    malformed collection (missing root, gaps, mixed sets, wrong format)
    must surface as the typed ``ShardSetError`` the checkpointer's
    fallback path is built on."""

    WORLD = 4

    def _state(self):
        rng = np.random.RandomState(3)
        return {
            "iteration": np.int64(7),
            "params": {"w": rng.randn(6, 5).astype(np.float32)},
            "opt_state": {
                "mu": rng.randn(self.WORLD, 8).astype(np.float32),
                "nu": rng.randn(self.WORLD, 8).astype(np.float32),
                "count": np.int32(7),
            },
        }

    def _topology(self):
        # per-leaf layout in opt_state flatten order: count, mu, nu
        return {"world_size": self.WORLD, "opt_leaves": [
            {"kind": "rep"}, {"kind": "shard"}, {"kind": "shard"}]}

    def _parts(self, state=None):
        state = state or self._state()
        topo = self._topology()
        out = []
        for m in range(self.WORLD):
            part, rec = ser.build_shard_part(state, topo, m, m + 1,
                                             root=(m == 0))
            out.append((rec, part))
        return state, out

    def test_round_trip_bitwise(self):
        state, parts = self._parts()
        got = ser.assemble_shard_state(parts)
        import jax

        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)), got, state)

    def test_assembly_order_independent(self):
        state, parts = self._parts()
        got = ser.assemble_shard_state(parts[::-1])
        np.testing.assert_array_equal(
            np.asarray(got["opt_state"]["mu"]),
            np.asarray(state["opt_state"]["mu"]))

    def test_non_root_parts_carry_only_shard_rows(self):
        _, parts = self._parts()
        for rec, part in parts[1:]:
            assert set(part) == {"shards"}
            assert all(v.shape == (1, 8)
                       for v in part["shards"].values())
        # root carries the replicated entries once
        assert "params" in parts[0][1]

    def test_rides_save_state_meta(self, tmp_path):
        _, parts = self._parts()
        rec, part = parts[1]
        p = str(tmp_path / "part")
        save_state(p, part, topology=self._topology(), shard_part=rec)
        assert ser.read_shard_part(p) == rec
        got_tree, got_topo, got_rec = ser.load_state_with_stamps(p)
        assert got_rec == rec and got_topo == self._topology()
        np.testing.assert_array_equal(
            np.asarray(got_tree["shards"]["leaf_00001"]),
            np.asarray(part["shards"]["leaf_00001"]))
        # a plain snapshot reads None
        q = str(tmp_path / "plain")
        save_state(q, _tree())
        assert ser.read_shard_part(q) is None

    def test_missing_member_is_typed(self):
        _, parts = self._parts()
        with pytest.raises(ser.ShardSetError, match="stop at 3"):
            ser.assemble_shard_state(parts[:-1])

    def test_gap_is_typed(self):
        _, parts = self._parts()
        with pytest.raises(ser.ShardSetError, match="gap or"):
            ser.assemble_shard_state([parts[0]] + parts[2:])

    def test_no_root_is_typed(self):
        _, parts = self._parts()
        with pytest.raises(ser.ShardSetError, match="exactly one root"):
            ser.assemble_shard_state(parts[1:])

    def test_mixed_worlds_is_typed(self):
        _, parts = self._parts()
        bad_rec = dict(parts[1][0], world=8)
        with pytest.raises(ser.ShardSetError, match="disagree"):
            ser.assemble_shard_state(
                [parts[0], (bad_rec, parts[1][1])] + parts[2:])

    def test_unknown_format_is_typed(self):
        _, parts = self._parts()
        root_rec = dict(parts[0][0], format=ser.SHARD_PART_FORMAT + 1)
        with pytest.raises(ser.ShardSetError, match="format"):
            ser.assemble_shard_state(
                [(root_rec, parts[0][1])] + parts[1:])

    def test_missing_shard_leaf_is_typed(self):
        _, parts = self._parts()
        rec1, part1 = parts[1]
        crippled = {"shards": dict(part1["shards"])}
        del crippled["shards"]["leaf_00002"]
        with pytest.raises(ser.ShardSetError, match="leaf_00002"):
            ser.assemble_shard_state(
                [parts[0], (rec1, crippled)] + parts[2:])

    def test_empty_set_is_typed(self):
        with pytest.raises(ser.ShardSetError, match="no shard parts"):
            ser.assemble_shard_state([])

    def test_bad_member_range_rejected(self):
        state = self._state()
        with pytest.raises(ValueError, match="member range"):
            ser.build_shard_part(state, self._topology(), 3, 9,
                                 root=False)

    def test_shard_leaf_without_world_axis_rejected(self):
        state = self._state()
        state["opt_state"]["mu"] = np.zeros((3, 8), np.float32)
        with pytest.raises(ValueError, match="world axis"):
            ser.build_shard_part(state, self._topology(), 0, 1,
                                 root=True)
