"""Collection gate: the whole test tree must COLLECT cleanly.

The failure mode this pins: an import-time error in one shared module
(``parallel/_compat.py``'s ``all_gather_invariant`` import, which had no
fallback for the installed jax) silently took 35 of 158 test files out
of the suite *at collection* — the run stayed green-looking while a
fifth of the coverage never executed.  ``--continue-on-collection-errors``
in the tier-1 command keeps the run alive but hides the rot; this gate
makes any collection error a test failure in its own right.

Kept fast (a couple of seconds): collection imports modules but runs
nothing.
"""

import os
import subprocess
import sys

_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", ".."))


def test_whole_suite_collects_without_errors():
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=_ROOT + os.pathsep + os.environ.get(
                   "PYTHONPATH", ""))
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "tests/", "-q", "--collect-only",
         "-p", "no:cacheprovider", "-p", "no:randomly"],
        capture_output=True, text=True, timeout=240, cwd=_ROOT, env=env)
    tail = (proc.stdout + proc.stderr)[-4000:]
    assert proc.returncode == 0, f"collection failed:\n{tail}"
    assert "error" not in proc.stdout.lower().splitlines()[-1], tail
    # belt and braces: pytest prints "N errors" in the summary line when
    # --continue-on-collection-errors style runs hit import rot
    assert "errors" not in proc.stdout.splitlines()[-1], tail
