"""CI satellite (ISSUE 13, extended in ISSUE 15): every metric name
the stack registers at runtime — and every flight-recorder span name
it records — must appear in docs/OBSERVABILITY.md's name tables: a
counter (or a span) that ships without documentation is a dashboard
nobody can interpret.  The scan is static over the package source
(the same names the runtime registers: every ``reg.inc/observe/
set("...")`` call site, every ``rec.span/instant/counter("...")``
site), plus the dynamic families, each expanded or template-checked:
``serve/shed_<reason>`` (over ``SHED_REASONS``),
``compile/retraces_<label>`` and ``memory/<subsystem>_bytes`` (the
program-ledger/accountant families), and the ``compile/<label>`` span
family."""

import os
import re

_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", ".."))
_PKG = os.path.join(_ROOT, "chainermn_tpu")
_DOC = os.path.join(_ROOT, "docs", "OBSERVABILITY.md")

# a registry record call with a literal slash-namespaced name:
# reg.inc("serve/admits"), registry.observe('comm/kv_wait', ...), ...
_CALL = re.compile(
    r"\.(?:inc|observe|set)\(\s*\n?\s*['\"]"
    r"([a-z_]+/[a-z0-9_]+)['\"]")
# a flight-recorder record call with a literal name.  ``.record`` is
# deliberately excluded: the Profiler shares that method name
# (prof.record("updater/host_time")) and its names are a different
# (printed-table) namespace.
_SPAN_CALL = re.compile(
    r"\.(?:span|instant|counter)\(\s*\n?\s*['\"]"
    r"([a-z_]+/[a-z0-9_]+)['\"]")
# the dynamic families
_DYNAMIC_SHED = re.compile(r"['\"]serve/shed_['\"]\s*\+\s*reason")
_DYNAMIC_RETRACES = re.compile(
    r"['\"]compile/retraces_['\"]\s*\+\s*_slug\(label\)")
_DYNAMIC_MEMORY = re.compile(r"memory/\{_slug\(name\)\}_bytes")
_DYNAMIC_COMPILE_SPAN = re.compile(r"f\"compile/\{label\}\"")


def _walk_sources():
    for dirpath, _dirnames, filenames in os.walk(_PKG):
        if "__pycache__" in dirpath:
            continue
        for fn in filenames:
            if fn.endswith(".py"):
                yield open(os.path.join(dirpath, fn)).read()


def _registered_names():
    names = set()
    saw = {"shed": False, "retraces": False, "memory": False}
    for src in _walk_sources():
        names.update(_CALL.findall(src))
        saw["shed"] |= bool(_DYNAMIC_SHED.search(src))
        saw["retraces"] |= bool(_DYNAMIC_RETRACES.search(src))
        saw["memory"] |= bool(_DYNAMIC_MEMORY.search(src))
    for family, present in saw.items():
        assert present, (
            f"the dynamic {family} metric call site moved — update "
            "this test's dynamic-name handling alongside it")
    from chainermn_tpu.serving.admission import SHED_REASONS

    names.discard("serve/shed_")        # concat prefixes, not names
    names.discard("compile/retraces_")
    names.update(f"serve/shed_{r}" for r in SHED_REASONS)
    return names


def _span_names():
    names = set()
    saw_compile = False
    for src in _walk_sources():
        names.update(_SPAN_CALL.findall(src))
        saw_compile |= bool(_DYNAMIC_COMPILE_SPAN.search(src))
    assert saw_compile, (
        "the ledger's compile/<label> span call site moved — update "
        "this test's dynamic-name handling alongside it")
    return names


def test_scan_finds_the_known_core():
    """The scanner itself must keep working: a regression that finds
    nothing would vacuously pass the coverage checks below."""
    names = _registered_names()
    for expected in ("serve/ttft", "serve/shed_total",
                     "serve/shed_overload", "train/step_time",
                     "checkpoint/snapshots_written", "comm/kv_retries",
                     "watchdog/stalls", "alerts/fired",
                     "elastic/live_resizes", "compile/retraces",
                     "compile/seconds", "compile/steady_retraces",
                     "memory/total_bytes", "goodput/compile_s"):
        assert expected in names
    assert len(names) > 40


def test_span_scan_finds_the_known_core():
    spans = _span_names()
    for expected in ("step/host", "serve/decode_round",
                     "serve/prefill", "checkpoint/save",
                     "autotune/probe", "watchdog/heartbeat",
                     "elastic/live_resize", "straggler/report"):
        assert expected in spans
    assert len(spans) > 20


def test_every_runtime_metric_name_is_documented():
    doc = open(_DOC).read()
    missing = []
    for name in sorted(_registered_names()):
        if name in doc:
            continue
        # the doc may list a dynamic family by its template row
        if name.startswith("serve/shed_") \
                and "serve/shed_<reason>" in doc:
            continue
        missing.append(name)
    # the dynamic families must be documented as template rows
    for template in ("compile/retraces_<label>",
                     "memory/<subsystem>_bytes"):
        if template not in doc:
            missing.append(template)
    assert not missing, (
        "metric names registered at runtime but absent from "
        f"docs/OBSERVABILITY.md's name table: {missing}")


def test_every_recorder_span_name_is_documented():
    """The ISSUE 15 extension: span names are operator surface too —
    they appear in Perfetto lanes, stall-report tails and goodput
    decompositions, so the flight-recorder table must name them."""
    doc = open(_DOC).read()
    missing = [name for name in sorted(_span_names())
               if name not in doc]
    if "compile/<label>" not in doc:
        missing.append("compile/<label>")
    assert not missing, (
        "flight-recorder span names recorded at runtime but absent "
        f"from docs/OBSERVABILITY.md: {missing}")
