"""CI satellite (ISSUE 13): every metric name the stack registers at
runtime must appear in docs/OBSERVABILITY.md's metric-name table — a
counter that ships without documentation is a dashboard nobody can
interpret.  The scan is static over the package source (the same
names the runtime registers: every ``reg.inc/observe/set("...")``
call site), plus the one dynamic family (``serve/shed_<reason>``,
expanded over ``SHED_REASONS``)."""

import os
import re

_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", ".."))
_PKG = os.path.join(_ROOT, "chainermn_tpu")
_DOC = os.path.join(_ROOT, "docs", "OBSERVABILITY.md")

# a registry record call with a literal slash-namespaced name:
# reg.inc("serve/admits"), registry.observe('comm/kv_wait', ...), ...
_CALL = re.compile(
    r"\.(?:inc|observe|set)\(\s*\n?\s*['\"]"
    r"([a-z_]+/[a-z0-9_]+)['\"]")
# the dynamic family: reg.inc("serve/shed_" + reason)
_DYNAMIC_SHED = re.compile(r"['\"]serve/shed_['\"]\s*\+\s*reason")


def _registered_names():
    names = set()
    saw_dynamic_shed = False
    for dirpath, _dirnames, filenames in os.walk(_PKG):
        if "__pycache__" in dirpath:
            continue
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            src = open(os.path.join(dirpath, fn)).read()
            names.update(_CALL.findall(src))
            if _DYNAMIC_SHED.search(src):
                saw_dynamic_shed = True
    assert saw_dynamic_shed, (
        "the serve/shed_<reason> call site moved — update this test's "
        "dynamic-name handling alongside it")
    from chainermn_tpu.serving.admission import SHED_REASONS

    names.discard("serve/shed_")    # the concat prefix, not a name
    names.update(f"serve/shed_{r}" for r in SHED_REASONS)
    return names


def test_scan_finds_the_known_core():
    """The scanner itself must keep working: a regression that finds
    nothing would vacuously pass the coverage check below."""
    names = _registered_names()
    for expected in ("serve/ttft", "serve/shed_total",
                     "serve/shed_overload", "train/step_time",
                     "checkpoint/snapshots_written", "comm/kv_retries",
                     "watchdog/stalls", "alerts/fired",
                     "elastic/live_resizes"):
        assert expected in names
    assert len(names) > 35


def test_every_runtime_metric_name_is_documented():
    doc = open(_DOC).read()
    missing = []
    for name in sorted(_registered_names()):
        if name in doc:
            continue
        # the doc may list a dynamic family by its template row
        if name.startswith("serve/shed_") \
                and "serve/shed_<reason>" in doc:
            continue
        missing.append(name)
    assert not missing, (
        "metric names registered at runtime but absent from "
        f"docs/OBSERVABILITY.md's name table: {missing}")
