"""Tier-1 runtime budget meta-test (ISSUE 15).

The tier-1 gate runs ``pytest -m 'not slow'`` under the ROADMAP's
``timeout -k 10 870`` — and at PR 14 the suite had quietly grown to
~960s, so the timeout truncated the tail and only the DOTS_PASSED
accounting papered over it.  This meta-test pins the budget
arithmetic against the recorded profile (``tests/tier1_budget.json``)
so it cannot silently regress again:

- the manifest's ``budget_s`` must equal the timeout in the ROADMAP's
  tier-1 command (neither can drift alone);
- the recorded ``-m 'not slow'`` wall time, minus what the
  slow-marking removed, must fit the budget with headroom;
- every manifest ``slow_marked`` nodeid must STILL be deselected by
  ``-m 'not slow'`` — un-marking a heavy drill fails here instead of
  re-breaching the timeout at the margin.

The budget arithmetic is BOX-SPEED-AWARE (ISSUE 18): the recorded
wall times came from one machine, and a 2.2×-slower box re-recording
them would read as a budget breach when nothing regressed.  The
manifest stores a ``calibration.reference_probe_s`` — the wall time
of a small fixed CPU workload on the recording box — and the fit
assertion scales the budget by ``max(1, probe_now / reference)``: a
slower box's inflated recording is environmental and still fits,
while on the recording box (scale 1) the check is exactly as strict
as before.  The scale never drops below 1 — a faster box must not
LOOSEN the guarantee the 870s timeout actually enforces.

What this cannot catch: a NEW slow test added after the recording.
The recording is refreshed whenever the manifest is (instructions in
its ``_comment``); the headroom term is the buffer that makes the
window between refreshes safe.
"""

import json
import os
import re
import subprocess
import sys
import time

_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", ".."))
_MANIFEST = os.path.join(_ROOT, "tests", "tier1_budget.json")


def _manifest():
    with open(_MANIFEST) as f:
        return json.load(f)


def _probe_s():
    """Wall time of a fixed CPU workload — the box-speed yardstick.

    Deliberately a mix of BLAS and element-wise numpy (the suite's own
    profile is jitted XLA-on-CPU, which leans on both); best-of-3 so a
    scheduler hiccup cannot masquerade as a slow box."""
    import numpy as np

    rng = np.random.RandomState(0)
    a = rng.rand(384, 384)
    best = float("inf")
    for _ in range(3):
        b = a.copy()
        t0 = time.perf_counter()
        for _ in range(100):
            b = np.tanh(b @ b.T / 384.0 + 0.1)
        best = min(best, time.perf_counter() - t0)
    return best


def _box_scale(m):
    ref = m["calibration"]["reference_probe_s"]
    return max(1.0, _probe_s() / ref)


def test_budget_matches_roadmap_timeout():
    roadmap = open(os.path.join(_ROOT, "ROADMAP.md")).read()
    m = re.search(r"timeout -k 10 (\d+)", roadmap)
    assert m, "ROADMAP.md tier-1 command lost its timeout"
    assert int(m.group(1)) == _manifest()["budget_s"], (
        "ROADMAP tier-1 timeout and tests/tier1_budget.json budget_s "
        "disagree — update them together")


def test_recorded_profile_fits_budget_with_headroom():
    m = _manifest()
    projected = (m["recorded_total_s"]
                 - sum(m["slow_marked"].values()))
    scale = _box_scale(m)
    assert projected + m["headroom_s"] <= m["budget_s"] * scale, (
        f"projected tier-1 wall {projected:.0f}s + headroom "
        f"{m['headroom_s']}s exceeds the {m['budget_s']}s budget "
        f"(box-speed scale {scale:.2f}) — mark more heavy tests slow "
        "(and re-record the manifest)")
    # the pre-marking recording really did breach (or crowd) the
    # budget — the slow-marking must be doing real work, not pinning
    # a vacuous inequality
    assert m["recorded_total_s"] + m["headroom_s"] > m["budget_s"] \
        or sum(m["slow_marked"].values()) > 100


def test_slow_marked_drills_stay_deselected():
    """One collect-only pass over the files the manifest names: every
    slow_marked nodeid must collect WITHOUT the marker filter and
    disappear UNDER it."""
    m = _manifest()
    files = sorted({nodeid.split("::")[0]
                    for nodeid in m["slow_marked"]})

    def collected(extra):
        proc = subprocess.run(
            [sys.executable, "-m", "pytest", "--collect-only", "-q",
             "-p", "no:cacheprovider", "-p", "no:randomly", *extra,
             *files],
            capture_output=True, text=True, timeout=300, cwd=_ROOT)
        assert proc.returncode in (0, 5), proc.stdout[-2000:]
        return proc.stdout

    unfiltered = collected([])
    filtered = collected(["-m", "not slow"])
    for nodeid in m["slow_marked"]:
        assert nodeid in unfiltered, (
            f"{nodeid} no longer exists — refresh "
            "tests/tier1_budget.json")
        assert nodeid not in filtered, (
            f"{nodeid} lost its slow marker — it costs "
            f"{m['slow_marked'][nodeid]}s of the tier-1 budget")
