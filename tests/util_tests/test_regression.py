"""Perf regression sentinel (ISSUE 15): noise-aware verdict math over
bench history, workload matching, and the ``bench.py --check`` wiring
through ``_bench_common.run_child_with_retries`` — fresh records are
scored BEFORE they join the history, verdicts ride the one JSON line,
and the exit code goes red only on a regression."""

import json
import os
import sys

import pytest

from chainermn_tpu.utils import regression

_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", ".."))


class TestVerdictMath:
    def test_no_history_is_evidence_not_a_verdict(self):
        out = regression.check_value(5.0, [], min_history=2)
        assert out["verdict"] == "no_history" and out["n_history"] == 0
        out = regression.check_value(5.0, [5.0], min_history=2)
        assert out["verdict"] == "no_history"

    def test_pass_within_slack_floor(self):
        # perfectly repeatable history: sigma 0, the 5% floor rules
        hist = [100.0, 100.0, 100.0]
        assert regression.check_value(96.0, hist)["verdict"] == "pass"
        assert regression.check_value(104.9, hist)["verdict"] == "pass"
        out = regression.check_value(94.9, hist)
        assert out["verdict"] == "regression"
        assert out["lower_bound"] == pytest.approx(95.0)
        assert regression.check_value(105.1, hist)["verdict"] \
            == "improved"

    def test_noise_widens_the_bound(self):
        # noisy history: 3 × (1.4826 × MAD) beats the 5% floor
        hist = [100.0, 90.0, 110.0, 95.0, 105.0]
        b = regression.noise_bounds(hist)
        assert b["median"] == 100.0
        assert b["slack"] == pytest.approx(3 * 1.4826 * 5.0)
        out = regression.check_value(85.0, hist)
        assert out["verdict"] == "pass"      # inside the noise band
        assert regression.check_value(70.0, hist)["verdict"] \
            == "regression"

    def test_direction_lower_is_better(self):
        hist = [10.0, 10.0, 10.0]
        assert regression.check_value(
            11.0, hist, direction="lower")["verdict"] == "regression"
        assert regression.check_value(
            9.0, hist, direction="lower")["verdict"] == "improved"
        with pytest.raises(ValueError):
            regression.check_value(1.0, hist, direction="sideways")

    def test_median_robust_to_one_outlier(self):
        hist = [100.0, 101.0, 99.0, 100.0, 5.0]    # one burst-hit run
        out = regression.check_value(97.0, hist)
        assert out["baseline_median"] == 100.0
        assert out["verdict"] == "pass"


class TestHistoryFiltering:
    RUNS = [
        {"metric": "m", "value": 100.0, "batch": 256},
        {"metric": "m", "value": 101.0, "batch": 256},
        {"metric": "m", "value": 50.0, "batch": 4},      # toy debug run
        {"metric": "m", "value": None, "batch": 256},    # failed run
        {"metric": "m", "value": 99.0, "cached": True},  # cache replay
        {"metric": "m", "value": 60.0, "batch": 256,
         "check_verdict": "regression"},  # sentinel-flagged regression
        {"metric": "other", "value": 7.0},
        {"metric": "m", "value": 102.0},                 # legacy, no batch
    ]

    def test_workload_match_and_exclusions(self):
        vals = regression.history_values(self.RUNS, "m",
                                         match={"batch": 256})
        # the toy run is excluded; the null, cached and
        # regression-flagged rows are excluded (a flagged regression
        # must not re-anchor the baseline); the legacy batch-less row
        # passes (leniency that retires itself)
        assert vals == [100.0, 101.0, 102.0]
        assert regression.history_values(self.RUNS, "other") == [7.0]

    def test_check_record(self, tmp_path):
        path = tmp_path / "hist.json"
        path.write_text(json.dumps({"runs": self.RUNS}))
        hist = regression.load_history(str(path))
        out = regression.check_record(
            {"metric": "m", "value": 98.0}, hist,
            match={"batch": 256})
        assert out["verdict"] == "pass" and out["n_history"] == 3
        out = regression.check_record(
            {"metric": "m", "value": None}, hist)
        assert out["verdict"] == "no_result"

    def test_stale_history_never_anchors(self):
        """Timestamped runs past the age cutoff are excluded — the
        same staleness rule the cache fallback applies: a verdict
        against a weeks-old baseline is not a verdict about this
        tree.  Legacy un-timestamped entries pass."""
        import datetime

        fresh = datetime.datetime.now(
            datetime.timezone.utc).isoformat(timespec="seconds")
        runs = [
            {"metric": "m", "value": 100.0,
             "timestamp": "2020-01-01T00:00:00+00:00"},
            {"metric": "m", "value": 50.0, "timestamp": fresh},
            {"metric": "m", "value": 51.0},     # legacy, no timestamp
        ]
        assert regression.history_values(runs, "m") == [50.0, 51.0]
        assert regression.history_values(
            runs, "m", max_age_days=None) == [100.0, 50.0, 51.0]

    def test_load_history_degrades(self, tmp_path):
        assert regression.load_history(
            str(tmp_path / "missing.json")) == []
        bad = tmp_path / "bad.json"
        bad.write_text("{not json")
        assert regression.load_history(str(bad)) == []


class TestBenchCheckWiring:
    """--check through run_child_with_retries against a scratch
    cache: scored before recording, verdict on the line, exit code
    red only on regression (the test_bench_contract driving style)."""

    @pytest.fixture()
    def bc(self, tmp_path, monkeypatch):
        sys.path.insert(0, _ROOT)
        try:
            import _bench_common as bc
        finally:
            sys.path.pop(0)
        monkeypatch.setattr(bc, "CACHE_PATH",
                            str(tmp_path / "cache.json"))
        return bc

    @staticmethod
    def _ok_cmd(value, **extra):
        rec = {"metric": "m", "value": value, "unit": "u",
               "vs_baseline": 1.0, **extra}
        return [sys.executable, "-c",
                f"print('BENCH_RESULT ' + {json.dumps(json.dumps(rec))})"]

    def test_first_runs_are_no_history_then_pass(self, bc, tmp_path,
                                                 capsys):
        # run 1: nothing to compare against — green, not a failure
        assert bc.run_child_with_retries(
            self._ok_cmd(100.0), str(tmp_path), [30], "m", "u",
            check=True) == 0
        rec = json.loads(capsys.readouterr().out.strip())
        assert rec["check"]["verdict"] == "no_history"
        assert rec["check"]["n_history"] == 0   # scored BEFORE append
        # run 2: one prior — still below min_history
        assert bc.run_child_with_retries(
            self._ok_cmd(100.0), str(tmp_path), [30], "m", "u",
            check=True) == 0
        rec = json.loads(capsys.readouterr().out.strip())
        assert rec["check"]["verdict"] == "no_history"
        # run 3: two matching priors — a real verdict
        assert bc.run_child_with_retries(
            self._ok_cmd(99.0), str(tmp_path), [30], "m", "u",
            check=True) == 0
        rec = json.loads(capsys.readouterr().out.strip())
        assert rec["check"]["verdict"] == "pass"
        # the verdict never pollutes the cache entries
        cache = json.load(open(bc.CACHE_PATH))
        assert all("check" not in r for r in cache["runs"])

    def test_regression_goes_red(self, bc, tmp_path, capsys):
        for v in (100.0, 100.0, 100.0):
            assert bc.run_child_with_retries(
                self._ok_cmd(v), str(tmp_path), [30], "m", "u") == 0
            capsys.readouterr()
        assert bc.run_child_with_retries(
            self._ok_cmd(80.0), str(tmp_path), [30], "m", "u",
            check=True) == 1
        rec = json.loads(capsys.readouterr().out.strip())
        assert rec["check"]["verdict"] == "regression"
        assert rec["check"]["baseline_median"] == 100.0
        # the regressed record is stamped in the cache, so CI
        # re-running the regressed tree CANNOT pull the baseline
        # down until the gate self-normalizes: every re-run keeps
        # scoring against the clean 100.0 history and stays red
        cache = json.load(open(bc.CACHE_PATH))
        assert cache["runs"][-1]["check_verdict"] == "regression"
        for _ in range(3):
            assert bc.run_child_with_retries(
                self._ok_cmd(80.0), str(tmp_path), [30], "m", "u",
                check=True) == 1
            rec = json.loads(capsys.readouterr().out.strip())
            assert rec["check"]["baseline_median"] == 100.0
        # without --check the same run stays contract-green
        assert bc.run_child_with_retries(
            self._ok_cmd(80.0), str(tmp_path), [30], "m", "u") == 0

    def test_smoke_runs_are_never_gated(self, bc, tmp_path, capsys):
        """A platform-pinned smoke run (use_cache=False) under --check
        gets the non-gating "smoke" verdict: its records are excluded
        from the hardware history, so scoring it against that history
        would gate a toy CPU number on a foreign-device baseline."""
        for v in (100.0, 100.0, 100.0):     # hardware history
            assert bc.run_child_with_retries(
                self._ok_cmd(v), str(tmp_path), [30], "m", "u") == 0
            capsys.readouterr()
        assert bc.run_child_with_retries(
            self._ok_cmd(2.0), str(tmp_path), [30], "m", "u",
            use_cache=False, check=True) == 0
        rec = json.loads(capsys.readouterr().out.strip())
        assert rec["check"]["verdict"] == "smoke"
        # and the smoke run left no cache entry behind
        assert all(r["value"] != 2.0
                   for r in json.load(open(bc.CACHE_PATH))["runs"])

    def test_device_kind_joins_the_match(self, bc, tmp_path, capsys):
        """A fresh record carrying device_kind is only scored against
        history of the SAME device kind — a first TPU run after an
        all-CPU history is no_history, not a meaningless verdict."""
        for v in (100.0, 101.0, 99.0):
            assert bc.run_child_with_retries(
                self._ok_cmd(v, device_kind="cpu"), str(tmp_path),
                [30], "m", "u") == 0
            capsys.readouterr()
        assert bc.run_child_with_retries(
            self._ok_cmd(3000.0, device_kind="TPU v5 lite"),
            str(tmp_path), [30], "m", "u", check=True) == 0
        rec = json.loads(capsys.readouterr().out.strip())
        assert rec["check"]["verdict"] == "no_history"
        # same-kind scoring still works
        assert bc.run_child_with_retries(
            self._ok_cmd(100.0, device_kind="cpu"), str(tmp_path),
            [30], "m", "u", check=True) == 0
        rec = json.loads(capsys.readouterr().out.strip())
        assert rec["check"]["verdict"] == "pass"

    def test_total_failure_under_check_is_red(self, bc, tmp_path,
                                              capsys):
        bad = [sys.executable, "-c", "raise SystemExit(3)"]
        assert bc.run_child_with_retries(
            bad, str(tmp_path), [30], "m", "u", check=True) == 1
        rec = json.loads(capsys.readouterr().out.strip())
        assert rec["value"] is None
        assert rec["check"]["verdict"] == "no_result"

    def test_cached_fallback_under_check(self, bc, tmp_path, capsys):
        for v in (100.0, 101.0):
            assert bc.run_child_with_retries(
                self._ok_cmd(v), str(tmp_path), [30], "m", "u") == 0
            capsys.readouterr()
        bad = [sys.executable, "-c", "raise SystemExit(3)"]
        # live failure + a fresh cache: the cached record is served
        # with the distinct NON-GATING verdict — green exit (the
        # outage is not a perf regression), but never a "pass": a
        # replayed record must not be scored against the history it
        # was copied from (it would always pass, waving a real
        # regression through a dead-chip window)
        assert bc.run_child_with_retries(
            bad, str(tmp_path), [30], "m", "u", check=True) == 0
        rec = json.loads(capsys.readouterr().out.strip())
        assert rec["cached"] is True
        assert rec["check"]["verdict"] == "cached"


def test_bench_scripts_wire_the_check_flag():
    """``bench.py --check`` (and bench_programs.py's) reach
    ``run_child_with_retries(check=...)`` — the one-line wiring that
    makes any bench script self-verify.  Source-level pin (the full
    child run is vma-gated on this host; the check semantics are
    unit-tested above through the same run_child_with_retries
    entrypoint the scripts call)."""
    for script in ("bench.py", "bench_programs.py"):
        src = open(os.path.join(_ROOT, script)).read()
        assert '"--check"' in src, script
        assert "check=args.check" in src, script
