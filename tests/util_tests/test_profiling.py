"""Profiling subsystem: registry math, communicator proxy timing, trace
smoke (SURVEY §5 — the subsystem the reference lacked)."""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from chainermn_tpu.utils.profiling import (
    Profiler,
    ProfileReport,
    profiled_communicator,
    trace,
)


def test_registry_math():
    p = Profiler()
    p.record("x", 0.5, nbytes=100)
    p.record("x", 1.5, nbytes=300)
    p.record("y", 0.1)
    s = p.stats["x"]
    assert s.count == 2 and s.total == 2.0 and s.maximum == 1.5
    assert s.bytes == 400
    table = p.summary()
    assert "x" in table and "y" in table and "mean_ms" in table
    p.reset()
    assert p.summary() == "(no profile data)"


def test_time_block_materialises_output():
    p = Profiler()
    with p.time_block("block") as box:
        box["out"] = jnp.ones((8,))
    assert p.stats["block"].count == 1
    assert p.stats["block"].total > 0


def test_disabled_profiler_records_nothing():
    p = Profiler(enabled=False)
    p.record("x", 1.0)
    assert not p.stats


def test_disabled_time_block_is_zero_cost(monkeypatch):
    """A disabled profiler must skip BOTH the sync materialisation
    (jax.device_get would collapse async-dispatch overlap) and the
    record — not just drop the stats row."""
    import jax as _jax

    def boom(*a, **k):
        raise AssertionError("disabled time_block materialised output")

    monkeypatch.setattr(_jax, "device_get", boom)
    p = Profiler(enabled=False)
    with p.time_block("block") as box:
        box["out"] = jnp.ones((8,))
    assert not p.stats

    called = []
    with p.time_block("fn", sync=lambda: called.append(1)):
        pass
    assert not called, "disabled time_block invoked its sync callable"


def test_disabled_profiled_communicator_skips_byte_accounting(
        comm, monkeypatch):
    """With profiler AND recorder off, the proxy must not pay the
    _nbytes tree walk (nor any timing) — the zero-overhead contract."""
    from chainermn_tpu.utils import profiling as prof_mod
    from chainermn_tpu.utils.telemetry import TraceRecorder, set_recorder

    def boom(x):
        raise AssertionError("_nbytes walked the tree while disabled")

    monkeypatch.setattr(prof_mod, "_nbytes", boom)
    prev = set_recorder(TraceRecorder(enabled=False))
    try:
        p = Profiler(enabled=False)
        pc = profiled_communicator(comm, p)
        assert pc.bcast_obj({"a": 1}) == {"a": 1}
        assert not p.stats
    finally:
        set_recorder(prev)


def test_profiled_communicator_caches_wrappers(comm):
    p = Profiler()
    pc = profiled_communicator(comm, p)
    first = pc.allreduce
    assert pc.allreduce is first, "per-name wrapper rebuilt on access"
    # the cached wrapper still respects a later enabled flip
    p.enabled = False
    x = jnp.ones((comm.size, 2), jnp.float32)
    first(x)
    assert not p.stats
    p.enabled = True
    first(x)
    assert p.stats["comm.allreduce"].count == 1


def test_profiled_communicator_times_collectives(comm):
    p = Profiler()
    pc = profiled_communicator(comm, p)
    x = jnp.ones((comm.size, 4), jnp.float32)

    out = pc.allreduce(x)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(x) * comm.size)
    assert p.stats["comm.allreduce"].count == 1
    assert p.stats["comm.allreduce"].bytes == x.size * 4

    assert pc.bcast_obj({"a": 1}) == {"a": 1}
    assert p.stats["comm.bcast_obj"].count == 1

    # non-collective attributes pass through untimed
    assert pc.rank == comm.rank
    assert pc.size == comm.size
    assert "rank" not in {k.split(".")[-1] for k in p.stats}


def test_profile_report_prints_and_resets(comm, capsys):
    p = Profiler()
    p.record("comm.allreduce", 0.25)

    class FakeUpdater:
        iteration = 3

    class FakeTrainer:
        updater = FakeUpdater()

    ProfileReport(p, comm=comm)(FakeTrainer())
    out = capsys.readouterr().out
    assert "comm.allreduce" in out and "iter 3" in out
    assert not p.stats  # reset=True


def test_profile_report_aggregates_across_processes():
    """With a comm, the printed table reflects the WORLD: counts/totals
    summed, max-of-max, divergent name sets unioned (the
    ObservationAggregator convention) — not rank 0's local view."""
    p = Profiler()
    p.record("comm.allreduce", 0.25, nbytes=100)

    class FakeComm:
        rank = 0
        inter_size = 3

        def allgather_obj(self, obj):
            return [
                obj,
                {"comm.allreduce": (3, 0.75, 0.5, 300)},
                {"rank2.only": (1, 1.0, 1.0, 0)},   # divergent key set
            ]

    # aggregate=False keeps the old local-table behaviour (a report
    # registered on rank 0 only must not enter a collective)
    assert ProfileReport(p, comm=FakeComm(),
                         aggregate=False)._aggregate() is p

    class OneProcComm(FakeComm):
        inter_size = 1

        def allgather_obj(self, obj):
            raise AssertionError(
                "single-process report entered the collective")

    assert ProfileReport(p, comm=OneProcComm())._aggregate() is p

    rep = ProfileReport(p, comm=FakeComm())
    agg = rep._aggregate()
    s = agg.stats["comm.allreduce"]
    assert s.count == 4
    assert s.total == pytest.approx(1.0)
    assert s.maximum == pytest.approx(0.5)
    assert s.bytes == 400
    assert agg.stats["rank2.only"].count == 1
    # the local profiler is untouched by aggregation
    assert p.stats["comm.allreduce"].count == 1


@pytest.mark.skipif(
    os.environ.get("CI_SKIP_TRACE") == "1", reason="trace smoke disabled")
def test_trace_writes_profile(tmp_path):
    logdir = str(tmp_path / "trace")
    with trace(logdir):
        jnp.sum(jnp.ones((128, 128)) @ jnp.ones((128, 128))).block_until_ready()
    dumped = []
    for root, _, files in os.walk(logdir):
        dumped += files
    assert dumped, "profiler wrote no trace files"
