"""Profiling subsystem: registry math, communicator proxy timing, trace
smoke (SURVEY §5 — the subsystem the reference lacked)."""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from chainermn_tpu.utils.profiling import (
    Profiler,
    ProfileReport,
    profiled_communicator,
    trace,
)


def test_registry_math():
    p = Profiler()
    p.record("x", 0.5, nbytes=100)
    p.record("x", 1.5, nbytes=300)
    p.record("y", 0.1)
    s = p.stats["x"]
    assert s.count == 2 and s.total == 2.0 and s.maximum == 1.5
    assert s.bytes == 400
    table = p.summary()
    assert "x" in table and "y" in table and "mean_ms" in table
    p.reset()
    assert p.summary() == "(no profile data)"


def test_time_block_materialises_output():
    p = Profiler()
    with p.time_block("block") as box:
        box["out"] = jnp.ones((8,))
    assert p.stats["block"].count == 1
    assert p.stats["block"].total > 0


def test_disabled_profiler_records_nothing():
    p = Profiler(enabled=False)
    p.record("x", 1.0)
    assert not p.stats


def test_profiled_communicator_times_collectives(comm):
    p = Profiler()
    pc = profiled_communicator(comm, p)
    x = jnp.ones((comm.size, 4), jnp.float32)

    out = pc.allreduce(x)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(x) * comm.size)
    assert p.stats["comm.allreduce"].count == 1
    assert p.stats["comm.allreduce"].bytes == x.size * 4

    assert pc.bcast_obj({"a": 1}) == {"a": 1}
    assert p.stats["comm.bcast_obj"].count == 1

    # non-collective attributes pass through untimed
    assert pc.rank == comm.rank
    assert pc.size == comm.size
    assert "rank" not in {k.split(".")[-1] for k in p.stats}


def test_profile_report_prints_and_resets(comm, capsys):
    p = Profiler()
    p.record("comm.allreduce", 0.25)

    class FakeUpdater:
        iteration = 3

    class FakeTrainer:
        updater = FakeUpdater()

    ProfileReport(p, comm=comm)(FakeTrainer())
    out = capsys.readouterr().out
    assert "comm.allreduce" in out and "iter 3" in out
    assert not p.stats  # reset=True


@pytest.mark.skipif(
    os.environ.get("CI_SKIP_TRACE") == "1", reason="trace smoke disabled")
def test_trace_writes_profile(tmp_path):
    logdir = str(tmp_path / "trace")
    with trace(logdir):
        jnp.sum(jnp.ones((128, 128)) @ jnp.ones((128, 128))).block_until_ready()
    dumped = []
    for root, _, files in os.walk(logdir):
        dumped += files
    assert dumped, "profiler wrote no trace files"
