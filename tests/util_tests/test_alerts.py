"""SLO burn-rate alerting (utils/alerts.py): deterministic window math
on an injected clock, fire/resolve transitions counted and JSONL'd,
quiet-on-baseline, the latency-rule lattice read, and the protective
advisory the AdmissionController consumes."""

import json

import pytest

from chainermn_tpu.utils.alerts import (
    AlertManager,
    LatencyRule,
    RatioRule,
    get_installed,
    install,
)
from chainermn_tpu.utils.metrics import (
    LATTICE_EDGES,
    MetricsRegistry,
    bucket_index,
)

WINDOWS = ((60.0, 5.0, 10.0),)      # one page-style pair, test-sized


def _mgr(reg, **kw):
    rule = RatioRule("shed-burn", bad="serve/shed_total",
                     total="serve/submitted", budget=0.01,
                     windows=WINDOWS)
    return AlertManager([rule], registry=reg, **kw), rule


def _cover(mgr, reg, t=0.0, seconds=61):
    """Healthy traffic long enough to cover the 60s long window — a
    partial window reads as no-evidence, so drills that expect to
    fire must first span it."""
    for _ in range(int(seconds)):
        reg.inc("serve/submitted", 10)
        t += 1.0
        mgr.tick(t)
    return t


class TestRuleValidation:
    def test_budget_bounds(self):
        for bad in (0.0, 1.0, -0.1, 2.0):
            with pytest.raises(ValueError):
                RatioRule("r", bad="b", total="t", budget=bad)

    def test_window_shape(self):
        with pytest.raises(ValueError):
            RatioRule("r", bad="b", total="t", budget=0.01,
                      windows=((5.0, 60.0, 10.0),))   # short > long
        with pytest.raises(ValueError):
            RatioRule("r", bad="b", total="t", budget=0.01,
                      windows=((60.0, 5.0, 0.0),))    # factor <= 0
        with pytest.raises(ValueError):
            RatioRule("r", bad="b", total="t", budget=0.01, windows=())

    def test_duplicate_rule_names_rejected(self):
        reg = MetricsRegistry(enabled=True)
        r1 = RatioRule("same", bad="b", total="t", budget=0.01)
        r2 = RatioRule("same", bad="b", total="t", budget=0.02)
        with pytest.raises(ValueError):
            AlertManager([r1, r2], registry=reg)

    def test_latency_rule_above_positive(self):
        with pytest.raises(ValueError):
            LatencyRule("r", histogram="h", above=0.0, budget=0.01)


class TestWindowMath:
    """Injectable-clock determinism: the same (t, bad, total) series
    always produces the same transitions at the same ticks."""

    def test_quiet_on_baseline(self):
        reg = MetricsRegistry(enabled=True)
        mgr, _ = _mgr(reg)
        t = 0.0
        for _ in range(120):
            reg.inc("serve/submitted", 10)
            t += 1.0
            assert mgr.tick(t) == []
        assert mgr.firing() == ()
        assert mgr.fired == 0
        # burn is computed and ~0, not None — there IS evidence
        burn = mgr.state()["rules"]["shed-burn"]["burn"]
        assert burn["60s"] == 0.0

    def test_fires_when_both_windows_burn(self):
        reg = MetricsRegistry(enabled=True)
        mgr, _ = _mgr(reg)
        t = 0.0
        for _ in range(30):             # healthy history
            reg.inc("serve/submitted", 10)
            t += 1.0
            mgr.tick(t)
        fired_at = None
        for _ in range(70):             # 50% shed: burn = 50 >> 10
            reg.inc("serve/submitted", 10)
            reg.inc("serve/shed_total", 5)
            t += 1.0
            ev = mgr.tick(t)
            if ev:
                fired_at = t
                assert ev[0]["transition"] == "fired"
                assert ev[0]["rule"] == "shed-burn"
                break
        # the short window saturates fast; the long window must cross
        # factor 10 before the pair agrees — deterministically
        assert fired_at is not None
        assert mgr.firing() == ("shed-burn",)
        assert mgr.fired == 1
        # deterministic replay: same series, same fire tick
        reg2 = MetricsRegistry(enabled=True)
        mgr2, _ = _mgr(reg2)
        t2 = 0.0
        refire = None
        for _ in range(30):
            reg2.inc("serve/submitted", 10)
            t2 += 1.0
            mgr2.tick(t2)
        for _ in range(70):
            reg2.inc("serve/submitted", 10)
            reg2.inc("serve/shed_total", 5)
            t2 += 1.0
            if mgr2.tick(t2):
                refire = t2
                break
        assert refire == fired_at

    def test_short_window_recovery_resolves(self):
        """The multi-window point: once the burn STOPS, the short
        window clears within ~its own length even though the long
        window still remembers the incident."""
        reg = MetricsRegistry(enabled=True)
        mgr, _ = _mgr(reg)
        t = _cover(mgr, reg)
        for _ in range(30):
            reg.inc("serve/submitted", 10)
            reg.inc("serve/shed_total", 8)
            t += 1.0
            mgr.tick(t)
        burn_end = t
        assert mgr.firing() == ("shed-burn",)
        resolved_at = None
        for _ in range(30):
            reg.inc("serve/submitted", 10)     # burn stops
            t += 1.0
            ev = mgr.tick(t)
            if ev:
                assert ev[0]["transition"] == "resolved"
                resolved_at = t
                break
        assert resolved_at is not None
        # resolved within a handful of short windows, long before the
        # 60s long window forgets
        assert resolved_at <= burn_end + 3 * 5.0
        assert mgr.resolved == 1

    def test_no_evidence_is_not_an_alert(self):
        """Zero traffic (delta total < min_total) → burn None → quiet,
        whatever the ratio would divide to."""
        reg = MetricsRegistry(enabled=True)
        mgr, _ = _mgr(reg)
        for t in range(1, 200):
            assert mgr.tick(float(t)) == []
        assert mgr.state()["rules"]["shed-burn"]["burn"]["60s"] is None

    def test_disabled_registry_reads_as_no_evidence(self):
        reg = MetricsRegistry(enabled=False)
        mgr, _ = _mgr(reg)
        for t in range(1, 100):
            assert mgr.tick(float(t)) == []
        assert mgr.firing() == ()

    def test_min_interval_rate_limits_evaluation(self):
        """tick() from a tight loop is one clock compare until the
        interval elapses — and window math over the sparser samples
        still fires at the same clock time."""
        reg = MetricsRegistry(enabled=True)
        mgr, _ = _mgr(reg, min_interval=1.0)
        t = 0.0
        for _ in range(100):            # 10 ticks per clock second
            reg.inc("serve/submitted", 1)
            t += 0.1
            mgr.tick(t)
        assert mgr.ticks == 100
        assert mgr.evals == 10          # one per elapsed interval
        # burn goes bad: every evaluated window must still catch it
        # (900 × 0.1s reaches past the 60s long window's coverage)
        for _ in range(900):
            reg.inc("serve/submitted", 1)
            reg.inc("serve/shed_total", 1)
            t += 0.1
            mgr.tick(t)
        assert mgr.firing() == ("shed-burn",)

    def test_sample_retention_bounded_under_fast_ticks(self):
        """A scheduler-loop ticking far faster than the resolution
        floor (shortest_window/64) must not grow the sample deque
        without bound — the newest sample is replaced instead."""
        reg = MetricsRegistry(enabled=True)
        mgr, _ = _mgr(reg)              # windows (60, 5): gap 5/64 s
        t = 0.0
        for _ in range(20_000):         # 100 Hz for 200 s
            reg.inc("serve/submitted", 1)
            t += 0.01
            mgr.tick(t)
        dq = mgr._samples["shed-burn"]
        # 60 s retained span / (5/64 s) ≈ 768 samples + slack
        assert len(dq) < 1000
        # and the window math still reads the live totals
        burn = mgr.state()["rules"]["shed-burn"]["burn"]
        assert burn["60s"] == 0.0

    def test_min_interval_zero_evaluates_every_tick(self):
        reg = MetricsRegistry(enabled=True)
        mgr, _ = _mgr(reg)
        for t in range(1, 20):
            mgr.tick(float(t))
        assert mgr.evals == mgr.ticks == 19

    def test_min_interval_validation(self):
        reg = MetricsRegistry(enabled=True)
        with pytest.raises(ValueError, match="min_interval"):
            _mgr(reg, min_interval=-0.5)


class TestLatencyRule:
    def test_bad_counts_strictly_above_lattice_edge(self):
        reg = MetricsRegistry(enabled=True)
        h = reg.histogram("serve/ttft")
        edge = LATTICE_EDGES[bucket_index(0.1)]
        rule = LatencyRule("slow-ttft", histogram="serve/ttft",
                           above=0.1, budget=0.1, windows=WINDOWS)
        h.observe(edge)                 # ON the edge: not bad
        h.observe(edge * 1.5)           # above: bad
        h.observe(0.001)                # fast: not bad
        bad, total = rule.read(reg)
        assert (bad, total) == (1.0, 3.0)

    def test_fires_on_slow_tail_quiet_on_fast(self):
        reg = MetricsRegistry(enabled=True)
        rule = LatencyRule("slow-ttft", histogram="serve/ttft",
                           above=0.1, budget=0.02, windows=WINDOWS)
        mgr = AlertManager([rule], registry=reg)
        h = reg.histogram("serve/ttft")
        t = 0.0
        for _ in range(20):             # fast baseline
            for _ in range(5):
                h.observe(0.01)
            t += 1.0
            assert mgr.tick(t) == []
        for _ in range(70):             # tail goes bad: 40% slow
            for _ in range(3):
                h.observe(0.01)
            h.observe(0.5)
            h.observe(0.5)
            t += 1.0
            mgr.tick(t)
        assert mgr.firing() == ("slow-ttft",)


class TestTransitionsAndLog:
    def test_transition_counters_and_gauge(self):
        reg = MetricsRegistry(enabled=True)
        mgr, _ = _mgr(reg)
        t = _cover(mgr, reg)
        for _ in range(30):
            reg.inc("serve/submitted", 10)
            reg.inc("serve/shed_total", 9)
            t += 1.0
            mgr.tick(t)
        assert reg.counter("alerts/fired").value == 1
        assert reg.gauge("alerts/firing").last == 1
        for _ in range(60):
            reg.inc("serve/submitted", 10)
            t += 1.0
            mgr.tick(t)
        assert reg.counter("alerts/resolved").value == 1
        assert reg.gauge("alerts/firing").last == 0

    def test_alert_log_jsonl(self, tmp_path):
        reg = MetricsRegistry(enabled=True)
        path = str(tmp_path / "alerts.jsonl")
        mgr, _ = _mgr(reg, log_path=path)
        t = _cover(mgr, reg)
        for _ in range(30):
            reg.inc("serve/submitted", 10)
            reg.inc("serve/shed_total", 9)
            t += 1.0
            mgr.tick(t)
        for _ in range(60):
            reg.inc("serve/submitted", 10)
            t += 1.0
            mgr.tick(t)
        lines = [json.loads(l) for l in open(path)]
        assert [l["transition"] for l in lines] == ["fired", "resolved"]
        assert lines[0]["rule"] == "shed-burn"
        assert lines[0]["budget"] == 0.01
        assert "burn" in lines[0]

    def test_broken_rule_parks_in_error_state(self):
        reg = MetricsRegistry(enabled=True)

        class Broken(RatioRule):
            def read(self, registry):
                raise RuntimeError("boom")

        rule = Broken("bad", bad="x", total="y", budget=0.01,
                      windows=WINDOWS)
        mgr = AlertManager([rule], registry=reg)
        assert mgr.tick(1.0) == []      # never raises
        st = mgr.state()["rules"]["bad"]
        assert st["state"] == "error"
        assert "boom" in st["detail"]

    def test_read_error_holds_firing_no_double_count(self):
        """An evaluation error is not evidence the overload stopped:
        a FIRING rule whose read starts raising keeps firing (and
        protective shedding), and recovery-while-still-burning does
        not re-count the fire transition."""
        reg = MetricsRegistry(enabled=True)

        class Flaky(RatioRule):
            broken = False

            def read(self, registry):
                if self.broken:
                    raise RuntimeError("scrape down")
                return super().read(registry)

        rule = Flaky("shed-burn", bad="serve/shed_total",
                     total="serve/submitted", budget=0.01,
                     windows=WINDOWS)
        mgr = AlertManager([rule], registry=reg)
        t = _cover(mgr, reg)
        for _ in range(30):             # burn it into firing
            reg.inc("serve/submitted", 10)
            reg.inc("serve/shed_total", 9)
            t += 1.0
            mgr.tick(t)
        assert mgr.firing() == ("shed-burn",)
        assert mgr.fired == 1
        rule.broken = True
        for _ in range(10):
            t += 1.0
            assert mgr.tick(t) == []    # errors emit no transitions
        assert mgr.state()["rules"]["shed-burn"]["state"] == "error"
        assert mgr.firing() == ("shed-burn",)   # HELD
        assert mgr.protective() is True
        rule.broken = False             # recovers, still burning
        for _ in range(3):
            reg.inc("serve/submitted", 10)
            reg.inc("serve/shed_total", 9)
            t += 1.0
            mgr.tick(t)
        assert mgr.firing() == ("shed-burn",)
        assert mgr.fired == 1           # no double count
        assert mgr.resolved == 0


class TestAdvisory:
    def test_protective_follows_protect_flag(self):
        reg = MetricsRegistry(enabled=True)
        loud = RatioRule("loud", bad="b", total="t", budget=0.01,
                         windows=WINDOWS, protect=False)
        mgr = AlertManager([loud], registry=reg)
        t = 0.0
        for _ in range(70):             # spans the 60s long window
            reg.inc("t", 10)
            reg.inc("b", 9)
            t += 1.0
            mgr.tick(t)
        assert mgr.firing() == ("loud",)
        assert mgr.protective() is False    # protect=False: page only

    def test_admission_controller_sheds_overload_while_protective(self):
        from chainermn_tpu.serving.admission import AdmissionController

        class FakeReq:
            def __init__(self, priority):
                self.priority = priority
                self.tenant = None
                self.max_new = 8
                self.deadline = None
                self.t_submit = 0.0

        state = {"on": True}
        ctrl = AdmissionController(
            alert_advisor=lambda: state["on"])
        # below-tier class shed "overload"; protected class 0 passes
        assert ctrl.check_submit(FakeReq(1), [], {}) == \
            (False, "overload", None)
        assert ctrl.check_submit(FakeReq(0), [], {}) == \
            (True, None, None)
        state["on"] = False
        assert ctrl.check_submit(FakeReq(1), [], {}) == \
            (True, None, None)

    def test_admission_manager_advisor_object(self):
        from chainermn_tpu.serving.admission import AdmissionController

        reg = MetricsRegistry(enabled=True)
        mgr, _ = _mgr(reg)
        ctrl = AdmissionController(alert_advisor=mgr)
        assert ctrl.protective() is False
        t = 0.0
        for _ in range(70):             # spans the 60s long window
            reg.inc("serve/submitted", 10)
            reg.inc("serve/shed_total", 9)
            t += 1.0
            mgr.tick(t)
        assert ctrl.protective() is True

    def test_broken_advisor_degrades_to_open(self):
        from chainermn_tpu.serving.admission import AdmissionController

        def bad():
            raise RuntimeError("advisor down")

        ctrl = AdmissionController(alert_advisor=bad)
        assert ctrl.protective() is False


class TestOverloadDrill:
    """The bench_overload-shaped acceptance drill, replayed on the
    injectable clock: an open-loop arrival trace against a fixed
    decode capacity — at 0.5× capacity the rules stay quiet, at 2×
    the queue grows without bound, TTFT blows through the latency
    rule and sheds burn the ratio rule; back at 0.5× both resolve."""

    WINDOWS = ((30.0, 5.0, 5.0),)

    def _rules(self):
        return [
            RatioRule("shed-burn", bad="serve/shed_total",
                      total="serve/submitted", budget=0.02,
                      windows=self.WINDOWS),
            LatencyRule("slow-ttft", histogram="serve/ttft",
                        above=0.5, budget=0.05,
                        windows=self.WINDOWS),
        ]

    def _replay(self, reg, mgr, t0, seconds, arrival_rate,
                service_rate, max_queue=40):
        """Deterministic fluid replay: each clock second,
        ``arrival_rate`` requests arrive, ``service_rate`` depart;
        TTFT observed = queue delay at admission; arrivals beyond
        ``max_queue`` shed (the AdmissionController's bounded queue)."""
        t, queue = t0, 0.0
        for _ in range(int(seconds)):
            t += 1.0
            queue += arrival_rate
            reg.inc("serve/submitted", arrival_rate)
            if queue > max_queue:
                reg.inc("serve/shed_total", queue - max_queue)
                queue = max_queue
            served = min(queue, service_rate)
            queue -= served
            for _ in range(int(served)):
                reg.observe("serve/ttft", 0.02 + queue / service_rate)
            mgr.tick(t)
        return t

    def test_fires_at_2x_capacity_quiet_unloaded(self):
        reg = MetricsRegistry(enabled=True)
        mgr = AlertManager(self._rules(), registry=reg)
        # unloaded baseline: 0.5x capacity, queue never forms
        t = self._replay(reg, mgr, 0.0, 120, arrival_rate=5,
                         service_rate=10)
        assert mgr.firing() == ()
        assert mgr.fired == 0
        # injected overload: 2x capacity
        t = self._replay(reg, mgr, t, 120, arrival_rate=20,
                         service_rate=10)
        assert set(mgr.firing()) == {"shed-burn", "slow-ttft"}
        assert mgr.protective() is True
        # cause stops: the short window resolves both
        self._replay(reg, mgr, t, 120, arrival_rate=5,
                     service_rate=10)
        assert mgr.firing() == ()
        assert mgr.resolved >= 2


class TestInstall:
    def test_install_and_watchdog_discovery(self):
        reg = MetricsRegistry(enabled=True)
        mgr, _ = _mgr(reg)
        prev = install(mgr)
        try:
            assert get_installed() is mgr
        finally:
            install(prev)

    def test_watchdog_report_embeds_alert_state(self, tmp_path):
        from chainermn_tpu.extensions.watchdog import TrainingWatchdog

        reg = MetricsRegistry(enabled=True)
        mgr, _ = _mgr(reg)
        t = _cover(mgr, reg)
        for _ in range(30):
            reg.inc("serve/submitted", 10)
            reg.inc("serve/shed_total", 9)
            t += 1.0
            mgr.tick(t)
        prev = install(mgr)
        try:
            wd = TrainingWatchdog(
                stall_timeout=0.05, check_interval=0.02,
                report_path=str(tmp_path / "stall.json"))
            wd._fire(True, 1.0, {}, {})
            assert wd.last_report["alerts"]["firing"] == ["shed-burn"]
            assert wd.last_report["alerts"]["protective"] is True
        finally:
            install(prev)
