"""Pattern-plan autotuner (``autotune_pattern_plan``): the plan-IR
candidate search riding the measured-plan cache.

The cache-key discipline is the same as ``autotune_plan`` — and the new
hazard here is the VARIANT: four patterns (and the legacy exchange
tuner) can share one payload signature and one cache file, and a plan
tuned for one must never serve another.  Second tunings of an exact
match must serve with ZERO probe executions.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import chainermn_tpu as cmn
from chainermn_tpu.ops import plan_ir
from chainermn_tpu.utils import autotune
from chainermn_tpu.utils.metrics import MetricsRegistry, set_registry

AX = "world"


@pytest.fixture()
def comm():
    return cmn.create_communicator("tpu_xla", axis_name=AX)


@pytest.fixture()
def registry():
    reg = MetricsRegistry(enabled=True)
    prev = set_registry(reg)
    try:
        yield reg
    finally:
        set_registry(prev)


def fsdp_payload(width=16):
    rng = np.random.RandomState(0)
    params = {
        "w": jnp.asarray(rng.randn(8, width, 4), jnp.float32),
        "b": jnp.asarray(rng.randn(8, 2), jnp.float32),
    }
    dims = {"w": 0, "b": 0}
    return params, dims


def tune(comm, params, cache, **kw):
    kw.setdefault("trials", 1)
    kw.setdefault("warmup", 1)
    return autotune.autotune_pattern_plan(comm, params,
                                          cache_path=cache, **kw)


class TestTuneAndCache:
    def test_fsdp_tune_then_zero_probe_serve(self, comm, tmp_path):
        cache = str(tmp_path / "plans.json")
        params, dims = fsdp_payload()
        plan = tune(comm, params, cache, pattern="fsdp_gather",
                    dims=dims, wire_dtypes=(None, "bfloat16"))
        assert not plan.from_cache and plan.n_probes > 0
        assert isinstance(plan.program, dict)
        assert plan.program["pattern"] == "fsdp_gather"
        assert plan.meta["pattern"] == "fsdp_gather"
        # every probed candidate passed parity — losing bitwise
        # equality disqualifies, it doesn't warn
        assert plan.meta["timings"]
        assert all(t["parity_ok"] for t in plan.meta["timings"])
        # the winner is a runnable program
        prog = plan_ir.ensure_program(plan, "fsdp_gather")
        assert prog.label == plan.strategy

        again = tune(comm, params, cache, pattern="fsdp_gather",
                     dims=dims, wire_dtypes=(None, "bfloat16"))
        assert again.from_cache and again.n_probes == 0
        assert again.program == plan.program
        assert again.strategy == plan.strategy

    @pytest.mark.parametrize("pattern,kw", [
        ("moe_all_to_all", {"split_axis": 0, "concat_axis": 1}),
        ("ring_permute", {}),
        ("pipeline_edge", {"shift": 1, "wrap": False}),
    ])
    def test_other_patterns_tune_and_serve(self, comm, tmp_path,
                                           pattern, kw):
        cache = str(tmp_path / "plans.json")
        payload = {
            "moe_all_to_all": jnp.ones((8, 4, 8), jnp.float32),
            "ring_permute": (jnp.ones((2, 8), jnp.float32),
                             jnp.ones((2, 8), jnp.float32)),
            "pipeline_edge": jnp.ones((4, 8), jnp.float32),
        }[pattern]
        plan = tune(comm, payload, cache, pattern=pattern, **kw)
        assert not plan.from_cache and plan.n_probes > 0
        assert plan.program["pattern"] == pattern
        again = tune(comm, payload, cache, pattern=pattern, **kw)
        assert again.from_cache and again.n_probes == 0

    def test_force_retunes_despite_cache(self, comm, tmp_path):
        cache = str(tmp_path / "plans.json")
        params, dims = fsdp_payload()
        tune(comm, params, cache, pattern="fsdp_gather", dims=dims)
        forced = tune(comm, params, cache, pattern="fsdp_gather",
                      dims=dims, force=True)
        assert not forced.from_cache and forced.n_probes > 0


class TestKeyDiscipline:
    def test_pattern_statics_rekey(self, comm, tmp_path):
        """dims / split axes / direction are part of the variant: the
        same payload bytes under different statics is a different
        search."""
        cache = str(tmp_path / "plans.json")
        params, dims = fsdp_payload()
        tune(comm, params, cache, pattern="fsdp_gather", dims=dims)
        other = tune(comm, params, cache, pattern="fsdp_gather",
                     dims={"w": 1, "b": 0})
        assert not other.from_cache  # dims change missed the cache

        x = jnp.ones((8, 8, 4), jnp.float32)
        tune(comm, x, cache, pattern="moe_all_to_all",
             split_axis=0, concat_axis=1)
        rev = tune(comm, x, cache, pattern="moe_all_to_all",
                   split_axis=1, concat_axis=0)
        assert not rev.from_cache

    def test_patterns_never_cross_serve(self, comm, tmp_path):
        """One payload, one cache file, two patterns: each serves only
        its own entry."""
        cache = str(tmp_path / "plans.json")
        x = jnp.ones((8, 4, 8), jnp.float32)
        moe = tune(comm, x, cache, pattern="moe_all_to_all",
                   split_axis=0, concat_axis=1)
        pipe = tune(comm, x, cache, pattern="pipeline_edge",
                    shift=1, wrap=False)
        assert not pipe.from_cache
        assert moe.key != pipe.key
        assert tune(comm, x, cache, pattern="moe_all_to_all",
                    split_axis=0, concat_axis=1).from_cache
        assert tune(comm, x, cache, pattern="pipeline_edge",
                    shift=1, wrap=False).from_cache

    def test_variant_separates_from_legacy_tuner(self, comm, tmp_path):
        """The legacy exchange tuner and the pattern tuner share the
        cache file but never each other's plans."""
        cache = str(tmp_path / "plans.json")
        params, dims = fsdp_payload()
        legacy = autotune.autotune_plan(comm, params, cache_path=cache,
                                        trials=1, warmup=1)
        pattern = tune(comm, params, cache, pattern="fsdp_gather",
                       dims=dims)
        assert legacy.key != pattern.key
        assert legacy.program is None and pattern.program is not None
        # both still serve from the shared file
        assert autotune.autotune_plan(
            comm, params, cache_path=cache, trials=1,
            warmup=1).from_cache
        assert tune(comm, params, cache, pattern="fsdp_gather",
                    dims=dims).from_cache

    def test_format_version_rekeys(self, comm, tmp_path, monkeypatch):
        cache = str(tmp_path / "plans.json")
        params, dims = fsdp_payload()
        tune(comm, params, cache, pattern="fsdp_gather", dims=dims)
        monkeypatch.setattr(autotune, "FORMAT_VERSION",
                            autotune.FORMAT_VERSION + 1)
        bumped = tune(comm, params, cache, pattern="fsdp_gather",
                      dims=dims)
        assert not bumped.from_cache

    def test_payload_change_rekeys(self, comm, tmp_path):
        cache = str(tmp_path / "plans.json")
        params, dims = fsdp_payload()
        tune(comm, params, cache, pattern="fsdp_gather", dims=dims)
        wide, _ = fsdp_payload(width=32)
        assert not tune(comm, wide, cache, pattern="fsdp_gather",
                        dims=dims).from_cache


class TestObservability:
    def test_per_pattern_hit_miss_counters(self, comm, tmp_path,
                                           registry):
        cache = str(tmp_path / "plans.json")
        params, dims = fsdp_payload()
        tune(comm, params, cache, pattern="fsdp_gather", dims=dims)
        assert registry.counter(
            "autotune/plan_cache_misses").value == 1
        assert registry.counter(
            "autotune/plan_cache_misses_fsdp_gather").value == 1
        tune(comm, params, cache, pattern="fsdp_gather", dims=dims)
        assert registry.counter(
            "autotune/plan_cache_hits").value == 1
        assert registry.counter(
            "autotune/plan_cache_hits_fsdp_gather").value == 1
        # a second pattern gets its own per-pattern counter
        tune(comm, jnp.ones((4, 8), jnp.float32), cache,
             pattern="pipeline_edge", shift=1, wrap=False)
        assert registry.counter(
            "autotune/plan_cache_misses_pipeline_edge").value == 1
        assert registry.counter(
            "autotune/plan_cache_misses").value == 2


class TestGuards:
    def test_tracer_guard(self, comm):
        params, dims = fsdp_payload()

        def bad(p):
            return autotune.autotune_pattern_plan(
                comm, p, pattern="fsdp_gather", dims=dims)

        with pytest.raises(RuntimeError, match="under tracing"):
            jax.jit(bad)(params)

    def test_unknown_pattern_raises(self, comm):
        with pytest.raises(ValueError, match="unknown pattern"):
            autotune.autotune_pattern_plan(
                comm, jnp.ones((4,)), pattern="bogus")

    def test_moe_multi_leaf_payload_raises(self, comm):
        with pytest.raises(ValueError):
            autotune.autotune_pattern_plan(
                comm, {"a": jnp.ones((8, 4, 8)),
                       "b": jnp.ones((8, 4, 8))},
                pattern="moe_all_to_all", trials=1, warmup=1,
                split_axis=0, concat_axis=1)


class TestPlanCellIntegration:
    def test_cell_retunes_with_pattern_tuner(self, comm, tmp_path):
        """A drift re-tune through PlanCell re-runs the PATTERN search
        (not the legacy exchange search) when the cell was resolved
        with one."""
        cache = str(tmp_path / "plans.json")
        params, dims = fsdp_payload()
        plan = tune(comm, params, cache, pattern="fsdp_gather",
                    dims=dims)
        cell = autotune.PlanCell(plan)
        cell.tuner = autotune.autotune_pattern_plan
        cell.tune_kwargs = {"pattern": "fsdp_gather", "dims": dims,
                            "cache_path": cache, "trials": 1,
                            "warmup": 1}
        gen = cell.generation
        new = cell.retune(comm, params)
        assert cell.generation == gen + 1
        assert new.program is not None
        assert new.program["pattern"] == "fsdp_gather"
        assert not new.from_cache  # force=True bypasses the cache
