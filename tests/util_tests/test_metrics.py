"""Metrics & SLO layer (utils/metrics): lattice bucket-boundary
exactness, exact-vs-interpolated percentiles, cross-rank merge math
(counters sum / gauges max / histograms bucket-sum, divergent-key
union), the disabled path's shared no-op singleton, Prometheus text
round-trip, JSONL snapshot schema, and the trainer extensions
(GoodputReport wall-time decomposition, MetricsTextfile flush) plus
the StandardUpdater step-time wiring."""

import json
import math

import numpy as np
import pytest

from chainermn_tpu.utils import metrics as M
from chainermn_tpu.utils.metrics import (
    Counter,
    Gauge,
    GoodputReport,
    Histogram,
    LATTICE_EDGES,
    MetricsRegistry,
    MetricsTextfile,
    bucket_index,
    export_jsonl,
    export_prometheus,
    get_registry,
    histogram_from_prometheus,
    merge_metrics,
    parse_prometheus_text,
    set_registry,
    to_prometheus,
)


@pytest.fixture()
def registry():
    """Fresh enabled registry installed as the global one; the previous
    global is restored afterwards."""
    reg = MetricsRegistry(enabled=True)
    prev = set_registry(reg)
    yield reg
    set_registry(prev)


class FakeComm:
    """N-rank allgather fake: rank 0's row is the caller's object, the
    rest are supplied — the merge-math harness (a single-process world
    only ever allgathers one row)."""

    inter_rank = 0
    inter_size = 3

    def __init__(self, *other_rows):
        self.rows = list(other_rows)

    def allgather_obj(self, obj):
        return [obj] + self.rows


# ---------------------------------------------------------------------- #
# lattice
# ---------------------------------------------------------------------- #

class TestLattice:
    def test_edges_are_log_spaced_and_monotonic(self):
        ratios = [LATTICE_EDGES[i + 1] / LATTICE_EDGES[i]
                  for i in range(len(LATTICE_EDGES) - 1)]
        assert all(r == pytest.approx(10 ** (1 / 8)) for r in ratios)
        assert list(LATTICE_EDGES) == sorted(LATTICE_EDGES)

    def test_boundary_exactness(self):
        """A value EXACTLY on an edge belongs to that edge's bucket
        (Prometheus ``le`` semantics), with no float-log wobble at any
        edge; the next representable value up crosses into the next
        bucket."""
        for i, edge in enumerate(LATTICE_EDGES):
            assert bucket_index(edge) == i
            assert bucket_index(math.nextafter(edge, math.inf)) == i + 1
        assert bucket_index(0.0) == 0
        assert bucket_index(float(LATTICE_EDGES[-1]) * 2) \
            == len(LATTICE_EDGES)

    def test_observe_lands_on_edge_bucket(self):
        h = Histogram()
        edge = LATTICE_EDGES[17]
        h.observe(edge)
        assert h.bucket_counts() == {17: 1}

    def test_count_above_is_strict_and_exact(self):
        """The burn-rate bad-count read: strictly-above buckets only,
        identical to the sparse bucket_counts sum."""
        h = Histogram()
        edge = LATTICE_EDGES[17]
        h.observe(edge)                 # IN bucket 17: not above it
        h.observe(edge * 1.01)          # bucket 18
        h.observe(float(LATTICE_EDGES[-1]) * 2)     # overflow bucket
        h.observe(1e-9)                 # bucket 0
        assert h.count_above(17) == 2
        assert h.count_above(17) == sum(
            c for i, c in h.bucket_counts().items() if i > 17)
        assert h.count_above(len(LATTICE_EDGES)) == 0


# ---------------------------------------------------------------------- #
# histogram percentiles
# ---------------------------------------------------------------------- #

class TestHistogram:
    def test_small_n_percentiles_exact_numpy_identical(self):
        rng = np.random.RandomState(0)
        vals = list(rng.lognormal(-4, 2, size=100))
        h = Histogram()
        for v in vals:
            h.observe(v)
        assert h.exact
        for q in (0, 10, 50, 90, 95, 99, 100):
            assert h.percentile(q) == pytest.approx(
                float(np.percentile(vals, q)), rel=1e-12)
        assert h.mean == pytest.approx(float(np.mean(vals)))

    def test_over_cap_interpolated_within_bucket_width(self):
        """Past the cap, samples drop and quantiles interpolate within
        a lattice bucket — error bounded by one bucket's width
        (10^(1/8) ≈ 1.33×)."""
        rng = np.random.RandomState(1)
        vals = list(rng.uniform(0.01, 0.1, size=2000))
        h = Histogram(sample_cap=64)
        for v in vals:
            h.observe(v)
        assert not h.exact and h.count == 2000
        for q in (50, 99):
            true = float(np.percentile(vals, q))
            est = h.percentile(q)
            assert true / 10 ** (1 / 8) <= est <= true * 10 ** (1 / 8)
        # extrema clamp the interpolation
        assert h.percentile(0) >= h.min
        assert h.percentile(100) <= h.max

    def test_empty_histogram(self):
        h = Histogram()
        assert h.percentile(50) is None and h.mean is None

    def test_merge_is_bucket_sum_and_keeps_exactness_under_cap(self):
        a, b = Histogram(), Histogram()
        vals_a, vals_b = [0.001, 0.02, 0.3], [0.004, 5.0]
        for v in vals_a:
            a.observe(v)
        for v in vals_b:
            b.observe(v)
        a.merge(b.to_snapshot())
        whole = Histogram()
        for v in vals_a + vals_b:
            whole.observe(v)
        assert a.bucket_counts() == whole.bucket_counts()
        assert a.count == 5 and a.exact
        assert a.percentile(50) == pytest.approx(whole.percentile(50))
        assert a.min == min(vals_a + vals_b)
        assert a.max == max(vals_a + vals_b)

    def test_merge_past_cap_drops_samples_keeps_buckets(self):
        a = Histogram(sample_cap=4)
        b = Histogram(sample_cap=4)
        for v in (0.001, 0.002, 0.003):
            a.observe(v)
        for v in (0.004, 0.005):
            b.observe(v)
        a.merge(b.to_snapshot())
        assert not a.exact and a.count == 5
        assert sum(a.bucket_counts().values()) == 5
        assert a.percentile(50) is not None

    def test_snapshot_round_trip_post_json(self):
        h = Histogram()
        for v in (0.001, 0.5, 30.0):
            h.observe(v)
        snap = json.loads(json.dumps(h.to_snapshot()))  # str keys
        back = Histogram.from_snapshot(snap)
        assert back.bucket_counts() == h.bucket_counts()
        assert back.percentile(99) == pytest.approx(h.percentile(99))


# ---------------------------------------------------------------------- #
# registry: disabled path + discipline
# ---------------------------------------------------------------------- #

class TestRegistry:
    def test_disabled_returns_shared_noop_singleton(self):
        """Allocation-free when disabled: every instrument getter hands
        back the SAME no-op object, the recorders early-return, and
        nothing reaches the table (the TraceRecorder _NULL_SPAN
        discipline)."""
        reg = MetricsRegistry(enabled=False)
        a = reg.counter("serve/admits")
        b = reg.histogram("serve/ttft")
        c = reg.gauge("serve/queue_depth")
        assert a is b is c is M._NULL_INSTRUMENT
        a.inc()
        b.observe(0.5)
        c.set(3)
        reg.inc("x")
        reg.observe("y", 1.0)
        reg.set("z", 2.0)
        assert len(reg) == 0 and reg.snapshot() == {}

    def test_enable_disable_toggle(self):
        reg = MetricsRegistry()
        reg.inc("a")
        reg.enable()
        reg.inc("a")
        reg.disable()
        reg.inc("a")
        assert reg.snapshot()["a"]["value"] == 1.0

    def test_name_keeps_first_type(self):
        reg = MetricsRegistry(enabled=True)
        reg.inc("x")
        with pytest.raises(TypeError):
            reg.histogram("x")

    def test_env_switch(self, monkeypatch):
        monkeypatch.delenv("CHAINERMN_TPU_METRICS", raising=False)
        assert not M._from_env().enabled
        monkeypatch.setenv("CHAINERMN_TPU_METRICS", "0")
        assert not M._from_env().enabled
        monkeypatch.setenv("CHAINERMN_TPU_METRICS", "1")
        assert M._from_env().enabled

    def test_snapshot_prefix_filter(self):
        reg = MetricsRegistry(enabled=True)
        reg.inc("serve/admits")
        reg.inc("train/iterations")
        assert set(reg.snapshot(prefix="serve/")) == {"serve/admits"}


# ---------------------------------------------------------------------- #
# cross-rank merge
# ---------------------------------------------------------------------- #

class TestMerge:
    def _rank_row(self, n_admits, depth, ttfts, extra=None):
        reg = MetricsRegistry(enabled=True)
        reg.inc("serve/admits", n_admits)
        reg.set("serve/queue_depth", depth)
        for v in ttfts:
            reg.observe("serve/ttft", v)
        if extra:
            reg.inc(extra)
        return reg.snapshot()

    def test_counter_gauge_histogram_merge_math(self, registry):
        registry.inc("serve/admits", 3)
        registry.set("serve/queue_depth", 2)
        for v in (0.01, 0.02):
            registry.observe("serve/ttft", v)
        comm = FakeComm(
            self._rank_row(5, 9, [0.04], extra="rank1/only"),
            self._rank_row(1, 4, [0.08, 0.5]),
        )
        merged = merge_metrics(comm, registry)
        s = merged.snapshot()
        # counters sum
        assert s["serve/admits"]["value"] == 9.0
        # gauges keep the fleet max
        assert s["serve/queue_depth"]["last"] == 9.0
        assert s["serve/queue_depth"]["max"] == 9.0
        # histograms bucket-sum on the shared lattice, exactly
        h = Histogram.from_snapshot(s["serve/ttft"])
        whole = Histogram()
        for v in (0.01, 0.02, 0.04, 0.08, 0.5):
            whole.observe(v)
        assert h.bucket_counts() == whole.bucket_counts()
        assert h.count == 5 and h.max == 0.5
        assert h.percentile(99) == pytest.approx(whole.percentile(99))
        # divergent name sets union (the ObservationAggregator
        # convention): a rank-1-only metric survives
        assert s["rank1/only"]["value"] == 1.0

    def test_merge_deterministic_identical_everywhere(self, registry):
        """The fold over rank-ordered rows is deterministic — every
        rank folding the same allgathered rows produces ONE identical
        snapshot (what rank-0-only exposition gates on)."""
        rows = [self._rank_row(i + 1, i, [0.01 * (i + 1)])
                for i in range(3)]

        class RowsComm:
            def allgather_obj(self, obj):
                return [json.loads(json.dumps(r)) for r in rows]

        snaps = [merge_metrics(RowsComm(), registry).snapshot()
                 for _ in range(3)]
        assert json.dumps(snaps[0], sort_keys=True, default=float) \
            == json.dumps(snaps[1], sort_keys=True, default=float) \
            == json.dumps(snaps[2], sort_keys=True, default=float)

    def test_merge_over_real_communicator(self, comm, registry):
        """The collective path: one process world, but the real
        ``allgather_obj`` transport (pickle round trip included)."""
        registry.inc("train/iterations", 7)
        registry.observe("train/step_time", 0.012)
        merged = merge_metrics(comm, registry)
        s = merged.snapshot()
        assert s["train/iterations"]["value"] == 7.0
        assert s["train/step_time"]["count"] == 1


# ---------------------------------------------------------------------- #
# exposition: Prometheus + JSONL
# ---------------------------------------------------------------------- #

class TestPrometheus:
    def test_round_trip_all_instrument_types(self):
        reg = MetricsRegistry(enabled=True)
        reg.inc("serve/admits", 42)
        reg.set("serve/queue_depth", 5)
        vals = [1e-8, 0.001, 0.0012, 0.5, 3.0, 1e6]
        for v in vals:
            reg.observe("serve/ttft", v)
        text = to_prometheus(reg, labels={"rank": "3"})
        assert '# TYPE serve_admits counter' in text
        assert 'rank="3"' in text
        parsed = parse_prometheus_text(text)
        assert parsed["serve_admits"] == {"type": "counter",
                                          "value": 42.0}
        assert parsed["serve_queue_depth"]["last"] == 5.0
        h = histogram_from_prometheus(parsed["serve_ttft"])
        orig = reg.histogram("serve/ttft")
        # cumulative-bucket diffs reconstruct the exact lattice counts
        # (underflow and overflow included)
        assert h.bucket_counts() == orig.bucket_counts()
        assert h.count == len(vals)
        assert h.sum == pytest.approx(orig.sum)

    def test_overflow_percentile_survives_wire_round_trip(self):
        """min/max don't survive the exposition wire; a quantile
        landing in the overflow bucket must degrade to the last lattice
        edge (a lower bound), not crash."""
        reg = MetricsRegistry(enabled=True)
        reg.observe("h", 0.5)
        reg.observe("h", 5e5)           # past the last edge
        h = histogram_from_prometheus(
            parse_prometheus_text(to_prometheus(reg))["h"])
        assert h.percentile(99.99) == pytest.approx(LATTICE_EDGES[-1])
        # with the live histogram the observed max bounds it instead
        live = reg.histogram("h")
        assert live.percentile(99.99) <= 5e5

    def test_histogram_has_mandatory_inf_bucket(self):
        reg = MetricsRegistry(enabled=True)
        reg.observe("h", 0.5)
        text = to_prometheus(reg)
        assert 'h_bucket{le="+Inf"} 1' in text
        parsed = parse_prometheus_text(text)
        assert parsed["h"]["buckets"][-1] == (math.inf, 1)

    def test_name_sanitization(self):
        reg = MetricsRegistry(enabled=True)
        reg.inc("serve/queue-wait.p99")
        parsed = parse_prometheus_text(to_prometheus(reg))
        assert "serve_queue_wait_p99" in parsed

    def test_export_atomic_file(self, tmp_path):
        reg = MetricsRegistry(enabled=True)
        reg.inc("c", 2)
        path = str(tmp_path / "metrics.prom")
        export_prometheus(path, reg, labels={"rank": "0"})
        parsed = parse_prometheus_text(open(path).read())
        assert parsed["c"]["value"] == 2.0
        assert not (tmp_path / "metrics.prom.tmp").exists()


class TestJsonl:
    def test_snapshot_schema(self, tmp_path):
        reg = MetricsRegistry(enabled=True)
        reg.inc("serve/admits", 2)
        reg.observe("serve/ttft", 0.01)
        path = str(tmp_path / "metrics.jsonl")
        export_jsonl(path, reg, rank=0)
        export_jsonl(path, reg, rank=0)
        lines = [json.loads(l) for l in open(path)]
        assert len(lines) == 2
        for entry in lines:
            assert {"ts", "rank", "metrics"} <= set(entry)
            m = entry["metrics"]
            assert m["serve/admits"] == {"type": "counter", "value": 2.0}
            h = m["serve/ttft"]
            assert h["type"] == "histogram"
            assert {"count", "sum", "min", "max", "counts",
                    "samples"} <= set(h)
            assert h["count"] == 1


# ---------------------------------------------------------------------- #
# GoodputReport
# ---------------------------------------------------------------------- #

class FakeTrainer:
    def __init__(self, out):
        class U:
            iteration = 11
        self.updater = U()
        self.observation = {}
        self.out = str(out)


class TestGoodputReport:
    def test_decomposition_sums_to_window(self, tmp_path, registry):
        from chainermn_tpu.utils.telemetry import TraceRecorder

        rec = TraceRecorder(enabled=True, rank=0)
        gp = GoodputReport(recorder=rec, registry=registry)
        gp.initialize()
        for _ in range(10):
            rec.record("step/dispatch", 0.004)
            rec.record("step/retire", 0.006)
            rec.record("step/host", 0.002)
        rec.record("checkpoint/save_shard", 0.05)
        rec.record("step/exchange_probe", 0.01)
        trainer = FakeTrainer(tmp_path)
        gp(trainer)
        rep = gp.last_report
        assert rep["productive_s"] == pytest.approx(0.1)
        assert rep["badput"]["host_blocked_s"] == pytest.approx(0.02)
        assert rep["badput"]["checkpoint_s"] == pytest.approx(0.05)
        assert rep["badput"]["exchange_probe_s"] == pytest.approx(0.01)
        # stall is the unaccounted remainder, clamped at zero: these
        # synthetic spans outweigh the (µs-scale) real wall window, so
        # nothing is unaccounted (the real-window tiling is asserted in
        # the trainer integration test below)
        assert rep["badput"]["stall_s"] == 0.0
        assert rep["goodput"] == pytest.approx(
            rep["productive_s"] / rep["window_s"])
        assert trainer.observation["main/goodput"] == rep["goodput"]
        # registry mirror for scrapers
        snap = registry.snapshot()
        assert snap["train/goodput"]["last"] == rep["goodput"]
        assert snap["goodput/checkpoint_s"]["value"] \
            == pytest.approx(0.05)
        # rank 0 writes the jsonl series
        line = json.loads(open(tmp_path / "goodput.jsonl").read())
        assert line["iteration"] == 11 and "badput" in line

    def test_disabled_recorder_reports_nothing(self, tmp_path):
        from chainermn_tpu.utils.telemetry import TraceRecorder

        gp = GoodputReport(recorder=TraceRecorder(enabled=False),
                           write=False)
        gp.initialize()
        trainer = FakeTrainer(tmp_path)
        gp(trainer)
        assert gp.last_report["goodput"] is None
        assert gp.last_report["trace_enabled"] is False
        assert "main/goodput" not in trainer.observation

    def test_private_channel_never_steals_other_consumers_feed(
            self, registry):
        """The goodput drain runs on its OWN phase channel — a
        catch-all StragglerReport drain (default channel) on the same
        or any other trigger still sees EVERY interval, including the
        names goodput accounts."""
        from chainermn_tpu.utils.telemetry import TraceRecorder

        rec = TraceRecorder(enabled=True, rank=0)
        gp = GoodputReport(recorder=rec, write=False,
                           registry=registry)
        gp.initialize()     # opens the channel before spans accumulate
        rec.record("step/dispatch", 0.01)
        rec.record("prefetch/slot_wait", 0.5)
        gp()
        assert gp.last_report["productive_s"] == pytest.approx(0.01)
        left = rec.drain_phase_stats()
        assert left["step/dispatch"]["count"] == 1
        assert left["step/dispatch"]["total_s"] == pytest.approx(0.01)
        assert "prefetch/slot_wait" in left
        # and the private channel's interval state is its own: a second
        # goodput fire sees only NEW spans, not the drained window again
        gp()
        assert gp.last_report["productive_s"] == 0.0


# ---------------------------------------------------------------------- #
# MetricsTextfile + trainer integration
# ---------------------------------------------------------------------- #

class TestMetricsTextfile:
    def test_writes_rank_labeled_promfile(self, tmp_path, registry):
        registry.inc("serve/admits", 4)
        mt = MetricsTextfile(registry=registry,
                             path=str(tmp_path / "metrics.prom"))
        mt()
        text = open(tmp_path / "metrics.prom").read()
        parsed = parse_prometheus_text(text)
        assert parsed["serve_admits"]["value"] == 4.0
        assert 'rank="0"' in text

    def test_trainer_integration_with_goodput(self, comm, tmp_path,
                                              registry):
        """Full stack on the 8-device mesh: enabled recorder + registry,
        updater feeds the step-time histogram, GoodputReport decomposes
        the window, MetricsTextfile flushes the promfile."""
        import jax
        import optax

        import chainermn_tpu as cmn
        from chainermn_tpu.models import (init_mlp, mlp_apply,
                                          softmax_cross_entropy)
        from chainermn_tpu.utils.telemetry import (TraceRecorder,
                                                   set_recorder)

        rec = TraceRecorder(enabled=True, rank=0)
        prev = set_recorder(rec)
        try:
            rng = np.random.RandomState(0)
            ds = [(rng.randn(6).astype(np.float32), np.int32(i % 3))
                  for i in range(64)]
            it = cmn.SerialIterator(ds, 16, shuffle=True, seed=3)
            params = init_mlp(jax.random.PRNGKey(0), [6, 12, 3])
            opt = cmn.create_multi_node_optimizer(optax.sgd(0.05), comm)

            def loss_fn(p, x, y):
                return softmax_cross_entropy(mlp_apply(p, x), y)

            upd = cmn.StandardUpdater(it, opt, loss_fn, params, comm)
            trainer = cmn.Trainer(upd, (2, "epoch"), out=str(tmp_path))
            trainer.extend(GoodputReport(comm))
            trainer.extend(MetricsTextfile(comm))
            trainer.run()

            snap = get_registry().snapshot()
            st = snap["train/step_time"]
            assert st["type"] == "histogram"
            assert st["count"] == trainer.updater.iteration
            assert snap["train/iterations"]["value"] \
                == trainer.updater.iteration
            assert snap["train/goodput"]["last"] > 0
            parsed = parse_prometheus_text(
                open(tmp_path / "metrics.prom").read())
            assert parsed["train_step_time"]["count"] \
                == trainer.updater.iteration
            lines = [json.loads(l)
                     for l in open(tmp_path / "goodput.jsonl")]
            assert len(lines) == 2      # one per epoch
            assert all(0 <= l["goodput"] <= 1 for l in lines)
        finally:
            set_recorder(prev)


class TestExemplars:
    """PR 13: bounded per-bucket exemplars link a histogram percentile
    to the causal trace of a concrete observation."""

    def test_one_exemplar_per_bucket_newest_wins(self):
        h = Histogram()
        h.observe(0.0101, exemplar="first")
        h.observe(0.0102, exemplar="second")     # same lattice bucket
        h.observe(0.5, exemplar="tail")
        assert bucket_index(0.0101) == bucket_index(0.0102)
        ex = h.exemplars()
        assert len(ex) == 2                      # bounded by buckets
        same_bucket = ex[bucket_index(0.0101)]
        assert same_bucket[0] == "second"
        assert same_bucket[1] == pytest.approx(0.0102)

    def test_exemplar_free_observe_allocates_no_table(self):
        h = Histogram()
        h.observe(0.010)
        assert h._exemplars is None
        assert h.exemplars() == {}
        assert h.exemplar_for(99) is None

    def test_exemplar_for_resolves_percentile_to_tail(self):
        h = Histogram()
        for _ in range(99):
            h.observe(0.001, exemplar="fast")
        h.observe(1.0, exemplar="slow")
        assert h.exemplar_for(99)[0] == "slow"
        assert h.exemplar_for(50)[0] == "fast"

    def test_exemplar_for_prefers_bucket_above(self):
        # no exemplar in the p99 bucket itself: the nearest ABOVE wins
        # (the offending request lives in the tail)
        h = Histogram()
        for _ in range(100):
            h.observe(0.001)
        h.observe(2.0, exemplar="outlier")
        assert h.exemplar_for(50)[0] == "outlier"

    def test_snapshot_merge_keeps_newest_ts(self):
        a, b = Histogram(), Histogram()
        a.observe(0.0101, exemplar="old")
        b.observe(0.0102, exemplar="new")       # same lattice bucket
        a._exemplars[bucket_index(0.0101)][2] = 1.0     # force ordering
        b._exemplars[bucket_index(0.0102)][2] = 2.0
        merged = Histogram()
        merged.merge(a.to_snapshot())
        merged.merge(b.to_snapshot())
        assert merged.exemplars()[bucket_index(0.0101)][0] == "new"
        # reversed fold order: same winner (deterministic)
        m2 = Histogram()
        m2.merge(b.to_snapshot())
        m2.merge(a.to_snapshot())
        assert m2.exemplars()[bucket_index(0.0101)][0] == "new"

    def test_registry_observe_exemplar_and_disabled_noop(self, registry):
        registry.observe("serve/ttft", 0.25, exemplar="tr-1")
        assert registry.histogram("serve/ttft").exemplar_for(99)[0] \
            == "tr-1"
        off = MetricsRegistry(enabled=False)
        off.observe("serve/ttft", 0.25, exemplar="tr-1")    # no-op
        assert len(off) == 0
        null = off.histogram("serve/ttft")
        assert null.exemplar_for(99) is None
        assert null.exemplars() == {}
        assert null.count_above(0) == 0

    def test_prometheus_round_trip_with_exemplars(self):
        h = Histogram()
        h.observe(0.01, exemplar="fast-trace")
        h.observe(0.8, exemplar="slow-trace")
        h.observe(0.011)
        text = to_prometheus({"serve/ttft": h.to_snapshot()},
                             openmetrics=True)
        assert ' # {trace_id="slow-trace"} ' in text
        # the DEFAULT is exemplar-free: classic 0.0.4 consumers
        # (textfile, watchdog stall reports) must never see the suffix
        assert "trace_id=" not in to_prometheus(
            {"serve/ttft": h.to_snapshot()})
        parsed = parse_prometheus_text(text)
        h2 = histogram_from_prometheus(parsed["serve_ttft"])
        assert h2.count == h.count
        assert h2.exemplar_for(99)[0] == "slow-trace"
        assert h2.exemplar_for(99)[1] == pytest.approx(0.8)
        # bucket counts identical to the exemplar-free round trip
        assert h2.bucket_counts() == h.bucket_counts()

    def test_digest_is_counters_and_gauges_only(self):
        """The /statusz per-scrape read: counter values + gauge
        lasts, histograms omitted (their samples/exemplars never
        serialized)."""
        reg = MetricsRegistry(enabled=True)
        reg.inc("serve/admits", 3)
        reg.set("serve/queue_depth", 7)
        reg.observe("serve/ttft", 0.2)
        assert reg.digest() == {"serve/admits": 3.0,
                                "serve/queue_depth": 7.0}
        assert MetricsRegistry(enabled=False).digest() == {}

    def test_textfile_export_is_exemplar_free_by_default(self,
                                                         tmp_path):
        """The node-exporter textfile collector speaks classic 0.0.4,
        whose parsers reject the OpenMetrics exemplar suffix — turning
        tracing on must never break an existing scrape."""
        reg = MetricsRegistry(enabled=True)
        reg.observe("serve/ttft", 0.8, exemplar="tr-1")
        path = str(tmp_path / "m.prom")
        export_prometheus(path, reg)
        text = open(path).read()
        assert "trace_id=" not in text and " # {" not in text
        export_prometheus(path, reg, openmetrics=True)  # the opt-in
        assert 'trace_id="tr-1"' in open(path).read()

    def test_exemplar_id_sanitized_in_exposition(self):
        """Caller-propagated trace ids are arbitrary strings; a quote
        or brace must not corrupt the exposition or break the
        round-trip."""
        h = Histogram()
        h.observe(0.8, exemplar='ab"cd}ef gh')
        text = to_prometheus({"serve/ttft": h.to_snapshot()},
                             openmetrics=True)
        assert '"' not in text.split(' # {trace_id="', 1)[1] \
            .split('"', 1)[1].split("}")[0]
        parsed = parse_prometheus_text(text)
        h2 = histogram_from_prometheus(parsed["serve_ttft"])
        assert h2.count == 1
        assert h2.bucket_counts() == h.bucket_counts()
        assert h2.exemplar_for(99)[0] == "ab_cd_ef_gh"

    def test_pre_exemplar_text_still_parses(self):
        """Back-compat both directions: exemplar-free emission has no
        suffix, and text from a pre-exemplar emitter parses cleanly."""
        h = Histogram()
        h.observe(0.01)
        h.observe(0.8)
        text = to_prometheus({"serve/ttft": h.to_snapshot()})
        assert " # {" not in text           # no suffix when none held
        # simulate pre-exemplar text by stripping any suffix form
        legacy = "\n".join(l.split(" # ")[0]
                           for l in text.splitlines()) + "\n"
        h2 = histogram_from_prometheus(
            parse_prometheus_text(legacy)["serve_ttft"])
        assert h2.count == 2
        assert h2.bucket_counts() == h.bucket_counts()
        assert h2.exemplar_for(99) is None


class TestAppendJsonl:
    """The atomic JSONL append every report flushes through: one
    O_APPEND write per line, so no crash — SIGKILL included — can
    leave a torn last line."""

    def test_appends_parseable_lines(self, tmp_path):
        path = str(tmp_path / "x.jsonl")
        M.append_jsonl(path, {"a": 1})
        M.append_jsonl(path, {"b": [1, 2]})
        lines = [json.loads(l) for l in open(path)]
        assert lines == [{"a": 1}, {"b": [1, 2]}]

    def test_sigkill_mid_stream_never_tears_a_line(self, tmp_path):
        """The kill drill the satellite demands: a child appends fat
        JSON lines in a tight loop, SIGKILL lands mid-stream, and
        every line on disk still parses — the last one included."""
        import os
        import signal
        import subprocess
        import sys
        import time as _time

        path = str(tmp_path / "killed.jsonl")
        metrics_py = os.path.abspath(M.__file__)
        child_src = (
            "import importlib.util, sys\n"
            f"spec = importlib.util.spec_from_file_location("
            f"'m', {metrics_py!r})\n"
            "m = importlib.util.module_from_spec(spec)\n"
            "spec.loader.exec_module(m)\n"
            "pad = 'x' * 700\n"
            "i = 0\n"
            "while True:\n"
            f"    m.append_jsonl({path!r}, "
            "{'i': i, 'pad': pad})\n"
            "    i += 1\n")
        proc = subprocess.Popen([sys.executable, "-c", child_src])
        try:
            deadline = _time.monotonic() + 30
            while _time.monotonic() < deadline:
                if os.path.exists(path) \
                        and os.path.getsize(path) > 50_000:
                    break
                _time.sleep(0.01)
            assert os.path.exists(path), "child never wrote"
            os.kill(proc.pid, signal.SIGKILL)
        finally:
            proc.wait(timeout=30)
        raw = open(path, "rb").read()
        assert len(raw) > 50_000
        assert raw.endswith(b"\n"), "torn final line"
        lines = raw.decode().splitlines()
        parsed = [json.loads(l) for l in lines]     # every line whole
        assert [p["i"] for p in parsed] == list(range(len(parsed)))

    def test_goodput_report_uses_atomic_append(self, tmp_path):
        # the write path is append_jsonl (single whole-line writes):
        # pin by checking the file grows one complete line per call
        rep = GoodputReport(write=True)

        class FakeUpdater:
            iteration = 3

        class FakeTrainer:
            out = str(tmp_path)
            updater = FakeUpdater()
            observation = {}

        from chainermn_tpu.utils.telemetry import (
            TraceRecorder,
            set_recorder,
        )

        prev = set_recorder(TraceRecorder(enabled=True))
        try:
            rep.initialize()
            rec = M.get_registry()
            from chainermn_tpu.utils.telemetry import get_recorder

            get_recorder().record("step/dispatch", 0.01)
            rep(FakeTrainer())
            rep(FakeTrainer())
        finally:
            set_recorder(prev)
        lines = [json.loads(l)
                 for l in open(tmp_path / "goodput.jsonl")]
        assert len(lines) == 2
        assert all("window_s" in l for l in lines)
